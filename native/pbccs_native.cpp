// Native host-runtime components for pbccs_tpu.
//
// TPU-native re-implementations of the reference's C++ host layers:
//  * BGZF block codec (the reference delegates BAM IO to pbbam/htslib;
//    here the hot (de)compression path is multithreaded over 64KB BGZF
//    blocks, which htslib also does in its bgzf_mt mode).
//  * Sparse-DP seed chaining (reference include/pacbio/ccs/ChainSeeds.h +
//    src/ChainSeeds.cpp sweep-line SDP), same link-gain semantics as
//    pbccs_tpu.align.seeds.chain_seeds, exposed for the host draft stage.
//  * Partial-order-alignment draft engine (reference ConsensusCore Poa:
//    PoaGraphImpl alignment/threading/consensus, src/C++/Poa/*), the
//    behavior-identical native backend of pbccs_tpu.poa.graph.PoaGraph --
//    the draft stage is the host-side bottleneck once polishing runs on
//    the accelerator.
//
// Exposed as a plain C ABI consumed via ctypes (pbccs_tpu/native.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

constexpr int kBlockPayload = 64 * 1024 - 512;  // matches io/bam.py _MAX_BLOCK

// one BGZF block: gzip member with BC extra subfield carrying BSIZE
bool CompressBlock(const uint8_t* data, size_t len, int level,
                   std::vector<uint8_t>* out) {
  uLong bound = compressBound(len) + 64;
  std::vector<uint8_t> payload(bound);
  z_stream zs{};
  if (deflateInit2(&zs, level, Z_DEFLATED, -15, 8, Z_DEFAULT_STRATEGY) != Z_OK)
    return false;
  zs.next_in = const_cast<Bytef*>(data);
  zs.avail_in = len;
  zs.next_out = payload.data();
  zs.avail_out = payload.size();
  int rc = deflate(&zs, Z_FINISH);
  deflateEnd(&zs);
  if (rc != Z_STREAM_END) return false;
  size_t clen = zs.total_out;

  static const uint8_t kHeader[16] = {0x1f, 0x8b, 0x08, 0x04, 0, 0, 0, 0,
                                      0,    0xff, 0x06, 0,    0x42, 0x43,
                                      0x02, 0};
  size_t total = 16 + 2 + clen + 8;
  out->resize(total);
  std::memcpy(out->data(), kHeader, 16);
  uint16_t bsize = static_cast<uint16_t>(total - 1);
  (*out)[16] = bsize & 0xff;
  (*out)[17] = bsize >> 8;
  std::memcpy(out->data() + 18, payload.data(), clen);
  uint32_t crc = crc32(0, data, len);
  uint32_t isize = static_cast<uint32_t>(len);
  uint8_t* tail = out->data() + 18 + clen;
  for (int b = 0; b < 4; ++b) tail[b] = (crc >> (8 * b)) & 0xff;
  for (int b = 0; b < 4; ++b) tail[4 + b] = (isize >> (8 * b)) & 0xff;
  return true;
}

}  // namespace

extern "C" {

// Compress `len` bytes into consecutive BGZF blocks of kBlockPayload bytes
// using `nthreads` workers.  Returns the number of bytes written to `out`
// (capacity `out_cap`), or -1 on failure / insufficient capacity.
int64_t pbccs_bgzf_compress(const uint8_t* data, int64_t len, int level,
                            int nthreads, uint8_t* out, int64_t out_cap) {
  if (len < 0) return -1;
  size_t nblocks = (len + kBlockPayload - 1) / kBlockPayload;
  if (nblocks == 0) return 0;
  std::vector<std::vector<uint8_t>> blocks(nblocks);
  std::vector<char> ok(nblocks, 1);
  nthreads = std::max(1, std::min<int>(nthreads, nblocks));

  auto worker = [&](size_t t) {
    for (size_t b = t; b < nblocks; b += nthreads) {
      size_t off = b * static_cast<size_t>(kBlockPayload);
      size_t n = std::min<size_t>(kBlockPayload, len - off);
      if (!CompressBlock(data + off, n, level, &blocks[b])) ok[b] = 0;
    }
  };
  std::vector<std::thread> threads;
  for (int t = 1; t < nthreads; ++t) threads.emplace_back(worker, t);
  worker(0);
  for (auto& th : threads) th.join();

  int64_t total = 0;
  for (size_t b = 0; b < nblocks; ++b) {
    if (!ok[b]) return -1;
    total += blocks[b].size();
  }
  if (total > out_cap) return -1;
  uint8_t* p = out;
  for (auto& blk : blocks) {
    std::memcpy(p, blk.data(), blk.size());
    p += blk.size();
  }
  return total;
}

// Decompress a BGZF byte stream (concatenated blocks; the 28-byte EOF
// block decodes to zero bytes).  Returns bytes written, -1 on malformed
// input, or -2 when out_cap is too small (retryable).
int64_t pbccs_bgzf_decompress(const uint8_t* data, int64_t len, uint8_t* out,
                              int64_t out_cap) {
  int64_t ip = 0, op = 0;
  while (ip + 18 <= len) {
    if (data[ip] != 0x1f || data[ip + 1] != 0x8b) return -1;
    uint16_t xlen = data[ip + 10] | (data[ip + 11] << 8);
    // find BC subfield for BSIZE
    int64_t xoff = ip + 12;
    int64_t bsize = -1;
    int64_t xend = xoff + xlen;
    while (xoff + 4 <= xend) {
      uint8_t si1 = data[xoff], si2 = data[xoff + 1];
      uint16_t slen = data[xoff + 2] | (data[xoff + 3] << 8);
      if (si1 == 'B' && si2 == 'C' && slen == 2)
        bsize = (data[xoff + 4] | (data[xoff + 5] << 8)) + 1;
      xoff += 4 + slen;
    }
    if (bsize < 0 || ip + bsize > len) return -1;
    int64_t cdata_off = ip + 12 + xlen;
    int64_t cdata_len = bsize - 12 - xlen - 8;
    if (cdata_len < 0 || cdata_off + cdata_len + 8 > ip + bsize) return -1;
    uint32_t isize = data[ip + bsize - 4] | (data[ip + bsize - 3] << 8) |
                     (data[ip + bsize - 2] << 16) | (data[ip + bsize - 1] << 24);
    if (op + isize > out_cap) return -2;  // under-capacity, caller may retry
    if (isize > 0) {
      z_stream zs{};
      if (inflateInit2(&zs, -15) != Z_OK) return -1;
      zs.next_in = const_cast<Bytef*>(data + cdata_off);
      zs.avail_in = cdata_len;
      zs.next_out = out + op;
      zs.avail_out = out_cap - op;
      int rc = inflate(&zs, Z_FINISH);
      inflateEnd(&zs);
      if (rc != Z_STREAM_END || zs.total_out != isize) return -1;
    }
    op += isize;
    ip += bsize;
  }
  return (ip == len || ip == len - 0) ? op : -1;
}

// Sparse-DP seed chaining; same semantics as align.seeds.chain_seeds:
// seeds (h[i], v[i]), chain gain mr*matches - |d_diag| - indels, links only
// to strictly earlier rows with h_b < h_a, ties -> nearest predecessor in
// (v, h)-sorted order.  Writes the chained (h, v) pairs; returns length.
int32_t pbccs_chain_seeds(const int32_t* h, const int32_t* v, int32_t n,
                          int32_t k, int32_t match_reward, int32_t* out_h,
                          int32_t* out_v) {
  if (n <= 0) return 0;
  std::vector<int32_t> idx(n);
  std::iota(idx.begin(), idx.end(), 0);
  std::stable_sort(idx.begin(), idx.end(), [&](int a, int b) {
    if (v[a] != v[b]) return v[a] < v[b];
    return h[a] < h[b];
  });
  std::vector<int64_t> H(n), V(n), D(n), score(n);
  std::vector<int32_t> pred(n, -1);
  for (int i = 0; i < n; ++i) {
    H[i] = h[idx[i]];
    V[i] = v[idx[i]];
    D[i] = H[i] - V[i];
    score[i] = k;
  }
  int row_start = 0;
  for (int a = 0; a < n; ++a) {
    if (V[a] != V[row_start]) row_start = a;
    int64_t best_score = 0;
    int32_t best = -1;
    for (int b = row_start - 1; b >= 0; --b) {  // reverse: nearest wins ties
      if (H[b] >= H[a]) continue;
      int64_t fwd = std::min(H[a] - H[b], V[a] - V[b]);
      int64_t matches = k - std::max<int64_t>(0, k - fwd);
      int64_t link = match_reward * matches - std::llabs(D[a] - D[b]) -
                     (fwd - matches);
      int64_t cand = score[b] + link;
      if (cand > best_score) {
        best_score = cand;
        best = b;
      }
    }
    if (best >= 0 && best_score > 0) {
      score[a] = best_score;
      pred[a] = best;
    }
  }
  int32_t end = -1;
  int64_t best_end = -1;
  for (int i = 0; i < n; ++i)
    if (pred[i] >= 0 && score[i] > best_end) {
      best_end = score[i];
      end = i;
    }
  if (end < 0) return 0;
  std::vector<int32_t> chain;
  for (int32_t cur = end; cur >= 0; cur = pred[cur]) chain.push_back(cur);
  std::reverse(chain.begin(), chain.end());
  for (size_t i = 0; i < chain.size(); ++i) {
    out_h[i] = static_cast<int32_t>(H[chain[i]]);
    out_v[i] = static_cast<int32_t>(V[chain[i]]);
  }
  return static_cast<int32_t>(chain.size());
}

}  // extern "C"

// ---------------------------------------------------------------------------
// POA draft engine.  Behavior-identical native backend of
// pbccs_tpu.poa.graph.PoaGraph (LOCAL read-vs-DAG alignment with
// match=+3 / mismatch=-5 / insert=-4 / delete=-4, traceback threading,
// spanning-read tagging, best-sum consensus path).  All scores are sums of
// small integers, so float equality in the traceback is exact on both the
// numpy and native paths.
// ---------------------------------------------------------------------------

namespace poa {

constexpr float kMatch = 3.0f, kMismatch = -5.0f;
constexpr float kInsert = -4.0f, kDelete = -4.0f;
constexpr float kNegInf = -1e30f;

struct Graph {
  std::vector<int8_t> base;
  std::vector<int32_t> nreads, spanning;
  std::vector<std::vector<int32_t>> preds, succs;
  int32_t n_reads = 0;
  std::vector<double> score;  // consensus-path vertex scores
  bool have_scores = false;
};

struct Plan {
  float score = kNegInf;
  int32_t best_vertex = -1, best_row = 0;
  bool rc = false;
  std::vector<int8_t> read;           // oriented read
  std::vector<float> cols;            // V * (I+1)
  std::vector<int32_t> mpred, dpred;  // V * (I+1)
};

int32_t AddVertex(Graph& g, int8_t b) {
  g.have_scores = false;
  g.base.push_back(b);
  g.nreads.push_back(1);
  g.spanning.push_back(0);
  g.preds.emplace_back();
  g.succs.emplace_back();
  return static_cast<int32_t>(g.base.size()) - 1;
}

void AddEdge(Graph& g, int32_t u, int32_t v) {
  if (u == v) return;
  auto& s = g.succs[u];
  if (std::find(s.begin(), s.end(), v) == s.end()) {
    s.push_back(v);
    g.preds[v].push_back(u);
  }
}

std::vector<int32_t> TopoOrder(const Graph& g) {
  size_t n = g.base.size();
  std::vector<int32_t> indeg(n), order;
  order.reserve(n);
  std::vector<int32_t> q;  // FIFO via index
  for (size_t v = 0; v < n; ++v) {
    indeg[v] = static_cast<int32_t>(g.preds[v].size());
    if (indeg[v] == 0) q.push_back(static_cast<int32_t>(v));
  }
  for (size_t head = 0; head < q.size(); ++head) {
    int32_t v = q[head];
    order.push_back(v);
    for (int32_t w : g.succs[v])
      if (--indeg[w] == 0) q.push_back(w);
  }
  return order;
}

void Reachable(const Graph& g, int32_t root,
               const std::vector<std::vector<int32_t>>& adj,
               std::vector<char>* seen) {
  std::vector<int32_t> stack{root};
  (*seen)[root] = 1;
  while (!stack.empty()) {
    int32_t u = stack.back();
    stack.pop_back();
    for (int32_t w : adj[u])
      if (!(*seen)[w]) {
        (*seen)[w] = 1;
        stack.push_back(w);
      }
  }
}

void TagSpan(Graph& g, int32_t start, int32_t end) {
  size_t n = g.base.size();
  std::vector<char> fwd(n, 0), bwd(n, 0);
  Reachable(g, start, g.succs, &fwd);
  Reachable(g, end, g.preds, &bwd);
  for (size_t v = 0; v < n; ++v)
    if (fwd[v] && bwd[v]) ++g.spanning[v];
}

std::vector<int32_t> AddFirstRead(Graph& g, const int8_t* read, int32_t n) {
  std::vector<int32_t> path;
  path.reserve(n);
  int32_t prev = -1;
  for (int32_t i = 0; i < n; ++i) {
    int32_t v = AddVertex(g, read[i]);
    if (prev >= 0) AddEdge(g, prev, v);
    path.push_back(v);
    prev = v;
  }
  ++g.n_reads;
  TagSpan(g, path.front(), path.back());
  return path;
}

// LOCAL alignment of `read` against the DAG (PoaGraph.try_add_read).
Plan TryAddRead(const Graph& g, std::vector<int8_t> read, bool rc) {
  Plan p;
  p.rc = rc;
  int32_t I = static_cast<int32_t>(read.size());
  size_t n = g.base.size();
  int32_t w = I + 1;
  size_t W = static_cast<size_t>(w);  // size_t stride: V*(I+1) can pass 2^31
  p.cols.assign(n * W, 0.0f);
  p.mpred.assign(n * W, -1);
  p.dpred.assign(n * W, -1);
  std::vector<float> best_m(w), best_d(w);
  static const std::vector<int32_t> kNoPred{-1};

  for (int32_t v : TopoOrder(g)) {
    int8_t vb = g.base[v];
    std::fill(best_m.begin(), best_m.end(), kNegInf);
    std::fill(best_d.begin(), best_d.end(), kNegInf);
    int32_t* bm = &p.mpred[v * W];
    int32_t* bd = &p.dpred[v * W];
    const auto& plist = g.preds[v].empty() ? kNoPred : g.preds[v];
    for (int32_t pr : plist) {
      const float* pc = pr < 0 ? nullptr : &p.cols[pr * W];
      for (int32_t i = 1; i < w; ++i) {
        float sub = read[i - 1] == vb ? kMatch : kMismatch;
        float m = (pc ? pc[i - 1] : 0.0f) + sub;
        if (m > best_m[i]) {
          best_m[i] = m;
          bm[i] = pr;
        }
      }
      for (int32_t i = 0; i < w; ++i) {
        float d = (pc ? pc[i] : 0.0f) + kDelete;
        if (d > best_d[i]) {
          best_d[i] = d;
          bd[i] = pr;
        }
      }
    }
    float* col = &p.cols[v * W];
    float run = kNegInf;
    for (int32_t i = 0; i < w; ++i) {
      float b = std::max(0.0f, std::max(best_m[i], best_d[i]));
      run = std::max(b, run + kInsert);
      col[i] = run;
    }
  }
  // best local end: first strict max in (vertex, row) flat order
  for (size_t f = 0; f < p.cols.size(); ++f)
    if (p.cols[f] > p.score) {
      p.score = p.cols[f];
      p.best_vertex = static_cast<int32_t>(f / W);
      p.best_row = static_cast<int32_t>(f % W);
    }
  p.read = std::move(read);
  return p;
}

// Thread the read along the traceback (PoaGraph.commit_add).
std::vector<int32_t> CommitAdd(Graph& g, const Plan& plan) {
  const std::vector<int8_t>& read = plan.read;
  int32_t I = static_cast<int32_t>(read.size());
  size_t w = static_cast<size_t>(I) + 1;  // size_t stride (see TryAddRead)
  std::vector<int32_t> path(I, -1);

  auto new_chain_vertex = [&](int32_t i, int32_t fork) {
    int32_t nv = AddVertex(g, read[i - 1]);
    if (fork >= 0) AddEdge(g, nv, fork);
    path[i - 1] = nv;
    return nv;
  };

  int32_t fork = -1;
  int32_t i = I;
  while (i > plan.best_row) {
    fork = new_chain_vertex(i, fork);
    --i;
  }

  int32_t v = plan.best_vertex;
  int32_t prev_visited = -1;
  while (v >= 0 && i >= 0) {
    float cell = plan.cols[v * w + i];
    int8_t vb = g.base[v];
    int32_t mp = plan.mpred[v * w + i];
    int32_t dp = plan.dpred[v * w + i];
    float m_val = kNegInf, e_val = kNegInf;
    if (i > 0) {
      float sub = read[i - 1] == vb ? kMatch : kMismatch;
      m_val = (mp >= 0 ? plan.cols[mp * w + i - 1] : 0.0f) + sub;
      e_val = plan.cols[v * w + i - 1] + kInsert;
    }
    float d_val = (dp >= 0 ? plan.cols[dp * w + i] : 0.0f) + kDelete;

    if (i > 0 && cell == m_val) {
      if (read[i - 1] == vb) {
        g.have_scores = false;
        ++g.nreads[v];
        if (fork >= 0) {
          AddEdge(g, v, fork);
          fork = -1;
        }
        path[i - 1] = v;
      } else {
        if (fork < 0) fork = prev_visited;
        fork = new_chain_vertex(i, fork);
      }
      --i;
      prev_visited = v;
      v = mp;
    } else if (cell == d_val && dp >= 0) {
      if (fork < 0) fork = prev_visited;
      prev_visited = v;
      v = dp;
    } else if (i > 0 && cell == e_val) {
      if (fork < 0) fork = prev_visited;
      fork = new_chain_vertex(i, fork);
      --i;
    } else {
      break;  // StartMove: alignment starts here
    }
  }

  if (i > 0 && fork < 0) fork = prev_visited;
  while (i > 0) {
    fork = new_chain_vertex(i, fork);
    --i;
  }

  ++g.n_reads;
  TagSpan(g, path.front(), plan.best_vertex);
  return path;
}

std::vector<int32_t> ConsensusPath(Graph& g, int32_t min_cov) {
  size_t n = g.base.size();
  g.score.assign(n, 0.0);
  g.have_scores = true;
  std::vector<double> reach(n, 0.0);
  std::vector<int32_t> bprev(n, -1);
  int32_t best_v = -1;
  double best_score = -1e300;
  for (int32_t v : TopoOrder(g)) {
    double sc = 2.0 * g.nreads[v] -
                std::max<int32_t>(g.spanning[v], min_cov) - 1e-4;
    g.score[v] = sc;
    double r = sc;
    int32_t bp = -1;
    for (int32_t pr : g.preds[v]) {
      double c = sc + reach[pr];
      if (c > r) {
        r = c;
        bp = pr;
      }
    }
    reach[v] = r;
    bprev[v] = bp;
    if (r > best_score || (r == best_score && v < best_v)) {
      best_score = r;
      best_v = v;
    }
  }
  std::vector<int32_t> path;
  for (int32_t v = best_v; v >= 0; v = bprev[v]) path.push_back(v);
  std::reverse(path.begin(), path.end());
  return path;
}

}  // namespace poa

extern "C" {

void* pbccs_poa_new() { return new poa::Graph(); }
void pbccs_poa_free(void* h) { delete static_cast<poa::Graph*>(h); }

// Add a read in its better orientation if the LOCAL alignment score clears
// min_score (SparsePoa.orient_and_add_read).  Writes the per-base vertex
// path (oriented read order) and whether the reverse complement was used.
// Returns 1 if added, 0 if rejected.
int32_t pbccs_poa_orient_add(void* h, const int8_t* read, int32_t n,
                             float min_score, int32_t* out_path,
                             uint8_t* out_rc) {
  auto* g = static_cast<poa::Graph*>(h);
  if (n <= 0) return 0;
  if (g->n_reads == 0) {
    auto path = poa::AddFirstRead(*g, read, n);
    std::memcpy(out_path, path.data(), n * sizeof(int32_t));
    *out_rc = 0;
    return 1;
  }
  std::vector<int8_t> fwd(read, read + n), rev(n);
  for (int32_t i = 0; i < n; ++i) {
    int8_t b = read[n - 1 - i];
    rev[i] = b < 4 ? static_cast<int8_t>(3 - b) : b;
  }
  poa::Plan pf = poa::TryAddRead(*g, std::move(fwd), false);
  poa::Plan pr = poa::TryAddRead(*g, std::move(rev), true);
  poa::Plan& plan = pf.score >= pr.score ? pf : pr;
  if (plan.score < min_score) return 0;
  auto path = poa::CommitAdd(*g, plan);
  std::memcpy(out_path, path.data(), n * sizeof(int32_t));
  *out_rc = plan.rc ? 1 : 0;
  return 1;
}

// Consensus path vertex ids; returns length (or -needed if cap too small).
int32_t pbccs_poa_consensus(void* h, int32_t min_cov, int32_t* out_vs,
                            int32_t cap) {
  auto* g = static_cast<poa::Graph*>(h);
  auto path = poa::ConsensusPath(*g, min_cov);
  int32_t m = static_cast<int32_t>(path.size());
  if (m > cap) return -m;
  std::memcpy(out_vs, path.data(), m * sizeof(int32_t));
  return m;
}

int32_t pbccs_poa_vertex_count(void* h) {
  return static_cast<int32_t>(static_cast<poa::Graph*>(h)->base.size());
}

// Per-vertex state snapshot; score is valid only after a consensus call
// on the current topology (returns 0 scores otherwise).
int32_t pbccs_poa_export(void* h, int8_t* base, int32_t* nreads,
                         int32_t* spanning, double* score) {
  auto* g = static_cast<poa::Graph*>(h);
  int32_t n = static_cast<int32_t>(g->base.size());
  std::memcpy(base, g->base.data(), n);
  std::memcpy(nreads, g->nreads.data(), n * sizeof(int32_t));
  std::memcpy(spanning, g->spanning.data(), n * sizeof(int32_t));
  for (int32_t v = 0; v < n; ++v)
    score[v] = g->have_scores ? g->score[v] : 0.0;
  return g->have_scores ? n : -n;
}

int32_t pbccs_poa_edge_count(void* h) {
  auto* g = static_cast<poa::Graph*>(h);
  size_t e = 0;
  for (auto& s : g->succs) e += s.size();
  return static_cast<int32_t>(e);
}

void pbccs_poa_edges(void* h, int32_t* u, int32_t* v) {
  auto* g = static_cast<poa::Graph*>(h);
  size_t k = 0;
  for (size_t a = 0; a < g->succs.size(); ++a)
    for (int32_t b : g->succs[a]) {
      u[k] = static_cast<int32_t>(a);
      v[k] = b;
      ++k;
    }
}

}  // extern "C"
