// Native host-runtime components for pbccs_tpu.
//
// TPU-native re-implementations of the reference's C++ host layers:
//  * BGZF block codec (the reference delegates BAM IO to pbbam/htslib;
//    here the hot (de)compression path is multithreaded over 64KB BGZF
//    blocks, which htslib also does in its bgzf_mt mode).
//  * Sparse-DP seed chaining (reference include/pacbio/ccs/ChainSeeds.h +
//    src/ChainSeeds.cpp sweep-line SDP), same link-gain semantics as
//    pbccs_tpu.align.seeds.chain_seeds, exposed for the host draft stage.
//
// Exposed as a plain C ABI consumed via ctypes (pbccs_tpu/native.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

constexpr int kBlockPayload = 64 * 1024 - 512;  // matches io/bam.py _MAX_BLOCK

// one BGZF block: gzip member with BC extra subfield carrying BSIZE
bool CompressBlock(const uint8_t* data, size_t len, int level,
                   std::vector<uint8_t>* out) {
  uLong bound = compressBound(len) + 64;
  std::vector<uint8_t> payload(bound);
  z_stream zs{};
  if (deflateInit2(&zs, level, Z_DEFLATED, -15, 8, Z_DEFAULT_STRATEGY) != Z_OK)
    return false;
  zs.next_in = const_cast<Bytef*>(data);
  zs.avail_in = len;
  zs.next_out = payload.data();
  zs.avail_out = payload.size();
  int rc = deflate(&zs, Z_FINISH);
  deflateEnd(&zs);
  if (rc != Z_STREAM_END) return false;
  size_t clen = zs.total_out;

  static const uint8_t kHeader[16] = {0x1f, 0x8b, 0x08, 0x04, 0, 0, 0, 0,
                                      0,    0xff, 0x06, 0,    0x42, 0x43,
                                      0x02, 0};
  size_t total = 16 + 2 + clen + 8;
  out->resize(total);
  std::memcpy(out->data(), kHeader, 16);
  uint16_t bsize = static_cast<uint16_t>(total - 1);
  (*out)[16] = bsize & 0xff;
  (*out)[17] = bsize >> 8;
  std::memcpy(out->data() + 18, payload.data(), clen);
  uint32_t crc = crc32(0, data, len);
  uint32_t isize = static_cast<uint32_t>(len);
  uint8_t* tail = out->data() + 18 + clen;
  for (int b = 0; b < 4; ++b) tail[b] = (crc >> (8 * b)) & 0xff;
  for (int b = 0; b < 4; ++b) tail[4 + b] = (isize >> (8 * b)) & 0xff;
  return true;
}

}  // namespace

extern "C" {

// Compress `len` bytes into consecutive BGZF blocks of kBlockPayload bytes
// using `nthreads` workers.  Returns the number of bytes written to `out`
// (capacity `out_cap`), or -1 on failure / insufficient capacity.
int64_t pbccs_bgzf_compress(const uint8_t* data, int64_t len, int level,
                            int nthreads, uint8_t* out, int64_t out_cap) {
  if (len < 0) return -1;
  size_t nblocks = (len + kBlockPayload - 1) / kBlockPayload;
  if (nblocks == 0) return 0;
  std::vector<std::vector<uint8_t>> blocks(nblocks);
  std::vector<char> ok(nblocks, 1);
  nthreads = std::max(1, std::min<int>(nthreads, nblocks));

  auto worker = [&](size_t t) {
    for (size_t b = t; b < nblocks; b += nthreads) {
      size_t off = b * static_cast<size_t>(kBlockPayload);
      size_t n = std::min<size_t>(kBlockPayload, len - off);
      if (!CompressBlock(data + off, n, level, &blocks[b])) ok[b] = 0;
    }
  };
  std::vector<std::thread> threads;
  for (int t = 1; t < nthreads; ++t) threads.emplace_back(worker, t);
  worker(0);
  for (auto& th : threads) th.join();

  int64_t total = 0;
  for (size_t b = 0; b < nblocks; ++b) {
    if (!ok[b]) return -1;
    total += blocks[b].size();
  }
  if (total > out_cap) return -1;
  uint8_t* p = out;
  for (auto& blk : blocks) {
    std::memcpy(p, blk.data(), blk.size());
    p += blk.size();
  }
  return total;
}

// Decompress a BGZF byte stream (concatenated blocks; the 28-byte EOF
// block decodes to zero bytes).  Returns bytes written, -1 on malformed
// input, or -2 when out_cap is too small (retryable).
int64_t pbccs_bgzf_decompress(const uint8_t* data, int64_t len, uint8_t* out,
                              int64_t out_cap) {
  int64_t ip = 0, op = 0;
  while (ip + 18 <= len) {
    if (data[ip] != 0x1f || data[ip + 1] != 0x8b) return -1;
    uint16_t xlen = data[ip + 10] | (data[ip + 11] << 8);
    // find BC subfield for BSIZE
    int64_t xoff = ip + 12;
    int64_t bsize = -1;
    int64_t xend = xoff + xlen;
    while (xoff + 4 <= xend) {
      uint8_t si1 = data[xoff], si2 = data[xoff + 1];
      uint16_t slen = data[xoff + 2] | (data[xoff + 3] << 8);
      if (si1 == 'B' && si2 == 'C' && slen == 2)
        bsize = (data[xoff + 4] | (data[xoff + 5] << 8)) + 1;
      xoff += 4 + slen;
    }
    if (bsize < 0 || ip + bsize > len) return -1;
    int64_t cdata_off = ip + 12 + xlen;
    int64_t cdata_len = bsize - 12 - xlen - 8;
    if (cdata_len < 0 || cdata_off + cdata_len + 8 > ip + bsize) return -1;
    uint32_t isize = data[ip + bsize - 4] | (data[ip + bsize - 3] << 8) |
                     (data[ip + bsize - 2] << 16) | (data[ip + bsize - 1] << 24);
    if (op + isize > out_cap) return -2;  // under-capacity, caller may retry
    if (isize > 0) {
      z_stream zs{};
      if (inflateInit2(&zs, -15) != Z_OK) return -1;
      zs.next_in = const_cast<Bytef*>(data + cdata_off);
      zs.avail_in = cdata_len;
      zs.next_out = out + op;
      zs.avail_out = out_cap - op;
      int rc = inflate(&zs, Z_FINISH);
      inflateEnd(&zs);
      if (rc != Z_STREAM_END || zs.total_out != isize) return -1;
    }
    op += isize;
    ip += bsize;
  }
  return (ip == len || ip == len - 0) ? op : -1;
}

// Sparse-DP seed chaining; same semantics as align.seeds.chain_seeds:
// seeds (h[i], v[i]), chain gain mr*matches - |d_diag| - indels, links only
// to strictly earlier rows with h_b < h_a, ties -> nearest predecessor in
// (v, h)-sorted order.  Writes the chained (h, v) pairs; returns length.
int32_t pbccs_chain_seeds(const int32_t* h, const int32_t* v, int32_t n,
                          int32_t k, int32_t match_reward, int32_t* out_h,
                          int32_t* out_v) {
  if (n <= 0) return 0;
  std::vector<int32_t> idx(n);
  std::iota(idx.begin(), idx.end(), 0);
  std::stable_sort(idx.begin(), idx.end(), [&](int a, int b) {
    if (v[a] != v[b]) return v[a] < v[b];
    return h[a] < h[b];
  });
  std::vector<int64_t> H(n), V(n), D(n), score(n);
  std::vector<int32_t> pred(n, -1);
  for (int i = 0; i < n; ++i) {
    H[i] = h[idx[i]];
    V[i] = v[idx[i]];
    D[i] = H[i] - V[i];
    score[i] = k;
  }
  int row_start = 0;
  for (int a = 0; a < n; ++a) {
    if (V[a] != V[row_start]) row_start = a;
    int64_t best_score = 0;
    int32_t best = -1;
    for (int b = row_start - 1; b >= 0; --b) {  // reverse: nearest wins ties
      if (H[b] >= H[a]) continue;
      int64_t fwd = std::min(H[a] - H[b], V[a] - V[b]);
      int64_t matches = k - std::max<int64_t>(0, k - fwd);
      int64_t link = match_reward * matches - std::llabs(D[a] - D[b]) -
                     (fwd - matches);
      int64_t cand = score[b] + link;
      if (cand > best_score) {
        best_score = cand;
        best = b;
      }
    }
    if (best >= 0 && best_score > 0) {
      score[a] = best_score;
      pred[a] = best;
    }
  }
  int32_t end = -1;
  int64_t best_end = -1;
  for (int i = 0; i < n; ++i)
    if (pred[i] >= 0 && score[i] > best_end) {
      best_end = score[i];
      end = i;
    }
  if (end < 0) return 0;
  std::vector<int32_t> chain;
  for (int32_t cur = end; cur >= 0; cur = pred[cur]) chain.push_back(cur);
  std::reverse(chain.begin(), chain.end());
  for (size_t i = 0; i < chain.size(); ++i) {
    out_h[i] = static_cast<int32_t>(H[chain[i]]);
    out_v[i] = static_cast<int32_t>(V[chain[i]]);
  }
  return static_cast<int32_t>(chain.size());
}

}  // extern "C"
