// Native host-runtime components for pbccs_tpu.
//
// TPU-native re-implementations of the reference's C++ host layers:
//  * BGZF block codec (the reference delegates BAM IO to pbbam/htslib;
//    here the hot (de)compression path is multithreaded over 64KB BGZF
//    blocks, which htslib also does in its bgzf_mt mode).
//  * Sparse-DP seed chaining (reference include/pacbio/ccs/ChainSeeds.h +
//    src/ChainSeeds.cpp sweep-line SDP), same link-gain semantics as
//    pbccs_tpu.align.seeds.chain_seeds, exposed for the host draft stage.
//  * Partial-order-alignment draft engine (reference ConsensusCore Poa:
//    PoaGraphImpl alignment/threading/consensus, src/C++/Poa/*), the
//    behavior-identical native backend of pbccs_tpu.poa.graph.PoaGraph --
//    the draft stage is the host-side bottleneck once polishing runs on
//    the accelerator.
//
// Exposed as a plain C ABI consumed via ctypes (pbccs_tpu/native.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

constexpr int kBlockPayload = 64 * 1024 - 512;  // matches io/bam.py _MAX_BLOCK

// one BGZF block: gzip member with BC extra subfield carrying BSIZE
bool CompressBlock(const uint8_t* data, size_t len, int level,
                   std::vector<uint8_t>* out) {
  uLong bound = compressBound(len) + 64;
  std::vector<uint8_t> payload(bound);
  z_stream zs{};
  if (deflateInit2(&zs, level, Z_DEFLATED, -15, 8, Z_DEFAULT_STRATEGY) != Z_OK)
    return false;
  zs.next_in = const_cast<Bytef*>(data);
  zs.avail_in = len;
  zs.next_out = payload.data();
  zs.avail_out = payload.size();
  int rc = deflate(&zs, Z_FINISH);
  deflateEnd(&zs);
  if (rc != Z_STREAM_END) return false;
  size_t clen = zs.total_out;

  static const uint8_t kHeader[16] = {0x1f, 0x8b, 0x08, 0x04, 0, 0, 0, 0,
                                      0,    0xff, 0x06, 0,    0x42, 0x43,
                                      0x02, 0};
  size_t total = 16 + 2 + clen + 8;
  out->resize(total);
  std::memcpy(out->data(), kHeader, 16);
  uint16_t bsize = static_cast<uint16_t>(total - 1);
  (*out)[16] = bsize & 0xff;
  (*out)[17] = bsize >> 8;
  std::memcpy(out->data() + 18, payload.data(), clen);
  uint32_t crc = crc32(0, data, len);
  uint32_t isize = static_cast<uint32_t>(len);
  uint8_t* tail = out->data() + 18 + clen;
  for (int b = 0; b < 4; ++b) tail[b] = (crc >> (8 * b)) & 0xff;
  for (int b = 0; b < 4; ++b) tail[4 + b] = (isize >> (8 * b)) & 0xff;
  return true;
}

}  // namespace

extern "C" {

// Compress `len` bytes into consecutive BGZF blocks of kBlockPayload bytes
// using `nthreads` workers.  Returns the number of bytes written to `out`
// (capacity `out_cap`), or -1 on failure / insufficient capacity.
int64_t pbccs_bgzf_compress(const uint8_t* data, int64_t len, int level,
                            int nthreads, uint8_t* out, int64_t out_cap) {
  if (len < 0) return -1;
  size_t nblocks = (len + kBlockPayload - 1) / kBlockPayload;
  if (nblocks == 0) return 0;
  std::vector<std::vector<uint8_t>> blocks(nblocks);
  std::vector<char> ok(nblocks, 1);
  nthreads = std::max(1, std::min<int>(nthreads, nblocks));

  auto worker = [&](size_t t) {
    for (size_t b = t; b < nblocks; b += nthreads) {
      size_t off = b * static_cast<size_t>(kBlockPayload);
      size_t n = std::min<size_t>(kBlockPayload, len - off);
      if (!CompressBlock(data + off, n, level, &blocks[b])) ok[b] = 0;
    }
  };
  std::vector<std::thread> threads;
  for (int t = 1; t < nthreads; ++t) threads.emplace_back(worker, t);
  worker(0);
  for (auto& th : threads) th.join();

  int64_t total = 0;
  for (size_t b = 0; b < nblocks; ++b) {
    if (!ok[b]) return -1;
    total += blocks[b].size();
  }
  if (total > out_cap) return -1;
  uint8_t* p = out;
  for (auto& blk : blocks) {
    std::memcpy(p, blk.data(), blk.size());
    p += blk.size();
  }
  return total;
}

// Decompress a BGZF byte stream (concatenated blocks; the 28-byte EOF
// block decodes to zero bytes).  Returns bytes written, -1 on malformed
// input, or -2 when out_cap is too small (retryable).
int64_t pbccs_bgzf_decompress(const uint8_t* data, int64_t len, uint8_t* out,
                              int64_t out_cap) {
  int64_t ip = 0, op = 0;
  while (ip + 18 <= len) {
    if (data[ip] != 0x1f || data[ip + 1] != 0x8b) return -1;
    uint16_t xlen = data[ip + 10] | (data[ip + 11] << 8);
    // find BC subfield for BSIZE
    int64_t xoff = ip + 12;
    int64_t bsize = -1;
    int64_t xend = xoff + xlen;
    while (xoff + 4 <= xend) {
      uint8_t si1 = data[xoff], si2 = data[xoff + 1];
      uint16_t slen = data[xoff + 2] | (data[xoff + 3] << 8);
      if (si1 == 'B' && si2 == 'C' && slen == 2)
        bsize = (data[xoff + 4] | (data[xoff + 5] << 8)) + 1;
      xoff += 4 + slen;
    }
    if (bsize < 0 || ip + bsize > len) return -1;
    int64_t cdata_off = ip + 12 + xlen;
    int64_t cdata_len = bsize - 12 - xlen - 8;
    if (cdata_len < 0 || cdata_off + cdata_len + 8 > ip + bsize) return -1;
    uint32_t isize = data[ip + bsize - 4] | (data[ip + bsize - 3] << 8) |
                     (data[ip + bsize - 2] << 16) | (data[ip + bsize - 1] << 24);
    if (op + isize > out_cap) return -2;  // under-capacity, caller may retry
    if (isize > 0) {
      z_stream zs{};
      if (inflateInit2(&zs, -15) != Z_OK) return -1;
      zs.next_in = const_cast<Bytef*>(data + cdata_off);
      zs.avail_in = cdata_len;
      zs.next_out = out + op;
      zs.avail_out = out_cap - op;
      int rc = inflate(&zs, Z_FINISH);
      inflateEnd(&zs);
      if (rc != Z_STREAM_END || zs.total_out != isize) return -1;
    }
    op += isize;
    ip += bsize;
  }
  return (ip == len || ip == len - 0) ? op : -1;
}

// Sparse-DP seed chaining; same semantics as align.seeds.chain_seeds:
// seeds (h[i], v[i]), chain gain mr*matches - |d_diag| - indels, links only
// to strictly earlier rows with h_b < h_a, ties -> nearest predecessor in
// (v, h)-sorted order.  Writes the chained (h, v) pairs; returns length.
int32_t pbccs_chain_seeds(const int32_t* h, const int32_t* v, int32_t n,
                          int32_t k, int32_t match_reward, int32_t* out_h,
                          int32_t* out_v) {
  if (n <= 0) return 0;
  std::vector<int32_t> idx(n);
  std::iota(idx.begin(), idx.end(), 0);
  std::stable_sort(idx.begin(), idx.end(), [&](int a, int b) {
    if (v[a] != v[b]) return v[a] < v[b];
    return h[a] < h[b];
  });
  std::vector<int64_t> H(n), V(n), D(n), score(n);
  std::vector<int32_t> pred(n, -1);
  for (int i = 0; i < n; ++i) {
    H[i] = h[idx[i]];
    V[i] = v[idx[i]];
    D[i] = H[i] - V[i];
    score[i] = k;
  }
  int row_start = 0;
  for (int a = 0; a < n; ++a) {
    if (V[a] != V[row_start]) row_start = a;
    int64_t best_score = 0;
    int32_t best = -1;
    for (int b = row_start - 1; b >= 0; --b) {  // reverse: nearest wins ties
      if (H[b] >= H[a]) continue;
      int64_t fwd = std::min(H[a] - H[b], V[a] - V[b]);
      int64_t matches = k - std::max<int64_t>(0, k - fwd);
      int64_t link = match_reward * matches - std::llabs(D[a] - D[b]) -
                     (fwd - matches);
      int64_t cand = score[b] + link;
      if (cand > best_score) {
        best_score = cand;
        best = b;
      }
    }
    if (best >= 0 && best_score > 0) {
      score[a] = best_score;
      pred[a] = best;
    }
  }
  int32_t end = -1;
  int64_t best_end = -1;
  for (int i = 0; i < n; ++i)
    if (pred[i] >= 0 && score[i] > best_end) {
      best_end = score[i];
      end = i;
    }
  if (end < 0) return 0;
  std::vector<int32_t> chain;
  for (int32_t cur = end; cur >= 0; cur = pred[cur]) chain.push_back(cur);
  std::reverse(chain.begin(), chain.end());
  for (size_t i = 0; i < chain.size(); ++i) {
    out_h[i] = static_cast<int32_t>(H[chain[i]]);
    out_v[i] = static_cast<int32_t>(V[chain[i]]);
  }
  return static_cast<int32_t>(chain.size());
}

}  // extern "C"

// ---------------------------------------------------------------------------
// POA draft engine.  Behavior-identical native backend of
// pbccs_tpu.poa.graph.PoaGraph (LOCAL read-vs-DAG alignment with
// match=+3 / mismatch=-5 / insert=-4 / delete=-4, traceback threading,
// spanning-read tagging, best-sum consensus path).  All scores are sums of
// small integers, so float equality in the traceback is exact on both the
// numpy and native paths.
// ---------------------------------------------------------------------------

namespace poa {

constexpr float kMatch = 3.0f, kMismatch = -5.0f;
constexpr float kInsert = -4.0f, kDelete = -4.0f;
constexpr float kNegInf = -1e30f;

struct Graph {
  std::vector<int8_t> base;
  std::vector<int32_t> nreads, spanning;
  std::vector<std::vector<int32_t>> preds, succs;
  int32_t n_reads = 0;
  std::vector<double> score;  // consensus-path vertex scores
  bool have_scores = false;
};

// Per-vertex banded DP storage: vertex v's column holds rows [lo[v], hi[v])
// at cols[off[v]..]; cells outside the band read as 0 = "a LOCAL alignment
// may start here" (consistent with the fill's max(0, ...) floor), preds as
// -1.  An unbanded plan is simply lo=0, hi=I+1 everywhere.
struct Plan {
  float score = kNegInf;
  int32_t best_vertex = -1, best_row = 0;
  bool rc = false;
  std::vector<int8_t> read;           // oriented read
  std::vector<int32_t> lo, hi;        // per-vertex DP-row band
  std::vector<int64_t> off;           // per-vertex offset into banded arrays
  std::vector<float> cols;            // sum of band widths
  std::vector<int32_t> mpred, dpred;

  float Cell(int32_t v, int32_t i) const {
    return (i >= lo[v] && i < hi[v]) ? cols[off[v] + i - lo[v]] : 0.0f;
  }
  int32_t MPred(int32_t v, int32_t i) const {
    return (i >= lo[v] && i < hi[v]) ? mpred[off[v] + i - lo[v]] : -1;
  }
  int32_t DPred(int32_t v, int32_t i) const {
    return (i >= lo[v] && i < hi[v]) ? dpred[off[v] + i - lo[v]] : -1;
  }
  bool InBand(int32_t v, int32_t i) const {
    return i >= lo[v] && i < hi[v];
  }
};

int32_t AddVertex(Graph& g, int8_t b) {
  g.have_scores = false;
  g.base.push_back(b);
  g.nreads.push_back(1);
  g.spanning.push_back(0);
  g.preds.emplace_back();
  g.succs.emplace_back();
  return static_cast<int32_t>(g.base.size()) - 1;
}

void AddEdge(Graph& g, int32_t u, int32_t v) {
  if (u == v) return;
  auto& s = g.succs[u];
  if (std::find(s.begin(), s.end(), v) == s.end()) {
    s.push_back(v);
    g.preds[v].push_back(u);
  }
}

std::vector<int32_t> TopoOrder(const Graph& g) {
  size_t n = g.base.size();
  std::vector<int32_t> indeg(n), order;
  order.reserve(n);
  std::vector<int32_t> q;  // FIFO via index
  for (size_t v = 0; v < n; ++v) {
    indeg[v] = static_cast<int32_t>(g.preds[v].size());
    if (indeg[v] == 0) q.push_back(static_cast<int32_t>(v));
  }
  for (size_t head = 0; head < q.size(); ++head) {
    int32_t v = q[head];
    order.push_back(v);
    for (int32_t w : g.succs[v])
      if (--indeg[w] == 0) q.push_back(w);
  }
  return order;
}

void Reachable(const Graph& g, int32_t root,
               const std::vector<std::vector<int32_t>>& adj,
               std::vector<char>* seen) {
  std::vector<int32_t> stack{root};
  (*seen)[root] = 1;
  while (!stack.empty()) {
    int32_t u = stack.back();
    stack.pop_back();
    for (int32_t w : adj[u])
      if (!(*seen)[w]) {
        (*seen)[w] = 1;
        stack.push_back(w);
      }
  }
}

void TagSpan(Graph& g, int32_t start, int32_t end) {
  size_t n = g.base.size();
  std::vector<char> fwd(n, 0), bwd(n, 0);
  Reachable(g, start, g.succs, &fwd);
  Reachable(g, end, g.preds, &bwd);
  for (size_t v = 0; v < n; ++v)
    if (fwd[v] && bwd[v]) ++g.spanning[v];
}

std::vector<int32_t> AddFirstRead(Graph& g, const int8_t* read, int32_t n) {
  std::vector<int32_t> path;
  path.reserve(n);
  int32_t prev = -1;
  for (int32_t i = 0; i < n; ++i) {
    int32_t v = AddVertex(g, read[i]);
    if (prev >= 0) AddEdge(g, prev, v);
    path.push_back(v);
    prev = v;
  }
  ++g.n_reads;
  TagSpan(g, path.front(), path.back());
  return path;
}

// ---- SDP-anchored banding (reference RangeFinder.cpp:72-167 semantics;
// see pbccs_tpu/poa/banding.py for the full derivation notes). ----

// Shared k-mer (cssPos, readPos) seeds via a sorted (hash, pos) table over
// the css; homopolymer k-mers and k-mers occurring > kMaxOcc times in the
// css are masked (reference HpHasher + FilterSeeds intent).

std::vector<int64_t> KmerHashes(const std::vector<int8_t>& s, int32_t k) {
  const int64_t mask = (int64_t(1) << (2 * k)) - 1;
  std::vector<int64_t> h(s.size() >= size_t(k) ? s.size() - k + 1 : 0, -1);
  int64_t cur = 0;
  int32_t valid = 0;
  for (size_t i = 0; i < s.size(); ++i) {
    if (s[i] < 0 || s[i] > 3) {
      valid = 0;
      cur = 0;
    } else {
      cur = ((cur << 2) | s[i]) & mask;
      ++valid;
    }
    if (valid >= k && i + 1 >= size_t(k)) h[i + 1 - k] = cur;
  }
  return h;
}

// Sorted (hash, cssPos) table -- built ONCE per read and shared by the
// forward/RC orientation seed searches (it depends only on the css).
std::vector<std::pair<int64_t, int32_t>> SeedTable(
    const std::vector<int8_t>& css, int32_t k) {
  auto h1 = KmerHashes(css, k);
  std::vector<std::pair<int64_t, int32_t>> table;
  table.reserve(h1.size());
  for (size_t i = 0; i < h1.size(); ++i)
    if (h1[i] >= 0) table.emplace_back(h1[i], static_cast<int32_t>(i));
  std::sort(table.begin(), table.end());
  return table;
}

void FindSeedsInTable(const std::vector<std::pair<int64_t, int32_t>>& table,
                      const std::vector<int8_t>& read, int32_t k,
                      std::vector<int32_t>* sh, std::vector<int32_t>* sv) {
  constexpr int32_t kMaxOcc = 64;
  std::vector<int64_t> hp(4);  // homopolymer hashes
  for (int64_t b = 0; b < 4; ++b) {
    int64_t v = 0;
    for (int32_t j = 0; j < k; ++j) v = (v << 2) | b;
    hp[b] = v;
  }
  auto h2 = KmerHashes(read, k);
  for (size_t j = 0; j < h2.size(); ++j) {
    int64_t h = h2[j];
    if (h < 0 || h == hp[0] || h == hp[1] || h == hp[2] || h == hp[3])
      continue;
    auto lo = std::lower_bound(table.begin(), table.end(),
                               std::make_pair(h, INT32_MIN));
    auto hi = std::upper_bound(table.begin(), table.end(),
                               std::make_pair(h, INT32_MAX));
    if (hi - lo > kMaxOcc) continue;
    for (auto it = lo; it != hi; ++it) {
      sh->push_back(it->second);
      sv->push_back(static_cast<int32_t>(j));
    }
  }
}


// Longest strictly-increasing (cssPos, readPos) subsequence of the seeds:
// the banding anchor chain, O(n log n) patience LIS.  Mirror of
// pbccs_tpu.poa.banding.anchor_chain (see its docstring for why the scored
// SDP chainer is not used on this path).
void AnchorChain(std::vector<int32_t>* sh, std::vector<int32_t>* sv) {
  const int32_t n = static_cast<int32_t>(sh->size());
  if (n == 0) return;
  std::vector<int32_t> idx(n);
  std::iota(idx.begin(), idx.end(), 0);
  // cssPos asc, readPos DESC so equal-cssPos seeds cannot chain together
  std::stable_sort(idx.begin(), idx.end(), [&](int32_t a, int32_t b) {
    if ((*sh)[a] != (*sh)[b]) return (*sh)[a] < (*sh)[b];
    return (*sv)[a] > (*sv)[b];
  });
  std::vector<int32_t> tails_r, tails_i, parent(n, -1);
  for (int32_t i = 0; i < n; ++i) {
    int32_t r = (*sv)[idx[i]];
    auto it = std::lower_bound(tails_r.begin(), tails_r.end(), r);
    size_t k = it - tails_r.begin();
    parent[i] = k ? tails_i[k - 1] : -1;
    if (it == tails_r.end()) {
      tails_r.push_back(r);
      tails_i.push_back(i);
    } else {
      *it = r;
      tails_i[k] = i;
    }
  }
  std::vector<int32_t> chain;
  for (int32_t i = tails_i.back(); i >= 0; i = parent[i]) chain.push_back(i);
  std::reverse(chain.begin(), chain.end());
  std::vector<int32_t> ch(chain.size()), cv(chain.size());
  for (size_t a = 0; a < chain.size(); ++a) {
    ch[a] = (*sh)[idx[chain[a]]];
    cv[a] = (*sv)[idx[chain[a]]];
  }
  sh->swap(ch);
  sv->swap(cv);
}

// Per-vertex DP-row bands [lo, hi) from chained anchors css<->read:
// direct ranges +-WIDTH at anchored consensus-path vertices, forward/
// reverse closure over the DAG, hull of both, full-width fallback for
// vertices both closures miss.  Returns empty (=> unbanded fill) when
// fewer than 2 anchors chain.
std::vector<int32_t> SdpBands(const Graph& g,
                              const std::vector<int32_t>& topo,
                              const std::vector<int32_t>& css_path,
                              const std::vector<int32_t>& ch,
                              const std::vector<int32_t>& cv,
                              int32_t read_len) {
  constexpr int32_t kWidth = 30;   // reference RangeFinder.cpp:15
  const int32_t I = read_len;
  const int32_t m = static_cast<int32_t>(ch.size());
  if (m < 2) return {};

  const size_t n = g.base.size();
  constexpr int32_t kBig = INT32_MAX / 2;
  // hull-identity encoding: empty = (+big, -big); values are read positions
  std::vector<int32_t> dlo(n, kBig), dhi(n, -kBig);
  std::vector<char> direct(n, 0);
  for (int32_t a = 0; a < m; ++a) {
    int32_t v = css_path[ch[a]];
    dlo[v] = std::min(dlo[v], std::max(cv[a] - kWidth, 0));
    dhi[v] = std::max(dhi[v], std::min(cv[a] + kWidth, I));
    direct[v] = 1;
  }

  std::vector<int32_t> flo(dlo), fhi(dhi);
  for (int32_t v : topo)
    if (!direct[v] && !g.preds[v].empty()) {
      int32_t b = kBig, e = -kBig;
      for (int32_t p : g.preds[v])
        if (flo[p] <= fhi[p]) {
          b = std::min(b, std::min(flo[p] + 1, I));
          e = std::max(e, std::min(fhi[p] + 1, I));
        }
      flo[v] = b;
      fhi[v] = e;
    }
  std::vector<int32_t> rlo(dlo), rhi(dhi);
  for (auto it = topo.rbegin(); it != topo.rend(); ++it) {
    int32_t v = *it;
    if (!direct[v] && !g.succs[v].empty()) {
      int32_t b = kBig, e = -kBig;
      for (int32_t s : g.succs[v])
        if (rlo[s] <= rhi[s]) {
          b = std::min(b, std::max(rlo[s] - 1, 0));
          e = std::max(e, std::max(rhi[s] - 1, 0));
        }
      rlo[v] = b;
      rhi[v] = e;
    }
  }

  std::vector<int32_t> bands(2 * n);
  for (size_t v = 0; v < n; ++v) {
    int32_t b = std::min(flo[v], rlo[v]);
    int32_t e = std::max(fhi[v], rhi[v]);
    if (b > e) {  // both closures empty: full width
      b = 0;
      e = I;
    }
    // read positions [b, e] -> DP rows [b, e+2): row i consumes read
    // position i-1, +1 more so a trailing delete/extra row is reachable
    int32_t lo = std::max(0, std::min(b, I));
    int32_t hi = std::min(I + 1, std::max(e + 2, lo + 1));
    bands[2 * v] = lo;
    bands[2 * v + 1] = hi;
  }
  return bands;
}

// LOCAL alignment of `read` against the DAG (PoaGraph.try_add_read).
// `bands` (empty = unbanded) restricts vertex v's fill to DP rows
// [bands[2v], bands[2v+1]) -- the SDP-anchored banding of SdpBands().
Plan TryAddRead(const Graph& g, const std::vector<int32_t>& topo,
                std::vector<int8_t> read, bool rc,
                const std::vector<int32_t>& bands) {
  Plan p;
  p.rc = rc;
  int32_t I = static_cast<int32_t>(read.size());
  size_t n = g.base.size();
  int32_t w = I + 1;

  p.lo.resize(n);
  p.hi.resize(n);
  p.off.resize(n);
  int64_t total = 0;
  for (size_t v = 0; v < n; ++v) {
    p.lo[v] = bands.empty() ? 0 : bands[2 * v];
    p.hi[v] = bands.empty() ? w : bands[2 * v + 1];
    p.off[v] = total;
    total += p.hi[v] - p.lo[v];
  }
  p.cols.assign(total, 0.0f);
  p.mpred.assign(total, -1);
  p.dpred.assign(total, -1);
  std::vector<float> best_m(w), best_d(w);
  static const std::vector<int32_t> kNoPred{-1};

  for (int32_t v : topo) {
    int8_t vb = g.base[v];
    const int32_t lo = p.lo[v], hi = p.hi[v];
    std::fill(best_m.begin() + lo, best_m.begin() + hi, kNegInf);
    std::fill(best_d.begin() + lo, best_d.begin() + hi, kNegInf);
    int32_t* bm = &p.mpred[p.off[v]];  // banded: index with [i - lo]
    int32_t* bd = &p.dpred[p.off[v]];
    const auto& plist = g.preds[v].empty() ? kNoPred : g.preds[v];
    for (int32_t pr : plist) {
      // Segmented band fill: the predecessor's Cell() is a plain array
      // read inside its band [plo, phi) and a constant 0 outside, so
      // split each loop into (below, in-band, above) segments and drop
      // the per-cell bounds branches -- this loop pair is the native
      // POA's hottest code (gprof: ~60% of orient_add).
      const int32_t plo = pr < 0 ? 0 : p.lo[pr];
      const int32_t phi = pr < 0 ? 0 : p.hi[pr];
      const float* pc = pr < 0 ? nullptr : &p.cols[p.off[pr]];
      const int32_t a = std::max(lo, 1);
      // match: pred cell (i - 1), in-band for i in [plo + 1, phi + 1)
      const int32_t a1 = pc ? std::max(a, plo + 1) : hi;
      const int32_t b1 = pc ? std::min(hi, phi + 1) : hi;
      for (int32_t i = a; i < std::min(a1, hi); ++i) {
        float m = read[i - 1] == vb ? kMatch : kMismatch;
        if (m > best_m[i]) {
          best_m[i] = m;
          bm[i - lo] = pr;
        }
      }
      for (int32_t i = a1; i < b1; ++i) {
        float m = pc[i - 1 - plo] + (read[i - 1] == vb ? kMatch : kMismatch);
        if (m > best_m[i]) {
          best_m[i] = m;
          bm[i - lo] = pr;
        }
      }
      for (int32_t i = std::max(b1, a); i < hi; ++i) {
        float m = read[i - 1] == vb ? kMatch : kMismatch;
        if (m > best_m[i]) {
          best_m[i] = m;
          bm[i - lo] = pr;
        }
      }
      // delete: pred cell (i), in-band for i in [plo, phi)
      const int32_t c1 = pc ? std::max(lo, plo) : hi;
      const int32_t d1 = pc ? std::min(hi, phi) : hi;
      for (int32_t i = lo; i < std::min(c1, hi); ++i) {
        if (kDelete > best_d[i]) {
          best_d[i] = kDelete;
          bd[i - lo] = pr;
        }
      }
      for (int32_t i = c1; i < d1; ++i) {
        float d = pc[i - plo] + kDelete;
        if (d > best_d[i]) {
          best_d[i] = d;
          bd[i - lo] = pr;
        }
      }
      for (int32_t i = std::max(d1, lo); i < hi; ++i) {
        if (kDelete > best_d[i]) {
          best_d[i] = kDelete;
          bd[i - lo] = pr;
        }
      }
    }
    float* col = &p.cols[p.off[v]];
    float run = kNegInf;  // row lo-1 is out of band: 0 + kInsert < 0 <= b
    for (int32_t i = lo; i < hi; ++i) {
      float b = std::max(0.0f, std::max(best_m[i], best_d[i]));
      run = std::max(b, run + kInsert);
      col[i - lo] = run;
    }
  }
  // best local end: first strict max in (vertex, row) flat order
  for (size_t v = 0; v < n; ++v)
    for (int32_t i = p.lo[v]; i < p.hi[v]; ++i) {
      float c = p.cols[p.off[v] + i - p.lo[v]];
      if (c > p.score) {
        p.score = c;
        p.best_vertex = static_cast<int32_t>(v);
        p.best_row = i;
      }
    }
  p.read = std::move(read);
  return p;
}

// Thread the read along the traceback (PoaGraph.commit_add).
std::vector<int32_t> CommitAdd(Graph& g, const Plan& plan) {
  const std::vector<int8_t>& read = plan.read;
  int32_t I = static_cast<int32_t>(read.size());
  std::vector<int32_t> path(I, -1);

  auto new_chain_vertex = [&](int32_t i, int32_t fork) {
    int32_t nv = AddVertex(g, read[i - 1]);
    if (fork >= 0) AddEdge(g, nv, fork);
    path[i - 1] = nv;
    return nv;
  };

  int32_t fork = -1;
  int32_t i = I;
  while (i > plan.best_row) {
    fork = new_chain_vertex(i, fork);
    --i;
  }

  int32_t v = plan.best_vertex;
  int32_t prev_visited = -1;
  while (v >= 0 && i >= 0) {
    if (!plan.InBand(v, i)) break;  // walked outside the band: StartMove
    float cell = plan.Cell(v, i);
    int8_t vb = g.base[v];
    int32_t mp = plan.MPred(v, i);
    int32_t dp = plan.DPred(v, i);
    float m_val = kNegInf, e_val = kNegInf;
    if (i > 0) {
      float sub = read[i - 1] == vb ? kMatch : kMismatch;
      m_val = (mp >= 0 ? plan.Cell(mp, i - 1) : 0.0f) + sub;
      e_val = plan.Cell(v, i - 1) + kInsert;
    }
    float d_val = (dp >= 0 ? plan.Cell(dp, i) : 0.0f) + kDelete;

    if (i > 0 && cell == m_val) {
      if (read[i - 1] == vb) {
        g.have_scores = false;
        ++g.nreads[v];
        if (fork >= 0) {
          AddEdge(g, v, fork);
          fork = -1;
        }
        path[i - 1] = v;
      } else {
        if (fork < 0) fork = prev_visited;
        fork = new_chain_vertex(i, fork);
      }
      --i;
      prev_visited = v;
      v = mp;
    } else if (cell == d_val && dp >= 0) {
      if (fork < 0) fork = prev_visited;
      prev_visited = v;
      v = dp;
    } else if (i > 0 && cell == e_val) {
      if (fork < 0) fork = prev_visited;
      fork = new_chain_vertex(i, fork);
      --i;
    } else {
      break;  // StartMove: alignment starts here
    }
  }

  if (i > 0 && fork < 0) fork = prev_visited;
  while (i > 0) {
    fork = new_chain_vertex(i, fork);
    --i;
  }

  ++g.n_reads;
  TagSpan(g, path.front(), plan.best_vertex);
  return path;
}

std::vector<int32_t> ConsensusPath(Graph& g, int32_t min_cov) {
  size_t n = g.base.size();
  g.score.assign(n, 0.0);
  g.have_scores = true;
  std::vector<double> reach(n, 0.0);
  std::vector<int32_t> bprev(n, -1);
  int32_t best_v = -1;
  double best_score = -1e300;
  for (int32_t v : TopoOrder(g)) {
    double sc = 2.0 * g.nreads[v] -
                std::max<int32_t>(g.spanning[v], min_cov) - 1e-4;
    g.score[v] = sc;
    double r = sc;
    int32_t bp = -1;
    for (int32_t pr : g.preds[v]) {
      double c = sc + reach[pr];
      if (c > r) {
        r = c;
        bp = pr;
      }
    }
    reach[v] = r;
    bprev[v] = bp;
    if (r > best_score || (r == best_score && v < best_v)) {
      best_score = r;
      best_v = v;
    }
  }
  std::vector<int32_t> path;
  for (int32_t v = best_v; v >= 0; v = bprev[v]) path.push_back(v);
  std::reverse(path.begin(), path.end());
  return path;
}

}  // namespace poa

extern "C" {

void* pbccs_poa_new() { return new poa::Graph(); }
void pbccs_poa_free(void* h) { delete static_cast<poa::Graph*>(h); }

// Add a read in its better orientation if the LOCAL alignment score clears
// min_score (SparsePoa.orient_and_add_read).  Writes the per-base vertex
// path (oriented read order) and whether the reverse complement was used.
// `band` != 0 enables the SDP-anchored banded fill (reference SdpRangeFinder
// ranges against the current consensus, PoaGraphImpl.cpp:394-401).
// Returns 1 if added, 0 if rejected.
int32_t pbccs_poa_orient_add(void* h, const int8_t* read, int32_t n,
                             float min_score, int32_t band, int32_t* out_path,
                             uint8_t* out_rc) {
  auto* g = static_cast<poa::Graph*>(h);
  if (n <= 0) return 0;
  if (g->n_reads == 0) {
    auto path = poa::AddFirstRead(*g, read, n);
    std::memcpy(out_path, path.data(), n * sizeof(int32_t));
    *out_rc = 0;
    return 1;
  }
  std::vector<int8_t> fwd(read, read + n), rev(n);
  for (int32_t i = 0; i < n; ++i) {
    int8_t b = read[n - 1 - i];
    rev[i] = b < 4 ? static_cast<int8_t>(3 - b) : b;
  }
  std::vector<int32_t> bands_fwd, bands_rev;
  auto topo = poa::TopoOrder(*g);
  if (band) {
    auto css_path = poa::ConsensusPath(*g, 0);
    // the min_cov=0 scores ConsensusPath just cached are banding-internal;
    // do not let them masquerade as a caller-requested consensus
    g->have_scores = false;
    std::vector<int8_t> css_seq(css_path.size());
    for (size_t i = 0; i < css_path.size(); ++i)
      css_seq[i] = g->base[css_path[i]];
    const int32_t k = (css_seq.size() < 1000 && fwd.size() < 1000) ? 6 : 10;
    std::vector<int32_t> fh, fv, rh, rv;
    auto table = poa::SeedTable(css_seq, k);   // shared by both strands
    poa::FindSeedsInTable(table, fwd, k, &fh, &fv);
    poa::AnchorChain(&fh, &fv);
    poa::FindSeedsInTable(table, rev, k, &rh, &rv);
    poa::AnchorChain(&rh, &rv);
    // Orientation triage by chain density (see poa/sparse.py): a much
    // thinner chain marks the (almost surely) wrong strand, which gets a
    // minimal one-row band -- scores ~0, loses the orientation contest --
    // instead of a wide garbage band or a full O(V*I) fill.
    auto minimal = [&]() {
      std::vector<int32_t> b(2 * g->base.size());
      for (size_t v = 0; v < g->base.size(); ++v) {
        b[2 * v] = 0;
        b[2 * v + 1] = 1;
      }
      return b;
    };
    const size_t nf = fh.size(), nr = rh.size();
    if (nf >= 2 && nf >= 4 * nr) {
      bands_fwd = poa::SdpBands(*g, topo, css_path, fh, fv, n);
      bands_rev = minimal();
    } else if (nr >= 2 && nr >= 4 * nf) {
      bands_rev = poa::SdpBands(*g, topo, css_path, rh, rv, n);
      bands_fwd = minimal();
    } else {
      bands_fwd = poa::SdpBands(*g, topo, css_path, fh, fv, n);
      bands_rev = poa::SdpBands(*g, topo, css_path, rh, rv, n);
    }
  }
  poa::Plan pf = poa::TryAddRead(*g, topo, std::move(fwd), false, bands_fwd);
  poa::Plan pr = poa::TryAddRead(*g, topo, std::move(rev), true, bands_rev);
  poa::Plan& plan = pf.score >= pr.score ? pf : pr;
  if (plan.score < min_score) return 0;
  auto path = poa::CommitAdd(*g, plan);
  std::memcpy(out_path, path.data(), n * sizeof(int32_t));
  *out_rc = plan.rc ? 1 : 0;
  return 1;
}

// Consensus path vertex ids; returns length (or -needed if cap too small).
int32_t pbccs_poa_consensus(void* h, int32_t min_cov, int32_t* out_vs,
                            int32_t cap) {
  auto* g = static_cast<poa::Graph*>(h);
  auto path = poa::ConsensusPath(*g, min_cov);
  int32_t m = static_cast<int32_t>(path.size());
  if (m > cap) return -m;
  std::memcpy(out_vs, path.data(), m * sizeof(int32_t));
  return m;
}

int32_t pbccs_poa_vertex_count(void* h) {
  return static_cast<int32_t>(static_cast<poa::Graph*>(h)->base.size());
}

// Per-vertex state snapshot; score is valid only after a consensus call
// on the current topology (returns 0 scores otherwise).
int32_t pbccs_poa_export(void* h, int8_t* base, int32_t* nreads,
                         int32_t* spanning, double* score) {
  auto* g = static_cast<poa::Graph*>(h);
  int32_t n = static_cast<int32_t>(g->base.size());
  std::memcpy(base, g->base.data(), n);
  std::memcpy(nreads, g->nreads.data(), n * sizeof(int32_t));
  std::memcpy(spanning, g->spanning.data(), n * sizeof(int32_t));
  for (int32_t v = 0; v < n; ++v)
    score[v] = g->have_scores ? g->score[v] : 0.0;
  return g->have_scores ? n : -n;
}

int32_t pbccs_poa_edge_count(void* h) {
  auto* g = static_cast<poa::Graph*>(h);
  size_t e = 0;
  for (auto& s : g->succs) e += s.size();
  return static_cast<int32_t>(e);
}

void pbccs_poa_edges(void* h, int32_t* u, int32_t* v) {
  auto* g = static_cast<poa::Graph*>(h);
  size_t k = 0;
  for (size_t a = 0; a < g->succs.size(); ++a)
    for (int32_t b : g->succs[a]) {
      u[k] = static_cast<int32_t>(a);
      v[k] = b;
      ++k;
    }
}

}  // extern "C"
