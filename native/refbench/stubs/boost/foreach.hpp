// Minimal stand-in for BOOST_FOREACH: C++11 range-for covers every use in
// the ConsensusCore Arrow compile set (no comma-typed loop variables).
#pragma once
#define BOOST_FOREACH(decl, col) for (decl : col)
