#pragma once
#include <boost/noncopyable.hpp>
