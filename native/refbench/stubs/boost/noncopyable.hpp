#pragma once
namespace boost {
class noncopyable {
 protected:
  noncopyable() = default;
  ~noncopyable() = default;

 public:
  noncopyable(const noncopyable&) = delete;
  noncopyable& operator=(const noncopyable&) = delete;
};
}  // namespace boost
