// Minimal boost::format: enough for ConsensusCore's diagnostic strings
// (exception text and ToString dumps, none on the hot path). Does not
// implement printf-style substitution — arguments are appended after the
// format string, which preserves the information content.
#pragma once
#include <ostream>
#include <sstream>
#include <string>

namespace boost {
class format {
 public:
  explicit format(const std::string& fmt) : fmt_(fmt) {}
  template <typename T>
  format& operator%(const T& v) {
    args_ << ' ' << v;
    return *this;
  }
  std::string str() const { return fmt_ + args_.str(); }
  operator std::string() const { return str(); }

 private:
  std::string fmt_;
  std::ostringstream args_;
};

inline std::string str(const format& f) { return f.str(); }
inline std::ostream& operator<<(std::ostream& os, const format& f) {
  return os << f.str();
}
}  // namespace boost
