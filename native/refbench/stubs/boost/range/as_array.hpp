// boost::as_array for plain C arrays: identity (range-for already treats a
// C array as an N-element range, which matches Boost.Range array semantics).
#pragma once
#include <cstddef>

namespace boost {
template <typename T, std::size_t N>
inline T (&as_array(T (&arr)[N]))[N] {
  return arr;
}
}  // namespace boost
