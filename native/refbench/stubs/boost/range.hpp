// Minimal Boost.Range surface: the primary iterator-metafunction templates
// that ConsensusCore's Feature.hpp specializes.
#pragma once
namespace boost {
template <typename T>
struct range_const_iterator {
  typedef typename T::const_iterator type;
};
template <typename T>
struct range_mutable_iterator {
  typedef typename T::iterator type;
};
}  // namespace boost
