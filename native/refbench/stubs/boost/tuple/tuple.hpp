// Minimal stand-in for boost/tuple/tuple.hpp backed by std::tuple.
// Part of the no-Boost shim set that lets the reference ConsensusCore Arrow
// sources compile unmodified for the honest CPU baseline (see ../../README.md).
#pragma once
#include <functional>
#include <tuple>

namespace boost {
template <typename... Ts>
using tuple = std::tuple<Ts...>;
using std::get;
using std::make_tuple;
using std::ref;
using std::tie;
}  // namespace boost
