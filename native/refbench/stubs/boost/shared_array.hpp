// Minimal boost::shared_array over std::shared_ptr<T[]> — only the surface
// ConsensusCore's Feature<T> uses (ctor from new[], operator[], get()).
#pragma once
#include <cstddef>
#include <memory>

namespace boost {
template <typename T>
class shared_array {
 public:
  shared_array() = default;
  explicit shared_array(T* p) : p_(p, std::default_delete<T[]>()) {}
  T& operator[](std::ptrdiff_t i) const { return p_.get()[i]; }
  T* get() const { return p_.get(); }
  explicit operator bool() const { return static_cast<bool>(p_); }

 private:
  std::shared_ptr<T[]> p_;
};
}  // namespace boost
