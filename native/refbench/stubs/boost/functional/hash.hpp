#pragma once
#include <functional>
namespace boost {
template <typename T>
struct hash : std::hash<T> {};
}  // namespace boost
