// Empty: the only symbol Mutation.cpp pulls from here is boost::str, which
// our format.hpp stub provides.
#pragma once
#include <boost/format.hpp>
