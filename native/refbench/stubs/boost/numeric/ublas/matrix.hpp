// Minimal ublas::matrix: dense row-major storage with (i,j) access — the
// only surface the reference compile set touches (PairwiseAlignment.cpp's
// NW score matrix; ContextParameterProvider's include is vestigial).
#pragma once
#include <cstddef>
#include <vector>

namespace boost {
namespace numeric {
namespace ublas {

template <typename T>
class matrix {
 public:
  matrix(std::size_t rows, std::size_t cols)
      : rows_(rows), cols_(cols), data_(rows * cols) {}
  T& operator()(std::size_t i, std::size_t j) { return data_[i * cols_ + j]; }
  const T& operator()(std::size_t i, std::size_t j) const {
    return data_[i * cols_ + j];
  }
  std::size_t size1() const { return rows_; }
  std::size_t size2() const { return cols_; }

 private:
  std::size_t rows_, cols_;
  std::vector<T> data_;
};

}  // namespace ublas
}  // namespace numeric
}  // namespace boost
