// No-op cpplog shim: ConsensusCore's LDEBUG/LTRACE… macros expand through
// LOG_* to a sink that discards everything (the real cpplog needs
// boost::thread). Logging off the hot path does not affect the benchmark.
#pragma once
#include <ostream>

namespace cpplog {
struct NullSink {
  template <typename T>
  NullSink& operator<<(const T&) {
    return *this;
  }
  NullSink& operator<<(std::ostream& (*)(std::ostream&)) { return *this; }
};
struct BaseLogger {};
struct StdErrLogger : BaseLogger {};
struct FilteringLogger : BaseLogger {
  template <typename... A>
  explicit FilteringLogger(A&&...) {}
};
}  // namespace cpplog

#define LL_TRACE 0
#define LL_DEBUG 1
#define LL_INFO 2
#define LL_WARN 3
#define LL_ERROR 4
#define LL_FATAL 5

#define LOG_TRACE(l) cpplog::NullSink()
#define LOG_DEBUG(l) cpplog::NullSink()
#define LOG_INFO(l) cpplog::NullSink()
#define LOG_WARN(l) cpplog::NullSink()
#define LOG_ERROR(l) cpplog::NullSink()
#define LOG_FATAL(l) cpplog::NullSink()
