// Injected via -include: range-for over ConsensusCore::Feature<T>.
// The real BOOST_FOREACH finds the reference's range_begin/range_end
// extension points; our range-for shim needs ADL-visible begin/end instead.
#pragma once
namespace ConsensusCore {
template <typename T>
class Feature;
template <typename T>
inline const T* begin(const Feature<T>& f) {
  return f.get();
}
template <typename T>
inline const T* end(const Feature<T>& f) {
  return f.get() + f.Length();
}
}  // namespace ConsensusCore
