#!/usr/bin/env python
"""Dump the bench.py workload in the text format refbench.cpp consumes.

Reproduces bench.build_tasks with the same seed, so the reference C++
baseline measures the identical 128 ZMWs the TPU bench polishes (first
draw; bench.py's timed repeats draw fresh but statistically identical
workloads from the same stream).

Usage: python native/refbench/dump_workload.py [OUT.txt]
Env knobs mirror bench.py: BENCH_ZMWS/BENCH_TPL_LEN/BENCH_PASSES/
BENCH_CORRUPTIONS, plus REFBENCH_ITERS (default 10, = bench.py's
RefineOptions.max_iterations) and REFBENCH_MIN_ZSCORE (default -5, the
reference CLI default).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def main() -> None:
    import numpy as np

    from bench import build_tasks
    from pbccs_tpu.models.arrow.params import decode_bases

    from bench import parse_passes

    n_zmws = int(os.environ.get("BENCH_ZMWS", 128))
    tpl_len = int(os.environ.get("BENCH_TPL_LEN", 300))
    n_passes = os.environ.get("BENCH_PASSES", "8")   # "8" or "3-10" range
    n_corr = int(os.environ.get("BENCH_CORRUPTIONS", 2))
    iters = int(os.environ.get("REFBENCH_ITERS", 10))
    min_z = float(os.environ.get("REFBENCH_MIN_ZSCORE", -5.0))

    out_path = sys.argv[1] if len(sys.argv) > 1 else "workload.txt"

    rng = np.random.default_rng(20260729)
    tasks, _truths = build_tasks(rng, n_zmws, tpl_len, n_passes, n_corr)
    # REFBENCH_DRAW=k dumps the k-th draw of the stream (default 1).
    # bench.py scores ACCURACY on draw #2 (warmup consumes draw #1, the
    # first timed repeat is draw #2), so converged/mean_qv comparisons
    # against the framework artifact must dump draw 2 -- throughput is
    # draw-invariant, accuracy is not (docs/ACCURACY.md).
    for _ in range(int(os.environ.get("REFBENCH_DRAW", 1)) - 1):
        tasks, _truths = build_tasks(rng, n_zmws, tpl_len, n_passes, n_corr)

    with open(out_path, "w") as f:
        # the CONFIG passes field is informational (per-ZMW read counts
        # ride the ZMW lines); write the range's low end as the int the
        # C++ parser expects
        f.write(f"CONFIG {n_zmws} {tpl_len} {parse_passes(n_passes)[0]} "
                f"{iters} {min_z}\n")
        for t in tasks:
            f.write(f"ZMW {t.id.replace(' ', '_')} "
                    f"{t.snr[0]} {t.snr[1]} {t.snr[2]} {t.snr[3]} "
                    f"{len(t.reads)}\n")
            f.write(f"DRAFT {decode_bases(t.tpl)}\n")
            for read, strand in zip(t.reads, t.strands):
                f.write(f"READ {strand} {decode_bases(read)}\n")
    print(f"wrote {out_path}: {n_zmws} ZMWs x L{tpl_len} x P{n_passes}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
