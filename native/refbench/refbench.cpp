// Honest CPU baseline harness: drives the REFERENCE ConsensusCore Arrow
// implementation (compiled unmodified from /root/reference, -O3 -msse3)
// on the exact workload bench.py measures, and reports ZMWs/sec.
//
// This is the "faithful reimplementation" clause of BASELINE.md satisfied
// with the original implementation itself: AddRead (FillAlphaBeta), the
// mutation-testing refinement loop, and the QV sweep are all reference code
// (reference ConsensusCore/src/C++/Arrow/SimpleRecursor.cpp:62-296,
// MultiReadMutationScorer.cpp:276-382, Consensus-inl.hpp:160-245).  Only
// this driver loop is ours: it re-states the ~60-line AbstractRefineConsensus
// control flow (greedy well-separated favorable mutations, template-hash
// cycle avoidance) because including Consensus.hpp would drag in the entire
// Quiver header chain, which needs much more of Boost than the shim set
// under stubs/ provides.
//
// Workload file (produced by dump_workload.py, identical ZMWs to bench.py):
//   CONFIG <n_zmws> <tpl_len> <n_passes> <max_iterations> <min_zscore>
//   ZMW <id> <snrA> <snrC> <snrG> <snrT> <n_reads>
//   DRAFT <acgt-string>
//   READ <strand:0|1> <acgt-string>                       (x n_reads)
//   READWIN <strand:0|1> <tstart> <tend> <acgt-string>    (window variant:
//       per-read draft window, as the pipeline's POA extents produce;
//       used by tools/crossval_real.py for real-data cross-validation)

#include <ConsensusCore/Arrow/ArrowConfig.hpp>
#include <ConsensusCore/Checksum.hpp>
#include <ConsensusCore/Arrow/ContextParameters.hpp>
#include <ConsensusCore/Arrow/MultiReadMutationScorer.hpp>
#include <ConsensusCore/Arrow/MutationEnumerator.hpp>
#include <ConsensusCore/Features.hpp>
#include <ConsensusCore/Mutation.hpp>
#include <ConsensusCore/Read.hpp>

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <fstream>
#include <functional>
#include <iostream>
#include <set>
#include <sstream>
#include <string>
#include <vector>

using namespace ConsensusCore;
using namespace ConsensusCore::Arrow;

// Checksum.cpp needs boost/crc.hpp (not in the shim set); the symbols are
// only reachable from Read::ToString diagnostics, never on the bench path.
namespace ConsensusCore {
std::string Checksum::Of(const QvSequenceFeatures&) { return "na"; }
std::string Checksum::Of(const ArrowSequenceFeatures&) { return "na"; }
}  // namespace ConsensusCore

namespace {

struct ReadInput {
    int strand = 0;
    int tStart = -1, tEnd = -1;  // -1: full draft span (legacy READ lines)
    std::string seq;
};

struct ZmwInput {
    std::string id;
    double snr[4];
    std::string draft;
    std::vector<ReadInput> reads;
};

struct Workload {
    int nZmws = 0, tplLen = 0, nPasses = 0, maxIterations = 10;
    double minZScore = -5.0;
    std::vector<ZmwInput> zmws;
};

Workload LoadWorkload(const std::string& path)
{
    std::ifstream in(path);
    if (!in) { std::cerr << "cannot open " << path << "\n"; exit(1); }
    Workload w;
    std::string tag;
    while (in >> tag) {
        if (tag == "CONFIG") {
            in >> w.nZmws >> w.tplLen >> w.nPasses >> w.maxIterations >> w.minZScore;
        } else if (tag == "ZMW") {
            ZmwInput z;
            int nReads;
            in >> z.id >> z.snr[0] >> z.snr[1] >> z.snr[2] >> z.snr[3] >> nReads;
            std::string t;
            in >> t >> z.draft;                        // DRAFT <seq>
            for (int r = 0; r < nReads; ++r) {
                ReadInput ri;
                in >> t;
                if (t == "READWIN")                    // READWIN <strand> <ts> <te> <seq>
                    in >> ri.strand >> ri.tStart >> ri.tEnd >> ri.seq;
                else                                   // READ <strand> <seq>
                    in >> ri.strand >> ri.seq;
                z.reads.push_back(std::move(ri));
            }
            w.zmws.push_back(std::move(z));
        }
    }
    return w;
}

// Same semantics as the reference's BestSubset (Consensus-inl.hpp:99-119):
// repeatedly take the max-scoring mutation and drop everything whose start
// lies within +/- separation (inclusive) of its start.
std::vector<ScoredMutation> GreedyWellSeparated(std::vector<ScoredMutation> cand,
                                                int separation)
{
    std::vector<ScoredMutation> out;
    while (!cand.empty()) {
        auto bestIt = std::max_element(
            cand.begin(), cand.end(),
            [](const ScoredMutation& a, const ScoredMutation& b) {
                return a.Score() < b.Score();
            });
        ScoredMutation best = *bestIt;
        out.push_back(best);
        std::vector<ScoredMutation> keep;
        for (const auto& s : cand)
            if (s.Start() < best.Start() - separation ||
                s.Start() > best.Start() + separation)
                keep.push_back(s);
        cand.swap(keep);
    }
    return out;
}

std::vector<Mutation> AsMutations(const std::vector<ScoredMutation>& s)
{
    return std::vector<Mutation>(s.begin(), s.end());
}

// The reference refinement control flow (AbstractRefineConsensus,
// Consensus-inl.hpp:160-245): round 0 tests every unique single-base
// mutation, later rounds only the neighborhood of the previous round's
// favorables; apply the best well-separated subset, trimming to one
// mutation when the would-be template was already visited.
bool Refine(ArrowMultiReadMutationScorer& mms, int maxIterations,
            size_t* nTested, size_t* nApplied)
{
    const int kSeparation = 10, kNeighborhood = 20;
    std::hash<std::string> hasher;
    std::set<size_t> tplHistory;
    std::vector<ScoredMutation> favorables;

    for (int iter = 0; iter < maxIterations; ++iter) {
        UniqueSingleBaseMutationEnumerator enumerator(mms.Template());
        std::vector<Mutation> toTry =
            (iter == 0) ? enumerator.Mutations()
                        : UniqueNearbyMutations(enumerator, AsMutations(favorables),
                                                kNeighborhood);
        *nTested += toTry.size();
        favorables.clear();
        for (const Mutation& m : toTry) {
            if (mms.FastIsFavorable(m)) {
                double s = mms.Score(m);
                favorables.push_back(m.WithScore(static_cast<float>(s)));
            }
        }
        if (favorables.empty()) return true;

        std::vector<ScoredMutation> best = GreedyWellSeparated(favorables, kSeparation);
        if (best.size() > 1) {
            std::string nextTpl = ApplyMutations(AsMutations(best), mms.Template());
            if (tplHistory.count(hasher(nextTpl)))
                best.resize(1);
        }
        *nApplied += best.size();
        tplHistory.insert(hasher(mms.Template()));
        mms.ApplyMutations(AsMutations(best));
    }
    return false;
}

// ConsensusQVs (Consensus-inl.hpp:277-297).
std::vector<int> QvSweep(ArrowMultiReadMutationScorer& mms)
{
    std::vector<int> qvs;
    UniqueSingleBaseMutationEnumerator enumerator(mms.Template());
    const size_t L = mms.Template().length();
    for (size_t pos = 0; pos < L; ++pos) {
        double scoreSum = 0.0;
        for (const Mutation& m : enumerator.Mutations(static_cast<int>(pos),
                                                      static_cast<int>(pos) + 1)) {
            double s = mms.Score(m);
            if (s < 0.0) scoreSum += std::exp(s);
        }
        double p = 1.0 - 1.0 / (1.0 + scoreSum);
        if (p <= 0.0) p = std::numeric_limits<double>::min();
        qvs.push_back(static_cast<int>(std::round(-10.0 * std::log10(p))));
    }
    return qvs;
}

}  // namespace

int main(int argc, char** argv)
{
    if (argc < 2) {
        std::cerr << "usage: refbench WORKLOAD [--repeats N]\n";
        return 1;
    }
    int repeats = 1;
    std::string dumpPath;
    for (int i = 2; i + 1 < argc; ++i) {
        if (std::string(argv[i]) == "--repeats") repeats = std::atoi(argv[i + 1]);
        if (std::string(argv[i]) == "--dump") dumpPath = argv[i + 1];
    }

    Workload w = LoadWorkload(argv[1]);
    std::cerr << "refbench: Z=" << w.zmws.size() << " L=" << w.tplLen
              << " P=" << w.nPasses << " iters=" << w.maxIterations
              << " minZ=" << w.minZScore << "\n";

    std::vector<double> repSecs;
    size_t nTested = 0, nApplied = 0, nConverged = 0, nDroppedReads = 0;
    double qvSum = 0.0; size_t qvCount = 0;

    std::ofstream dump;
    if (!dumpPath.empty()) dump.open(dumpPath);

    for (int rep = 0; rep < repeats; ++rep) {
        nTested = nApplied = nConverged = nDroppedReads = 0;
        qvSum = 0.0; qvCount = 0;
        auto t0 = std::chrono::steady_clock::now();
        for (const ZmwInput& z : w.zmws) {
            ContextParameters ctx(SNR(z.snr[0], z.snr[1], z.snr[2], z.snr[3]));
            ArrowConfig config(ctx, ConsensusCore::Arrow::BandingOptions(12.5));
            ArrowMultiReadMutationScorer mms(config, z.draft);
            for (const auto& sr : z.reads) {
                ArrowSequenceFeatures features(sr.seq);
                int ts = sr.tStart >= 0 ? sr.tStart : 0;
                int te = sr.tEnd >= 0 ? sr.tEnd
                                      : static_cast<int>(z.draft.size());
                MappedArrowRead mr(ArrowRead(features, z.id, "N/A"),
                                   sr.strand ? REVERSE_STRAND : FORWARD_STRAND,
                                   ts, te);
                if (mms.AddRead(mr, w.minZScore) != SUCCESS) ++nDroppedReads;
            }
            if (Refine(mms, w.maxIterations, &nTested, &nApplied)) ++nConverged;
            std::vector<int> qvs = QvSweep(mms);
            for (int qv : qvs) { qvSum += qv; ++qvCount; }
            if (rep == 0 && dump.is_open()) {
                std::string qstr;  // phred+33, clamped like QVsToASCII
                for (int qv : qvs)
                    qstr += static_cast<char>(std::min(std::max(qv, 0), 93) + 33);
                dump << z.id << " " << mms.Template() << " " << qstr << "\n";
            }
        }
        auto t1 = std::chrono::steady_clock::now();
        repSecs.push_back(std::chrono::duration<double>(t1 - t0).count());
    }

    // median run time: same statistic bench.py reports for the device,
    // so the vs_reference_cpp ratio compares like with like
    std::sort(repSecs.begin(), repSecs.end());
    double medSec = repSecs[repSecs.size() / 2];
    double zps = w.zmws.size() / medSec;
    std::printf("{\"reference_cpp_zmws_per_sec\": %.6f, \"bench_s\": %.4f, "
                "\"n_zmws\": %zu, \"converged\": %zu, \"dropped_reads\": %zu, "
                "\"mutations_tested\": %zu, \"mutations_applied\": %zu, "
                "\"mean_qv\": %.3f, \"threads\": 1}\n",
                zps, medSec, w.zmws.size(), nConverged, nDroppedReads,
                nTested, nApplied, qvCount ? qvSum / qvCount : 0.0);
    return 0;
}
