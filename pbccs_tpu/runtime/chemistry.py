"""Chemistry identification: (BindingKit, SequencingKit, BasecallerVersion)
triples -> chemistry names, plus the hardcoded P6-C4 acceptance gate.

Parity: reference ChemistryMapping/ChemistryTriple (include/pacbio/ccs/
ChemistryMapping.h:49-72, ChemistryTriple.h:46-85, parsing
ChemistryMapping.cpp:53-83) and the CLI gate VerifyChemistry
(src/main/ccs.cpp:263-281).
"""

from __future__ import annotations

import dataclasses
import xml.etree.ElementTree as ET

from pbccs_tpu.io.bam import ReadGroupInfo


@dataclasses.dataclass(frozen=True)
class ChemistryTriple:
    binding_kit: str
    sequencing_kit: str
    major_version: str  # "major.minor" of the basecaller/software version

    @staticmethod
    def from_strings(binding_kit: str, sequencing_kit: str,
                     software_version: str) -> "ChemistryTriple":
        parts = software_version.split(".")
        major = ".".join(parts[:2]) if len(parts) >= 2 else software_version
        return ChemistryTriple(binding_kit, sequencing_kit, major)


class ChemistryMapping:
    """Parse a mapping XML: <Mapping><BindingKit/><SequencingKit/>
    <SoftwareVersion/><SequencingChemistry/></Mapping> entries, with a
    DefaultSequencingChemistry fallback."""

    def __init__(self, xml_path: str):
        self.mapping: dict[ChemistryTriple, str] = {}
        self.default: str | None = None
        root = ET.parse(xml_path).getroot()
        for m in root.iter():
            if m.tag.endswith("Mapping"):
                get = lambda tag: next(
                    (c.text or "" for c in m if c.tag.endswith(tag)), "")
                chem = get("SequencingChemistry")
                if not chem:
                    continue
                bk, sk, sv = (get("BindingKit"), get("SequencingKit"),
                              get("SoftwareVersion"))
                if bk or sk or sv:
                    self.mapping[ChemistryTriple.from_strings(bk, sk, sv)] = chem
                else:
                    self.default = chem
            elif m.tag.endswith("DefaultSequencingChemistry"):
                self.default = m.text or None

    def find(self, triple: ChemistryTriple) -> str | None:
        return self.mapping.get(triple, self.default)


def verify_chemistry(rg: ReadGroupInfo) -> bool:
    """The reference's hardcoded P6-C4-only gate (ccs.cpp:263-281)."""
    bc_major = ".".join(rg.basecaller_version.split(".")[:2])
    if bc_major not in ("2.1", "2.3"):
        return False
    if rg.sequencing_kit != "100356200":
        return False
    return rg.binding_kit in ("100356300", "100372700")
