"""ZMW whitelist: parse `--zmws` selection specs and answer membership.

Spec grammar (parity: reference include/pacbio/ccs/Whitelist.h:52-130):
  all | *:*                      every ZMW of every movie
  <ranges>                       global ZMW ranges, e.g. "1-3,5"
  *:<ranges>                     same
  <movie>:<ranges>               ranges scoped to one movie
  <movie>:*                      every ZMW of one movie
  spec;spec;...                  union over movies (each movie at most once,
                                 no mixing global with per-movie)
"""

from __future__ import annotations

from pbccs_tpu.utils.intervals import IntervalTree


class Whitelist:
    def __init__(self, spec: str):
        self._all = False
        self._global: IntervalTree | None = None
        self._movies: dict[str, IntervalTree | None] = {}

        if spec in ("all", "*:*"):
            self._all = True
            return

        for mspec in spec.split(";"):
            if mspec in ("all", "*:*") or self._global is not None:
                raise ValueError("invalid whitelist specification")
            parts = mspec.split(":")
            if len(parts) == 1:
                if not self._movies:
                    self._global = IntervalTree.from_string(parts[0])
                    continue
            elif len(parts) == 2 and parts[0] == "*":
                if not self._movies:
                    self._global = IntervalTree.from_string(parts[1])
                    continue
            elif len(parts) == 2 and parts[0] not in self._movies:
                self._movies[parts[0]] = (
                    None if parts[1] == "*" else IntervalTree.from_string(parts[1]))
                continue
            raise ValueError("invalid whitelist specification")

    def contains(self, movie_name: str, hole_number: int) -> bool:
        if self._all:
            return True
        if self._global is not None:
            return self._global.contains(hole_number)
        if movie_name in self._movies:
            tree = self._movies[movie_name]
            return tree is None or tree.contains(hole_number)
        return False
