"""Persistent JAX compilation-cache setup shared by the CLI and bench.

The polish programs take minutes to compile at batch shapes; cached
executables make reruns start fast.  Respects a user-provided
JAX_COMPILATION_CACHE_DIR (or an already-set config value) and falls back
to the repo checkout's .jax_cache when writable, else a per-user cache
directory."""

from __future__ import annotations

import contextlib
import os
import threading

_monitoring_installed = False
_suppress_events = threading.local()


@contextlib.contextmanager
def suppress_cache_metrics():
    """Hide compile/cache-event counts from the ledger counters for the
    duration.  Used by the roofline CostCard extraction: its AOT compile
    of the canonical bucket program races the workload's own jit on the
    shared persistent cache, so counting its hit/miss would make the
    deterministic compile-class ledger counters timing-dependent."""
    prev = getattr(_suppress_events, "v", False)
    _suppress_events.v = True
    try:
        yield
    finally:
        _suppress_events.v = prev


def _install_cache_metrics() -> None:
    """Route jax's compilation-cache monitoring events into the metrics
    registry: ccs_compile_cache_events_total{kind="hit"|"miss"} plus
    ccs_compiles_total for backend compiles.  Best-effort -- event names
    are jax-internal and version-dependent, so unknown events are ignored
    and a jax without jax.monitoring leaves the counters at zero."""
    global _monitoring_installed
    if _monitoring_installed:
        return
    _monitoring_installed = True
    from pbccs_tpu.obs.metrics import default_registry

    reg = default_registry()
    hits = reg.counter("ccs_compile_cache_events_total",
                       "Persistent compilation cache hits/misses",
                       kind="hit")
    misses = reg.counter("ccs_compile_cache_events_total", kind="miss")
    compiles = reg.counter("ccs_compiles_total",
                           "Backend compile events observed via "
                           "jax.monitoring")

    def on_event(event: str, **kw) -> None:
        if getattr(_suppress_events, "v", False):
            return
        if "compilation_cache" in event:
            if "hit" in event:
                hits.inc()
            elif "miss" in event:
                misses.inc()
        elif "backend_compile" in event or event.endswith("/compile"):
            compiles.inc()

    try:
        import jax.monitoring

        jax.monitoring.register_event_listener(on_event)
    except Exception:  # noqa: BLE001 -- observability must not block setup
        pass


def enable_compilation_cache(cache_dir: str | None = None) -> str:
    """Point jax's persistent compilation cache at ``cache_dir`` (the
    `--compileCache` flag), else an environment/config-provided dir,
    else the checkout-local / per-user default.  An explicit dir is the
    fleet-restart contract: every `ccs serve` replica and `ccs warmup`
    pointed at the same directory shares one executable store, so a
    rolling replica restart pays a disk load (seconds) instead of the
    first-run XLA compile (~a minute per bucket shape)."""
    import jax

    _install_cache_metrics()

    configured = cache_dir or \
        os.environ.get("JAX_COMPILATION_CACHE_DIR") or \
        jax.config.jax_compilation_cache_dir
    if configured:
        cache_dir = configured
    else:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        cache_dir = os.path.join(repo, ".jax_cache")
        # use the checkout-local cache only when running from a source tree
        # (a pip install would land this in site-packages, where executables
        # are lost on upgrade) and it is actually writable
        in_checkout = os.path.isdir(os.path.join(repo, ".git"))
        writable = os.access(cache_dir if os.path.isdir(cache_dir) else repo,
                             os.W_OK)
        if not (in_checkout and writable):
            cache_dir = os.path.join(
                os.path.expanduser("~"), ".cache", "pbccs_tpu", "jax")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # respect a user-provided min-compile-time; default to caching anything
    # that took >= 1 s to compile
    if os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS") is None \
            and jax.config.jax_persistent_cache_min_compile_time_secs <= 0:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return cache_dir
