"""Host runtime: ZMW selection, ordered work pipeline, logging, chemistry."""
