"""Tuned-knob resolution: the consumer half of `ccs tune`.

One process-wide resolution ladder, consulted by every knob site:

    explicit flag / env  >  matching host profile  >  hand-tuned default

Profiles are OPT-IN: nothing is loaded unless `--tuneProfile PATH`,
`--tuneProfile auto`, or the `PBCCS_TUNE_PROFILE` env equivalent asks
for it (``auto`` scans the committed ``profiles/`` directory --
override with ``PBCCS_TUNE_PROFILE_DIR`` -- for the first fingerprint
match).  The default-off posture keeps every existing workflow
byte-for-byte on the hand-tuned constants; a profile only changes
behavior on the host class it was measured on.

Application is fail-open by design (the satellite-3 contract):

  * a missing/corrupt/torn profile file degrades to defaults with a
    logged note, never a crash;
  * a fingerprint mismatch (wrong device kind, different jax version)
    falls through to defaults with a logged note;
  * an applied profile is attributed everywhere: the
    ``ccs_tune_profile_applied`` gauge carries its id as a label, and
    obs/ledger.py stamps every record's ``tuned_profile`` field via
    :func:`ledger_tag` (``"none"`` when running on defaults), so any
    BENCH/PERF_BASELINE row is traceable to the exact knob set.

Knob *reads* (:func:`knob_int` etc.) are dict lookups on module state --
cheap enough for per-trace call sites like
``models/arrow/params.effective_band_width``.  This module must stay
import-light: params.py imports it at module load, and a ledger append
must never drag a jax backend init in (fingerprinting only happens
inside the opt-in :func:`configure`).
"""

from __future__ import annotations

import os
import threading
from typing import Any

_lock = threading.Lock()
# the active profile (a tune.profile.HostProfile) and how it got here
_state: dict[str, Any] = {"profile": None, "source": None}


def _default_profile_dir() -> str:
    env = os.environ.get("PBCCS_TUNE_PROFILE_DIR")
    if env:
        return env
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo_root, "profiles")


def configure(spec: str | None, logger=None) -> bool:
    """Resolve and apply a host profile; returns True when one applied.

    ``spec`` is the --tuneProfile value: a path, ``"auto"``, or None
    (consult PBCCS_TUNE_PROFILE; unset/empty/"off" means defaults).
    Every degradation path logs a note and leaves the process on the
    hand-tuned constants -- configure never raises on bad input."""
    if spec is None:
        spec = os.environ.get("PBCCS_TUNE_PROFILE") or None
    if spec is None or spec.strip().lower() in ("", "off", "none"):
        return False

    from pbccs_tpu.tune import profile as profile_mod

    def _note(msg: str) -> None:
        if logger is not None:
            logger.notice(f"tune: {msg}")

    try:
        host_fp = profile_mod.host_fingerprint()
    except Exception as e:  # noqa: BLE001 -- fail-open by contract
        _note(f"cannot fingerprint this host ({e}); running on "
              "hand-tuned defaults")
        return False

    if spec.strip().lower() == "auto":
        prof, notes = profile_mod.discover_profile(
            _default_profile_dir(), host_fp)
        for n in notes:
            _note(n)
        if prof is None:
            return False
    else:
        prof, note = profile_mod.load_profile(spec)
        if prof is None:
            _note(f"{note}; running on hand-tuned defaults")
            return False
        mismatch = profile_mod.fingerprint_mismatch(
            prof.fingerprint, host_fp)
        if mismatch is not None:
            _note(f"profile {spec} not applied: {mismatch}; running "
                  "on hand-tuned defaults")
            return False

    with _lock:
        _state["profile"] = prof
        _state["source"] = spec
    from pbccs_tpu.obs.metrics import default_registry

    registry = default_registry()
    registry.gauge(
        "ccs_tune_profile_applied",
        "1 when a ccs-tune host profile is active (label = profile id)",
        profile=prof.profile_id).set(1)
    if logger is not None:
        logger.info(f"tune: applied host profile {prof.profile_id} "
                    f"({spec}): knobs {sorted(prof.knobs)}")
    return True


def reset() -> None:
    """Drop the active profile (tests)."""
    with _lock:
        _state["profile"] = None
        _state["source"] = None


def active_profile():
    """The applied tune.profile.HostProfile, or None on defaults."""
    return _state["profile"]


def ledger_tag() -> str:
    """What every perf-ledger record's ``tuned_profile`` field carries:
    the applied profile id, or ``"none"`` on hand-tuned defaults."""
    prof = _state["profile"]
    return prof.profile_id if prof is not None else "none"


def knob(name: str) -> Any:
    """Raw profile knob value, or None (no profile / knob absent)."""
    prof = _state["profile"]
    if prof is None:
        return None
    return prof.knobs.get(name)


def knob_int(name: str) -> int | None:
    v = knob(name)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return int(v)


def knob_float(name: str) -> float | None:
    v = knob(name)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def knob_str_list(name: str) -> list[str] | None:
    v = knob(name)
    if isinstance(v, list) and v and all(isinstance(s, str) for s in v):
        return list(v)
    return None
