"""Subread identity: movie name + hole number + optional query interval.

Parity: ReadId (reference include/pacbio/ccs/ReadId.h:52-77,
src/ReadId.cpp): formats as `movie/zmw` or `movie/zmw/qstart_qend` and
parses the same forms back."""

from __future__ import annotations

import dataclasses

from pbccs_tpu.utils.intervals import Interval


@dataclasses.dataclass(frozen=True)
class ReadId:
    movie_name: str
    hole_number: int
    zmw_interval: Interval | None = None

    def __str__(self) -> str:
        if self.zmw_interval is None:
            return f"{self.movie_name}/{self.hole_number}"
        return (f"{self.movie_name}/{self.hole_number}/"
                f"{self.zmw_interval.left}_{self.zmw_interval.right}")

    @classmethod
    def parse(cls, text: str) -> "ReadId":
        parts = text.split("/")
        if len(parts) < 2:
            raise ValueError(f"not a read id: {text!r}")
        movie, hole = parts[0], int(parts[1])
        if len(parts) >= 3 and "_" in parts[2]:
            b, e = parts[2].split("_", 1)
            return cls(movie, hole, Interval(int(b), int(e)))
        return cls(movie, hole)
