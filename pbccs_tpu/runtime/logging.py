"""Asynchronous leveled logger.

Log records are formatted on the calling thread and queued to a dedicated
writer thread, so the hot pipeline never blocks on IO; fatal signals flush
the queue before re-raising.  Parity: reference include/pacbio/ccs/
Logging.h:58-368 (8 levels, UTC timestamps + thread ids, async queue,
signal-handler flush).
"""

from __future__ import annotations

import atexit
import datetime
import enum
import queue
import signal
import sys
import threading
import traceback
from typing import TextIO


class LogLevel(enum.IntEnum):
    TRACE = 0
    DEBUG = 1
    INFO = 2
    NOTICE = 3
    WARN = 4
    ERROR = 5
    CRITICAL = 6
    FATAL = 7

    @staticmethod
    def from_string(name: str) -> "LogLevel":
        try:
            return LogLevel[name.upper()]
        except KeyError:
            raise ValueError(f"invalid log level: {name!r}") from None


class Logger:
    """Async logger with a dedicated writer thread."""

    _default: "Logger | None" = None
    _default_lock = threading.Lock()
    _atexit_installed = False

    def __init__(self, stream: TextIO | None = None,
                 level: LogLevel = LogLevel.INFO):
        self._stream = stream if stream is not None else sys.stderr
        self.level = level
        self._queue: queue.Queue[str | None] = queue.Queue()
        self._thread = threading.Thread(target=self._writer, daemon=True,
                                        name="pbccs-log-writer")
        self._thread.start()

    # ------------------------------------------------------------- plumbing

    def _writer(self) -> None:
        while True:
            msg = self._queue.get()
            try:
                if msg is None:
                    return
                self._stream.write(msg)
                self._stream.flush()
            except Exception:  # noqa: BLE001 -- logging must never raise
                pass
            finally:
                self._queue.task_done()

    def log(self, level: LogLevel, message: str) -> None:
        if level < self.level:
            return
        now = datetime.datetime.now(datetime.timezone.utc)
        tid = threading.get_ident() & 0xFFFF
        self._queue.put(
            f">|> {now:%Y%m%d %H:%M:%S.%f} -|- {level.name} -|- "
            f"0x{tid:04x} -|- {message}\n")

    def flush(self) -> None:
        """Block until every queued record has been written."""
        self._queue.join()

    def close(self) -> None:
        self._queue.put(None)
        self._thread.join(timeout=5)

    # ------------------------------------------------------------ interface

    def trace(self, msg: str) -> None: self.log(LogLevel.TRACE, msg)
    def debug(self, msg: str) -> None: self.log(LogLevel.DEBUG, msg)
    def info(self, msg: str) -> None: self.log(LogLevel.INFO, msg)
    def notice(self, msg: str) -> None: self.log(LogLevel.NOTICE, msg)
    def warn(self, msg: str) -> None: self.log(LogLevel.WARN, msg)
    def error(self, msg: str) -> None: self.log(LogLevel.ERROR, msg)
    def critical(self, msg: str) -> None: self.log(LogLevel.CRITICAL, msg)
    def fatal(self, msg: str) -> None: self.log(LogLevel.FATAL, msg)

    # ------------------------------------------------------------- default

    @classmethod
    def default(cls, logger: "Logger | None" = None) -> "Logger":
        """Get (or install) the process-default logger.

        Locked: two threads racing the first call used to construct TWO
        loggers -- two writer threads, interleaved half-installed state --
        and the loser's writer thread leaked for the process lifetime."""
        with cls._default_lock:
            if logger is not None:
                cls._default = logger
            if cls._default is None:
                cls._default = Logger()
            if not cls._atexit_installed:
                cls._atexit_installed = True
                atexit.register(cls._flush_default_at_exit)
            return cls._default

    @classmethod
    def _flush_default_at_exit(cls) -> None:
        """Drain + stop the default logger's writer thread at interpreter
        exit so queued records (e.g. from a CLI run) are never dropped."""
        with cls._default_lock:
            log = cls._default
        if log is not None:
            try:
                log.flush()
                log.close()
            except Exception:  # noqa: BLE001 -- logging must never raise
                pass


def install_signal_handlers(logger: Logger | None = None) -> None:
    """Flush the async logger on fatal signals, then re-raise the default
    behavior (reference Logging.h:328-364)."""
    logger = logger or Logger.default()

    def handler(signum, frame):
        logger.fatal(f"caught signal {signal.Signals(signum).name}:\n"
                     + "".join(traceback.format_stack(frame)))
        logger.flush()
        signal.signal(signum, signal.SIG_DFL)
        signal.raise_signal(signum)

    for sig in (signal.SIGABRT, signal.SIGINT, signal.SIGSEGV, signal.SIGTERM):
        try:
            signal.signal(sig, handler)
        except (ValueError, OSError):  # non-main thread / unsupported
            pass
