"""Device-wait accounting -- back-compat shim over pbccs_tpu.obs.metrics.

The historical module-level API (stage timers, device_fetch, reset) now
records into the process-wide MetricsRegistry (obs/metrics.py):

  ccs_stage_seconds_total{stage=...}   thread-seconds per pipeline stage
  ccs_device_wait_seconds_total        blocking time inside device fetches
  ccs_device_fetches_total             fetch count
  ccs_device_fetch_seconds             per-fetch latency histogram

Registry values are monotone; a *measurement window* (window(), a
MeasurementScope over the default registry) reports deltas.  reset()
keeps its historical meaning -- start a new window -- but now only
replaces the MODULE-DEFAULT window that the module-level getters read
from: a live serving engine holds its own window (engine status), so a
bench.py reset in the same process can no longer clobber the engine's
counters (and vice versa).

device_fetch() additionally attributes its blocking time to the
innermost open trace span (obs/trace.py) so exported span trees carry
wall vs device-wait decomposition.
"""

from __future__ import annotations

import contextlib
import threading
import time

import numpy as np

from pbccs_tpu.obs import metrics as _metrics
from pbccs_tpu.obs import trace as _trace

STAGE_SECONDS = "ccs_stage_seconds_total"
DEVICE_WAIT_SECONDS = "ccs_device_wait_seconds_total"
DEVICE_FETCHES = "ccs_device_fetches_total"
DEVICE_FETCH_SECONDS = "ccs_device_fetch_seconds"

_registry = _metrics.default_registry()
_device_wait = _registry.counter(
    DEVICE_WAIT_SECONDS, "Blocking seconds inside device-to-host fetches")
_fetches = _registry.counter(DEVICE_FETCHES, "Device-to-host fetch count")
_fetch_hist = _registry.histogram(
    DEVICE_FETCH_SECONDS, "Per-fetch blocking latency (s)",
    buckets=_metrics.log_buckets(1e-5, 30.0))

# per-stage Counter handles, cached so the hot path is one dict hit + one
# locked add (the old defaultdict had the same cost profile)
_stage_counters: dict[str, _metrics.Counter] = {}
_stage_lock = threading.Lock()

_window = _registry.scope()   # module-default measurement window
_window_lock = threading.Lock()


def _stage_counter(name: str) -> _metrics.Counter:
    c = _stage_counters.get(name)
    if c is None:
        with _stage_lock:
            c = _stage_counters.get(name)
            if c is None:
                c = _registry.counter(
                    STAGE_SECONDS,
                    "Accumulated thread-seconds per pipeline stage",
                    stage=name)
                _stage_counters[name] = c
    return c


@contextlib.contextmanager
def stage(name: str):
    """Attribute the enclosed wall time to a named pipeline stage
    (summed across threads; see stage_seconds).  Cheap enough to leave on:
    two perf_counter calls + one locked add per use."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _stage_counter(name).inc(time.perf_counter() - t0)


def add_stage(name: str, dt: float) -> None:
    """Attribute dt seconds to a stage (for callers that already timed)."""
    _stage_counter(name).inc(dt)


def device_fetch(arr, dtype=None) -> np.ndarray:
    """np.asarray(arr) with the blocking time attributed to device wait
    (registry counters + the innermost open trace span)."""
    t0 = time.perf_counter()
    out = np.asarray(arr, dtype) if dtype is not None else np.asarray(arr)
    dt = time.perf_counter() - t0
    _device_wait.inc(dt)
    _fetches.inc()
    _fetch_hist.observe(dt)
    _trace.add_device_wait(dt)
    return out


# ------------------------------------------------------- measurement windows

def window() -> _metrics.MeasurementScope:
    """Open an independent measurement window over the default registry.
    Any number may be live at once; none interferes with another."""
    return _registry.scope()


def reset() -> None:
    """Back-compat: start a new MODULE-DEFAULT window (what the
    module-level getters below report from).  Does not zero anything and
    does not touch windows other callers hold."""
    global _window
    with _window_lock:
        _window = _registry.scope()


def _module_window() -> _metrics.MeasurementScope:
    """The module-default window, read under the same lock reset() swaps
    it under: a getter racing a reset() must see one coherent scope, not
    whatever the interpreter happened to publish (the Logger.default()
    race of PR 2, in sibling form)."""
    with _window_lock:
        return _window


def stage_seconds(win: _metrics.MeasurementScope | None = None
                  ) -> dict[str, float]:
    """Per-stage accumulated THREAD time over the given window (default:
    the module window, i.e. since the last reset()).  With overlapped
    workers the stages can sum past wall time; the e2e attribution
    compares each stage against wall to find what binds the 1-core host."""
    win = win or _module_window()
    # stages untouched inside the window are dropped (zero delta), which
    # matches the old cleared-dict-on-reset surface
    return {dict(labels)["stage"]: v
            for labels, v in win.counters(STAGE_SECONDS).items() if v != 0}


def device_wait_seconds(win: _metrics.MeasurementScope | None = None
                        ) -> float:
    return (win or _module_window()).counter_value(DEVICE_WAIT_SECONDS)


def fetch_count(win: _metrics.MeasurementScope | None = None) -> int:
    return int((win or _module_window()).counter_value(DEVICE_FETCHES))
