"""Device-wait accounting (the tracing/profiling subsystem, SURVEY.md §5).

The polish stage's execution model batches all device work and fetches
results at a handful of sync points (one stacked fetch per refinement
round); everything else is host marshalling.  Routing those fetches
through device_fetch() splits wall time into host-side vs
device-wait-side, which over this environment's tunneled device link is
the meaningful decomposition (each fetch blocks on dispatch + device
execution + transfer).  bench.py reports device_wait_fraction from these
counters; reset() starts a measurement window.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time

import numpy as np

_device_wait_s = 0.0
_fetches = 0
_stage_s: dict[str, float] = collections.defaultdict(float)
_lock = threading.Lock()  # fetches may come from concurrent batch workers


@contextlib.contextmanager
def stage(name: str):
    """Attribute the enclosed wall time to a named pipeline stage
    (summed across threads; see stage_seconds).  Cheap enough to leave on:
    two perf_counter calls + one locked dict add per use."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            _stage_s[name] += dt


def add_stage(name: str, dt: float) -> None:
    """Attribute dt seconds to a stage (for callers that already timed)."""
    with _lock:
        _stage_s[name] += dt


def stage_seconds() -> dict[str, float]:
    """Per-stage accumulated THREAD time since reset().  With overlapped
    workers the stages can sum past wall time; the e2e attribution compares
    each stage against wall to find what binds the 1-core host."""
    with _lock:
        return dict(_stage_s)


def device_fetch(arr, dtype=None) -> np.ndarray:
    """np.asarray(arr) with the blocking time attributed to device wait."""
    global _device_wait_s, _fetches
    t0 = time.perf_counter()
    out = np.asarray(arr, dtype) if dtype is not None else np.asarray(arr)
    dt = time.perf_counter() - t0
    with _lock:
        _device_wait_s += dt
        _fetches += 1
    return out


def reset() -> None:
    global _device_wait_s, _fetches
    with _lock:
        _device_wait_s = 0.0
        _fetches = 0
        _stage_s.clear()


def device_wait_seconds() -> float:
    with _lock:
        return _device_wait_s


def fetch_count() -> int:
    with _lock:
        return _fetches
