"""Ordered bounded work pipeline: produce tasks, consume results in order.

The host-side scheduling spine of the CLI: a bounded pool runs consensus
batches concurrently while a consumer drains results in submission order
(so the output BAM preserves input order), with worker exceptions propagated
to both producer and consumer.  Parity: reference include/pacbio/ccs/
WorkQueue.h:53-217 (bounded head set, FIFO future queue, Finalize).

On TPU the heavy lifting is batched device programs, so the pool's job is
overlap of host stages (BAM decode, bucketing, writeback) with device
compute -- threads, not processes, are the right tool (the GIL is released
inside device calls and zlib).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Iterator, TypeVar

T = TypeVar("T")


class WorkQueue:
    """Bounded thread pool whose results are consumed in submission order."""

    def __init__(self, n_workers: int, max_pending: int | None = None):
        if n_workers < 1:
            raise ValueError("need at least one worker")
        self._pool = ThreadPoolExecutor(max_workers=n_workers,
                                        thread_name_prefix="pbccs-worker")
        self._sem = threading.BoundedSemaphore(max_pending or 3 * n_workers)
        self._futures: queue.Queue[Future | None] = queue.Queue()
        self._failed = threading.Event()
        self._first_error: BaseException | None = None

    def produce(self, fn: Callable[..., T], *args, **kwargs) -> None:
        """Submit a task; blocks when the pipeline is full (backpressure).

        Raises the original worker exception if a prior task already failed
        (reference WorkQueue.h:108-111 exception propagation to the
        producer)."""
        if self._failed.is_set():
            raise RuntimeError("work queue failed; no new tasks accepted"
                               ) from self._first_error
        self._sem.acquire()

        def run():
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                if not self._failed.is_set():
                    self._first_error = e
                self._failed.set()
                raise
            finally:
                self._sem.release()

        self._futures.put(self._pool.submit(run))

    def finalize(self) -> None:
        """Signal that no more tasks will be produced."""
        self._futures.put(None)

    def results(self) -> Iterator:
        """Yield task results in submission order; re-raises the first
        worker exception (reference WorkQueue.h:129-166)."""
        while True:
            fut = self._futures.get()
            if fut is None:
                break
            yield fut.result()

    def consume_with(self, consumer: Callable[[T], None]) -> None:
        for result in self.results():
            consumer(result)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True)

    def __enter__(self) -> "WorkQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
