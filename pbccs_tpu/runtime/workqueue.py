"""Ordered bounded work pipeline: produce tasks, consume results in order.

The host-side scheduling spine of the CLI: a bounded pool runs consensus
batches concurrently while a consumer drains results in submission order
(so the output BAM preserves input order), with worker exceptions propagated
to both producer and consumer.  Parity: reference include/pacbio/ccs/
WorkQueue.h:53-217 (bounded head set, FIFO future queue, Finalize).

On TPU the heavy lifting is batched device programs, so the pool's job is
overlap of host stages (BAM decode, bucketing, writeback) with device
compute -- threads, not processes, are the right tool (the GIL is released
inside device calls and zlib).

The pipeline bound counts results not yet CONSUMED, not tasks not yet
finished: releasing the slot at task completion let `_futures` hold
unboundedly many completed results whenever the consumer lagged the pool
(the reference's bounded head set has the same consume-time semantics,
WorkQueue.h:129-166).
"""

from __future__ import annotations

import queue
import threading
import traceback
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Iterator, TypeVar

from pbccs_tpu.obs.metrics import default_registry

T = TypeVar("T")

_reg = default_registry()
# shared across WorkQueue instances (concurrent queues sum; normally one)
_depth = _reg.gauge("ccs_workqueue_depth",
                    "Tasks produced but not yet consumed")
_produced = _reg.counter("ccs_workqueue_produced_total",
                         "Tasks submitted to the work queue")
_consumed = _reg.counter("ccs_workqueue_consumed_total",
                         "Task results consumed in order")
_failures = _reg.counter("ccs_workqueue_task_failures_total",
                         "Worker tasks that raised (propagated)")


class WorkQueue:
    """Bounded thread pool whose results are consumed in submission order."""

    def __init__(self, n_workers: int, max_pending: int | None = None):
        if n_workers < 1:
            raise ValueError("need at least one worker")
        self._pool = ThreadPoolExecutor(max_workers=n_workers,
                                        thread_name_prefix="pbccs-worker")
        self._sem = threading.BoundedSemaphore(max_pending or 3 * n_workers)
        self._futures: queue.Queue[Future | None] = queue.Queue()
        self._failed = threading.Event()
        self._first_error: BaseException | None = None
        self._error_lock = threading.Lock()

    def _raise_failed(self) -> None:
        with self._error_lock:
            err = self._first_error
        raise RuntimeError("work queue failed; no new tasks accepted"
                           ) from err

    def produce(self, fn: Callable[..., T], *args, **kwargs) -> None:
        """Submit a task; blocks when the pipeline is full (backpressure).

        The slot is held until the result is CONSUMED from results(), so
        max_pending bounds the completed-but-unconsumed backlog too.
        Raises the original worker exception if a prior task already failed
        (reference WorkQueue.h:108-111 exception propagation to the
        producer); a producer blocked on a full pipeline wakes up and
        raises when a worker fails while it waits."""
        if self._failed.is_set():
            self._raise_failed()
        while not self._sem.acquire(timeout=0.05):
            if self._failed.is_set():
                self._raise_failed()

        def run():
            try:
                from pbccs_tpu.resilience import faults

                # chaos site: a worker-task crash exercises the
                # propagate-to-producer/consumer path (and, under the
                # CLI's --checkpoint, the resume-after-crash path)
                faults.maybe_fail("workqueue.task")
                return fn(*args, **kwargs)
            except BaseException as e:
                # a propagated task failure aborts the whole pipeline;
                # make sure the log carries the traceback even if the
                # driver only surfaces the message
                _failures.inc()
                from pbccs_tpu.runtime.logging import Logger
                Logger.default().error(
                    "work queue task failed: "
                    + "".join(traceback.format_exception(
                        type(e), e, e.__traceback__)))
                # publish the error BEFORE the flag: a producer/consumer
                # woken by _failed must never observe _first_error unset
                with self._error_lock:
                    if self._first_error is None:
                        self._first_error = e
                self._failed.set()
                raise

        self._futures.put(self._pool.submit(run))
        _produced.inc()
        _depth.inc()

    def finalize(self) -> None:
        """Signal that no more tasks will be produced."""
        self._futures.put(None)

    def results(self) -> Iterator:
        """Yield task results in submission order; re-raises the first
        worker exception (reference WorkQueue.h:129-166).  Each task's
        pipeline slot is released here, when its result is consumed."""
        while True:
            fut = self._futures.get()
            if fut is None:
                break
            try:
                result = fut.result()
            finally:
                self._sem.release()
                _depth.dec()
                _consumed.inc()
            yield result

    def consume_with(self, consumer: Callable[[T], None]) -> None:
        for result in self.results():
            consumer(result)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True)
        # drain unconsumed results (consumer bailed early, e.g. on a worker
        # exception) so any producer still blocked in acquire() can wake
        while True:
            try:
                fut = self._futures.get_nowait()
            except queue.Empty:
                break
            if fut is not None:
                try:
                    self._sem.release()
                    _depth.dec()
                except ValueError:
                    pass  # bounded: already fully released
        # wake any consumer still blocked on the queue (producer aborted
        # before finalize); a stray sentinel in a discarded queue is harmless
        self._futures.put(None)

    def __enter__(self) -> "WorkQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
