"""ctypes bindings for the native host-runtime library (native/
pbccs_native.cpp): multithreaded BGZF codec and sparse-DP seed chaining.

The library is optional: every entry point has a pure-Python equivalent
(io.bam zlib path, align.seeds.chain_seeds), so a missing or unbuildable
.so degrades to the fallback silently.  Build with `make -C native`; the
loader also tries an on-demand build once when a compiler is available
(set PBCCS_NATIVE=0 to disable the native path entirely)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import weakref
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                           "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libpbccs_native.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("PBCCS_NATIVE", "").strip().lower() in ("0", "false", "off", "no"):
        return None
    src = os.path.join(_NATIVE_DIR, "pbccs_native.cpp")
    stale = (not os.path.exists(_LIB_PATH)
             or (os.path.exists(src)
                 and os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)))
    if stale and os.path.exists(os.path.join(_NATIVE_DIR, "Makefile")):
        # a failed rebuild degrades to the pure-Python path, but it must
        # be DIAGNOSABLE: log what broke (compiler stderr, timeout, a
        # missing make) instead of swallowing everything
        from pbccs_tpu.runtime.logging import Logger

        try:
            proc = subprocess.run(["make", "-B", "-C", _NATIVE_DIR],
                                  capture_output=True, timeout=120,
                                  check=False)
            if proc.returncode != 0:
                stderr = proc.stderr.decode(errors="replace").strip()
                Logger.default().warn(
                    f"native library rebuild failed (make exit "
                    f"{proc.returncode}); using pure-Python fallbacks. "
                    f"stderr:\n{stderr[-2000:]}")
        except subprocess.TimeoutExpired as e:
            stderr = (e.stderr or b"").decode(errors="replace").strip()
            Logger.default().warn(
                f"native library rebuild timed out after {e.timeout:g}s; "
                f"using pure-Python fallbacks. stderr:\n{stderr[-2000:]}")
        except OSError as e:
            Logger.default().warn(
                f"native library rebuild could not run make ({e}); "
                "using pure-Python fallbacks")
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.pbccs_bgzf_compress.restype = ctypes.c_int64
    lib.pbccs_bgzf_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_int64]
    lib.pbccs_bgzf_decompress.restype = ctypes.c_int64
    lib.pbccs_bgzf_decompress.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]
    lib.pbccs_chain_seeds.restype = ctypes.c_int32
    lib.pbccs_chain_seeds.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p]
    lib.pbccs_poa_new.restype = ctypes.c_void_p
    lib.pbccs_poa_new.argtypes = []
    lib.pbccs_poa_free.restype = None
    lib.pbccs_poa_free.argtypes = [ctypes.c_void_p]
    lib.pbccs_poa_orient_add.restype = ctypes.c_int32
    lib.pbccs_poa_orient_add.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32, ctypes.c_float,
        ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p]
    lib.pbccs_poa_consensus.restype = ctypes.c_int32
    lib.pbccs_poa_consensus.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p, ctypes.c_int32]
    lib.pbccs_poa_vertex_count.restype = ctypes.c_int32
    lib.pbccs_poa_vertex_count.argtypes = [ctypes.c_void_p]
    lib.pbccs_poa_export.restype = ctypes.c_int32
    lib.pbccs_poa_export.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p]
    lib.pbccs_poa_edge_count.restype = ctypes.c_int32
    lib.pbccs_poa_edge_count.argtypes = [ctypes.c_void_p]
    lib.pbccs_poa_edges.restype = None
    lib.pbccs_poa_edges.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def bgzf_compress(data: bytes, level: int = 6,
                  nthreads: int | None = None) -> Optional[bytes]:
    """Multithreaded BGZF compression of `data` (no EOF block appended);
    None if the native library is unavailable or fails."""
    lib = _load()
    if lib is None:
        return None
    if not data:
        return b""
    nthreads = nthreads or min(8, os.cpu_count() or 1)
    cap = len(data) + (len(data) // (64 * 1024) + 2) * 1024 + 1024
    out = ctypes.create_string_buffer(cap)
    n = lib.pbccs_bgzf_compress(data, len(data), level, nthreads, out, cap)
    if n < 0:
        return None
    return out.raw[:n]


def bgzf_decompress(data: bytes, expected_size: int | None = None) -> Optional[bytes]:
    """Decompress a concatenated-BGZF-block byte stream; None on failure."""
    lib = _load()
    if lib is None:
        return None
    if not data:
        return b""
    cap = expected_size if expected_size is not None else max(len(data) * 6, 1 << 20)
    while True:
        out = ctypes.create_string_buffer(cap)
        n = lib.pbccs_bgzf_decompress(data, len(data), out, cap)
        if n >= 0:
            return out.raw[:n]
        if n != -2 or expected_size is not None or cap > (1 << 31):
            return None            # -1 = corrupt input; give up immediately
        cap *= 4                   # -2 = under-capacity; grow and retry


class NativePoa:
    """Handle-based native POA engine (behavior-identical to
    poa.graph.PoaGraph; see native/pbccs_native.cpp).  None-returning
    factory `native_poa()` keeps the pure-Python fallback silent."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._h = lib.pbccs_poa_new()
        self.n_reads = 0
        # weakref.finalize rather than __del__: at interpreter shutdown the
        # ctypes machinery may already be torn down, making a __del__-based
        # free raise noisy ignored exceptions
        self._finalizer = weakref.finalize(self, lib.pbccs_poa_free, self._h)

    def orient_add(self, read: np.ndarray, min_score: float = 0.0):
        """(path, reverse_complemented) or None when rejected."""
        from pbccs_tpu.poa.banding import banding_enabled

        r = np.ascontiguousarray(read, np.int8)
        n = len(r)
        path = np.zeros(n, np.int32)
        rc = ctypes.c_uint8(0)
        added = self._lib.pbccs_poa_orient_add(
            self._h, r.ctypes.data_as(ctypes.c_void_p), n,
            ctypes.c_float(min_score), int(banding_enabled()),
            path.ctypes.data_as(ctypes.c_void_p), ctypes.byref(rc))
        if not added:
            return None
        self.n_reads += 1
        return path.tolist(), bool(rc.value)

    def consensus_path(self, min_coverage: int) -> list[int]:
        cap = max(self._lib.pbccs_poa_vertex_count(self._h), 1)
        out = np.zeros(cap, np.int32)
        m = self._lib.pbccs_poa_consensus(
            self._h, min_coverage, out.ctypes.data_as(ctypes.c_void_p), cap)
        assert m >= 0
        return out[:m].tolist()

    def bases(self) -> np.ndarray:
        """(V,) int8 per-vertex bases (no full graph export)."""
        n = self._lib.pbccs_poa_vertex_count(self._h)
        base = np.zeros(n, np.int8)
        nreads = np.zeros(n, np.int32)
        spanning = np.zeros(n, np.int32)
        score = np.zeros(n, np.float64)
        self._lib.pbccs_poa_export(
            self._h, base.ctypes.data_as(ctypes.c_void_p),
            nreads.ctypes.data_as(ctypes.c_void_p),
            spanning.ctypes.data_as(ctypes.c_void_p),
            score.ctypes.data_as(ctypes.c_void_p))
        return base

    def export_graph(self):
        """Read-only PoaGraph snapshot (for variant calling / GraphViz)."""
        from pbccs_tpu.poa.graph import PoaGraph

        n = self._lib.pbccs_poa_vertex_count(self._h)
        base = np.zeros(n, np.int8)
        nreads = np.zeros(n, np.int32)
        spanning = np.zeros(n, np.int32)
        score = np.zeros(n, np.float64)
        have = self._lib.pbccs_poa_export(
            self._h, base.ctypes.data_as(ctypes.c_void_p),
            nreads.ctypes.data_as(ctypes.c_void_p),
            spanning.ctypes.data_as(ctypes.c_void_p),
            score.ctypes.data_as(ctypes.c_void_p)) >= 0
        e = self._lib.pbccs_poa_edge_count(self._h)
        eu = np.zeros(e, np.int32)
        ev = np.zeros(e, np.int32)
        self._lib.pbccs_poa_edges(self._h,
                                  eu.ctypes.data_as(ctypes.c_void_p),
                                  ev.ctypes.data_as(ctypes.c_void_p))
        g = PoaGraph()
        g.base = base.tolist()
        g.nreads = nreads.tolist()
        g.spanning = spanning.tolist()
        g.preds = [[] for _ in range(n)]
        g.succs = [[] for _ in range(n)]
        for u, v in zip(eu.tolist(), ev.tolist()):
            g.succs[u].append(v)
            g.preds[v].append(u)
        g.n_reads = self.n_reads
        if have:
            g.vertex_score = score.astype(np.float32)
        return g


def native_poa() -> Optional[NativePoa]:
    lib = _load()
    return NativePoa(lib) if lib is not None else None


def chain_seeds(seeds: np.ndarray, k: int,
                match_reward: int = 3) -> Optional[np.ndarray]:
    """Native SDP chaining; same semantics as align.seeds.chain_seeds.
    None if the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    n = len(seeds)
    if n == 0:
        return np.zeros((0, 2), np.int32)
    h = np.ascontiguousarray(seeds[:, 0], np.int32)
    v = np.ascontiguousarray(seeds[:, 1], np.int32)
    out_h = np.zeros(n, np.int32)
    out_v = np.zeros(n, np.int32)
    m = lib.pbccs_chain_seeds(
        h.ctypes.data_as(ctypes.c_void_p), v.ctypes.data_as(ctypes.c_void_p),
        n, k, match_reward,
        out_h.ctypes.data_as(ctypes.c_void_p),
        out_v.ctypes.data_as(ctypes.c_void_p))
    return np.stack([out_h[:m], out_v[:m]], axis=1)
