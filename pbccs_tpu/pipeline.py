"""Per-ZMW consensus pipeline: filter -> POA draft -> Arrow polish -> QV.

TPU re-design of the reference's per-ZMW orchestration
(reference include/pacbio/ccs/Consensus.h:224-555): the same stage boundaries
and yield gates, but the polish stage is a batched device program and the
whole pipeline is structured so batches of ZMWs can be bucketed and vmapped
(see pbccs_tpu.parallel for the sharded batch driver).

Failure accounting matches the reference's eight result categories
(reference include/pacbio/ccs/Consensus.h:155-208, src/main/ccs.cpp:233-262).
"""

from __future__ import annotations

import dataclasses
import enum
import time
import traceback
from typing import Sequence

import numpy as np

from pbccs_tpu.obs import trace as obs_trace
from pbccs_tpu.obs.metrics import default_registry
from pbccs_tpu.runtime.logging import Logger
from pbccs_tpu.models.arrow.params import decode_bases, encode_bases
from pbccs_tpu.models.arrow.refine import (
    RefineOptions,
    predicted_accuracy,
    refine_consensus,
)
from pbccs_tpu.models.arrow.scorer import (ADD_ALPHABETAMISMATCH, ADD_SUCCESS,
                                           ArrowMultiReadScorer)
from pbccs_tpu.poa.sparse import PoaAlignmentSummary, SparsePoa

# Local-context adapter flags (reference pbbam LocalContextFlags; a subread is
# a full pass iff it is flanked by adapter hits on both sides).
ADAPTER_BEFORE = 1
ADAPTER_AFTER = 2

_reg = default_registry()

# every entry into the shared batch-polish core (offline driver, sched
# executor, serve flush, quarantine/OOM sub-dispatches re-enter): the
# kernel-invocation count the perf ledger records and the regression
# sentinel gates as a CPU-deterministic counter
_m_polish_dispatches = _reg.counter(
    "ccs_polish_dispatches_total",
    "polish_prepared_batch dispatches (incl. sub-dispatch re-entries)")


def record_zmw_failure(stage: str, exc: BaseException,
                       zmw: str | None = None) -> None:
    """Account one swallowed per-ZMW/per-batch exception: the class +
    traceback go to the debug log and ccs_zmw_failures_total{stage,exc}
    increments -- a fault-isolation boundary must never also be an
    information sink (the pre-resilience handlers discarded both)."""
    _reg.counter("ccs_zmw_failures_total",
                 "Exceptions absorbed by per-ZMW fault isolation",
                 stage=stage, exc=type(exc).__name__).inc()
    where = f"{stage}[{zmw}]" if zmw else stage
    tb = "".join(traceback.format_exception(type(exc), exc,
                                            exc.__traceback__))
    Logger.default().debug(
        f"{where}: absorbed {type(exc).__name__}: {exc}\n{tb}")


@dataclasses.dataclass(frozen=True)
class ConsensusSettings:
    """Pipeline knobs, reference defaults
    (reference include/pacbio/ccs/Consensus.h:86-111)."""

    max_poa_coverage: int = 1024
    min_length: int = 10
    min_passes: int = 3
    min_snr: float = 4.0  # CLI-level gate in the reference (ccs.cpp:441)
    min_predicted_accuracy: float = 0.90
    min_zscore: float = -5.0
    max_drop_fraction: float = 0.34
    refine: RefineOptions = dataclasses.field(default_factory=RefineOptions)
    # polish model family: "arrow" (the ccs default) or "quiver" (the
    # QV-feature model; reference ConsensusCore carries both behind one
    # templated refine/QV implementation, Consensus.hpp:64-79).  Subreads
    # without QV tracks polish with flat default tracks.
    model: str = "arrow"
    # quarantined poison ZMWs (batch AND serial polish failed) emit a
    # draft-only consensus (capped QVs, `df` tag) instead of dropping as
    # Failure.OTHER (resilience.quarantine; off = reference parity)
    degrade_quarantined: bool = False


@dataclasses.dataclass
class Subread:
    """One subread of a ZMW (reference ReadType, Consensus.h:115-124)."""

    id: str
    seq: np.ndarray  # int8 base codes
    flags: int = ADAPTER_BEFORE | ADAPTER_AFTER
    read_accuracy: float = 0.8

    @classmethod
    def from_str(cls, id: str, seq: str, **kw) -> "Subread":
        return cls(id, encode_bases(seq), **kw)

    @property
    def is_full_pass(self) -> bool:
        return bool(self.flags & ADAPTER_BEFORE) and bool(self.flags & ADAPTER_AFTER)


@dataclasses.dataclass
class Chunk:
    """All subreads of one ZMW (reference ChunkType, Consensus.h:126-133)."""

    id: str
    reads: list[Subread]
    snr: np.ndarray  # (4,) per-channel SNR, ACGT order


class Failure(enum.Enum):
    """Yield categories (reference ResultType, Consensus.h:155-208)."""

    SUCCESS = "Success"
    POOR_SNR = "PoorSNR"
    NO_SUBREADS = "NoSubreads"
    TOO_SHORT = "TooShort"
    TOO_MANY_UNUSABLE = "TooManyUnusable"
    TOO_FEW_PASSES = "TooFewPasses"
    NON_CONVERGENT = "NonConvergent"
    POOR_QUALITY = "PoorQuality"
    OTHER = "Other"


@dataclasses.dataclass
class ConsensusResult:
    """One CCS read (reference ConsensusType, Consensus.h:135-153)."""

    id: str
    sequence: str
    qvs: np.ndarray
    num_passes: int
    predicted_accuracy: float
    global_zscore: float
    avg_zscore: float
    zscores: np.ndarray
    status_counts: list[int]
    mutations_tested: int
    mutations_applied: int
    snr: np.ndarray
    elapsed_ms: float
    # set by resilience.quarantine.degrade_to_draft: the sequence is the
    # unpolished POA draft with capped QVs (emitted with a `df` BAM tag)
    draft_only: bool = False

    @property
    def qualities(self) -> str:
        """Phred+33 ASCII, clamped to [0, 93]
        (reference QVsToASCII, Consensus.h:328-339)."""
        return "".join(chr(min(max(0, int(q)), 93) + 33) for q in self.qvs)


@dataclasses.dataclass
class ResultTally:
    """Mutable per-batch yield counters + results."""

    results: list[ConsensusResult] = dataclasses.field(default_factory=list)
    counts: dict[Failure, int] = dataclasses.field(
        default_factory=lambda: {f: 0 for f in Failure})

    def tally(self, failure: Failure) -> None:
        self.counts[failure] += 1

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def merge(self, other: "ResultTally") -> None:
        self.results.extend(other.results)
        for f, c in other.counts.items():
            self.counts[f] += c


def filter_reads(reads: Sequence[Subread], min_length: int
                 ) -> list[Subread | None]:
    """Median-length window filter + full-pass-first priority sort.

    Returns the reads (or None for dropped ones) sorted so that full-pass
    reads closest to the median length come first.  Parity: reference
    FilterReads (Consensus.h:224-292): median over full-pass lengths (else
    the longest read), drop reads >= 2*median, return nothing when the median
    itself is < min_length.
    """
    if not reads:
        return []

    lengths = [len(r.seq) for r in reads if r.is_full_pass]
    longest = max(len(r.seq) for r in reads)
    median = float(np.median(lengths)) if lengths else float(longest)
    max_len = 2.0 * median

    if median < float(min_length):
        return []

    def lex_key(r: Subread | None):
        if r is None:
            return (-1.0, -1.0)  # sorts last
        l = float(len(r.seq))
        v = min(l / median, median / l)
        return (v, 0.0) if r.is_full_pass else (0.0, v)

    # non-ACGT codes (N / pad) never match in the POA or the HMM and would
    # desync sequence vs QV lengths downstream; empty reads divide-by-zero
    # in the sort key; both are unusable
    kept: list[Subread | None] = [
        r if 0 < len(r.seq) < max_len and bool((r.seq < 4).all()) else None
        for r in reads]
    kept.sort(key=lex_key, reverse=True)
    return kept


def poa_consensus(reads: Sequence[Subread | None], max_poa_coverage: int
                  ) -> tuple[np.ndarray, list[int], list[PoaAlignmentSummary]]:
    """Draft consensus via sparse POA.

    Returns (consensus codes, per-read keys (-1 = unadded), summaries).
    Parity: reference PoaConsensus (Consensus.h:352-390) including the
    min-coverage equation minCov = 1 if cov < 5 else (cov+1)/2 - 1.
    """
    poa = SparsePoa()
    keys: list[int] = []
    cov = 0
    for r in reads:
        if r is None:
            keys.append(-1)
            continue
        key = poa.orient_and_add_read(r.seq)
        keys.append(key)
        if key >= 0:
            cov += 1
            if cov >= max_poa_coverage:
                break
    min_cov = 1 if cov < 5 else (cov + 1) // 2 - 1
    css, summaries = poa.find_consensus(min_cov)
    return css, keys, summaries


@dataclasses.dataclass
class MappedRead:
    """A subread clipped to its POA extents, oriented onto the draft
    (reference ExtractMappedRead, Consensus.h:296-325)."""

    id: str
    seq: np.ndarray
    strand: int  # 0 = forward, 1 = reverse-complemented
    tpl_start: int
    tpl_end: int
    is_full_pass: bool


def extract_mapped_read(read: Subread, summary: PoaAlignmentSummary,
                        min_length: int) -> MappedRead | None:
    rs, re_ = summary.extent_on_read
    ts, te = summary.extent_on_consensus
    if rs > re_ or re_ - rs < min_length:
        return None
    if summary.reverse_complemented:
        # extents are in oriented-read (revcomp) coordinates; the scorer
        # aligns the NATIVE read against the reverse-complement template
        # window tpl_r[L-te : L-ts], whose native-frame slice is below
        n = len(read.seq)
        seq = read.seq[n - re_: n - rs]
        strand = 1
    else:
        seq = read.seq[rs:re_]
        strand = 0
    return MappedRead(read.id, seq, strand, ts, te, read.is_full_pass)


@dataclasses.dataclass
class PreparedZmw:
    """One ZMW past the filter/draft/mapping stages, ready to polish."""

    chunk: Chunk
    css: np.ndarray
    mapped: list[MappedRead]
    n_candidates: int
    n_unmappable: int
    prep_ms: float


def prepare_chunk(chunk: Chunk, settings: ConsensusSettings
                  ) -> tuple[Failure | None, PreparedZmw | None]:
    """Filter -> POA draft -> read mapping (the host stages of the per-ZMW
    pipeline, reference Consensus.h:396-434)."""
    t0 = time.monotonic()

    if float(np.min(chunk.snr)) < settings.min_snr:
        return Failure.POOR_SNR, None

    from pbccs_tpu.runtime import timing

    with obs_trace.span("filter", zmw=chunk.id):
        reads = filter_reads(chunk.reads, settings.min_length)
    if not reads or all(r is None for r in reads):
        return Failure.NO_SUBREADS, None

    with obs_trace.span("draft", zmw=chunk.id):
        with timing.stage("draft.poa"):
            css, keys, summaries = poa_consensus(reads,
                                                 settings.max_poa_coverage)
        if len(css) < settings.min_length:
            return Failure.TOO_SHORT, None

        # map reads onto the draft
        mapped: list[MappedRead] = []
        n_unmappable = 0
        with timing.stage("draft.map"):
            for r, k in zip(reads, keys):
                if r is None or k < 0:
                    continue
                mr = extract_mapped_read(r, summaries[k],
                                         settings.min_length)
                if mr is None:
                    n_unmappable += 1
                    continue
                mapped.append(mr)

    n_candidates = sum(1 for k in keys if k >= 0)
    if not mapped:
        return Failure.NO_SUBREADS, None

    prep_ms = (time.monotonic() - t0) * 1e3
    return None, PreparedZmw(chunk, css, mapped, n_candidates,
                             n_unmappable, prep_ms)


def _read_gates(prep: PreparedZmw, statuses: np.ndarray,
                settings: ConsensusSettings
                ) -> tuple[Failure | None, list[int], int]:
    """Post-AddRead yield gates (reference Consensus.h:437-471): returns
    (failure or None, per-status counts, usable full passes)."""
    status_counts = [0] * 5
    n_passes = 0
    n_dropped = prep.n_unmappable
    for i, m in enumerate(prep.mapped):
        st = int(statuses[i])
        status_counts[st] += 1
        if st == ADD_SUCCESS and m.is_full_pass:
            n_passes += 1
        elif st != ADD_SUCCESS:
            n_dropped += 1

    if n_passes < settings.min_passes:
        return Failure.TOO_FEW_PASSES, status_counts, n_passes
    if prep.n_candidates > 0 and \
            n_dropped / prep.n_candidates > settings.max_drop_fraction:
        return Failure.TOO_MANY_UNUSABLE, status_counts, n_passes
    return None, status_counts, n_passes


def _finish_zmw(prep: PreparedZmw, settings: ConsensusSettings,
                tpl: np.ndarray, qvs: np.ndarray, refine,
                zscores: np.ndarray, global_z: float,
                status_counts: list[int], n_passes: int,
                elapsed_ms: float) -> tuple[Failure, ConsensusResult | None]:
    """Post-polish yield gates + result assembly
    (reference Consensus.h:497-553)."""
    if not refine.converged:
        return Failure.NON_CONVERGENT, None

    pred_acc = predicted_accuracy(qvs)
    if pred_acc < settings.min_predicted_accuracy:
        return Failure.POOR_QUALITY, None

    sequence = decode_bases(tpl)
    if len(sequence) != len(qvs):  # invalid bases reached the template
        return Failure.OTHER, None

    zs = zscores[np.isfinite(zscores)]
    avg_z = float(zs.mean()) if len(zs) else float("nan")
    return Failure.SUCCESS, ConsensusResult(
        id=prep.chunk.id,
        sequence=sequence,
        qvs=qvs,
        num_passes=n_passes,
        predicted_accuracy=pred_acc,
        global_zscore=global_z,
        avg_zscore=avg_z,
        zscores=zscores.copy(),
        status_counts=status_counts,
        mutations_tested=refine.n_tested,
        mutations_applied=refine.n_applied,
        snr=np.asarray(prep.chunk.snr),
        elapsed_ms=elapsed_ms)


def polish_prepared_quiver(prep: PreparedZmw, settings: ConsensusSettings
                           ) -> tuple[Failure, ConsensusResult | None]:
    """Quiver-model polish of a prepared ZMW: same stage structure as the
    Arrow path (gates -> refine -> QVs -> finish), driven through the
    generic refine/QV implementations over QuiverMultiReadScorer
    (reference Quiver/MultiReadMutationScorer.cpp behind the templated
    RefineConsensus/ConsensusQVs, Consensus-inl.hpp:160-297).  Subreads
    carry no QV tracks here, so the features use flat default tracks
    (param-only move scores); Quiver has no closed-form Z-score moments
    (an Arrow-specific construct, Arrow/Expectations.hpp), so z-score
    fields report NaN and the z-score gate is vacuous."""
    from pbccs_tpu.models.arrow.refine import consensus_qvs
    from pbccs_tpu.models.quiver.features import flat_default_features
    from pbccs_tpu.models.quiver.scorer import QuiverMultiReadScorer

    t0 = time.monotonic()
    scorer = QuiverMultiReadScorer(
        prep.css,
        [flat_default_features(m.seq) for m in prep.mapped],
        [m.strand for m in prep.mapped],
        [m.tpl_start for m in prep.mapped],
        [m.tpl_end for m in prep.mapped])

    failure, status_counts, n_passes = _read_gates(prep, scorer.statuses,
                                                   settings)
    if failure is not None:
        return failure, None

    refine = refine_consensus(scorer, settings.refine)
    if not refine.converged:
        return Failure.NON_CONVERGENT, None
    qvs = consensus_qvs(scorer)
    elapsed_ms = prep.prep_ms + (time.monotonic() - t0) * 1e3
    nan_zs = np.full(scorer.n_reads, np.nan)
    return _finish_zmw(prep, settings, scorer.tpl, qvs, refine,
                       nan_zs, float("nan"), status_counts, n_passes,
                       elapsed_ms)


def polish_prepared(prep: PreparedZmw, settings: ConsensusSettings
                    ) -> tuple[Failure, ConsensusResult | None]:
    """The serial polish half of the per-ZMW pipeline, given an already
    prepared (filtered + drafted + mapped) ZMW.  The serial scorer owns the
    wider-band AddRead retry."""
    if settings.model == "quiver":
        return polish_prepared_quiver(prep, settings)
    t0 = time.monotonic()
    scorer = ArrowMultiReadScorer(
        prep.css, prep.chunk.snr,
        [m.seq for m in prep.mapped],
        [m.strand for m in prep.mapped],
        [m.tpl_start for m in prep.mapped],
        [m.tpl_end for m in prep.mapped],
        min_zscore=settings.min_zscore)

    failure, status_counts, n_passes = _read_gates(prep, scorer.statuses,
                                                   settings)
    if failure is not None:
        return failure, None

    global_z = scorer.global_zscore()
    refine = refine_consensus(scorer, settings.refine)
    if not refine.converged:
        return Failure.NON_CONVERGENT, None
    qvs = scorer.consensus_qvs()
    elapsed_ms = prep.prep_ms + (time.monotonic() - t0) * 1e3
    return _finish_zmw(prep, settings, scorer.tpl, qvs, refine,
                       scorer.zscores, global_z, status_counts, n_passes,
                       elapsed_ms)


def process_chunk(chunk: Chunk, settings: ConsensusSettings | None = None
                  ) -> tuple[Failure, ConsensusResult | None]:
    """The per-ZMW pipeline (reference Consensus, Consensus.h:396-553)."""
    settings = settings or ConsensusSettings()
    failure, prep = prepare_chunk(chunk, settings)
    if failure is not None:
        return failure, None
    return polish_prepared(prep, settings)


def _polish_tasks(preps: Sequence[PreparedZmw]) -> list:
    """The ZmwTask batch of a prepared ZMW sequence (ONE construction
    shared by the inline dispatch and the prepare-side prebake)."""
    from pbccs_tpu.parallel.batch import ZmwTask

    return [ZmwTask(p.chunk.id, p.css, np.asarray(p.chunk.snr),
                    [m.seq for m in p.mapped],
                    [m.strand for m in p.mapped],
                    [m.tpl_start for m in p.mapped],
                    [m.tpl_end for m in p.mapped]) for p in preps]


def prebake_polish(preps: Sequence[PreparedZmw], *,
                   buckets: tuple[int, int, int] | None = None,
                   min_z: int = 1):
    """Pre-bake a prepared batch's device inputs on the PREPARE side:
    build the ZmwTask batch and its bucket-shaped numpy marshalling
    (parallel.batch.premarshal -- padded planes + f64 SNR transition
    tables).  The sched/ prepare workers run this so the device executor
    thread's BatchPolisher adopts arrays instead of re-deriving them;
    pass the result to polish_prepared_batch(prebaked=...)."""
    from pbccs_tpu.parallel.batch import premarshal

    return premarshal(_polish_tasks(preps), buckets=buckets, min_z=min_z)


def _polish_batch_arrow(preps: Sequence[PreparedZmw],
                        settings: ConsensusSettings, *,
                        buckets: tuple[int, int, int] | None = None,
                        min_z: int = 1, prebaked=None
                        ) -> list[tuple[Failure, ConsensusResult | None]]:
    """One lockstep BatchPolisher dispatch over `preps`: the raw Arrow
    device path, outcomes ALIGNED with `preps`.  Raises on any batch-path
    failure -- fault handling (hang watchdog, transient-error retry,
    poison-ZMW quarantine) lives in polish_prepared_batch."""
    from pbccs_tpu.runtime import timing

    t0 = time.monotonic()
    from pbccs_tpu.parallel.batch import BatchPolisher

    tasks = prebaked.tasks if prebaked is not None else _polish_tasks(preps)
    with obs_trace.span("polish.setup", zmws=len(preps)):
        polisher = BatchPolisher(tasks, min_zscore=settings.min_zscore,
                                 buckets=buckets, min_z=min_z,
                                 prebaked=prebaked)
    gate_info = []
    for z, p in enumerate(preps):
        gate_info.append(_read_gates(p, polisher.statuses[z], settings))
    # ZMWs that shed reads to the alpha/beta mating gate retry in ONE
    # wider-band (2x) sub-batch -- the batched analogue of the serial
    # scorer's whole-scorer escalation (the reference rebands a
    # mismatched pair up to 5 times before dropping,
    # SimpleRecursor.cpp:642-691).  Keep-better-width per ZMW: a ZMW
    # polishes at the wide band iff it MATES more reads there
    # (status != ALPHABETAMISMATCH -- deliberately counting reads the
    # wide band mates but the z-score gate then drops: the reference
    # rebands to achieve alpha/beta agreement FIRST and applies the
    # z-score gate to whatever mated, so reband-to-mate-then-gate is
    # the parity semantics, not mates-that-survive-gating).  Otherwise
    # it stays in the narrow batch with its drops (the serial retry's
    # revert).  Either way the ZMW stays on the batched device path.
    reband = sorted(z for z, p in enumerate(preps)
                    if (polisher.statuses[z, : len(p.mapped)]
                        == ADD_ALPHABETAMISMATCH).any())
    wide = None
    wide_pick: dict[int, int] = {}
    if reband:
        wcfg = dataclasses.replace(
            polisher.config,
            banding=dataclasses.replace(
                polisher.config.banding,
                # 2x the EFFECTIVE width (the W(L) schedule may have
                # shrunk the narrow batch below the configured width);
                # a non-default width passes through the schedule
                band_width=2 * polisher._W))
        try:  # speculative build: any failure keeps the narrow batch
            from pbccs_tpu.utils import next_pow2

            # pin shapes to the narrow batch's buckets + pow2 Z so the
            # data-dependent reband count doesn't mint fresh compiles
            wide = BatchPolisher([tasks[z] for z in reband],
                                 config=wcfg,
                                 min_zscore=settings.min_zscore,
                                 buckets=(polisher._Imax,
                                          polisher._Jmax,
                                          polisher._R),
                                 min_z=next_pow2(len(reband), 4))
        except Exception as e:  # noqa: BLE001 -- keep the narrow batch
            record_zmw_failure("polish.wide_build", e,
                               zmw=f"reband[{len(reband)}]")
            wide = None
        if wide is not None:
            for i, z in enumerate(reband):
                nr = len(preps[z].mapped)
                n_narrow = int((polisher.statuses[z, :nr]
                                != ADD_ALPHABETAMISMATCH).sum())
                n_wide = int((wide.statuses[i, :nr]
                              != ADD_ALPHABETAMISMATCH).sum())
                if n_wide > n_narrow:
                    wide_pick[z] = i
                    gate_info[z] = _read_gates(
                        preps[z], wide.statuses[i], settings)
        # banding observability: retry outcomes per batch (the
        # reference's NumFlipFlops analogue at batch granularity)
        Logger.default().debug(
            f"band retry: {len(reband)} ZMW(s) had mating failures at "
            f"W={polisher._W}; "
            f"{len(wide_pick)} adopted the 2x band, "
            f"{len(reband) - len(wide_pick)} reverted")
    # gate-failed ZMWs are excluded from refinement/QV (the serial path
    # returns before polishing them); their batch slots stay idle
    gate_failed = {z for z, g in enumerate(gate_info) if g[0] is not None}
    skip = gate_failed | set(wide_pick)
    # z-score statistics are reported for the draft template, before
    # refinement (parity with the serial path)
    global_zs = polisher.global_zscores()
    with obs_trace.span("polish.refine", zmws=len(preps) - len(skip)):
        refine_results = polisher.refine(settings.refine, skip=skip)
    wide_refine = wide_qvs = wide_gz = None
    if wide_pick:
        try:  # the whole wide retry is speculative: any failure in its
            # polish falls back to the narrow batch's completed results
            # (with the narrow gates) instead of discarding the batch
            wide_skip = {i for i in range(wide.n_zmws)
                         if i not in {wi for z, wi in wide_pick.items()
                                      if z not in gate_failed}}
            wide_gz = wide.global_zscores()
            wide_refine = wide.refine(settings.refine, skip=wide_skip)
            wide_qvs = wide.consensus_qvs(
                skip=wide_skip | {i for i, r in enumerate(wide_refine)
                                  if not r.converged})
        except Exception as e:  # noqa: BLE001 -- revert to narrow batch
            record_zmw_failure("polish.wide", e,
                               zmw=f"reband[{len(wide_pick)}]")
            retry = set(wide_pick)
            for z in list(wide_pick):
                gate_info[z] = _read_gates(
                    preps[z], polisher.statuses[z], settings)
            wide_pick.clear()
            gate_failed = {z for z, g in enumerate(gate_info)
                           if g[0] is not None}
            skip = gate_failed
            # refine ONLY the formerly wide-routed ZMWs: the rest of
            # the narrow batch already refined in the first pass, and
            # re-running them would hand non-convergent ZMWs a second
            # full iteration budget and rebuild their refine stats
            todo = retry - gate_failed
            if todo:
                retry_results = polisher.refine(
                    settings.refine,
                    skip=set(range(polisher.n_zmws)) - todo)
                for z in todo:
                    refine_results[z] = retry_results[z]
    # non-converged ZMWs are discarded by _finish_zmw; don't pay the QV
    # sweep (the most expensive single pass) for them
    skip = skip | {z for z, r in enumerate(refine_results)
                   if not r.converged}
    with obs_trace.span("polish.qv", zmws=len(preps) - len(skip)):
        qvs = polisher.consensus_qvs(skip=skip)
    polish_s = time.monotonic() - t0
    timing.add_stage("polish", polish_s)
    polish_ms = polish_s * 1e3 / max(len(preps), 1)

    # outcomes accumulate into a local list so a mid-loop fault cannot
    # double-count ZMWs when the serial fallback reruns them
    outcomes: list[tuple[Failure, ConsensusResult | None]] = []
    for z, p in enumerate(preps):
        failure, status_counts, n_passes = gate_info[z]
        if failure is not None:
            outcomes.append((failure, None))
            continue
        nr = len(p.mapped)
        if z in wide_pick:
            i = wide_pick[z]
            failure, result = _finish_zmw(
                p, settings, wide.tpls[i], wide_qvs[i], wide_refine[i],
                wide.zscores[i, :nr], wide_gz[i], status_counts,
                n_passes, p.prep_ms + polish_ms)
        else:
            failure, result = _finish_zmw(
                p, settings, polisher.tpls[z], qvs[z],
                refine_results[z], polisher.zscores[z, :nr],
                global_zs[z], status_counts, n_passes,
                p.prep_ms + polish_ms)
        outcomes.append((failure, result))
    return outcomes


def _pinned_batch_shapes(preps: Sequence[PreparedZmw],
                         buckets: tuple[int, int, int] | None,
                         min_z: int) -> tuple[tuple[int, int, int], int]:
    """The effective (Imax, Jmax, R)/Z shapes the full batch polishes at:
    quarantine sub-dispatches pin to these so they replay the parent's
    compiled programs -- and, because band width W is a function of the
    Jmax bucket, produce byte-identical results for surviving ZMWs.

    zq/rq stay at their defaults (1): _polish_batch_arrow builds its
    BatchPolisher without a mesh, so the parent's shapes were derived
    with the same quanta.  A meshed dispatch path would need the mesh's
    axis sizes threaded through here."""
    from pbccs_tpu.parallel.batch import effective_shapes

    imax, jmax, r, z = effective_shapes(
        len(preps),
        max(len(p.mapped) for p in preps),
        max((len(m.seq) for p in preps for m in p.mapped), default=8),
        max(len(p.css) for p in preps),
        buckets=buckets, min_z=min_z)
    return (imax, jmax, r), z


def _guarded_dispatch(preps: Sequence[PreparedZmw],
                      settings: ConsensusSettings, *,
                      buckets: tuple[int, int, int] | None,
                      min_z: int, prebaked=None
                      ) -> list[tuple[Failure, ConsensusResult | None]]:
    """One fault-domain batch dispatch: the chaos fault site
    ("polish.dispatch", keyed by ZMW ids so poison specs can target one
    ZMW), the hang watchdog (ambient deadline: --polishTimeout /
    PBCCS_WATCHDOG_S; disabled by default), and a bounded retry on
    transient device errors.  A watchdog timeout is never retried -- a
    hang is not transient; the quarantine path isolates it instead."""
    from pbccs_tpu.resilience import faults, retry, watchdog

    ids = [p.chunk.id for p in preps]

    def dispatch():
        # the fault site sits INSIDE the watchdog scope: an injected
        # delay exercises exactly the hung-dispatch recovery path
        faults.maybe_fail("polish.dispatch", keys=ids)
        return _polish_batch_arrow(preps, settings, buckets=buckets,
                                   min_z=min_z, prebaked=prebaked)

    def attempt():
        return watchdog.run_with_deadline(dispatch, site="polish.dispatch")

    return retry.DEVICE_RETRY.run(
        attempt,
        retry_on=lambda e: not isinstance(e, watchdog.WatchdogTimeout)
        and retry.is_transient_device_error(e),
        site="polish.dispatch")


def polish_prepared_batch(preps: Sequence[PreparedZmw],
                          settings: ConsensusSettings | None = None, *,
                          buckets: tuple[int, int, int] | None = None,
                          min_z: int = 1,
                          on_error: str = "bisect",
                          raise_device_shaped: bool = False,
                          prebaked=None
                          ) -> list[tuple[Failure, ConsensusResult | None]]:
    """Polish a batch of prepared ZMWs in one lockstep BatchPolisher and
    return per-ZMW outcomes ALIGNED with `preps` -- the polish core shared
    by the offline driver (process_chunks) and the serving engine
    (pbccs_tpu.serve.engine.CcsEngine), which needs to route each outcome
    back to the client that submitted it.

    `buckets`/`min_z` pin the BatchPolisher's (Imax, Jmax, R)/Z shapes to
    caller-chosen lower bounds: the serving engine pins them to its length
    bucket + pow2 sizes so variable-size online flushes reuse one bounded
    compiled-program menu instead of minting a fresh device loop per
    (batch size, read count) draw.

    A batch-path error no longer re-runs everything serially with the
    exception discarded: the dispatch is guarded (hang watchdog,
    transient-XLA retry) and a persistent failure routes to
    resilience.quarantine -- with on_error="bisect" (default) the batch
    is bisected in O(k log Z) pinned-shape re-dispatches to isolate the
    k poison ZMW(s); on_error="serial" keeps the legacy whole-batch
    serial fallback.  Either way a ZMW that fails even its serial rescue
    is quarantined (logged + counted, optionally degraded to draft-only
    consensus) instead of silently reporting Failure.OTHER.

    `raise_device_shaped=True` (the device-fleet drivers' FIRST attempt
    at a batch) re-raises hardware-shaped failures -- a WatchdogTimeout,
    a persistent XLA runtime error, a RetriesExhausted -- instead of
    quarantining: bisecting on the device that just hung would burn
    O(Z log Z) timeouts on the same sick hardware, while re-raising lets
    the DevicePool strike/bench it and requeue the WHOLE batch to a
    healthy device.  Injected poison-ZMW faults (resilience.faults
    InjectedFault at polish.dispatch) are task-shaped and always stay on
    the quarantine path.

    `prebaked`: a PrebakedBatch from prebake_polish (built on a prepare
    worker) adopted by the full-batch dispatch only -- quarantine and
    OOM-split sub-dispatches and serial rescues always re-marshal their
    own subsets, so fault recovery is unchanged.

    Capacity governance (resilience.resources): a capacity-shaped
    failure (device OOM / RESOURCE_EXHAUSTED) is NEVER retried at the
    same shape and NEVER quarantined -- the batch splits Z -> Z/2
    through the same bucket-pinned sub-dispatch machinery quarantine
    uses (shapes pinned, so survivors stay byte-identical) and the
    MemoryGovernor records a shape ceiling, so later batches for the
    bucket are pre-split at admission instead of re-discovering the
    OOM."""
    settings = settings or ConsensusSettings()
    _m_polish_dispatches.inc()
    if settings.model == "quiver":
        # Quiver has no lockstep batch driver: it polishes per ZMW (its
        # scorer batches fills internally), with the same fault isolation
        out: list[tuple[Failure, ConsensusResult | None]] = []
        for p in preps:
            try:
                out.append(polish_prepared(p, settings))
            except Exception as e:  # noqa: BLE001 -- per-ZMW isolation
                record_zmw_failure("polish.quiver", e, zmw=p.chunk.id)
                out.append((Failure.OTHER, None))
        return out
    from pbccs_tpu.resilience import resources

    pin, z_pin = _pinned_batch_shapes(preps, buckets, min_z)
    cap = resources.default_governor().cap(
        resources.shape_bucket(*pin), device=resources.current_device())
    if cap is not None and len(preps) > cap:
        # admission pre-split: the governor already learned this bucket
        # OOMs past `cap` ZMWs on this device -- dispatch ceiling-sized
        # parts (pinned to the parent shapes, so results match the
        # unsplit batch byte for byte) instead of paying the OOM again
        resources.note_presplit()
        Logger.default().info(
            f"memory governor: pre-splitting batch of {len(preps)} "
            f"ZMW(s) at ceiling {cap} (bucket {pin})")
        out = []
        start = 0
        for size in resources.split_sizes(len(preps), cap):
            out.extend(_polish_split_part(
                preps[start:start + size], settings, pin,
                on_error=on_error,
                raise_device_shaped=raise_device_shaped))
            start += size
        return out
    return _polish_guarded(preps, settings, buckets=buckets, min_z=min_z,
                           pin=pin, z_pin=z_pin, on_error=on_error,
                           raise_device_shaped=raise_device_shaped,
                           prebaked=prebaked)


def _polish_split_part(preps: Sequence[PreparedZmw],
                       settings: ConsensusSettings, pin, *,
                       on_error: str, raise_device_shaped: bool
                       ) -> list[tuple[Failure, ConsensusResult | None]]:
    """One OOM-split part: pinned to the parent's (Imax, Jmax, R)
    bucket (byte-identity) with its OWN pow2 Z (the smaller Z IS the
    memory relief), full recovery semantics (further capacity splits,
    quarantine, serial rescue) intact."""
    from pbccs_tpu.utils import next_pow2

    z = next_pow2(len(preps), 1)
    return _polish_guarded(preps, settings, buckets=pin, min_z=z,
                           pin=pin, z_pin=z, on_error=on_error,
                           raise_device_shaped=raise_device_shaped,
                           prebaked=None)


def _capacity_split(preps: Sequence[PreparedZmw],
                    settings: ConsensusSettings, pin, *,
                    on_error: str, raise_device_shaped: bool,
                    exc: BaseException
                    ) -> list[tuple[Failure, ConsensusResult | None]]:
    """Recovery from a capacity-shaped dispatch failure at batch size Z:
    record the governor ceiling (Z // 2 for this device + bucket) and
    re-dispatch the two halves at the pinned bucket shapes.  A singleton
    that alone exceeds the device gets the serial host-path rescue (its
    last chance to fit), then quarantines -- never a same-shape retry
    loop, never a bisection tour over healthy ZMWs."""
    from pbccs_tpu.resilience import quarantine, resources

    record_zmw_failure("polish.capacity", exc,
                       zmw=f"batch[{len(preps)}]")
    resources.default_governor().record_oom(
        resources.shape_bucket(*pin), len(preps))
    if len(preps) == 1:
        return [quarantine.serial_rescue(preps[0], settings, exc)]
    resources.note_oom_split()
    mid = len(preps) // 2
    out: list[tuple[Failure, ConsensusResult | None]] = []
    for sub in (preps[:mid], preps[mid:]):
        out.extend(_polish_split_part(
            sub, settings, pin, on_error=on_error,
            raise_device_shaped=raise_device_shaped))
    return out


def _polish_guarded(preps: Sequence[PreparedZmw],
                    settings: ConsensusSettings, *,
                    buckets: tuple[int, int, int] | None, min_z: int,
                    pin, z_pin: int, on_error: str,
                    raise_device_shaped: bool, prebaked
                    ) -> list[tuple[Failure, ConsensusResult | None]]:
    """One guarded dispatch with the full failure-taxonomy recovery:
    capacity-shaped -> adaptive split (checked FIRST -- an OOM must
    never reach the device-shaped re-raise or the quarantine tour),
    device-shaped -> optional re-raise for the fleet scheduler,
    task-shaped -> quarantine bisection / legacy serial fallback."""
    try:
        return _guarded_dispatch(preps, settings, buckets=buckets,
                                 min_z=min_z, prebaked=prebaked)
    except Exception as e:  # noqa: BLE001 -- classified below
        from pbccs_tpu.resilience import quarantine, resources, retry, \
            watchdog

        if resources.is_capacity_error(e):
            return _capacity_split(preps, settings, pin,
                                   on_error=on_error,
                                   raise_device_shaped=raise_device_shaped,
                                   exc=e)
        if raise_device_shaped and (
                isinstance(e, (watchdog.WatchdogTimeout,
                               retry.RetriesExhausted))
                or type(e).__name__ == "XlaRuntimeError"):
            raise
        if on_error == "serial":
            # legacy fault isolation (reference Consensus.h:543-548):
            # re-run every ZMW through the serial pipeline, each with
            # the same rescue semantics bisection's singletons get
            record_zmw_failure("polish.batch", e,
                               zmw=f"batch[{len(preps)}]")
            return [quarantine.serial_rescue(p, settings, e)
                    for p in preps]
        return quarantine.isolate(
            preps,
            lambda sub: _guarded_dispatch(sub, settings, buckets=pin,
                                          min_z=z_pin),
            settings, e)


def prepare_batch(chunks: Sequence[Chunk],
                  settings: ConsensusSettings | None = None
                  ) -> tuple[ResultTally, list[PreparedZmw]]:
    """The host half of a batch: run every chunk through the prep stages
    (filter -> POA draft -> mapping) with per-ZMW fault isolation,
    returning (tally of prep-stage outcomes, survivors ready to polish).
    Shared by process_chunks and the device-fleet scheduler's prepare
    workers (pbccs_tpu.sched.executor), so the two drivers cannot drift."""
    from pbccs_tpu.resilience import faults
    from pbccs_tpu.runtime import timing

    settings = settings or ConsensusSettings()
    tally = ResultTally()
    preps: list[PreparedZmw] = []
    with timing.stage("draft"):
        for chunk in chunks:
            try:
                faults.maybe_fail("prep.zmw", keys=[chunk.id])
                failure, prep = prepare_chunk(chunk, settings)
            except Exception as e:  # noqa: BLE001 -- per-ZMW isolation
                record_zmw_failure("prepare", e, zmw=chunk.id)
                tally.tally(Failure.OTHER)
                continue
            if failure is not None:
                tally.tally(failure)
            else:
                preps.append(prep)
    return tally, preps


def process_chunks(chunks: Sequence[Chunk],
                   settings: ConsensusSettings | None = None,
                   batch_polish: bool = True,
                   on_error: str = "bisect") -> ResultTally:
    """Process a batch of ZMWs; exceptions become Other tallies (logged +
    counted, record_zmw_failure) and the batch continues (reference
    Consensus.h:543-548).

    With batch_polish (the default), all ZMWs that survive the host stages
    polish together in one lockstep BatchPolisher (polish_prepared_batch) --
    the TPU execution model (one batched device program per refinement
    round) instead of the reference's one-thread-per-ZMW loop.  `on_error`
    selects the batch-failure recovery (see polish_prepared_batch)."""
    settings = settings or ConsensusSettings()
    tally = ResultTally()
    # the lockstep BatchPolisher is the Arrow device path; Quiver polishes
    # through the per-ZMW pipeline (its scorer batches fills internally)
    if not batch_polish or settings.model == "quiver":
        for chunk in chunks:
            try:
                failure, result = process_chunk(chunk, settings)
            except Exception as e:  # noqa: BLE001 -- per-ZMW isolation
                record_zmw_failure("zmw", e, zmw=chunk.id)
                tally.tally(Failure.OTHER)
                continue
            tally.tally(failure)
            if result is not None:
                tally.results.append(result)
        return tally

    prep_tally, preps = prepare_batch(chunks, settings)
    tally.merge(prep_tally)
    if not preps:
        return tally

    with obs_trace.span("polish", zmws=len(preps)):
        outcomes = polish_prepared_batch(preps, settings,
                                         on_error=on_error)
    for failure, result in outcomes:
        tally.tally(failure)
        if result is not None:
            tally.results.append(result)
    return tally
