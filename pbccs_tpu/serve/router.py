"""`ccs router`: a health-checked front door over N `ccs serve` replicas.

The serve engine made one PROCESS the failure domain: a crashed or
drained `ccs serve` loses every in-flight session.  The router lifts the
device-fleet resilience idioms (pbccs_tpu/sched: sticky routing,
bench-and-requeue, bounded failure tours) to replica granularity:

  * **Sticky bucket-aware routing.**  Each submit is validated at the
    edge (the same `chunk_from_wire` contract the replicas apply), keyed
    by its approximate compiled-shape bucket (read-length geometry; the
    replica's prep stage derives the exact bucket), and routed with the
    shared ``sched.health.StickyMap`` -- the replica that already
    compiled a bucket's program menu keeps receiving it, spilling to the
    least-loaded healthy replica only past ``spill_depth`` in-flight
    (work-conserving stickiness, exactly the DevicePool rule).  Load is
    weighted by the replica's STATUS-REPORTED queue depth, not the
    router's own in-flight count alone: each health probe's `status`
    reply carries the engine's `pending` figure, and the excess over
    what this router has in flight (work admitted from other clients,
    or a backlog the engine is still chewing) counts toward the
    replica's effective depth -- an unevenly-loaded fleet spills away
    from the busy replica instead of queueing blindly behind it.
  * **Health checks.**  A background loop probes every replica with the
    protocol's `status` verb; a probe unanswered past
    ``health_timeout_s`` is a strike, ``bench_after`` strikes mark the
    replica unhealthy (``sched.health.HealthTracker``), and -- unlike a
    benched device -- a later successful probe RE-ADMITS it (a restarted
    replica routinely comes back).  `status` replies also carry the
    replica's ``accepting`` flag, so a SIGTERM-draining replica stops
    receiving new work before its socket ever closes.
  * **Failover with exactly-once replies.**  Every client submit gets a
    router-assigned request id (the protocol's id field is rewritten on
    both hops).  When a replica dies (connection loss), times out its
    probes, or rejects with `overloaded`/`closed`, its unanswered
    requests are transparently resubmitted to a healthy replica the
    request has not yet visited (``attempted`` bounds the tour to the
    fleet, mirroring ``_Task.excluded``).  A reply that RACES a failover
    is emitted exactly once: the first reply for an id wins, completes
    the request, and any later duplicate finds the id retired and is
    dropped (counted ``ccs_router_dedup_dropped_total``).  Polish is
    pure, so the duplicated device work is waste, never corruption.

The router front door reuses the serve server's framed-session armor
(`server._FramedSession`): max frame length, idle reap, per-session
in-flight cap, and abort accounting all behave identically at both
tiers (tools/fuzz_inputs.py points the same wire legs at each).

The multi-tenant edge (serve/tenancy.py) layers in FRONT of routing:
with ``--authTokens`` every session authenticates (token -> tenant),
admission is weighted-fair across tenants (per-tenant in-flight quotas,
bounded park queues, deficit-round-robin release), and when the fleet's
windowed SLO burn rate (from the same health probes) crosses
``--shedBurnRate`` the router sheds priority >= 1 work with a
``retry_after_ms`` hint before it can queue.  ``--tlsCert/--tlsKey``
secure the front door and the metrics endpoint; ``--tlsCa`` +
``--authToken`` secure and authenticate the replica links.

Metrics: ``ccs_router_routed_total{replica}``,
``ccs_router_failovers_total{replica}``,
``ccs_router_health_checks_total{replica,outcome}``,
``ccs_router_replica_unhealthy_total{replica}``,
``ccs_router_inflight{replica}``, ``ccs_router_dedup_dropped_total``,
``ccs_router_fleet_burn_rate`` (tenant-plane ``ccs_tenant_*`` metrics
live in serve/tenancy.py).
"""

from __future__ import annotations

import argparse
import dataclasses
import queue
import signal
import socket
import sys
import threading
import time
from typing import Any, Callable

from pbccs_tpu.obs import trace as obs_trace
from pbccs_tpu.obs.metrics import (
    default_registry,
    merge_expositions,
    relabel_exposition,
)
from pbccs_tpu.runtime.logging import Logger, LogLevel
from pbccs_tpu.sched.health import HealthPolicy, HealthTracker, StickyMap
from pbccs_tpu.serve import protocol, tenancy
from pbccs_tpu.serve.server import CcsServer, _FramedSession

_reg = default_registry()
_m_dedup = _reg.counter(
    "ccs_router_dedup_dropped_total",
    "Late duplicate replies dropped after a reply/failover race "
    "(exactly-once emission)")


class RouterClosed(RuntimeError):
    """Router is shutting down (or never started); no new requests."""


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Router knobs (see module docstring for the policy they drive)."""

    # ---- health probing ----
    health_interval_s: float = 2.0   # probe cadence per replica
    health_timeout_s: float = 5.0    # unanswered probe = one strike
    bench_after: int = 2             # strikes before a replica is unhealthy
    readmit_after: int = 1           # good probes before re-admission
    connect_timeout_s: float = 5.0   # replica (re)connect bound
    # reconnect attempts to a DOWN replica back off exponentially
    # (base, doubling, capped) instead of re-firing a blocking connect
    # on every health tick against a replica the supervisor knows is
    # mid-restart; a successful connect resets the schedule.  Skipped
    # ticks count ccs_router_reconnect_backoffs_total{replica}.
    reconnect_backoff_base_s: float = 0.5
    reconnect_backoff_cap_s: float = 15.0
    # dynamic membership (fleet verb / supervisor): allow a router that
    # starts with ZERO replicas -- members arrive via add_replica() as
    # the supervisor's children come up -- and allow removing the last
    # member (the supervisor cycles 1-replica fleets through restarts)
    allow_empty: bool = False
    # ---- routing ----
    # a home replica keeps its bucket while its in-flight depth is <=
    # spill_depth; past it the least-loaded healthy replica takes the
    # spill and becomes an additional home (work-conserving stickiness;
    # ~one flush-worth of requests keeps a replica's pipeline fed)
    spill_depth: int = 8
    # ---- wire-protocol armor (enforced by the shared framed session;
    # same semantics as the ServeConfig fields of the same name) ----
    max_line_bytes: int = 8 << 20
    max_inflight_per_session: int = 64
    idle_timeout_s: float = 600.0
    # ---- performance ledger (obs.ledger) ----
    # append fleet-wide NDJSON perf records to this path (--perfLedger):
    # every interval the router records its own snapshot plus one
    # replica_snapshot per reachable replica (that replica's own ledger
    # block when it writes one, else a live-state record from its
    # status reply) -- the fleet-wide ledger merge.  None disables.
    perf_ledger_path: str | None = None
    perf_ledger_interval_s: float = 30.0
    # ---- multi-tenant edge (serve/tenancy.py) ----
    # weighted-fair admission engages when the router front door runs a
    # token file AND fair_queue is on: per-tenant in-flight quotas (from
    # the token file), a bounded per-tenant park queue, DRR drain.  Off
    # (or with no token file) admission is the legacy direct dispatch.
    fair_queue: bool = True
    fair_queue_depth: int = 64     # parked submits per tenant, max
    drr_quantum: int = 4           # DRR credit per round (x tenant weight)
    # SLO-driven shedding: when the fleet burn rate (violations /
    # requests over shed_window_s, from health-probe slo blocks) crosses
    # the threshold, submits from priority >= 1 tenants are rejected
    # `overloaded` with a retry_after_ms hint; priority 0 is NEVER shed.
    # 0 disables shedding.
    shed_burn_threshold: float = 0.0
    shed_window_s: float = 30.0
    retry_after_ms: float = 1000.0  # backoff hint on shed/quota rejects

    def __post_init__(self):
        if self.bench_after < 1:
            raise ValueError("bench_after must be >= 1")
        if self.readmit_after < 1:
            raise ValueError("readmit_after must be >= 1")
        if self.spill_depth < 0:
            raise ValueError("spill_depth must be >= 0")
        # a zero interval busy-spins the health loop; a zero timeout
        # strikes replicas that answer within milliseconds
        if self.health_interval_s <= 0:
            raise ValueError("health_interval_s must be > 0")
        if self.health_timeout_s <= 0:
            raise ValueError("health_timeout_s must be > 0")
        if self.connect_timeout_s <= 0:
            raise ValueError("connect_timeout_s must be > 0")
        if self.reconnect_backoff_base_s <= 0:
            raise ValueError("reconnect_backoff_base_s must be > 0")
        if self.reconnect_backoff_cap_s < self.reconnect_backoff_base_s:
            raise ValueError("reconnect_backoff_cap_s must be >= "
                             "reconnect_backoff_base_s")
        if self.fair_queue_depth < 1:
            raise ValueError("fair_queue_depth must be >= 1")
        if self.drr_quantum < 1:
            raise ValueError("drr_quantum must be >= 1")
        if not 0.0 <= self.shed_burn_threshold <= 1.0:
            raise ValueError("shed_burn_threshold must be in [0, 1] "
                             "(a violation fraction; 0 disables)")
        if self.shed_window_s <= 0:
            raise ValueError("shed_window_s must be > 0")
        if self.retry_after_ms < 0:
            raise ValueError("retry_after_ms must be >= 0")


def parse_replica_spec(spec) -> tuple[str, int]:
    """Normalize one replica spec -- "host:port" (host defaulting to
    loopback) or a (host, port) pair -- raising ValueError with a
    usage-shaped message on garbage (the fleet verb surfaces it as
    bad_request)."""
    if isinstance(spec, str):
        host, _, port_s = spec.rpartition(":")
        try:
            return host or "127.0.0.1", int(port_s)
        except ValueError:
            raise ValueError(
                f"replica spec {spec!r}: want HOST:PORT") from None
    host, port = spec
    return host, int(port)


def route_key(chunk) -> tuple[int, int]:
    """Approximate compiled-shape bucket of a ZMW from read-length
    geometry alone (the router never drafts): the median read length
    stands in for the template length the replica's POA will produce.
    Affinity only -- a mismatch costs a compile on the routed replica,
    never correctness."""
    from pbccs_tpu.parallel.batch import length_bucket

    lens = sorted(len(r.seq) for r in chunk.reads)
    return length_bucket(lens[len(lens) // 2], lens[-1])


class RoutedRequest:
    """One client submit in flight through the router; emitted exactly
    once (guarded by the router lock via `done`)."""

    __slots__ = ("rid", "key", "wire", "deadline_ms", "emit", "attempted",
                 "assigned", "done", "submit_t", "trace", "tenant")

    def __init__(self, rid: str, key, wire: dict, deadline_ms,
                 emit: Callable[[dict], None],
                 trace: dict | None = None,
                 tenant: str | None = None):
        self.rid = rid
        self.key = key
        self.wire = wire
        self.deadline_ms = deadline_ms
        self.emit = emit
        self.attempted: set[str] = set()   # replica names tried
        self.assigned: str | None = None
        self.done = False
        self.submit_t = time.monotonic()
        # inbound trace context (client-sent or edge-minted): trace_id is
        # NEVER rewritten; the replica hop carries it with span_id
        # rewritten to this request's router span (`rt-<rid>`), exactly
        # as the request id itself is rewritten
        self.trace = trace
        # resolved tenant identity (token-derived at the edge session);
        # forwarded to replicas in the wire `tenant` field and the key
        # the fair queue charges admission against
        self.tenant = tenant

    def span_id(self) -> str:
        """The router-side span id the replica hop parents under."""
        return f"rt-{self.rid}"


class ReplicaLink:
    """One NDJSON/TCP connection from the router to a replica; replies
    stream back through a dedicated reader thread."""

    def __init__(self, router: "CcsRouter", replica: "_Replica",
                 sock: socket.socket):
        self._router = router
        self._replica = replica
        self._sock = sock
        self._wlock = threading.Lock()
        # alive transitions under their own lock: _wlock is held across
        # a blocking sendall (frame atomicity on the replica hop), same
        # discipline as server._FramedSession (ccs-analyze CONC001)
        self._slock = threading.Lock()
        self.alive = True
        self.failed = False   # set once by the router's _fail_link sweep
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"ccs-router-link-{replica.name}")

    def start(self) -> None:
        self._reader.start()

    def send(self, msg: dict) -> bool:
        """Best-effort frame to the replica; False marks the link dead
        (the caller runs the failover sweep, never this thread)."""
        token = self._router._link_token
        if token is not None and protocol.FIELD_AUTH not in msg:
            # authenticated replica hop: EVERY router-originated frame
            # (submits, health probes, fleet calls) carries the link
            # token, so a token-guarded replica never strikes its own
            # router's probes as unauthorized
            msg = dict(msg)
            msg[protocol.FIELD_AUTH] = token
        data = protocol.encode_msg(msg)
        try:
            with self._wlock:
                self._sock.sendall(data)
            return True
        except OSError:
            with self._slock:
                self.alive = False
            return False

    def _read_loop(self) -> None:
        try:
            with self._sock.makefile("rb") as rf:
                for line in rf:
                    if not line.strip():
                        continue
                    try:
                        msg = protocol.decode_line(line)
                    except protocol.ProtocolError:
                        continue  # never kill the link on one bad frame
                    self._router._on_replica_msg(self._replica, self, msg)
        except OSError:
            pass  # connection loss; the finally block runs the failover
        finally:
            with self._slock:
                self.alive = False
            self._router._on_link_lost(self._replica, self)

    def close(self) -> None:
        with self._slock:
            self.alive = False
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class _Replica:
    """Router-side bookkeeping for one `ccs serve` backend (mutable
    state guarded by the router lock)."""

    def __init__(self, index: int, host: str, port: int):
        self.index = index
        self.host = host
        self.port = port
        self.name = f"{host}:{port}"
        self.link: ReplicaLink | None = None
        self.connecting = False     # a reconnect attempt is in flight
        self.draining = False       # replica said it stopped accepting
        # reconnect backoff (exponential, capped): no attempt before
        # reconnect_at; a failed attempt doubles reconnect_backoff_s, a
        # successful connect resets both
        self.reconnect_backoff_s = 0.0
        self.reconnect_at = 0.0
        # engine-reported pending work BEYOND this router's own
        # in-flight (other clients / engine backlog), refreshed by each
        # status probe: routing weighs it so an unevenly-loaded fleet
        # spills off the busy replica (0 until the first probe answers)
        self.external_backlog = 0
        self.inflight: dict[str, RoutedRequest] = {}
        self.probe_id: str | None = None
        self.probe_t = 0.0
        self.routed = 0
        self.failovers = 0
        self.m_routed = _reg.counter(
            "ccs_router_routed_total",
            "Requests routed to each replica", replica=self.name)
        self.m_failover = _reg.counter(
            "ccs_router_failovers_total",
            "Unanswered requests resubmitted away from a replica "
            "(connection loss, probe timeout, drain, backpressure)",
            replica=self.name)
        self.m_hc_ok = _reg.counter(
            "ccs_router_health_checks_total",
            "Router health probes by outcome",
            replica=self.name, outcome="ok")
        self.m_hc_fail = _reg.counter(
            "ccs_router_health_checks_total",
            replica=self.name, outcome="fail")
        self.m_unhealthy = _reg.counter(
            "ccs_router_replica_unhealthy_total",
            "Times a replica was marked unhealthy", replica=self.name)
        self.m_inflight = _reg.gauge(
            "ccs_router_inflight",
            "Requests in flight per replica", replica=self.name)
        self.m_reconnect_backoff = _reg.counter(
            "ccs_router_reconnect_backoffs_total",
            "Health ticks that skipped a reconnect attempt while a down "
            "replica's exponential backoff window was open",
            replica=self.name)

    def depth(self) -> int:
        return len(self.inflight)

    def effective_depth(self) -> int:
        """Routing load: the router's own in-flight plus the engine's
        status-reported backlog from elsewhere (ROADMAP item 5: weight
        admission by replica status depth, not in-flight count alone)."""
        return len(self.inflight) + self.external_backlog


class CcsRouter:
    """The replica-fleet scheduler behind the router front door (see
    module docstring).  Engine-shaped for server.CcsServer: exposes
    .config / .status() / .metrics_text(), and the router session calls
    submit_routed()."""

    def __init__(self, replicas, config: RouterConfig | None = None, *,
                 logger: Logger | None = None,
                 tenants: tenancy.TenantDirectory | None = None,
                 link_ssl=None, link_token: str | None = None):
        """`replicas`: "host:port" strings or (host, port) pairs.

        `tenants` (the edge token directory) turns on weighted-fair
        admission and SLO-burn shedding; `link_ssl` (an ssl.SSLContext)
        wraps every replica connection; `link_token` rides every
        router-originated frame so token-guarded replicas accept the
        router's submits and probes."""
        self.config = config or RouterConfig()
        self._log = logger or Logger.default()
        self._tenants = tenants
        self._link_ssl = link_ssl
        self._link_token = link_token
        self._fair = (tenancy.FairQueue(
            tenants, queue_depth=self.config.fair_queue_depth,
            quantum=self.config.drr_quantum)
            if tenants is not None and self.config.fair_queue else None)
        if self._fair is not None and hasattr(tenants, "add_listener"):
            # online token-map reloads: new tenants need admission
            # state before their first submit reaches try_admit
            tenants.add_listener(self._fair.refresh)
        self._burn = tenancy.BurnMeter(self.config.shed_window_s)
        self._shed_total = 0
        # non-reentrant fair-queue pump: the holder of _pump_lock drains
        # until _pump_flag stays clear (a dispatch failing inline frees
        # slots and re-raises the flag; the holder's loop picks it up)
        self._pump_lock = threading.Lock()
        self._pump_flag = threading.Event()
        self._m_burn = _reg.gauge(
            "ccs_router_fleet_burn_rate",
            "Windowed fleet SLO burn rate (violations/requests) from "
            "replica health probes; the shed policy thresholds on it")
        parsed = [parse_replica_spec(spec) for spec in replicas]
        if not parsed and not self.config.allow_empty:
            raise ValueError("CcsRouter needs at least one replica")
        self._replicas = [_Replica(i, h, p)
                          for i, (h, p) in enumerate(parsed)]
        self._by_name = {r.name: r for r in self._replicas}
        # monotone member index: removed slots never recycle an index,
        # so a re-added name gets fresh bookkeeping order
        self._replica_seq = len(self._replicas)
        self._lock = threading.Lock()
        self._sticky = StickyMap()
        self._health = HealthTracker(HealthPolicy(
            bench_after=self.config.bench_after,
            readmit_after=self.config.readmit_after))
        self._requests: dict[str, RoutedRequest] = {}
        self._seq = 0
        self._probe_seq = 0
        # fleet-call plumbing (trace fan-out, metrics federation): ids
        # `fl<N>` on replica links complete these waiters, never the
        # request path
        self._fleet_seq = 0
        self._fleet_waits: dict[str, tuple[threading.Event, list]] = {}
        # router-owned span capture (the `trace` verb); CAS against the
        # process-wide tracer exactly like the engine's
        self._trace_lock = threading.Lock()
        self._capture: obs_trace.Tracer | None = None
        self._accepting = False    # submit gate (drain flips this first)
        self._down = True          # hard stop (failover stops too)
        # fleet supervisor hook (serve/supervisor.py): its status block
        # rides the status verb and fleet restart/readmit delegate to it
        self._supervisor = None
        self._routed_total = 0
        self._completed_total = 0
        self._failover_total = 0
        self._dedup_total = 0
        self._start_t = 0.0
        self._stop = threading.Event()
        self._health_thread: threading.Thread | None = None
        self._emit_queue: queue.Queue | None = None
        self._emit_thread: threading.Thread | None = None
        # fleet-wide performance ledger (config.perf_ledger_path)
        self._ledger = None
        self._ledger_window = None
        self._ledger_thread: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "CcsRouter":
        with self._lock:
            if self._accepting:
                return self
            self._accepting = True
            self._down = False
        self._start_t = time.monotonic()
        with self._lock:
            initial = list(self._replicas)
        for replica in initial:
            self._try_connect(replica)
        self._stop.clear()
        emit_queue: queue.Queue = queue.Queue()
        emit_thread = threading.Thread(
            target=self._emit_worker, args=(emit_queue,), daemon=True,
            name="ccs-router-emit")
        health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="ccs-router-health")
        with self._lock:
            self._emit_queue = emit_queue
            self._emit_thread = emit_thread
            self._health_thread = health_thread
        emit_thread.start()
        health_thread.start()
        if self.config.perf_ledger_path:
            from pbccs_tpu.obs.ledger import PerfLedger
            from pbccs_tpu.runtime import timing

            ledger = PerfLedger(self.config.perf_ledger_path,
                                logger=self._log)
            ledger_thread = threading.Thread(
                target=self._ledger_loop, args=(ledger,), daemon=True,
                name="ccs-router-ledger")
            with self._lock:
                self._ledger = ledger
                self._ledger_window = timing.window()
                self._ledger_thread = ledger_thread
            ledger_thread.start()
        with self._lock:
            names = [r.name for r in self._replicas]
            up = sum(1 for r in self._replicas if r.link is not None)
        self._log.info(
            f"ccs router up: {len(names)} replica(s) "
            f"[{', '.join(names)}], {up} connected")
        return self

    def close(self, drain: bool = True,
              deadline_s: float | None = None) -> bool:
        """Stop admission; with drain (default) wait for in-flight
        routed requests -- failover keeps working during the drain, so a
        replica dying mid-drain does not strand its requests.  Past
        ``deadline_s`` the remainder fail with a structured `closed`
        error.  Returns True when everything completed normally."""
        with self._lock:
            if self._down and not self._accepting:
                return True
            self._accepting = False
            pending0 = len(self._requests)
        drained = drain or pending0 == 0
        if drain:
            give_up_at = (time.monotonic() + deadline_s
                          if deadline_s else None)
            while True:
                with self._lock:
                    if not self._requests:
                        break
                    pending = len(self._requests)
                if give_up_at is not None and time.monotonic() > give_up_at:
                    drained = False
                    self._log.warn(
                        f"router drain deadline ({deadline_s}s) exceeded "
                        f"with {pending} request(s) pending: aborting")
                    break
                time.sleep(0.01)
        # stop any live capture while the replica links still exist: the
        # trace-stop fan-out must reach the replicas or their globally-
        # installed tracers outlive the router (accumulating spans until
        # max_spans, and refusing the next router's trace start).  The
        # dumps themselves are discarded -- a short bound keeps shutdown
        # from waiting on a sick replica.
        self.trace_stop(timeout_s=2.0)
        self._stop.set()
        with self._lock:
            health_thread = self._health_thread
            self._health_thread = None
        if health_thread is not None:
            health_thread.join(timeout=10.0)
        # fleet ledger: stop the loop, take one FINAL merged snapshot
        # while the replica links still exist (they close just below)
        with self._lock:
            ledger = self._ledger
            ledger_thread = self._ledger_thread
            self._ledger = None
            self._ledger_thread = None
        if ledger_thread is not None:
            ledger_thread.join(timeout=10.0)
        if ledger is not None:
            try:
                self._append_fleet_records(ledger, timeout_s=2.0)
            except Exception as e:  # noqa: BLE001 -- the ledger must
                # never block or break shutdown
                self._log.debug(f"final fleet ledger tick failed: {e!r}")
            ledger.close()
        with self._lock:
            self._down = True
            leftovers = [r for r in self._requests.values() if not r.done]
            for req in leftovers:
                req.done = True
            self._requests.clear()
            links = []
            for replica in self._replicas:
                replica.inflight.clear()
                replica.m_inflight.set(0)
                if replica.link is not None:
                    links.append(replica.link)
                    replica.link = None
        for req in leftovers:
            self._emit(req, protocol.error_to_wire(
                None, protocol.ERR_CLOSED, "router is shutting down"))
        # fair-queue stragglers (parked, never dispatched -- not in
        # _requests): fail them with the same structured closed error
        if self._fair is not None:
            for _tenant, req in self._fair.flush():
                if not req.done:
                    req.done = True
                    self._emit(req, protocol.error_to_wire(
                        None, protocol.ERR_CLOSED,
                        "router is shutting down"))
        for link in links:
            link.close()
        with self._lock:
            emit_queue, self._emit_queue = self._emit_queue, None
            emit_thread, self._emit_thread = self._emit_thread, None
        if emit_queue is not None:
            emit_queue.put(None)   # behind every queued reply
        if emit_thread is not None:
            emit_thread.join(timeout=10.0)
        # unblock any fleet-call waiters (their links are gone)
        with self._lock:
            waits = list(self._fleet_waits.values())
            self._fleet_waits.clear()
        for event, _slot in waits:
            event.set()
        self._log.info("ccs router down")
        return drained

    def __enter__(self) -> "CcsRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------- dynamic membership

    def set_supervisor(self, supervisor) -> None:
        """Install the fleet supervisor (serve/supervisor.py, or None to
        clear): its status_block() rides every status reply under
        FIELD_SUPERVISOR, and the fleet verb's restart/readmit actions
        delegate to it."""
        with self._lock:
            self._supervisor = supervisor

    def get_supervisor(self):
        with self._lock:
            return self._supervisor

    def pending_count(self) -> int:
        """Requests admitted but not yet answered (the autoscaler's
        queue-depth signal; cheaper than a full status())."""
        with self._lock:
            return len(self._requests)

    def replica_names(self) -> list[str]:
        with self._lock:
            return [r.name for r in self._replicas]

    def add_replica(self, spec) -> str:
        """Admit a new member (fleet verb `add` / supervisor respawn).
        The connect is attempted inline (bounded by connect_timeout_s);
        a member that is not up yet simply stays down until a health
        tick reaches it.  Returns the member name; raises ValueError on
        a bad spec or duplicate membership, RouterClosed after close()."""
        host, port = parse_replica_spec(spec)
        name = f"{host}:{port}"
        with self._lock:
            if self._down:
                raise RouterClosed("router is shutting down")
            if name in self._by_name:
                raise ValueError(f"replica {name} is already a member")
            self._replica_seq += 1
            replica = _Replica(self._replica_seq - 1, host, port)
            self._replicas.append(replica)
            self._by_name[name] = replica
        self._try_connect(replica)
        self._log.info(f"router: replica {name} joined the fleet")
        return name

    def remove_replica(self, name: str, drain: bool = True,
                       timeout_s: float = 30.0) -> dict:
        """Retire a member through the proven drain path: routing to it
        stops immediately, its sticky homes migrate, and its in-flight
        requests complete in place (bounded by `timeout_s`) -- anything
        still parked past the deadline (or with drain=False) fails over
        to the rest of the fleet via the shared sweep transaction, so
        removal never loses a request.  Refuses to remove the last
        member unless the router allows an empty fleet (supervised
        mode).  Returns {"replica", "drained", "failed_over"}."""
        with self._lock:
            replica = self._by_name.get(name)
            if replica is None:
                raise ValueError(f"replica {name} is not a member")
            if len(self._replicas) <= 1 and not self.config.allow_empty:
                raise ValueError(
                    "cannot remove the last replica (in-flight work "
                    "would have no failover target)")
            replica.draining = True            # no new routes from here on
            self._sticky.forget_member(name)   # homes migrate now
        drained = True
        if drain:
            deadline = time.monotonic() + max(float(timeout_s), 0.0)
            while True:
                with self._lock:
                    if not replica.inflight:
                        break
                if time.monotonic() > deadline:
                    drained = False
                    break
                time.sleep(0.01)
        with self._lock:
            # the remainder (drain=False, deadline hit, or replies that
            # raced the sweep) moves to the surviving members
            moved = self._sweep_inflight_locked(replica)
            if self._by_name.get(name) is replica:
                del self._by_name[name]
            try:
                self._replicas.remove(replica)
            except ValueError:
                pass
            link, replica.link = replica.link, None
            if link is not None:
                # the close below FINs the reader thread into
                # _on_link_lost; marking the link failed here makes that
                # sweep a no-op (the member is already gone -- a health
                # strike now would haunt a future member of this name)
                link.failed = True
            replica.probe_id = None
            self._health.forget(name)
        for req in moved:
            self._dispatch(req)
        if link is not None:
            link.close()
        self._log.info(
            f"router: replica {name} left the fleet "
            f"({'drained clean' if drained else 'drain deadline hit'}, "
            f"{len(moved)} request(s) failed over)")
        return {"replica": name, "drained": drained,
                "failed_over": len(moved)}

    # ------------------------------------------------------------ submission

    def submit_routed(self, wire_zmw: dict, key, deadline_ms,
                      emit: Callable[[dict], None],
                      trace: dict | None = None,
                      tenant: str | None = None) -> RoutedRequest:
        """Route one validated wire-shaped ZMW; `emit` receives exactly
        one reply dict (result or structured error; the caller rewrites
        the id).  `trace` is the request's validated trace context
        (client-sent, or edge-minted by the session when a capture is
        live); `tenant` the session's resolved identity.  With a token
        directory configured the request passes the shed gate (SLO burn
        x priority class) and the fair queue before routing.  Raises
        RouterClosed after close()."""
        with self._lock:
            if not self._accepting:
                raise RouterClosed("router is not accepting requests")
            self._seq += 1
            rid = f"q{self._seq}"
        req = RoutedRequest(rid, key, wire_zmw, deadline_ms, emit,
                            trace=trace, tenant=tenant)
        fair = self._fair
        if fair is None or tenant is None:
            self._dispatch(req)
            return req
        tenancy.count_request(tenant)
        cfg = self.config
        row = self._tenants.get(tenant)
        # shed gate first: under SLO burn, best-effort classes are
        # rejected BEFORE they can occupy queue slots (priority 0 is
        # never shed -- it rides straight into fair admission)
        # per-tenant SLO target when the token map declares one, else
        # the fleet-wide --shedBurnRate (a latency-tolerant tenant can
        # carry a loose threshold while the fleet sheds at its default)
        threshold = cfg.shed_burn_threshold
        if row is not None and row.shed_burn_rate is not None:
            threshold = row.shed_burn_rate
        burn = self._burn.rate() if threshold > 0 else 0.0
        if (threshold > 0 and row is not None
                and row.priority >= 1
                and burn >= threshold):
            fair.record_shed(tenant)
            with self._lock:
                self._shed_total += 1
            req.done = True
            self._emit(req, protocol.error_to_wire(
                None, protocol.ERR_OVERLOADED,
                f"shedding priority-{row.priority} work: fleet SLO burn "
                f"{burn:.3f} >= {threshold:g}; retry later",
                retry_after_ms=cfg.retry_after_ms))
            return req
        verdict = fair.try_admit(tenant, req)
        if verdict == "dispatch":
            self._dispatch(req)
        elif verdict == "rejected":
            req.done = True
            self._emit(req, protocol.error_to_wire(
                None, protocol.ERR_OVERLOADED,
                f"tenant {tenant!r} over quota with a full fair queue "
                f"({cfg.fair_queue_depth} parked); retry later",
                retry_after_ms=cfg.retry_after_ms))
        # "queued": parked under the tenant's bound; a freed slot
        # releases it through _pump_fair in DRR order
        return req

    def _pump_fair(self) -> None:
        """Dispatch whatever the fair queue releases.  Non-reentrant:
        a dispatch that fails inline completes requests -> frees slots
        -> lands here again; the inner call just raises the flag and the
        active pumper's loop re-drains.  Never called under the router
        lock (dispatch sends block)."""
        fair = self._fair
        if fair is None:
            return
        self._pump_flag.set()
        while self._pump_flag.is_set():
            if not self._pump_lock.acquire(blocking=False):
                return  # the active pumper will observe the flag
            try:
                self._pump_flag.clear()
                for _tenant, req in fair.drain():
                    self._dispatch(req)
            finally:
                self._pump_lock.release()

    def _routable_locked(self, replica: _Replica) -> bool:
        return (replica.link is not None and replica.link.alive
                and not replica.draining
                and self._health.healthy(replica.name))

    def _eligible_locked(self, req: RoutedRequest) -> list[_Replica]:
        return [r for r in self._replicas
                if r.name not in req.attempted and self._routable_locked(r)]

    def _pick_locked(self, req: RoutedRequest) -> _Replica | None:
        eligible = self._eligible_locked(req)
        if not eligible:
            return None

        def load(r: _Replica):
            return (r.effective_depth(),
                    self._sticky.resident_count(r.name), r.index)

        target, _outcome = self._sticky.route(
            req.key, eligible, member_id=lambda r: r.name, load=load,
            depth=lambda r: r.effective_depth(),
            spill_depth=self.config.spill_depth)
        return target

    def _dispatch(self, req: RoutedRequest) -> None:
        """Route + send, retrying across replicas until the frame is on
        a wire or the fleet is exhausted.  Never called under the router
        lock (sends block)."""
        while True:
            with self._lock:
                if req.done:
                    return
                if self._down:
                    fail = protocol.error_to_wire(
                        None, protocol.ERR_CLOSED, "router is shutting down")
                    self._complete_locked(req)
                else:
                    target = self._pick_locked(req)
                    if target is None:
                        code = (protocol.ERR_OVERLOADED
                                if not req.attempted
                                else protocol.ERR_INTERNAL)
                        detail = ("no healthy replica available; retry"
                                  if not req.attempted else
                                  "request failed on every healthy replica "
                                  f"(attempted: {sorted(req.attempted)})")
                        fail = protocol.error_to_wire(None, code, detail)
                        self._complete_locked(req)
                    else:
                        fail = None
                        req.attempted.add(target.name)
                        req.assigned = target.name
                        target.inflight[req.rid] = req
                        target.m_inflight.set(target.depth())
                        self._requests[req.rid] = req
                        self._sticky.note(req.key, target.name)
                        target.routed += 1
                        target.m_routed.inc()
                        self._routed_total += 1
                        link = target.link
            if fail is not None:
                self._emit(req, fail)
                return
            msg: dict[str, Any] = {"verb": protocol.VERB_SUBMIT,
                                   "id": req.rid, "zmw": req.wire}
            if req.deadline_ms is not None:
                msg["deadline_ms"] = req.deadline_ms
            if req.trace is not None:
                # replica hop: same trace_id, span_id rewritten to the
                # router's per-request span (the id-rewrite rule applied
                # to trace context) -- a failover re-dispatch repeats
                # exactly this frame, so the trace follows the request
                msg[protocol.FIELD_TRACE] = {
                    protocol.KEY_TRACE_ID:
                        req.trace[protocol.KEY_TRACE_ID],
                    protocol.KEY_SPAN_ID: req.span_id()}
            if req.tenant is not None:
                # forward the ORIGINAL submitter's identity; the replica
                # honors it because the link token's tenant is trusted
                # (tenancy.resolve_tenant's one exception)
                msg[protocol.FIELD_TENANT] = {
                    protocol.KEY_TENANT_NAME: req.tenant}
            if link.send(msg):
                return
            # the link died under us.  If the request is still parked on
            # this replica, detach it (so the link's failure sweep does
            # not double-dispatch it) and loop to try the next replica;
            # if the sweep got here FIRST the request is already live
            # elsewhere -- touching req.assigned now would orphan the
            # new owner's inflight entry and double-dispatch the request
            with self._lock:
                if req.done:
                    return
                if target.inflight.get(req.rid) is req:
                    del target.inflight[req.rid]
                    target.m_inflight.set(target.depth())
                    req.assigned = None
                    target.failovers += 1
                    target.m_failover.inc()
                    self._failover_total += 1
                    mine = True
                else:
                    mine = False
            self._fail_link(target, link, "send failed")
            if not mine:
                return

    def _record_request_span(self, req: RoutedRequest, msg: dict) -> None:
        """Retroactive per-request router span (recorded at emission:
        the one point every request passes exactly once).  Its exported
        span_id is the `rt-<rid>` the replica hop already named as its
        remote parent, so the merged fleet trace connects client ->
        router -> replica under one trace_id."""
        tracer = obs_trace.get_tracer()
        if tracer is None:
            return
        tracer.add_span(
            "router.request", time.monotonic() - req.submit_t,
            ctx=req.trace, span_id=req.span_id(),
            replica=req.assigned,
            attempts=len(req.attempted),
            outcome=msg.get("type"))

    def _emit(self, req: RoutedRequest, msg: dict) -> None:
        """Hand a completed reply to the dedicated emission thread.
        Emit callbacks write to CLIENT sockets (blocking, bounded only
        by the session armor); run on a replica link's reader thread
        they would starve that link's health-probe replies behind one
        slow client and falsely bench a healthy replica -- the same
        hand-off the serve engine does for batch completions."""
        self._record_request_span(req, msg)
        with self._lock:
            q = self._emit_queue
        if q is not None:
            q.put((req, msg))
            self._pump_fair()   # a completion may have freed a slot
            return
        # router already torn down (or never started): emit inline,
        # best-effort -- there is no reader thread left to protect
        try:
            req.emit(msg)
        except Exception as e:  # noqa: BLE001 -- a dead client must not
            # leak out of the teardown path
            self._log.debug(f"router reply emit failed: {e!r}")
        self._pump_fair()

    def _emit_worker(self, q: queue.Queue) -> None:
        while True:
            item = q.get()
            if item is None:
                return
            req, msg = item
            try:
                req.emit(msg)
            except Exception as e:  # noqa: BLE001 -- one dead client
                # must never take the emission worker down
                self._log.debug(f"router reply emit failed: {e!r}")

    def _complete_locked(self, req: RoutedRequest) -> None:
        """Retire a request (caller emits OUTSIDE the lock)."""
        req.done = True
        self._requests.pop(req.rid, None)
        if req.assigned is not None:
            # .get: the owner may have left the fleet (remove_replica)
            # between assignment and this completion
            owner = self._by_name.get(req.assigned)
            if owner is not None \
                    and owner.inflight.pop(req.rid, None) is not None:
                owner.m_inflight.set(owner.depth())
        if req.tenant is not None and self._fair is not None:
            # free the tenant's admission slot (FairQueue has its own
            # lock and never calls back -- safe under the router lock);
            # the emit that follows this completion runs _pump_fair, so
            # the freed slot releases parked work promptly
            self._fair.complete(req.tenant)
        self._completed_total += 1

    # ----------------------------------------------------------- replica IO

    def _on_replica_msg(self, replica: _Replica, link: ReplicaLink,
                        msg: dict) -> None:
        mtype = msg.get("type")
        if mtype == protocol.TYPE_CLOSED:
            # unsolicited drain/idle notice: stop routing there, keep
            # waiting on in-flight replies (they land before the replica
            # closes the socket; a close without them is a link loss and
            # the failover sweep picks them up)
            with self._lock:
                replica.draining = True
            self._log.info(f"router: replica {replica.name} announced "
                           f"close ({msg.get('reason')})")
            return
        rid = msg.get("id")
        if isinstance(rid, str) and rid.startswith("hc"):
            self._on_probe_reply(replica, msg)
            return
        if isinstance(rid, str) and rid.startswith("fl"):
            # fleet-call reply (trace fan-out / metrics federation):
            # complete the waiter, never the request path
            with self._lock:
                waiter = self._fleet_waits.pop(rid, None)
            if waiter is not None:
                event, slot = waiter
                slot.append(msg)
                event.set()
            return
        resubmit = None
        with self._lock:
            req = self._requests.get(rid)
            if req is None or req.done:
                # reply/failover race resolved in the other reply's
                # favor (or a stale id): drop, exactly-once held
                self._dedup_total += 1
                _m_dedup.inc()
                return
            retryable = (mtype == protocol.TYPE_ERROR
                         and msg.get("code") in (protocol.ERR_OVERLOADED,
                                                 protocol.ERR_CLOSED))
            if retryable and msg.get("code") == protocol.ERR_CLOSED:
                replica.draining = True
            owns = replica.inflight.get(rid) is req
            if not owns and mtype == protocol.TYPE_ERROR:
                # a STALE error from a replica this request already
                # failed over from (probe-timeout sweep detached it):
                # the current owner will answer; completing or
                # re-routing on it would emit a spurious error for a
                # request another replica is serving, or clobber that
                # replica's ownership (the same still-parked rule
                # _dispatch's send-failure path applies).  A stale
                # RESULT, by contrast, is a valid answer and wins the
                # race below.
                self._dedup_total += 1
                _m_dedup.inc()
                return
            if owns and retryable and self._eligible_locked(req):
                # replica-side backpressure/drain: move the request to a
                # replica that can absorb it instead of surfacing an
                # error the rest of the fleet could have served
                del replica.inflight[rid]
                replica.m_inflight.set(replica.depth())
                req.assigned = None
                replica.failovers += 1
                replica.m_failover.inc()
                self._failover_total += 1
                resubmit = req
            else:
                self._complete_locked(req)
        if resubmit is not None:
            self._dispatch(resubmit)
        else:
            self._emit(req, msg)

    def _on_link_lost(self, replica: _Replica, link: ReplicaLink) -> None:
        with self._lock:
            if self._down:
                return
        self._fail_link(replica, link, "connection lost")

    def _sweep_inflight_locked(self,
                               replica: _Replica) -> list[RoutedRequest]:
        """Detach every not-yet-done in-flight request from `replica`,
        counting the failovers.  Caller holds the router lock and
        re-dispatches the returned requests AFTER releasing it (the one
        move-a-replica's-work transaction, shared by the link-failure
        and probe-timeout-bench paths)."""
        moved = [r for r in replica.inflight.values() if not r.done]
        replica.inflight.clear()
        replica.m_inflight.set(0)
        for req in moved:
            req.assigned = None
        if moved:
            replica.failovers += len(moved)
            replica.m_failover.inc(len(moved))
            # caller holds self._lock (the _locked-suffix contract)
            # ccs-analyze: ignore[CONC001]
            self._failover_total += len(moved)
        return moved

    def _fail_link(self, replica: _Replica, link: ReplicaLink,
                   why: str) -> None:
        """One dead link: detach it, strike the replica's health, and
        re-dispatch its unanswered requests elsewhere.  Idempotent per
        link object (send failures and the reader's EOF both land
        here)."""
        with self._lock:
            if link.failed:
                return
            link.failed = True
            if replica.link is link:
                replica.link = None
            moved = self._sweep_inflight_locked(replica)
            replica.probe_id = None
            replica.external_backlog = 0   # stale once the link is gone
            benched = self._health.record_failure(replica.name)
            if benched:
                replica.m_unhealthy.inc()
                self._sticky.forget_member(replica.name)
        self._log.warn(
            f"router: replica {replica.name} link down ({why}); "
            f"failing over {len(moved)} in-flight request(s)")
        link.close()
        for req in moved:
            self._dispatch(req)

    # --------------------------------------------------------------- health

    def _try_connect(self, replica: _Replica) -> bool:
        """One blocking connect attempt; False ONLY on a refused/failed
        connect (the signal the reconnect backoff doubles on) -- a stale
        attempt (already connected, shut down, or the member left the
        fleet) is not a failure."""
        try:
            sock = socket.create_connection(
                (replica.host, replica.port),
                timeout=self.config.connect_timeout_s)
        except OSError:
            return False  # stays down; the next due tick retries
        if self._link_ssl is not None:
            # TLS replica hop: handshake under the same connect bound; a
            # failed handshake (plaintext replica, cert the CA rejects)
            # is a failed connect -- backoff doubles, no traceback
            try:
                sock = self._link_ssl.wrap_socket(
                    sock, server_hostname=replica.host)
            except OSError:
                try:
                    sock.close()
                except OSError:
                    pass
                return False
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        link = ReplicaLink(self, replica, sock)
        with self._lock:
            if self._down or replica.link is not None \
                    or self._by_name.get(replica.name) is not replica:
                stale = True
            else:
                stale = False
                replica.link = link
                # a fresh connection says nothing about engine health; a
                # reconnect after drain must also clear the drain flag so
                # the next probe can re-admit a restarted replica (and a
                # restarted replica's backlog figure starts clean)
                replica.draining = False
                replica.probe_id = None
                replica.external_backlog = 0
        if stale:
            link.close()
            return True
        link.start()
        self._log.info(f"router: connected to replica {replica.name}")
        return True

    def _health_loop(self) -> None:
        while not self._stop.wait(self.config.health_interval_s):
            # snapshot under the lock: membership changes mid-tick
            # (fleet add/remove) must not race this iteration
            with self._lock:
                replicas = list(self._replicas)
            for replica in replicas:
                self._probe(replica)

    def _probe(self, replica: _Replica) -> None:
        now = time.monotonic()
        with self._lock:
            if self._down:
                return
            link = replica.link
            outstanding = replica.probe_id
            sent_t = replica.probe_t
        if link is None or not link.alive:
            # reconnect OFF the health thread: a blocking connect() to a
            # down replica (up to connect_timeout_s) would stretch the
            # probe cadence for every HEALTHY replica behind it
            with self._lock:
                if replica.connecting or self._down \
                        or self._by_name.get(replica.name) is not replica:
                    return  # busy, shutting down, or left the fleet
                if now < replica.reconnect_at:
                    # exponential backoff window still open: count the
                    # skipped attempt, don't hammer a dead port
                    replica.m_reconnect_backoff.inc()
                    return
                replica.connecting = True

            def attempt(replica=replica):
                ok = False
                try:
                    ok = self._try_connect(replica)
                finally:
                    with self._lock:
                        replica.connecting = False
                        if ok:
                            replica.reconnect_backoff_s = 0.0
                            replica.reconnect_at = 0.0
                        else:
                            base = self.config.reconnect_backoff_base_s
                            replica.reconnect_backoff_s = min(
                                self.config.reconnect_backoff_cap_s,
                                max(base, replica.reconnect_backoff_s * 2))
                            replica.reconnect_at = (
                                time.monotonic()
                                + replica.reconnect_backoff_s)

            threading.Thread(
                target=attempt, daemon=True,
                name=f"ccs-router-connect-{replica.name}").start()
            return
        if outstanding is not None:
            if now - sent_t < self.config.health_timeout_s:
                return  # still within the reply window
            # unanswered probe: one strike; benching moves the in-flight
            # requests but KEEPS the link open, so a late reply still
            # wins the exactly-once race instead of being torn down
            moved: list[RoutedRequest] = []
            with self._lock:
                replica.probe_id = None
                benched = self._health.record_failure(replica.name)
                if benched:
                    replica.m_unhealthy.inc()
                    self._sticky.forget_member(replica.name)
                    moved = self._sweep_inflight_locked(replica)
            replica.m_hc_fail.inc()
            if benched:
                self._log.warn(
                    f"router: replica {replica.name} unhealthy (probe "
                    f"timeout); failing over {len(moved)} request(s)")
            for req in moved:
                self._dispatch(req)
            return
        self._probe_seq += 1
        pid = f"hc{self._probe_seq}"
        with self._lock:
            replica.probe_id = pid
            replica.probe_t = now
        if not link.send({"verb": protocol.VERB_STATUS, "id": pid}):
            self._fail_link(replica, link, "health probe send failed")

    def _on_probe_reply(self, replica: _Replica, msg: dict) -> None:
        if msg.get("type") == protocol.TYPE_ERROR:
            # a replica ANSWERING probes with structured errors (e.g.
            # rejecting the router's link token as unauthorized) is not
            # healthy: strike it like a timeout, with the reason logged
            # -- a token misconfiguration must surface, not read as ok
            moved: list[RoutedRequest] = []
            with self._lock:
                if msg.get("id") != replica.probe_id:
                    return
                replica.probe_id = None
                benched = self._health.record_failure(replica.name)
                if benched:
                    replica.m_unhealthy.inc()
                    self._sticky.forget_member(replica.name)
                    moved = self._sweep_inflight_locked(replica)
            replica.m_hc_fail.inc()
            self._log.warn(
                f"router: replica {replica.name} rejected a health probe "
                f"({msg.get('code')}: {msg.get('message')})")
            for req in moved:
                self._dispatch(req)
            return
        # SLO burn signal: every probe reply's `slo` block (lifetime
        # requests/violations) feeds the shed policy's windowed meter
        self._burn.observe(replica.name, msg.get("slo"))
        self._m_burn.set(round(self._burn.rate(), 6))
        accepting = bool(msg.get("accepting", True))
        try:
            pending = max(0, int(msg.get("pending", 0)))
        except (TypeError, ValueError):
            pending = 0
        with self._lock:
            if msg.get("id") != replica.probe_id:
                # a STALE probe reply (its timeout already struck, or it
                # belongs to a previous link): crediting it would reset
                # the strike count of a replica that persistently
                # answers slower than health_timeout_s, and count toward
                # re-admission of a benched one -- only the outstanding
                # probe's reply is evidence of current health
                return
            replica.probe_id = None
            replica.draining = not accepting
            # admission weighting: the engine's pending figure minus
            # what WE have in flight there is load other clients (or an
            # engine backlog) put on it; fold it into routing depth
            replica.external_backlog = max(0, pending - replica.depth())
            recovered = self._health.record_success(replica.name)
        replica.m_hc_ok.inc()
        if recovered:
            self._log.info(f"router: replica {replica.name} recovered; "
                           "re-admitted to routing")

    # ------------------------------------------------------- fleet calls

    def _fleet_call(self, frame: dict, timeout_s: float = 5.0
                    ) -> dict[str, dict]:
        """Send one verb frame to every CONNECTED replica and collect
        the replies: {replica_name: reply}.  Replies use `fl<N>` ids so
        the link reader routes them to waiters, never the request path;
        a replica that cannot answer within the timeout (or whose link
        died) is simply absent from the result -- fleet introspection
        must degrade, not block behind a sick replica forever."""
        waiters: list[tuple[str, _Replica, threading.Event, list]] = []
        with self._lock:
            targets = [(r, r.link) for r in self._replicas
                       if r.link is not None and r.link.alive]
        for replica, link in targets:
            with self._lock:
                self._fleet_seq += 1
                fid = f"fl{self._fleet_seq}"
                event: threading.Event = threading.Event()
                slot: list = []
                self._fleet_waits[fid] = (event, slot)
            if not link.send(dict(frame, id=fid)):
                with self._lock:
                    self._fleet_waits.pop(fid, None)
                continue
            waiters.append((fid, replica, event, slot))
        out: dict[str, dict] = {}
        deadline = time.monotonic() + timeout_s
        for _fid, replica, event, slot in waiters:
            if event.wait(max(deadline - time.monotonic(), 0.0)) and slot:
                out[replica.name] = slot[0]
        # drop THIS call's straggler waiters so the map cannot grow
        # unbounded (concurrent fleet calls -- an HTTP scrape racing a
        # trace stop -- own their fids; never touch theirs)
        with self._lock:
            for fid, _replica, event, _slot in waiters:
                if not event.is_set():
                    self._fleet_waits.pop(fid, None)
        return out

    # ------------------------------------------------------ trace fan-out

    def trace_start(self) -> bool:
        """Install a router-side span capture AND fan a trace-start out
        to every connected replica (the protocol's `trace` verb at the
        router tier).  Returns False when a capture is already live."""
        with self._trace_lock:
            if self._capture is not None:
                return False
            cap = obs_trace.Tracer(tag="router")
            if not obs_trace.install_tracer(cap):
                return False
            self._capture = cap
        self._fleet_call({"verb": protocol.VERB_TRACE, "action": "start"},
                         timeout_s=5.0)
        return True

    def trace_stop(self, timeout_s: float = 10.0) -> dict | None:
        """Stop the capture: collect each replica's span dump (trace
        verb, action=stop), stop the router's own, and return
        {"trace": <router chrome>, "replicas": {name: chrome}} -- the
        inputs tools/trace_merge.py assembles into one fleet timeline.
        None when no capture was running."""
        with self._trace_lock:
            cap, self._capture = self._capture, None
            if cap is None:
                return None
            obs_trace.clear_tracer(cap)
        replies = self._fleet_call(
            {"verb": protocol.VERB_TRACE, "action": "stop"},
            timeout_s=timeout_s)
        replicas = {name: msg["trace"] for name, msg in replies.items()
                    if isinstance(msg.get("trace"), dict)}
        return {"trace": cap.to_chrome(), "replicas": replicas}

    # --------------------------------------------- fleet perf ledger

    def _ledger_loop(self, ledger) -> None:
        interval = max(self.config.perf_ledger_interval_s, 0.1)
        while not self._stop.wait(interval):
            try:
                self._append_fleet_records(ledger)
            except Exception as e:  # noqa: BLE001 -- observability must
                # degrade, never take the router down
                self._log.debug(f"fleet ledger tick failed: {e!r}")

    def _append_fleet_records(self, ledger, timeout_s: float = 5.0) -> None:
        """One fleet ledger tick: the router's own snapshot plus one
        replica_snapshot per reachable replica.  A replica that writes
        its own ledger contributes its newest record (the status verb's
        `perf` block); one that does not contributes a live-state record
        from its status reply.  Unreachable replicas are absent."""
        from pbccs_tpu.obs import ledger as obs_ledger

        with self._lock:
            window = self._ledger_window
            pending = len(self._requests)
            completed = self._completed_total
        if window is not None:
            ledger.append(obs_ledger.run_record(
                window, kind="router_snapshot", source="ccs-router",
                extra={
                    "uptime_s": round(time.monotonic() - self._start_t, 3),
                    "pending": pending,
                    "completed": completed,
                }))
        replies = self._fleet_call({"verb": protocol.VERB_STATUS},
                                   timeout_s=timeout_s)
        for name, msg in sorted(replies.items()):
            perf = msg.get(protocol.FIELD_PERF)
            last = (perf or {}).get(protocol.KEY_PERF_LAST) \
                if isinstance(perf, dict) else None
            if isinstance(last, dict):
                rec = {k: v for k, v in last.items()
                       if k in obs_ledger.LEDGER_FIELDS
                       and k not in ("schema_version", "t_unix")}
            else:
                rec = {}
            rec.update(kind="replica_snapshot", source="ccs-router",
                       replica=name)
            for wire_key, field in (("pending", "pending"),
                                    ("completed", "completed"),
                                    ("errors", "errors"),
                                    ("in_flight_zmws", "in_flight_zmws"),
                                    ("uptime_s", "uptime_s"),
                                    ("queue_depth", "queue_depth")):
                v = msg.get(wire_key)
                if isinstance(v, (int, float)):
                    rec[field] = v
            ledger.append(rec)
        if self._fair is not None:
            # one tenant_snapshot per tenant per tick: the per-tenant
            # ledger plane analyze/perf tooling reads
            for row in self._fair.rows():
                ledger.append({
                    "kind": "tenant_snapshot", "source": "ccs-router",
                    "tenant": row["name"],
                    "tenant_priority": row["priority"],
                    "tenant_inflight": row["inflight"],
                    "tenant_queued": row["queued"],
                    "tenant_completed": row["completed"],
                    "tenant_sheds": row["shed"],
                    "tenant_rejects": row["rejected"],
                })

    # ------------------------------------------- status / metrics (session)

    def accepting(self) -> bool:
        """Cheap liveness for /healthz: False once a drain began."""
        with self._lock:
            return self._accepting

    def status(self) -> dict:
        with self._lock:
            replicas = [{
                "replica": r.name,
                "connected": r.link is not None and r.link.alive,
                "healthy": self._health.healthy(r.name),
                "draining": r.draining,
                "inflight": r.depth(),
                "external_backlog": r.external_backlog,
                "routed": r.routed,
                "failovers": r.failovers,
            } for r in self._replicas]
            ledger = self._ledger
            supervisor = self._supervisor
            perf = {protocol.FIELD_PERF: ledger.perf_block()} \
                if ledger is not None else {}
            out = {
                "engine": "ccs-router",
                **perf,
                "accepting": self._accepting,
                "uptime_s": round(time.monotonic() - self._start_t, 3),
                "pending": len(self._requests),
                "routed": self._routed_total,
                "completed": self._completed_total,
                "failovers": self._failover_total,
                "deduped": self._dedup_total,
                "shed": self._shed_total,
                "replicas": replicas,
            }
        if supervisor is not None:
            # OUTSIDE the router lock: supervisor threads call
            # add_replica/remove_replica (which take the router lock)
            # while holding their own -- nesting the other way here
            # would be a lock-order inversion
            out[protocol.FIELD_SUPERVISOR] = supervisor.status_block()
        if self._fair is not None:
            # per-tenant accounting (FairQueue's own lock; outside the
            # router lock): `ccs top` renders this block verbatim
            burn = self._burn.rate()
            out[protocol.FIELD_TENANCY] = {
                protocol.KEY_TEN_TENANTS: self._fair.rows(),
                protocol.KEY_TEN_BURN: round(burn, 6),
                protocol.KEY_TEN_SHEDDING: bool(
                    self.config.shed_burn_threshold > 0
                    and burn >= self.config.shed_burn_threshold),
            }
        return out

    def metrics_text(self) -> str:
        """FEDERATED fleet exposition: the router's own registry plus
        every reachable replica's `metrics` verb body relabeled under
        `replica="host:port"`, merged into one valid exposition -- a
        single Prometheus target (the router's --metricsPort, or its
        NDJSON metrics verb) sees the whole fleet.  Unreachable replicas
        degrade to absence, never to a blocked scrape."""
        parts = [_reg.render_prometheus()]
        replies = self._fleet_call({"verb": protocol.VERB_METRICS},
                                   timeout_s=5.0)
        for name, msg in sorted(replies.items()):
            body = msg.get("body")
            if isinstance(body, str) and body:
                parts.append(relabel_exposition(body, replica=name))
        return merge_expositions(parts)


class _RouterSession(_FramedSession):
    """A framed session bound to the replica router: submits are
    validated at the edge, then fanned out; replica replies pass through
    verbatim with the id rewritten back to the client's."""

    def _on_submit(self, msg: dict) -> None:
        rid = msg.get("id")
        if not self._try_acquire_slot(rid):
            return
        parsed = self._parse_submit(msg)
        if parsed is None:
            self._release_slot()
            return
        chunk, deadline_ms, trace_ctx, tenant = parsed
        directory = self.server.tenants
        if directory is not None and tenant is not None \
                and directory.get(tenant) is None:
            # a trusted peer forwarded an identity the token file does
            # not know: refuse rather than route unaccounted work (the
            # fair queue has no state for it)
            self._release_slot()
            tenancy.count_auth_failure("unknown_tenant")
            self.send(protocol.error_to_wire(
                rid, protocol.ERR_UNAUTHORIZED,
                f"unknown tenant {tenant!r}"))
            return
        if trace_ctx is None and obs_trace.get_tracer() is not None:
            # edge-minted trace id: with a capture live, every request
            # gets a fleet-wide identity even when the client sent none
            trace_ctx = {protocol.KEY_TRACE_ID: obs_trace.new_trace_id(),
                         protocol.KEY_SPAN_ID: None}

        def on_reply(reply: dict) -> None:
            self._release_slot()
            out = dict(reply)
            out["id"] = rid
            self.send(out)

        try:
            # forward the NORMALIZED wire form (defaults filled, floats
            # coerced): both hops then carry the exact payload the
            # validation accepted
            self.server.engine.submit_routed(
                protocol.chunk_to_wire(chunk), route_key(chunk),
                deadline_ms, on_reply, trace=trace_ctx, tenant=tenant)
        except RouterClosed as e:
            self._release_slot()
            self.send(protocol.error_to_wire(rid, protocol.ERR_CLOSED,
                                             str(e)))

    def _on_trace(self, msg: dict) -> None:
        """Router-tier trace verb: start/stop fan out to the replica
        fleet; stop returns the router's own capture plus each
        replica's under `replicas` (tools/trace_merge.py merges them)."""
        rid = msg.get("id")
        action = msg.get("action")
        if action == "start":
            started = self.server.engine.trace_start()
            self.send({"type": protocol.TYPE_TRACE, "id": rid,
                       "state": "started" if started
                       else "already_running"})
        elif action == "stop":
            bundle = self.server.engine.trace_stop()
            reply = {"type": protocol.TYPE_TRACE, "id": rid,
                     "state": "stopped" if bundle is not None
                     else "not_running"}
            if bundle is not None:
                reply["trace"] = bundle["trace"]
                reply["replicas"] = bundle["replicas"]
            self.send(reply)
        else:
            self.send(protocol.error_to_wire(
                rid, protocol.ERR_BAD_REQUEST,
                'trace.action must be "start" or "stop"'))

    def _on_fleet(self, msg: dict) -> None:
        self.send(self._fleet_reply(msg))

    def _fleet_reply(self, msg: dict) -> dict:
        """Compute (never send) the reply to a fleet admin verb --
        membership surgery on the live router: list / add / remove run
        directly against the routing table; restart / readmit need the
        supervising control plane (`ccs fleet`) and are refused on an
        unsupervised router."""
        rid = msg.get("id")
        action = msg.get("action")
        router: CcsRouter = self.server.engine
        if action == "list":
            status = router.status()
            reply = {"type": protocol.TYPE_FLEET, "id": rid,
                     "action": action, "ok": True,
                     "replicas": status["replicas"]}
            if protocol.FIELD_SUPERVISOR in status:
                reply[protocol.FIELD_SUPERVISOR] = \
                    status[protocol.FIELD_SUPERVISOR]
            return reply
        if action == "add":
            spec = msg.get("replica")
            if not isinstance(spec, str):
                return protocol.error_to_wire(
                    rid, protocol.ERR_BAD_REQUEST,
                    "fleet.add needs a replica HOST:PORT string")
            try:
                name = router.add_replica(spec)
            except RouterClosed as e:
                return protocol.error_to_wire(
                    rid, protocol.ERR_CLOSED, str(e))
            except ValueError as e:
                return protocol.error_to_wire(
                    rid, protocol.ERR_BAD_REQUEST, str(e))
            return {"type": protocol.TYPE_FLEET, "id": rid,
                    "action": action, "ok": True, "replica": name}
        if action == "remove":
            spec = msg.get("replica")
            if not isinstance(spec, str):
                return protocol.error_to_wire(
                    rid, protocol.ERR_BAD_REQUEST,
                    "fleet.remove needs a replica HOST:PORT string")
            timeout_s = msg.get("timeout_s", 30.0)
            if not isinstance(timeout_s, (int, float)) \
                    or isinstance(timeout_s, bool):
                return protocol.error_to_wire(
                    rid, protocol.ERR_BAD_REQUEST,
                    "fleet.timeout_s must be a number")
            try:
                out = router.remove_replica(
                    spec, drain=bool(msg.get("drain", True)),
                    timeout_s=float(timeout_s))
            except ValueError as e:
                return protocol.error_to_wire(
                    rid, protocol.ERR_BAD_REQUEST, str(e))
            return {"type": protocol.TYPE_FLEET, "id": rid,
                    "action": action, "ok": True, **out}
        if action in ("restart", "readmit"):
            supervisor = router.get_supervisor()
            if supervisor is None:
                return protocol.error_to_wire(
                    rid, protocol.ERR_BAD_REQUEST,
                    f"fleet.{action} needs a fleet supervisor "
                    "(`ccs fleet`); this router is unsupervised")
            if action == "restart":
                started = supervisor.request_rolling_restart()
                return {"type": protocol.TYPE_FLEET, "id": rid,
                        "action": action, "ok": True,
                        "state": "started" if started
                        else "already_running"}
            slot = msg.get("slot")
            if not isinstance(slot, int) or isinstance(slot, bool):
                return protocol.error_to_wire(
                    rid, protocol.ERR_BAD_REQUEST,
                    "fleet.readmit needs an integer slot")
            try:
                supervisor.readmit(slot)
            except ValueError as e:
                return protocol.error_to_wire(
                    rid, protocol.ERR_BAD_REQUEST, str(e))
            return {"type": protocol.TYPE_FLEET, "id": rid,
                    "action": action, "ok": True, "slot": slot}
        return protocol.error_to_wire(
            rid, protocol.ERR_BAD_REQUEST,
            'fleet.action must be "list", "add", "remove", '
            '"restart" or "readmit"')


class RouterServer(CcsServer):
    """The router's TCP front: the serve accept loop + session armor
    over a CcsRouter instead of a local engine."""

    session_class = _RouterSession
    name = "ccs router"


# ------------------------------------------------------------------ ccs router

def build_router_parser() -> argparse.ArgumentParser:
    defaults = RouterConfig()
    p = argparse.ArgumentParser(
        prog="ccs router",
        description="Health-checked front door spreading CCS serve "
                    "sessions across N `ccs serve` replicas with sticky "
                    "bucket routing and zero-loss failover.")
    p.add_argument("--host", default="127.0.0.1",
                   help="Bind address. Default = %(default)s")
    p.add_argument("--port", type=int, default=7330,
                   help="Bind port (0 = ephemeral). Default = %(default)s")
    p.add_argument("--replica", action="append", required=True,
                   metavar="HOST:PORT",
                   help="One `ccs serve` backend (repeatable).")
    p.add_argument("--routerHealthInterval", type=float,
                   default=defaults.health_interval_s,
                   help="Seconds between status-verb health probes per "
                        "replica. Default = %(default)s")
    p.add_argument("--routerHealthTimeout", type=float,
                   default=defaults.health_timeout_s,
                   help="Probe unanswered this long = one strike. "
                        "Default = %(default)s")
    p.add_argument("--routerBenchAfter", type=int,
                   default=defaults.bench_after,
                   help="Consecutive strikes before a replica is marked "
                        "unhealthy (in-flight requests fail over). "
                        "Default = %(default)s")
    p.add_argument("--routerReadmitAfter", type=int,
                   default=defaults.readmit_after,
                   help="Consecutive good probes before an unhealthy "
                        "replica is re-admitted. Default = %(default)s")
    p.add_argument("--routerSpillDepth", type=int, default=None,
                   help="In-flight depth past which a sticky bucket "
                        "spills off its home replica. Default: the "
                        "applied --tuneProfile's router_spill_depth, "
                        f"else {defaults.spill_depth}")
    p.add_argument("--tuneProfile", default=None, metavar="PATH|auto",
                   help="ccs-tune host profile (runtime/tuning.py): "
                        "supplies a --routerSpillDepth default when the "
                        "explicit flag is absent.  'auto' scans the "
                        "profiles/ directory for a fingerprint match; "
                        "failures degrade to built-in defaults with a "
                        "logged note.  Default: PBCCS_TUNE_PROFILE, "
                        "else no profile.")
    # the same wire armor the replicas enforce, applied at the edge
    p.add_argument("--maxLineBytes", type=int,
                   default=defaults.max_line_bytes,
                   help="Longest accepted NDJSON frame. "
                        "Default = %(default)s")
    p.add_argument("--maxInflightPerSession", type=int,
                   default=defaults.max_inflight_per_session,
                   help="Per-session in-flight submit cap. "
                        "Default = %(default)s")
    p.add_argument("--idleTimeout", type=float,
                   default=defaults.idle_timeout_s,
                   help="Reap idle sessions after this many seconds; "
                        "0 disables. Default = %(default)s")
    p.add_argument("--drainTimeout", type=float, default=30.0,
                   help="On SIGTERM/SIGINT, wait this long for routed "
                        "in-flight requests before failing the rest. "
                        "Default = %(default)s")
    p.add_argument("--metricsPort", type=int, default=0,
                   help="Serve the FEDERATED fleet exposition (router + "
                        "every replica under a replica label) on a "
                        "stdlib-HTTP /metrics endpoint (-1 = ephemeral, "
                        "printed as CCS-METRICS-READY; 0 disables). "
                        "Default = %(default)s")
    p.add_argument("--perfLedger", default=None, metavar="PATH",
                   help="Append the FLEET-WIDE performance ledger to "
                        "PATH: per interval, the router's own snapshot "
                        "plus one replica_snapshot per reachable "
                        "replica (its own ledger record when it runs "
                        "--perfLedger, else its live status figures). "
                        "Default: off.")
    p.add_argument("--perfLedgerInterval", type=float,
                   default=defaults.perf_ledger_interval_s,
                   help="Seconds between fleet ledger ticks. "
                        "Default = %(default)s")
    # ---- multi-tenant edge (serve/tenancy.py) ----
    p.add_argument("--tlsCert", default=None, metavar="PEM",
                   help="TLS certificate chain for the front door AND "
                        "the metrics endpoint (with --tlsKey). "
                        "Default: plaintext.")
    p.add_argument("--tlsKey", default=None, metavar="PEM",
                   help="TLS private key (with --tlsCert).")
    p.add_argument("--authTokens", default=None, metavar="FILE",
                   help="JSON token->tenant map; turns on edge token "
                        "auth, per-tenant fair queuing, and SLO-burn "
                        "shedding. Default: open front door.")
    p.add_argument("--tlsCa", default=None, metavar="PEM",
                   help="CA bundle to verify REPLICA certificates; also "
                        "switches replica links to TLS. Default: "
                        "plaintext links.")
    p.add_argument("--tlsReplicas", action="store_true",
                   help="Wrap replica links in TLS without CA pinning "
                        "(encrypted, unauthenticated; prefer --tlsCa).")
    p.add_argument("--authToken", default=None, metavar="TOKEN",
                   help="Bearer token the router presents on every "
                        "replica-link frame (submits, health probes, "
                        "fleet calls) to token-guarded replicas.")
    p.add_argument("--shedBurnRate", type=float,
                   default=defaults.shed_burn_threshold,
                   help="Fleet SLO burn rate (violating fraction over "
                        "--shedWindow) past which priority >= 1 tenants "
                        "are shed with a retry hint; 0 disables. "
                        "Default = %(default)s")
    p.add_argument("--shedWindow", type=float,
                   default=defaults.shed_window_s,
                   help="Burn-rate sliding window, seconds. "
                        "Default = %(default)s")
    p.add_argument("--shedRetryMs", type=float,
                   default=defaults.retry_after_ms,
                   help="retry_after_ms hint on shed/quota rejections. "
                        "Default = %(default)s")
    p.add_argument("--tenantQueueDepth", type=int,
                   default=defaults.fair_queue_depth,
                   help="Parked submits per tenant before rejection. "
                        "Default = %(default)s")
    p.add_argument("--noFairQueue", action="store_true",
                   help="Disable weighted-fair admission even with "
                        "--authTokens (auth only; legacy direct "
                        "dispatch).")
    p.add_argument("--logLevel", default="INFO")
    return p


def run_router(argv: list[str] | None = None) -> int:
    """`ccs router` entry point (dispatched from pbccs_tpu.cli)."""
    args = build_router_parser().parse_args(argv)
    log = Logger.default(Logger(level=LogLevel.from_string(args.logLevel)))
    from pbccs_tpu.runtime import tuning

    tuning.configure(args.tuneProfile, logger=log)
    if args.routerSpillDepth is None:
        # explicit flag > applied host profile > RouterConfig default
        tuned = tuning.knob_int("router_spill_depth")
        args.routerSpillDepth = (tuned if tuned is not None
                                 else RouterConfig().spill_depth)
    from pbccs_tpu.serve.server import load_edge_config

    edge = load_edge_config(args, "ccs router")
    if edge is None:
        return 2
    ssl_ctx, tenants = edge
    link_ssl = (tenancy.client_ssl_context(args.tlsCa)
                if args.tlsCa or args.tlsReplicas else None)
    try:
        config = RouterConfig(
            health_interval_s=args.routerHealthInterval,
            health_timeout_s=args.routerHealthTimeout,
            bench_after=args.routerBenchAfter,
            readmit_after=args.routerReadmitAfter,
            spill_depth=args.routerSpillDepth,
            max_line_bytes=args.maxLineBytes,
            max_inflight_per_session=args.maxInflightPerSession,
            idle_timeout_s=args.idleTimeout,
            perf_ledger_path=args.perfLedger,
            perf_ledger_interval_s=args.perfLedgerInterval,
            fair_queue=not args.noFairQueue,
            fair_queue_depth=args.tenantQueueDepth,
            shed_burn_threshold=args.shedBurnRate,
            shed_window_s=args.shedWindow,
            retry_after_ms=args.shedRetryMs)
        router = CcsRouter(args.replica, config, logger=log,
                           tenants=tenants, link_ssl=link_ssl,
                           link_token=args.authToken)
    except ValueError as e:
        # a knob or replica spec the dataclass/router rejected: a clean
        # usage error, not a traceback (the message names the field)
        print(f"ccs router: {e}", file=sys.stderr)
        return 2
    with router:
        server = RouterServer(router, args.host, args.port, logger=log,
                              ssl_context=ssl_ctx, tenants=tenants)
        server.start()
        from pbccs_tpu.serve.server import start_metrics_endpoint

        metrics_http = start_metrics_endpoint(
            args.metricsPort, router.metrics_text, args.host, log,
            health=router.accepting, ssl_context=ssl_ctx)
        # machine-readable ready line for wrappers (mirrors CCS-SERVE-READY)
        print(f"CCS-ROUTER-READY {server.host} {server.port}", flush=True)

        stop = threading.Event()

        def _on_signal(signum, frame):
            print(f"CCS-ROUTER-DRAINING "
                  f"signal={signal.Signals(signum).name}", flush=True)
            stop.set()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, _on_signal)
            except ValueError:  # not the main thread (embedded router)
                pass
        try:
            stop.wait()
        except KeyboardInterrupt:
            pass
        log.info("ccs router draining: admission stopped, waiting for "
                 f"routed requests (deadline {args.drainTimeout}s)")
        server.stop_accepting()
        server.notify_draining()
        drained = router.close(drain=True, deadline_s=args.drainTimeout)
        server.shutdown()
        if metrics_http is not None:
            metrics_http.shutdown()
        log.info("ccs router drained cleanly" if drained
                 else "ccs router drain deadline hit; failed remainder")
    log.flush()
    return 0
