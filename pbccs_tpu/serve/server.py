"""TCP front end of the serving engine: NDJSON sessions over sockets.

One accept loop, one reader thread per client session.  Replies are
written by whichever thread completes them (engine polish workers via
the request callback, the session reader for status/ping/errors) under a
per-session write lock, so per-ZMW results STREAM back as they complete
-- out of order across requests, interleaved across the session's
in-flight submissions.

Failure containment: a malformed frame gets a structured `bad_request`
reply and the session lives on; an engine-side raise gets `internal` and
the server lives on; a client that disconnects mid-stream only kills its
own session (its in-flight requests complete engine-side and their
replies are dropped on the closed socket).
"""

from __future__ import annotations

import argparse
import socket
import sys
import threading

from pbccs_tpu.runtime.logging import Logger, LogLevel
from pbccs_tpu.serve import protocol
from pbccs_tpu.serve.engine import (
    CcsEngine,
    EngineClosed,
    EngineOverloaded,
    Request,
    ServeConfig,
)


class _Session:
    """One connected client: a reader loop + a locked writer."""

    def __init__(self, server: "CcsServer", conn: socket.socket, peer):
        self.server = server
        self.conn = conn
        self.peer = peer
        self.alive = True
        self._wlock = threading.Lock()

    def send(self, msg: dict) -> None:
        """Best-effort reply: a dead socket marks the session closed but
        never raises into the completer (engine callbacks must survive
        client disconnects)."""
        data = protocol.encode_msg(msg)
        try:
            with self._wlock:
                self.conn.sendall(data)
        except OSError:
            self.alive = False

    # ------------------------------------------------------------- verbs

    def _on_submit(self, msg: dict) -> None:
        rid = msg.get("id")
        try:
            chunk = protocol.chunk_from_wire(msg.get("zmw"))
        except protocol.ProtocolError as e:
            self.send(protocol.error_to_wire(
                rid, protocol.ERR_BAD_REQUEST, str(e)))
            return
        deadline_ms = msg.get("deadline_ms")
        if deadline_ms is not None and not isinstance(deadline_ms,
                                                      (int, float)):
            self.send(protocol.error_to_wire(
                rid, protocol.ERR_BAD_REQUEST, "deadline_ms must be a number"))
            return

        def on_done(req: Request) -> None:
            if req.error is not None:
                self.send(protocol.error_to_wire(
                    rid, protocol.ERR_INTERNAL, req.error))
            else:
                self.send(protocol.result_to_wire(
                    rid, req.chunk.id, req.failure, req.result,
                    req.latency_ms))

        try:
            self.server.engine.submit(chunk, deadline_ms=deadline_ms,
                                      callback=on_done)
        except EngineOverloaded as e:
            self.send(protocol.error_to_wire(
                rid, protocol.ERR_OVERLOADED, str(e)))
        except EngineClosed as e:
            self.send(protocol.error_to_wire(rid, protocol.ERR_CLOSED,
                                             str(e)))

    def _on_status(self, msg: dict) -> None:
        status = self.server.engine.status()
        status.update(type=protocol.TYPE_STATUS, id=msg.get("id"),
                      sessions=self.server.session_count(),
                      protocol_version=protocol.PROTOCOL_VERSION)
        self.send(status)

    def _on_metrics(self, msg: dict) -> None:
        self.send({"type": protocol.TYPE_METRICS, "id": msg.get("id"),
                   "content_type": protocol.METRICS_CONTENT_TYPE,
                   "body": self.server.engine.metrics_text()})

    def _on_trace(self, msg: dict) -> None:
        rid = msg.get("id")
        action = msg.get("action")
        if action == "start":
            started = self.server.engine.trace_start()
            self.send({"type": protocol.TYPE_TRACE, "id": rid,
                       "state": "started" if started
                       else "already_running"})
        elif action == "stop":
            chrome = self.server.engine.trace_stop()
            reply = {"type": protocol.TYPE_TRACE, "id": rid,
                     "state": "stopped" if chrome is not None
                     else "not_running"}
            if chrome is not None:
                reply["trace"] = chrome
            self.send(reply)
        else:
            self.send(protocol.error_to_wire(
                rid, protocol.ERR_BAD_REQUEST,
                'trace.action must be "start" or "stop"'))

    # ------------------------------------------------------------- reader

    def run(self) -> None:
        log = self.server.log
        log.debug(f"session open: {self.peer}")
        try:
            with self.conn.makefile("rb") as rf:
                for line in rf:
                    if not line.strip():
                        continue
                    try:
                        msg = protocol.decode_line(line)
                    except protocol.ProtocolError as e:
                        self.send(protocol.error_to_wire(
                            None, protocol.ERR_BAD_REQUEST, str(e)))
                        continue
                    verb = msg.get("verb")
                    if verb == protocol.VERB_SUBMIT:
                        self._on_submit(msg)
                    elif verb == protocol.VERB_STATUS:
                        self._on_status(msg)
                    elif verb == protocol.VERB_METRICS:
                        self._on_metrics(msg)
                    elif verb == protocol.VERB_TRACE:
                        self._on_trace(msg)
                    elif verb == protocol.VERB_PING:
                        self.send({"type": protocol.TYPE_PONG,
                                   "id": msg.get("id")})
                    else:
                        self.send(protocol.error_to_wire(
                            msg.get("id"), protocol.ERR_BAD_REQUEST,
                            f"unknown verb: {verb!r}"))
        except OSError:
            pass  # peer reset mid-read: same as EOF
        finally:
            self.alive = False
            try:
                self.conn.close()
            except OSError:
                pass
            self.server._forget(self)
            log.debug(f"session closed: {self.peer}")


class CcsServer:
    """Threaded NDJSON-over-TCP server fronting one CcsEngine."""

    def __init__(self, engine: CcsEngine, host: str = "127.0.0.1",
                 port: int = 0, logger: Logger | None = None):
        self.engine = engine
        self.log = logger or Logger.default()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        # closing a socket does not reliably wake a blocking accept() on
        # Linux; a short accept timeout lets the loop observe shutdown
        self._sock.settimeout(0.2)
        self.host, self.port = self._sock.getsockname()[:2]
        self._sessions: set[_Session] = set()
        self._slock = threading.Lock()
        self._accept_thread: threading.Thread | None = None
        self._shutdown = threading.Event()

    def session_count(self) -> int:
        with self._slock:
            return len(self._sessions)

    def _forget(self, session: _Session) -> None:
        with self._slock:
            self._sessions.discard(session)

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, peer = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listening socket closed
            conn.settimeout(None)  # sessions block; accept timeout is ours
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # keepalive reaps sessions whose peer vanished without FIN
            # (power loss, NAT timeout): without it the reader thread and
            # fd of every half-open session leak for the server's lifetime
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            session = _Session(self, conn, peer)
            with self._slock:
                self._sessions.add(session)
            threading.Thread(target=session.run, daemon=True,
                             name=f"ccs-serve-session-{peer}").start()

    def start(self) -> "CcsServer":
        """Start accepting in the background; returns immediately."""
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="ccs-serve-accept")
        self._accept_thread.start()
        self.log.info(f"ccs serve listening on {self.host}:{self.port}")
        return self

    def serve_forever(self) -> None:
        self.start()
        try:
            self._shutdown.wait()
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        if self._shutdown.is_set():
            return
        self._shutdown.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._slock:
            sessions = list(self._sessions)
        for s in sessions:
            try:
                s.conn.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)

    def __enter__(self) -> "CcsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()


# ------------------------------------------------------------------- ccs serve

def build_serve_parser() -> argparse.ArgumentParser:
    defaults = ServeConfig()  # one source of defaults (engine.ServeConfig)
    p = argparse.ArgumentParser(
        prog="ccs serve",
        description="Serve CCS consensus over a streaming NDJSON/TCP "
                    "protocol (long-lived engine, dynamic batching).")
    p.add_argument("--host", default="127.0.0.1",
                   help="Bind address. Default = %(default)s")
    p.add_argument("--port", type=int, default=7331,
                   help="Bind port (0 = ephemeral). Default = %(default)s")
    p.add_argument("--maxBatch", type=int, default=defaults.max_batch,
                   help="ZMWs per polish batch (bucket fill-flush size). "
                        "Default = %(default)s")
    p.add_argument("--maxWaitMs", type=float, default=defaults.max_wait_ms,
                   help="Max time a request waits to be batched before a "
                        "deadline flush. Default = %(default)s")
    p.add_argument("--maxPending", type=int, default=defaults.max_pending,
                   help="Admission bound: requests in the system before "
                        "submits are rejected as overloaded. "
                        "Default = %(default)s")
    p.add_argument("--prepWorkers", type=int, default=defaults.prep_workers,
                   help="Host draft/mapping threads. Default = %(default)s")
    p.add_argument("--deadlineMs", type=float,
                   default=defaults.default_deadline_ms,
                   help="Default per-request deadline. Default = %(default)s")
    # consensus + resilience knobs shared (definition and defaults) with
    # the offline CLI; serve maps --polishTimeout to the ENGINE-level
    # watchdog (ServeConfig.polish_timeout_ms) rather than the ambient
    # per-dispatch one, so a single timer governs each polish batch
    from pbccs_tpu.cli import add_consensus_args, add_resilience_args

    add_consensus_args(p)
    add_resilience_args(p)
    p.add_argument("--logLevel", default="INFO")
    return p


def run_serve(argv: list[str] | None = None) -> int:
    """`ccs serve` entry point (dispatched from pbccs_tpu.cli)."""
    args = build_serve_parser().parse_args(argv)

    from pbccs_tpu.resilience import faults

    if args.faults is not None:
        faults.configure(args.faults, seed=args.faultSeed)

    from pbccs_tpu.runtime.cache import enable_compilation_cache

    enable_compilation_cache()
    log = Logger.default(Logger(level=LogLevel.from_string(args.logLevel)))

    from pbccs_tpu.cli import consensus_settings_from_args

    settings = consensus_settings_from_args(args)
    config = ServeConfig(
        max_batch=args.maxBatch,
        max_wait_ms=args.maxWaitMs,
        max_pending=args.maxPending,
        prep_workers=args.prepWorkers,
        default_deadline_ms=args.deadlineMs,
        min_read_score=args.minReadScore,
        polish_timeout_ms=(args.polishTimeout or 0) * 1e3)

    with CcsEngine(settings, config, logger=log) as engine:
        server = CcsServer(engine, args.host, args.port, logger=log)
        # machine-readable ready line for wrappers (serve_bench polls it)
        print(f"CCS-SERVE-READY {server.host} {server.port}", flush=True)
        server.serve_forever()
    log.flush()
    return 0
