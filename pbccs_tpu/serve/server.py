"""TCP front end of the serving engine: NDJSON sessions over sockets.

One accept loop, one reader thread per client session.  Replies are
written by whichever thread completes them (engine polish workers via
the request callback, the session reader for status/ping/errors) under a
per-session write lock, so per-ZMW results STREAM back as they complete
-- out of order across requests, interleaved across the session's
in-flight submissions.

Failure containment: a malformed frame gets a structured `bad_request`
reply and the session lives on; an engine-side raise gets `internal` and
the server lives on; a client that disconnects mid-stream only kills its
own session (its in-flight requests complete engine-side and their
replies are dropped on the closed socket).

Wire-protocol armor (ServeConfig knobs): the session reader enforces a
max frame length (oversized -> `bad_request` + close), an idle read
timeout (slow-loris sessions with nothing in flight are reaped with a
`closed` notice), and a per-session in-flight cap (excess submits are
rejected `overloaded` without touching the engine).  Every abnormal
session end is counted under ccs_serve_session_aborts_total{cause} and
logged at debug with peer + direction, so a fleet saturating the armor
is visible before it is a problem.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import ssl
import sys
import threading

from pbccs_tpu.obs.metrics import default_registry
from pbccs_tpu.runtime.logging import Logger, LogLevel
from pbccs_tpu.serve import protocol, tenancy
from pbccs_tpu.serve.engine import (
    CcsEngine,
    EngineClosed,
    EngineOverloaded,
    Request,
    ServeConfig,
)

_reg = default_registry()
_m_cap_rejects = _reg.counter(
    "ccs_serve_inflight_cap_rejects_total",
    "Submits rejected by the per-session in-flight cap")


def _count_abort(cause: str) -> None:
    _reg.counter("ccs_serve_session_aborts_total",
                 "Sessions ended abnormally, by cause",
                 cause=cause).inc()


class _FramedSession:
    """One connected client: a reader loop + a locked writer.

    Owns everything front-door-generic -- the bounded NDJSON framing
    loop, the wire-protocol armor (max frame length, idle reap,
    per-session in-flight cap), abort accounting, and the status /
    metrics / ping verbs -- against any `engine`-shaped front
    (`server.engine` must expose .config with the armor fields,
    .status(), and .metrics_text()).  `_Session` binds it to a local
    CcsEngine; the replica router's session (serve/router.py) binds the
    SAME armor to its fan-out front door, so the hostile-input
    guarantees hold identically at both tiers (tools/fuzz_inputs.py
    points the same wire legs at each)."""

    _RECV = 1 << 16

    def __init__(self, server: "CcsServer", conn: socket.socket, peer):
        self.server = server
        self.conn = conn
        self.peer = peer
        self.alive = True
        self.closing = False      # server-initiated close (drain/shutdown)
        self._wlock = threading.Lock()
        # alive transitions get their OWN lock: _wlock is held across a
        # blocking sendall (frame atomicity), so taking it just to flip
        # the flag would let one wedged completer stall the reader's
        # teardown (and with --idleTimeout 0, stall it forever)
        self._slock = threading.Lock()
        self._ilock = threading.Lock()
        self._inflight = 0
        # resolved ONCE per session from the first authenticated frame's
        # bearer token (tenancy.TenantDirectory); None on an open front
        # door.  Written only by the reader thread (_authenticate).
        self.tenant: tenancy.Tenant | None = None

    def inflight(self) -> int:
        with self._ilock:
            return self._inflight

    def send(self, msg: dict) -> None:
        """Best-effort reply: a dead socket marks the session closed but
        never raises into the completer (engine callbacks must survive
        client disconnects)."""
        data = protocol.encode_msg(msg)
        try:
            with self._wlock:
                self.conn.sendall(data)
        except OSError as e:
            # `alive` is read/written by the reader thread and every
            # completer that replies here: transition it under the state
            # lock so exactly one path logs the death (ccs-analyze CONC001)
            with self._slock:
                was_alive, self.alive = self.alive, False
            if was_alive and not self.closing:
                self.server.log.debug(
                    f"session {self.peer}: send failed ({e!r}); "
                    "marking session dead")
                _count_abort("send_failed")

    # ------------------------------------------------------------- armor

    def _try_acquire_slot(self, rid) -> bool:
        """Reserve one in-flight slot for a submit; a capped session gets
        a structured `overloaded` reply BEFORE parsing/admission (one
        hostile session can neither monopolize the engine pool nor make
        it parse unbounded payloads it will reject anyway)."""
        cap = self.server.engine.config.max_inflight_per_session
        with self._ilock:
            if self._inflight >= cap:
                capped = True
            else:
                capped = False
                self._inflight += 1
        if capped:
            _m_cap_rejects.inc()
            self.send(protocol.error_to_wire(
                rid, protocol.ERR_OVERLOADED,
                f"per-session in-flight cap ({cap}) reached; "
                "wait for results before submitting more"))
            return False
        return True

    def _release_slot(self) -> None:
        with self._ilock:
            self._inflight -= 1

    # ------------------------------------------------------------- verbs

    def _on_submit(self, msg: dict) -> None:
        raise NotImplementedError   # front-door specific (_Session/router)

    def _on_trace(self, msg: dict) -> None:
        self.send(protocol.error_to_wire(
            msg.get("id"), protocol.ERR_BAD_REQUEST,
            "trace is not supported by this front door"))

    def _on_fleet(self, msg: dict) -> None:
        # fleet membership administration is a ROUTER verb; the local
        # serve front door rejects it structurally (the router session
        # subclass overrides this with the real implementation)
        self.send(protocol.error_to_wire(
            msg.get("id"), protocol.ERR_BAD_REQUEST,
            "fleet is not supported by this front door"))

    def _parse_submit(self, msg: dict):
        """Shared submit decode: validated (chunk, deadline, trace
        context, effective tenant name), or None after a structured
        `bad_request` reply (the caller already released its
        slot-acquire responsibilities via the returned sentinel).  The
        tenant is the AUTHENTICATED identity (tenancy.resolve_tenant):
        the wire `tenant` field only matters from a trusted token."""
        rid = msg.get("id")
        try:
            chunk = protocol.chunk_from_wire(msg.get("zmw"))
            trace_ctx = protocol.trace_from_wire(
                msg.get(protocol.FIELD_TRACE))
            wire_tenant = protocol.tenant_from_wire(
                msg.get(protocol.FIELD_TENANT))
        except protocol.ProtocolError as e:
            self.send(protocol.error_to_wire(
                rid, protocol.ERR_BAD_REQUEST, str(e)))
            return None
        deadline_ms = msg.get("deadline_ms")
        if deadline_ms is not None and not isinstance(deadline_ms,
                                                      (int, float)):
            self.send(protocol.error_to_wire(
                rid, protocol.ERR_BAD_REQUEST, "deadline_ms must be a number"))
            return None
        tenant = tenancy.resolve_tenant(self.tenant, wire_tenant)
        return chunk, deadline_ms, trace_ctx, tenant

    def _on_status(self, msg: dict) -> None:
        status = self.server.engine.status()
        status.update(type=protocol.TYPE_STATUS, id=msg.get("id"),
                      sessions=self.server.session_count(),
                      protocol_version=protocol.PROTOCOL_VERSION)
        self.send(status)

    def _on_metrics(self, msg: dict) -> None:
        self.send({"type": protocol.TYPE_METRICS, "id": msg.get("id"),
                   "content_type": protocol.METRICS_CONTENT_TYPE,
                   "body": self.server.engine.metrics_text()})

    # ------------------------------------------------------------- reader

    def _authenticate(self, msg: dict) -> bool:
        """Token auth gate, ahead of verb dispatch: on an authenticated
        front door (--authTokens) every frame must carry a known `auth`
        bearer token.  Failure answers a structured ERR_UNAUTHORIZED --
        the session survives, exactly like bad_request, but the frame is
        never parsed further (no verb, no payload).  The resolved tenant
        is cached on the session; per-frame tokens are still checked so
        an interleaved bad frame cannot ride an earlier good one."""
        directory = self.server.tenants
        if directory is None:
            return True
        token = msg.get(protocol.FIELD_AUTH)
        if token is None:
            reason = "missing_token"
        else:
            tenant = directory.authenticate(token)
            if tenant is not None:
                self.tenant = tenant
                return True
            reason = "bad_token"
        tenancy.count_auth_failure(reason)
        self.send(protocol.error_to_wire(
            msg.get("id"), protocol.ERR_UNAUTHORIZED,
            f"auth failed ({reason}): this front door requires a known "
            f"`{protocol.FIELD_AUTH}` bearer token on every frame"))
        return False

    def _dispatch(self, line: bytes) -> None:
        try:
            msg = protocol.decode_line(line)
        except protocol.ProtocolError as e:
            self.send(protocol.error_to_wire(
                None, protocol.ERR_BAD_REQUEST, str(e)))
            return
        if not self._authenticate(msg):
            return
        verb = msg.get("verb")
        if verb == protocol.VERB_SUBMIT:
            self._on_submit(msg)
        elif verb == protocol.VERB_STATUS:
            self._on_status(msg)
        elif verb == protocol.VERB_METRICS:
            self._on_metrics(msg)
        elif verb == protocol.VERB_TRACE:
            self._on_trace(msg)
        elif verb == protocol.VERB_FLEET:
            self._on_fleet(msg)
        elif verb == protocol.VERB_PING:
            self.send({"type": protocol.TYPE_PONG, "id": msg.get("id")})
        else:
            self.send(protocol.error_to_wire(
                msg.get("id"), protocol.ERR_BAD_REQUEST,
                f"unknown verb: {verb!r}"))

    def run(self) -> None:
        log = self.server.log
        cfg = self.server.engine.config
        log.debug(f"session open: {self.peer}")
        cause = None
        try:
            self.conn.settimeout(cfg.idle_timeout_s or None)
            buf = bytearray()
            while True:
                nl = buf.find(b"\n")
                # the current frame's length so far -- complete (up to
                # the newline) or still accumulating (whole buffer, the
                # only per-session allocation an untrusted peer controls)
                if (nl if nl >= 0 else len(buf)) > cfg.max_line_bytes:
                    self.send(protocol.error_to_wire(
                        None, protocol.ERR_BAD_REQUEST,
                        f"frame exceeds max_line_bytes="
                        f"{cfg.max_line_bytes}; closing session"))
                    cause = "oversized_frame"
                    return
                if nl < 0:
                    try:
                        data = self.conn.recv(self._RECV)
                    except socket.timeout:
                        if self.inflight() > 0:
                            continue  # quiet but waiting on results
                        self.send({"type": protocol.TYPE_CLOSED,
                                   "reason": "idle_timeout"})
                        cause = "idle_timeout"
                        return
                    except OSError as e:
                        if not self.closing:
                            log.debug(f"session {self.peer}: recv failed "
                                      f"({e!r}); treating as peer reset")
                            cause = "peer_reset"
                        return
                    if not data:
                        if buf.strip():
                            # peer sent half a frame then FIN
                            cause = "torn_frame"
                        return
                    buf += data
                    continue
                line = bytes(buf[:nl])
                del buf[: nl + 1]
                if line.strip():
                    self._dispatch(line)
        finally:
            with self._slock:
                self.alive = False
            if cause is not None:
                _count_abort(cause)
                log.debug(f"session {self.peer} aborted: {cause}")
            try:
                self.conn.close()
            except OSError:
                pass
            self.server._forget(self)
            log.debug(f"session closed: {self.peer}")


class _Session(_FramedSession):
    """A framed session bound to a LOCAL CcsEngine (the `ccs serve`
    front door): submits admit into the engine, trace drives the
    engine's span capture."""

    def _on_submit(self, msg: dict) -> None:
        rid = msg.get("id")
        if not self._try_acquire_slot(rid):
            return
        parsed = self._parse_submit(msg)
        if parsed is None:
            self._release_slot()
            return
        chunk, deadline_ms, trace_ctx, tenant = parsed
        if tenant is not None:
            # replica-side per-tenant accounting: the router forwards the
            # original submitter on the hop, so the federated exposition
            # shows each tenant's load per replica
            tenancy.count_request(tenant)

        def on_done(req: Request) -> None:
            self._release_slot()
            if req.error is not None:
                self.send(protocol.error_to_wire(
                    rid, protocol.ERR_INTERNAL, req.error))
            else:
                self.send(protocol.result_to_wire(
                    rid, req.chunk.id, req.failure, req.result,
                    req.latency_ms))

        try:
            self.server.engine.submit(chunk, deadline_ms=deadline_ms,
                                      callback=on_done,
                                      trace_ctx=trace_ctx)
        except EngineOverloaded as e:
            self._release_slot()
            self.send(protocol.error_to_wire(
                rid, protocol.ERR_OVERLOADED, str(e)))
        except EngineClosed as e:
            self._release_slot()
            self.send(protocol.error_to_wire(rid, protocol.ERR_CLOSED,
                                             str(e)))

    def _on_trace(self, msg: dict) -> None:
        rid = msg.get("id")
        action = msg.get("action")
        if action == "start":
            started = self.server.engine.trace_start()
            self.send({"type": protocol.TYPE_TRACE, "id": rid,
                       "state": "started" if started
                       else "already_running"})
        elif action == "stop":
            chrome = self.server.engine.trace_stop()
            reply = {"type": protocol.TYPE_TRACE, "id": rid,
                     "state": "stopped" if chrome is not None
                     else "not_running"}
            if chrome is not None:
                reply["trace"] = chrome
            self.send(reply)
        else:
            self.send(protocol.error_to_wire(
                rid, protocol.ERR_BAD_REQUEST,
                'trace.action must be "start" or "stop"'))


class CcsServer:
    """Threaded NDJSON-over-TCP server fronting one CcsEngine.

    Subclasses swap `session_class`/`name` to front a different
    engine-shaped object with the same accept loop + armor (the replica
    router's RouterServer does)."""

    session_class: type = _Session
    name = "ccs serve"

    # a stalled TLS handshake occupies ITS bring-up thread this long at
    # most; the accept loop is never behind it
    handshake_timeout_s = 10.0

    def __init__(self, engine: CcsEngine, host: str = "127.0.0.1",
                 port: int = 0, logger: Logger | None = None,
                 ssl_context: ssl.SSLContext | None = None,
                 tenants: "tenancy.TenantDirectory | None" = None):
        self.engine = engine
        self.log = logger or Logger.default()
        self.ssl_context = ssl_context
        self.tenants = tenants
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        # closing a socket does not reliably wake a blocking accept() on
        # Linux; a short accept timeout lets the loop observe shutdown
        self._sock.settimeout(0.2)
        self.host, self.port = self._sock.getsockname()[:2]
        self._sessions: set[_Session] = set()
        self._slock = threading.Lock()
        self._accept_thread: threading.Thread | None = None
        self._shutdown = threading.Event()

    def session_count(self) -> int:
        with self._slock:
            return len(self._sessions)

    def _forget(self, session: _Session) -> None:
        with self._slock:
            self._sessions.discard(session)

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, peer = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listening socket closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # keepalive reaps sessions whose peer vanished without FIN
            # (power loss, NAT timeout): without it the reader thread and
            # fd of every half-open session leak for the server's lifetime
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            # per-connection bring-up happens OFF this loop: with TLS on,
            # the handshake blocks, and one stalled/hostile handshake
            # must never stop the fleet accepting (slow-loris armor)
            threading.Thread(target=self._run_session, args=(conn, peer),
                             daemon=True,
                             name=f"ccs-serve-session-{peer}").start()

    def _run_session(self, conn: socket.socket, peer) -> None:
        """Bring one accepted connection up (TLS handshake when
        configured) and run its session.  A failed handshake is a
        counted structured abort (ccs_serve_session_aborts_total
        {cause="tls_handshake"}) -- a plaintext client probing a TLS'd
        port, a bad cert, or a stalled handshake never tracebacks and
        never reaches the framing layer."""
        if self.ssl_context is not None:
            conn.settimeout(self.handshake_timeout_s)
            try:
                conn = self.ssl_context.wrap_socket(conn, server_side=True)
            except (OSError, ssl.SSLError) as e:
                _count_abort("tls_handshake")
                self.log.debug(
                    f"session {peer}: TLS handshake failed ({e!r})")
                try:
                    conn.close()
                except OSError:
                    pass
                return
        conn.settimeout(None)  # sessions block; the reader sets idle reap
        session = self.session_class(self, conn, peer)
        with self._slock:
            if self._shutdown.is_set():
                try:
                    conn.close()
                except OSError:
                    pass
                return
            self._sessions.add(session)
        session.run()

    def start(self) -> "CcsServer":
        """Start accepting in the background; returns immediately."""
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="ccs-serve-accept")
        self._accept_thread.start()
        self.log.info(f"{self.name} listening on {self.host}:{self.port}")
        return self

    def serve_forever(self) -> None:
        self.start()
        try:
            self._shutdown.wait()
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def stop_accepting(self) -> None:
        """Close the listening socket: existing sessions live on, new
        connects fail (the graceful-drain first step)."""
        try:
            self._sock.close()
        except OSError:
            pass

    def notify_draining(self) -> None:
        """Graceful-drain second step: tell every idle session (nothing
        in flight) the server is going away via a `closed` notice and
        close it; sessions with in-flight requests stay open so their
        streamed results can land before shutdown()."""
        with self._slock:
            sessions = list(self._sessions)
        for s in sessions:
            if s.inflight() > 0:
                continue
            s.closing = True
            s.send({"type": protocol.TYPE_CLOSED, "reason": "draining"})
            try:
                # shutdown (not close): the reader thread still holds the
                # fd in recv, and only shutdown() FINs the peer + wakes
                # the reader while it does
                s.conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def shutdown(self) -> None:
        if self._shutdown.is_set():
            return
        self._shutdown.set()
        self.stop_accepting()
        with self._slock:
            sessions = list(self._sessions)
        for s in sessions:
            s.closing = True
            try:
                s.conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.conn.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)

    def __enter__(self) -> "CcsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()


# ------------------------------------------------------------------- ccs serve

def build_serve_parser() -> argparse.ArgumentParser:
    defaults = ServeConfig()  # one source of defaults (engine.ServeConfig)
    p = argparse.ArgumentParser(
        prog="ccs serve",
        description="Serve CCS consensus over a streaming NDJSON/TCP "
                    "protocol (long-lived engine, dynamic batching).")
    p.add_argument("--host", default="127.0.0.1",
                   help="Bind address. Default = %(default)s")
    p.add_argument("--port", type=int, default=7331,
                   help="Bind port (0 = ephemeral). Default = %(default)s")
    p.add_argument("--maxBatch", type=int, default=None,
                   help="ZMWs per polish batch (bucket fill-flush size). "
                        "Default: the applied --tuneProfile's "
                        "serve_max_batch, else "
                        f"{defaults.max_batch}")
    p.add_argument("--maxWaitMs", type=float, default=None,
                   help="Max time a request waits to be batched before a "
                        "deadline flush. Default: the applied "
                        "--tuneProfile's serve_max_wait_ms, else "
                        f"{defaults.max_wait_ms}")
    p.add_argument("--maxPending", type=int, default=defaults.max_pending,
                   help="Admission bound: requests in the system before "
                        "submits are rejected as overloaded. "
                        "Default = %(default)s")
    p.add_argument("--prepWorkers", type=int, default=defaults.prep_workers,
                   help="Host draft/mapping threads. Default = %(default)s")
    p.add_argument("--devices", type=int, default=defaults.devices,
                   help="Polish across a device fleet (pbccs_tpu.sched): "
                        "N>1 uses the first N visible devices, 0 all of "
                        "them, 1 the legacy single-device polish "
                        "executor. Default = %(default)s")
    p.add_argument("--schedPolicy",
                   choices=("sticky", "least", "roundrobin"),
                   default=defaults.sched_policy,
                   help="Device-fleet routing: sticky keeps a compiled-"
                        "shape bucket on the device that already compiled "
                        "it (least-loaded otherwise). "
                        "Default = %(default)s")
    p.add_argument("--deadlineMs", type=float,
                   default=defaults.default_deadline_ms,
                   help="Default per-request deadline. Default = %(default)s")
    # wire-protocol armor + drain (the input-hardening knobs; see
    # protocol.py "Protocol armor" and docs/DESIGN.md "Input hardening")
    p.add_argument("--maxLineBytes", type=int,
                   default=defaults.max_line_bytes,
                   help="Longest accepted NDJSON frame; oversized frames "
                        "get bad_request and the session closes. "
                        "Default = %(default)s")
    p.add_argument("--maxInflightPerSession", type=int,
                   default=defaults.max_inflight_per_session,
                   help="Submits one session may have in flight before "
                        "rejection as overloaded. Default = %(default)s")
    p.add_argument("--idleTimeout", type=float,
                   default=defaults.idle_timeout_s,
                   help="Reap sessions idle (no bytes, nothing in flight) "
                        "this many seconds; 0 disables. "
                        "Default = %(default)s")
    p.add_argument("--drainTimeout", type=float, default=30.0,
                   help="On SIGTERM/SIGINT, wait this long for in-flight "
                        "requests before fast-aborting the rest. "
                        "Default = %(default)s")
    # multi-tenant edge (serve/tenancy.py, docs/DESIGN.md "Multi-tenant
    # edge"): TLS on the front door + the metrics scrape, and a
    # token->tenant map that turns on per-frame bearer-token auth
    p.add_argument("--tlsCert", default=None, metavar="PEM",
                   help="Serve the NDJSON front door (and --metricsPort) "
                        "over TLS with this certificate chain; requires "
                        "--tlsKey.  Default: plaintext.")
    p.add_argument("--tlsKey", default=None, metavar="PEM",
                   help="Private key for --tlsCert.")
    p.add_argument("--authTokens", default=None, metavar="FILE",
                   help="JSON token->tenant map (tenancy.TenantDirectory): "
                        "when set, every frame must carry a known `auth` "
                        "bearer token or gets a structured `unauthorized`. "
                        "Default: open front door.")
    # observability plane (obs/): the HTTP scrape surface + SLO target
    p.add_argument("--metricsPort", type=int, default=0,
                   help="Serve a stdlib-HTTP Prometheus /metrics scrape "
                        "endpoint on this port (-1 = ephemeral, printed "
                        "as CCS-METRICS-READY; 0 disables). "
                        "Default = %(default)s")
    p.add_argument("--sloP99Ms", type=float, default=defaults.slo_p99_ms,
                   help="Per-request latency objective in ms: slower "
                        "requests count into ccs_slo_violations_total "
                        "and the status verb's slo block (0 disables). "
                        "Default = %(default)s")
    p.add_argument("--perfLedger", default=None, metavar="PATH",
                   help="Append schema-versioned NDJSON performance "
                        "records (obs/ledger.py) to PATH: one snapshot "
                        "per --perfLedgerInterval plus a final record "
                        "at drain; the status verb grows a `perf` "
                        "block the router federates.  Default: off.")
    p.add_argument("--perfLedgerInterval", type=float,
                   default=defaults.perf_ledger_interval_s,
                   help="Seconds between perf-ledger snapshots. "
                        "Default = %(default)s")
    p.add_argument("--compileCache", default=None, metavar="DIR",
                   help="Persistent XLA compilation-cache directory "
                        "shared across replicas/restarts: a rolling "
                        "restart reloads its compiled polish programs "
                        "from disk in seconds instead of recompiling "
                        "(default: JAX_COMPILATION_CACHE_DIR, else the "
                        "checkout-local .jax_cache).")
    p.add_argument("--tuneProfile", default=None, metavar="PATH|auto",
                   help="ccs-tune host profile (runtime/tuning.py): "
                        "supplies defaults for --maxBatch/--maxWaitMs "
                        "plus the batch knobs (band width, dense "
                        "blocking) when the explicit flag/env is absent. "
                        "'auto' scans the profiles/ directory for a "
                        "fingerprint match; a missing/corrupt/mismatched "
                        "profile degrades to built-in defaults with a "
                        "logged note.  Default: PBCCS_TUNE_PROFILE, "
                        "else no profile.")
    # consensus + resilience knobs shared (definition and defaults) with
    # the offline CLI; serve maps --polishTimeout to the ENGINE-level
    # watchdog (ServeConfig.polish_timeout_ms) rather than the ambient
    # per-dispatch one, so a single timer governs each polish batch
    from pbccs_tpu.cli import add_consensus_args, add_resilience_args

    add_consensus_args(p)
    add_resilience_args(p)
    p.add_argument("--logLevel", default="INFO")
    return p


def run_serve(argv: list[str] | None = None) -> int:
    """`ccs serve` entry point (dispatched from pbccs_tpu.cli)."""
    args = build_serve_parser().parse_args(argv)
    if args.devices < 0:
        print(f"option --devices: must be >= 0, got {args.devices}",
              file=sys.stderr)
        return 2
    edge = load_edge_config(args, "ccs serve")
    if edge is None:
        return 2
    ssl_ctx, tenants = edge

    from pbccs_tpu.resilience import faults

    if args.faults is not None:
        faults.configure(args.faults, seed=args.faultSeed)
    # fault site: fires before the engine exists, so an armed
    # `serve.start:crashloop` spec kills the replica instantly (the
    # supervisor's quarantine path is chaos-testable without a broken
    # build).  Keys on the fleet slot the supervisor exports, so a
    # `~N` modifier targets one slot of a homogeneous fleet.
    faults.maybe_fail("serve.start",
                      keys=(os.environ.get("PBCCS_FLEET_SLOT", ""),))

    from pbccs_tpu.runtime.cache import enable_compilation_cache

    enable_compilation_cache(args.compileCache)
    log = Logger.default(Logger(level=LogLevel.from_string(args.logLevel)))

    from pbccs_tpu.runtime import tuning

    tuning.configure(args.tuneProfile, logger=log)
    serve_defaults = ServeConfig()
    # resolution ladder (docs/DESIGN.md "Auto-tuning"): explicit flag >
    # applied host profile > ServeConfig default
    max_batch = (args.maxBatch
                 if args.maxBatch is not None
                 else tuning.knob_int("serve_max_batch")
                 or serve_defaults.max_batch)
    max_wait_ms = (args.maxWaitMs
                   if args.maxWaitMs is not None
                   else tuning.knob_float("serve_max_wait_ms")
                   or serve_defaults.max_wait_ms)

    from pbccs_tpu.cli import consensus_settings_from_args

    settings = consensus_settings_from_args(args)
    config = ServeConfig(
        max_batch=max_batch,
        max_wait_ms=max_wait_ms,
        max_pending=args.maxPending,
        prep_workers=args.prepWorkers,
        devices=args.devices,
        sched_policy=args.schedPolicy,
        default_deadline_ms=args.deadlineMs,
        min_read_score=args.minReadScore,
        polish_timeout_ms=(args.polishTimeout or 0) * 1e3,
        max_line_bytes=args.maxLineBytes,
        max_inflight_per_session=args.maxInflightPerSession,
        idle_timeout_s=args.idleTimeout,
        slo_p99_ms=args.sloP99Ms,
        perf_ledger_path=args.perfLedger,
        perf_ledger_interval_s=args.perfLedgerInterval)

    with CcsEngine(settings, config, logger=log) as engine:
        server = CcsServer(engine, args.host, args.port, logger=log,
                           ssl_context=ssl_ctx, tenants=tenants)
        server.start()
        metrics_http = start_metrics_endpoint(
            args.metricsPort, engine.metrics_text, args.host, log,
            health=engine.accepting, ssl_context=ssl_ctx)
        # machine-readable ready line for wrappers (serve_bench polls it)
        print(f"CCS-SERVE-READY {server.host} {server.port}", flush=True)

        # graceful drain: a k8s-style TERM (or ^C) stops admission,
        # finishes what is in flight (bounded by --drainTimeout, falling
        # back to fast abort), and exits 0 -- never a mid-batch kill
        stop = threading.Event()

        def _on_signal(signum, frame):
            # machine-readable line for wrappers (mirrors CCS-SERVE-READY)
            print(f"CCS-SERVE-DRAINING "
                  f"signal={signal.Signals(signum).name}", flush=True)
            stop.set()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, _on_signal)
            except ValueError:  # not the main thread (embedded serve)
                pass
        try:
            stop.wait()
        except KeyboardInterrupt:
            pass
        log.info("ccs serve draining: admission stopped, waiting for "
                 f"in-flight requests (deadline {args.drainTimeout}s)")
        server.stop_accepting()
        server.notify_draining()
        drained = engine.close(drain=True, deadline_s=args.drainTimeout)
        server.shutdown()
        if metrics_http is not None:
            metrics_http.shutdown()
        log.info("ccs serve drained cleanly" if drained
                 else "ccs serve drain deadline hit; aborted remainder")
    log.flush()
    return 0


def load_edge_config(args, prog: str):
    """Shared `--tlsCert/--tlsKey/--authTokens` resolution for `ccs
    serve` / `ccs router` / `ccs fleet`: returns (ssl_context | None,
    TenantDirectory | None), or None after printing a structured usage
    error (the caller exits 2).  Bad PEMs and malformed token files are
    startup errors, never a half-secured listener."""
    if bool(args.tlsCert) != bool(args.tlsKey):
        print(f"{prog}: --tlsCert and --tlsKey must be given together",
              file=sys.stderr)
        return None
    ssl_ctx = None
    if args.tlsCert:
        try:
            ssl_ctx = tenancy.server_ssl_context(args.tlsCert, args.tlsKey)
        except (OSError, ssl.SSLError) as e:
            print(f"{prog}: cannot load TLS cert/key: {e}", file=sys.stderr)
            return None
    tenants = None
    if args.authTokens:
        try:
            # online-reloadable (SIGHUP or mtime change): an edited
            # token map takes effect on the next frame without a
            # rolling restart.  The FIRST load still fails loud.
            tenants = tenancy.ReloadableTenantDirectory(args.authTokens)
        except (OSError, ValueError) as e:
            print(f"{prog}: --authTokens: {e}", file=sys.stderr)
            return None
        tenants.install_sighup()
    return ssl_ctx, tenants


def start_metrics_endpoint(port: int, render, host: str, log,
                           health=None, ssl_context=None):
    """Shared `--metricsPort` wiring for `ccs serve` and `ccs router`:
    0 disables, -1 binds an ephemeral port; the bound port is printed as
    a machine-readable CCS-METRICS-READY line (wrappers/smokes poll it,
    mirroring CCS-SERVE-READY).  `health` backs /healthz (engine/router
    `accepting`), so a draining process probes 503 before its socket
    ever closes.  `ssl_context` (the front door's --tlsCert context)
    makes the scrape endpoint HTTPS -- a TLS'd fleet has NO plaintext
    surface, including metrics."""
    if port == 0:
        return None
    from pbccs_tpu.obs.httpexp import start_metrics_http

    server = start_metrics_http(render, host=host,
                                port=0 if port < 0 else port,
                                health=health, ssl_context=ssl_context)
    print(f"CCS-METRICS-READY {host} {server.server_port}", flush=True)
    scheme = "https" if ssl_context is not None else "http"
    log.info(f"metrics scrape endpoint on "
             f"{scheme}://{host}:{server.server_port}/metrics")
    return server
