"""Online CCS serving: long-lived engine, dynamic batching, NDJSON/TCP.

The production-scale counterpart of the batch CLI (see docs/DESIGN.md
"Serving"): `engine.CcsEngine` owns the device and batches concurrent
requests; `server.CcsServer`/`client.CcsClient` speak the streaming
protocol (`protocol`); `batcher.DynamicBatcher` is the socket-free
scheduling core.  `ccs serve` (cli.py) is the process entry point;
`router.CcsRouter`/`ccs router` is the multi-replica front door
(health-checked failover across N serve processes, docs/DESIGN.md
"Fleet serving").
"""

from pbccs_tpu.serve.batcher import Batch, DynamicBatcher, PendingItem
from pbccs_tpu.serve.engine import (
    CcsEngine,
    EngineClosed,
    EngineOverloaded,
    Request,
    ServeConfig,
)

__all__ = [
    "Batch",
    "CcsEngine",
    "DynamicBatcher",
    "EngineClosed",
    "EngineOverloaded",
    "PendingItem",
    "Request",
    "ServeConfig",
]
