"""Fleet autopilot: self-healing replica supervision for `ccs serve`.

`ccs fleet` is the control plane the router deliberately is not: it
SPAWNS the replicas (router + N `ccs serve` child processes), watches
them through the same federated status/metrics plane every other tool
uses, and closes two loops the router alone cannot:

  self-healing   a crashed replica is removed from the routing table
                 (its ephemeral port is gone forever), respawned with
                 exponential backoff, and re-added under its NEW port
                 via the router's dynamic-membership API.  K rapid
                 deaths inside a sliding window quarantine the slot --
                 the same strike/bench shape sched/health.py applies to
                 devices, lifted to process granularity -- with a
                 structured reason; a quarantined slot rejoins only on
                 an explicit `ccs fleet readmit`.
  elasticity     sustained router queue depth spawns an extra replica
                 (warm-started through the shared --compileCache);
                 sustained idleness retires the youngest one by a
                 PROVEN drain: sticky homes migrate, in-flight work
                 completes or fails over, then SIGTERM -> SIGKILL past
                 the drain deadline.

`ccs fleet restart` is the zero-loss rolling deploy built from the same
primitives: one slot at a time, drain -> SIGTERM -> respawn warm ->
health-gate -> next.

Every decision (respawn, quarantine, readmit, scale_up, scale_down,
add, remove, drain_kill, rolling_restart_*) is appended to the perf
ledger as a schema-declared `fleet_event` record (meta class: the perf
gate never selects them) and kept in a bounded in-memory tail that
rides the router's status verb under `supervisor` -- which is how
`ccs top` tells a *restarting* replica from a *dead* one.

The child-process interface is injectable (``spawn_fn``), so the whole
state machine -- backoff schedule, quarantine, drain escalation,
rolling deploys -- is unit-testable with fake children and a fake
clock (tests/test_supervisor.py); tools/autopilot_smoke.py exercises
the real thing with kill -9 and injected crash loops.
"""

from __future__ import annotations

import argparse
import collections
import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Callable

from pbccs_tpu.obs.ledger import PerfLedger
from pbccs_tpu.runtime.logging import Logger, LogLevel
from pbccs_tpu.serve import protocol
from pbccs_tpu.serve.router import (CcsRouter, RouterConfig, RouterServer,
                                    parse_replica_spec)

# slot lifecycle states; `ccs top` renders these for roster-absent rows
SLOT_STARTING = "starting"      # spawn in progress / scheduled now
SLOT_UP = "up"                  # child alive and a router member
SLOT_DRAINING = "draining"      # planned retirement: drain then stop
SLOT_RESTARTING = "restarting"  # died (or rolling); respawn scheduled
SLOT_DEAD = "dead"              # crash-loop quarantined; manual readmit
SLOT_STOPPED = "stopped"        # retired on purpose (scale-down/shutdown)

# fleet_event vocabulary (each becomes one perf-ledger meta record)
EV_ADD = "add"
EV_REMOVE = "remove"
EV_RESPAWN = "respawn"
EV_QUARANTINE = "quarantine"
EV_READMIT = "readmit"
EV_SCALE_UP = "scale_up"
EV_SCALE_DOWN = "scale_down"
EV_DRAIN_KILL = "drain_kill"
EV_ROLLING_BEGIN = "rolling_restart_begin"
EV_ROLLING_STEP = "rolling_restart_step"
EV_ROLLING_DONE = "rolling_restart_done"


class SpawnError(RuntimeError):
    """A child failed to reach CCS-SERVE-READY (died, hung past the
    ready deadline, or could not exec)."""

    def __init__(self, msg: str, exit_code: int | None = None):
        super().__init__(msg)
        self.exit_code = exit_code


@dataclasses.dataclass
class SupervisorConfig:
    """Autopilot policy knobs (see `ccs fleet --help` for the flags)."""

    replicas: int = 2                  # initial fleet size
    min_replicas: int | None = None    # scale-down floor (None = replicas)
    max_replicas: int | None = None    # scale-up ceiling (None = replicas)
    backoff_base_s: float = 0.5        # first respawn delay
    backoff_factor: float = 2.0        # growth per consecutive death
    backoff_cap_s: float = 30.0        # respawn delay ceiling
    crashloop_window_s: float = 30.0   # sliding death window
    crashloop_threshold: int = 3       # deaths in window => quarantine
    drain_timeout_s: float = 30.0      # drain budget before SIGKILL
    health_gate_timeout_s: float = 60.0  # rolling: healthy-again budget
    ready_timeout_s: float = 300.0     # spawn-to-READY budget
    scale_up_pending: int = 0          # queue depth that burns (0 = off)
    scale_up_sustain_s: float = 2.0    # burn must last this long
    scale_down_idle_s: float = 10.0    # zero-pending span before retire
    poll_interval_s: float = 0.2       # supervision tick
    event_history: int = 64            # status-verb event tail length

    def __post_init__(self) -> None:
        if self.replicas < 1:
            raise ValueError("SupervisorConfig.replicas must be >= 1")
        if self.min_replicas is None:
            self.min_replicas = self.replicas
        if self.max_replicas is None:
            self.max_replicas = max(self.replicas, self.min_replicas)
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                "need 1 <= min_replicas <= max_replicas "
                f"(got {self.min_replicas}..{self.max_replicas})")
        if self.backoff_base_s <= 0 or self.backoff_factor < 1.0:
            raise ValueError("backoff_base_s must be > 0 and "
                             "backoff_factor >= 1.0")
        if self.backoff_cap_s < self.backoff_base_s:
            raise ValueError("backoff_cap_s must be >= backoff_base_s")
        if self.crashloop_threshold < 1:
            raise ValueError("crashloop_threshold must be >= 1")


def backoff_schedule(config: SupervisorConfig, attempt: int) -> float:
    """Respawn delay before the `attempt`-th consecutive respawn
    (1-based): base * factor**(attempt-1), capped.  Pure + deterministic
    -- the chaos tests assert the exact schedule."""
    if attempt <= 0:
        return 0.0
    return min(config.backoff_cap_s,
               config.backoff_base_s
               * config.backoff_factor ** (attempt - 1))


class _Slot:
    """One supervised replica slot (supervisor lock guards all fields)."""

    def __init__(self, slot: int):
        self.slot = slot
        self.state = SLOT_STARTING
        self.child = None               # spawn_fn handle; None when down
        self.replica: str | None = None  # router membership name
        self.incarnation = 0            # next PBCCS_FLEET_INCARNATION
        self.deaths: collections.deque[float] = collections.deque()
        self.attempt = 0                # consecutive respawns so far
        self.backoff_s = 0.0            # current scheduled delay
        self.respawn_at = 0.0           # clock() time of next spawn
        self.reason = ""                # structured quarantine/retire why
        self.spawning = False           # spawn worker in flight
        self.managed = False            # rolling/retire worker owns it


class FleetSupervisor:
    """The autopilot state machine over a CcsRouter and its children.

    ``spawn_fn(slot, incarnation) -> handle`` must block until the child
    is serving and return a handle with ``host``/``port``/``pid``,
    ``poll()`` (exit code or None), ``send_signal(sig)``, ``kill()`` and
    ``wait(timeout)`` (raising subprocess.TimeoutExpired/TimeoutError),
    or raise SpawnError.  ``clock`` is injectable for deterministic
    backoff tests."""

    def __init__(self, router: CcsRouter, config: SupervisorConfig,
                 spawn_fn: Callable[[int, int], object],
                 clock: Callable[[], float] = time.monotonic,
                 ledger: PerfLedger | None = None,
                 logger: Logger | None = None):
        self.router = router
        self.config = config
        self.spawn_fn = spawn_fn
        self.clock = clock
        self._ledger = ledger
        self._log = logger or Logger.default()
        self._lock = threading.Lock()
        self._slots: dict[int, _Slot] = {}
        self._events: collections.deque[dict] = collections.deque(
            maxlen=config.event_history)
        self._rolling: dict | None = None
        self._burn_since: float | None = None
        self._idle_since: float | None = None
        self._stop = threading.Event()
        self._loop_thread: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "FleetSupervisor":
        with self._lock:
            for i in range(self.config.replicas):
                self._slots[i] = _Slot(i)
        self._loop_thread = threading.Thread(
            target=self._loop, daemon=True, name="ccs-fleet-supervisor")
        self._loop_thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop supervising and shut every child down (drain = SIGTERM
        first, SIGKILL past the drain budget; else straight SIGKILL)."""
        self._stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10.0)
        with self._lock:
            children = [(s, s.child) for s in self._slots.values()
                        if s.child is not None]
            for s, _ in children:
                s.state = SLOT_STOPPED
        for s, child in children:
            self._shutdown_child(s, child,
                                 self.config.drain_timeout_s
                                 if drain else 0.0)
        with self._lock:
            for s, _ in children:
                s.child = None
                s.replica = None

    # ----------------------------------------------------------- main loop

    def _loop(self) -> None:
        while not self._stop.wait(self.config.poll_interval_s):
            try:
                self._tick_slots()
                self._tick_autoscale()
            except Exception as e:  # supervision must outlive surprises
                self._log.warn(f"fleet: supervision tick failed: {e!r}")

    def _tick_slots(self) -> None:
        now = self.clock()
        with self._lock:
            slots = list(self._slots.values())
        for s in slots:
            with self._lock:
                if s.managed or s.spawning:
                    continue
                # a quiet stretch resets the consecutive-death streak:
                # backoff growth punishes crash LOOPS, not a monthly blip
                while s.deaths and now - s.deaths[0] \
                        > self.config.crashloop_window_s:
                    s.deaths.popleft()
                if s.state == SLOT_UP and not s.deaths:
                    s.attempt = 0
                    s.backoff_s = 0.0
                child = s.child
                spawn_due = (child is None
                             and s.state in (SLOT_STARTING,
                                             SLOT_RESTARTING)
                             and now >= s.respawn_at)
            if child is not None and child.poll() is not None:
                self._record_death(s, f"exit {child.poll()}")
                continue
            if spawn_due:
                self._launch_spawn(s)

    # ------------------------------------------------------- spawn/respawn

    def _launch_spawn(self, s: _Slot) -> None:
        with self._lock:
            if s.spawning or s.child is not None:
                return
            s.spawning = True
            s.state = SLOT_STARTING
        threading.Thread(target=self._spawn_worker, args=(s,),
                         daemon=True,
                         name=f"ccs-fleet-spawn-{s.slot}").start()

    def _spawn_worker(self, s: _Slot) -> None:
        with self._lock:
            incarnation = s.incarnation
            s.incarnation += 1
        try:
            child = self.spawn_fn(s.slot, incarnation)
        except SpawnError as e:
            with self._lock:
                s.spawning = False
            self._record_death(s, str(e))
            return
        try:
            name = self.router.add_replica((child.host, child.port))
        except ValueError as e:
            # membership refused (dup name / shutdown): not a crash loop
            self._log.warn(f"fleet: slot {s.slot} join refused: {e}")
            child.kill()
            with self._lock:
                s.spawning = False
            self._record_death(s, f"join refused: {e}")
            return
        with self._lock:
            s.child = child
            s.replica = name
            s.state = SLOT_UP
            s.reason = ""
            s.spawning = False
            self._event(EV_ADD, slot=s.slot, reason=name,
                        attempt=s.attempt)
        self._log.info(f"fleet: slot {s.slot} up as {name} "
                       f"(incarnation {incarnation})")

    def _record_death(self, s: _Slot, why: str) -> None:
        """A child died (or never reached ready): sweep it out of the
        router, then either quarantine the slot or schedule a backed-off
        respawn.  Never called with the supervisor lock held."""
        now = self.clock()
        with self._lock:
            if s.child is not None:
                try:
                    s.child.kill()  # reap a half-dead handle for certain
                except Exception:  # noqa: BLE001 -- already-dead is fine
                    pass
            s.child = None
            name, s.replica = s.replica, None
            s.deaths.append(now)
            while s.deaths and now - s.deaths[0] \
                    > self.config.crashloop_window_s:
                s.deaths.popleft()
            quarantine = len(s.deaths) >= self.config.crashloop_threshold
            if quarantine:
                s.state = SLOT_DEAD
                s.reason = (f"crash-loop: {len(s.deaths)} deaths in "
                            f"{self.config.crashloop_window_s:g}s "
                            f"({why}); `ccs fleet readmit --slot "
                            f"{s.slot}` to retry")
                s.backoff_s = 0.0
                self._event(EV_QUARANTINE, slot=s.slot, reason=s.reason)
            else:
                s.attempt += 1
                s.backoff_s = backoff_schedule(self.config, s.attempt)
                s.respawn_at = now + s.backoff_s
                s.state = SLOT_RESTARTING
                s.reason = why
                self._event(EV_RESPAWN, slot=s.slot, reason=why,
                            attempt=s.attempt, backoff_s=s.backoff_s)
        if name is not None:
            self._router_remove(name, drain=False, timeout_s=0.0)
        if quarantine:
            self._log.warn(f"fleet: slot {s.slot} QUARANTINED ({why})")
        else:
            self._log.warn(f"fleet: slot {s.slot} died ({why}); respawn "
                           f"in {s.backoff_s:.2f}s (attempt {s.attempt})")

    def _router_remove(self, name: str, drain: bool,
                       timeout_s: float) -> None:
        try:
            out = self.router.remove_replica(name, drain=drain,
                                             timeout_s=timeout_s)
        except ValueError:
            return  # already gone (e.g. an admin removed it first)
        with self._lock:
            self._event(EV_REMOVE, slot=None, reason=name,
                        backoff_s=None,
                        attempt=out.get("failed_over") or None)

    # -------------------------------------------------------- autoscaling

    def _active_count(self) -> int:
        """Slots that are serving or will be shortly (lock held)."""
        return sum(1 for s in self._slots.values()
                   if s.state in (SLOT_UP, SLOT_STARTING,
                                  SLOT_RESTARTING))

    def _tick_autoscale(self) -> None:
        if self.config.max_replicas <= self.config.min_replicas \
                and self.config.scale_up_pending <= 0:
            return
        with self._lock:
            if self._rolling is not None:
                self._burn_since = self._idle_since = None
                return
        pending = self.router.pending_count()
        now = self.clock()
        if self.config.scale_up_pending > 0 \
                and pending > self.config.scale_up_pending:
            self._idle_since = None
            if self._burn_since is None:
                self._burn_since = now
            elif now - self._burn_since >= self.config.scale_up_sustain_s:
                self._burn_since = None
                self._scale_up(pending)
            return
        self._burn_since = None
        if pending > 0:
            self._idle_since = None
            return
        if self._idle_since is None:
            self._idle_since = now
        elif now - self._idle_since >= self.config.scale_down_idle_s:
            self._idle_since = None
            self._scale_down()

    def _scale_up(self, pending: int) -> None:
        with self._lock:
            if self._active_count() >= self.config.max_replicas:
                return
            if any(s.spawning for s in self._slots.values()):
                return  # one membership change at a time
            # reuse a retired slot id before minting a new one, so the
            # roster stays compact across breathe-in/breathe-out cycles
            stopped = [s for s in self._slots.values()
                       if s.state == SLOT_STOPPED]
            if stopped:
                s = min(stopped, key=lambda s: s.slot)
                s.state = SLOT_STARTING
                s.respawn_at = 0.0
                s.reason = ""
            else:
                sid = max(self._slots) + 1 if self._slots else 0
                s = self._slots[sid] = _Slot(sid)
            self._event(EV_SCALE_UP, slot=s.slot,
                        reason=f"pending={pending} sustained "
                               f"{self.config.scale_up_sustain_s:g}s")
        self._log.info(f"fleet: scale up -> slot {s.slot} "
                       f"(pending={pending})")

    def _scale_down(self) -> None:
        with self._lock:
            up = [s for s in self._slots.values() if s.state == SLOT_UP
                  and not s.managed and s.child is not None]
            if self._active_count() <= self.config.min_replicas or not up:
                return
            s = max(up, key=lambda s: s.slot)  # retire the youngest
            s.state = SLOT_DRAINING
            s.managed = True
            s.reason = (f"idle {self.config.scale_down_idle_s:g}s; "
                        "draining for retirement")
            self._event(EV_SCALE_DOWN, slot=s.slot, reason=s.reason)
        self._log.info(f"fleet: scale down -> draining slot {s.slot}")
        threading.Thread(target=self._retire_worker, args=(s,),
                         daemon=True,
                         name=f"ccs-fleet-retire-{s.slot}").start()

    def _retire_worker(self, s: _Slot) -> None:
        try:
            with self._lock:
                name, child = s.replica, s.child
            if name is not None:
                self._router_remove(name, drain=True,
                                    timeout_s=self.config.drain_timeout_s)
            if child is not None:
                self._shutdown_child(s, child,
                                     self.config.drain_timeout_s)
            with self._lock:
                s.child = None
                s.replica = None
                s.state = SLOT_STOPPED
        finally:
            with self._lock:
                s.managed = False

    def _shutdown_child(self, s: _Slot, child,
                        drain_timeout_s: float) -> None:
        """SIGTERM (the replica drains itself) with SIGKILL escalation
        past the budget -- the drain_kill ledger event marks the
        escalation so a stuck build is visible in the audit trail."""
        if drain_timeout_s > 0:
            try:
                child.send_signal(signal.SIGTERM)
            except Exception:  # noqa: BLE001 -- racing an exited child
                pass
            try:
                child.wait(timeout=drain_timeout_s)
                return
            except (subprocess.TimeoutExpired, TimeoutError):
                pass
        try:
            child.kill()
            child.wait(timeout=10.0)
        except Exception:  # noqa: BLE001 -- SIGKILL is the last resort
            pass
        with self._lock:
            self._event(EV_DRAIN_KILL, slot=s.slot,
                        reason=f"drain budget {drain_timeout_s:g}s "
                               "exceeded; escalated to SIGKILL")

    # ---------------------------------------------------- rolling restart

    def request_rolling_restart(self) -> bool:
        """Begin a zero-loss rolling deploy; False when one is already
        running."""
        with self._lock:
            if self._rolling is not None:
                return False
            plan = sorted(s.slot for s in self._slots.values()
                          if s.state == SLOT_UP and not s.managed)
            self._rolling = {"state": "running", "plan": plan,
                             "done": [], "current": None}
            self._event(EV_ROLLING_BEGIN,
                        reason=f"slots {plan}")
        threading.Thread(target=self._rolling_worker, daemon=True,
                         name="ccs-fleet-rolling").start()
        return True

    def _rolling_worker(self) -> None:
        with self._lock:
            plan = list(self._rolling["plan"])
        ok = True
        for sid in plan:
            if self._stop.is_set():
                ok = False
                break
            if not self._rolling_step(sid):
                ok = False
                break
        with self._lock:
            state = "done" if ok else "failed"
            self._event(EV_ROLLING_DONE,
                        reason=f"{state}: "
                               f"{len(self._rolling['done'])}/"
                               f"{len(plan)} slots cycled")
            self._rolling = None
        self._log.info(f"fleet: rolling restart {state}")

    def _rolling_step(self, sid: int) -> bool:
        """Cycle ONE slot: drain -> SIGTERM -> respawn warm ->
        health-gate.  Never holds the supervisor lock across a router
        or child call."""
        with self._lock:
            s = self._slots.get(sid)
            if s is None or s.state != SLOT_UP or s.managed:
                return True  # it left the roster since planning; skip
            s.managed = True
            s.state = SLOT_RESTARTING
            s.reason = "rolling deploy"
            self._rolling["current"] = sid
            name, child = s.replica, s.child
        try:
            if name is not None:
                self._router_remove(name, drain=True,
                                    timeout_s=self.config.drain_timeout_s)
            if child is not None:
                self._shutdown_child(s, child,
                                     self.config.drain_timeout_s)
            with self._lock:
                s.child = None
                s.replica = None
                incarnation = s.incarnation
                s.incarnation += 1
            try:
                new_child = self.spawn_fn(s.slot, incarnation)
            except SpawnError as e:
                # hand the slot back to the self-healing path (it owns
                # backoff + quarantine) and stop the deploy: a build
                # that cannot come back up must not take down the rest
                with self._lock:
                    s.managed = False
                self._record_death(s, f"rolling respawn failed: {e}")
                return False
            try:
                new_name = self.router.add_replica(
                    (new_child.host, new_child.port))
            except ValueError as e:
                new_child.kill()
                with self._lock:
                    s.managed = False
                self._record_death(s, f"rolling join refused: {e}")
                return False
            with self._lock:
                s.child = new_child
                s.replica = new_name
                s.state = SLOT_UP
                s.reason = ""
            gated = self._health_gate(new_name)
            with self._lock:
                self._rolling["done"].append(sid)
                self._rolling["current"] = None
                self._event(EV_ROLLING_STEP, slot=sid, reason=new_name)
            if not gated:
                self._log.warn(f"fleet: rolling: {new_name} never went "
                               "healthy inside the gate; aborting")
                return False
            return True
        finally:
            with self._lock:
                s.managed = False

    def _health_gate(self, name: str) -> bool:
        """Block until the router reports `name` connected AND healthy
        (or the gate budget runs out) -- the rolling deploy only moves
        to the next slot behind a proven-good replacement."""
        deadline = self.clock() + self.config.health_gate_timeout_s
        while self.clock() < deadline and not self._stop.is_set():
            for r in self.router.status().get("replicas", ()):
                if r.get("replica") == name and r.get("connected") \
                        and r.get("healthy"):
                    return True
            time.sleep(self.config.poll_interval_s)
        return False

    # ------------------------------------------------------------- admin

    def readmit(self, slot: int) -> None:
        """Manually un-quarantine a slot (`ccs fleet readmit`)."""
        with self._lock:
            s = self._slots.get(slot)
            if s is None:
                raise ValueError(f"unknown slot {slot} (have "
                                 f"{sorted(self._slots)})")
            if s.state != SLOT_DEAD:
                raise ValueError(
                    f"slot {slot} is {s.state}, not quarantined")
            s.deaths.clear()
            s.attempt = 0
            s.backoff_s = 0.0
            s.respawn_at = self.clock()
            s.state = SLOT_RESTARTING
            s.reason = ""
            self._event(EV_READMIT, slot=slot)
        self._log.info(f"fleet: slot {slot} re-admitted")

    def status_block(self) -> dict:
        """The `supervisor` field of the router's status verb.  Touches
        ONLY supervisor state: the router calls this while its own lock
        is released, and taking the router lock here would invert the
        add/remove_replica lock order."""
        with self._lock:
            slots = [{
                "slot": s.slot,
                "state": s.state,
                "replica": s.replica,
                "pid": getattr(s.child, "pid", None),
                "incarnation": max(s.incarnation - 1, 0),
                "deaths": len(s.deaths),
                "backoff_s": round(s.backoff_s, 3),
                "reason": s.reason,
            } for _, s in sorted(self._slots.items())]
            rolling = dict(self._rolling) if self._rolling else None
            return {protocol.KEY_SUP_SLOTS: slots,
                    protocol.KEY_SUP_EVENTS: list(self._events),
                    protocol.KEY_SUP_ROLLING: rolling}

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def _event(self, event: str, slot: int | None = None,
               reason: str = "", attempt: int | None = None,
               backoff_s: float | None = None) -> None:
        """Record one autopilot decision (lock held by caller): bounded
        in-memory tail for the status verb + one schema-declared
        fleet_event ledger record (meta: the perf gate ignores them)."""
        rec = {"t_event": round(time.time(), 3), "event": event}
        if slot is not None:
            rec["slot"] = slot
        if reason:
            rec["reason"] = reason
        if attempt is not None:
            rec["attempt"] = attempt
        if backoff_s is not None:
            rec["backoff_s"] = round(backoff_s, 3)
        self._events.append(rec)
        if self._ledger is not None:
            led = {"kind": "fleet_event", "fleet_event": event}
            for k in ("slot", "reason", "attempt", "backoff_s"):
                if k in rec:
                    led[k] = rec[k]
            self._ledger.append(led)


# --------------------------------------------------------- real children

class _ProcChild:
    """subprocess.Popen adapter satisfying the spawn_fn handle shape."""

    def __init__(self, proc: subprocess.Popen, host: str, port: int):
        self.proc = proc
        self.host = host
        self.port = port

    @property
    def pid(self) -> int:
        return self.proc.pid

    def poll(self):
        return self.proc.poll()

    def send_signal(self, sig) -> None:
        try:
            self.proc.send_signal(sig)
        except ProcessLookupError:
            pass

    def kill(self) -> None:
        try:
            self.proc.kill()
        except ProcessLookupError:
            pass

    def wait(self, timeout=None):
        return self.proc.wait(timeout)


def make_serve_spawn(serve_args: list[str], ready_timeout_s: float,
                     logger: Logger | None = None
                     ) -> Callable[[int, int], _ProcChild]:
    """The production spawn_fn: one `ccs serve --port 0` subprocess per
    call, blocking until its CCS-SERVE-READY line.  The slot id and the
    0-based respawn counter ride the environment (PBCCS_FLEET_SLOT /
    PBCCS_FLEET_INCARNATION) so fault injection can target one slot's
    early incarnations (`serve.start:crashloop=3~1`)."""
    log = logger or Logger.default()

    def spawn(slot: int, incarnation: int) -> _ProcChild:
        cmd = [sys.executable, "-m", "pbccs_tpu.cli", "serve",
               "--host", "127.0.0.1", "--port", "0"] + list(serve_args)
        env = dict(os.environ,
                   PBCCS_FLEET_SLOT=str(slot),
                   PBCCS_FLEET_INCARNATION=str(incarnation))
        try:
            proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                    stderr=subprocess.DEVNULL, text=True)
        except OSError as e:
            raise SpawnError(f"slot {slot}: exec failed: {e}") from None
        # ready-or-dead: the watchdog kills a child that is alive but
        # silent past the deadline, turning the hang into stdout EOF
        watchdog = threading.Timer(max(ready_timeout_s, 1.0), proc.kill)
        watchdog.daemon = True
        watchdog.start()
        try:
            line = proc.stdout.readline()
            while line and not line.startswith("CCS-SERVE-READY"):
                line = proc.stdout.readline()
        finally:
            watchdog.cancel()
        if not line:
            try:
                rc = proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                rc = proc.wait(timeout=10.0)
            raise SpawnError(
                f"slot {slot} incarnation {incarnation} died before "
                f"ready (exit {rc})", exit_code=rc)
        _, host, port = line.split()[:3]
        # keep draining stdout forever: a full pipe would wedge the child
        threading.Thread(
            target=lambda: collections.deque(proc.stdout, maxlen=0),
            daemon=True, name=f"ccs-fleet-stdout-{slot}").start()
        log.debug(f"fleet: slot {slot} child pid {proc.pid} ready on "
                  f"{host}:{port}")
        return _ProcChild(proc, host, int(port))

    return spawn


# ------------------------------------------------------------- ccs fleet

def build_fleet_parser() -> argparse.ArgumentParser:
    rdefaults = RouterConfig(allow_empty=True)
    sdefaults = SupervisorConfig()
    p = argparse.ArgumentParser(
        prog="ccs fleet",
        description="Self-healing serve fleet: a supervised router + N "
                    "`ccs serve` replicas with crash respawn, "
                    "crash-loop quarantine, autoscaling and zero-loss "
                    "rolling restarts.  With no action, runs the "
                    "fleet; with an action, administers a running one "
                    "over its router port.")
    p.add_argument("action", nargs="?", default="run",
                   choices=["run", "list", "add", "remove", "restart",
                            "readmit"],
                   help="run (default) = supervise a fleet; the rest "
                        "are admin verbs against --target.")
    # ----- admin-client knobs
    p.add_argument("--target", metavar="HOST:PORT", default=None,
                   help="Router address for admin actions.")
    p.add_argument("--replica", metavar="HOST:PORT", default=None,
                   help="Replica to add/remove (admin actions).")
    p.add_argument("--slot", type=int, default=None,
                   help="Quarantined slot to readmit.")
    p.add_argument("--noDrain", action="store_true",
                   help="remove: skip the drain (fail over in-flight "
                        "work immediately).")
    # ----- fleet-run knobs
    p.add_argument("--host", default="127.0.0.1",
                   help="Router bind address. Default = %(default)s")
    p.add_argument("--port", type=int, default=7330,
                   help="Router bind port (0 = ephemeral). "
                        "Default = %(default)s")
    p.add_argument("--replicas", type=int, default=sdefaults.replicas,
                   help="Initial replica count. Default = %(default)s")
    p.add_argument("--minReplicas", type=int, default=None,
                   help="Autoscale floor. Default = --replicas")
    p.add_argument("--maxReplicas", type=int, default=None,
                   help="Autoscale ceiling. Default = --replicas "
                        "(autoscaling up disabled)")
    p.add_argument("--serveArg", action="append", default=[],
                   metavar="ARG",
                   help="Extra argument passed to every `ccs serve` "
                        "child (repeatable; use --serveArg=--flag=v "
                        "for flag-shaped values).")
    p.add_argument("--compileCache", default=None, metavar="DIR",
                   help="Persistent compile cache shared by every "
                        "replica: respawns and scale-ups warm-start "
                        "instead of recompiling. Default: off.")
    p.add_argument("--backoffBase", type=float,
                   default=sdefaults.backoff_base_s,
                   help="First respawn delay (seconds); doubles per "
                        "consecutive death. Default = %(default)s")
    p.add_argument("--backoffCap", type=float,
                   default=sdefaults.backoff_cap_s,
                   help="Respawn delay ceiling. Default = %(default)s")
    p.add_argument("--crashloopWindow", type=float,
                   default=sdefaults.crashloop_window_s,
                   help="Sliding window for the quarantine counter. "
                        "Default = %(default)s")
    p.add_argument("--crashloopThreshold", type=int,
                   default=sdefaults.crashloop_threshold,
                   help="Deaths inside the window that quarantine the "
                        "slot. Default = %(default)s")
    p.add_argument("--scaleUpPending", type=int,
                   default=sdefaults.scale_up_pending,
                   help="Router queue depth that triggers a scale-up "
                        "when sustained (0 disables). "
                        "Default = %(default)s")
    p.add_argument("--scaleUpSustain", type=float,
                   default=sdefaults.scale_up_sustain_s,
                   help="Seconds the queue must stay burning before a "
                        "scale-up. Default = %(default)s")
    p.add_argument("--scaleDownIdle", type=float,
                   default=sdefaults.scale_down_idle_s,
                   help="Seconds of zero pending work before the "
                        "youngest replica is drained away. "
                        "Default = %(default)s")
    p.add_argument("--readyTimeout", type=float,
                   default=sdefaults.ready_timeout_s,
                   help="Spawn-to-READY budget per child. "
                        "Default = %(default)s")
    p.add_argument("--healthGateTimeout", type=float,
                   default=sdefaults.health_gate_timeout_s,
                   help="Rolling restart: how long a respawned replica "
                        "gets to probe healthy before the deploy "
                        "aborts. Default = %(default)s")
    p.add_argument("--routerHealthInterval", type=float,
                   default=rdefaults.health_interval_s,
                   help="Router health-probe cadence. "
                        "Default = %(default)s")
    p.add_argument("--routerHealthTimeout", type=float,
                   default=rdefaults.health_timeout_s,
                   help="Unanswered-probe strike deadline. "
                        "Default = %(default)s")
    p.add_argument("--drainTimeout", type=float,
                   default=sdefaults.drain_timeout_s,
                   help="Drain budget (replica retirement, rolling "
                        "steps, admin remove) before SIGKILL. "
                        "Default = %(default)s")
    p.add_argument("--metricsPort", type=int, default=0,
                   help="Federated /metrics endpoint port (-1 = "
                        "ephemeral, 0 = off). Default = %(default)s")
    p.add_argument("--perfLedger", default=None, metavar="PATH",
                   help="Append fleet_event audit records (and the "
                        "router's fleet snapshots) to PATH. "
                        "Default: off.")
    p.add_argument("--perfLedgerInterval", type=float,
                   default=rdefaults.perf_ledger_interval_s,
                   help="Router fleet-snapshot cadence. "
                        "Default = %(default)s")
    # ----- multi-tenant edge (serve/tenancy.py): one flag set secures
    # every surface -- router front door, metrics endpoint, spawned
    # replicas, router->replica links, and the admin client
    p.add_argument("--tlsCert", default=None, metavar="PEM",
                   help="TLS certificate chain for the router front "
                        "door, the metrics endpoint AND every spawned "
                        "replica (with --tlsKey). Default: plaintext.")
    p.add_argument("--tlsKey", default=None, metavar="PEM",
                   help="TLS private key (with --tlsCert).")
    p.add_argument("--authTokens", default=None, metavar="FILE",
                   help="JSON token->tenant map applied at the router "
                        "edge AND passed to every replica; enables "
                        "per-tenant fair queuing + SLO shedding. "
                        "Default: open.")
    p.add_argument("--tlsCa", default=None, metavar="PEM",
                   help="CA bundle verifying replica/router certs for "
                        "the router links and admin actions; also "
                        "switches those connections to TLS.")
    p.add_argument("--authToken", default=None, metavar="TOKEN",
                   help="Bearer token for the router's replica links "
                        "(map it to a trusted tenant in --authTokens) "
                        "and for admin actions against --target.")
    p.add_argument("--shedBurnRate", type=float,
                   default=rdefaults.shed_burn_threshold,
                   help="Fleet SLO burn rate past which priority >= 1 "
                        "tenants are shed (0 disables). "
                        "Default = %(default)s")
    p.add_argument("--shedRetryMs", type=float,
                   default=rdefaults.retry_after_ms,
                   help="retry_after_ms hint on shed/quota rejections. "
                        "Default = %(default)s")
    p.add_argument("--tenantQueueDepth", type=int,
                   default=rdefaults.fair_queue_depth,
                   help="Parked submits per tenant before rejection. "
                        "Default = %(default)s")
    p.add_argument("--logLevel", default="INFO")
    return p


def child_serve_args(args) -> list[str]:
    """The argv tail every spawned `ccs serve` child gets.  The edge
    security flags pass DOWN: a TLS'd/token-guarded fleet must not spawn
    plaintext-open replicas on adjacent ports (the user's --serveArg
    values still come last so an argparse rematch lets them win)."""
    serve_args = ["--maxInflightPerSession", "256",
                  "--logLevel", "ERROR"]
    if args.compileCache:
        serve_args += ["--compileCache", args.compileCache]
    if args.tlsCert:
        serve_args += ["--tlsCert", args.tlsCert, "--tlsKey", args.tlsKey]
    if args.authTokens:
        serve_args += ["--authTokens", args.authTokens]
    serve_args += list(args.serveArg)
    return serve_args


def _fleet_admin(args, log: Logger) -> int:
    """One fleet admin verb round-tripped over a raw router session."""
    if not args.target:
        print("ccs fleet: admin actions need --target HOST:PORT",
              file=sys.stderr)
        return 2
    try:
        host, port = parse_replica_spec(args.target)
    except ValueError as e:
        print(f"ccs fleet: {e}", file=sys.stderr)
        return 2
    frame: dict = {"verb": protocol.VERB_FLEET, "id": "fleet-admin",
                   "action": args.action}
    if args.action in ("add", "remove"):
        if not args.replica:
            print(f"ccs fleet {args.action}: needs --replica HOST:PORT",
                  file=sys.stderr)
            return 2
        frame["replica"] = args.replica
        if args.action == "remove":
            frame["drain"] = not args.noDrain
            frame["timeout_s"] = args.drainTimeout
    if args.action == "readmit":
        if args.slot is None:
            print("ccs fleet readmit: needs --slot N", file=sys.stderr)
            return 2
        frame["slot"] = args.slot
    if args.authToken:
        # token-guarded router: every admin frame authenticates
        frame[protocol.FIELD_AUTH] = args.authToken
    try:
        with socket.create_connection((host, port), timeout=30.0) as c:
            if args.tlsCa:
                from pbccs_tpu.serve import tenancy

                c = tenancy.client_ssl_context(args.tlsCa).wrap_socket(
                    c, server_hostname=host)
            c.sendall(json.dumps(frame).encode() + b"\n")
            rf = c.makefile("rb")
            while True:
                line = rf.readline()
                if not line:
                    print("ccs fleet: connection closed before a reply",
                          file=sys.stderr)
                    return 1
                msg = json.loads(line)
                if msg.get("id") == frame["id"]:
                    break
    except OSError as e:
        print(f"ccs fleet: cannot reach {host}:{port}: {e}",
              file=sys.stderr)
        return 1
    print(json.dumps(msg, indent=2, sort_keys=True))
    return 0 if msg.get("type") == protocol.TYPE_FLEET else 1


def run_fleet(argv: list[str] | None = None) -> int:
    """`ccs fleet` entry point (dispatched from pbccs_tpu.cli)."""
    args = build_fleet_parser().parse_args(argv)
    log = Logger.default(Logger(level=LogLevel.from_string(args.logLevel)))
    if args.action != "run":
        return _fleet_admin(args, log)

    # children: quiet by default, per-session cap sized to the trusted
    # router link (it multiplexes every client over one session); the
    # edge security flags pass down so the whole fleet shares one
    # identity surface (child_serve_args is unit-tested directly)
    serve_args = child_serve_args(args)
    from pbccs_tpu.serve import tenancy
    from pbccs_tpu.serve.server import load_edge_config

    edge = load_edge_config(args, "ccs fleet")
    if edge is None:
        return 2
    ssl_ctx, tenants = edge
    link_ssl = (tenancy.client_ssl_context(args.tlsCa)
                if args.tlsCa or args.tlsCert else None)
    if tenants is not None:
        # the router's own link identity must exist in the token file
        # and be trusted, or every spawned replica would reject the
        # router's probes/submits -- fail at startup, not in production
        row = tenants.authenticate(args.authToken) \
            if args.authToken else None
        if row is None or not row.trusted:
            print("ccs fleet: --authTokens needs --authToken mapping to "
                  "a TRUSTED tenant (the router's replica-link identity)",
                  file=sys.stderr)
            return 2

    try:
        rconfig = RouterConfig(
            allow_empty=True,  # membership is the supervisor's job
            health_interval_s=args.routerHealthInterval,
            health_timeout_s=args.routerHealthTimeout,
            perf_ledger_path=args.perfLedger,
            perf_ledger_interval_s=args.perfLedgerInterval,
            fair_queue_depth=args.tenantQueueDepth,
            shed_burn_threshold=args.shedBurnRate,
            retry_after_ms=args.shedRetryMs)
        sconfig = SupervisorConfig(
            replicas=args.replicas,
            min_replicas=args.minReplicas,
            max_replicas=args.maxReplicas,
            backoff_base_s=args.backoffBase,
            backoff_cap_s=args.backoffCap,
            crashloop_window_s=args.crashloopWindow,
            crashloop_threshold=args.crashloopThreshold,
            drain_timeout_s=args.drainTimeout,
            health_gate_timeout_s=args.healthGateTimeout,
            ready_timeout_s=args.readyTimeout,
            scale_up_pending=args.scaleUpPending,
            scale_up_sustain_s=args.scaleUpSustain,
            scale_down_idle_s=args.scaleDownIdle)
    except ValueError as e:
        print(f"ccs fleet: {e}", file=sys.stderr)
        return 2
    router = CcsRouter([], rconfig, logger=log, tenants=tenants,
                       link_ssl=link_ssl, link_token=args.authToken)
    # the supervisor's audit ledger appends to the same NDJSON file as
    # the router's snapshot loop; O_APPEND + one-line flushed writes
    # keep the two interleavable without a shared handle
    ledger = PerfLedger(args.perfLedger, logger=log) \
        if args.perfLedger else None
    supervisor = FleetSupervisor(
        router, sconfig,
        make_serve_spawn(serve_args, args.readyTimeout, log),
        ledger=ledger, logger=log)
    with router:
        router.set_supervisor(supervisor)
        server = RouterServer(router, args.host, args.port, logger=log,
                              ssl_context=ssl_ctx, tenants=tenants)
        server.start()
        from pbccs_tpu.serve.server import start_metrics_endpoint

        metrics_http = start_metrics_endpoint(
            args.metricsPort, router.metrics_text, args.host, log,
            health=router.accepting, ssl_context=ssl_ctx)
        supervisor.start()
        print(f"CCS-FLEET-READY {server.host} {server.port}", flush=True)

        stop = threading.Event()

        def _on_signal(signum, frame):
            print(f"CCS-FLEET-DRAINING "
                  f"signal={signal.Signals(signum).name}", flush=True)
            stop.set()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, _on_signal)
            except ValueError:  # not the main thread (embedded fleet)
                pass
        try:
            stop.wait()
        except KeyboardInterrupt:
            pass
        log.info("ccs fleet draining: children first, then the router")
        server.stop_accepting()
        server.notify_draining()
        supervisor.stop(drain=True)
        drained = router.close(drain=True, deadline_s=args.drainTimeout)
        server.shutdown()
        if metrics_http is not None:
            metrics_http.shutdown()
        if ledger is not None:
            ledger.close()
        log.info("ccs fleet drained cleanly" if drained
                 else "ccs fleet drain deadline hit; failed remainder")
    log.flush()
    return 0
