"""Wire protocol of the online CCS serving engine: newline-delimited JSON.

One JSON object per line, UTF-8, over a byte stream (TCP).  Client
messages carry a `verb`; server messages carry a `type`.  Every client
message may carry an `id` (any JSON string) which the server echoes on
the reply, so concurrent requests on one session stream back
out-of-order and the client re-associates them.  This module is
transport-free -- encode/decode plus the ZMW/result wire layout -- so
protocol tests never open a socket (server.py and client.py own the
sockets).

Request-id plumbing through the router tier (serve/router.py): `ccs
router` rewrites the id on BOTH hops -- a client submit's id maps to a
router-assigned `q<N>` toward the replica, and the replica's reply maps
back before emission.  The router id is the failover/dedup key: after a
replica failure the same `q<N>` may be resubmitted to another replica,
and the first reply bearing it wins (later duplicates are dropped), so
a client sees exactly one reply per id it sent.  Ids beginning `hc` on
a replica link are the router's own status-verb health probes, and ids
beginning `fl` its fleet-introspection calls (metrics federation, trace
fan-out).  All of this is invisible at both edges; no wire shape
changes.

Trace context (the fleet observability plane): a submit frame MAY carry
a `trace` object -- {"trace_id": <hex string>, "span_id": <string>} --
naming the distributed trace the request belongs to and the sender-side
span it continues.  Each tier propagates it inward (client -> router ->
replica session -> engine prep/polish spans -> sched dispatch) and the
router REWRITES span_id on the replica hop to its own per-request span,
exactly as it rewrites the request id; trace_id is never rewritten, so
one id names the request across every process.  The field is pure
observability: it changes no consensus, no routing, no admission.  A
malformed `trace` object is rejected `bad_request` like any other
malformed field (the armor validates everything it forwards).

Client verbs:
  submit  {"verb": "submit", "id": ..., "zmw": <zmw>, "deadline_ms": ...,
           "trace": {"trace_id": ..., "span_id": ...}}   # trace optional
  status  {"verb": "status", "id": ...}
  metrics {"verb": "metrics", "id": ...}
  trace   {"verb": "trace", "id": ..., "action": "start" | "stop"}
  fleet   {"verb": "fleet", "id": ..., "action": "list" | "add" |
           "remove" | "restart" | "readmit", "replica": "host:port",
           "timeout_s": ...}   # replica/timeout_s action-dependent
  ping    {"verb": "ping", "id": ...}

Server replies:
  result  {"type": "result", "id": ..., "status": "<Failure name>",
           "zmw": ..., "latency_ms": ...,  # + on Success:
           "sequence": ..., "qual": <phred+33>, "num_passes": ...,
           "predicted_accuracy": ..., "avg_zscore": ...}
  error   {"type": "error", "id": ..., "code": "<machine code>",
           "error": "<human message>"}
  status  {"type": "status", "id": ..., ...engine.status()...}
          -- includes a `perf` block (schema_version, records,
          last_record: the newest performance-ledger record) when the
          process writes a perf ledger (--perfLedger)
  metrics {"type": "metrics", "id": ...,
           "content_type": "text/plain; version=0.0.4",
           "body": "<Prometheus text exposition>"}
  trace   {"type": "trace", "id": ..., "state": "started" |
           "already_running" | "stopped" | "not_running",
           "trace": {..Chrome-trace JSON..}}  # on state "stopped" only
  fleet   {"type": "fleet", "id": ..., "action": <echoed>, "ok": true,
           ...action-specific fields (replicas roster for list, the
           member name for add/remove, drain outcome for remove)...}
  pong    {"type": "pong", "id": ...}
  closed  {"type": "closed", "reason": "draining" | "idle_timeout"}
          -- unsolicited: the server is about to close this session
          (graceful drain, or the idle-session reaper fired)

Error codes: bad_request (unparseable/invalid message -- the session
stays open unless the frame itself broke framing, e.g. oversized),
overloaded (admission queue full OR the per-session in-flight cap OR a
tenant's fair-queue bound OR SLO-burn shedding: backpressure, retry
later -- shed/over-quota rejections additionally carry a
`retry_after_ms` hint the client backoff honors), closed (engine
shutting down), internal (the request raised inside the engine; the
SERVER stays up, only this request fails), unauthorized (an
authenticated front door -- `--authTokens` -- saw a frame whose `auth`
bearer token is missing or unknown; the session stays open, nothing
else in the frame was parsed).

Multi-tenant edge (serve/tenancy.py): with a token file configured,
every frame must carry `auth: "<token>"`; the token maps to a tenant
(quota, priority class, DRR weight) and IS the identity.  A submit MAY
carry a `tenant` object -- {"name": <tenant>} -- but it is honored only
from a `trusted` token (the router forwarding the original submitter to
a replica); from anyone else it is ignored, so tenants cannot spoof
each other's accounting or quotas.

Protocol armor (ServeConfig limits, enforced by server._Session): frames
longer than max_line_bytes get `bad_request` and the session closes;
sessions idle past idle_timeout_s with nothing in flight are reaped with
a `closed` notice; submits past max_inflight_per_session are rejected
`overloaded` without touching the engine.  The `zmw` payload passes the
same io.validate.validate_chunk contract the offline CLI reader applies,
so both front doors reject garbage identically.

The ZMW wire layout mirrors pipeline.Chunk:
  {"id": "movie/hole", "snr": [A, C, G, T],
   "reads": [{"id": ..., "seq": "ACGT...", "flags": 3,
              "accuracy": 0.8}, ...]}
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

from pbccs_tpu.models.arrow.params import decode_bases, encode_bases
from pbccs_tpu.pipeline import Chunk, ConsensusResult, Failure, Subread

PROTOCOL_VERSION = 1

# client verbs
VERB_SUBMIT = "submit"
VERB_STATUS = "status"
VERB_METRICS = "metrics"
VERB_TRACE = "trace"
VERB_FLEET = "fleet"
VERB_PING = "ping"

# server reply types
TYPE_RESULT = "result"
TYPE_ERROR = "error"
TYPE_STATUS = "status"
TYPE_METRICS = "metrics"
TYPE_TRACE = "trace"
TYPE_FLEET = "fleet"
TYPE_PONG = "pong"
TYPE_CLOSED = "closed"

# the Prometheus text exposition format version the metrics verb speaks
METRICS_CONTENT_TYPE = "text/plain; version=0.0.4"

# error codes
ERR_BAD_REQUEST = "bad_request"
ERR_OVERLOADED = "overloaded"
ERR_CLOSED = "closed"
ERR_INTERNAL = "internal"
ERR_UNAUTHORIZED = "unauthorized"

# optional wire fields (cross-cutting objects that may ride a verb frame)
FIELD_TRACE = "trace"
# the trace-context object's keys
KEY_TRACE_ID = "trace_id"
KEY_SPAN_ID = "span_id"
# the status reply's performance-ledger block (obs.ledger.perf_block):
# schema version, records appended by this process, most recent record.
# Declared here (and in WIRE_FIELDS below) so protolint polices the
# status addition like every other wire name.
FIELD_PERF = "perf"
KEY_PERF_SCHEMA = "schema_version"
KEY_PERF_RECORDS = "records"
KEY_PERF_LAST = "last_record"

# status-verb roofline block (obs/roofline.py status_block): per-bucket
# CostCard bound + measured achieved/efficiency.
FIELD_ROOFLINE = "roofline"
KEY_ROOFLINE_SCHEMA = "schema_version"
KEY_ROOFLINE_PEAK = "peak_tflops"
KEY_ROOFLINE_BUCKETS = "buckets"

# status-verb supervisor block (serve/supervisor.py status_block): the
# fleet autopilot's slot table (state machine per managed replica
# process), its recent fleet events, and rolling-restart progress.
# Present only when a supervisor controls the answering router.
FIELD_SUPERVISOR = "supervisor"
KEY_SUP_SLOTS = "slots"
KEY_SUP_EVENTS = "events"
KEY_SUP_ROLLING = "rolling_restart"

# multi-tenant edge (serve/tenancy.py).  `auth` is the bearer token an
# authenticated front door (--authTokens) requires on EVERY verb frame;
# a frame without a known token gets ERR_UNAUTHORIZED.  `tenant` is the
# identity object the router forwards on the replica hop -- the token,
# not this field, is the identity at the edge (a non-trusted session's
# tenant field is ignored; see tenancy.resolve_tenant).
FIELD_AUTH = "auth"
FIELD_TENANT = "tenant"
KEY_TENANT_NAME = "name"
# error replies answering a shed/over-quota submit carry a client
# backoff hint in milliseconds (client.submit_with_retry honors it,
# capped + jittered); rides reply frames, so it has no carrier verb.
FIELD_RETRY_AFTER = "retry_after_ms"
# status-verb tenancy block (tenancy.FairQueue.rows + shed state):
# per-tenant admission accounting rendered by `ccs top`.
FIELD_TENANCY = "tenancy"
KEY_TEN_TENANTS = "tenants"
KEY_TEN_BURN = "burn_rate"
KEY_TEN_SHEDDING = "shedding"


# ------------------------------------------------------------------ wire spec
#
# Machine-readable protocol state machine.  `ccs analyze`'s protolint
# pass (pbccs_tpu/analysis/protolint.py) parses these tables from the
# AST -- never importing this module -- and statically checks
# server.py / router.py / client.py against them: every verb a client
# tier can send has a registered handler on the serving tier's
# dispatch, every reply type and error code that reaches a wire is
# declared here, and every handler completes-or-fails a request
# exactly once, only while owning it.  Values resolve through the
# VERB_*/TYPE_*/ERR_* constants above, so the spec cannot drift from
# the names the code ships (drift either way is a PRO001 finding).
#
# Per-verb fields:
#   handler  the session method that serves the verb (None = handled
#            inline by the dispatch loop itself, e.g. ping/pong);
#   replies  reply types the verb may terminate with (any verb may
#            additionally fail with TYPE_ERROR);
#   ownership "callback" marks the ownership-transfer rule: the handler
#            acquires the session in-flight slot and hands completion
#            (reply + slot release) to a registered callback -- the
#            exactly-once and lease obligations move with it.

WIRE_VERBS = {
    VERB_SUBMIT: {"handler": "_on_submit",
                  "replies": (TYPE_RESULT, TYPE_ERROR),
                  "ownership": "callback"},
    VERB_STATUS: {"handler": "_on_status", "replies": (TYPE_STATUS,)},
    VERB_METRICS: {"handler": "_on_metrics", "replies": (TYPE_METRICS,)},
    VERB_TRACE: {"handler": "_on_trace",
                 "replies": (TYPE_TRACE, TYPE_ERROR)},
    VERB_FLEET: {"handler": "_on_fleet",
                 "replies": (TYPE_FLEET, TYPE_ERROR)},
    VERB_PING: {"handler": None, "replies": (TYPE_PONG,)},
}

WIRE_REPLIES = (TYPE_RESULT, TYPE_ERROR, TYPE_STATUS, TYPE_METRICS,
                TYPE_TRACE, TYPE_FLEET, TYPE_PONG, TYPE_CLOSED)

# server->client types no verb elicits (drain / idle-reap notices)
WIRE_UNSOLICITED = (TYPE_CLOSED,)

WIRE_ERRORS = (ERR_BAD_REQUEST, ERR_OVERLOADED, ERR_CLOSED, ERR_INTERNAL,
               ERR_UNAUTHORIZED)

# optional cross-cutting wire FIELDS: {field: {"keys": (...), "verbs":
# (carrier verbs...)}}.  protolint's PRO001 checks the FIELD_*/KEY_*
# constants against this table both ways (the same membership rule as
# verbs/replies/errors), so the trace-context contract cannot drift
# from the names the code ships.
WIRE_FIELDS = {
    FIELD_TRACE: {"keys": (KEY_TRACE_ID, KEY_SPAN_ID),
                  "verbs": (VERB_SUBMIT,)},
    # rides the STATUS exchange: the reply to a `status` verb carries a
    # `perf` block when the serving process writes a performance ledger
    # (--perfLedger); absent otherwise.  The router federates these
    # blocks fleet-wide into its own ledger.
    FIELD_PERF: {"keys": (KEY_PERF_SCHEMA, KEY_PERF_RECORDS,
                          KEY_PERF_LAST),
                 "verbs": (VERB_STATUS,)},
    # rides the STATUS exchange: present once the roofline plane holds a
    # CostCard or a charge for any bucket; absent on cold replicas or
    # under PBCCS_ROOFLINE=0.
    FIELD_ROOFLINE: {"keys": (KEY_ROOFLINE_SCHEMA, KEY_ROOFLINE_PEAK,
                              KEY_ROOFLINE_BUCKETS),
                     "verbs": (VERB_STATUS,)},
    # rides the STATUS exchange: present when a fleet supervisor
    # (serve/supervisor.py) controls the answering router -- the slot
    # table `ccs top` renders restarting/dead/draining states from,
    # plus the recent fleet events and rolling-restart progress.
    FIELD_SUPERVISOR: {"keys": (KEY_SUP_SLOTS, KEY_SUP_EVENTS,
                                KEY_SUP_ROLLING),
                       "verbs": (VERB_STATUS,)},
    # may ride EVERY verb frame: the bearer token an authenticated front
    # door (--authTokens) requires before dispatching the verb at all; a
    # missing/unknown token answers ERR_UNAUTHORIZED and the frame is
    # never parsed further.
    FIELD_AUTH: {"keys": (),
                 "verbs": (VERB_SUBMIT, VERB_STATUS, VERB_METRICS,
                           VERB_TRACE, VERB_FLEET, VERB_PING)},
    # rides the SUBMIT frame on the router->replica hop: the router
    # (whose link token is `trusted`) forwards the ORIGINAL submitter's
    # identity so replica-side accounting stays per-tenant.  From a
    # non-trusted session the field is ignored (spoofing defense).
    FIELD_TENANT: {"keys": (KEY_TENANT_NAME,),
                   "verbs": (VERB_SUBMIT,)},
    # rides error REPLIES (shed / over-quota): no carrier verb.
    FIELD_RETRY_AFTER: {"keys": (), "verbs": ()},
    # rides the STATUS exchange: present when the answering router runs
    # with a token file -- per-tenant admission rows (FairQueue.rows),
    # the fleet burn rate, and whether shedding is engaged.
    FIELD_TENANCY: {"keys": (KEY_TEN_TENANTS, KEY_TEN_BURN,
                             KEY_TEN_SHEDDING),
                    "verbs": (VERB_STATUS,)},
}


class ProtocolError(ValueError):
    """A message violates the wire contract (bad JSON, wrong field types,
    missing required fields)."""


def encode_msg(msg: dict[str, Any]) -> bytes:
    """One NDJSON frame: compact JSON + newline."""
    return json.dumps(msg, separators=(",", ":")).encode() + b"\n"


def decode_line(line: bytes | str) -> dict[str, Any]:
    """Parse one NDJSON frame; raises ProtocolError on anything that is
    not a JSON object."""
    if isinstance(line, bytes):
        try:
            line = line.decode()
        except UnicodeDecodeError as e:
            raise ProtocolError(f"frame is not UTF-8: {e}") from None
    try:
        msg = json.loads(line)
    except json.JSONDecodeError as e:
        raise ProtocolError(f"frame is not JSON: {e}") from None
    if not isinstance(msg, dict):
        raise ProtocolError("frame is not a JSON object")
    return msg


# ---------------------------------------------------------------- trace wire

# armor bound: trace ids/span ids are opaque strings, but the session
# must not carry arbitrarily large attacker-chosen payloads into every
# span/export downstream
_TRACE_VALUE_MAX = 128


def trace_from_wire(obj: Any) -> dict[str, Any] | None:
    """Validate + normalize a frame's optional `trace` field.  Returns
    {"trace_id": str, "span_id": str | None}, or None when absent;
    raises ProtocolError (-> bad_request) on malformed input."""
    if obj is None:
        return None
    if not isinstance(obj, dict):
        raise ProtocolError("trace must be an object")
    trace_id = obj.get(KEY_TRACE_ID)
    if not isinstance(trace_id, str) or not trace_id \
            or len(trace_id) > _TRACE_VALUE_MAX:
        raise ProtocolError(
            f"trace.{KEY_TRACE_ID} must be a non-empty string "
            f"(<= {_TRACE_VALUE_MAX} chars)")
    span_id = obj.get(KEY_SPAN_ID)
    if span_id is not None and (not isinstance(span_id, str)
                                or len(span_id) > _TRACE_VALUE_MAX):
        raise ProtocolError(
            f"trace.{KEY_SPAN_ID} must be a string "
            f"(<= {_TRACE_VALUE_MAX} chars)")
    return {KEY_TRACE_ID: trace_id, KEY_SPAN_ID: span_id}


# --------------------------------------------------------------- tenant wire

def tenant_from_wire(obj: Any) -> dict[str, Any] | None:
    """Validate + normalize a frame's optional `tenant` field (the
    identity object a trusted router forwards on the replica hop).
    Returns {"name": str}, or None when absent; raises ProtocolError
    (-> bad_request) on malformed input -- the same armor contract as
    trace_from_wire, and the same size bound."""
    if obj is None:
        return None
    if not isinstance(obj, dict):
        raise ProtocolError("tenant must be an object")
    name = obj.get(KEY_TENANT_NAME)
    if not isinstance(name, str) or not name \
            or len(name) > _TRACE_VALUE_MAX:
        raise ProtocolError(
            f"tenant.{KEY_TENANT_NAME} must be a non-empty string "
            f"(<= {_TRACE_VALUE_MAX} chars)")
    return {KEY_TENANT_NAME: name}


# ------------------------------------------------------------------ ZMW wire

def chunk_to_wire(chunk: Chunk) -> dict[str, Any]:
    return {
        "id": chunk.id,
        "snr": [float(s) for s in np.asarray(chunk.snr)],
        "reads": [{"id": r.id, "seq": decode_bases(r.seq),
                   "flags": int(r.flags),
                   "accuracy": float(r.read_accuracy)}
                  for r in chunk.reads],
    }


def chunk_from_wire(zmw: Any) -> Chunk:
    """Validate + decode a submit message's `zmw` field; raises
    ProtocolError with a client-actionable message on malformed input."""
    if not isinstance(zmw, dict):
        raise ProtocolError("zmw must be an object")
    zid = zmw.get("id")
    if not isinstance(zid, str) or not zid:
        raise ProtocolError("zmw.id must be a non-empty string")
    snr = zmw.get("snr", [8.0] * 4)
    if (not isinstance(snr, list) or len(snr) != 4
            or not all(isinstance(s, (int, float))
                       and not isinstance(s, bool) for s in snr)):
        raise ProtocolError("zmw.snr must be 4 numbers (ACGT)")
    reads = zmw.get("reads")
    if not isinstance(reads, list) or not reads:
        raise ProtocolError("zmw.reads must be a non-empty array")
    subreads = []
    for i, r in enumerate(reads):
        if not isinstance(r, dict) or not isinstance(r.get("seq"), str):
            raise ProtocolError(f"zmw.reads[{i}].seq must be a string")
        try:
            seq = encode_bases(r["seq"])
        except UnicodeEncodeError:
            raise ProtocolError(
                f"zmw.reads[{i}].seq must be ASCII base characters"
            ) from None
        if isinstance(r.get("flags"), bool) \
                or isinstance(r.get("accuracy"), bool):
            raise ProtocolError(
                f"zmw.reads[{i}] flags/accuracy must be numeric")
        try:
            flags = int(r.get("flags", 3))
            accuracy = float(r.get("accuracy", 0.8))
        except (TypeError, ValueError):
            raise ProtocolError(
                f"zmw.reads[{i}] flags/accuracy must be numeric") from None
        subreads.append(Subread(id=str(r.get("id", f"{zid}/{i}")), seq=seq,
                                flags=flags, read_accuracy=accuracy))
    chunk = Chunk(zid, subreads, np.asarray(snr, np.float64))
    from pbccs_tpu.io.validate import ChunkValidationError, validate_chunk

    try:
        # the same contract the offline CLI reader enforces (io.validate):
        # counts ccs_input_invalid_records_total{reason} and gives the
        # client the structured reason
        validate_chunk(chunk)
    except ChunkValidationError as e:
        raise ProtocolError(f"zmw rejected ({e.reason}): {e}") from None
    return chunk


# --------------------------------------------------------------- result wire

def result_to_wire(request_id: Any, zmw_id: str, failure: Failure,
                   result: ConsensusResult | None,
                   latency_ms: float) -> dict[str, Any]:
    """One streamed per-ZMW result (Success carries the consensus; any
    other status is a structured yield-gate outcome, not an error)."""
    msg: dict[str, Any] = {
        "type": TYPE_RESULT,
        "id": request_id,
        "zmw": zmw_id,
        "status": failure.value,
        "latency_ms": round(float(latency_ms), 3),
    }
    if result is not None:
        msg.update(
            sequence=result.sequence,
            qual=result.qualities,
            num_passes=int(result.num_passes),
            predicted_accuracy=round(float(result.predicted_accuracy), 6),
            avg_zscore=(float(result.avg_zscore)
                        if np.isfinite(result.avg_zscore) else None),
        )
        if result.draft_only:
            # quarantine degradation: the sequence is the unpolished POA
            # draft with capped QVs (resilience.quarantine)
            msg["draft_only"] = True
    return msg


def error_to_wire(request_id: Any, code: str, message: str,
                  retry_after_ms: float | None = None) -> dict[str, Any]:
    """One structured error reply.  `retry_after_ms` (shed / over-quota
    rejections) tells the client WHEN to retry -- submit_with_retry
    honors it over its own exponential schedule, so a shedding fleet
    paces its retry storm instead of amplifying it."""
    msg = {"type": TYPE_ERROR, "id": request_id, "code": code,
           "error": message}
    if retry_after_ms is not None:
        msg[FIELD_RETRY_AFTER] = round(float(retry_after_ms), 3)
    return msg
