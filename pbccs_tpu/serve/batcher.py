"""Dynamic batcher: group pending ZMW requests into compiled-shape buckets.

The continuous-batching core of the serving engine.  Each pending item
carries the (Jmax, Imax) length bucket its ZMW polishes in
(parallel.batch.length_bucket -- the same shape key the offline
BatchPolisher derives, so every flush reuses already-compiled polish
programs) and a flush-by time.  A bucket flushes when

  * it FILLS (max_batch items: the device batch is worth dispatching), or
  * the OLDEST item's flush-by expires (max-wait flush: the item's
    deadline slack ran out, so it stops waiting for co-batchable traffic
    and ships with whatever company it has -- possibly alone).

This module is pure data structure + clock arithmetic: no threads, no
sockets, no device calls.  The engine (serve.engine.CcsEngine) owns the
thread that sleeps until next_deadline() and dispatches what due()
returns; tests drive the same API with a fake clock.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Hashable

from pbccs_tpu.obs.metrics import default_registry

BucketKey = Hashable

_reg = default_registry()
_m_flushes = {reason: _reg.counter("ccs_serve_flushes_total",
                                   "Bucket flushes by trigger",
                                   reason=reason)
              for reason in ("fill", "deadline", "drain")}
_m_batch_zmws = _reg.histogram("ccs_serve_batch_zmws",
                               "ZMWs per flushed batch",
                               buckets=(1, 2, 4, 8, 16, 32, 64, 128))
_m_bucketed = _reg.gauge("ccs_serve_bucketed",
                         "Requests parked in the dynamic batcher")


def _record_flush(batch: "Batch") -> "Batch":
    _m_flushes[batch.reason].inc()
    _m_batch_zmws.observe(len(batch.items))
    return batch


@dataclasses.dataclass
class PendingItem:
    """One admitted request waiting for its bucket to flush."""

    key: BucketKey
    payload: Any        # opaque to the batcher (the engine stores requests)
    admit_t: float      # monotonic admission time
    flush_by: float     # monotonic max-wait deadline (admit_t + slack)


@dataclasses.dataclass
class Batch:
    """One flushed bucket, ready to polish."""

    key: BucketKey
    items: list[PendingItem]
    reason: str         # "fill" | "deadline" | "drain"


class DynamicBatcher:
    """Thread-safe bucketed pending pool with fill- and deadline-flush.

    All methods may be called from any thread; flushed batches are
    returned to exactly one caller (items leave the pool atomically)."""

    def __init__(self, max_batch: int):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.max_batch = max_batch
        self._lock = threading.Lock()
        self._buckets: dict[BucketKey, list[PendingItem]] = {}

    def add(self, item: PendingItem) -> Batch | None:
        """Admit one item; returns the fill-triggered Batch if this item
        topped off its bucket, else None."""
        with self._lock:
            pending = self._buckets.setdefault(item.key, [])
            pending.append(item)
            if len(pending) >= self.max_batch:
                del self._buckets[item.key]
                _m_bucketed.dec(len(pending) - 1)
                return _record_flush(Batch(item.key, pending, "fill"))
            _m_bucketed.inc()
            return None

    def due(self, now: float) -> list[Batch]:
        """Pop every bucket whose OLDEST item's flush-by has expired.

        The whole bucket ships, not just the expired item: the remaining
        items ride along for free (their polish is one batched program
        either way), which is the latency-optimal choice under the
        one-device model."""
        out = []
        with self._lock:
            for key in [k for k, items in self._buckets.items()
                        if min(i.flush_by for i in items) <= now]:
                batch = Batch(key, self._buckets.pop(key), "deadline")
                _m_bucketed.dec(len(batch.items))
                out.append(_record_flush(batch))
        return out

    def drain(self) -> list[Batch]:
        """Pop everything (engine shutdown / flush-now)."""
        with self._lock:
            out = [_record_flush(Batch(k, items, "drain"))
                   for k, items in self._buckets.items()]
            for b in out:
                _m_bucketed.dec(len(b.items))
            self._buckets.clear()
        return out

    def next_deadline(self) -> float | None:
        """Earliest flush-by over all pending items (None when empty) --
        what the engine's batcher thread sleeps until."""
        with self._lock:
            deadlines = [i.flush_by for items in self._buckets.values()
                         for i in items]
        return min(deadlines) if deadlines else None

    def pending_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._buckets.values())

    def depth_by_bucket(self) -> dict[str, int]:
        """Queue depth per bucket key (status introspection)."""
        with self._lock:
            return {str(k): len(v) for k, v in self._buckets.items()}
