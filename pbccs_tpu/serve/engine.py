"""CcsEngine: the long-lived online CCS serving core.

Owns device state and compiled polish programs for the lifetime of the
process and turns independently-arriving ZMW requests into the batched
lockstep polish programs the device wants (parallel.batch.BatchPolisher
via pipeline.polish_prepared_batch).  The offline CLI knows its whole
workload up front; the engine does not, so it:

  * admits requests through a BOUNDED pool (max_pending): a full engine
    rejects with EngineOverloaded instead of growing without bound --
    the server maps this to a structured `overloaded` reply and the
    client retries (backpressure reaches the edge instead of the OOM
    killer);
  * preps admitted requests (filter -> POA draft -> mapping, the host
    stages) on a small worker pool, then parks them in the dynamic
    batcher under their (Jmax, Imax) length bucket
    (parallel.batch.length_bucket);
  * flushes a bucket to the polish executor when it fills (max_batch)
    or when its oldest request's deadline slack expires
    (min(admit + max_wait, deadline - polish_margin); see
    serve.batcher), so a lone request never waits longer than its slack
    for company;
  * completes each request individually (out-of-order across batches)
    through its callback/event -- a raising request or batch fails THAT
    batch's requests with a structured error and the engine keeps
    serving.

The device itself is single-owner: polish batches run on a dedicated
executor (default 1 worker -- one lockstep batch on device at a time,
matching the offline driver; the WorkQueue overlap trick applies to host
stages, which here live on the prep workers)."""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Callable, Sequence

from pbccs_tpu.obs import flight as _obs_flight  # noqa: F401 -- import
# registers the refine-loop gauges, so an idle replica's exposition
# still carries ccs_refine_* series (zeroes) and `ccs top` renders a
# uniform per-replica surface instead of nulls until first traffic
from pbccs_tpu.obs import roofline as obs_roofline
from pbccs_tpu.obs import trace as obs_trace
from pbccs_tpu.obs.metrics import default_registry, log_buckets
from pbccs_tpu.pipeline import (
    Chunk,
    ConsensusResult,
    ConsensusSettings,
    Failure,
    PreparedZmw,
    polish_prepared_batch,
    prepare_chunk,
)
from pbccs_tpu.runtime import timing
from pbccs_tpu.runtime.logging import Logger
from pbccs_tpu.serve.batcher import Batch, DynamicBatcher, PendingItem

_reg = default_registry()
_m_admitted = _reg.counter("ccs_serve_admitted_total",
                           "Requests admitted past the bounded pool")
_m_rejected = _reg.counter("ccs_serve_rejected_total",
                           "Submits rejected as overloaded")
_m_completed = _reg.counter("ccs_serve_completed_total",
                            "Requests completed (any outcome)")
_m_errors = _reg.counter("ccs_serve_errors_total",
                         "Requests completed with a structured error")
_m_pending = _reg.gauge("ccs_serve_pending",
                        "Admitted-but-incomplete requests")
_m_inflight_batches = _reg.gauge("ccs_serve_in_flight_batches",
                                 "Polish batches dispatched, not finished")
_m_inflight_zmws = _reg.gauge("ccs_serve_in_flight_zmws",
                              "ZMWs inside in-flight polish batches")
# admission-to-completion latency; log buckets 1 ms .. ~5 min
_m_latency = _reg.histogram("ccs_serve_request_latency_seconds",
                            "Admission-to-completion request latency (s)",
                            buckets=log_buckets(1e-3, 300.0))
# SLO plane: per-request stage intervals (the latency story decomposed:
# admission wait -> prepare -> batcher queue -> dispatch wait -> polish
# -> emit) and the --sloP99Ms burn-rate counters.  Stage handles are
# pre-created (hot path holds direct references).
_STAGE_BUCKETS = log_buckets(1e-4, 300.0)
_m_stages = {stage: _reg.histogram(
    "ccs_serve_stage_latency_seconds",
    "Per-request stage intervals (admission wait, prepare, batcher "
    "queue, dispatch wait, polish, emit)",
    buckets=_STAGE_BUCKETS, stage=stage)
    for stage in ("admission", "prepare", "queue", "dispatch", "polish",
                  "emit")}
_m_slo_requests = _reg.counter(
    "ccs_slo_requests_total",
    "Requests measured against the --sloP99Ms latency objective")
_m_slo_violations = _reg.counter(
    "ccs_slo_violations_total",
    "Requests whose admission-to-completion latency exceeded --sloP99Ms "
    "(burn-rate numerator; ccs_slo_requests_total is the denominator)")


def _flush_shapes(preps: Sequence[PreparedZmw]) -> tuple[int, int, int]:
    """The (imax, jmax, r) bucket a flush of these preps polishes in --
    the ONE derivation shared by the pinned polish call and the
    capacity-bucket key, so the governor ceiling the pool records is
    the same key the polish-time admission pre-split looks up."""
    from pbccs_tpu.parallel.batch import length_bucket
    from pbccs_tpu.utils import next_pow2

    jmax, imax = length_bucket(
        max(len(p.css) for p in preps),
        max((len(m.seq) for p in preps for m in p.mapped), default=8))
    r = next_pow2(max(len(p.mapped) for p in preps), 4)
    return imax, jmax, r


def _polish_shape_pinned(preps: Sequence[PreparedZmw], settings, *,
                         raise_device_shaped: bool = False):
    """polish_prepared_batch with shapes pinned to the flush's length
    bucket + pow2 Z/R: online flushes vary in size (1..max_batch ZMWs,
    arbitrary read counts), and letting each draw pick its own shapes
    would mint a fresh compiled device loop per (Z, R) combination -- the
    same bounded-program-menu rule the offline straggler/wide-retry
    sub-batches follow (parallel/batch.py BatchPolisher `buckets`)."""
    from pbccs_tpu.utils import next_pow2

    imax, jmax, r = _flush_shapes(preps)
    return polish_prepared_batch(preps, settings,
                                 buckets=(imax, jmax, r),
                                 min_z=next_pow2(len(preps), 4),
                                 raise_device_shaped=raise_device_shaped)


class EngineOverloaded(RuntimeError):
    """Admission pool full: shed load, client should retry with backoff."""


class EngineClosed(RuntimeError):
    """Engine is shutting down (or never started); no new requests."""


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving knobs (see module docstring for the policy they drive)."""

    max_batch: int = 16            # bucket fill-flush size (ZMWs per batch)
    max_wait_ms: float = 250.0     # max time a request waits to be batched
    max_pending: int = 256         # admitted-but-incomplete request bound
    prep_workers: int = 2          # host draft/mapping threads
    polish_workers: int = 1        # concurrent device batches (devices=1)
    # polish across a device fleet (pbccs_tpu.sched.DevicePool): N>1 uses
    # the first N visible devices, 0 all of them, 1 (default) the legacy
    # single-device polish executor.  Flushed buckets route STICKY by
    # compiled-shape bucket (sched_policy), a repeatedly-failing device
    # is benched and its batches requeue to healthy devices.
    devices: int = 1
    sched_policy: str = "sticky"   # sticky | least | roundrobin
    default_deadline_ms: float = 60_000.0   # per-request deadline default
    polish_margin_ms: float = 0.0  # slack reserved for the polish itself
    # the offline CLI's read-score input gate (cli.py --minReadScore),
    # applied at admission so serve and offline see the same read sets
    min_read_score: float = 0.75
    # watchdog deadline per polish batch (resilience.watchdog): a hung
    # device program becomes a structured timeout error on THAT batch's
    # requests and the engine keeps serving.  0 disables.  Size it well
    # above a worst-case polish incl. quarantine bisection re-dispatches.
    polish_timeout_ms: float = 0.0
    # ---- wire-protocol armor (enforced by server._Session) ----
    # longest accepted NDJSON frame; an oversized frame gets a
    # `bad_request` reply and the session closes (the line buffer is the
    # only per-session allocation an untrusted peer controls)
    max_line_bytes: int = 8 << 20
    # submits one session may have in flight before further submits are
    # rejected `overloaded` WITHOUT touching the engine (one hostile
    # session cannot monopolize the shared admission pool)
    max_inflight_per_session: int = 64
    # reap sessions with nothing in flight that send no byte for this
    # long (slow-loris defense); 0 disables
    idle_timeout_s: float = 600.0
    # ---- SLO plane ----
    # per-request latency objective in ms (--sloP99Ms): requests slower
    # than this count into ccs_slo_violations_total (burn-rate
    # numerator) and the status verb's `slo` block.  0 disables.
    slo_p99_ms: float = 0.0
    # ---- performance ledger (obs.ledger) ----
    # append schema-versioned NDJSON perf records to this path
    # (--perfLedger): one snapshot every perf_ledger_interval_s plus a
    # final one at close, and the status verb grows a `perf` block the
    # router federates fleet-wide.  None disables.
    perf_ledger_path: str | None = None
    perf_ledger_interval_s: float = 30.0


@dataclasses.dataclass
class Request:
    """One in-flight ZMW request; completed exactly once."""

    seq: int
    chunk: Chunk
    submit_t: float                  # monotonic admission time
    deadline_t: float                # monotonic absolute deadline
    callback: Callable[["Request"], None] | None = None
    # inbound cross-process trace context ({"trace_id", "span_id"}, the
    # protocol's `trace` submit field): engine spans parent under it
    trace_ctx: dict | None = None
    # outcome (exactly one of failure or error set at completion)
    failure: Failure | None = None
    result: ConsensusResult | None = None
    error: str | None = None
    latency_ms: float = 0.0
    # stage timestamps (monotonic; 0.0 = stage never reached) feeding
    # the ccs_serve_stage_latency_seconds histograms at completion
    t_prep0: float = 0.0
    t_prep1: float = 0.0
    t_dispatch: float = 0.0
    t_polish0: float = 0.0
    t_polish1: float = 0.0
    done: threading.Event = dataclasses.field(default_factory=threading.Event)

    def wait(self, timeout: float | None = None) -> bool:
        return self.done.wait(timeout)


class CcsEngine:
    """Long-lived dynamic-batching consensus engine (see module doc)."""

    def __init__(self, settings: ConsensusSettings | None = None,
                 config: ServeConfig | None = None, *,
                 prep_fn: Callable[..., tuple[Failure | None,
                                              PreparedZmw | None]] | None = None,
                 polish_fn: Callable[..., list[tuple[Failure,
                                                     ConsensusResult | None]]]
                 | None = None,
                 logger: Logger | None = None):
        """prep_fn/polish_fn default to the real pipeline stages; tests
        inject stubs to exercise scheduling without device work."""
        self.settings = settings or ConsensusSettings()
        self.config = config or ServeConfig()
        self._prep_fn = prep_fn or prepare_chunk
        self._polish_fn = polish_fn or _polish_shape_pinned
        self._log = logger or Logger.default()

        self._lock = threading.Lock()
        self._window = timing.window()   # re-opened at start()
        self._trace_lock = threading.Lock()
        self._capture: obs_trace.Tracer | None = None
        self._seq = 0
        self._pending = 0            # admitted, not yet completed
        self._admitted = 0
        self._rejected = 0
        self._completed = 0
        self._errors = 0
        self._in_flight_batches = 0
        self._in_flight_zmws = 0
        self._prep_queue: queue.Queue[Request | None] = queue.Queue()
        self._batcher = DynamicBatcher(self.config.max_batch)
        self._wake = threading.Condition()
        self._closed = True
        self._abort = False
        self._stop_flush = False
        self._start_t = 0.0
        self._threads: list[threading.Thread] = []
        self._pool = None   # DevicePool when config.devices != 1
        self._complete_queue = None   # fleet-mode completion hand-off
        self._complete_thread = None
        self._n_polish_workers = 0   # set by start(); close() must not
        # depend on attributes a failed start() never assigned
        # performance ledger (obs.ledger): periodic snapshot records
        # while serving + a final record at close
        self._ledger = None
        self._ledger_stop = threading.Event()
        self._ledger_thread: threading.Thread | None = None

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "CcsEngine":
        with self._lock:
            if not self._closed:
                return self
            self._closed = False
            self._abort = False
            self._stop_flush = False
        self._start_t = time.monotonic()
        # the engine's OWN measurement window: a timing.reset() elsewhere
        # in the process (bench.py) no longer clobbers engine counters
        self._window = timing.window()
        # pick up CostCards minted by an earlier warmup process so the
        # roofline block/gauges have bounds before the first polish
        obs_roofline.tracker().load_persisted()
        n_polish = self.config.polish_workers
        if self.config.devices != 1:
            # device-fleet mode: the DevicePool's per-device executor
            # threads replace the single polish executor; flushed buckets
            # route sticky by compiled-shape bucket (pbccs_tpu/sched)
            from pbccs_tpu.sched import (DevicePool, DevicePoolConfig,
                                         select_devices)

            try:
                devs = select_devices(self.config.devices)
            except ValueError as e:
                raise ValueError(f"ServeConfig.devices: {e}") from None
            pool = DevicePool(
                devs, DevicePoolConfig(policy=self.config.sched_policy),
                logger=self._log)
            n_polish = 0
            # batch completions run arbitrary caller code (replies on a
            # possibly-slow client socket, bounded only by the session's
            # idle timeout): hand them to a dedicated thread so a stalled
            # send blocks this thread, never a device executor
            complete_queue = queue.Queue()
            complete_thread = threading.Thread(
                target=self._completion_worker, daemon=True,
                name="ccs-serve-complete")
            # publish under the lock: status() and close() read these
            # attributes from other threads (ccs-analyze CONC001)
            with self._lock:
                self._pool = pool
                self._complete_queue = complete_queue
                self._complete_thread = complete_thread
            complete_thread.start()
        self._threads = [
            threading.Thread(target=self._prep_worker, daemon=True,
                             name=f"ccs-serve-prep-{i}")
            for i in range(self.config.prep_workers)
        ] + [
            threading.Thread(target=self._flush_loop, daemon=True,
                             name="ccs-serve-batcher"),
        ] + [
            threading.Thread(target=self._polish_worker, daemon=True,
                             name=f"ccs-serve-polish-{i}")
            for i in range(n_polish)
        ]
        self._n_polish_workers = n_polish
        self._polish_queue: queue.Queue[Batch | None] = queue.Queue()
        for t in self._threads:
            t.start()
        if self.config.perf_ledger_path:
            from pbccs_tpu.obs.ledger import PerfLedger

            ledger = PerfLedger(self.config.perf_ledger_path,
                                logger=self._log)
            ledger_thread = threading.Thread(
                target=self._ledger_worker, args=(ledger,), daemon=True,
                name="ccs-serve-ledger")
            self._ledger_stop.clear()
            with self._lock:
                self._ledger = ledger
                self._ledger_thread = ledger_thread
            ledger_thread.start()
        self._log.info(
            f"ccs engine up: max_batch={self.config.max_batch} "
            f"max_wait={self.config.max_wait_ms}ms "
            f"max_pending={self.config.max_pending}"
            + (f" devices={self._pool.n_devices}" if self._pool else ""))
        return self

    def close(self, drain: bool = True,
              deadline_s: float | None = None) -> bool:
        """Stop admission; with drain (default) finish everything already
        admitted, else fail pending requests with a `closed` error.

        ``deadline_s`` bounds the drain wait: past it the engine falls
        back to fast abort (remaining requests fail with a structured
        `closed` error) instead of hanging shutdown on a stuck device.
        Returns True when every admitted request completed normally."""
        with self._lock:
            if self._closed:
                return True
            self._closed = True
            self._abort = not drain
            pending0 = self._pending
        # drain=False with requests in the system WILL fail them with a
        # `closed` error -- that is not a clean drain
        drained = drain or pending0 == 0
        if drain:
            # wait for admitted requests to complete (they flow through
            # prep -> batcher -> polish on their own; the flush loop ships
            # not-yet-due buckets immediately once it sees _closed)
            give_up_at = (time.monotonic() + deadline_s
                          if deadline_s else None)
            while True:
                with self._lock:
                    if self._pending == 0:
                        break
                    pending = self._pending
                if give_up_at is not None and time.monotonic() > give_up_at:
                    with self._lock:
                        self._abort = True
                    drained = False
                    self._log.warn(
                        f"drain deadline ({deadline_s}s) exceeded with "
                        f"{pending} request(s) pending: aborting")
                    break
                with self._wake:
                    self._wake.notify_all()
                time.sleep(0.01)
        # stop the workers (flush loop last: it must outlive the preps so
        # a request prepped during the drain still gets shipped)
        for _ in range(self.config.prep_workers):
            self._prep_queue.put(None)
        with self._wake:
            self._wake.notify_all()
        for t in self._threads:
            if t.name.startswith("ccs-serve-prep"):
                t.join(timeout=10.0)
        with self._lock:
            self._stop_flush = True
        with self._wake:
            self._wake.notify_all()
        for _ in range(self._n_polish_workers):
            self._polish_queue.put(None)
        for t in self._threads:
            t.join(timeout=10.0)
        with self._lock:
            aborted = self._abort
            pool = self._pool
            complete_thread = self._complete_thread
            complete_queue = self._complete_queue
        if pool is not None:
            # draining already waited for in-flight batches; an abort
            # fails queued pool tasks (their callbacks complete the
            # requests with a structured error) and bounds the worker
            # joins like the legacy polish-worker path, so a hung device
            # program cannot hold the drain-deadline fallback hostage
            pool.close(wait=not aborted,
                       join_timeout_s=10.0 if aborted else 60.0)
            with self._lock:
                self._pool = None
        if complete_thread is not None:
            # after pool.close() every settled future has enqueued its
            # completion; the sentinel lands behind them all
            complete_queue.put(None)
            complete_thread.join(timeout=10.0)
            with self._lock:
                self._complete_thread = None
        if aborted:
            # fail whatever is still parked anywhere
            leftovers = [i.payload[0] for b in self._batcher.drain()
                         for i in b.items]
            while True:
                try:
                    req = self._prep_queue.get_nowait()
                except queue.Empty:
                    break
                if req is not None:
                    leftovers.append(req)
            for req in leftovers:
                self._complete_error(req, "engine closed")
        # performance ledger: stop the snapshot loop, then one FINAL
        # record so a short-lived engine still leaves a run record
        with self._lock:
            ledger = self._ledger
            ledger_thread = self._ledger_thread
            self._ledger = None
            self._ledger_thread = None
        if ledger is not None:
            self._ledger_stop.set()
            if ledger_thread is not None:
                ledger_thread.join(timeout=10.0)
            ledger.append(self._ledger_record())
            ledger.close()
        self.trace_stop()  # never leak a live capture past the engine
        self._log.info("ccs engine down")
        return drained

    def __enter__(self) -> "CcsEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------- admission

    def submit(self, chunk: Chunk, deadline_ms: float | None = None,
               callback: Callable[[Request], None] | None = None,
               trace_ctx: dict | None = None) -> Request:
        """Admit one ZMW; returns its Request handle (completes via
        callback and/or .wait()).  `trace_ctx` is the request's inbound
        cross-process trace context (protocol `trace` field); engine
        spans parent under it.  Raises EngineOverloaded when max_pending
        requests are in the system and EngineClosed after close()."""
        now = time.monotonic()
        deadline_ms = (self.config.default_deadline_ms
                       if deadline_ms is None else float(deadline_ms))
        with self._lock:
            if self._closed:
                raise EngineClosed("engine is not accepting requests")
            if self._pending >= self.config.max_pending:
                self._rejected += 1
                _m_rejected.inc()
                raise EngineOverloaded(
                    f"{self._pending} requests pending (max "
                    f"{self.config.max_pending})")
            self._pending += 1
            self._admitted += 1
            _m_admitted.inc()
            _m_pending.inc()
            self._seq += 1
            req = Request(seq=self._seq, chunk=chunk, submit_t=now,
                          deadline_t=now + deadline_ms / 1e3,
                          callback=callback, trace_ctx=trace_ctx)
        self._prep_queue.put(req)
        return req

    # ---------------------------------------------------------------- stages

    def _prep_worker(self) -> None:
        while True:
            req = self._prep_queue.get()
            if req is None:
                return
            with self._lock:
                aborting = self._abort
            if aborting:
                self._complete_error(req, "engine closed")
                continue
            # the offline CLI's read-score input gate (cli.py), applied
            # pre-draft so serve and offline polish the same read sets
            kept = [r for r in req.chunk.reads
                    if r.read_accuracy >= self.config.min_read_score]
            if len(kept) != len(req.chunk.reads):
                req.chunk = Chunk(req.chunk.id, kept, req.chunk.snr)
            req.t_prep0 = time.monotonic()
            try:
                with obs_trace.span("serve.prep", ctx=req.trace_ctx,
                                    zmw=req.chunk.id), \
                        timing.stage("serve.prep"):
                    failure, prep = self._prep_fn(req.chunk, self.settings)
            except Exception as e:  # noqa: BLE001 -- isolate the request
                self._complete_error(req, f"prep failed: {e!r}")
                continue
            req.t_prep1 = time.monotonic()
            if failure is not None:
                self._complete(req, failure, None)
                continue
            from pbccs_tpu.parallel.batch import length_bucket

            key = length_bucket(
                len(prep.css),
                max((len(m.seq) for m in prep.mapped), default=8))
            slack_end = req.deadline_t - self.config.polish_margin_ms / 1e3
            flush_by = min(req.submit_t + self.config.max_wait_ms / 1e3,
                           slack_end)
            filled = self._batcher.add(PendingItem(
                key=key, payload=(req, prep), admit_t=req.submit_t,
                flush_by=flush_by))
            if filled is not None:
                self._dispatch(filled)
            else:
                with self._wake:
                    self._wake.notify_all()  # re-arm the flush timer

    def _flush_loop(self) -> None:
        """Sleep until the earliest flush-by, then ship due buckets.

        Exits only on _stop_flush (set after the prep workers join), so a
        request prepped during a close() drain is still shipped."""
        while True:
            with self._lock:
                if self._stop_flush:
                    return
                closed = self._closed
            with self._wake:
                nxt = self._batcher.next_deadline()
                if nxt is None:
                    # closed-but-empty still naps: close() may be waiting
                    # on in-flight polishes and this must not busy-spin
                    self._wake.wait(timeout=0.05 if closed else 0.2)
                else:
                    delay = nxt - time.monotonic()
                    if delay > 0 and not closed:
                        self._wake.wait(timeout=min(delay, 0.2))
            with self._lock:
                closed = self._closed
            batches = self._batcher.due(time.monotonic())
            if closed:
                # shutting down: ship everything, due or not
                batches += self._batcher.drain()
            for batch in batches:
                self._dispatch(batch)

    def _capacity_bucket(self, batch: Batch):
        """The resources.shape_bucket this flush polishes in (the shape
        derivation is _flush_shapes, shared with _polish_shape_pinned),
        so governor ceilings learned at dispatch time pre-split later
        flushes."""
        from pbccs_tpu.resilience import resources

        preps = [item.payload[1] for item in batch.items]
        return resources.shape_bucket(*_flush_shapes(preps))

    def _dispatch(self, batch: Batch) -> None:
        from pbccs_tpu.resilience import resources

        # serve flushes consult the governor's learned ceilings: a
        # bucket that OOMed at some Z dispatches as ceiling-sized
        # sub-batches from the start (fleet-wide minimum -- the target
        # device is not picked yet), instead of paying the OOM again
        bucket = self._capacity_bucket(batch)
        cap = resources.default_governor().cap(bucket)
        parts = [batch]
        if cap is not None and len(batch.items) > cap:
            resources.note_presplit()
            # capacity-split postmortem: what the refine loops were doing
            # just before the governor had to intervene
            from pbccs_tpu.obs import flight

            flight.dump("capacity-split", self._log)
            self._log.info(
                f"flush bucket={batch.key}: governor ceiling {cap} "
                f"splits {len(batch.items)} ZMW(s) into "
                f"{len(resources.split_sizes(len(batch.items), cap))} "
                "dispatches")
            parts, start = [], 0
            for size in resources.split_sizes(len(batch.items), cap):
                parts.append(Batch(batch.key,
                                   batch.items[start:start + size],
                                   batch.reason))
                start += size
        for part in parts:
            self._dispatch_part(part, bucket)

    def _dispatch_part(self, batch: Batch, capacity_bucket) -> None:
        now = time.monotonic()
        for item in batch.items:
            item.payload[0].t_dispatch = now
        with self._lock:
            self._in_flight_batches += 1
            self._in_flight_zmws += len(batch.items)
        _m_inflight_batches.inc()
        _m_inflight_zmws.inc(len(batch.items))
        self._log.debug(
            f"flush bucket={batch.key} n={len(batch.items)} "
            f"reason={batch.reason}")
        if self._pool is not None:
            # device-fleet mode: the pool picks the device (sticky by the
            # batch's compiled-shape bucket); a device-shaped failure
            # requeues the WHOLE batch to a healthy device before the
            # requests see an error (pbccs_tpu/sched), and a
            # capacity-shaped one records a governor ceiling + requeues
            # to the same device for a split re-dispatch
            attempts = [0]

            def run(_device, batch=batch, attempts=attempts):
                attempts[0] += 1
                return self._run_polish(batch,
                                        first_attempt=attempts[0] == 1)

            self._pool.submit(
                batch.key, run, zmws=len(batch.items),
                capacity_bucket=capacity_bucket,
                callback=lambda fut: self._pool_done(batch, fut))
        else:
            self._polish_queue.put(batch)

    def _run_polish(self, batch: Batch, first_attempt: bool = False) -> list:
        """One batch through the polish fn under the watchdog; raises on
        failure (the caller routes the error to this batch's requests).
        On a fleet's first attempt the default polish fn re-raises
        device-shaped failures (persistent XLA errors) instead of
        quarantining in place, so the pool can bench the sick device and
        requeue the whole batch to a healthy one -- mirroring the batch
        executor (pbccs_tpu.sched.executor)."""
        raise_dev = (first_attempt and self._pool is not None
                     and self._pool.n_devices > 1
                     and self._polish_fn is _polish_shape_pinned)
        preps = [item.payload[1] for item in batch.items]
        reqs = [item.payload[0] for item in batch.items]
        # batch-level span: parents under the FIRST traced request's
        # context; every member trace id rides in args so the fleet
        # merge can associate the shared device work with each request
        ctx = next((r.trace_ctx for r in reqs if r.trace_ctx), None)
        trace_ids = sorted({r.trace_ctx["trace_id"] for r in reqs
                            if r.trace_ctx})[:32]
        t_polish0 = time.monotonic()
        for req in reqs:
            req.t_polish0 = t_polish0
        try:
            # per-dispatch roofline scope keyed by the flush's shape
            # bucket; reentrancy-guarded, so in fleet mode (this method
            # runs inside a pool task that opened its own scope) only the
            # pool's outer scope counts
            rl_label = obs_roofline.bucket_label(*_flush_shapes(preps))
            with obs_trace.span("serve.polish", ctx=ctx,
                                bucket=str(batch.key),
                                zmws=len(batch.items),
                                reason=batch.reason,
                                trace_ids=trace_ids), \
                    timing.stage("serve.polish"), \
                    obs_roofline.dispatch_scope(rl_label,
                                                zmws=len(batch.items)):
                outcomes = self._run_polish_inner(preps, raise_dev,
                                                  first_attempt)
        finally:
            t_polish1 = time.monotonic()
            for req in reqs:
                req.t_polish1 = t_polish1
        if len(outcomes) != len(batch.items):
            raise RuntimeError(
                f"polish returned {len(outcomes)} outcomes for "
                f"{len(batch.items)} requests")
        return outcomes

    def _run_polish_inner(self, preps, raise_dev: bool,
                          first_attempt: bool) -> list:
        from pbccs_tpu.resilience.watchdog import (WatchdogTimeout,
                                                   run_with_deadline)

        # the watchdog turns a hung device program into a structured
        # timeout on THIS batch's requests; the engine keeps serving
        try:
            return run_with_deadline(
                (lambda: self._polish_fn(preps, self.settings,
                                         raise_device_shaped=True))
                if raise_dev else
                (lambda: self._polish_fn(preps, self.settings)),
                self.config.polish_timeout_ms / 1e3,
                site="serve.polish")
        except WatchdogTimeout as e:
            if not first_attempt and self._pool is not None:
                # a SECOND expiry on a different device is workload-
                # shaped (the batch is just slower than the deadline,
                # e.g. a cold compile), not sick hardware: wrap it so
                # the pool fails the batch instead of striking another
                # healthy device and touring the whole fleet at one
                # full timeout per hop
                raise RuntimeError(
                    f"polish timed out on two devices: {e}") from e
            raise

    def _complete_batch(self, batch: Batch, outcomes: list | None = None,
                        error: BaseException | None = None) -> None:
        reqs = [item.payload[0] for item in batch.items]
        pairs: list = []
        if error is None:
            # validate shape BEFORE completing anything: a malformed
            # outcome must fail the whole batch, never complete part of
            # it and strand the rest (in pool mode this runs inside a
            # SchedFuture callback, where an escaped exception is only
            # debug-logged)
            try:
                pairs = [(failure, result) for failure, result in outcomes]
            except Exception as e:  # noqa: BLE001
                error = RuntimeError(f"malformed polish outcomes: {e!r}")
        try:
            if error is not None:
                for req in reqs:
                    self._complete_error(req, f"polish failed: {error!r}")
            else:
                for req, (failure, result) in zip(reqs, pairs):
                    self._complete(req, failure, result)
        finally:
            # in-flight accounting must survive any completion error or
            # close(drain=True) spins forever waiting on this batch
            with self._lock:
                self._in_flight_batches -= 1
                self._in_flight_zmws -= len(batch.items)
            _m_inflight_batches.dec()
            _m_inflight_zmws.dec(len(batch.items))

    def _pool_done(self, batch: Batch, fut) -> None:
        # runs on a device executor thread: hand off immediately so the
        # device goes back to polishing while replies hit client sockets
        exc = fut.exception()
        self._complete_queue.put(
            (batch, None if exc is not None else fut.result(), exc))

    def _completion_worker(self) -> None:
        while True:
            item = self._complete_queue.get()
            if item is None:
                return
            batch, outcomes, error = item
            try:
                self._complete_batch(batch, outcomes, error=error)
            except Exception as e:  # noqa: BLE001 -- the completer must
                # outlive any one batch (accounting already ran in
                # _complete_batch's finally)
                self._log.warn(f"batch completion failed: {e!r}")

    def _polish_worker(self) -> None:
        while True:
            batch = self._polish_queue.get()
            if batch is None:
                return
            try:
                outcomes = self._run_polish(batch)
            except Exception as e:  # noqa: BLE001 -- fail THIS batch only
                self._complete_batch(batch, error=e)
            else:
                self._complete_batch(batch, outcomes)

    # ------------------------------------------------------------ completion

    @staticmethod
    def _observe_stages(req: Request, now: float) -> None:
        """Per-request stage intervals into the SLO histograms.  Stages a
        request never reached (early failure, prep-side yield gate) are
        skipped, not recorded as zero; clock jitter is clamped at 0."""
        marks = (("admission", req.submit_t, req.t_prep0),
                 ("prepare", req.t_prep0, req.t_prep1),
                 ("queue", req.t_prep1, req.t_dispatch),
                 ("dispatch", req.t_dispatch, req.t_polish0),
                 ("polish", req.t_polish0, req.t_polish1),
                 ("emit", req.t_polish1, now))
        for stage, t0, t1 in marks:
            if t0 > 0.0 and t1 > 0.0:
                _m_stages[stage].observe(max(t1 - t0, 0.0))

    def _finish(self, req: Request) -> None:
        now = time.monotonic()
        req.latency_ms = (now - req.submit_t) * 1e3
        with self._lock:
            self._pending -= 1
            self._completed += 1
            if req.error is not None:
                self._errors += 1
        _m_pending.dec()
        _m_completed.inc()
        if req.error is not None:
            _m_errors.inc()
        _m_latency.observe(req.latency_ms / 1e3)
        self._observe_stages(req, now)
        if self.config.slo_p99_ms > 0:
            _m_slo_requests.inc()
            if req.latency_ms > self.config.slo_p99_ms:
                _m_slo_violations.inc()
        req.done.set()
        if req.callback is not None:
            try:
                req.callback(req)
            except Exception as e:  # noqa: BLE001 -- a dead client must
                # never take the engine down with it
                self._log.debug(f"result callback failed: {e!r}")

    def _complete(self, req: Request, failure: Failure,
                  result: ConsensusResult | None) -> None:
        req.failure, req.result = failure, result
        self._finish(req)

    def _complete_error(self, req: Request, message: str) -> None:
        req.error = message
        self._log.warn(f"request {req.chunk.id}: {message}")
        self._finish(req)

    # ------------------------------------------------ performance ledger

    def _ledger_record(self) -> dict:
        """One serve-snapshot ledger record: registry deltas over the
        engine's own measurement window plus the live serving state."""
        from pbccs_tpu.obs import ledger as obs_ledger

        with self._lock:
            pending = self._pending
            in_flight = self._in_flight_zmws
            completed = self._completed
            errors = self._errors
        return obs_ledger.run_record(
            self._window, kind="serve_snapshot", source="ccs-serve",
            extra={
                "uptime_s": round(time.monotonic() - self._start_t, 3),
                "pending": pending,
                "in_flight_zmws": in_flight,
                "completed": completed,
                "errors": errors,
                "queue_depth": max(0, pending - in_flight),
                "slo_requests": int(_m_slo_requests.value),
                "slo_violations": int(_m_slo_violations.value),
            })

    def _ledger_worker(self, ledger) -> None:
        interval = max(self.config.perf_ledger_interval_s, 0.1)
        while not self._ledger_stop.wait(interval):
            try:
                ledger.append(self._ledger_record())
            except Exception as e:  # noqa: BLE001 -- the ledger must
                # never take the engine down (a failing append already
                # disabled itself with a counted warning)
                self._log.debug(f"perf ledger snapshot failed: {e!r}")

    # ---------------------------------------- status / metrics / trace

    def accepting(self) -> bool:
        """Cheap liveness for /healthz: False once close() began (the
        same figure the status verb reports)."""
        with self._lock:
            return not self._closed

    def status(self) -> dict:
        """Engine introspection for the protocol's `status` verb.  Stage
        and device-wait figures come from the engine's OWN measurement
        window (opened at start()), so concurrent windows elsewhere in
        the process cannot clobber them."""
        with self._lock:
            snap = dict(
                # False once close() began: the router's health probes
                # read this to stop routing to a draining replica before
                # its socket ever closes
                accepting=not self._closed,
                pending=self._pending,
                admitted=self._admitted,
                rejected=self._rejected,
                completed=self._completed,
                errors=self._errors,
                in_flight_batches=self._in_flight_batches,
                in_flight_zmws=self._in_flight_zmws,
            )
            pool = self._pool   # close() nulls this under the same lock
            ledger = self._ledger
        stage_s = {k: round(v, 4)
                   for k, v in timing.stage_seconds(self._window).items()}
        sched = {"sched": pool.status()} if pool is not None else {}
        # the status verb's perf block (protocol.FIELD_PERF): present
        # only when this process writes a ledger, federated fleet-wide
        # by `ccs router --perfLedger`
        perf = {"perf": ledger.perf_block()} if ledger is not None else {}
        # the status verb's roofline block (protocol.FIELD_ROOFLINE):
        # per-bucket CostCard bound + measured achieved/efficiency;
        # absent until the plane has a card or a charge
        rl_block = obs_roofline.tracker().status_block()
        rl = {"roofline": rl_block} if rl_block else {}
        return {
            "engine": "ccs-serve",
            **sched,
            **perf,
            **rl,
            "slo": self._slo_block(),
            "uptime_s": round(time.monotonic() - self._start_t, 3),
            "queue_depth": max(0, snap["pending"] - snap["in_flight_zmws"]),
            "bucketed": self._batcher.pending_count(),
            "depth_by_bucket": self._batcher.depth_by_bucket(),
            "max_pending": self.config.max_pending,
            "max_batch": self.config.max_batch,
            "max_wait_ms": self.config.max_wait_ms,
            "stage_seconds": stage_s,
            "device_wait_s": round(
                timing.device_wait_seconds(self._window), 4),
            "device_fetches": timing.fetch_count(self._window),
            "metrics": self.metrics_snapshot(),
            **snap,
        }

    def _slo_block(self) -> dict:
        """The status verb's SLO summary: the burn-rate pair plus an
        observed-p99 estimate from the latency histogram (bucket upper
        bound -- honest to within the log-bucket resolution)."""
        import math

        from pbccs_tpu.obs.metrics import histogram_quantile

        counts, _s, n = _m_latency.snapshot()
        p99 = histogram_quantile(counts, _m_latency.bounds, 0.99)
        requests = _m_slo_requests.value
        violations = _m_slo_violations.value
        return {
            "target_p99_ms": self.config.slo_p99_ms,
            "enabled": self.config.slo_p99_ms > 0,
            "requests": int(requests),
            "violations": int(violations),
            "violation_rate": round(violations / requests, 6)
            if requests else 0.0,
            "observed_p99_ms_le": round(p99 * 1e3, 3)
            if n and math.isfinite(p99) else None,
        }

    def metrics_text(self) -> str:
        """Prometheus text exposition of the process registry (the
        protocol's `metrics` verb scrapes this)."""
        return _reg.render_prometheus()

    def metrics_snapshot(self) -> dict:
        """Compact /metrics-style name->value snapshot (counters and
        gauges only; histograms ride the text exposition) for the
        `status` verb."""
        out = {}
        for (name, labels), (kind, val) in sorted(_reg.snapshot().items()):
            if kind == "histogram" or not name.startswith(
                    ("ccs_serve_", "ccs_batch_", "ccs_device_",
                     "ccs_retries_", "ccs_quarantine", "ccs_degraded_",
                     "ccs_watchdog_", "ccs_faults_", "ccs_sched_",
                     "ccs_slo_", "ccs_refine_", "ccs_flight_",
                     "ccs_metrics_", "ccs_roofline_", "ccs_tenant_")):
                continue
            suffix = "{%s}" % ",".join(
                f"{k}={v}" for k, v in labels) if labels else ""
            out[name + suffix] = round(val, 6)
        return out

    def trace_start(self) -> bool:
        """Install a process-wide capture tracer (the protocol's `trace`
        verb, action=start).  Returns False when a capture -- this
        engine's or anyone else's -- is already running."""
        with self._trace_lock:
            if self._capture is not None:
                return False
            cap = obs_trace.Tracer()
            if not obs_trace.install_tracer(cap):  # someone else's capture
                return False
            self._capture = cap
            return True

    def trace_stop(self) -> dict | None:
        """Stop the capture and return the Chrome-trace JSON object
        (None when no capture was running).  Clears the global tracer
        only if it is still OUR capture (CAS) -- never tears down a
        capture another owner installed since."""
        with self._trace_lock:
            cap, self._capture = self._capture, None
            if cap is None:
                return None
            obs_trace.clear_tracer(cap)
        return cap.to_chrome()
