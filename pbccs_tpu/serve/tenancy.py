"""Multi-tenant edge: identity, fairness, and accounting (ROADMAP item 4).

Three small planes, deliberately transport-free so router/server own the
sockets and this module owns the policy (mirroring protocol.py):

Identity -- a token file (`--authTokens FILE`, JSON) maps bearer tokens
to tenants.  Every frame at an authenticated front door must carry an
`auth` token (protocol.FIELD_AUTH); the session resolves it ONCE through
TenantDirectory.authenticate and caches the tenant.  The token is the
identity: a client-supplied `tenant` wire field is IGNORED unless the
authenticated tenant is marked `trusted` (the router's own link token),
which is how the router forwards the ORIGINAL tenant to replicas without
letting ordinary clients spoof each other.

Fairness -- FairQueue: per-tenant in-flight quotas with deficit-round-
robin drain.  A tenant under its quota dispatches immediately; over
quota its requests park in a bounded per-tenant queue (one flooding
tenant fills only its OWN queue, never another tenant's slots); past the
queue bound it gets a structured `overloaded` with a retry_after_ms
hint.  Freed capacity is granted to parked tenants in weighted DRR
order, so sustained contention converges to the configured weights
rather than to whoever submits fastest.

Accounting -- every admission outcome lands in the obs registry under
`ccs_tenant_*` (REG001-policed), and FairQueue.rows() feeds the status
verb's `tenancy` block, `ccs top`, and `tenant_snapshot` ledger records.

TLS helpers live here too (stdlib `ssl` only): one server context shape
shared by `ccs serve`/`ccs router`/the metrics endpoint, one client
context shape shared by CcsClient, router replica links, and the fleet
admin path.  Certificate verification is against the operator-provided
CA bundle (`--tlsCa`); hostname checking is off because fleets address
replicas by ephemeral host:port, not by certificate names -- the CA
pinning is the trust anchor.  Threat notes in docs/DESIGN.md
"Multi-tenant edge".
"""

from __future__ import annotations

import collections
import dataclasses
import json
import ssl
import threading
from typing import Any, Callable

from pbccs_tpu.obs.metrics import default_registry
from pbccs_tpu.serve import protocol

_reg = default_registry()

# armor bound on bearer tokens (mirrors protocol._TRACE_VALUE_MAX): the
# edge must not hash/compare attacker-chosen megabyte strings per frame
TOKEN_MAX_CHARS = 256


def count_auth_failure(reason: str) -> None:
    """One rejected frame at an authenticated front door, by reason
    (missing_token / bad_token / unknown_tenant)."""
    _reg.counter("ccs_tenant_auth_failures_total",
                 "Frames rejected by edge token auth, by reason",
                 reason=reason).inc()


def count_request(tenant: str) -> None:
    """One submit attributed to a tenant (counted at every tier that
    resolves an identity: router edge and, via the forwarded tenant
    field, each replica -- the federated exposition keeps them apart
    with the replica label)."""
    _reg.counter("ccs_tenant_requests_total",
                 "Submits attributed to a tenant", tenant=tenant).inc()


# ------------------------------------------------------------------ identity

@dataclasses.dataclass(frozen=True)
class Tenant:
    """One row of the token->tenant map.

    priority is a shed CLASS, 0 = highest: under SLO-burn shedding the
    router rejects work from priority >= 1 tenants first and NEVER
    sheds priority 0 (see CcsRouter).  weight scales the DRR quantum --
    a weight-2 tenant drains twice as fast as a weight-1 tenant when
    both are parked.  trusted marks infrastructure tokens (the router's
    replica-link token): only a trusted peer may forward another
    tenant's identity in the wire `tenant` field.  shed_burn_rate is an
    optional PER-TENANT SLO burn threshold: when set, this tenant is
    shed at its own rate instead of the fleet-wide --shedBurnRate (a
    latency-tolerant batch tenant can carry 0.5 while interactive
    tenants shed at the fleet default)."""

    name: str
    token: str
    max_inflight: int = 8
    priority: int = 1
    weight: int = 1
    trusted: bool = False
    shed_burn_rate: float | None = None


class TenantDirectory:
    """Immutable token->tenant map parsed from the --authTokens file.

    File format (README "Multi-tenant quickstart"):

        {"tenants": [
          {"name": "alpha", "token": "<secret>", "max_inflight": 8,
           "priority": 1, "weight": 1},
          {"name": "_router", "token": "<secret>", "priority": 0,
           "trusted": true}
        ]}

    max_inflight/priority/weight/trusted are optional with the Tenant
    defaults above.  Names and tokens must be unique; a malformed file
    is a startup error (ValueError), never a half-loaded directory.
    """

    def __init__(self, tenants: list[Tenant]):
        if not tenants:
            raise ValueError("token file declares no tenants")
        by_name: dict[str, Tenant] = {}
        by_token: dict[str, Tenant] = {}
        for t in tenants:
            if t.name in by_name:
                raise ValueError(f"duplicate tenant name {t.name!r}")
            if t.token in by_token:
                raise ValueError(f"duplicate token (tenant {t.name!r})")
            by_name[t.name] = t
            by_token[t.token] = t
        self._by_name = by_name
        self._by_token = by_token

    @classmethod
    def from_file(cls, path: str) -> "TenantDirectory":
        with open(path, encoding="utf-8") as f:
            try:
                doc = json.load(f)
            except json.JSONDecodeError as e:
                raise ValueError(f"token file is not JSON: {e}") from None
        if not isinstance(doc, dict) or not isinstance(doc.get("tenants"),
                                                       list):
            raise ValueError('token file must be {"tenants": [...]}')
        tenants = []
        for i, row in enumerate(doc["tenants"]):
            if not isinstance(row, dict):
                raise ValueError(f"tenants[{i}] must be an object")
            name, token = row.get("name"), row.get("token")
            if not isinstance(name, str) or not name:
                raise ValueError(f"tenants[{i}].name must be a non-empty "
                                 "string")
            if (not isinstance(token, str) or not token
                    or len(token) > TOKEN_MAX_CHARS):
                raise ValueError(
                    f"tenants[{i}].token must be a non-empty string "
                    f"(<= {TOKEN_MAX_CHARS} chars)")
            max_inflight = row.get("max_inflight", Tenant.max_inflight)
            priority = row.get("priority", Tenant.priority)
            weight = row.get("weight", Tenant.weight)
            trusted = row.get("trusted", Tenant.trusted)
            if (not isinstance(max_inflight, int) or max_inflight < 1
                    or isinstance(max_inflight, bool)):
                raise ValueError(f"tenants[{i}].max_inflight must be an "
                                 "int >= 1")
            if (not isinstance(priority, int) or priority < 0
                    or isinstance(priority, bool)):
                raise ValueError(f"tenants[{i}].priority must be an "
                                 "int >= 0 (0 = highest, never shed)")
            if (not isinstance(weight, int) or weight < 1
                    or isinstance(weight, bool)):
                raise ValueError(f"tenants[{i}].weight must be an int >= 1")
            if not isinstance(trusted, bool):
                raise ValueError(f"tenants[{i}].trusted must be a bool")
            burn = row.get("shed_burn_rate")
            if burn is not None:
                if (isinstance(burn, bool)
                        or not isinstance(burn, (int, float))
                        or not 0.0 <= burn <= 1.0):
                    raise ValueError(
                        f"tenants[{i}].shed_burn_rate must be a number "
                        "in [0, 1] (a violation fraction; omit to use "
                        "the fleet-wide --shedBurnRate)")
                burn = float(burn)
            tenants.append(Tenant(name=name, token=token,
                                  max_inflight=max_inflight,
                                  priority=priority, weight=weight,
                                  trusted=trusted, shed_burn_rate=burn))
        return cls(tenants)

    def authenticate(self, token: Any) -> Tenant | None:
        """Resolve a frame's bearer token; None on anything that is not
        a known token (the caller answers ERR_UNAUTHORIZED)."""
        if not isinstance(token, str) or not token \
                or len(token) > TOKEN_MAX_CHARS:
            return None
        return self._by_token.get(token)

    def get(self, name: str) -> Tenant | None:
        return self._by_name.get(name)

    def tenants(self) -> list[Tenant]:
        return list(self._by_name.values())


class ReloadableTenantDirectory:
    """A TenantDirectory that follows its --authTokens file online.

    Wraps the immutable directory with the reload policy ROADMAP item
    4's follow-on asks for: the map is re-read on SIGHUP
    (``install_sighup``) or when the file's mtime changes (checked at
    most once per ``recheck_s`` on the access path, so the per-frame
    auth cost is one monotonic-clock compare).  Semantics:

      * the FIRST load happens in the constructor and raises like
        ``TenantDirectory.from_file`` -- a malformed file is still a
        loud startup error;
      * a malformed or unreadable file at RELOAD time keeps the
        previous map (one warning + a
        ``ccs_tenant_map_reloads_total{outcome=error}`` count) -- an
        operator mid-edit must never take the front door down;
      * in-flight sessions keep their resolved identity (the session
        caches its Tenant); NEW frames resolve against the new map, so
        deleting a token revokes on the next frame (the per-frame
        re-auth in server._authenticate);
      * listeners registered with ``add_listener`` run after every
        successful swap (outside the lock) -- the router points
        ``FairQueue.refresh`` here so new tenants get admission state
        without a restart.
    """

    def __init__(self, path: str, *, recheck_s: float = 1.0,
                 logger=None, clock: Callable[[], float] | None = None):
        import time
        self._path = path
        self._recheck_s = recheck_s
        self._log = logger
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._inner = TenantDirectory.from_file(path)
        self._mtime = self._stat_mtime()
        self._next_check = self._clock() + recheck_s
        self._listeners: list[Callable[[TenantDirectory], None]] = []
        # written from the signal handler WITHOUT the lock (a handler
        # interrupting a lock holder on the main thread must not block)
        self._sighup = False

    def _stat_mtime(self) -> int | None:
        try:
            import os
            return os.stat(self._path).st_mtime_ns
        except OSError:
            return None

    def _logger(self):
        if self._log is not None:
            return self._log
        # load_edge_config builds the directory before the run installs
        # its leveled logger; resolve the process default lazily so
        # reload notes land in the real log, not a throwaway
        from pbccs_tpu.runtime.logging import Logger
        return Logger.default()

    def _warn(self, msg: str) -> None:
        self._logger().warn(msg)

    def add_listener(self, cb: Callable[[TenantDirectory], None]) -> None:
        with self._lock:
            self._listeners.append(cb)

    def install_sighup(self) -> bool:
        """Arm SIGHUP -> reload-on-next-access; False where signals are
        unavailable (non-main thread, platforms without SIGHUP)."""
        import signal
        if not hasattr(signal, "SIGHUP"):
            return False

        def _handler(signum, frame):
            self._sighup = True

        try:
            signal.signal(signal.SIGHUP, _handler)
        except ValueError:   # not the main thread
            return False
        return True

    def maybe_reload(self) -> bool:
        """One throttled reload check; True when a new map was swapped
        in.  Called from the access path (authenticate/get/tenants) and
        safe to call from anywhere -- failures degrade to the previous
        map, never to an exception."""
        now = self._clock()
        fresh = None
        with self._lock:
            hup, self._sighup = self._sighup, False
            if not hup and now < self._next_check:
                return False
            self._next_check = now + self._recheck_s
            mtime = self._stat_mtime()
            if not hup and (mtime is None or mtime == self._mtime):
                return False
            try:
                fresh = TenantDirectory.from_file(self._path)
            except (OSError, ValueError) as e:
                # remember the bad mtime so a broken edit warns once,
                # not once per recheck window
                self._mtime = mtime
                _reg.counter(
                    "ccs_tenant_map_reloads_total",
                    "Online --authTokens map reloads, by outcome",
                    outcome="error").inc()
                self._warn(f"--authTokens reload failed; keeping the "
                           f"previous map: {e}")
                return False
            self._inner = fresh
            self._mtime = mtime
            listeners = list(self._listeners)
        _reg.counter("ccs_tenant_map_reloads_total",
                     "Online --authTokens map reloads, by outcome",
                     outcome="ok").inc()
        self._logger().info(f"--authTokens map reloaded: "
                            f"{len(fresh.tenants())} tenant(s)")
        for cb in listeners:   # outside the lock: FairQueue.refresh
            cb(fresh)          # takes its own lock
        return True

    # -- the TenantDirectory surface, behind the reload check --------

    def authenticate(self, token: Any) -> Tenant | None:
        self.maybe_reload()
        with self._lock:
            inner = self._inner
        return inner.authenticate(token)

    def get(self, name: str) -> Tenant | None:
        self.maybe_reload()
        with self._lock:
            inner = self._inner
        return inner.get(name)

    def tenants(self) -> list[Tenant]:
        self.maybe_reload()
        with self._lock:
            inner = self._inner
        return inner.tenants()


def resolve_tenant(session_tenant: Tenant | None,
                   wire_tenant: dict[str, Any] | None) -> str | None:
    """The spoofing rule, in one place: the authenticated token's tenant
    IS the identity; the wire `tenant` field is honored only from a
    trusted peer (the router forwarding the original submitter to a
    replica).  Returns the effective tenant name, or None when the
    front door runs open (no token file)."""
    if session_tenant is None:
        return None
    if wire_tenant is not None and session_tenant.trusted:
        return wire_tenant[protocol.KEY_TENANT_NAME]
    return session_tenant.name


# ----------------------------------------------------------------------- TLS

def server_ssl_context(certfile: str, keyfile: str) -> ssl.SSLContext:
    """TLS context for an accepting front door (`--tlsCert/--tlsKey`):
    raises on unreadable/mismatched PEMs at startup, never mid-accept."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_cert_chain(certfile, keyfile)
    return ctx


def client_ssl_context(cafile: str | None) -> ssl.SSLContext:
    """TLS context for a connecting tier (`--tlsCa`): the CA bundle is
    the trust anchor (hostname checking off -- fleet members are
    addressed by ephemeral host:port, not certificate names).  With no
    CA the channel is encrypted but unauthenticated; operators should
    always pin the CA outside tests."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.check_hostname = False
    if cafile:
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(cafile)
    else:
        ctx.verify_mode = ssl.CERT_NONE
    return ctx


# ------------------------------------------------------------------ fairness

class _TenantState:
    """Mutable per-tenant admission state, owned by FairQueue's lock."""

    __slots__ = ("tenant", "inflight", "queue", "deficit", "completed",
                 "queued_total", "rejected", "shed")

    def __init__(self, tenant: Tenant):
        self.tenant = tenant
        self.inflight = 0
        self.queue: collections.deque = collections.deque()
        self.deficit = 0
        self.completed = 0
        self.queued_total = 0
        self.rejected = 0
        self.shed = 0


class FairQueue:
    """Weighted deficit-round-robin admission across tenants.

    Sits in FRONT of the router's sticky/spill routing: try_admit either
    grants a slot (tenant under quota), parks the item (bounded
    per-tenant queue), or rejects (queue full).  complete() returns a
    freed slot; drain() then hands parked items back out in DRR order --
    each round a parked tenant's deficit grows by weight * quantum and
    it releases items while deficit and quota allow, so weights govern
    drain share under contention and no tenant is ever starved (every
    tenant with backlog is visited every round).

    The queue has its own lock and never calls back into the router, so
    the router may use it under OR outside its own lock without
    inversion; dispatching drained items is the caller's job (outside
    any lock -- sends block)."""

    def __init__(self, directory: TenantDirectory, *,
                 queue_depth: int = 64, quantum: int = 4):
        self._lock = threading.Lock()
        self._queue_depth = max(1, queue_depth)
        self._quantum = max(1, quantum)
        self._states = {t.name: _TenantState(t)
                        for t in directory.tenants()}
        # DRR visiting order (fixed; leftover deficits, not the order,
        # carry fairness across rounds)
        self._ring = list(self._states)
        self._m_inflight = {
            n: _reg.gauge("ccs_tenant_inflight",
                          "Requests a tenant has in flight past admission",
                          tenant=n) for n in self._states}
        self._m_qdepth = {
            n: _reg.gauge("ccs_tenant_queue_depth",
                          "Requests parked in a tenant's fair queue",
                          tenant=n) for n in self._states}

    def _state(self, tenant: str) -> _TenantState | None:
        return self._states.get(tenant)

    def refresh(self, directory: "TenantDirectory") -> None:
        """Follow a reloaded token map (ReloadableTenantDirectory
        listener): NEW tenants get admission state + gauges so their
        first submit cannot KeyError; EXISTING tenants keep their
        counters, queue, and banked deficit but adopt the new quota/
        weight/priority on the next admission decision.  Tenants
        REMOVED from the map keep their state until it drains -- their
        tokens no longer authenticate, so no new work arrives, and
        in-flight completions still need the slot accounting."""
        with self._lock:
            for t in directory.tenants():
                st = self._states.get(t.name)
                if st is None:
                    self._states[t.name] = _TenantState(t)
                    self._ring.append(t.name)
                    self._m_inflight[t.name] = _reg.gauge(
                        "ccs_tenant_inflight",
                        "Requests a tenant has in flight past admission",
                        tenant=t.name)
                    self._m_qdepth[t.name] = _reg.gauge(
                        "ccs_tenant_queue_depth",
                        "Requests parked in a tenant's fair queue",
                        tenant=t.name)
                else:
                    st.tenant = t

    def try_admit(self, tenant: str, item: Any) -> str:
        """Admission verdict for one request: "dispatch" (slot granted,
        caller routes it now), "queued" (parked; drain() will release
        it), or "rejected" (per-tenant queue full -- caller answers
        overloaded + retry_after_ms)."""
        with self._lock:
            st = self._states[tenant]
            if st.inflight < st.tenant.max_inflight:
                st.inflight += 1
                self._m_inflight[tenant].set(st.inflight)
                return "dispatch"
            if len(st.queue) < self._queue_depth:
                st.queue.append(item)
                st.queued_total += 1
                self._m_qdepth[tenant].set(len(st.queue))
                _reg.counter("ccs_tenant_queued_total",
                             "Submits parked in the fair queue (over "
                             "quota, under queue bound)",
                             tenant=tenant).inc()
                return "queued"
            st.rejected += 1
            _reg.counter("ccs_tenant_rejects_total",
                         "Submits rejected at admission, by reason",
                         tenant=tenant, reason="quota").inc()
            return "rejected"

    def record_shed(self, tenant: str) -> None:
        with self._lock:
            st = self._states.get(tenant)
            if st is not None:
                st.shed += 1
        _reg.counter("ccs_tenant_rejects_total",
                     "Submits rejected at admission, by reason",
                     tenant=tenant, reason="shed").inc()

    def complete(self, tenant: str) -> None:
        """One admitted request finished (any outcome): free its slot.
        The caller should then drain() and dispatch what comes back."""
        with self._lock:
            st = self._states.get(tenant)
            if st is None:
                return
            st.inflight = max(0, st.inflight - 1)
            st.completed += 1
            self._m_inflight[tenant].set(st.inflight)
        _reg.counter("ccs_tenant_completed_total",
                     "Admitted requests completed, per tenant",
                     tenant=tenant).inc()

    def drain(self) -> list[tuple[str, Any]]:
        """Release parked items that now fit their tenant's quota, in
        weighted-DRR order; returns [(tenant, item), ...] for the
        caller to dispatch OUTSIDE any lock."""
        released: list[tuple[str, Any]] = []
        with self._lock:
            # rounds continue while any visit releases work: one freed
            # slot usually releases one item, a burst of completions
            # more.  Every backlogged tenant is visited every round, so
            # leftover deficit -- not visiting order -- carries fairness
            # across rounds AND across drain() calls.
            progressed = True
            while progressed:
                progressed = False
                for name in self._ring:
                    st = self._states[name]
                    if not st.queue:
                        st.deficit = 0   # no backlog -> no banked credit
                        continue
                    if st.inflight >= st.tenant.max_inflight:
                        # quota-bound, not bandwidth-bound: banking
                        # credit here would burst unfairly on free-up
                        continue
                    st.deficit += st.tenant.weight * self._quantum
                    while (st.queue and st.deficit > 0
                           and st.inflight < st.tenant.max_inflight):
                        st.inflight += 1
                        st.deficit -= 1
                        released.append((name, st.queue.popleft()))
                        progressed = True
                    self._m_inflight[name].set(st.inflight)
                    self._m_qdepth[name].set(len(st.queue))
        return released

    def flush(self) -> list[tuple[str, Any]]:
        """Empty every queue (router close): the caller fails the items
        with a structured `closed`."""
        out: list[tuple[str, Any]] = []
        with self._lock:
            for name, st in self._states.items():
                while st.queue:
                    out.append((name, st.queue.popleft()))
                self._m_qdepth[name].set(0)
        return out

    def rows(self) -> list[dict[str, Any]]:
        """Per-tenant accounting snapshot: the status verb's `tenancy`
        block, `ccs top`'s tenant table, and the router's
        tenant_snapshot ledger records all render these rows."""
        with self._lock:
            return [{
                "name": name,
                "priority": st.tenant.priority,
                "weight": st.tenant.weight,
                "max_inflight": st.tenant.max_inflight,
                "inflight": st.inflight,
                "queued": len(st.queue),
                "completed": st.completed,
                "queued_total": st.queued_total,
                "rejected": st.rejected,
                "shed": st.shed,
            } for name, st in sorted(self._states.items())]


# ------------------------------------------------------------- SLO burn meter

class BurnMeter:
    """Windowed fleet SLO burn rate from health-probe status replies.

    Each probe reply's `slo` block carries lifetime requests/violations
    counters; the meter differences them per replica and keeps the
    deltas in a sliding window, so rate() is the fleet-wide fraction of
    recent requests that violated the SLO -- the signal the router's
    shed policy thresholds on.  A replica restart (counters moving
    backwards) resets that replica's baseline instead of producing
    negative deltas."""

    def __init__(self, window_s: float = 30.0,
                 clock: Callable[[], float] | None = None):
        import time
        self._window_s = window_s
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._prev: dict[str, tuple[int, int]] = {}
        self._events: collections.deque = collections.deque()

    def observe(self, replica: str, slo_block: Any) -> None:
        if not isinstance(slo_block, dict):
            return
        req, vio = slo_block.get("requests"), slo_block.get("violations")
        if not isinstance(req, int) or not isinstance(vio, int):
            return
        now = self._clock()
        with self._lock:
            preq, pvio = self._prev.get(replica, (req, vio))
            self._prev[replica] = (req, vio)
            dreq, dvio = req - preq, vio - pvio
            if dreq < 0 or dvio < 0:   # replica restarted; re-baseline
                return
            if dreq > 0:
                self._events.append((now, dreq, dvio))
            self._trim_locked(now)

    def forget(self, replica: str) -> None:
        with self._lock:
            self._prev.pop(replica, None)

    def _trim_locked(self, now: float) -> None:
        while self._events and now - self._events[0][0] > self._window_s:
            self._events.popleft()

    def rate(self) -> float:
        """Fleet burn over the window: violations/requests in [0, 1];
        0.0 when the window is empty (no signal = no shedding)."""
        now = self._clock()
        with self._lock:
            self._trim_locked(now)
            req = sum(e[1] for e in self._events)
            vio = sum(e[2] for e in self._events)
        return (vio / req) if req > 0 else 0.0
