"""Python client for the CCS serving protocol.

One TCP session, many concurrent in-flight requests: `submit*` returns a
PendingReply immediately, a background reader thread re-associates the
out-of-order streamed replies by request id, and `.reply()` blocks the
caller until that request's result lands.  Thread-safe: any number of
caller threads may share one client (the load generator runs many).

Security: `tls_ca`/`tls` wrap the session in TLS (tenancy.
client_ssl_context -- CA-pinned verification, no hostname check), and
`auth_token` rides every frame as the `auth` bearer token an
authenticated front door requires.

Resilience: `submit_with_retry` rides out BOTH `overloaded`
backpressure (jittered exponential backoff, or the server's
`retry_after_ms` hint when a shed reply carries one) and connection
loss -- a
dropped socket fails the in-flight attempt with ConnectionError, the
next attempt reconnects to the same endpoint and RESUBMITS the payload
under a fresh request id (an unacknowledged submit is the client's to
replay; the server/router side dedups nothing because a new id is a new
request and polish is pure).  Every attempt cleans up after itself: a
reply that never came (timeout, exhaustion) discards its pending handle,
so no id dangles in the reply map holding a session in-flight slot."""

from __future__ import annotations

import socket
import threading
from typing import TYPE_CHECKING, Any, Sequence

import numpy as np

from pbccs_tpu.pipeline import Chunk
from pbccs_tpu.serve import protocol

if TYPE_CHECKING:
    from pbccs_tpu.resilience.retry import RetryPolicy


class ServeError(RuntimeError):
    """A structured error reply from the server.  `retry_after_ms`
    carries the server's backoff hint when the reply had one (shed /
    over-quota rejections); None otherwise."""

    def __init__(self, code: str, message: str,
                 retry_after_ms: float | None = None):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.retry_after_ms = retry_after_ms


class PendingReply:
    """Handle for one in-flight request."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._event = threading.Event()
        self._msg: dict[str, Any] | None = None
        self._gen = 0   # connection generation (set at registration)

    def _complete(self, msg: dict[str, Any]) -> None:
        self._msg = msg
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def reply(self, timeout: float | None = None,
              check: bool = True) -> dict[str, Any]:
        """The raw reply message; with check (default), error replies
        raise ServeError and a dropped connection raises ConnectionError."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"no reply for request {self.request_id!r}")
        msg = self._msg
        if check and msg.get("type") == protocol.TYPE_ERROR:
            hint = msg.get(protocol.FIELD_RETRY_AFTER)
            if not isinstance(hint, (int, float)) or isinstance(hint, bool) \
                    or hint < 0:
                hint = None
            raise ServeError(msg.get("code", "unknown"),
                             msg.get("error", ""), retry_after_ms=hint)
        if check and msg.get("type") == "__disconnected__":
            raise ConnectionError("server connection closed mid-stream")
        return msg


class CcsClient:
    """NDJSON/TCP client for `ccs serve` / `ccs router`
    (context-manager friendly)."""

    def __init__(self, host: str, port: int, timeout: float | None = None,
                 tls_ca: str | None = None, tls: bool = False,
                 auth_token: str | None = None):
        """`tls_ca` (a CA bundle path) connects over TLS and verifies
        the server against it; `tls=True` alone encrypts without
        verification (tests).  `auth_token` attaches the bearer token to
        EVERY outgoing frame -- the client-side half of the server's
        --authTokens contract."""
        self._host, self._port = host, port
        self._timeout = timeout
        self._auth_token = auth_token
        self._ssl_context = None
        if tls_ca is not None or tls:
            from pbccs_tpu.serve import tenancy

            self._ssl_context = tenancy.client_ssl_context(tls_ca)
        self._wlock = threading.Lock()
        self._plock = threading.Lock()
        self._pending: dict[str, PendingReply] = {}
        self._seq = 0
        self._gen = 0            # bumps on every (re)connect
        self._closed = False
        # serializes connect/reconnect (never held across a reply wait)
        self._conn_lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._reader: threading.Thread | None = None
        with self._conn_lock:
            self._open_locked()

    # ----------------------------------------------------------- plumbing

    def _open_locked(self) -> None:
        """(Re)open the transport; caller holds _conn_lock.  Any previous
        socket is closed DETERMINISTICALLY first (no half-open fd
        lingers behind a failed retry loop) and its reader joined, so
        its leftover handles fail before new ones register."""
        old_sock, old_reader = self._sock, self._reader
        self._sock = None
        if old_sock is not None:
            try:
                old_sock.close()
            except OSError:
                pass
        if old_reader is not None:
            old_reader.join(timeout=5.0)
        sock = socket.create_connection((self._host, self._port),
                                        timeout=30.0)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self._ssl_context is not None:
            # handshake under the connect timeout; a TLS failure surfaces
            # as the same ConnectionError shape a refused connect does
            try:
                sock = self._ssl_context.wrap_socket(
                    sock, server_hostname=self._host)
            except OSError as e:  # ssl.SSLError is an OSError
                try:
                    sock.close()
                except OSError:
                    pass
                raise ConnectionError(f"TLS handshake failed: {e}") from None
        sock.settimeout(self._timeout)
        self._gen += 1
        self._sock = sock
        self._reader = threading.Thread(
            target=self._read_loop, args=(sock, self._gen), daemon=True,
            name=f"ccs-client-reader-{self._gen}")
        self._reader.start()

    def _ensure_connected(self) -> None:
        """Reconnect when the transport died (reader exited).  Used by
        submit_with_retry between attempts; plain submits keep the
        original fail-fast behavior."""
        with self._conn_lock:
            if self._closed:
                raise ConnectionError("client is closed")
            if (self._sock is not None and self._reader is not None
                    and self._reader.is_alive()):
                return
            self._open_locked()

    def _next_id(self) -> str:
        with self._plock:
            self._seq += 1
            return f"r{self._seq}"

    def _discard(self, handle: PendingReply) -> None:
        """Drop a handle whose reply will never be consumed (timeout /
        retry exhaustion): a late reply then falls on the floor instead
        of completing into a map nobody reads, and the map cannot grow
        without bound under a retry loop."""
        with self._plock:
            self._pending.pop(handle.request_id, None)

    def _send(self, msg: dict[str, Any], handle: PendingReply) -> None:
        if self._auth_token is not None:
            # every frame authenticates (the server checks per-frame);
            # one injection point covers every verb
            msg.setdefault(protocol.FIELD_AUTH, self._auth_token)
        try:
            with self._wlock:
                # capture (sock, gen) and REGISTER under the write lock:
                # registering before it with a stale generation would let
                # a racing reconnect's leftover sweep fail this handle as
                # __disconnected__ even though the frame then goes out on
                # the NEW connection (_open_locked bumps _gen before
                # publishing the new socket, so a new sock implies the
                # matching gen here)
                sock = self._sock
                if sock is None:
                    raise OSError("no connection")
                if self._reader is not None and not self._reader.is_alive():
                    # the transport is known dead: a sendall could still
                    # "succeed" into the kernel buffer and park this
                    # handle forever (no reader will ever fail it)
                    raise OSError("connection closed")
                with self._plock:
                    handle._gen = self._gen
                    self._pending[handle.request_id] = handle
                sock.sendall(protocol.encode_msg(msg))
        except OSError as e:
            with self._plock:
                self._pending.pop(handle.request_id, None)
            raise ConnectionError(f"send failed: {e}") from None

    def _read_loop(self, sock: socket.socket, gen: int) -> None:
        try:
            with sock.makefile("rb") as rf:
                for line in rf:
                    if not line.strip():
                        continue
                    try:
                        msg = protocol.decode_line(line)
                    except protocol.ProtocolError:
                        continue  # never kill the reader on one bad frame
                    rid = msg.get("id")
                    with self._plock:
                        handle = self._pending.pop(rid, None)
                    if handle is not None:
                        handle._complete(msg)
        except OSError:
            pass
        finally:
            # fail whatever THIS connection still owes so callers
            # unblock; handles registered on a newer connection (a
            # racing reconnect) are someone else's to answer
            with self._plock:
                leftovers = [h for h in self._pending.values()
                             if h._gen <= gen]
                for h in leftovers:
                    self._pending.pop(h.request_id, None)
            for handle in leftovers:
                handle._complete({"type": "__disconnected__",
                                  "id": handle.request_id})

    # ------------------------------------------------------------- verbs

    def submit_wire(self, zmw: dict[str, Any],
                    deadline_ms: float | None = None,
                    trace: dict[str, Any] | None = None) -> PendingReply:
        """Submit an already-wire-shaped ZMW dict.  `trace` attaches a
        distributed-trace context ({"trace_id", "span_id"}) to the
        frame; when omitted and a span capture is live on THIS process,
        the calling thread's innermost span's context is attached
        automatically, so a traced load generator's requests carry their
        trace across the wire with no per-call plumbing."""
        if trace is None:
            from pbccs_tpu.obs import trace as obs_trace

            trace = obs_trace.current_context()
        handle = PendingReply(self._next_id())
        msg: dict[str, Any] = {"verb": protocol.VERB_SUBMIT,
                               "id": handle.request_id, "zmw": zmw}
        if deadline_ms is not None:
            msg["deadline_ms"] = deadline_ms
        if trace is not None:
            msg[protocol.FIELD_TRACE] = trace
        self._send(msg, handle)
        return handle

    def submit_chunk(self, chunk: Chunk,
                     deadline_ms: float | None = None,
                     trace: dict[str, Any] | None = None) -> PendingReply:
        return self.submit_wire(protocol.chunk_to_wire(chunk), deadline_ms,
                                trace)

    def submit(self, zmw_id: str, reads: Sequence[str],
               snr: Sequence[float] | None = None,
               deadline_ms: float | None = None) -> PendingReply:
        """Convenience: sequences as strings, default full-pass flags."""
        snr = [8.0] * 4 if snr is None else [float(s) for s in np.asarray(snr)]
        zmw = {"id": zmw_id, "snr": snr,
               "reads": [{"seq": s} for s in reads]}
        return self.submit_wire(zmw, deadline_ms)

    def submit_with_retry(self, zmw: Chunk | dict[str, Any],
                          deadline_ms: float | None = None,
                          policy: "RetryPolicy | None" = None,
                          reply_timeout: float | None = 600.0,
                          trace: dict[str, Any] | None = None
                          ) -> dict[str, Any]:
        """Submit one ZMW, riding out `overloaded` backpressure AND
        connection loss: an overloaded rejection re-submits with
        jittered exponential backoff (resilience.retry.OVERLOADED_RETRY
        by default -- bounded attempts AND a wall deadline); a dropped
        connection reconnects and resubmits the unacknowledged payload
        under a fresh request id.  Blocks until the final reply; returns
        the reply message.  Non-retryable errors raise immediately;
        exhausted retries raise retry.RetriesExhausted from the last
        structured error, with no request id left dangling in the reply
        map in any exit path."""
        from pbccs_tpu.resilience import retry as retry_mod

        policy = policy or retry_mod.OVERLOADED_RETRY
        wire = protocol.chunk_to_wire(zmw) if isinstance(zmw, Chunk) else zmw

        def attempt() -> dict[str, Any]:
            self._ensure_connected()
            # the retry attempt reuses the SAME trace context: a
            # resubmitted payload is the same logical request, and one
            # trace_id must tell its whole retry story
            handle = self.submit_wire(wire, deadline_ms, trace)
            try:
                return handle.reply(reply_timeout)
            finally:
                if not handle.done():
                    # timed out / interrupted: never leave the id parked
                    # in the reply map (it would pin a server-session
                    # in-flight slot to a reply nobody consumes)
                    self._discard(handle)

        def hint(e: BaseException) -> float | None:
            # honor the server's shed/over-quota pacing hint (seconds);
            # RetryPolicy caps + jitters it, so a hostile hint cannot
            # park the client and a fleet of clients decorrelates
            ms = getattr(e, "retry_after_ms", None)
            return ms / 1e3 if ms is not None else None

        return policy.run(
            attempt,
            # a deliberately-closed client must fail fast, not burn the
            # retry budget reconnect-looping against itself
            retry_on=lambda e: (isinstance(e, ConnectionError)
                                and not self._closed)
            or (isinstance(e, ServeError)
                and e.code == protocol.ERR_OVERLOADED),
            site="client.submit", delay_hint=hint)

    def status(self, timeout: float | None = 30.0) -> dict[str, Any]:
        handle = PendingReply(self._next_id())
        self._send({"verb": protocol.VERB_STATUS, "id": handle.request_id},
                   handle)
        return handle.reply(timeout)

    def metrics(self, timeout: float | None = 30.0) -> str:
        """Prometheus text-format metrics scrape (the `metrics` verb)."""
        handle = PendingReply(self._next_id())
        self._send({"verb": protocol.VERB_METRICS,
                    "id": handle.request_id}, handle)
        return handle.reply(timeout).get("body", "")

    def trace(self, action: str,
              timeout: float | None = 30.0) -> dict[str, Any]:
        """Start/stop a server-side span capture; a stop reply carries
        the Chrome-trace JSON under "trace"."""
        handle = PendingReply(self._next_id())
        self._send({"verb": protocol.VERB_TRACE, "id": handle.request_id,
                    "action": action}, handle)
        return handle.reply(timeout)

    def ping(self, timeout: float | None = 30.0) -> None:
        handle = PendingReply(self._next_id())
        self._send({"verb": protocol.VERB_PING, "id": handle.request_id},
                   handle)
        handle.reply(timeout)

    # ---------------------------------------------------------- lifecycle

    def close(self) -> None:
        with self._conn_lock:
            if self._closed:
                return
            self._closed = True
            sock, reader = self._sock, self._reader
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if reader is not None:
            reader.join(timeout=5.0)

    def __enter__(self) -> "CcsClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
