"""Python client for the CCS serving protocol.

One TCP session, many concurrent in-flight requests: `submit*` returns a
PendingReply immediately, a background reader thread re-associates the
out-of-order streamed replies by request id, and `.reply()` blocks the
caller until that request's result lands.  Thread-safe: any number of
caller threads may share one client (the load generator runs many).
"""

from __future__ import annotations

import socket
import threading
from typing import TYPE_CHECKING, Any, Sequence

import numpy as np

from pbccs_tpu.pipeline import Chunk
from pbccs_tpu.serve import protocol

if TYPE_CHECKING:
    from pbccs_tpu.resilience.retry import RetryPolicy


class ServeError(RuntimeError):
    """A structured error reply from the server."""

    def __init__(self, code: str, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


class PendingReply:
    """Handle for one in-flight request."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._event = threading.Event()
        self._msg: dict[str, Any] | None = None

    def _complete(self, msg: dict[str, Any]) -> None:
        self._msg = msg
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def reply(self, timeout: float | None = None,
              check: bool = True) -> dict[str, Any]:
        """The raw reply message; with check (default), error replies
        raise ServeError and a dropped connection raises ConnectionError."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"no reply for request {self.request_id!r}")
        msg = self._msg
        if check and msg.get("type") == protocol.TYPE_ERROR:
            raise ServeError(msg.get("code", "unknown"),
                             msg.get("error", ""))
        if check and msg.get("type") == "__disconnected__":
            raise ConnectionError("server connection closed mid-stream")
        return msg


class CcsClient:
    """NDJSON/TCP client for `ccs serve` (context-manager friendly)."""

    def __init__(self, host: str, port: int, timeout: float | None = None):
        self._sock = socket.create_connection((host, port), timeout=30.0)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(timeout)
        self._wlock = threading.Lock()
        self._plock = threading.Lock()
        self._pending: dict[str, PendingReply] = {}
        self._seq = 0
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name="ccs-client-reader")
        self._reader.start()

    # ----------------------------------------------------------- plumbing

    def _next_id(self) -> str:
        with self._plock:
            self._seq += 1
            return f"r{self._seq}"

    def _send(self, msg: dict[str, Any], handle: PendingReply) -> None:
        with self._plock:
            self._pending[handle.request_id] = handle
        try:
            with self._wlock:
                self._sock.sendall(protocol.encode_msg(msg))
        except OSError as e:
            with self._plock:
                self._pending.pop(handle.request_id, None)
            raise ConnectionError(f"send failed: {e}") from None

    def _read_loop(self) -> None:
        try:
            with self._sock.makefile("rb") as rf:
                for line in rf:
                    if not line.strip():
                        continue
                    try:
                        msg = protocol.decode_line(line)
                    except protocol.ProtocolError:
                        continue  # never kill the reader on one bad frame
                    rid = msg.get("id")
                    with self._plock:
                        handle = self._pending.pop(rid, None)
                    if handle is not None:
                        handle._complete(msg)
        except OSError:
            pass
        finally:
            # fail whatever is still waiting so callers unblock
            with self._plock:
                leftovers = list(self._pending.values())
                self._pending.clear()
            for handle in leftovers:
                handle._complete({"type": "__disconnected__",
                                  "id": handle.request_id})

    # ------------------------------------------------------------- verbs

    def submit_wire(self, zmw: dict[str, Any],
                    deadline_ms: float | None = None) -> PendingReply:
        """Submit an already-wire-shaped ZMW dict."""
        handle = PendingReply(self._next_id())
        msg: dict[str, Any] = {"verb": protocol.VERB_SUBMIT,
                               "id": handle.request_id, "zmw": zmw}
        if deadline_ms is not None:
            msg["deadline_ms"] = deadline_ms
        self._send(msg, handle)
        return handle

    def submit_chunk(self, chunk: Chunk,
                     deadline_ms: float | None = None) -> PendingReply:
        return self.submit_wire(protocol.chunk_to_wire(chunk), deadline_ms)

    def submit(self, zmw_id: str, reads: Sequence[str],
               snr: Sequence[float] | None = None,
               deadline_ms: float | None = None) -> PendingReply:
        """Convenience: sequences as strings, default full-pass flags."""
        snr = [8.0] * 4 if snr is None else [float(s) for s in np.asarray(snr)]
        zmw = {"id": zmw_id, "snr": snr,
               "reads": [{"seq": s} for s in reads]}
        return self.submit_wire(zmw, deadline_ms)

    def submit_with_retry(self, zmw: Chunk | dict[str, Any],
                          deadline_ms: float | None = None,
                          policy: "RetryPolicy | None" = None,
                          reply_timeout: float | None = 600.0
                          ) -> dict[str, Any]:
        """Submit one ZMW, honoring `overloaded` backpressure: an
        overloaded rejection re-submits with jittered exponential backoff
        (resilience.retry.OVERLOADED_RETRY by default -- bounded attempts
        AND a wall deadline), so a client fleet sheds load instead of
        hammering a full engine.  Blocks until the final reply; returns
        the reply message.  Non-overloaded errors raise immediately;
        exhausted retries raise retry.RetriesExhausted from the last
        overloaded rejection."""
        from pbccs_tpu.resilience import retry as retry_mod

        policy = policy or retry_mod.OVERLOADED_RETRY
        wire = protocol.chunk_to_wire(zmw) if isinstance(zmw, Chunk) else zmw

        def attempt() -> dict[str, Any]:
            return self.submit_wire(wire, deadline_ms).reply(reply_timeout)

        return policy.run(
            attempt,
            retry_on=lambda e: isinstance(e, ServeError)
            and e.code == protocol.ERR_OVERLOADED,
            site="client.submit")

    def status(self, timeout: float | None = 30.0) -> dict[str, Any]:
        handle = PendingReply(self._next_id())
        self._send({"verb": protocol.VERB_STATUS, "id": handle.request_id},
                   handle)
        return handle.reply(timeout)

    def metrics(self, timeout: float | None = 30.0) -> str:
        """Prometheus text-format metrics scrape (the `metrics` verb)."""
        handle = PendingReply(self._next_id())
        self._send({"verb": protocol.VERB_METRICS,
                    "id": handle.request_id}, handle)
        return handle.reply(timeout).get("body", "")

    def trace(self, action: str,
              timeout: float | None = 30.0) -> dict[str, Any]:
        """Start/stop a server-side span capture; a stop reply carries
        the Chrome-trace JSON under "trace"."""
        handle = PendingReply(self._next_id())
        self._send({"verb": protocol.VERB_TRACE, "id": handle.request_id,
                    "action": action}, handle)
        return handle.reply(timeout)

    def ping(self, timeout: float | None = 30.0) -> None:
        handle = PendingReply(self._next_id())
        self._send({"verb": protocol.VERB_PING, "id": handle.request_id},
                   handle)
        handle.reply(timeout)

    # ---------------------------------------------------------- lifecycle

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._reader.join(timeout=5.0)

    def __enter__(self) -> "CcsClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
