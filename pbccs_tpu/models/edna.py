"""Edna: channel-space pulse evaluator (experimental basecaller-adjacent
model; reference ConsensusCore/include/ConsensusCore/Edna/EdnaEvaluator.hpp,
EdnaConfig.hpp:46-67).  Not used by the CCS pipeline; exported for API
parity with the reference's SWIG surface.

The model works on channel observations (1..4; 0 = dark/deletion) against a
channel-space template: per template base a stay probability pStay, a merge
probability pMerge (when the next template channel matches), and move/stay
emission distributions over the 5 observation symbols.  Move scores are
log-space, matching QvEvaluator's interface so the Quiver recursor
machinery applies."""

from __future__ import annotations

import dataclasses

import numpy as np


def _log(x: float) -> float:
    """log with an exact -inf at zero (a zero-probability move is a legal
    score, not a RuntimeWarning)."""
    return float(np.log(x)) if x > 0.0 else float("-inf")


@dataclasses.dataclass(frozen=True)
class EdnaModelParams:
    """pStay/pMerge per template base (4,), move/stay emission tables
    (4, 5) over observations {0=dark, 1..4=channels}
    (reference EdnaConfig.hpp:46-67)."""

    p_stay: tuple
    p_merge: tuple
    move_dists: tuple     # flattened (4, 5) row-major, as in the reference
    stay_dists: tuple

    def move_dist(self, tpl_base: int, obs: int) -> float:
        return self.move_dists[(tpl_base - 1) * 5 + obs]

    def stay_dist(self, tpl_base: int, obs: int) -> float:
        return self.stay_dists[(tpl_base - 1) * 5 + obs]


class EdnaEvaluator:
    """Move scores for one (channel read, channel template) pair
    (reference EdnaEvaluator.hpp:70-262)."""

    def __init__(self, channels: np.ndarray, channel_tpl: np.ndarray,
                 params: EdnaModelParams, pin_start: bool = True,
                 pin_end: bool = True):
        self.channels = np.asarray(channels, np.int32)
        self.tpl = np.asarray(channel_tpl, np.int32)
        self.params = params
        self.pin_start = pin_start
        self.pin_end = pin_end

    def read_length(self) -> int:
        return len(self.channels)

    def template_length(self) -> int:
        return len(self.tpl)

    def _tpl_base(self, j: int) -> int:
        return int(self.tpl[j]) if j < len(self.tpl) else 1

    def _p_stay(self, j: int) -> float:
        return self.params.p_stay[self._tpl_base(j) - 1]

    def _p_merge(self, j: int) -> float:
        if j < len(self.tpl) - 1 and self.tpl[j] == self.tpl[j + 1]:
            return self.params.p_merge[self._tpl_base(j) - 1]
        return 0.0

    def is_match(self, i: int, j: int) -> bool:
        return bool(self.channels[i] == self.tpl[j])

    def inc(self, i: int, j: int) -> float:
        ps = self._p_stay(j)
        pm = (1.0 - ps) * self._p_merge(j)
        trans = 1.0 - ps - pm
        em = self.params.move_dist(self._tpl_base(j), int(self.channels[i]))
        return _log(trans * em)

    def delete(self, i: int, j: int) -> float:
        if (not self.pin_start and i == 0) or \
                (not self.pin_end and i == self.read_length()):
            return 0.0
        ps = self._p_stay(j)
        pm = (1.0 - ps) * self._p_merge(j)
        trans = 1.0 - ps - pm
        em = self.params.move_dist(self._tpl_base(j), 0)
        return _log(trans * em)

    def extra(self, i: int, j: int) -> float:
        trans = self._p_stay(j)
        em = self.params.stay_dist(self._tpl_base(j), int(self.channels[i]))
        return _log(trans * em)

    def merge(self, i: int, j: int) -> float:
        """Merge move score, *including* the pulse emission so merge() and
        score_move(j, j+2, obs) agree.  (Documented deviation: the
        reference's Edna Merge() drops the emission term,
        EdnaEvaluator.hpp:222-237, which disagrees with its own ScoreMove
        and leaves the forward probability unnormalized; Edna is flagged
        experimental there.)"""
        if not (j < len(self.tpl) - 1 and self.channels[i] == self.tpl[j]
                and self.channels[i] == self.tpl[j + 1]):
            return -np.inf
        ps = self._p_stay(j)
        pm = (1.0 - ps) * self._p_merge(j)
        em = self.params.move_dist(self._tpl_base(j + 1), int(self.channels[i]))
        return _log(pm * em)

    def score_move(self, j1: int, j2: int, obs: int) -> float:
        """Transition+emission log score for moving template j1 -> j2 while
        observing `obs` (reference EdnaEvaluator.hpp:239-262)."""
        ps = self._p_stay(j1)
        pm = (1.0 - ps) * self._p_merge(j1)
        if j1 == j2:
            return _log(ps * self.params.stay_dist(self._tpl_base(j1), obs))
        if j1 + 1 == j2:
            trans = 1.0 - ps - pm
            return _log(trans * self.params.move_dist(self._tpl_base(j1), obs))
        if j1 + 2 == j2:
            return _log(pm * self.params.move_dist(self._tpl_base(j1 + 1), obs))
        raise ValueError("moves advance the template by 0, 1 or 2")

    def loglik(self) -> float:
        """Dense forward log-likelihood over the full move set (the Edna
        counterpart of the Quiver dense oracle); shares the edna_fill
        recursion so the oracle and the counts machinery cannot drift."""
        alpha, _ = edna_fill(self)
        return float(alpha[self.read_length(), self.template_length()])


def _transition(ev: EdnaEvaluator, i: int, j1: int, j2: int,
                obs: int) -> float:
    """Log score of the model transition from (i*, j1) to j2 observing
    `obs` (0 = dark, consuming no pulse; else consuming pulse i).  The ONE
    definition of the move set, shared by edna_fill and edna_counts so the
    posterior counts always partition the fill's total:

      j1 -> j1+1 pulse: move (score_move);  dark: delete() (pin-aware)
      j1 -> j1   pulse: stay (final column clamps params); dark: no move
      j1 -> j1+2 pulse: merge() (match-gated);             dark: no move
    """
    J = ev.template_length()
    if j2 == j1 + 1:
        return ev.score_move(j1, j2, obs) if obs else ev.delete(i, j1)
    if j2 == j1:
        jj = min(j1, J - 1)
        return ev.score_move(jj, jj, obs) if obs else -np.inf
    if j2 == j1 + 2:
        return ev.merge(i, j1) if obs else -np.inf
    raise ValueError("moves advance the template by 0, 1 or 2")


def edna_fill(ev: EdnaEvaluator) -> tuple[np.ndarray, np.ndarray]:
    """Dense log-space alpha/beta for the Edna pair-HMM.

    alpha[i, j] = log P(first i pulses consumed, positioned at template
    column j); transitions INTO a column carry their emission (score_move
    semantics), so beta[i, j] = log P(remaining pulses | at (i, j)) with
    the arrival emission excluded -- exactly the decomposition
    EdnaCounts.DoCount sums over (alpha(i,j1) + ScoreMove(j1,j2,obs) +
    beta(i',j2))."""
    I, J = ev.read_length(), ev.template_length()
    obs = ev.channels
    alpha = np.full((I + 1, J + 1), -np.inf)
    alpha[0, 0] = 0.0
    for j in range(J + 1):
        for i in range(I + 1):
            if i == 0 and j == 0:
                continue
            acc = -np.inf
            if j >= 1 and i >= 1:          # move consuming a pulse
                acc = np.logaddexp(acc, alpha[i - 1, j - 1]
                                   + _transition(ev, i - 1, j - 1, j,
                                                 int(obs[i - 1])))
            if j >= 1:                     # move consuming a dark
                acc = np.logaddexp(acc, alpha[i, j - 1]
                                   + _transition(ev, i, j - 1, j, 0))
            if i >= 1:                     # stay emitting a pulse
                acc = np.logaddexp(acc, alpha[i - 1, j]
                                   + _transition(ev, i - 1, j, j,
                                                 int(obs[i - 1])))
            if j >= 2 and i >= 1:          # merge (2-column move)
                acc = np.logaddexp(acc, alpha[i - 1, j - 2]
                                   + _transition(ev, i - 1, j - 2, j,
                                                 int(obs[i - 1])))
            alpha[i, j] = acc

    beta = np.full((I + 1, J + 1), -np.inf)
    beta[I, J] = 0.0
    for j in range(J, -1, -1):
        for i in range(I, -1, -1):
            if i == I and j == J:
                continue
            acc = -np.inf
            if j < J and i < I:
                acc = np.logaddexp(acc, beta[i + 1, j + 1]
                                   + _transition(ev, i, j, j + 1, int(obs[i])))
            if j < J:
                acc = np.logaddexp(acc, beta[i, j + 1]
                                   + _transition(ev, i, j, j + 1, 0))
            if i < I:
                acc = np.logaddexp(acc, beta[i + 1, j]
                                   + _transition(ev, i, j, j, int(obs[i])))
            if j + 2 <= J and i < I:
                acc = np.logaddexp(acc, beta[i + 1, j + 2]
                                   + _transition(ev, i, j, j + 2, int(obs[i])))
            beta[i, j] = acc
    return alpha, beta


def edna_counts(ev: EdnaEvaluator, alpha: np.ndarray, beta: np.ndarray,
                j1: int, j2: int) -> np.ndarray:
    """(5,) log-space posterior transition masses from template column j1 to
    j2, split by observed channel (0 = dark) -- the training statistic of
    the reference's EdnaCounts::DoCount (EdnaCounts.cpp:68-105):

      results[0]    = logsum_i alpha(i, j1) + ScoreMove(j1, j2, 0)
                                            + beta(i, j2)
      results[base] = logsum_i alpha(i, j1) + ScoreMove(j1, j2, base)
                                            + beta(i+1, j2)
    """
    I = ev.read_length()
    results = np.full(5, -np.inf)
    for i in range(I + 1):
        results[0] = np.logaddexp(
            results[0], alpha[i, j1] + _transition(ev, i, j1, j2, 0)
            + beta[i, j2])
    for i in range(I):
        base = int(ev.channels[i])
        results[base] = np.logaddexp(
            results[base], alpha[i, j1] + _transition(ev, i, j1, j2, base)
            + beta[i + 1, j2])
    return results

