"""One-shot read-vs-template scoring convenience.

Parity: Arrow/Quiver ReadScorer (reference ConsensusCore/include/
ConsensusCore/Arrow/ReadScorer.hpp:50-74, src/C++/Arrow/ReadScorer.cpp and
the Quiver-namespace twin): construct the banded forward matrix for one
(read, template) pair and return the log-likelihood, without standing up a
multi-read scorer."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from pbccs_tpu.models.arrow.params import ArrowConfig, effective_band_width, \
    encode_bases, snr_to_transition_table_host, template_transition_params
from pbccs_tpu.ops.fwdbwd import banded_forward, forward_loglik
from pbccs_tpu.utils import next_pow2


def _codes(seq) -> np.ndarray:
    if isinstance(seq, str):
        return encode_bases(seq)
    return np.asarray(seq, np.int8)





def score_read(read, template, snr, config: ArrowConfig | None = None) -> float:
    """log P(read | template) under the Arrow pair-HMM
    (ReadScorer::Score, Arrow/ReadScorer.cpp)."""
    config = config or ArrowConfig()
    read_c = _codes(read)
    tpl_c = _codes(template)
    imax = next_pow2(len(read_c) + 8)
    jmax = next_pow2(len(tpl_c) + 8)
    rpad = np.full(imax, 4, np.int8)
    rpad[: len(read_c)] = read_c
    tpad = np.full(jmax, 4, np.int8)
    tpad[: len(tpl_c)] = tpl_c
    table = jnp.asarray(snr_to_transition_table_host(np.asarray(snr, np.float64)),
                        jnp.float32)
    trans = template_transition_params(jnp.asarray(tpad), table,
                                       jnp.int32(len(tpl_c)))
    alpha = banded_forward(jnp.asarray(rpad), jnp.int32(len(read_c)),
                           jnp.asarray(tpad), trans, jnp.int32(len(tpl_c)),
                           effective_band_width(config.banding, jmax))
    return float(forward_loglik(alpha, len(read_c), len(tpl_c)))


def score_read_quiver(features, template, config=None) -> float:
    """log P(read | template) under the Quiver model
    (Quiver/ReadScorer.cpp)."""
    from pbccs_tpu.models.quiver.params import QuiverConfig
    from pbccs_tpu.models.quiver.recursor import (
        feature_arrays, quiver_forward, quiver_loglik)

    config = config or QuiverConfig()
    tpl_c = _codes(template)
    imax = next_pow2(len(features) + 8)
    jmax = next_pow2(len(tpl_c) + 8)
    tpad = np.full(jmax, 4, np.int8)
    tpad[: len(tpl_c)] = tpl_c
    fa = feature_arrays(features, imax)
    alpha = quiver_forward(fa, jnp.int32(len(features)), jnp.asarray(tpad),
                           jnp.int32(len(tpl_c)), config)
    return float(quiver_loglik(alpha, len(features), len(tpl_c)))
