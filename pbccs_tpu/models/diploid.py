"""Heterozygous site detection from per-read mutation score matrices.

Parity: Arrow/Quiver Diploid (reference ConsensusCore/src/C++/Arrow/
Diploid.cpp:95-238; the Quiver-namespace copy is identical math): given a
(reads x genotypes) site score matrix whose first column is the no-op
allele, compare Pr(R | hom) = logsumexp_g sum_i S[i,g] against
Pr(R | het) = logsumexp over same-length-diff genotype pairs of
sum_i logaddexp(S[i,g0], S[i,g1]) - I*log2, and call the site heterozygous
when the log Bayes factor beats the prior ratio.

Vectorized over sites as array ops so batches of candidate sites evaluate
in one call (the reference evaluates one site at a time through SWIG)."""

from __future__ import annotations

import dataclasses

import numpy as np

# per-genotype template length deltas for the standard 9-mutation site
# basis: 4 substitutions, 4 insertions, 1 deletion
# (reference Diploid.cpp:97)
LENGTH_DIFFS = np.array([0, 0, 0, 0, 1, 1, 1, 1, -1])


@dataclasses.dataclass
class DiploidSite:
    allele0: int
    allele1: int
    log_bayes_factor: float
    allele_for_read: np.ndarray


def homozygous_loglik(site_scores: np.ndarray) -> float:
    """logsumexp over genotypes of the summed per-read scores
    (Diploid.cpp:122-133)."""
    g_scores = site_scores.sum(axis=0)
    return float(_logsumexp(g_scores))


def heterozygous_loglik(site_scores: np.ndarray,
                        length_diffs: np.ndarray | None = None):
    """logsumexp over valid genotype pairs; returns (ll, allele0, allele1)
    (Diploid.cpp:138-176).  Pairs must have equal template length deltas so
    the het hypothesis compares alleles of the same coordinate frame."""
    ld = LENGTH_DIFFS if length_diffs is None else np.asarray(length_diffs)
    I, G = site_scores.shape
    pair_scores = []
    pairs = []
    for g0 in range(G):
        for g1 in range(g0 + 1, G):
            if ld[g0] != ld[g1]:
                continue
            total = -I * np.log(2.0) + np.logaddexp(
                site_scores[:, g0], site_scores[:, g1]).sum()
            pair_scores.append(total)
            pairs.append((g0, g1))
    if not pairs:
        return -np.inf, -1, -1
    pair_scores = np.asarray(pair_scores)
    best = int(np.argmax(pair_scores))
    return float(_logsumexp(pair_scores)), pairs[best][0], pairs[best][1]


def assign_reads_to_alleles(site_scores: np.ndarray, allele0: int,
                            allele1: int) -> np.ndarray:
    """Per-read hard assignment to the likelier allele (Diploid.cpp:203-212)."""
    return np.where(site_scores[:, allele0] > site_scores[:, allele1], 0, 1)


def is_site_heterozygous(site_scores: np.ndarray, log_prior_ratio: float = 0.0,
                         length_diffs: np.ndarray | None = None) -> DiploidSite | None:
    """Bayes-factor het test (Diploid.cpp:218-238); None if homozygous.

    site_scores: (reads, genotypes) log-likelihood deltas with column 0 the
    no-op allele; log_prior_ratio = log Pr(hom)/Pr(het) >= 0."""
    site_scores = np.asarray(site_scores, np.float64)
    hom = homozygous_loglik(site_scores)
    het, a0, a1 = heterozygous_loglik(site_scores, length_diffs)
    log_bf = het - hom
    if log_bf - log_prior_ratio > 0:
        return DiploidSite(a0, a1, float(log_bf),
                           assign_reads_to_alleles(site_scores, a0, a1))
    return None


def _logsumexp(x: np.ndarray) -> float:
    m = np.max(x)
    if not np.isfinite(m):
        return m
    return m + np.log(np.exp(x - m).sum())
