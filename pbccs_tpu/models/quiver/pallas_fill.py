"""Pallas TPU path for the banded Quiver fills.

Same two-stage design as the Arrow fill kernel (ops/fwdbwd_pallas): an XLA
coefficient precompute turns the Quiver recurrence
(reference ConsensusCore/src/C++/Quiver/SimpleRecursor.cpp:62-231, move
scores QvEvaluator.hpp:160-207) into per-column CIRCULAR-lane band
coefficients (fwdbwd.BandedMatrix: cell (i, j) at lane i mod W)

    col[L] = cm[L] * roll(prev, 1)[L]       (Incorporate)
           + cd[L] * prev[L]                (Delete)
           + cg[L] * roll(prev2, 1)[L] / scale_prev   (Merge, j-2)
           + cc[L] * col[L-1 circ]          (Extra, in-column)

with all band-membership masks folded into cm/cd/cg and the circular
scan's cut into cc, and the shared column-scan kernel
(fwdbwd_pallas._fill_kernel with merge=True) runs the sequential scan
with the band state -- including the two-column Merge carry -- resident
in VMEM.  (The circular layout replaced the Merge carry's 15-variant
dynamic shift-select chain, which made the kernel pathologically slow to
compile on Mosaic -- the round-4 Quiver compile wall.)  This is the device analogue of
the reference's SSE recursor (SseRecursor.cpp:66-130): the reference
vectorizes 4 rows per __m128, here the whole band rides the vector lanes.

Emission lookups per (column, band-lane) use the same one-hot-matmul
windowing as the Arrow precompute; QV feature tracks are general floats, so
their windows run at exact=True (f32 HIGHEST) rather than the bf16 base-code
fast path.

Parity: tests/test_quiver_pallas.py fuzzes these fills against the JAX
banded recursor (models/quiver/recursor.py) and the dense log-space oracle,
mirroring the reference's typed-recursor concordance tests
(ConsensusCore/src/Tests/TestRecursors.cpp:63-69).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from pbccs_tpu.models.quiver.params import MERGE, QuiverConfig
from pbccs_tpu.models.quiver.recursor import QuiverFeatureArrays, _move_params
from pbccs_tpu.ops.fwdbwd import BandedMatrix, band_offsets
from pbccs_tpu.ops.fwdbwd_pallas import (_circ_rows_cols, _edge_clip_rows,
                                         _in_band2, _pad_cols, _pad_r,
                                         _pad_reads, _rev_clip_rows,
                                         _run_fill, window_rows_circ)

_TINY = 1e-30


def _win(x, starts, W: int, exact: bool = True):
    """y[j, L] = x[row(L)] over the circular window (one back row pad)."""
    xp = jnp.concatenate([x, x[-1:]])
    return window_rows_circ(xp, starts, W, exact=exact)


def _win_m1(x, starts, W: int, exact: bool = True):
    """y[j, L] = x[row(L) - 1] (front-clipped, circular window)."""
    xp = jnp.concatenate([x[0:1], x])
    return window_rows_circ(xp, starts, W, exact=exact)


def _emissions(pp, feat: QuiverFeatureArrays, rows, seq_w, subs_w, ins_w,
               dtag_w, dqv_w, mqv_w, tb_inc, tb_extra, tb_mrg, tb_mrg2,
               I, in_tpl, mrg_ok, pin_s, pin_e):
    """exp-space Inc/Del/Extra/Merge planes over an (nc, W) window whose
    feature tracks were gathered at the per-plane row index (see callers).
    Mirrors recursor._inc/_del/_extra/_merge value for value."""
    inc = jnp.where(seq_w == tb_inc, pp["match"],
                    pp["mismatch"] + pp["mismatch_s"] * subs_w)

    tagged = (rows < I) & (dtag_w == tb_inc.astype(jnp.float32))
    dele = jnp.where(tagged,
                     pp["deletion_with_tag"]
                     + pp["deletion_with_tag_s"] * dqv_w,
                     pp["deletion_n"])
    free = ((~pin_s) & (rows == 0)) | ((~pin_e) & (rows == I))
    dele = jnp.where(free, 0.0, dele)

    extra_match = in_tpl & (seq_w == tb_extra)
    extra = jnp.where(extra_match,
                      pp["branch"] + pp["branch_s"] * ins_w,
                      pp["nce"] + pp["nce_s"] * ins_w)

    good = mrg_ok & (seq_w == tb_mrg) & (tb_mrg == tb_mrg2)
    tb = jnp.clip(tb_mrg, 0, 3)
    mrg_score = pp["merge"][tb[:, 0]][:, None] \
        + pp["merge_s"][tb[:, 0]][:, None] * mqv_w
    mrg = jnp.where(good, jnp.exp(mrg_score), 0.0)
    return jnp.exp(inc), jnp.exp(dele), jnp.exp(extra), mrg


def _forward_coeffs(feat: QuiverFeatureArrays, I, tpl, J, offsets, W: int,
                    pp, use_merge: bool, pin_s, pin_e):
    """Per-column band coefficients of the Quiver alpha recurrence for one
    read, mirroring recursor.quiver_forward column for column."""
    nc = offsets.shape[0]
    Jmax = tpl.shape[0]
    j = jnp.arange(nc, dtype=jnp.int32)[:, None]
    o = offsets[:, None]
    om1 = _edge_clip_rows(offsets, 1, nc)[:, None]
    om2 = _edge_clip_rows(offsets, 2, nc)[:, None]

    rows = _circ_rows_cols(offsets, W)
    valid = (rows >= 0) & (rows <= I)

    # feature windows at row index rows-1 (Inc/Extra/Merge read base) and
    # rows (Del tag/qv)
    seq_f = feat.seq.astype(jnp.float32)
    seq_m1 = _win_m1(seq_f, offsets, W, exact=False)
    subs_m1 = _win_m1(feat.subs_qv, offsets, W)
    ins_m1 = _win_m1(feat.ins_qv, offsets, W)
    mqv_m1 = _win_m1(feat.merge_qv, offsets, W)
    dtag_0 = _win(feat.del_tag, offsets, W, exact=False)
    dqv_0 = _win(feat.del_qv, offsets, W)

    tb_prev = _edge_clip_rows(tpl, 1, nc)[:, None]     # template base j-1
    tb_cur = _edge_clip_rows(tpl, 0, nc)[:, None]      # template base j
    tb_prev2 = _edge_clip_rows(tpl, 2, nc)[:, None]    # template base j-2

    inc, dele, extra, mrg = _emissions(
        pp, feat, rows, seq_m1, subs_m1, ins_m1, dtag_0, dqv_0, mqv_m1,
        tb_inc=tb_prev, tb_extra=tb_cur, tb_mrg=tb_prev2, tb_mrg2=tb_prev,
        I=I, in_tpl=j < J, mrg_ok=(j >= 2) & use_merge,
        pin_s=pin_s, pin_e=pin_e)

    live = (j >= 1) & (j <= J)
    cm = jnp.where(valid & (rows >= 1) & live
                   & _in_band2(rows - 1, om1, W), inc, 0.0)
    cd = jnp.where(valid & live & _in_band2(rows, om1, W), dele, 0.0)
    cg = jnp.where(valid & (rows >= 1) & live
                   & _in_band2(rows - 1, om2, W), mrg, 0.0)
    # column 0 chains Extra below the alpha(0,0) impulse; dead cols j > J
    # have no in-column move; rows > o cuts the circular scan at the
    # band's first row
    cc = jnp.where(valid & (rows >= 1) & (j <= J) & (rows > o), extra, 0.0)

    mask = (j[:, 0] <= J).astype(jnp.float32)
    seed = (jnp.arange(W) == 0).astype(jnp.float32)
    return cm, cd, cc, cg, mask, seed, jnp.int32(0)


def _backward_coeffs(feat: QuiverFeatureArrays, I, tpl, J, offsets, W: int,
                     pp, use_merge: bool, pin_s, pin_e):
    """Beta coefficients in the static kernel frame (kernel column cc holds
    beta column j = Jmax - cc, lanes reversed), mirroring
    recursor.quiver_backward column for column."""
    nc = offsets.shape[0]
    Jmax = tpl.shape[0]
    cc_idx = jnp.arange(nc, dtype=jnp.int32)[:, None]
    j = Jmax - cc_idx
    o_jv = _rev_clip_rows(offsets, Jmax, nc)
    o_j = o_jv[:, None]
    o_j1 = _rev_clip_rows(offsets, Jmax + 1, nc)[:, None]
    o_j2 = _rev_clip_rows(offsets, Jmax + 2, nc)[:, None]

    rows = _circ_rows_cols(o_jv, W)
    valid = (rows >= 0) & (rows <= I)

    # all backward lookups are at row index `rows` (shared circular lanes;
    # no lane reversal -- the kernel's backward mode rolls the other way)
    seq_0 = _win(feat.seq.astype(jnp.float32), o_jv, W, exact=False)
    subs_0 = _win(feat.subs_qv, o_jv, W)
    ins_0 = _win(feat.ins_qv, o_jv, W)
    mqv_0 = _win(feat.merge_qv, o_jv, W)
    dtag_0 = _win(feat.del_tag, o_jv, W, exact=False)
    dqv_0 = _win(feat.del_qv, o_jv, W)

    tb = _rev_clip_rows(tpl, Jmax, nc)[:, None]            # base j (clipped)
    tb_next = _rev_clip_rows(tpl, Jmax + 1, nc)[:, None]   # base j+1

    inc, dele, extra, mrg = _emissions(
        pp, feat, rows, seq_0, subs_0, ins_0, dtag_0, dqv_0, mqv_0,
        tb_inc=tb, tb_extra=tb, tb_mrg=tb, tb_mrg2=tb_next,
        I=I, in_tpl=j < J, mrg_ok=(j + 1 < J) & use_merge,
        pin_s=pin_s, pin_e=pin_e)

    live = (j >= 0) & (j < J)
    cm = jnp.where(valid & (rows < I) & live
                   & _in_band2(rows + 1, o_j1, W), inc, 0.0)
    cd = jnp.where(valid & live & _in_band2(rows, o_j1, W), dele, 0.0)
    cg = jnp.where(valid & (rows < I) & live
                   & _in_band2(rows + 1, o_j2, W), mrg, 0.0)
    # rows < o + W - 1 cuts the reverse circular scan at the band top
    cc = jnp.where(valid & (rows < I) & (j >= 0) & (j <= J)
                   & (rows < o_j + W - 1), extra, 0.0)

    mask = ((j[:, 0] >= 0) & (j[:, 0] <= J)).astype(jnp.float32)
    seed = (jnp.arange(W) == I % W).astype(jnp.float32)
    return cm, cd, cc, cg, mask, seed, \
        (Jmax - J).astype(jnp.int32)


def _batch(coeff_fn, feat, rlens, tpls, tlens, config, W, pin_start, pin_end,
           rev_store: bool):
    R, Imax = feat.seq.shape
    Jmax = tpls.shape[1]
    nc = _pad_cols(Jmax + 1)
    Rp = _pad_reads(R)
    pp = _move_params(config.qv_params)
    use_merge = bool(config.moves_available & MERGE)

    I = rlens.astype(jnp.int32)
    J = tlens.astype(jnp.int32)
    offsets = jax.vmap(lambda i, jl: band_offsets(i, jl, nc, W))(I, J)
    outs = jax.vmap(
        lambda f, i, t, jl, o: coeff_fn(
            f, i, t.astype(jnp.int32), jl, o, W, pp, use_merge,
            jnp.asarray(pin_start), jnp.asarray(pin_end)),
        out_axes=(1, 1, 1, 1, 1, 0, 0),
    )(feat, I, tpls, J, offsets)
    cm, cd, cc, cg, mask, seed, seedcol = outs
    cm, cd, cc, cg, mask = _pad_r([cm, cd, cc, cg, mask], R, Rp, axis=1)
    seed, seedcol = _pad_r([seed, seedcol], R, Rp)
    vals, ls = _run_fill(cm, cd, cc, mask, seed, seedcol,
                         rev_store=rev_store, cg=cg)
    return vals, ls, offsets, nc


def pallas_quiver_forward_batch(feat: QuiverFeatureArrays, rlens, tpls,
                                tlens, config: QuiverConfig, width: int,
                                pin_start: bool = True,
                                pin_end: bool = True) -> BandedMatrix:
    """Batched banded Quiver alpha fills: feat leaves (R, Imax), tpls
    (R, Jmax), rlens/tlens (R,)."""
    vals, ls, offsets, _ = _batch(_forward_coeffs, feat, rlens, tpls, tlens,
                                  config, width, pin_start, pin_end,
                                  rev_store=False)
    R = rlens.shape[0]
    Jmax = tpls.shape[1]
    return BandedMatrix(vals[:R, : Jmax + 1], offsets[:, : Jmax + 1],
                        ls[:R, : Jmax + 1])


def pallas_quiver_backward_batch(feat: QuiverFeatureArrays, rlens, tpls,
                                 tlens, config: QuiverConfig, width: int,
                                 pin_start: bool = True,
                                 pin_end: bool = True) -> BandedMatrix:
    """Batched banded Quiver beta fills (kernel frame un-flipped here, as
    ops.fwdbwd_pallas.pallas_backward_batch does for Arrow)."""
    vals, ls, offsets, nc = _batch(_backward_coeffs, feat, rlens, tpls,
                                   tlens, config, width, pin_start, pin_end,
                                   rev_store=True)
    R = rlens.shape[0]
    Jmax = tpls.shape[1]
    lo = nc - 1 - Jmax
    return BandedMatrix(vals[:R, lo: lo + Jmax + 1],
                        offsets[:, : Jmax + 1], ls[:R, lo: lo + Jmax + 1])


def quiver_loglik_batch(alpha: BandedMatrix, rlens, tlens):
    """LL[r] = log alpha(I, J) + column scales, as masked reductions (the
    Quiver final column is a full band, so the pick is a 2-axis mask)."""
    I = rlens.astype(jnp.int32)[:, None]
    J = tlens.astype(jnp.int32)[:, None]
    from pbccs_tpu.ops.fwdbwd import circ_rows
    ncols = alpha.vals.shape[1]
    W = alpha.vals.shape[2]
    jcols = jnp.arange(ncols, dtype=jnp.int32)[None, :]
    at_J = (jcols == J)[:, :, None]
    rows = circ_rows(alpha.offsets, W)         # circular lane -> row
    final = jnp.sum(jnp.where(at_J & (rows == I[:, :, None]),
                              alpha.vals, 0.0), axis=(1, 2))
    ls = jnp.sum(jnp.where(jcols <= J, alpha.log_scales, 0.0), axis=1)
    return jnp.log(jnp.maximum(final, _TINY)) + ls
