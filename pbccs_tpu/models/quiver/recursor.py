"""Banded Quiver recursor: scaled natural-space forward/backward with the
Merge move.

Parity: the reference's log-space banded recursor
(ConsensusCore/src/C++/Quiver/SimpleRecursor.cpp:62-231) with moves
Incorporate / Extra / Delete / Merge (QvEvaluator.hpp:160-207) and the
SumProduct combiner.  TPU re-design notes:

* log-space logsumexp recurrences are the exp-space affine recurrences in
  disguise, so the fill reuses the Arrow machinery: static band of width W
  (band_offsets), natural-scale arithmetic with per-column max rescale
  (ScaledMatrix semantics), and the in-column Extra move evaluated as an
  associative affine scan.
* the Merge move consumes two template columns for one read base, so the
  column scan carries the previous *two* columns; the j-2 operand is
  re-normalized by the j-1 column's scale before combining.
* per-column read-feature lookups use jnp.take: this path is the CPU/
  reference implementation of the model family (Arrow is the production
  TPU path); a Pallas port would follow ops/fwdbwd_pallas if Quiver ever
  becomes hot.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pbccs_tpu.models.quiver.params import MERGE, QuiverConfig, QvModelParams
from pbccs_tpu.ops.fwdbwd import (BandedMatrix, _affine_scan_circ,
                                  _gather_band, band_offsets, circ_rows)

_TINY = 1e-30


class QuiverFeatureArrays(NamedTuple):
    """Padded device-side feature tracks for one read."""

    seq: jax.Array       # (Imax,) int32
    ins_qv: jax.Array    # (Imax,) f32
    subs_qv: jax.Array
    del_qv: jax.Array
    del_tag: jax.Array   # (Imax,) f32 base codes
    merge_qv: jax.Array


def feature_arrays(feat, imax: int) -> QuiverFeatureArrays:
    """Pad host features to (imax,) device arrays."""
    n = len(feat.seq)
    pad = lambda a, fill: jnp.asarray(
        np.concatenate([np.asarray(a, np.float32)[:imax],
                        np.full(max(0, imax - n), fill, np.float32)]))
    seq = np.full(imax, 4, np.int32)
    seq[:min(n, imax)] = np.asarray(feat.seq[:imax], np.int32)
    return QuiverFeatureArrays(
        jnp.asarray(seq), pad(feat.ins_qv, 0), pad(feat.subs_qv, 0),
        pad(feat.del_qv, 0), pad(feat.del_tag, 4), pad(feat.merge_qv, 0))


def _move_params(params: QvModelParams):
    return dict(
        match=params.match, mismatch=params.mismatch,
        mismatch_s=params.mismatch_s, branch=params.branch,
        branch_s=params.branch_s, deletion_n=params.deletion_n,
        deletion_with_tag=params.deletion_with_tag,
        deletion_with_tag_s=params.deletion_with_tag_s,
        nce=params.nce, nce_s=params.nce_s,
        merge=jnp.asarray(params.merge, jnp.float32),
        merge_s=jnp.asarray(params.merge_s, jnp.float32))


def _inc(pp, f: QuiverFeatureArrays, i, tpl_base):
    """Inc(i, j): log score of incorporating read base i against tpl base
    (QvEvaluator.hpp:160-168)."""
    Imax = f.seq.shape[0]
    ic = jnp.clip(i, 0, Imax - 1)
    is_match = f.seq[ic] == tpl_base
    return jnp.where(is_match, pp["match"],
                     pp["mismatch"] + pp["mismatch_s"] * f.subs_qv[ic])


def _del(pp, f: QuiverFeatureArrays, i, tpl_base, I, pin_start, pin_end):
    """Del(i, j) (QvEvaluator.hpp:170-185): free at unpinned ends."""
    Imax = f.seq.shape[0]
    ic = jnp.clip(i, 0, Imax - 1)
    tagged = (i < I) & (f.del_tag[ic] == tpl_base.astype(jnp.float32))
    score = jnp.where(tagged,
                      pp["deletion_with_tag"] + pp["deletion_with_tag_s"] * f.del_qv[ic],
                      pp["deletion_n"])
    free = ((~pin_start) & (i == 0)) | ((~pin_end) & (i == I))
    return jnp.where(free, 0.0, score)


def _extra(pp, f: QuiverFeatureArrays, i, tpl_base, in_tpl):
    """Extra(i, j) (QvEvaluator.hpp:187-193)."""
    Imax = f.seq.shape[0]
    ic = jnp.clip(i, 0, Imax - 1)
    is_match = in_tpl & (f.seq[ic] == tpl_base)
    return jnp.where(is_match,
                     pp["branch"] + pp["branch_s"] * f.ins_qv[ic],
                     pp["nce"] + pp["nce_s"] * f.ins_qv[ic])


def _merge(pp, f: QuiverFeatureArrays, i, tpl_base, tpl_base_next, ok):
    """Merge(i, j) (QvEvaluator.hpp:195-207): read base i must equal both
    template bases j and j+1; -inf otherwise (natural scale 0)."""
    Imax = f.seq.shape[0]
    ic = jnp.clip(i, 0, Imax - 1)
    good = ok & (f.seq[ic] == tpl_base) & (tpl_base == tpl_base_next)
    tb = jnp.clip(tpl_base, 0, 3)
    score = pp["merge"][tb] + pp["merge_s"][tb] * f.merge_qv[ic]
    return jnp.where(good, score, -jnp.inf)


def quiver_forward(feat: QuiverFeatureArrays, read_len, tpl, tpl_len,
                   config: QuiverConfig, width: int | None = None,
                   pin_start: bool = True, pin_end: bool = True) -> BandedMatrix:
    """Banded alpha fill (FillAlpha, Quiver/SimpleRecursor.cpp:62-148)."""
    pp = _move_params(config.qv_params)
    use_merge = bool(config.moves_available & MERGE)
    W = width or config.banding.band_width
    Jmax = tpl.shape[0]
    tpl32 = tpl.astype(jnp.int32)
    I = jnp.asarray(read_len, jnp.int32)
    J = jnp.asarray(tpl_len, jnp.int32)
    offsets = band_offsets(I, J, Jmax + 1, W)
    pin_s = jnp.asarray(pin_start)
    pin_e = jnp.asarray(pin_end)

    col0_rows = jnp.arange(W, dtype=jnp.int32)
    # column 0: alpha(0,0)=1; alpha(i,0) = alpha(i-1,0)*Extra(i-1, 0)
    # (offsets[0] == 0, so circular lanes == rows and c0 is already zero
    # at the scan's cut lane 0)
    b0 = jnp.zeros(W).at[0].set(1.0)
    c0 = jnp.where((col0_rows >= 1) & (col0_rows <= I),
                   jnp.exp(_extra(pp, feat, col0_rows - 1, tpl32[0], J > 0)), 0.0)
    col0 = _affine_scan_circ(b0, c0)
    s0 = jnp.maximum(jnp.max(col0), _TINY)
    col0 = col0 / s0
    ls0 = jnp.log(s0)

    def step(carry, j):
        prev, prev_off, prev2, prev2_off, s_prev = carry
        o = offsets[j]
        rows = circ_rows(o, W)
        valid = (rows >= 0) & (rows <= I)
        tb_prev = tpl32[jnp.clip(j - 1, 0, Jmax - 1)]      # template base j-1
        tb_cur = tpl32[jnp.clip(j, 0, Jmax - 1)]
        tb_prev2 = tpl32[jnp.clip(j - 2, 0, Jmax - 1)]

        inc = jnp.exp(_inc(pp, feat, rows - 1, tb_prev))
        dele = jnp.exp(_del(pp, feat, rows, tb_prev, I, pin_s, pin_e))
        a_im1_jm1 = _gather_band(prev, prev_off, rows - 1)
        a_i_jm1 = _gather_band(prev, prev_off, rows)

        b = jnp.where(rows >= 1, a_im1_jm1 * inc, 0.0)
        b = b + a_i_jm1 * dele
        if use_merge:
            mrg = jnp.exp(_merge(pp, feat, rows - 1, tb_prev2, tb_prev, j >= 2))
            a_im1_jm2 = _gather_band(prev2, prev2_off, rows - 1) / s_prev
            b = b + jnp.where(rows >= 1, a_im1_jm2 * mrg, 0.0)
        b = jnp.where(valid, b, 0.0)

        ext = jnp.exp(_extra(pp, feat, rows - 1, tb_cur, j < J))
        # rows > o cuts the circular scan at the band's first row
        c = jnp.where(valid & (rows >= 1) & (rows > o), ext, 0.0)
        col = _affine_scan_circ(b, c)

        active = j <= J
        cmax = jnp.max(col)
        scale = jnp.where(active & (cmax > 0), cmax, 1.0)
        col = jnp.where(active, col / scale, 0.0)
        ls = jnp.where(active, jnp.log(jnp.maximum(scale, _TINY)), 0.0)
        return ((col, o, prev, prev_off, scale),
                (col, ls))

    (_, _, _, _, _), (cols, lss) = lax.scan(
        step, (col0, offsets[0], jnp.zeros(W), offsets[0], jnp.asarray(1.0)),
        jnp.arange(1, Jmax + 1, dtype=jnp.int32))
    vals = jnp.concatenate([col0[None], cols], axis=0)
    log_scales = jnp.concatenate([ls0[None], lss])
    return BandedMatrix(vals, offsets, log_scales)


def quiver_backward(feat: QuiverFeatureArrays, read_len, tpl, tpl_len,
                    config: QuiverConfig, width: int | None = None,
                    pin_start: bool = True, pin_end: bool = True) -> BandedMatrix:
    """Banded beta fill (FillBeta, Quiver/SimpleRecursor.cpp:151-231).

    beta(i,j) combines beta(i+1,j+1)+Inc(i,j), beta(i+1,j)+Extra(i,j),
    beta(i,j+1)+Del(i,j) and beta(i+1,j+2)+Merge(i,j); seed beta(I,J)=1."""
    pp = _move_params(config.qv_params)
    use_merge = bool(config.moves_available & MERGE)
    W = width or config.banding.band_width
    Jmax = tpl.shape[0]
    tpl32 = tpl.astype(jnp.int32)
    I = jnp.asarray(read_len, jnp.int32)
    J = jnp.asarray(tpl_len, jnp.int32)
    offsets = band_offsets(I, J, Jmax + 1, W)
    pin_s = jnp.asarray(pin_start)
    pin_e = jnp.asarray(pin_end)

    def col_fill(j, nxt, nxt_off, nxt2, nxt2_off, s_next, seedcol):
        o = offsets[jnp.clip(j, 0, Jmax)]
        rows = circ_rows(o, W)
        valid = (rows >= 0) & (rows <= I)
        tb = tpl32[jnp.clip(j, 0, Jmax - 1)]
        tb_next = tpl32[jnp.clip(j + 1, 0, Jmax - 1)]

        inc = jnp.exp(_inc(pp, feat, rows, tb))
        dele = jnp.exp(_del(pp, feat, rows, tb, I, pin_s, pin_e))
        b_ip1_jp1 = _gather_band(nxt, nxt_off, rows + 1)
        b_i_jp1 = _gather_band(nxt, nxt_off, rows)
        b = jnp.where((rows < I) & (j < J), b_ip1_jp1 * inc, 0.0)
        b = b + jnp.where(j < J, b_i_jp1 * dele, 0.0)
        if use_merge:
            mrg = jnp.exp(_merge(pp, feat, rows, tb, tb_next, j + 1 < J))
            b_ip1_jp2 = _gather_band(nxt2, nxt2_off, rows + 1) / s_next
            b = b + jnp.where(rows < I, b_ip1_jp2 * mrg, 0.0)
        b = b + jnp.where(seedcol & (rows == I), 1.0, 0.0)
        b = jnp.where(valid, b, 0.0)

        ext = jnp.exp(_extra(pp, feat, rows, tb, j < J))
        # rows < o + W - 1 cuts the reverse circular scan at the band top
        c = jnp.where(valid & (rows < I) & (rows < o + W - 1), ext, 0.0)
        return _affine_scan_circ(b, c, reverse=True), o

    def step(carry, j):
        nxt, nxt_off, nxt2, nxt2_off, s_next = carry
        col, o = col_fill(j, nxt, nxt_off, nxt2, nxt2_off, s_next, j == J)
        active = j <= J
        cmax = jnp.max(col)
        scale = jnp.where(active & (cmax > 0), cmax, 1.0)
        col = jnp.where(active, col / scale, 0.0)
        ls = jnp.where(active, jnp.log(jnp.maximum(scale, _TINY)), 0.0)
        return ((col, o, nxt, nxt_off, scale), (col, ls))

    (_, _, _, _, _), (cols_rev, ls_rev) = lax.scan(
        step, (jnp.zeros(W), offsets[Jmax], jnp.zeros(W), offsets[Jmax],
               jnp.asarray(1.0)),
        jnp.arange(Jmax, -1, -1, dtype=jnp.int32))
    vals = cols_rev[::-1]
    log_scales = ls_rev[::-1]
    return BandedMatrix(vals, offsets, log_scales)


def quiver_loglik(alpha: BandedMatrix, read_len, tpl_len):
    """LL = log alpha(I, J) + accumulated column scales."""
    I = jnp.asarray(read_len, jnp.int32)
    J = jnp.asarray(tpl_len, jnp.int32)
    final = _gather_band(alpha.vals[J], alpha.offsets[J], I[None])[0]
    ncols = alpha.vals.shape[0]
    mask = jnp.arange(ncols) <= J
    return jnp.log(jnp.maximum(final, _TINY)) + \
        jnp.sum(jnp.where(mask, alpha.log_scales, 0.0))


def quiver_loglik_backward(beta: BandedMatrix, tpl_len):
    J = jnp.asarray(tpl_len, jnp.int32)
    b00 = _gather_band(beta.vals[0], beta.offsets[0], jnp.asarray([0], jnp.int32))[0]
    ncols = beta.vals.shape[0]
    mask = jnp.arange(ncols) <= J
    return jnp.log(jnp.maximum(b00, _TINY)) + \
        jnp.sum(jnp.where(mask, beta.log_scales, 0.0))


def viterbi_alignment(feat, tpl_codes, params: QvModelParams,
                      use_merge: bool = True, pin_start: bool = True,
                      pin_end: bool = True):
    """Read-vs-template viterbi alignment: max-combiner DP + traceback to
    a gapped PairwiseAlignment (reference RecursorBase::Alignment,
    RecursorBase.hpp:53-116 + RecursorBase.cpp:126-264, including the
    Merge move's two-template-column step).

    Like the reference's, this is a diagnostic/API routine off the hot
    path (the production scorers never traceback), so it runs as a dense
    host DP; moves tie-break in the reference's probe order
    (Incorporate > Delete > Extra > Merge on strict >)."""
    from pbccs_tpu.align.pairwise import PairwiseAlignment
    from pbccs_tpu.models.arrow.params import decode_bases

    seq = np.asarray(feat.seq, np.int64)
    tpl = np.asarray(tpl_codes, np.int64)
    I, J = len(seq), len(tpl)
    NEG = -np.inf

    def inc(i, j):
        if seq[i] == tpl[j]:
            return params.match
        return params.mismatch + params.mismatch_s * feat.subs_qv[i]

    def dele(i, j):
        if (not pin_start and i == 0) or (not pin_end and i == I):
            return 0.0
        if i < I and feat.del_tag[i] == tpl[j]:
            return params.deletion_with_tag + \
                params.deletion_with_tag_s * feat.del_qv[i]
        return params.deletion_n

    def extra(i, j):
        if j < J and seq[i] == tpl[j]:
            return params.branch + params.branch_s * feat.ins_qv[i]
        return params.nce + params.nce_s * feat.ins_qv[i]

    def merge(i, j):
        if seq[i] == tpl[j] and tpl[j] == tpl[j + 1]:
            tb = int(tpl[j])
            return params.merge[tb] + params.merge_s[tb] * feat.merge_qv[i]
        return NEG

    # viterbi fill: dense_loglik's recurrence with max in place of
    # logsumexp (the reference's ViterbiCombiner)
    a = np.full((I + 1, J + 1), NEG)
    a[0, 0] = 0.0
    for j in range(J + 1):
        for i in range(I + 1):
            if i == 0 and j == 0:
                continue
            best = NEG
            if i > 0 and j > 0:
                best = max(best, a[i - 1, j - 1] + inc(i - 1, j - 1))
            if i > 0:
                best = max(best, a[i - 1, j] + extra(i - 1, j))
            if j > 0:
                best = max(best, a[i, j - 1] + dele(i, j - 1))
            if use_merge and j > 1 and i > 0:
                best = max(best, a[i - 1, j - 2] + merge(i - 1, j - 2))
            a[i, j] = best

    # traceback (RecursorBase.cpp:150-218): recompute each move's total
    # and take the best, probing in the reference's order
    i, j = I, J
    moves: list[tuple[int, int]] = []          # (read_delta, ref_delta)
    while i > 0 or j > 0:
        best_move, best = None, NEG
        if i > 0 and j > 0:
            t = a[i - 1, j - 1] + inc(i - 1, j - 1)
            if t > best:
                best_move, best = (1, 1), t
        if j > 0:
            free = (not pin_end and i == I) or (not pin_start and i == 0)
            t = a[i, j - 1] + (0.0 if free else dele(i, j - 1))
            if t > best:
                best_move, best = (0, 1), t
        if i > 0:
            t = a[i - 1, j] + extra(i - 1, j)
            if t > best:
                best_move, best = (1, 0), t
        if use_merge and i > 0 and j > 1:
            t = a[i - 1, j - 2] + merge(i - 1, j - 2)
            if t > best:
                best_move, best = (1, 2), t
        assert best_move is not None
        moves.append(best_move)
        i -= best_move[0]
        j -= best_move[1]
    moves.reverse()

    tstr = decode_bases(tpl.astype(np.int8))
    qstr = decode_bases(seq[:I].astype(np.int8))
    target, query = [], []
    i = j = 0
    for rd, td in moves:
        if rd == 1 and td == 1:          # incorporate
            target.append(tstr[j])
            query.append(qstr[i])
        elif rd == 1 and td == 0:        # extra
            target.append("-")
            query.append(qstr[i])
        elif rd == 0 and td == 1:        # delete
            target.append(tstr[j])
            query.append("-")
        else:                            # merge: two tpl columns, one base
            target.append(tstr[j])
            target.append(tstr[j + 1])
            query.append("-")
            query.append(qstr[i])
        i += rd
        j += td
    return PairwiseAlignment("".join(target), "".join(query))


def dense_loglik(feat, tpl_codes, params: QvModelParams, use_merge: bool = True,
                 pin_start: bool = True, pin_end: bool = True) -> float:
    """Dense log-space oracle (numpy) for validating the banded fills; the
    direct transliteration of the recurrence, kept simple and slow."""
    seq = np.asarray(feat.seq, np.int64)
    tpl = np.asarray(tpl_codes, np.int64)
    I, J = len(seq), len(tpl)
    NEG = -np.inf
    a = np.full((I + 1, J + 1), NEG)
    a[0, 0] = 0.0

    def inc(i, j):
        if seq[i] == tpl[j]:
            return params.match
        return params.mismatch + params.mismatch_s * feat.subs_qv[i]

    def dele(i, j):
        if (not pin_start and i == 0) or (not pin_end and i == I):
            return 0.0
        if i < I and feat.del_tag[i] == tpl[j]:
            return params.deletion_with_tag + params.deletion_with_tag_s * feat.del_qv[i]
        return params.deletion_n

    def extra(i, j):
        if j < J and seq[i] == tpl[j]:
            return params.branch + params.branch_s * feat.ins_qv[i]
        return params.nce + params.nce_s * feat.ins_qv[i]

    def merge(i, j):
        if seq[i] == tpl[j] and tpl[j] == tpl[j + 1]:
            tb = int(tpl[j])
            return params.merge[tb] + params.merge_s[tb] * feat.merge_qv[i]
        return NEG

    for j in range(J + 1):
        for i in range(I + 1):
            terms = []
            if i == 0 and j == 0:
                continue
            if i > 0 and j > 0:
                terms.append(a[i - 1, j - 1] + inc(i - 1, j - 1))
            if i > 0:
                terms.append(a[i - 1, j] + extra(i - 1, j))
            if j > 0:
                terms.append(a[i, j - 1] + dele(i, j - 1))
            if use_merge and j > 1 and i > 0:
                terms.append(a[i - 1, j - 2] + merge(i - 1, j - 2))
            if terms:
                a[i, j] = np.logaddexp.reduce(terms)
    return float(a[I, J])
