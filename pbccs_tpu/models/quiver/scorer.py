"""Quiver multi-read mutation scorer.

Parity target: the Quiver-namespace MultiReadMutationScorer (reference
ConsensusCore/include/ConsensusCore/Quiver/MultiReadMutationScorer.hpp:55-246,
src/C++/Quiver/MultiReadMutationScorer.cpp): per-read template windows on
the forward/RC template, AddRead alpha/beta mating gate, Score(mutation) =
sum over reads of LL(mutated) - LL(current), ApplyMutations with coordinate
remap.  Unlike Arrow there is no per-position transition track -- move
scores depend on the template only through base identity -- so mutation
scoring re-fills the mutated window directly (the reference's
extend+link specialization is a serial-CPU optimization; the batched
re-fill keeps every candidate on the device grid)."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pbccs_tpu.models.arrow import mutations as mutlib
from pbccs_tpu.models.arrow.params import revcomp
from pbccs_tpu.models.quiver.params import QuiverConfig
from pbccs_tpu.models.quiver.recursor import (
    QuiverFeatureArrays,
    feature_arrays,
    quiver_backward,
    quiver_forward,
    quiver_loglik,
    quiver_loglik_backward,
)
from pbccs_tpu.ops.fwdbwd_pallas import fills_use_pallas

from pbccs_tpu.utils import next_pow2 as _next_pow2

ADD_SUCCESS, ADD_ALPHABETAMISMATCH = 0, 1
_AB_MISMATCH_TOL = 1e-3
_MUT_CHUNK = 256

import functools


@functools.partial(jax.jit, static_argnames=("config", "width"))
def _lls_program(feats, rl, tp, tl, *, config, width):
    """(rows,) forward log-likelihoods of a flat (read, window) batch via
    the XLA recursor — ONE jitted program (eager per-op dispatch over a
    tunneled device link costs ~0.1 s per op; a whole polish ran minutes
    of pure dispatch latency before this was jitted)."""
    def one(feat, rlen, win, wlen):
        alpha = quiver_forward(feat, rlen, win, wlen, config, width)
        return quiver_loglik(alpha, rlen, wlen)

    return jax.vmap(one)(feats, rl, tp, tl)


@functools.partial(jax.jit, static_argnames=("config", "width"))
def _ab_program(feats, rl, tp, tl, *, config, width):
    """Batched forward+backward log-likelihoods (the AddRead mating gate's
    inputs) as one jitted program; XLA-recursor counterpart of the Pallas
    branch in _rebuild."""
    def one(feat, rlen, win, wlen):
        alpha = quiver_forward(feat, rlen, win, wlen, config, width)
        beta = quiver_backward(feat, rlen, win, wlen, config, width)
        return (quiver_loglik(alpha, rlen, wlen),
                quiver_loglik_backward(beta, wlen))

    return jax.vmap(one)(feats, rl, tp, tl)


@functools.partial(jax.jit, static_argnames=("config", "width"))
def _pallas_ab_program(feats, rl, tp, tl, *, config, width):
    """Pallas-batch AddRead fills + LLs as ONE jitted program.  Eager
    pallas_call bypasses jit executable caching AND the persistent
    compilation cache, so every process paid the full remote Mosaic
    compile again -- the quiver bench's repeated 45-minute walls."""
    from pbccs_tpu.models.quiver.pallas_fill import (
        pallas_quiver_backward_batch, pallas_quiver_forward_batch,
        quiver_loglik_batch)

    alpha = pallas_quiver_forward_batch(feats, rl, tp, tl, config, width)
    beta = pallas_quiver_backward_batch(feats, rl, tp, tl, config, width)
    ll_a = quiver_loglik_batch(alpha, rl, tl)
    jcols = jnp.arange(beta.log_scales.shape[1])[None, :]
    ll_b = (jnp.log(jnp.maximum(beta.vals[:, 0, 0], 1e-30))
            + jnp.where(jcols <= tl[:, None], beta.log_scales, 0.0
                        ).sum(axis=1))
    return ll_a, ll_b


@functools.partial(jax.jit, static_argnames=("config", "width"))
def _pallas_lls_program(feats, rl, tp, tl, *, config, width):
    """Pallas-batch forward LLs as ONE jitted program (see
    _pallas_ab_program for why jit is load-bearing here)."""
    from pbccs_tpu.models.quiver.pallas_fill import (
        pallas_quiver_forward_batch, quiver_loglik_batch)

    alpha = pallas_quiver_forward_batch(feats, rl, tp, tl, config, width)
    return quiver_loglik_batch(alpha, rl, tl)





class QuiverMultiReadScorer:
    """Per-template Quiver polishing state over QV-feature reads."""

    def __init__(self, tpl: np.ndarray, reads: Sequence, strands: Sequence[int],
                 tstarts: Sequence[int], tends: Sequence[int],
                 config: QuiverConfig | None = None):
        self.config = config or QuiverConfig()
        self.tpl = np.asarray(tpl, np.int8)
        self.n_reads = len(reads)
        self._feats = list(reads)
        self._strands = np.asarray(strands, np.int32)
        self._tstarts = np.asarray(tstarts, np.int32)
        self._tends = np.asarray(tends, np.int32)
        self._Imax = _next_pow2(max((len(f) for f in reads), default=8) + 8, 64)
        # template-axis bucket PINNED with growth headroom (one formula:
        # _jmax_bucket below): recomputing next_pow2(L) from the CURRENT
        # length minted a fresh Jmax -- and recompiled the whole
        # fill-program menu through the remote compile helper, ~1-2 min per
        # program -- every time a round's accepted indels crossed a pow2
        # boundary.  One bucket serves every rebuild and mutated-window
        # score; templates outgrowing it re-bucket (rare, _rebuild).
        self._Jmax = 0      # set by _rebuild(first=True)'s bucket guard
        self._W = self.config.banding.band_width
        self._dev_feats = [feature_arrays(f, self._Imax) for f in reads]
        self._rlens = np.asarray([min(len(f), self._Imax) for f in reads], np.int32)
        self.statuses = np.zeros(self.n_reads, np.int32)
        self.active = np.zeros(self.n_reads, bool)
        self._rebuild(first=True)

    # ------------------------------------------------------------------ setup

    def _window_codes(self, r: int, tpl: np.ndarray) -> np.ndarray:
        """Read r's oriented template window of `tpl`."""
        ts, te = int(self._tstarts[r]), int(self._tends[r])
        win = tpl[ts:te]
        if self._strands[r] == 1:
            win = revcomp(win)
        return win

    def _stacked_feats(self, idx=None) -> QuiverFeatureArrays:
        feats = self._dev_feats if idx is None else \
            [self._dev_feats[i] for i in idx]
        return QuiverFeatureArrays(*(jnp.stack([getattr(f, n) for f in feats])
                                     for n in QuiverFeatureArrays._fields))

    def _jmax_bucket(self, L: int) -> int:
        """Headroom-proportional template bucket.  Shares only the headroom
        term with parallel/batch._jmax_bucket (+10 for the mutated-window
        pad); this rounds up to a power of two so the Pallas fill programs
        see a tiny shape menu, where batch pads to a multiple of 64."""
        return _next_pow2(L + max(16, L // 32) + 10, 64)

    def _rebuild(self, first: bool) -> None:
        L = len(self.tpl)
        if L + 8 > self._Jmax:   # template outgrew the bucket: re-bucket
            self._Jmax = self._jmax_bucket(L)
        Jmax = self._Jmax
        wins_np, wlens = [], []
        for r in range(self.n_reads):
            win = self._window_codes(r, self.tpl)
            wpad = np.full(Jmax, 4, np.int8)
            wpad[:len(win)] = win
            wins_np.append(wpad)
            wlens.append(len(win))
        # read axis pads to pow2 (shared contract for both fill backends)
        # so the per-ZMW pass count doesn't mint a compiled shape each
        R = self.n_reads
        Rp = _next_pow2(max(R, 1), 4)
        pad_r = ((0, Rp - R), (0, 0))
        feats = self._stacked_feats()
        feats = QuiverFeatureArrays(*(jnp.pad(t, pad_r) for t in feats))
        rl = jnp.asarray(np.pad(self._rlens, (0, Rp - R),
                                constant_values=2))
        tp = jnp.asarray(np.pad(np.stack(wins_np), pad_r,
                                constant_values=4))
        tl = jnp.asarray(np.pad(np.asarray(wlens, np.int32),
                                (0, Rp - R), constant_values=2))
        if fills_use_pallas():
            # one batched Pallas launch over the read axis (the device
            # analogue of the reference's per-read SSE recursor,
            # SseRecursor.cpp:66-130), as ONE jitted program so the
            # executable + persistent caches apply
            lls_a, lls_b = _pallas_ab_program(feats, rl, tp, tl,
                                              config=self.config,
                                              width=self._W)
        else:
            # XLA-recursor path: one jitted batched program
            lls_a, lls_b = _ab_program(feats, rl, tp, tl,
                                       config=self.config, width=self._W)
        ll_a = np.asarray(lls_a, np.float64)[:R]
        ll_b = np.asarray(lls_b, np.float64)[:R]
        self.baselines = ll_a
        denom = np.where(ll_b == 0, 1.0, ll_b)
        mated = (np.abs(1.0 - ll_a / denom) <= _AB_MISMATCH_TOL) & \
            np.isfinite(ll_a) & np.isfinite(ll_b)
        if first:
            self.active = mated.copy()
            self.statuses = np.where(mated, ADD_SUCCESS, ADD_ALPHABETAMISMATCH)
        else:
            self.active &= mated

    # ---------------------------------------------------------------- scoring

    def baseline_total(self) -> float:
        return float(self.baselines[self.active].sum())

    def _windows_for(self, tpl: np.ndarray, jmax: int):
        outs = []
        for r in range(self.n_reads):
            win = self._window_codes(r, tpl)
            wpad = np.full(jmax, 4, np.int8)
            wpad[:len(win)] = win
            outs.append((wpad, len(win)))
        return outs

    def score_mutations(self, muts: Sequence[mutlib.Mutation]) -> np.ndarray:
        """score(m) = sum over active overlapping reads of
        (LL(T+m) - LL(T)) via full banded refills of the mutated windows.

        Reads sharing an oriented window geometry (ts, te, strand) share
        the mutated windows, so windows build once per GROUP and every
        fill dispatch batches (reads-in-group x mutation-chunk) rows --
        per-read per-chunk dispatches cost a device round trip each
        (~0.1-0.25 s over a tunneled link), which made the per-ZMW polish
        dispatch-bound."""
        if not muts:
            return np.zeros(0)
        L = len(self.tpl)
        jmax = self._Jmax        # pinned bucket (see __init__)
        scores = np.zeros(len(muts))

        groups: dict[tuple[int, int, int], list[int]] = {}
        for r in range(self.n_reads):
            if self.active[r]:
                key = (int(self._tstarts[r]), int(self._tends[r]),
                       int(self._strands[r]))
                groups.setdefault(key, []).append(r)

        for (ts, te, strand), rds in groups.items():
            wins, wlens, idxs = [], [], []
            for k, m in enumerate(muts):
                overlap = (ts <= m.end) & (m.start <= te) \
                    if m.mtype == mutlib.INSERTION \
                    else (ts < m.end) & (m.start < te)
                if not overlap:
                    continue
                mt = mutlib.apply_mutations(self.tpl, [m])
                # window bounds remap: positions <= start unchanged; the
                # window end moves with the template length delta
                delta = len(mt) - L
                te_m = te + delta if m.start < te else te
                win = mt[ts:te_m]
                if strand == 1:
                    win = revcomp(win)
                wpad = np.full(jmax, 4, np.int8)
                wpad[:len(win)] = win
                wins.append(wpad)
                wlens.append(len(win))
                idxs.append(k)
            if not wins:
                continue
            lls = self._fill_lls_group(rds, np.stack(wins),
                                       np.asarray(wlens, np.int32))
            scores[np.asarray(idxs)] += (
                lls - self.baselines[np.asarray(rds)][:, None]).sum(axis=0)
        return scores

    def _fill_lls_group(self, rds: Sequence[int], wins: np.ndarray,
                        wlens: np.ndarray) -> np.ndarray:
        """(len(rds), M) absolute LLs of each read in the group against
        each mutated window: one fill dispatch per fixed-size mutation
        chunk, with (read x window) riding the batch axis.  Chunks of
        _MUT_CHUNK (+ one pow2 tail) bound the compiled-shape menu --
        an unbounded next_pow2(M) menu compiled a fresh fill program per
        distinct candidate count per round."""
        M = len(wins)
        if M > _MUT_CHUNK:
            outs = [self._fill_lls_group(rds, wins[lo: lo + _MUT_CHUNK],
                                         wlens[lo: lo + _MUT_CHUNK])
                    for lo in range(0, M, _MUT_CHUNK)]
            return np.concatenate(outs, axis=1)
        G = len(rds)
        Mpad = _next_pow2(M, 8)
        wins_p = np.concatenate(
            [wins, np.full((Mpad - M, wins.shape[1]), 4, np.int8)])
        wlens_p = np.concatenate([wlens, np.full(Mpad - M, 2, np.int32)])
        # batch rows: read-major (read g's windows at rows [g*Mpad, ...)),
        # then the TOTAL row count pads to pow2 -- G varies per ZMW with
        # the strand mix, and a (G x Mpad)-keyed shape menu compiled a
        # fresh fill program per combination
        rows = G * Mpad
        rows_p = _next_pow2(rows, 64)
        tl = jnp.asarray(np.pad(np.tile(wlens_p, G), (0, rows_p - rows),
                                constant_values=2))
        tp = jnp.asarray(np.pad(np.tile(wins_p, (G, 1)),
                                ((0, rows_p - rows), (0, 0)),
                                constant_values=4))
        feats = QuiverFeatureArrays(
            *(jnp.pad(jnp.repeat(
                jnp.stack([self._dev_feats[r][i] for r in rds]),
                Mpad, axis=0), ((0, rows_p - rows), (0, 0)))
              for i in range(len(QuiverFeatureArrays._fields))))
        rl = jnp.asarray(np.pad(
            np.repeat(self._rlens[np.asarray(rds)], Mpad),
            (0, rows_p - rows), constant_values=2))
        if fills_use_pallas():
            lls = _pallas_lls_program(feats, rl, tp, tl, config=self.config,
                                      width=self._W)
        else:
            lls = _lls_program(feats, rl, tp, tl, config=self.config,
                               width=self._W)
        return np.asarray(lls, np.float64)[:rows].reshape(G, Mpad)[:, :M]

    # ------------------------------------------------------------------- QVs

    def consensus_qvs(self) -> np.ndarray:
        """Per-position QVs via the generic single-mutation sweep
        (models.arrow.refine.consensus_qvs; reference ConsensusQVs is
        templated over both scorer families, Consensus-inl.hpp:277-297)."""
        from pbccs_tpu.models.arrow.refine import consensus_qvs

        return consensus_qvs(self)

    # --------------------------------------------------------------- mutation

    def apply_mutations(self, muts: Sequence[mutlib.Mutation]) -> None:
        if not muts:
            return
        L = len(self.tpl)
        mtp = mutlib.target_to_query_positions(muts, L)
        self.tpl = mutlib.apply_mutations(self.tpl, muts)
        self._tstarts = mtp[np.clip(self._tstarts, 0, L)].astype(np.int32)
        self._tends = mtp[np.clip(self._tends, 0, L)].astype(np.int32)
        self._rebuild(first=False)
