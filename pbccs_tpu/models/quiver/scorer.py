"""Quiver multi-read mutation scorer.

Parity target: the Quiver-namespace MultiReadMutationScorer (reference
ConsensusCore/include/ConsensusCore/Quiver/MultiReadMutationScorer.hpp:55-246,
src/C++/Quiver/MultiReadMutationScorer.cpp): per-read template windows on
the forward/RC template, AddRead alpha/beta mating gate, Score(mutation) =
sum over reads of LL(mutated) - LL(current), ApplyMutations with coordinate
remap.  Unlike Arrow there is no per-position transition track -- move
scores depend on the template only through base identity -- so mutation
scoring re-fills the mutated window directly (the reference's
extend+link specialization is a serial-CPU optimization; the batched
re-fill keeps every candidate on the device grid)."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pbccs_tpu.models.arrow import mutations as mutlib
from pbccs_tpu.models.arrow.params import revcomp
from pbccs_tpu.models.quiver.params import QuiverConfig
from pbccs_tpu.models.quiver.recursor import (
    QuiverFeatureArrays,
    feature_arrays,
    quiver_backward,
    quiver_forward,
    quiver_loglik,
    quiver_loglik_backward,
)
from pbccs_tpu.ops.fwdbwd_pallas import fills_use_pallas

from pbccs_tpu.utils import next_pow2 as _next_pow2

ADD_SUCCESS, ADD_ALPHABETAMISMATCH = 0, 1
_AB_MISMATCH_TOL = 1e-3
_MUT_CHUNK = 256





class QuiverMultiReadScorer:
    """Per-template Quiver polishing state over QV-feature reads."""

    def __init__(self, tpl: np.ndarray, reads: Sequence, strands: Sequence[int],
                 tstarts: Sequence[int], tends: Sequence[int],
                 config: QuiverConfig | None = None):
        self.config = config or QuiverConfig()
        self.tpl = np.asarray(tpl, np.int8)
        self.n_reads = len(reads)
        self._feats = list(reads)
        self._strands = np.asarray(strands, np.int32)
        self._tstarts = np.asarray(tstarts, np.int32)
        self._tends = np.asarray(tends, np.int32)
        self._Imax = _next_pow2(max((len(f) for f in reads), default=8) + 8, 64)
        self._W = self.config.banding.band_width
        self._dev_feats = [feature_arrays(f, self._Imax) for f in reads]
        self._rlens = np.asarray([min(len(f), self._Imax) for f in reads], np.int32)
        self.statuses = np.zeros(self.n_reads, np.int32)
        self.active = np.zeros(self.n_reads, bool)
        self._rebuild(first=True)

    # ------------------------------------------------------------------ setup

    def _window_codes(self, r: int, tpl: np.ndarray) -> np.ndarray:
        """Read r's oriented template window of `tpl`."""
        ts, te = int(self._tstarts[r]), int(self._tends[r])
        win = tpl[ts:te]
        if self._strands[r] == 1:
            win = revcomp(win)
        return win

    def _stacked_feats(self, idx=None) -> QuiverFeatureArrays:
        feats = self._dev_feats if idx is None else \
            [self._dev_feats[i] for i in idx]
        return QuiverFeatureArrays(*(jnp.stack([getattr(f, n) for f in feats])
                                     for n in QuiverFeatureArrays._fields))

    def _rebuild(self, first: bool) -> None:
        L = len(self.tpl)
        Jmax = _next_pow2(L + 8, 64)
        self._wins = []
        wins_np, wlens = [], []
        for r in range(self.n_reads):
            win = self._window_codes(r, self.tpl)
            wpad = np.full(Jmax, 4, np.int8)
            wpad[:len(win)] = win
            self._wins.append((jnp.asarray(wpad), jnp.int32(len(win))))
            wins_np.append(wpad)
            wlens.append(len(win))
        if fills_use_pallas():
            # one batched Pallas launch over the read axis (the device
            # analogue of the reference's per-read SSE recursor,
            # SseRecursor.cpp:66-130)
            from pbccs_tpu.models.quiver.pallas_fill import (
                pallas_quiver_backward_batch, pallas_quiver_forward_batch,
                quiver_loglik_batch)

            feats = self._stacked_feats()
            rl = jnp.asarray(self._rlens)
            tp = jnp.asarray(np.stack(wins_np))
            tl = jnp.asarray(wlens, jnp.int32)
            alpha = pallas_quiver_forward_batch(feats, rl, tp, tl,
                                                self.config, self._W)
            beta = pallas_quiver_backward_batch(feats, rl, tp, tl,
                                                self.config, self._W)
            ll_a = np.asarray(quiver_loglik_batch(alpha, rl, tl), np.float64)
            jcols = np.arange(beta.log_scales.shape[1])[None, :]
            ll_b = np.log(np.maximum(np.asarray(beta.vals[:, 0, 0]), 1e-30)) \
                + np.where(jcols <= np.asarray(tl)[:, None],
                           np.asarray(beta.log_scales), 0.0).sum(axis=1)
        else:
            lls_a, lls_b = [], []
            for r in range(self.n_reads):
                wpad, wlen = self._wins[r]
                alpha = quiver_forward(self._dev_feats[r], self._rlens[r],
                                       wpad, wlen, self.config, self._W)
                beta = quiver_backward(self._dev_feats[r], self._rlens[r],
                                       wpad, wlen, self.config, self._W)
                lls_a.append(float(quiver_loglik(alpha, self._rlens[r],
                                                 wlens[r])))
                lls_b.append(float(quiver_loglik_backward(beta, wlens[r])))
            ll_a = np.asarray(lls_a)
            ll_b = np.asarray(lls_b)
        self.baselines = ll_a
        denom = np.where(ll_b == 0, 1.0, ll_b)
        mated = (np.abs(1.0 - ll_a / denom) <= _AB_MISMATCH_TOL) & \
            np.isfinite(ll_a) & np.isfinite(ll_b)
        if first:
            self.active = mated.copy()
            self.statuses = np.where(mated, ADD_SUCCESS, ADD_ALPHABETAMISMATCH)
        else:
            self.active &= mated

    # ---------------------------------------------------------------- scoring

    def baseline_total(self) -> float:
        return float(self.baselines[self.active].sum())

    def _windows_for(self, tpl: np.ndarray, jmax: int):
        outs = []
        for r in range(self.n_reads):
            win = self._window_codes(r, tpl)
            wpad = np.full(jmax, 4, np.int8)
            wpad[:len(win)] = win
            outs.append((wpad, len(win)))
        return outs

    def score_mutations(self, muts: Sequence[mutlib.Mutation]) -> np.ndarray:
        """score(m) = sum over active overlapping reads of
        (LL(T+m) - LL(T)) via full banded refills of the mutated windows."""
        if not muts:
            return np.zeros(0)
        L = len(self.tpl)
        jmax = _next_pow2(L + 10, 64)
        scores = np.zeros(len(muts))
        # per read: build all mutated windows on host, fill in device chunks
        for r in range(self.n_reads):
            if not self.active[r]:
                continue
            ts, te = int(self._tstarts[r]), int(self._tends[r])
            wins, wlens, idxs = [], [], []
            for k, m in enumerate(muts):
                overlap = (ts <= m.end) & (m.start <= te) if m.mtype == mutlib.INSERTION \
                    else (ts < m.end) & (m.start < te)
                if not overlap:
                    continue
                mt = mutlib.apply_mutations(self.tpl, [m])
                # window bounds remap: positions <= start unchanged; the
                # window end moves with the template length delta
                delta = len(mt) - L
                te_m = te + delta if m.start < te else te
                win = mt[ts:te_m]
                if self._strands[r] == 1:
                    win = revcomp(win)
                wpad = np.full(jmax, 4, np.int8)
                wpad[:len(win)] = win
                wins.append(wpad)
                wlens.append(len(win))
                idxs.append(k)
            if not wins:
                continue
            lls = self._fill_lls(r, np.stack(wins), np.asarray(wlens, np.int32))
            for k, ll in zip(idxs, lls):
                scores[k] += ll - self.baselines[r]
        return scores

    def _fill_lls(self, r: int, wins: np.ndarray, wlens: np.ndarray) -> np.ndarray:
        M = len(wins)
        Mpad = _next_pow2(M, 8)
        wins_p = np.concatenate([wins, np.full((Mpad - M, wins.shape[1]), 4, np.int8)])
        wlens_p = np.concatenate([wlens, np.full(Mpad - M, 2, np.int32)])
        feat = self._dev_feats[r]
        rlen = jnp.int32(self._rlens[r])
        if fills_use_pallas():
            # the mutated windows ride the kernel's read axis (one read
            # broadcast against M candidate windows)
            from pbccs_tpu.models.quiver.pallas_fill import (
                pallas_quiver_forward_batch, quiver_loglik_batch)

            feats = QuiverFeatureArrays(
                *(jnp.broadcast_to(t[None], (Mpad,) + t.shape)
                  for t in feat))
            rl = jnp.full(Mpad, rlen, jnp.int32)
            tl = jnp.asarray(wlens_p)
            alpha = pallas_quiver_forward_batch(feats, rl,
                                                jnp.asarray(wins_p), tl,
                                                self.config, self._W)
            lls = quiver_loglik_batch(alpha, rl, tl)
            return np.asarray(lls, np.float64)[:M]

        def one(win, wlen):
            alpha = quiver_forward(feat, rlen, win, wlen, self.config, self._W)
            return quiver_loglik(alpha, rlen, wlen)

        lls = jax.vmap(one)(jnp.asarray(wins_p), jnp.asarray(wlens_p))
        return np.asarray(lls, np.float64)[:M]

    # --------------------------------------------------------------- mutation

    def apply_mutations(self, muts: Sequence[mutlib.Mutation]) -> None:
        if not muts:
            return
        L = len(self.tpl)
        mtp = mutlib.target_to_query_positions(muts, L)
        self.tpl = mutlib.apply_mutations(self.tpl, muts)
        self._tstarts = mtp[np.clip(self._tstarts, 0, L)].astype(np.int32)
        self._tends = mtp[np.clip(self._tends, 0, L)].astype(np.int32)
        self._rebuild(first=False)
