"""Quiver model parameters and per-chemistry configuration table.

Parity targets: QvModelParams / QuiverConfig / QuiverConfigTable
(reference ConsensusCore/include/ConsensusCore/Quiver/QuiverConfig.hpp:78-249,
src/C++/Quiver/QuiverConfig.cpp).  Trained per-chemistry parameter sets are
distributed outside the reference library (GenomicConsensus .ini bundles);
the table ships the same default/alias/fallback lookup mechanics plus an
untrained default set with the reference's test-fixture scale
(src/Tests/ParameterSettings.cpp:47-63)."""

from __future__ import annotations

import dataclasses
from typing import Iterator

# move flags (reference QuiverConfig.hpp:52-59)
INCORPORATE, EXTRA, DELETE, MERGE = 1, 2, 4, 8
BASIC_MOVES = INCORPORATE | EXTRA | DELETE
ALL_MOVES = BASIC_MOVES | MERGE

FALLBACK = "*"


@dataclasses.dataclass(frozen=True)
class QvModelParams:
    """Trained per-chemistry move-score parameters (log scale); affine in
    the QV features: score = param + param_slope * qv."""

    chemistry: str = "unknown"
    model: str = "default"
    match: float = 0.0
    mismatch: float = -10.0
    mismatch_s: float = -0.1
    branch: float = -5.0
    branch_s: float = -0.1
    deletion_n: float = -6.0
    deletion_with_tag: float = -7.0
    deletion_with_tag_s: float = -0.1
    nce: float = -8.0
    nce_s: float = -0.1
    merge: tuple[float, float, float, float] = (-2.0, -2.0, -2.0, -2.0)
    merge_s: tuple[float, float, float, float] = (0.0, 0.0, 0.0, 0.0)


@dataclasses.dataclass(frozen=True)
class BandingOptions:
    """Static band width replaces the reference's adaptive ScoreDiff banding
    (QuiverConfig.hpp:60-75) on TPU; score_diff is kept for parity checks."""

    band_width: int = 96
    score_diff: float = 12.5


@dataclasses.dataclass(frozen=True)
class QuiverConfig:
    qv_params: QvModelParams = QvModelParams()
    moves_available: int = ALL_MOVES
    banding: BandingOptions = BandingOptions()
    fast_score_threshold: float = -12.5
    add_threshold: float = 1.0


class QuiverConfigTable:
    """Chemistry-name -> QuiverConfig with alias + fallback lookup
    (reference QuiverConfig.hpp:196-249, QuiverConfig.cpp:63-140)."""

    def __init__(self) -> None:
        self._table: list[tuple[str, QuiverConfig]] = []

    def _contains(self, name: str) -> bool:
        return any(k == name for k, _ in self._table)

    def insert_default(self, config: QuiverConfig) -> bool:
        return self.insert_as(FALLBACK, config)

    def insert(self, config: QuiverConfig) -> bool:
        name = config.qv_params.chemistry
        if not name:
            raise ValueError("config chemistry name is empty")
        return self.insert_as(name, config)

    def insert_as(self, name: str, config: QuiverConfig) -> bool:
        if self._contains(name):
            return False
        self._table.append((name, config))
        return True

    def at(self, name: str) -> QuiverConfig:
        for k, c in self._table:
            if k == name:
                return c
        for k, c in self._table:
            if k == FALLBACK:
                return c
        raise KeyError(f"no Quiver config for chemistry {name!r} and no default")

    def keys(self) -> list[str]:
        return [k for k, _ in self._table]

    def __len__(self) -> int:
        return len(self._table)

    def __iter__(self) -> Iterator[tuple[str, QuiverConfig]]:
        return iter(self._table)


def default_quiver_config_table() -> QuiverConfigTable:
    table = QuiverConfigTable()
    table.insert_default(QuiverConfig())
    return table
