"""Quiver model family: the QV-feature-based consensus model (the
reference's legacy float/SSE path, ConsensusCore/include/ConsensusCore/
Quiver).  Arrow (models.arrow) is the CCS production path; Quiver is kept
at full capability for GenomicConsensus-style workflows that supply
per-base QV feature tracks."""

from pbccs_tpu.models.quiver.params import (  # noqa: F401
    ALL_MOVES,
    BASIC_MOVES,
    BandingOptions,
    QuiverConfig,
    QuiverConfigTable,
    QvModelParams,
)
from pbccs_tpu.models.quiver.features import QvSequenceFeatures  # noqa: F401
from pbccs_tpu.models.quiver.recursor import (  # noqa: F401
    quiver_forward,
    quiver_backward,
    quiver_loglik,
    quiver_loglik_backward,
)
from pbccs_tpu.models.quiver.scorer import QuiverMultiReadScorer  # noqa: F401
