"""QV feature tracks for the Quiver model (reference
ConsensusCore/include/ConsensusCore/Features.hpp:50-123: QvSequenceFeatures
= sequence + InsQV, SubsQV, DelQV, DelTag, MergeQV)."""

from __future__ import annotations

import dataclasses

import numpy as np

from pbccs_tpu.models.arrow.params import encode_bases


@dataclasses.dataclass
class QvSequenceFeatures:
    """One read's base codes + 5 per-base QV tracks.

    seq: int8 base codes (0..3; 4 = N); qv tracks: float32, one value per
    base.  del_tag is a base *code* track (the likely deleted base before
    each position), compared against template bases by Del()."""

    seq: np.ndarray
    ins_qv: np.ndarray
    subs_qv: np.ndarray
    del_qv: np.ndarray
    del_tag: np.ndarray
    merge_qv: np.ndarray

    def __post_init__(self) -> None:
        n = len(self.seq)
        for name in ("ins_qv", "subs_qv", "del_qv", "del_tag", "merge_qv"):
            if len(getattr(self, name)) != n:
                raise ValueError(f"feature track {name} length != sequence length")

    @classmethod
    def from_str(cls, seq: str, ins_qv=None, subs_qv=None, del_qv=None,
                 del_tag=None, merge_qv=None) -> "QvSequenceFeatures":
        codes = encode_bases(seq)
        n = len(codes)
        zeros = lambda: np.zeros(n, np.float32)
        return cls(codes,
                   np.asarray(ins_qv, np.float32) if ins_qv is not None else zeros(),
                   np.asarray(subs_qv, np.float32) if subs_qv is not None else zeros(),
                   np.asarray(del_qv, np.float32) if del_qv is not None else zeros(),
                   np.asarray(del_tag, np.float32) if del_tag is not None
                   else np.full(n, 4, np.float32),
                   np.asarray(merge_qv, np.float32) if merge_qv is not None else zeros())

    def __len__(self) -> int:
        return len(self.seq)


def flat_default_features(seq: np.ndarray) -> QvSequenceFeatures:
    """Features for a read WITHOUT QV tracks: zero QVs (param-only move
    scores) and an 'N' del-tag (never matches a template base) -- the
    fallback the quiver pipeline/bench use for plain-sequence subreads."""
    codes = np.asarray(seq, np.int8)
    n = len(codes)
    z = np.zeros(n, np.float32)
    return QvSequenceFeatures(codes, z, z, z, np.full(n, 4, np.float32), z)
