"""Arrow model parameters: SNR-conditioned dinucleotide transition model.

The Arrow pair-HMM conditions its per-template-position transition
probabilities {Match, Branch, Stick, Dark(=deletion)} on the dinucleotide
context (current base, next base) and the per-channel signal-to-noise ratio of
the ZMW.  Eight contexts exist: homopolymer contexts AA/CC/GG/TT (next base
equals current) and generic contexts NA/NC/NG/NT.  For each context a trained
3x4 coefficient matrix maps [1, snr, snr^2, snr^3] of the *next* base's
channel SNR through a softmax-with-reference to the four probabilities.

Behavioral parity target: ConsensusCore Arrow ContextParameterProvider
(reference ConsensusCore/src/C++/Arrow/ContextParameterProvider.cpp:23-113)
and TemplateParameterPair construction (TemplateParameterPair.cpp:43-60).
The coefficient tables below are the reference's trained model constants
(model *data*, equivalent to shipped weights).

TPU-first design: instead of a per-position hash-map lookup, the whole
template's transition-parameter track is computed as one vectorized gather +
polynomial evaluation over an int8 base tensor, jit/vmap friendly.
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np

from pbccs_tpu.runtime import tuning as _tuning

# Base encoding used framework-wide: A=0 C=1 G=2 T=3, padding/invalid = 4.
BASE_A, BASE_C, BASE_G, BASE_T, BASE_PAD = 0, 1, 2, 3, 4
N_BASES = 4
BASES = "ACGT"

_BASE_LUT = np.full(256, BASE_PAD, dtype=np.int8)
for _i, _b in enumerate(BASES):
    _BASE_LUT[ord(_b)] = _i
    _BASE_LUT[ord(_b.lower())] = _i


def encode_bases(seq: str) -> np.ndarray:
    """ASCII sequence -> int8 codes (A=0 C=1 G=2 T=3, other=4)."""
    return _BASE_LUT[np.frombuffer(seq.encode("ascii"), dtype=np.uint8)]


def decode_bases(codes: np.ndarray) -> str:
    """int8 codes -> ASCII sequence. Pad codes (>=4) are dropped."""
    codes = np.asarray(codes)
    return "".join(BASES[c] for c in codes if 0 <= c < 4)


_COMPLEMENT = np.array([3, 2, 1, 0, 4], dtype=np.int8)


def revcomp(codes: np.ndarray) -> np.ndarray:
    """Reverse complement of an int8 base vector (pads map to pad)."""
    return _COMPLEMENT[np.asarray(codes)[::-1]]


def revcomp_padded(tpl: "jax.Array", length: "jax.Array") -> "jax.Array":
    """Jittable reverse complement of the first `length` entries of a padded
    int8 template; the tail stays padding (code 4)."""
    Jmax = tpl.shape[0]
    idx = length - 1 - jnp.arange(Jmax, dtype=jnp.int32)
    comp = jnp.asarray(_COMPLEMENT)
    vals = comp[jnp.take(tpl, jnp.clip(idx, 0, Jmax - 1)).astype(jnp.int32)]
    return jnp.where(idx >= 0, vals, 4).astype(jnp.int8)


# Transition-probability channel order used framework-wide.
TRANS_MATCH, TRANS_BRANCH, TRANS_STICK, TRANS_DARK = 0, 1, 2, 3

# Trained SNR-polynomial coefficients.  ctx index = next_base + 4*(cur != next)
# i.e. 0..3 = AA,CC,GG,TT ; 4..7 = NA,NC,NG,NT.   Per context: rows are the
# softmax numerators [Dark, Match, Stick] (Branch is the softmax reference),
# columns are [1, snr, snr^2, snr^3] of the next base's channel SNR.
# Values: reference ContextParameterProvider.cpp:23-66 (trained model data).
CONTEXT_COEFF = np.array(
    [
        [  # AA
            [3.76122480667588, -0.536010820176981, 0.0275375059387171, -0.000470200724345621],
            [3.57517725358548, -0.0257545295375707, -0.000163673803286944, 5.3256984681724e-06],
            [0.858421613302247, -0.0276654216841666, -8.85549766507732e-05, -4.85355908595337e-05],
        ],
        [  # CC
            [5.66725538674764, -1.10462196933913, 0.0879811093908922, -0.00259393800835979],
            [4.11682756767018, -0.124758322644639, 0.00659795177909886, -0.000361914629195461],
            [3.17103818507405, -0.729020290806687, 0.0749784690396837, -0.00262779517495421],
        ],
        [  # GG
            [3.81920778703052, -0.540309003502589, 0.0389569264893982, -0.000901245733796236],
            [3.31322216145728, 0.123514009118836, -0.00807401406655071, 0.000230843924466035],
            [2.06006877520527, -0.451486652688621, 0.0375212898173045, -0.000937676250926241],
        ],
        [  # TT
            [5.39308368236762, -1.32931568057267, 0.107844580241936, -0.00316462903462847],
            [4.21031404956015, -0.347546363361823, 0.0293839179303896, -0.000893802212450644],
            [2.33143889851302, -0.586068444099136, 0.040044954697795, -0.000957298861394191],
        ],
        [  # NA
            [2.35936060895653, -0.463630601682986, 0.0179206897766131, -0.000230839937063052],
            [3.22847830625841, -0.0886820214931539, 0.00555981712798726, -0.000137686231186054],
            [-0.101031042923432, -0.0138783767832632, -0.00153408019582419, 7.66780338484727e-06],
        ],
        [  # NC
            [5.956054206161, -1.71886470811695, 0.153315470604752, -0.00474488595513198],
            [3.89418464416296, -0.174182841558867, 0.0171719290275442, -0.000653629721359769],
            [2.40532887070852, -0.652606650098156, 0.0688783864119339, -0.00246479494650594],
        ],
        [  # NG
            [3.53508304630569, -0.788027301381263, 0.0469367803413207, -0.00106221924705805],
            [2.85440184222226, 0.166346531056167, -0.0166161828155307, 0.000439492705370092],
            [0.238188180807376, 0.0589443522886522, -0.0123401045958974, 0.000336854126836293],
        ],
        [  # NT
            [5.36199280681367, -1.46099908985536, 0.126755291030074, -0.0039102734460725],
            [3.41597143103046, -0.066984162951578, 0.0138944877787003, -0.000558939998921912],
            [1.37371376794871, -0.246963827944892, 0.0209674231346363, -0.000684856715039738],
        ],
    ],
    dtype=np.float64,
)

# Hard-coded trained miscall probability (reference Arrow/ArrowConfig.hpp:52).
MISMATCH_PROBABILITY = 0.00505052456472967


@dataclasses.dataclass(frozen=True)
class ModelParams:
    """Scalar emission parameters of the Arrow HMM.

    Parity: reference Arrow/ArrowConfig.hpp:85-113 (the IQV PMFs there are
    all-ones placeholders, so they are omitted here; re-add as a per-read
    emission track if ever trained).
    """

    pr_miscall: float = MISMATCH_PROBABILITY

    @property
    def pr_not_miscall(self) -> float:
        return 1.0 - self.pr_miscall

    @property
    def pr_third_of_miscall(self) -> float:
        return self.pr_miscall / 3.0


@dataclasses.dataclass(frozen=True)
class BandingOptions:
    """Banded-DP budget. score_diff is in nats (reference BandingOptions;
    pbccs passes 12.5, include/pacbio/ccs/Consensus.h:438).  On TPU the
    adaptive per-column band becomes a static band of `band_width` rows per
    column centered on the main diagonal; `score_diff` is retained for the
    band-adequacy (alpha/beta mismatch) check semantics."""

    score_diff: float = 12.5
    #: None = the per-length-bucket schedule (effective_band_width); an
    #: explicit width always wins (the 2x mating retry relies on this).
    band_width: int | None = None


def effective_band_width(banding: "BandingOptions", jmax: int) -> int:
    """Per-length-bucket band width schedule.

    The round-4 banding counters showed mean band occupancy ~0.60 at every
    short config -- W=96 wastes ~40% of band compute at <=576-column
    buckets -- while long templates need guided rebanding rather than more
    width (ops/fwdbwd.guided_band_offsets).  The schedule runs W=64 at
    short buckets, W=96 above.  An explicitly configured band_width always
    wins (so the pipeline's 2x mating retry escalates the width it asks
    for, even under the env override); PBCCS_BAND_W replaces the
    schedule's default choice only.

    Long buckets (> 8192) run W=96, occupancy-driven (round 6): the
    round-5 schedule ran them at W=128 because the alignment drift after
    a big apply round clipped the W=96 band at the round-1 rebuild with
    TWO guided passes -- one read unmated and the ZMW ran away on weak
    evidence (+834 bases, bucket overflow, round-5 bench draw).  But the
    measured cost of the width was real: cfg3's 15 kb band occupancy was
    0.465 at W=128 (BENCH_r05.json), i.e. more than half the band
    compute, VMEM, and HBM traffic polished empty lanes.  The round-6
    schedule fixes the CAUSE instead of widening around it: long buckets
    run a THIRD argmax-guided refill pass (scorer.guided_fill_passes),
    which re-centers the band on the post-apply path the round-5 failure
    drifted off, and keep W=96.  The mating gate still protects
    correctness (a clipped read drops or triggers the 2x retry, whose
    explicit band_width bypasses this schedule).  PBCCS_BAND_W replaces
    the schedule's choice for A/B measurement.

    The reference's analogue is the adaptive per-column band itself
    (SimpleRecursor.cpp:693-757), which sizes effort to the data; a static
    schedule keyed on the compile-time bucket plus guided re-centering is
    the XLA-friendly form."""
    if banding.band_width is not None:
        return banding.band_width
    env = os.environ.get("PBCCS_BAND_W")
    if env:
        return int(env)
    # tuned-profile default (runtime/tuning.py resolution ladder): an
    # applied `ccs tune` host profile replaces the schedule's choice,
    # exactly like PBCCS_BAND_W but measured instead of hand-picked
    tuned = _tuning.knob_int("band_w")
    if tuned is not None:
        return tuned
    return 64 if jmax <= 576 else 96


@dataclasses.dataclass(frozen=True)
class ArrowConfig:
    """Parity: reference Arrow/ArrowConfig.hpp:112-129."""

    model: ModelParams = dataclasses.field(default_factory=ModelParams)
    banding: BandingOptions = dataclasses.field(default_factory=BandingOptions)
    fast_score_threshold: float = -12.5
    add_threshold: float = float("nan")


def snr_to_transition_table(snr: jax.Array, dtype=jnp.float32) -> jax.Array:
    """Per-ZMW (8, 4) table of transition probabilities from channel SNRs.

    snr: (4,) per-channel SNR in A,C,G,T order.
    Returns table[ctx, {match, branch, stick, dark}], natural scale.

    Parity: ContextParameterProvider::GetTransitionParameters
    (reference ContextParameterProvider.cpp:69-113): numerators
    exp(poly([Dark, Match, Stick])) with Branch the implicit reference
    (numerator 1); probabilities are the softmax over the four.
    """
    snr = jnp.asarray(snr, dtype=jnp.float32)
    coeff = jnp.asarray(CONTEXT_COEFF, dtype=jnp.float32)  # (8, 3, 4)
    # channel of ctx k is (k mod 4): the *next* base of the dinucleotide.
    chan_snr = jnp.tile(snr, 2)  # (8,)
    powers = chan_snr[:, None] ** jnp.arange(4, dtype=jnp.float32)  # (8, 4)
    xb = jnp.exp(jnp.einsum("crp,cp->cr", coeff, powers))  # (8, 3) = Dark,Match,Stick
    denom = 1.0 + jnp.sum(xb, axis=-1)  # (8,)
    dark = xb[:, 0] / denom
    match = xb[:, 1] / denom
    stick = xb[:, 2] / denom
    branch = 1.0 / denom
    return jnp.stack([match, branch, stick, dark], axis=-1).astype(dtype)


def snr_to_transition_table_host(snr: np.ndarray) -> np.ndarray:
    """Float64 host evaluation of snr_to_transition_table.

    The reference evaluates the SNR polynomial + softmax in double
    (ContextParameterProvider.cpp:69-113); in float32 the exp(cubic) is
    sensitive to op ordering, so eager vs jit/vmap evaluation of the jnp
    version can disagree by ~0.4% per probability — enough to shift window
    log-likelihoods by ~0.1 nat.  The table is tiny (8x4 per ZMW), so both
    the per-ZMW and batched scorers compute it here, on host, in float64,
    and feed the result into their jitted programs."""
    snr = np.asarray(snr, np.float64)
    chan_snr = np.tile(snr, 2)  # (8,)
    powers = chan_snr[:, None] ** np.arange(4)  # (8, 4)
    xb = np.exp(np.einsum("crp,cp->cr", CONTEXT_COEFF, powers))  # Dark,Match,Stick
    denom = 1.0 + xb.sum(axis=-1)
    return np.stack(
        [xb[:, 1] / denom, 1.0 / denom, xb[:, 2] / denom, xb[:, 0] / denom],
        axis=-1,
    )


def context_index(cur_base: jax.Array, next_base: jax.Array) -> jax.Array:
    """Dinucleotide context id: next_base + 4 * (cur != next).

    Parity: ContextParameters context-string construction ("AA".."TT" when the
    bases repeat else "N"+next; reference ContextParameters.cpp /
    GetParametersForContext)."""
    return next_base + 4 * (cur_base != next_base).astype(next_base.dtype)


def transition_lookup(cur_base: jax.Array, next_base: jax.Array,
                      table: jax.Array) -> jax.Array:
    """(..., 4) transition rows for dinucleotide contexts, as a one-hot
    matmul on the MXU — the gather form (table[ctx]) lowers to the TPU
    scalar core.  Single source of truth for the clip bounds / dtype /
    precision flags (oriented_window and dense_patch_grids both ride it;
    eager-vs-jit table evaluation drift caused a ~0.1-nat parity bug
    once)."""
    idx = jnp.clip(context_index(cur_base.astype(jnp.int32),
                                 next_base.astype(jnp.int32)), 0, 7)
    onehot = (idx[..., None] == jnp.arange(8)).astype(jnp.float32)
    return jax.lax.dot_general(
        onehot, table.astype(jnp.float32),
        (((onehot.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST)


def template_transition_params(
    tpl: jax.Array, trans_table: jax.Array, length: jax.Array | None = None
) -> jax.Array:
    """Per-position transition track for a template.

    tpl: (L,) int8 base codes (possibly padded).
    trans_table: (8, 4) from snr_to_transition_table.
    length: actual template length (traced scalar) if tpl is padded.

    Returns (L, 4) [match, branch, stick, dark]; position i conditions on
    (tpl[i], tpl[i+1]).  The final position's params are zero, matching the
    reference's sentinel (TemplateParameterPair.cpp:56-58) -- they are never
    read by the recursion.
    """
    tpl = jnp.asarray(tpl)
    L = tpl.shape[0]
    nxt = jnp.roll(tpl, -1)
    ctx = context_index(tpl.astype(jnp.int32), nxt.astype(jnp.int32))
    params = trans_table[jnp.clip(ctx, 0, 7)]  # (L, 4)
    if length is None:
        last = L - 1
    else:
        last = length - 1
    pos = jnp.arange(L)
    valid = pos < last
    return jnp.where(valid[:, None], params, 0.0)
