"""Closed-form per-position moments of the Arrow HMM log-likelihood.

Used for the Z-score gate on reads at AddRead time: a read whose observed
log-likelihood is many standard deviations below the model's expectation is
dropped (reference MultiReadMutationScorer.cpp:295-319).

Parity: ExpectedContextLL / PerBaseMeanAndVariance
(reference ConsensusCore/include/ConsensusCore/Arrow/Expectations.hpp:11-57),
vectorized over template positions.
"""

from __future__ import annotations

import jax.numpy as jnp

from pbccs_tpu.models.arrow.params import (
    TRANS_BRANCH,
    TRANS_DARK,
    TRANS_MATCH,
    TRANS_STICK,
    MISMATCH_PROBABILITY,
)

_TINY = 1e-30


def per_base_mean_and_variance(trans, eps: float = MISMATCH_PROBABILITY):
    """Per-position (mean, variance) of the log-likelihood contribution.

    trans: (L, 4) natural-scale transition track.
    Returns (mean, var), each (L,).  Padded/sentinel positions (all-zero
    transition rows) yield mean=var=0 so masked sums are safe.
    """
    p_m = trans[..., TRANS_MATCH]
    p_b = trans[..., TRANS_BRANCH]
    p_s = trans[..., TRANS_STICK]
    p_d = trans[..., TRANS_DARK]

    l_m = jnp.log(jnp.maximum(p_m, _TINY))
    l_b = jnp.log(jnp.maximum(p_b, _TINY))
    l_s = jnp.log(jnp.maximum(p_s, _TINY))
    l_d = jnp.log(jnp.maximum(p_d, _TINY))

    lg3 = -jnp.log(3.0)
    e_m, e2_m = eps * lg3, eps * lg3 * lg3
    e_d = e2_d = 0.0
    e_b = e2_b = 0.0
    e_s, e2_s = lg3, lg3 * lg3

    def enn(lm, ld, lb, ls, EM, ED, EB, ES):
        md = (lm + EM) * p_m / (p_m + p_d + _TINY) + (ld + ED) * p_d / (p_m + p_d + _TINY)
        ei = (lb + EB) * p_b / (p_b + p_s + _TINY) + (ls + ES) * p_s / (p_b + p_s + _TINY)
        bs = ei * (p_s + p_b) / (p_m + p_d + _TINY)
        return md + bs

    mean = enn(l_m, l_d, l_b, l_s, e_m, e_d, e_b, e_s)
    var = enn(l_m**2, l_d**2, l_b**2, l_s**2, e2_m, e2_d, e2_b, e2_s) - mean * mean

    live = trans.sum(axis=-1) > 0
    return jnp.where(live, mean, 0.0), jnp.where(live, var, 0.0)


def window_zscore(ll, trans, start, end):
    """Z-score of a read's LL over oriented-template positions [start, end-1)
    (the reference sums moments over [TemplateStart, TemplateEnd-1),
    MultiReadMutationScorer.cpp:299-317)."""
    mean, var = per_base_mean_and_variance(trans)
    L = trans.shape[0]
    pos = jnp.arange(L)
    m = (pos >= start) & (pos < end - 1)
    mu = jnp.sum(jnp.where(m, mean, 0.0))
    v = jnp.sum(jnp.where(m, var, 0.0))
    return (ll - mu) / jnp.sqrt(jnp.maximum(v, _TINY))
