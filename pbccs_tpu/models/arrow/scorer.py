"""Multi-read mutation scorer: the per-ZMW polish-stage state machine.

TPU re-design of ArrowMultiReadMutationScorer (reference
ConsensusCore/src/C++/Arrow/MultiReadMutationScorer.cpp): owns the forward and
reverse-complement template tracks, one banded alpha/beta pair per read, and
scores candidate template mutations as batched device calls over the whole
(read x mutation) grid instead of the reference's per-read serial loop.

Host/device split: mutation lists, favorability selection and template
splicing are host-side (they are tiny and data-dependent); window building,
forward/backward fills, Z-scores and mutation scoring are jitted batched
device programs with static (R, M, Imax, Jmax, W) bucket shapes.
"""

from __future__ import annotations

import functools
import os
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pbccs_tpu.models.arrow import mutations as mutlib
from pbccs_tpu.models.arrow.expectations import per_base_mean_and_variance
from pbccs_tpu.models.arrow.params import (
    ArrowConfig,
    effective_band_width,
    revcomp,
    snr_to_transition_table_host,
    template_transition_params,
    transition_lookup,
)
from pbccs_tpu.ops.fwdbwd import (
    backward_loglik,
    banded_backward,
    banded_forward,
    forward_loglik,
)
from pbccs_tpu.ops.fwdbwd import MAX_BAND_ADVANCE as _MAX_BAND_SHIFT
from pbccs_tpu.ops.fwdbwd_pallas import fills_use_pallas
from pbccs_tpu.utils import next_pow2 as _next_pow2
from pbccs_tpu.ops.mutation_score import (
    INS,
    SUB,
    MutationPatch,
    interior_read_scores_fast,
    make_patches_fast,
    scale_prefix,
    scale_suffix,
)

# AddRead outcome codes (reference Arrow/MultiReadMutationScorer.hpp:60-61).
ADD_SUCCESS, ADD_ALPHABETAMISMATCH, ADD_MEM_FAIL, ADD_POOR_ZSCORE, ADD_OTHER = range(5)

_AB_MISMATCH_TOL = 1e-3  # reference SimpleRecursor.cpp:53


def mated_mask(ll_a, ll_b, rlens, tstarts, tends):
    """Reads whose alpha/beta fills mate: |1 - LL_a/LL_b| within tolerance,
    both finite, and read-vs-window slope plausible.  The slope gate
    (rlens <= MAX_BAND_ADVANCE * window span) is deliberate POLICY, not a
    kernel constraint (the circular-lane kernels represent any band
    advance): a read more than ~8x its template window is insert-junk the
    reference also sheds, via AlphaBetaMismatchException
    (SimpleRecursor.cpp:683-688).
    All args are host numpy arrays with matching leading shape."""
    mated = np.abs(1.0 - ll_a / np.where(ll_b == 0, 1.0, ll_b)) <= _AB_MISMATCH_TOL
    mated &= np.isfinite(ll_a) & np.isfinite(ll_b)
    mated &= rlens <= _MAX_BAND_SHIFT * np.maximum(tends - tstarts, 1)
    return mated





def oriented_window(strand, ts, te, tpl_f, tpl_r, L, table):
    """Build one read's oriented template window (bases, transitions, len).

    Only the BASES are gathered — one (Jmax,) gather from the stacked
    fwd/rev template.  The transition track is recomputed from the window
    itself: win_trans[j] = T(win[j], win[j+1]) equals the full-template
    track inside the window (template_transition_params conditions on
    (t[i], t[i+1]); rows j >= wlen-1 are masked to zero either way), and
    the 4-lane f32 trans gather this replaces was ~4/5 of the rebuild's
    scalar-core gather volume on the round-5 device profile.  The (8, 4)
    table lookup rides a tiny one-hot matmul, not a gather."""
    Jmax = tpl_f.shape[0]
    ws = jnp.where(strand == 0, ts, L - te)
    wlen = te - ts
    idx = jnp.arange(Jmax, dtype=jnp.int32)
    src = jnp.clip(ws + idx, 0, Jmax - 1)
    both = jnp.concatenate([tpl_f, tpl_r])
    base = both[jnp.where(strand == 0, 0, Jmax) + src]
    win_tpl = jnp.where(idx < wlen, base, 4).astype(jnp.int8)
    w32 = win_tpl.astype(jnp.int32)
    params = transition_lookup(w32, jnp.roll(w32, -1), table)
    win_trans = jnp.where((idx < wlen - 1)[:, None], params, 0.0)
    return win_tpl, win_trans, wlen


def guided_fill_passes(jmax: int) -> int:
    """How many argmax-guided refill ("flip-flop") passes the fill dispatch
    runs after the diagonal-band fill at this template bucket.

    At long templates the alignment path's indel random walk drifts
    ~sqrt(L) rows off the straight diagonal; past ~W/2 the fixed band
    clips real probability mass -- alpha and beta stay CONSISTENT (same
    band) so the mating gate passes, but the likelihood surface is wrong
    and polish accuracy collapses (the round-4 15 kb regression).  Guided
    refills re-center the band on the observed path (fwdbwd.
    guided_band_offsets), the TPU analogue of the reference's guide-matrix
    rebanding + flip-flop (SimpleRecursor.cpp:642-757).  Short templates
    drift well within W/2 (measured +-16 rows at 2 kb) and skip the cost.

    Env override PBCCS_GUIDED: integer pass count, or 0 to disable.

    Thresholds from the drift model (std ~ sqrt(2 * p_indel * L) rows):
    at 2 kb measured drift is +-16 (well inside W/2 = 48, no passes); at
    3 kb ~2 sigma reaches W/2 (start guiding); by 8 kb+ the diagonal can
    be multiple band-widths off.  Buckets past 8 kb run THREE passes
    (round 6): the third pass is what lets the occupancy-driven W
    schedule (params.effective_band_width) hold W=96 at 15 kb -- the
    round-5 W=128 escape hatch existed because two passes left one read's
    post-apply drift outside a 96-row band.  Re-centering is O(fill) and
    shares the fill executables; width is paid on every fill, score, and
    VMEM byte of the polish."""
    env = os.environ.get("PBCCS_GUIDED")
    if env is not None:
        return max(0, int(env))
    if jmax <= 3072:
        return 0
    return 1 if jmax <= 8192 else 3


def fill_alpha_beta_batch(reads, rlens, win_tpl, win_trans, wlens, width: int,
                          use_pallas: bool | None = None, offsets=None,
                          guided_passes: int = 0):
    """Batched alpha/beta fills + log-likelihoods + scale prefixes.

    Dispatches to the Pallas TPU kernel (ops.fwdbwd_pallas) when available,
    else the pure-JAX banded path.  All args carry a leading read-batch axis.
    Returns (alpha, beta, ll_a, ll_b, alpha_prefix, beta_suffix).

    `use_pallas` must be resolved by the caller when this runs under jit --
    the dispatch is a trace-time decision, so jitted callers thread it
    through as a static argument (else a stale executable would silently
    ignore a changed PBCCS_PALLAS).

    `offsets` (R, nc) pins the band layout (e.g. carried from a previous
    round's guided fill); `guided_passes` > 0 additionally re-centers the
    band on the alpha argmax path and refills that many times (static
    trace-time count -- see guided_fill_passes)."""
    from pbccs_tpu.ops.fwdbwd import BandedMatrix, guided_band_offsets

    alpha, ll_a = _fill_alpha(reads, rlens, win_tpl, win_trans, wlens,
                              width, use_pallas, offsets)
    for _ in range(guided_passes):
        g_off = jax.vmap(
            lambda av, ao, i, jl: guided_band_offsets(av, ao, i, jl, width)
        )(alpha.vals, alpha.offsets, rlens, wlens)
        alpha_g, ll_g = _fill_alpha(reads, rlens, win_tpl, win_trans, wlens,
                                    width, use_pallas, g_off)
        # keep-better per read: a re-centered band normally recovers the
        # probability mass the diagonal band clipped, but when the first
        # fill locked onto a wrong ridge the guided band can LOSE mass --
        # never trade down (same keep-better-width rule as the host's 2x
        # band retry, and the reference's flip-flop acceptance test)
        keep = ll_g >= ll_a
        alpha = BandedMatrix(
            jnp.where(keep[:, None, None], alpha_g.vals, alpha.vals),
            jnp.where(keep[:, None], alpha_g.offsets, alpha.offsets),
            jnp.where(keep[:, None], alpha_g.log_scales, alpha.log_scales))
        ll_a = jnp.where(keep, ll_g, ll_a)
    beta, ll_b = _fill_beta(reads, rlens, win_tpl, win_trans, wlens,
                            width, use_pallas,
                            alpha.offsets if guided_passes else offsets)
    apre = jax.vmap(scale_prefix)(alpha.log_scales)
    bsuf = jax.vmap(scale_suffix)(beta.log_scales)
    return alpha, beta, ll_a, ll_b, apre, bsuf


def _fill_alpha(reads, rlens, win_tpl, win_trans, wlens, width: int,
                use_pallas: bool | None, offsets):
    from pbccs_tpu.ops import fwdbwd_pallas as fpal

    if use_pallas is None:
        use_pallas = fpal.fills_use_pallas()
    if use_pallas:
        alpha = fpal.pallas_forward_batch(reads, rlens, win_tpl, win_trans,
                                          wlens, width, offsets=offsets)
        return alpha, fpal.forward_loglik_batch(alpha, rlens, wlens)
    alpha = jax.vmap(
        lambda r, i, t, tr, j, o: banded_forward(r, i, t, tr, j, width,
                                                 offsets=o),
        in_axes=(0, 0, 0, 0, 0, None if offsets is None else 0),
    )(reads, rlens, win_tpl, win_trans, wlens, offsets)
    return alpha, jax.vmap(forward_loglik)(alpha, rlens, wlens)


def _fill_beta(reads, rlens, win_tpl, win_trans, wlens, width: int,
               use_pallas: bool | None, offsets):
    from pbccs_tpu.ops import fwdbwd_pallas as fpal

    if use_pallas is None:
        use_pallas = fpal.fills_use_pallas()
    if use_pallas:
        beta = fpal.pallas_backward_batch(reads, rlens, win_tpl, win_trans,
                                          wlens, width, offsets=offsets)
        return beta, fpal.backward_loglik_batch(beta, wlens)
    beta = jax.vmap(
        lambda r, i, t, tr, j, o: banded_backward(r, i, t, tr, j, width,
                                                  offsets=o),
        in_axes=(0, 0, 0, 0, 0, None if offsets is None else 0),
    )(reads, rlens, win_tpl, win_trans, wlens, offsets)
    return beta, jax.vmap(backward_loglik)(beta, wlens)


def fill_alpha_beta_batch_zr(reads, rlens, win_tpl, win_trans, wlens,
                             width: int, use_pallas: bool, mesh=None,
                             guided_passes: int = 0):
    """(Z, R)-leading alpha/beta fills + log-likelihoods + scale prefixes.

    Unsharded (mesh=None) this flattens to the (Z*R,) read batch and
    delegates to fill_alpha_beta_batch.  Under a ('zmw','read') mesh with
    the Pallas kernel enabled, the fills run inside jax.shard_map: each
    device flattens ITS OWN (Z/nz, R/nr) block and launches the kernel on
    it -- pallas_call has no GSPMD partitioning rule, so without this
    wrapper mesh runs had to fall back to the pure-JAX fill path and
    forfeit the kernel's measured ~69x single-chip advantage.  Reads are
    independent, so no collectives are needed in the body; boundary
    shardings match the batch arrays' native P('zmw','read') layout."""
    Z, R = reads.shape[:2]
    flat = lambda a: a.reshape((Z * R,) + a.shape[2:])
    unflat = lambda a: a.reshape((Z, R) + a.shape[1:])

    if mesh is None or not use_pallas:
        out = fill_alpha_beta_batch(flat(reads), flat(rlens), flat(win_tpl),
                                    flat(win_trans), flat(wlens), width,
                                    use_pallas, guided_passes=guided_passes)
        return jax.tree.map(unflat, out)

    from jax.sharding import PartitionSpec
    from pbccs_tpu.parallel.mesh import READ_AXIS, ZMW_AXIS, shard_map

    def body(r, i, t, tr, j):
        # each device runs the unsharded path on its local (Z/nz, R/nr) block
        return fill_alpha_beta_batch_zr(r, i, t, tr, j, width, True, None,
                                        guided_passes=guided_passes)

    spec = PartitionSpec(ZMW_AXIS, READ_AXIS)
    # check_vma=False: pallas_call's out_shapes carry no varying-mesh-axes
    # metadata; the body is per-read elementwise so nothing varies anyway
    return shard_map(body, mesh=mesh, in_specs=spec, out_specs=spec,
                     check_vma=False)(
        reads, rlens, win_tpl, win_trans, wlens)


@functools.partial(jax.jit, static_argnames=("width", "use_pallas",
                                             "guided_passes"))
def _setup_reads(reads, rlens, strands, tstarts, tends,
                 tpl_f, tpl_r, L, table, width: int,
                 use_pallas: bool, guided_passes: int = 0):
    """Build per-read oriented windows and fill alpha/beta for each read."""
    win_tpl, win_trans, wlens = jax.vmap(
        lambda s, a, b: oriented_window(s, a, b, tpl_f, tpl_r, L, table)
    )(strands, tstarts, tends)
    alpha, beta, ll_a, ll_b, apre, bsuf = fill_alpha_beta_batch(
        reads, rlens, win_tpl, win_trans, wlens, width, use_pallas,
        guided_passes=guided_passes)
    return (win_tpl, win_trans, wlens, alpha, beta, ll_a, ll_b, apre, bsuf)


def window_moments(strand, ts, te, mean_f, var_f, mean_r, var_r, L):
    """(mu, var) of E[log-lik] over one read's window of the oriented
    template (closed-form HMM moments, Expectations.hpp:45).

    Note: the reference indexes the reverse template's moments with
    forward-frame coordinates (MultiReadMutationScorer.cpp:299-317); we use
    the read's actual window on the oriented template, which is the intended
    statistic (documented deviation)."""
    s = jnp.where(strand == 0, ts, L - te)
    e = jnp.where(strand == 0, te, L - ts)
    pos = jnp.arange(mean_f.shape[0])
    m = (pos >= s) & (pos < e - 1)
    mu = jnp.sum(jnp.where(m, jnp.where(strand == 0, mean_f, mean_r), 0.0))
    v = jnp.sum(jnp.where(m, jnp.where(strand == 0, var_f, var_r), 0.0))
    return mu, v


@jax.jit
def _read_moments(strands, tstarts, tends, trans_f, trans_r, L):
    """Per-read (mu, var) over each read's oriented window."""
    mean_f, var_f = per_base_mean_and_variance(trans_f)
    mean_r, var_r = per_base_mean_and_variance(trans_r)

    def one(strand, ts, te):
        return window_moments(strand, ts, te, mean_f, var_f, mean_r, var_r, L)

    return jax.vmap(one)(strands, tstarts, tends)


@jax.jit
def _make_patches(tpl, trans, trans_table, L, pos, mtype, new_base):
    return make_patches_fast(tpl, trans, trans_table, L, pos, mtype, new_base)


def interior_read_scores(read, rlen, strand, ts, te, wt, wtr, wl,
                         alpha, beta, apre, bsuf,
                         mpos_f, mend_f, mtype,
                         patches_f: MutationPatch, patches_r: MutationPatch):
    """(M,) absolute mutated-template log-likelihoods of one read via
    extend+link, given forward-frame mutation arrays + fwd/rev patches.

    Routed through the gather-free batched scorer
    (ops.mutation_score.interior_read_scores_fast); the per-mutation
    extend_link_score path it replaced is kept in ops.mutation_score as the
    reference implementation, with parity enforced by
    tests/test_mutation_fast.py."""
    return interior_read_scores_fast(read, rlen, strand, ts, te, wt, wtr, wl,
                                     alpha, beta, apre, bsuf,
                                     mpos_f, mend_f, mtype,
                                     patches_f, patches_r)


@jax.jit
def _score_interior(reads, rlens, strands, tstarts, tends,
                    win_tpl, win_trans, wlens,
                    alpha_vals, alpha_offs, alpha_ls,
                    beta_vals, beta_offs, beta_ls,
                    a_prefix, b_suffix,
                    mpos_f, mend_f, mtype,
                    patches_f: MutationPatch, patches_r: MutationPatch):
    """(R, M) absolute mutated-template log-likelihoods via extend+link."""
    from pbccs_tpu.ops.fwdbwd import BandedMatrix

    def per_read(read, rlen, strand, ts, te, wt, wtr, wl,
                 av, ao, als, bv, bo, bls, apre, bsuf):
        return interior_read_scores(
            read, rlen, strand, ts, te, wt, wtr, wl,
            BandedMatrix(av, ao, als), BandedMatrix(bv, bo, bls), apre, bsuf,
            mpos_f, mend_f, mtype, patches_f, patches_r)

    return jax.vmap(per_read)(reads, rlens, strands, tstarts, tends,
                              win_tpl, win_trans, wlens,
                              alpha_vals, alpha_offs, alpha_ls,
                              beta_vals, beta_offs, beta_ls,
                              a_prefix, b_suffix)


@functools.partial(jax.jit, static_argnames=("width", "use_pallas"))
def _score_edge(reads, rlens, win_tpl, win_trans, wlens,
                pair_read, pair_p, pair_type,
                patch_bases, patch_trans, patch_shift, width: int,
                use_pallas: bool):
    """(E,) absolute LLs via full banded refill of the mutated window.

    Per-pair read/window rows are picked with one-hot matmuls (runtime-index
    row gathers lower to the TPU scalar core) and the mutated windows are
    built densely with static shifts; the (E,) fills then run through the
    batched fill dispatch (Pallas kernel on TPU)."""
    from pbccs_tpu.ops.fwdbwd_pallas import (
        forward_loglik_batch, pallas_forward_batch)
    from pbccs_tpu.ops.mutation_score import _row_select, mutated_windows_per_pair

    R, Imax = reads.shape
    Jm = win_tpl.shape[1]
    reads_e = _row_select(pair_read, reads.astype(jnp.float32)).astype(jnp.int8)
    sel = _row_select(pair_read, jnp.concatenate(
        [rlens[:, None].astype(jnp.float32),
         wlens[:, None].astype(jnp.float32),
         win_tpl.astype(jnp.float32)], axis=1))
    rlens_e = sel[:, 0].astype(jnp.int32)
    wlens_e = sel[:, 1].astype(jnp.int32)
    wt_e = sel[:, 2:].astype(jnp.int32)
    wtr_e = _row_select(pair_read, win_trans.reshape(R, Jm * 4)).reshape(-1, Jm, 4)

    patch = MutationPatch(patch_bases, patch_trans, patch_shift)
    bases, trans, new_lens = mutated_windows_per_pair(
        wt_e, wtr_e, wlens_e, pair_p, pair_type, patch)

    if use_pallas:
        alpha = pallas_forward_batch(reads_e, rlens_e, bases, trans,
                                     new_lens, width)
        return forward_loglik_batch(alpha, rlens_e, new_lens)
    alpha = jax.vmap(lambda r, i, t, tr, j: banded_forward(r, i, t, tr, j, width))(
        reads_e, rlens_e, bases, trans, new_lens)
    return jax.vmap(forward_loglik)(alpha, rlens_e, new_lens)


class ArrowMultiReadScorer:
    """Per-ZMW polish state (MultiReadMutationScorer equivalent).

    Reads are provided pre-mapped (strand + [tstart, tend) template window
    from the draft stage).  AddRead gating (alpha/beta mating + Z-score,
    reference MultiReadMutationScorer.cpp:276-325) happens in batch at
    construction; gate outcomes are in `self.statuses`.
    """

    def __init__(self, tpl: np.ndarray, snr: np.ndarray,
                 read_codes: Sequence[np.ndarray], strands: Sequence[int],
                 tstarts: Sequence[int], tends: Sequence[int],
                 config: ArrowConfig | None = None,
                 min_zscore: float = float("nan"),
                 imax: int | None = None, jmax: int | None = None):
        self.config = config or ArrowConfig()
        self.snr = np.asarray(snr, np.float64)
        self.tpl = np.asarray(tpl, np.int8)
        self.n_reads = len(read_codes)
        self.min_zscore = min_zscore

        R = _next_pow2(self.n_reads, 4)
        self._R = R
        self._Imax = imax or _next_pow2(max(len(r) for r in read_codes) + 8, 64)
        self._Jmax = jmax or _next_pow2(len(tpl) + 8, 64)
        self._W = effective_band_width(self.config.banding, self._Jmax)

        self._reads = np.full((R, self._Imax), 4, np.int8)
        self._rlens = np.zeros(R, np.int32)
        for i, rc in enumerate(read_codes):
            n = min(len(rc), self._Imax)
            self._reads[i, :n] = rc[:n]
            self._rlens[i] = n
        self._strands = np.zeros(R, np.int32)
        self._strands[: self.n_reads] = strands
        self._tstarts = np.zeros(R, np.int32)
        self._tstarts[: self.n_reads] = tstarts
        self._tends = np.zeros(R, np.int32)
        self._tends[: self.n_reads] = tends
        # padding rows: map to a trivial window to keep kernels finite
        for i in range(self.n_reads, R):
            self._rlens[i] = 2
            self._reads[i, :2] = [0, 0]
            self._tends[i] = min(2, len(tpl))

        self.trans_table = jnp.asarray(
            snr_to_transition_table_host(self.snr), jnp.float32)
        self.active = np.zeros(R, bool)
        self.statuses = np.full(self.n_reads, ADD_OTHER, np.int32)
        self.zscores = np.full(self.n_reads, np.nan)
        self.band_retried = False
        self.n_band_retries = 0

        self._rebuild(first=True)
        failed = self.statuses == ADD_ALPHABETAMISMATCH
        if failed.any():
            # The reference refills a mismatched alpha/beta pair up to 5
            # times with rebanding before dropping the read
            # (SimpleRecursor.cpp:642-691).  The static-band analogue is one
            # escalation of the whole scorer to a 2x band -- per-read widths
            # would break the (R, J+1, W) lockstep shapes.  Escalation is
            # kept only when it MATES more reads: for insert-heavy reads the
            # float32 in-column dynamic range (~87 nats/column) binds before
            # band coverage does, and a wider band can then lose mass and
            # unmate reads the narrow band kept, so the better width wins.
            # The first build is snapshotted so the revert (the common case)
            # and any failure of the speculative wide build (e.g. device
            # memory) restore it without a third set of fills.
            snap = {k: getattr(self, k) for k in self._RETRY_SNAPSHOT}
            gates = (self.statuses.copy(), self.active.copy(),
                     self.zscores.copy())
            w0 = self._W
            n0 = int((self.statuses != ADD_ALPHABETAMISMATCH).sum())
            try:
                self._W *= 2
                self._reset_gates()
                self._rebuild(first=True)
                better = int((self.statuses
                              != ADD_ALPHABETAMISMATCH).sum()) > n0
            except Exception:  # noqa: BLE001 -- speculative build only
                better = False
            if better:
                self.band_retried = True
                self.n_band_retries = int(
                    (failed & (self.statuses != ADD_ALPHABETAMISMATCH)).sum())
            else:
                self._W = w0
                for k, v in snap.items():
                    setattr(self, k, v)
                self.statuses, self.active, self.zscores = gates

    # ------------------------------------------------------------------ setup

    _RETRY_SNAPSHOT = (
        "tpl_f", "trans_f", "tpl_r", "trans_r", "win_tpl", "win_trans",
        "wlens", "alpha", "beta", "a_prefix", "b_suffix", "baselines",
        "_ll_mu", "_ll_var")

    def _reset_gates(self) -> None:
        self.statuses[:] = ADD_OTHER
        self.active[:] = False
        self.zscores[:] = np.nan

    def _template_tensors(self):
        L = len(self.tpl)
        padded = np.full(self._Jmax, 4, np.int8)
        padded[:L] = self.tpl
        tpl_f = jnp.asarray(padded)
        trans_f = template_transition_params(tpl_f, self.trans_table, L)
        rc = np.full(self._Jmax, 4, np.int8)
        rc[:L] = revcomp(self.tpl)
        tpl_r = jnp.asarray(rc)
        trans_r = template_transition_params(tpl_r, self.trans_table, L)
        return tpl_f, trans_f, tpl_r, trans_r

    def _rebuild(self, first: bool = False):
        """(Re)build windows + alpha/beta for all reads against self.tpl.

        On the first build, gate reads (mating + Z-score).  On rebuilds after
        ApplyMutations, only the mating check can deactivate reads
        (reference MultiReadMutationScorer.cpp:237-267)."""
        L = len(self.tpl)
        self.tpl_f, self.trans_f, self.tpl_r, self.trans_r = self._template_tensors()
        (self.win_tpl, self.win_trans, self.wlens, self.alpha, self.beta,
         ll_a, ll_b, self.a_prefix, self.b_suffix) = _setup_reads(
            jnp.asarray(self._reads), jnp.asarray(self._rlens),
            jnp.asarray(self._strands), jnp.asarray(self._tstarts),
            jnp.asarray(self._tends),
            self.tpl_f, self.tpl_r, jnp.int32(L), self.trans_table,
            self._W, fills_use_pallas(),
            guided_fill_passes(self._Jmax))

        ll_a = np.asarray(ll_a, np.float64)
        ll_b = np.asarray(ll_b, np.float64)
        self.baselines = ll_b
        mated = mated_mask(ll_a, ll_b, self._rlens, self._tstarts, self._tends)

        mu, var = _read_moments(
            jnp.asarray(self._strands), jnp.asarray(self._tstarts),
            jnp.asarray(self._tends), self.trans_f, self.trans_r, jnp.int32(L))
        self._ll_mu = np.asarray(mu, np.float64)
        self._ll_var = np.asarray(var, np.float64)

        if first:
            z = (ll_b - self._ll_mu) / np.sqrt(np.maximum(self._ll_var, 1e-12))
            for i in range(self.n_reads):
                if not mated[i]:
                    self.statuses[i] = ADD_ALPHABETAMISMATCH
                    self.active[i] = False
                    continue
                self.zscores[i] = z[i]
                if not np.isnan(self.min_zscore) and (
                        not np.isfinite(z[i]) or z[i] < self.min_zscore):
                    self.statuses[i] = ADD_POOR_ZSCORE
                    self.active[i] = False
                else:
                    self.statuses[i] = ADD_SUCCESS
                    self.active[i] = True
        else:
            self.active[: self.n_reads] &= mated[: self.n_reads]
        self.active[self.n_reads:] = False

    # ------------------------------------------------------------- scoring

    def baseline_total(self) -> float:
        return float(self.baselines[self.active].sum())

    def global_zscore(self) -> float:
        """Z-score of the summed log-likelihood over all active reads
        (reference MultiReadMutationScorer::ZScores global statistic,
        Arrow/MultiReadMutationScorer.hpp:174-263)."""
        act = self.active
        if not act.any():
            return float("nan")
        var = self._ll_var[act].sum()
        if var <= 0:
            return float("nan")
        ll = self.baselines[act].sum()
        return float((ll - self._ll_mu[act].sum()) / np.sqrt(var))

    def _mutation_arrays(self, muts: Sequence[mutlib.Mutation]):
        L = len(self.tpl)
        M = len(muts)
        pos_f = np.array([m.start for m in muts], np.int32)
        end_f = np.array([m.end for m in muts], np.int32)
        mtype = np.array([m.mtype for m in muts], np.int32)
        base_f = np.array([m.new_base for m in muts], np.int32)
        rcm = [mutlib.reverse_complement_mutation(m, L) for m in muts]
        pos_r = np.array([m.start for m in rcm], np.int32)
        base_r = np.array([m.new_base for m in rcm], np.int32)
        return pos_f, end_f, mtype, base_f, pos_r, base_r

    def score_mutations(self, muts: Sequence[mutlib.Mutation]) -> np.ndarray:
        """Sum over active overlapping reads of (LL(mutated) - LL(current)).

        Parity: MultiReadMutationScorer::Score (MultiReadMutationScorer.cpp:
        339-368) without the serial FastScore early-exit (the masked batched
        sum makes the same favorability decisions)."""
        if not muts:
            return np.zeros(0)
        L = len(self.tpl)
        R, nR = self._R, self.n_reads
        pos_f, end_f, mtype, base_f, pos_r, base_r = self._mutation_arrays(muts)
        M = len(muts)
        Mpad = _next_pow2(M, 16)
        pad = lambda a, fill: np.concatenate([a, np.full(Mpad - M, fill, a.dtype)])
        pos_fp, end_fp = pad(pos_f, L // 2), pad(end_f, L // 2 + 1)
        mtypep, base_fp = pad(mtype, SUB), pad(base_f, 0)
        pos_rp, base_rp = pad(pos_r, L // 2), pad(base_r, 0)

        patches_f = _make_patches(self.tpl_f.astype(jnp.int32), self.trans_f,
                                  self.trans_table, jnp.int32(L),
                                  jnp.asarray(pos_fp), jnp.asarray(mtypep),
                                  jnp.asarray(base_fp))
        patches_r = _make_patches(self.tpl_r.astype(jnp.int32), self.trans_r,
                                  self.trans_table, jnp.int32(L),
                                  jnp.asarray(pos_rp), jnp.asarray(mtypep),
                                  jnp.asarray(base_rp))

        # host-side classification per (read, mut): overlap, window coords,
        # interior vs edge
        ts = self._tstarts[:, None]
        te = self._tends[:, None]
        strand = self._strands[:, None]
        ms, me = pos_f[None, :], end_f[None, :]
        is_ins = (mtype == INS)[None, :]
        overlap = np.where(is_ins, (ts <= me) & (ms <= te), (ts < me) & (ms < te))
        p_w = np.where(strand == 0, ms - ts, te - me)
        e_w = np.where(strand == 0, me - ts, te - ms)
        wlen = (te - ts)
        interior = (p_w >= 3) & (e_w <= wlen - 2)
        act = self.active[:, None]
        valid = act & overlap
        int_mask = valid & interior
        edge_mask = valid & ~interior

        abs_ll = np.asarray(_score_interior(
            jnp.asarray(self._reads), jnp.asarray(self._rlens),
            jnp.asarray(self._strands), jnp.asarray(self._tstarts),
            jnp.asarray(self._tends),
            self.win_tpl, self.win_trans, self.wlens,
            self.alpha.vals, self.alpha.offsets, self.alpha.log_scales,
            self.beta.vals, self.beta.offsets, self.beta.log_scales,
            self.a_prefix, self.b_suffix,
            jnp.asarray(pos_fp), jnp.asarray(end_fp), jnp.asarray(mtypep),
            patches_f, patches_r), np.float64)[:, :M]

        totals = np.where(int_mask, abs_ll - self.baselines[:, None], 0.0).sum(axis=0)

        # edge pairs via full refill
        er, em_ = np.nonzero(edge_mask)
        if len(er):
            E = len(er)
            Epad = _next_pow2(E, 8)
            pr = np.zeros(Epad, np.int32)
            pp = np.zeros(Epad, np.int32)
            pt = np.zeros(Epad, np.int32)
            pr[:E] = er
            pp[:E] = p_w[er, em_]
            pt[:E] = mtype[em_]
            pb = np.zeros((Epad, 2), np.int32)
            ptr = np.zeros((Epad, 2, 4), np.float32)
            psh = np.zeros(Epad, np.int32)
            pf_b = np.asarray(patches_f.bases)
            pf_t = np.asarray(patches_f.trans)
            pf_s = np.asarray(patches_f.shift)
            pr_b = np.asarray(patches_r.bases)
            pr_t = np.asarray(patches_r.trans)
            pr_s = np.asarray(patches_r.shift)
            fwd = self._strands[er] == 0
            pb[:E] = np.where(fwd[:, None], pf_b[em_], pr_b[em_])
            ptr[:E] = np.where(fwd[:, None, None], pf_t[em_], pr_t[em_])
            psh[:E] = np.where(fwd, pf_s[em_], pr_s[em_])
            edge_ll = np.asarray(_score_edge(
                jnp.asarray(self._reads), jnp.asarray(self._rlens),
                self.win_tpl, self.win_trans, self.wlens,
                jnp.asarray(pr), jnp.asarray(pp), jnp.asarray(pt),
                jnp.asarray(pb), jnp.asarray(ptr), jnp.asarray(psh),
                self._W, fills_use_pallas()), np.float64)[:E]
            np.add.at(totals, em_, edge_ll - self.baselines[er])

        return totals

    # ------------------------------------------------------------- mutation

    def apply_mutations(self, muts: Sequence[mutlib.Mutation]) -> None:
        """Splice mutations into the template, remap read windows, refill.

        Parity: MultiReadMutationScorer::ApplyMutations
        (MultiReadMutationScorer.cpp:237-267)."""
        if not muts:
            return
        L = len(self.tpl)
        mtp = mutlib.target_to_query_positions(muts, L)
        self.tpl = mutlib.apply_mutations(self.tpl, muts)
        newJ = _next_pow2(len(self.tpl) + 8, 64)
        if newJ != self._Jmax:
            self._Jmax = newJ
        self._tstarts = mtp[np.clip(self._tstarts, 0, L)].astype(np.int32)
        self._tends = mtp[np.clip(self._tends, 0, L)].astype(np.int32)
        self._rebuild(first=False)

    # ------------------------------------------------------------------- QVs

    def consensus_qvs(self) -> np.ndarray:
        """Per-position QVs from single-base mutation scores, via the
        generic sweep shared with Quiver (models.arrow.refine.consensus_qvs;
        reference ConsensusQVs, Consensus-inl.hpp:277-297)."""
        from pbccs_tpu.models.arrow.refine import consensus_qvs

        return consensus_qvs(self)
