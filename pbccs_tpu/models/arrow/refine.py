"""Consensus refinement: iterative greedy mutation testing.

Host-driven outer loop (the mutation choice is sequential and data-dependent)
around batched device scoring rounds -- the TPU shape of the reference's
AbstractRefineConsensus (reference ConsensusCore/include/ConsensusCore/
Consensus-inl.hpp:160-245) with matching selection semantics: favorable =
score above a noise floor (favorability_threshold -- the reference's own
acceptance test is `sum > 0.04` nats, a FIXED f64 threshold,
MultiReadMutationScorer.cpp:56; ours scales with the f32 noise magnitude
instead, a deliberate documented deviation -- see the
FAVORABILITY_NOISE_FLOOR note below and docs/PARITY.md), greedy
well-separated best subset, template-hash cycle avoidance, neighborhood
re-scans after round 0.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from pbccs_tpu.models.arrow import mutations as mutlib
from pbccs_tpu.models.arrow.scorer import ArrowMultiReadScorer


@dataclasses.dataclass(frozen=True)
class RefineOptions:
    """Defaults: reference Consensus.hpp:55-61."""

    max_iterations: int = 40
    mutation_separation: int = 10
    mutation_neighborhood: int = 20


@dataclasses.dataclass
class RefineResult:
    converged: bool
    n_tested: int = 0
    n_applied: int = 0
    iterations: int = 0


#: Relative f32 score-noise floor for favorability.  The reference accepts
#: a mutation when its summed score clears a FIXED threshold of +0.04 nats
#: in f64 (MultiReadMutationScorer.cpp:56 -- NOT the bare `score > 0` an
#: earlier revision of this comment claimed; the templated refine loop's
#: `score > 0` test, Consensus-inl.hpp:208, runs against scores that
#: already had the 0.04 subtracted).  With float32 fills the accumulated
#: rounding error on a mutation delta grows with the log-likelihood
#: magnitude — measured ~0.05 nats at a 15 kb x 3-read ZMW
#: (sum |baseline| ~ 5e4), where sub-noise "favorable" deltas of
#: +0.002..0.05 in BOTH directions of an insert/delete pair ping-ponged the
#: refinement loop to its iteration budget (the reference converges 4/4 on
#: the same draw; the worst measured two-sided flip was ~1.1e-6 relative).
#: DELIBERATE SCALED-FLOOR DEVIATION (documented in docs/PARITY.md): we
#: scale the threshold with sum |baseline| instead of adopting the fixed
#: 0.04 — a fixed floor is both too LOOSE at long templates (f32 noise
#: reaches ~0.05 nats, above it) and unnecessarily strict at short ones
#: (~0.007 nats at the 300 bp headline, two orders below typical true
#: deltas, where 0.04 would reject real sub-0.04 refinements the f32
#: arithmetic resolves fine).
FAVORABILITY_NOISE_FLOOR = 2.5e-6


def favorability_threshold(abs_baseline_sum) -> float:
    """Minimum score a mutation must beat to count as favorable."""
    return FAVORABILITY_NOISE_FLOOR * abs_baseline_sum


def refine_consensus(scorer: ArrowMultiReadScorer,
                     opts: RefineOptions | None = None) -> RefineResult:
    """Iteratively apply favorable mutations until none remain (converged)
    or the iteration budget runs out (non-convergent)."""
    opts = opts or RefineOptions()
    res = RefineResult(converged=False)
    tpl_history: set[int] = set()
    favorable: list[mutlib.Mutation] = []

    for it in range(opts.max_iterations):
        res.iterations = it + 1
        if it == 0:
            muts = mutlib.enumerate_unique(scorer.tpl)
        else:
            muts = mutlib.unique_nearby_mutations(scorer.tpl, favorable,
                                                  opts.mutation_neighborhood)
        res.n_tested += len(muts)
        scores = scorer.score_mutations(muts)
        eps = favorability_threshold(
            float(np.abs(scorer.baselines[scorer.active]).sum()))
        favorable = [m.with_score(s) for m, s in zip(muts, scores) if s > eps]
        if not favorable:
            res.converged = True
            break

        best = mutlib.best_subset(favorable, opts.mutation_separation)

        # cycle avoidance (Consensus-inl.hpp:229-241): a multi-mutation
        # subset whose result was already visited is trimmed to its best
        # single mutation.  Like the reference, a repeated template does
        # NOT terminate the loop: applying the mutation and iterating on
        # lets mutations elsewhere shift the cycling site's score and
        # break the cycle (observed to recover otherwise-lost ZMWs); a
        # persistent cycle runs out the iteration budget and ends
        # non-convergent, exactly as the reference's does.
        if len(best) > 1:
            next_tpl = mutlib.apply_mutations(scorer.tpl, best)
            if hash(next_tpl.tobytes()) in tpl_history:
                best = [max(best, key=lambda m: m.score)]

        res.n_applied += len(best)
        tpl_history.add(hash(scorer.tpl.tobytes()))
        scorer.apply_mutations(best)

    return res


def consensus_qvs(scorer) -> np.ndarray:
    """Per-position consensus QVs from a full single-mutation sweep against
    the scorer's current template (reference ConsensusQVs,
    Consensus-inl.hpp:277-297).  Generic over the scorer interface
    (tpl / score_mutations), mirroring the reference's implementation
    being templated over Arrow and Quiver scorers (Consensus.hpp:64-79);
    ArrowMultiReadScorer keeps its own batched method, Quiver delegates
    here."""
    muts = mutlib.enumerate_unique(scorer.tpl)
    scores = np.asarray(scorer.score_mutations(muts), np.float64)
    ssum = np.zeros(len(scorer.tpl))
    neg = scores < 0.0
    starts = np.asarray([m.start for m in muts], np.int64)
    np.add.at(ssum, starts[neg], np.exp(scores[neg]))
    return mutlib.qvs_from_neg_sums(ssum)


def predicted_accuracy(qvs: np.ndarray) -> float:
    """1 - mean per-base error probability (reference Consensus.h:506-512)."""
    if len(qvs) == 0:
        return 0.0
    return float(1.0 - np.power(10.0, qvs / -10.0).mean())
