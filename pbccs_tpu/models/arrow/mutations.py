"""Mutation algebra (host side): single-base template edits, enumeration,
application, and coordinate remapping.

This is deliberately plain NumPy/Python: mutation lists are small, data
dependent, and consumed by the host-driven refinement loop between batched
device rounds (SURVEY.md section 7 step 4).  Device-side *scoring* of
mutations lives in ops/mutation_score.py.

Parity targets:
  Mutation / ApplyMutations / TargetToQueryPositions
      reference ConsensusCore/src/C++/Mutation.cpp:116-197,
      ConsensusCore/include/ConsensusCore/Mutation.hpp:57-113
  enumerators
      reference ConsensusCore/src/C++/Arrow/MutationEnumerator.cpp:81-215
  virtual-mutation patches
      reference ConsensusCore/src/C++/Arrow/TemplateParameterPair.cpp:70-140
  OrientedMutation / ReadScoresMutation
      reference ConsensusCore/src/C++/Arrow/MultiReadMutationScorer.cpp:71-139
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, NamedTuple, Sequence

import numpy as np

SUBSTITUTION, INSERTION, DELETION = 0, 1, 2


@dataclasses.dataclass(frozen=True, order=True)
class Mutation:
    """A single edit of the template.

    start/end follow the reference convention: substitution replaces
    [start, end); deletion removes [start, end); insertion inserts new_base
    *before* position start (start == end).
    """

    start: int
    end: int
    mtype: int
    new_base: int = -1  # int8 base code; -1 for deletion
    score: float = 0.0  # filled by scoring (ScoredMutation)

    @property
    def length_diff(self) -> int:
        if self.mtype == INSERTION:
            return 1
        if self.mtype == DELETION:
            return -(self.end - self.start)
        return 0

    def with_score(self, s: float) -> "Mutation":
        return dataclasses.replace(self, score=float(s))


def substitution(pos: int, base: int) -> Mutation:
    return Mutation(pos, pos + 1, SUBSTITUTION, base)


def insertion(pos: int, base: int) -> Mutation:
    return Mutation(pos, pos, INSERTION, base)


def deletion(pos: int) -> Mutation:
    return Mutation(pos, pos + 1, DELETION)


def enumerate_all(tpl: np.ndarray, begin: int = 0, end: int | None = None) -> list[Mutation]:
    """All ~9 single-base mutations per position
    (AllSingleBaseMutationEnumerator, MutationEnumerator.cpp:81-110)."""
    end = len(tpl) if end is None else min(end, len(tpl))
    begin = max(begin, 0)
    out: list[Mutation] = []
    for pos in range(begin, end):
        for b in range(4):
            if b != tpl[pos]:
                out.append(substitution(pos, b))
        for b in range(4):
            out.append(insertion(pos, b))
        out.append(deletion(pos))
    return out


def enumerate_unique(tpl: np.ndarray, begin: int = 0, end: int | None = None) -> list[Mutation]:
    """Homopolymer-deduplicated enumeration: insertions/deletions only at the
    start of a homopolymer run (UniqueSingleBaseMutationEnumerator,
    MutationEnumerator.cpp:111-147)."""
    end = len(tpl) if end is None else min(end, len(tpl))
    begin = max(begin, 0)
    out: list[Mutation] = []
    for pos in range(begin, end):
        prev = tpl[pos - 1] if pos > 0 else -1
        for b in range(4):
            if b != tpl[pos]:
                out.append(substitution(pos, b))
        for b in range(4):
            if b != prev:
                out.append(insertion(pos, b))
        if tpl[pos] != prev:
            out.append(deletion(pos))
    return out


def unique_nearby_mutations(tpl: np.ndarray, centers: Iterable[Mutation],
                            neighborhood: int) -> list[Mutation]:
    """Unique mutations within +-neighborhood of prior mutations, deduplicated
    (UniqueNearbyMutations, MutationEnumerator-inl.hpp)."""
    seen = set()
    out: list[Mutation] = []
    for m in centers:
        lo = m.start - neighborhood
        hi = m.end + neighborhood
        for cand in enumerate_unique(tpl, lo, hi):
            key = (cand.start, cand.end, cand.mtype, cand.new_base)
            if key not in seen:
                seen.add(key)
                out.append(cand)
    return out


class MutationArrays(NamedTuple):
    """A flat batch of single-base mutations as numpy arrays.

    Same information as a list[Mutation], but amenable to vectorized
    marshalling: the lockstep batch polisher enumerates ~9 candidates per
    template position per round, and building Python objects for each was
    measured as a dominant host cost (SURVEY.md section 3.4's mutation test
    volume).  Field semantics match Mutation (start/end/mtype/new_base)."""

    start: np.ndarray      # (M,) int32
    end: np.ndarray        # (M,) int32
    mtype: np.ndarray      # (M,) int32
    new_base: np.ndarray   # (M,) int32 (-1 for deletions)

    @property
    def size(self) -> int:
        return int(self.start.size)

    def take(self, idx) -> "MutationArrays":
        return MutationArrays(self.start[idx], self.end[idx],
                              self.mtype[idx], self.new_base[idx])

    def to_mutations(self, scores=None) -> list[Mutation]:
        scores = np.zeros(self.size) if scores is None else scores
        return [Mutation(int(s), int(e), int(t), int(b), float(sc))
                for s, e, t, b, sc in zip(self.start, self.end, self.mtype,
                                          self.new_base, scores)]


def arrays_from_mutations(muts: Sequence[Mutation]) -> MutationArrays:
    return MutationArrays(
        np.fromiter((m.start for m in muts), np.int32, len(muts)),
        np.fromiter((m.end for m in muts), np.int32, len(muts)),
        np.fromiter((m.mtype for m in muts), np.int32, len(muts)),
        np.fromiter((m.new_base for m in muts), np.int32, len(muts)))


_SLOT_BASES = np.array([0, 1, 2, 3, 0, 1, 2, 3, -1], np.int32)
_SLOT_TYPES = np.array([SUBSTITUTION] * 4 + [INSERTION] * 4 + [DELETION],
                       np.int32)
_SLOT_ENDOFF = np.array([1, 1, 1, 1, 0, 0, 0, 0, 1], np.int32)


def enumerate_unique_arrays(tpl: np.ndarray, begin: int = 0,
                            end: int | None = None) -> MutationArrays:
    """Vectorized enumerate_unique: identical candidates in identical order
    (per position: subs by base, then ins by base, then del), no per-candidate
    Python objects."""
    L = len(tpl)
    end = L if end is None else min(end, L)
    begin = max(begin, 0)
    if end <= begin:
        z = np.zeros(0, np.int32)
        return MutationArrays(z, z, z, z)
    t = np.asarray(tpl[begin:end], np.int32)
    prev = np.empty_like(t)
    prev[0] = tpl[begin - 1] if begin > 0 else -1
    prev[1:] = t[:-1]
    P = end - begin
    pos = np.arange(begin, end, dtype=np.int32)

    valid = np.empty((P, 9), bool)
    valid[:, :4] = _SLOT_BASES[:4][None, :] != t[:, None]
    valid[:, 4:8] = _SLOT_BASES[4:8][None, :] != prev[:, None]
    valid[:, 8] = t != prev
    f = valid.ravel()

    starts = np.repeat(pos, 9)
    ends = starts + np.tile(_SLOT_ENDOFF, P)
    mtypes = np.tile(_SLOT_TYPES, P)
    bases = np.tile(_SLOT_BASES, P)
    return MutationArrays(starts[f], ends[f], mtypes[f], bases[f])


def unique_nearby_arrays(tpl: np.ndarray, centers: Iterable[Mutation],
                         neighborhood: int) -> MutationArrays:
    """Vectorized unique_nearby_mutations: same candidates, same first-seen
    order (dedup keeps the earliest occurrence across center windows)."""
    parts = [enumerate_unique_arrays(tpl, m.start - neighborhood,
                                     m.end + neighborhood) for m in centers]
    if not parts:
        z = np.zeros(0, np.int32)
        return MutationArrays(z, z, z, z)
    cat = MutationArrays(*(np.concatenate(x) for x in zip(*parts)))
    # key uniquely identifies (start, end, mtype, new_base) for single-base
    # mutations: (start, mtype, base) suffices (end is start + f(mtype))
    key = (cat.start.astype(np.int64) * 16 + cat.mtype * 5
           + (cat.new_base + 1))
    _, first = np.unique(key, return_index=True)
    first.sort()
    return cat.take(first)


def reverse_complement_arrays(arr: MutationArrays, tpl_len: int
                              ) -> MutationArrays:
    """Vectorized reverse_complement_mutation over a batch."""
    comp = np.where(arr.new_base < 0, -1, 3 - arr.new_base).astype(np.int32)
    return MutationArrays((tpl_len - arr.end).astype(np.int32),
                          (tpl_len - arr.start).astype(np.int32),
                          arr.mtype, comp)


def apply_mutations(tpl: np.ndarray, muts: Sequence[Mutation]) -> np.ndarray:
    """Apply sorted mutations left-to-right with a running length offset
    (ApplyMutations, Mutation.cpp:116-128)."""
    out = list(tpl)
    diff = 0
    for m in sorted(muts, key=lambda m: (m.start, m.end, m.mtype, m.new_base)):
        s = m.start + diff
        if m.mtype == SUBSTITUTION:
            out[s:s + (m.end - m.start)] = [m.new_base]
        elif m.mtype == INSERTION:
            out[s:s] = [m.new_base]
        else:
            del out[s:s + (m.end - m.start)]
        diff += m.length_diff
    return np.asarray(out, dtype=np.int8)


def mutations_to_transcript(muts: Sequence[Mutation], tpl_len: int) -> str:
    """MIDR transcript of sorted mutations (Mutation.cpp:130-171)."""
    tpos = 0
    t = []
    for m in sorted(muts, key=lambda m: (m.start, m.end, m.mtype, m.new_base)):
        t.append("M" * (m.start - tpos))
        tpos = m.start
        if m.mtype == INSERTION:
            t.append("I")
        elif m.mtype == DELETION:
            n = m.end - m.start
            t.append("D" * n)
            tpos += n
        else:
            n = m.end - m.start
            t.append("R" * n)
            tpos += n
    t.append("M" * (tpl_len - tpos))
    return "".join(t)


def target_to_query_positions(muts: Sequence[Mutation], tpl_len: int) -> np.ndarray:
    """Old-template position -> new-template position map, length tpl_len+1
    (TargetToQueryPositions, Mutation.cpp:173-197)."""
    transcript = mutations_to_transcript(muts, tpl_len)
    mtp = np.zeros(tpl_len + 1, dtype=np.int64)
    tpos, qpos = 0, 0
    for c in transcript:
        if c in "MR":
            mtp[tpos] = qpos
            tpos += 1
            qpos += 1
        elif c == "I":
            qpos += 1
        elif c == "D":
            mtp[tpos] = qpos
            tpos += 1
    mtp[tpos] = qpos
    return mtp


def best_subset(scored: list[Mutation], separation: int) -> list[Mutation]:
    """Greedy top-scoring well-separated subset (BestSubset,
    Consensus-inl.hpp:90-118).  DeleteRange there removes mutations whose
    start lies within [best.start - sep, best.start + sep] inclusive."""
    if separation == 0:
        return list(scored)
    pool = list(scored)
    out: list[Mutation] = []
    while pool:
        best = max(pool, key=lambda m: m.score)
        out.append(best)
        lo, hi = best.start - separation, best.start + separation
        pool = [m for m in pool if not (lo <= m.start <= hi)]
    return out


def reverse_complement_mutation(m: Mutation, tpl_len: int) -> Mutation:
    """The same edit expressed on the reverse-complement template
    (MultiReadMutationScorer.cpp:343-348)."""
    comp = {-1: -1, 0: 3, 1: 2, 2: 1, 3: 0}
    return Mutation(tpl_len - m.end, tpl_len - m.start, m.mtype, comp[m.new_base], m.score)


def read_scores_mutation(m: Mutation, tstart: int, tend: int) -> bool:
    """Does this read's template window feel this mutation?
    (ReadScoresMutation, MultiReadMutationScorer.cpp:71-80)."""
    if m.mtype == INSERTION:
        return tstart <= m.end and m.start <= tend
    return tstart < m.end and m.start < tend


def oriented_mutation(m: Mutation, strand: int, tstart: int, tend: int) -> Mutation:
    """Clip to the read window and express in read-frame (window) coords
    (OrientedMutation, MultiReadMutationScorer.cpp:93-139)."""
    if m.end - m.start > 1:
        cs, ce = max(m.start, tstart), min(m.end, tend)
        cm = Mutation(cs, ce, m.mtype, m.new_base, m.score)
    else:
        cm = m
    if strand == 0:
        return Mutation(cm.start - tstart, cm.end - tstart, cm.mtype, cm.new_base, cm.score)
    comp = {-1: -1, 0: 3, 1: 2, 2: 1, 3: 0}
    return Mutation(tend - cm.end, tend - cm.start, cm.mtype, comp[cm.new_base], cm.score)


# ---------------------------------------------------------------------- QVs

_LN10 = float(np.log(10.0))
# Value the direct f64 aggregation yields when no negative-scoring mutation
# exists at a position (prob clamps to float64 tiny):
# round(-10*log10(2.225e-308)) == 3077.  Kept as the saturation value so the
# stable form below is output-compatible with the legacy evaluation.
QV_SATURATED = int(np.round(-10.0 * np.log10(np.finfo(np.float64).tiny)))


def qvs_from_neg_sums(ssum: np.ndarray) -> np.ndarray:
    """Per-position consensus QVs from the summed exp(score) of
    negative-scoring single-base mutations (reference ConsensusQVs,
    Consensus-inl.hpp:277-297).

    Stable log-space form: QV = -10*log10(ssum / (1 + ssum)), evaluated as
    -10*(log ssum - softplus(log ssum))/ln 10.  Algebraically identical to
    the reference's -10*log10(1 - 1/(1 + ssum)) but free of that form's
    catastrophic cancellation, which pins every position with
    ssum < ~1e-16 (all mutation scores below ~-37 nats, routine at high
    pass counts) to the tiny-clamp value.  Positions with NO negative
    mutation keep the legacy clamp value QV_SATURATED; downstream
    consumers clamp to [0, 93] (pipeline QVsToASCII, reference
    Consensus.h:328-339), where both forms agree everywhere."""
    ssum = np.asarray(ssum, np.float64)
    with np.errstate(divide="ignore"):
        t = np.log(ssum)
    qv = -10.0 * (t - np.logaddexp(0.0, t)) / _LN10
    return np.where(ssum > 0.0, np.round(qv), QV_SATURATED).astype(np.int32)
