"""Batched ZMW polishing: many ZMWs per device program, sharded over a mesh.

This is the TPU replacement for the reference's one-thread-per-ZMW WorkQueue
(reference include/pacbio/ccs/WorkQueue.h:53-217) *and* the per-ZMW serial
mutation-testing loop (reference ConsensusCore/include/ConsensusCore/
Consensus-inl.hpp:160-245): Z bucketed ZMWs advance through the refinement
loop in lockstep, each round being one jitted batched program over the
(ZMW, read, mutation) grid.  Mutation-score totals reduce over the read
axis, so sharding reads across the 'read' mesh axis makes XLA insert the
all-reduce; the ZMW axis is pure data parallelism.

Selection semantics per ZMW are identical to the host refinement loop
(models/arrow/refine.py): favorable = score above the f32 noise floor
(refine.favorability_threshold, recomputed per round -- a deliberate
scaled-floor deviation from the reference's FIXED +0.04-nat acceptance
threshold, MultiReadMutationScorer.cpp:56; rationale in docs/PARITY.md),
greedy well-separated best subset, template-hash cycle avoidance,
converged ZMWs drop out of the mutation workload (their slots are
masked, not recompiled away).
"""

from __future__ import annotations

import os
import dataclasses
import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pbccs_tpu.models.arrow import mutations as mutlib
from pbccs_tpu.models.arrow.expectations import per_base_mean_and_variance
from pbccs_tpu.models.arrow.params import (
    ArrowConfig,
    effective_band_width,
    revcomp_padded,
    snr_to_transition_table_host,
    template_transition_params,
)
from pbccs_tpu.models.arrow import refine as refine_mod
from pbccs_tpu.models.arrow.refine import RefineOptions, RefineResult
from pbccs_tpu.models.arrow.scorer import (
    ADD_ALPHABETAMISMATCH,
    ADD_POOR_ZSCORE,
    ADD_SUCCESS,
    fill_alpha_beta_batch_zr,
    fills_use_pallas,
    guided_fill_passes,
    interior_read_scores,
    oriented_window,
    window_moments,
)
from pbccs_tpu.ops.fwdbwd import BandedMatrix
from pbccs_tpu.ops.mutation_score import (
    INS,
    SUB,
    edge_read_scores_fast,
    make_patches_fast,
)
from pbccs_tpu.obs import flight as obs_flight
from pbccs_tpu.obs import roofline as obs_roofline
from pbccs_tpu.obs import trace as obs_trace
from pbccs_tpu.obs.metrics import default_registry, log_buckets
from pbccs_tpu.parallel.mesh import READ_AXIS, ZMW_AXIS, pad_to
from pbccs_tpu.runtime.timing import device_fetch
from pbccs_tpu.utils import next_pow2

# bucket fill / padding-waste observability: pow2 padding of the (Z, R)
# axes is real device work, so the fill ratios tell later perf PRs how
# much of a batch's FLOPs polish actual reads vs padding
_reg = default_registry()
_m_polishes = _reg.counter("ccs_batch_polishes_total",
                           "BatchPolisher batches constructed")
_m_zmw_slots = _reg.counter("ccs_batch_slots_total",
                            "Padded batch slots by axis", axis="zmw")
_m_zmw_used = _reg.counter("ccs_batch_slots_used_total",
                           "Occupied batch slots by axis", axis="zmw")
_m_read_slots = _reg.counter("ccs_batch_slots_total", axis="read")
_m_read_used = _reg.counter("ccs_batch_slots_used_total", axis="read")
_FILL_BUCKETS = log_buckets(0.0625, 1.0, 2.0)
_m_zmw_fill = _reg.histogram("ccs_batch_fill_ratio",
                             "Used/padded slot ratio per batch by axis",
                             buckets=_FILL_BUCKETS, axis="zmw")
_m_read_fill = _reg.histogram("ccs_batch_fill_ratio",
                              buckets=_FILL_BUCKETS, axis="read")

# mutation-axis chunk: every scoring call uses this static M so one compiled
# program serves every refinement round and the QV sweep
MUT_CHUNK = 512
# edge-mutation slab width: boundary mutations are O(reads), not O(template),
# so their batched program uses a small static mutation axis
EDGE_SLAB = 64
# windows shorter than this score boundary mutations by full refill: the
# extend-from-begin and extend-to-end regimes would overlap
MIN_FAST_EDGE_WLEN = 8


def _jmax_bucket(max_len: int) -> int:
    """Template-axis bucket: headroom PROPORTIONAL to length, not the old
    flat +16 -- net insertions during refinement scale with template
    length, and a 15 kb polish whose templates outgrew a +16 bucket
    overflow-bailed the device-resident loop every round (straight into
    the host loop's per-round fetches + length-scaled chunk programs)."""
    return pad_to(max_len + max(16, max_len // 32), 64)


def _imax_bucket(raw_imax: int) -> int:
    """Read-axis bucket: granularity scales with length (~1/8th,
    power-of-two steps, floor 64): long-read workloads draw max read
    lengths that differ by hundreds of bases run to run, and a fixed
    64-step bucket minted a fresh executable set per draw -- a ~90 s
    recompile inside every timed 15 kb repeat."""
    step = max(64, 1 << max(raw_imax - 1, 1).bit_length() - 3)
    return pad_to(raw_imax, step)


def length_bucket(tpl_len: int, max_read_len: int) -> tuple[int, int]:
    """The (Jmax, Imax) compiled-shape bucket a ZMW of this geometry
    polishes in -- the grouping key of the serving engine's dynamic
    batcher (pbccs_tpu.serve.batcher): ZMWs that share a bucket share
    every compiled polish program, so batching within a bucket never
    mints new executables."""
    return _jmax_bucket(tpl_len), _imax_bucket(max_read_len + 8)


def effective_shapes(n_zmws: int, max_reads: int, max_read_len: int,
                     max_tpl_len: int, *,
                     buckets: tuple[int, int, int] | None = None,
                     min_z: int = 1, zq: int = 1, rq: int = 1
                     ) -> tuple[int, int, int, int]:
    """The (Imax, Jmax, R, Z) a BatchPolisher with these inputs compiles
    at -- the ONE place the bucket arithmetic lives.  BatchPolisher's
    constructor uses it, and the quarantine bisection path
    (pipeline._pinned_batch_shapes) uses it to pin sub-dispatches to the
    parent batch's shapes, so isolating a poison ZMW replays compiled
    programs and (W being a function of Jmax) reproduces surviving ZMWs
    byte-identically."""
    Z = pad_to(max(n_zmws, min_z), zq)
    R = pad_to(max_reads, max(4, rq))
    Imax = _imax_bucket(max_read_len + 8)
    Jmax = _jmax_bucket(max_tpl_len)
    if buckets is not None:
        Imax = max(Imax, buckets[0])
        R = max(R, buckets[2])
        # adopt the parent's Jmax bucket EXACTLY when templates fit:
        # letting _jmax_bucket of a mid-refinement template overshoot
        # the parent bucket would mint a fresh draw-dependent shape
        # (a cold compile, the very thing buckets exist to prevent)
        if max_tpl_len + 2 <= buckets[1]:
            Jmax = buckets[1]
        else:
            Jmax = max(Jmax, buckets[1])
    return Imax, Jmax, R, Z


@dataclasses.dataclass
class ZmwTask:
    """One ZMW's polish-stage inputs (draft template + mapped reads)."""

    id: str
    tpl: np.ndarray           # (L,) int8 draft consensus
    snr: np.ndarray           # (4,)
    reads: Sequence[np.ndarray]
    strands: Sequence[int]
    tstarts: Sequence[int]
    tends: Sequence[int]


@dataclasses.dataclass
class PrebakedBatch:
    """Bucket-shaped host marshalling of a ZmwTask batch, pre-built off
    the device thread (premarshal): the padded numpy planes and the f64
    SNR transition tables that BatchPolisher.__init__ otherwise derives
    inline.  The sched/ prepare pool builds these per batch
    (pipeline.prebake_polish) so the device executor thread adopts
    arrays instead of marshalling -- the same prepare/polish overlap the
    pool already gives the POA stage, extended to the polish setup.

    One code path: BatchPolisher without a prebake calls premarshal()
    itself, so prepared and inline batches are byte-identical by
    construction."""

    tasks: list
    shapes: tuple[int, int, int, int]   # (Imax, Jmax, R, Z)
    snrs: np.ndarray
    reads: np.ndarray
    rlens: np.ndarray
    strands: np.ndarray
    tstarts: np.ndarray
    tends: np.ndarray
    n_reads: np.ndarray
    real_rows: np.ndarray
    host_tables: np.ndarray


def premarshal_nbytes(shapes: tuple[int, int, int, int]) -> int:
    """Host bytes a premarshal() of these effective (Imax, Jmax, R, Z)
    shapes holds -- the per-batch charge the resource governor's
    HostBudget gates the prepare pool on (resilience.resources).  Sums
    the marshalled planes exactly (reads int8 dominates); the ZmwTask
    arrays themselves are references into the reader's buffers and are
    bounded separately by the pipeline's in-flight count."""
    imax, _jmax, r, z = shapes
    return (z * r * imax          # reads int8
            + 4 * z * r * 4       # rlens/strands/tstarts/tends int32
            + z * 4 * 8           # snrs float64
            + z * 4               # n_reads int32
            + z * r               # real_rows bool
            + z * 8 * 4 * 4)      # host_tables float32 (8, 4) per ZMW


def premarshal(tasks: Sequence[ZmwTask], *,
               buckets: tuple[int, int, int] | None = None,
               min_z: int = 1, zq: int = 1, rq: int = 1) -> PrebakedBatch:
    """Marshal a ZmwTask batch into its bucket-shaped numpy planes
    (effective_shapes geometry).  Pure host work -- safe on any thread;
    the heavy item is the per-ZMW float64 SNR transition tables."""
    if not tasks:
        raise ValueError("empty batch")
    Imax, Jmax, R, Z = effective_shapes(
        len(tasks),
        max(len(t.reads) for t in tasks),
        max((len(r) for t in tasks for r in t.reads), default=8),
        max(len(t.tpl) for t in tasks),
        buckets=buckets, min_z=min_z, zq=zq, rq=rq)

    snrs = np.full((Z, 4), 8.0)
    reads = np.full((Z, R, Imax), 4, np.int8)
    rlens = np.zeros((Z, R), np.int32)
    strands = np.zeros((Z, R), np.int32)
    tstarts = np.zeros((Z, R), np.int32)
    tends = np.zeros((Z, R), np.int32)
    n_reads = np.zeros(Z, np.int32)
    for z, t in enumerate(tasks):
        snrs[z] = t.snr
        n_reads[z] = len(t.reads)
        for i, rc in enumerate(t.reads):
            n = min(len(rc), Imax)
            reads[z, i, :n] = rc[:n]
            rlens[z, i] = n
        strands[z, : len(t.reads)] = t.strands
        tstarts[z, : len(t.reads)] = t.tstarts
        tends[z, : len(t.reads)] = t.tends
    # padding read rows (and whole padding ZMWs) get a trivial window
    for z in range(Z):
        L = len(tasks[z].tpl) if z < len(tasks) else 2
        nr = int(n_reads[z])
        reads[z, nr:, :2] = 0
        rlens[z, nr:] = 2
        tends[z, nr:] = min(2, L)

    real_rows = np.zeros((Z, R), bool)
    for z in range(len(tasks)):
        real_rows[z, : int(n_reads[z])] = True

    host_tables = np.stack(
        [snr_to_transition_table_host(snrs[z]) for z in range(Z)]
    ).astype(np.float32)
    return PrebakedBatch(list(tasks), (Imax, Jmax, R, Z), snrs, reads,
                         rlens, strands, tstarts, tends, n_reads,
                         real_rows, host_tables)


@functools.partial(jax.jit, static_argnames=("width", "use_pallas", "mesh",
                                             "guided_passes"))
def _batch_setup(tpls, tlens, tables, reads, rlens, strands, tstarts, tends,
                 width: int, use_pallas: bool, mesh: Mesh | None = None,
                 guided_passes: int = 0):
    """Per-ZMW template tracks + per-read window fills + moments.

    All leading axes are (Z, ...) with reads (Z, R, Imax).  `tables` are the
    per-ZMW (8, 4) SNR transition tables, computed on host in float64
    (snr_to_transition_table_host) so batched and per-ZMW scorers agree.
    Window building vmaps over (ZMW, read); the alpha/beta fills run on the
    flattened (Z*R) read batch so the Pallas kernel path serves every read
    in one launch."""

    def one_zmw(tpl, L, table, st1, ts1, te1):
        trans_f = template_transition_params(tpl, table, L)
        tpl_r = revcomp_padded(tpl, L)
        trans_r = template_transition_params(tpl_r, table, L)

        win = jax.vmap(
            lambda s, a, b: oriented_window(s, a, b, tpl, tpl_r, L, table)
        )(st1, ts1, te1)

        mean_f, var_f = per_base_mean_and_variance(trans_f)
        mean_r, var_r = per_base_mean_and_variance(trans_r)
        mu, var = jax.vmap(
            lambda s, a, b: window_moments(s, a, b, mean_f, var_f, mean_r, var_r, L)
        )(st1, ts1, te1)

        return win + (trans_f, tpl_r, trans_r, table, mu, var)

    (win_tpl, win_trans, wlens, trans_f, tpl_r, trans_r, table, mu, var) = \
        jax.vmap(one_zmw)(tpls, tlens, tables, strands, tstarts, tends)

    alpha, beta, ll_a, ll_b, apre, bsuf = fill_alpha_beta_batch_zr(
        reads, rlens, win_tpl, win_trans, wlens, width, use_pallas, mesh,
        guided_passes=guided_passes)
    return (win_tpl, win_trans, wlens, alpha, beta,
            ll_a, ll_b, apre, bsuf,
            trans_f, tpl_r, trans_r, table, mu, var)


def lowering_target():
    """The canonical per-bucket program the roofline plane lowers for
    CostCard extraction (obs/roofline.py): the jitted _batch_setup.
    Exposed as a function so roofline never imports batch at module
    scope (batch imports roofline; this breaks the cycle)."""
    return _batch_setup


@jax.jit
def _stack_chunks(chunks):
    """Stack per-chunk (Z, M) totals into one (C, Z, M) device array."""
    return jnp.stack(chunks)


def _mated_mask_dev(ll_a, ll_b, rlens, tstarts, tends):
    """Device-side mated_mask (scorer.mated_mask) so refinement rounds can
    update the read-active mask without a device->host stats fetch."""
    from pbccs_tpu.models.arrow.scorer import _AB_MISMATCH_TOL, _MAX_BAND_SHIFT

    mated = jnp.abs(1.0 - ll_a / jnp.where(ll_b == 0, 1.0, ll_b)) <= _AB_MISMATCH_TOL
    mated &= jnp.isfinite(ll_a) & jnp.isfinite(ll_b)
    mated &= rlens <= _MAX_BAND_SHIFT * jnp.maximum(tends - tstarts, 1)
    return mated


@jax.jit
def _update_active(active, ll_a, ll_b, rlens, tstarts, tends):
    return active & _mated_mask_dev(ll_a, ll_b, rlens, tstarts, tends)


@jax.jit
def _update_active_partial(active, ll_a, ll_b, rlens, tstarts, tends,
                           real_sub, idx):
    nz = active.shape[0]
    prev = active[jnp.clip(idx, 0, nz - 1)]
    rows = prev & real_sub & _mated_mask_dev(ll_a, ll_b, rlens,
                                             tstarts, tends)
    return active.at[idx].set(rows, mode="drop")


@jax.jit
def _favorability_eps(baselines, active):
    """(Z,) per-round favorability floor from the CURRENT device-side
    baselines/active mask (refine.favorability_threshold) -- bit-identical
    to the device-resident loop's in-program computation, so the host
    fallback loop selects exactly as the device loop does."""
    return refine_mod.favorability_threshold(
        jnp.sum(jnp.where(active, jnp.abs(baselines), 0.0), axis=1))


@jax.jit
def _fold_edge_slab(totals, et, sel_idx, used):
    """totals[z, sel_idx[z,k]] += et[z,k] where used — on device, so edge
    slabs cost no extra device->host fetch (each fetch over the tunneled
    link costs ~0.1-0.25 s regardless of size)."""
    upd = jnp.where(used, et, 0.0)
    z = jnp.arange(totals.shape[0], dtype=jnp.int32)[:, None]
    return totals.at[z, sel_idx].add(upd)


@jax.jit
def _fold_fallback(totals, ll, baselines, active, ez, er, em, valid):
    """totals[ez, em] += ll - baselines[ez, er] for fallback pairs (pairs of
    inactive reads are dropped -- the host pair list is geometry-only)."""
    base = baselines[ez, er]
    upd = jnp.where(valid & active[ez, er], ll - base, 0.0)
    return totals.at[ez, em].add(upd)


@jax.jit
def _scatter_z(full, subset, idx):
    """full[leaf][idx[k]] = subset[leaf][k] for every pytree leaf; OOB pad
    indices are dropped."""
    return jax.tree.map(
        lambda f, s: f.at[idx].set(s.astype(f.dtype), mode="drop"),
        full, subset)


@jax.jit
def _batch_interior_totals(reads, rlens, strands, tstarts, tends,
                           win_tpl, win_trans, wlens,
                           alpha_vals, alpha_offs, alpha_ls,
                           beta_vals, beta_offs, beta_ls,
                           a_prefix, b_suffix, baselines,
                           tpl32_f, trans_f, tpl32_r, trans_r, table, tlens,
                           mpos_f, mend_f, mtype, mbase_f, mpos_r, mbase_r,
                           int_mask, active):
    """(Z, M) = sum over reads of masked (LL(mut) - baseline), plus the
    fwd/rev virtual-mutation patches (built in the same program: a separate
    patch dispatch per chunk costs two extra device round-trips per
    refinement round).  int_mask is geometry-only; the read-active mask
    lives on device (active, (Z, R) bool).

    The read-axis reduction is the collective: with reads sharded over the
    'read' mesh axis XLA lowers the sum to an all-reduce over ICI."""
    int_mask = int_mask & active[:, :, None]

    def one_patches(t, tr, tb, l, p1, mt1, b1):
        return make_patches_fast(t, tr, tb, l, p1, mt1, b1)

    patches_f = jax.vmap(one_patches)(tpl32_f, trans_f, table, tlens,
                                      mpos_f, mtype, mbase_f)
    patches_r = jax.vmap(one_patches)(tpl32_r, trans_r, table, tlens,
                                      mpos_r, mtype, mbase_r)

    def one_zmw(read1, rlen1, st1, ts1, te1, wt1, wtr1, wl1,
                av1, ao1, als1, bv1, bo1, bls1, apre1, bsuf1, base1,
                mp1, me1, mt1, pf1, pr1, mask1):
        def one_read(read, rlen, strand, ts, te, wt, wtr, wl,
                     av, ao, als, bv, bo, bls, apre, bsuf, bl, mask):
            lls = interior_read_scores(
                read, rlen, strand, ts, te, wt, wtr, wl,
                BandedMatrix(av, ao, als), BandedMatrix(bv, bo, bls),
                apre, bsuf, mp1, me1, mt1, pf1, pr1)
            return jnp.where(mask, lls - bl, 0.0)

        per_read = jax.vmap(one_read)(
            read1, rlen1, st1, ts1, te1, wt1, wtr1, wl1,
            av1, ao1, als1, bv1, bo1, bls1, apre1, bsuf1, base1, mask1)
        return jnp.sum(per_read, axis=0)

    totals = jax.vmap(one_zmw)(reads, rlens, strands, tstarts, tends,
                               win_tpl, win_trans, wlens,
                               alpha_vals, alpha_offs, alpha_ls,
                               beta_vals, beta_offs, beta_ls,
                               a_prefix, b_suffix, baselines,
                               mpos_f, mend_f, mtype,
                               patches_f, patches_r, int_mask)
    return totals, patches_f, patches_r


@jax.jit
def _batch_edge_fast_totals(reads, rlens, strands, tstarts, tends,
                            win_tpl, win_trans, wlens,
                            alpha_vals, alpha_offs, alpha_ls,
                            beta_vals, beta_offs, beta_ls,
                            a_prefix, b_suffix, baselines,
                            tpl32_f, trans_f, tpl32_r, trans_r, table, tlens,
                            mpos_f, mend_f, mtype, mbase_f, mpos_r, mbase_r,
                            edge_mask, active):
    """(Z, ME) = sum over reads of masked (LL(mut) - baseline) for
    near-window-boundary mutations via the short extension programs
    (ops.mutation_score.edge_scores_fast); same layout/collective shape as
    _batch_interior_totals.  edge_mask is geometry-only; the read-active
    mask lives on device (active, (Z, R) bool)."""
    edge_mask = edge_mask & active[:, :, None]

    def one_patches(t, tr, tb, l, p1, mt1, b1):
        return make_patches_fast(t, tr, tb, l, p1, mt1, b1)

    patches_f = jax.vmap(one_patches)(tpl32_f, trans_f, table, tlens,
                                      mpos_f, mtype, mbase_f)
    patches_r = jax.vmap(one_patches)(tpl32_r, trans_r, table, tlens,
                                      mpos_r, mtype, mbase_r)

    def one_zmw(read1, rlen1, st1, ts1, te1, wt1, wtr1, wl1,
                av1, ao1, als1, bv1, bo1, bls1, apre1, bsuf1, base1,
                mp1, me1, mt1, pf1, pr1, mask1):
        def one_read(read, rlen, strand, ts, te, wt, wtr, wl,
                     av, ao, als, bv, bo, bls, apre, bsuf, bl, mask):
            lls = edge_read_scores_fast(
                read, rlen, strand, ts, te, wt, wtr, wl,
                BandedMatrix(av, ao, als), BandedMatrix(bv, bo, bls),
                apre, bsuf, mp1, me1, mt1, pf1, pr1)
            return jnp.where(mask, lls - bl, 0.0)

        per_read = jax.vmap(one_read)(
            read1, rlen1, st1, ts1, te1, wt1, wtr1, wl1,
            av1, ao1, als1, bv1, bo1, bls1, apre1, bsuf1, base1, mask1)
        return jnp.sum(per_read, axis=0)

    return jax.vmap(one_zmw)(reads, rlens, strands, tstarts, tends,
                             win_tpl, win_trans, wlens,
                             alpha_vals, alpha_offs, alpha_ls,
                             beta_vals, beta_offs, beta_ls,
                             a_prefix, b_suffix, baselines,
                             mpos_f, mend_f, mtype,
                             patches_f, patches_r, edge_mask)


@functools.partial(jax.jit, static_argnames=("width", "use_pallas"))
def _batch_edge(reads, rlens, win_tpl, win_trans, wlens,
                zidx, ridx, pw, mt, pb, ptr, psh, width: int,
                use_pallas: bool):
    """(E,) absolute LLs of edge (read, mutation) pairs via full refill.

    Flattens (Z, R) and delegates to the scorer's batched edge program
    (one-hot row selects + dense mutated windows + batched fills)."""
    Z, R = reads.shape[:2]
    flat = lambda a: a.reshape((Z * R,) + a.shape[2:])
    from pbccs_tpu.models.arrow.scorer import _score_edge
    return _score_edge.__wrapped__(
        flat(reads), flat(rlens), flat(win_tpl), flat(win_trans), flat(wlens),
        zidx * R + ridx, pw, mt, pb, ptr, psh, width, use_pallas)


@dataclasses.dataclass
class _Continuation:
    """Device-loop outcome state that later BatchPolisher calls must
    respect — the straggler-continuation + QV-cache bookkeeping that grew
    ad hoc across refine_device/consensus_qvs (round-4 review ask).

    Invariants:
    * `sub_polishers` maps parent ZMW index -> (sub BatchPolisher, sub
      row).  Non-empty implies `stale_fills`: those parent rows' device
      fills are PRE-continuation, so any later refine() must rebuild
      (begin_refine) before reusing them; QVs for those ZMWs must come
      from the sub-polisher (delegated_qvs), never the parent sweep.
    * `qv_cache` holds (skip set at sweep time, (Z, Jmax) int32 QVs) from
      the loop's eager run_qv_ints sweep against the loop's FINAL
      templates.  It is only valid while those templates are current:
      begin_refine clears it.  A cached sweep serves a later
      consensus_qvs call iff no ZMW live in that call was skipped in the
      cached sweep.
    """

    stale_fills: bool = False
    qv_cache: tuple | None = None
    sub_polishers: dict = dataclasses.field(default_factory=dict)

    def begin_refine(self, polisher: "BatchPolisher") -> None:
        """Entering a new refinement: rebuild stale fills from the current
        host templates and drop state tied to the previous loop's end."""
        if self.stale_fills:
            polisher._setup(first=False)
            self.stale_fills = False
        self.sub_polishers = {}
        self.qv_cache = None

    def record_continuation(self, mapping: dict) -> None:
        """A straggler sub-batch finished rows for these parent ZMWs."""
        self.sub_polishers.update(mapping)
        self.stale_fills = True

    def cached_qvs(self, n_zmws: int, skip: set, tpls) -> list | None:
        """Serve consensus QVs from the loop-time sweep if every ZMW live
        in THIS call was live in the cached sweep too."""
        if self.qv_cache is None:
            return None
        cached_skip, qv_m = self.qv_cache
        if (set(range(n_zmws)) - skip) & cached_skip:
            return None
        return [np.zeros(0, np.int32) if z in skip
                else qv_m[z, : len(tpls[z])].copy() for z in range(n_zmws)]

    def delegated_qvs(self, out: list, skip: set) -> list:
        """Overwrite QVs of continuation-finished ZMWs from their
        sub-polishers (grouped per sub so each sweeps at most once)."""
        subs = self.sub_polishers
        for sub in {id(s): s for s, _ in subs.values()}.values():
            wanted = {i: z for z, (s, i) in subs.items()
                      if s is sub and z not in skip}
            if not wanted:
                continue  # all delegated ZMWs are skipped: no sweep at all
            sub_skip = {i for z, (s, i) in subs.items()
                        if s is sub and z in skip}
            sub_q = sub.consensus_qvs(skip=sub_skip)
            for i, z in wanted.items():
                out[z] = sub_q[i]
        return out


class BatchPolisher:
    """Z bucketed ZMWs polished in lockstep on one device mesh.

    Equivalent per-ZMW semantics to models.arrow.scorer.ArrowMultiReadScorer
    + models.arrow.refine.refine_consensus, with leading (Z,) batch axes and
    optional ('zmw' x 'read') mesh sharding."""

    def __init__(self, tasks: Sequence[ZmwTask],
                 config: ArrowConfig | None = None,
                 min_zscore: float = float("nan"),
                 mesh: Mesh | None = None, *,
                 buckets: tuple[int, int, int] | None = None,
                 min_z: int = 1,
                 prebaked: PrebakedBatch | None = None):
        """`buckets` = (Imax, Jmax, R) lower bounds and `min_z` a ZMW-axis
        lower bound: sub-batches carved out of a parent batch (straggler
        continuations, wide-band retries) pin their shapes to the parent's
        buckets and a pow2 Z so the compiled-program menu is bounded --
        letting each draw's straggler count pick its own shapes compiled a
        fresh ~minute-long device loop mid-bench (the round-3 53x
        tail-latency outlier).

        `prebaked`: a PrebakedBatch marshalled ahead of time on a prepare
        worker (pipeline.prebake_polish); adopted when its shapes match
        this construction's effective shapes, else silently re-marshalled
        (premarshal is the single marshalling code path either way)."""
        if not tasks:
            raise ValueError("empty batch")
        self.config = config or ArrowConfig()
        self.min_zscore = min_zscore
        self.mesh = mesh
        self.n_zmws = len(tasks)
        self.ids = [t.id for t in tasks]
        self.tpls: list[np.ndarray] = [np.asarray(t.tpl, np.int8) for t in tasks]

        zq = mesh.shape[ZMW_AXIS] if mesh else 1
        rq = mesh.shape[READ_AXIS] if mesh else 1
        shapes = effective_shapes(
            self.n_zmws,
            max(len(t.reads) for t in tasks),
            max((len(r) for t in tasks for r in t.reads), default=8),
            max(len(t.tpl) for t in tasks),
            buckets=buckets, min_z=min_z, zq=zq, rq=rq)
        pb = prebaked
        # adoption requires the prebake to be THIS task batch (object
        # identity), not merely shape-compatible: two same-bucket batches
        # premarshal to identical shapes, and silently adopting the
        # wrong one would polish the wrong reads
        if pb is None or pb.shapes != shapes or len(pb.tasks) != len(tasks) \
                or any(a is not b for a, b in zip(pb.tasks, tasks)):
            pb = premarshal(tasks, buckets=buckets, min_z=min_z,
                            zq=zq, rq=rq)
        self._Imax, self._Jmax, self._R, self._Z = pb.shapes
        self._W = effective_band_width(self.config.banding, self._Jmax)

        self._snrs = pb.snrs
        self._reads = pb.reads
        self._rlens = pb.rlens
        self._strands = pb.strands
        # the window planes are mutated in place by apply_mutations, so a
        # prebake that may be replayed (a device-failure requeue re-runs
        # the same polish closure) hands each polisher its own copy
        self._tstarts = pb.tstarts.copy()
        self._tends = pb.tends.copy()
        self._n_reads = pb.n_reads
        self._real_rows = pb.real_rows

        Z, R = self._Z, self._R
        n_reads_real = int(self._n_reads[: self.n_zmws].sum())
        _m_polishes.inc()
        _m_zmw_slots.inc(Z)
        _m_zmw_used.inc(self.n_zmws)
        _m_read_slots.inc(Z * R)
        _m_read_used.inc(n_reads_real)
        _m_zmw_fill.observe(self.n_zmws / Z)
        _m_read_fill.observe(n_reads_real / (Z * R))

        self._stats_host = None  # lazily fetched AddRead statistics
        self._cont = _Continuation()
        self._host_tables = pb.host_tables
        # flight-recorder batch tag: first ZMW id + batch size names the
        # batch compactly in postmortem dumps
        self._flight_tag = f"{self.ids[0]}+{self.n_zmws}"
        # roofline CostCard: one AOT extraction per shape bucket per
        # process (memoized + disk-cached), BEFORE the first _setup so
        # its execution charge finds the card -- a process whose only
        # polisher is the bucket's first would otherwise never charge.
        # The AOT compile warms the persistent cache for the jit call
        # below (same program, same statics).  Mesh runs skip it -- the
        # canonical card program is the mesh=None lowering.
        if self.mesh is None:
            obs_roofline.note_bucket(
                imax=self._Imax, jmax=self._Jmax, r=self._R, z=self._Z,
                width=self._W, use_pallas=fills_use_pallas(),
                guided_passes=guided_fill_passes(self._Jmax))
        self._setup(first=True)

    # --------------------------------------------------- AddRead statistics

    def _ensure_stats(self) -> None:
        """Materialize the host-visible AddRead statistics from the device
        stack in ONE fetch, on first access.  The gate DECISIONS (statuses,
        active) are fetched verbatim from the device computation so host
        and device never disagree; z-score VALUES are recomputed in f64
        for reporting (as before the gates moved on device)."""
        if self._stats_host is not None:
            return
        stats = device_fetch(self._addread_stats_dev, np.float64)
        ll_a_h, ll_b_h, mu_h, var_h, statuses_f = stats
        statuses = statuses_f.astype(np.int32)
        real = self._real_rows
        mated = real & (statuses != ADD_ALPHABETAMISMATCH)
        z = (ll_b_h - mu_h) / np.sqrt(np.maximum(var_h, 1e-12))
        self._stats_host = {
            "baselines": ll_b_h,
            "ll_mu": mu_h,
            "ll_var": var_h,
            "zscores": np.where(mated, z, np.nan),
            "statuses": statuses,
            "active": real & (statuses == ADD_SUCCESS),
        }

    @property
    def baselines(self) -> np.ndarray:
        self._ensure_stats()
        return self._stats_host["baselines"]

    @property
    def _ll_mu(self) -> np.ndarray:
        self._ensure_stats()
        return self._stats_host["ll_mu"]

    @property
    def _ll_var(self) -> np.ndarray:
        self._ensure_stats()
        return self._stats_host["ll_var"]

    @property
    def zscores(self) -> np.ndarray:
        self._ensure_stats()
        return self._stats_host["zscores"]

    @property
    def statuses(self) -> np.ndarray:
        self._ensure_stats()
        return self._stats_host["statuses"]

    @property
    def active(self) -> np.ndarray:
        """AddRead-time active mask (host snapshot; the live refinement
        mask stays on device as _active_dev)."""
        self._ensure_stats()
        return self._stats_host["active"]

    # ------------------------------------------------------------------ setup

    def _shard(self, arr, read_axis: int | None = None):
        if self.mesh is None:
            return jnp.asarray(arr)
        parts: list = [None] * np.ndim(arr)
        parts[0] = ZMW_AXIS
        if read_axis is not None:
            parts[read_axis] = READ_AXIS
        return jax.device_put(np.asarray(arr),
                              NamedSharding(self.mesh, P(*parts)))

    def _tpl_lengths(self) -> np.ndarray:
        """(Z,) template lengths (padding rows = 2), cached between
        apply_mutations calls; shared by the marshalling paths for their
        mid-template default-dummy geometry."""
        if getattr(self, "_tpl_lengths_cache", None) is None:
            self._tpl_lengths_cache = np.array(
                [len(self.tpls[z]) for z in range(self.n_zmws)]
                + [2] * (self._Z - self.n_zmws), np.int32)
        return self._tpl_lengths_cache

    def _template_arrays(self):
        Z = self._Z
        tl = np.full((Z, self._Jmax), 4, np.int8)
        tlens = np.full(Z, 2, np.int32)
        for z in range(self.n_zmws):
            L = len(self.tpls[z])
            if L > self._Jmax:
                raise ValueError("template outgrew bucket")
            tl[z, :L] = self.tpls[z]
            tlens[z] = L
        return tl, tlens

    def _setup(self, first: bool) -> None:
        """(Re)build all window fills; gate reads on the first build.

        Device copies of the loop-invariant read arrays are cached here:
        re-uploading (Z, R, Imax) tensors on every scoring call costs a
        host->device transfer per refinement round."""
        tl, tlens = self._template_arrays()
        self._tlens = tlens
        if not hasattr(self, "_reads_dev"):
            self._reads_dev = self._shard(self._reads, 1)
            self._rlens_dev = self._shard(self._rlens, 1)
            self._strands_dev = self._shard(self._strands, 1)
        self._tstarts_dev = self._shard(self._tstarts, 1)
        self._tends_dev = self._shard(self._tends, 1)
        self._tlens_dev = self._shard(tlens)
        self._baselines_dev = None  # set after fills below
        (self.win_tpl, self.win_trans, self.wlens, alpha, beta,
         ll_a, ll_b, self.a_prefix, self.b_suffix,
         self.trans_f, self.tpl_r, self.trans_r, self.table,
         mu, var) = _batch_setup(
            self._shard(tl), self._tlens_dev,
            self._shard(self._host_tables),
            self._reads_dev,
            self._rlens_dev,
            self._strands_dev,
            self._tstarts_dev,
            self._tends_dev,
            self._W,
            # under a mesh the Pallas fills run per-device inside
            # jax.shard_map (fill_alpha_beta_batch_zr); pallas_call itself
            # has no GSPMD partitioning rule
            use_pallas=fills_use_pallas(),
            mesh=self.mesh,
            guided_passes=guided_fill_passes(self._Jmax))
        self.alpha, self.beta = alpha, beta
        # charge this execution of the canonical program against the
        # bucket's CostCard bound (no-op until a card exists)
        obs_roofline.charge_execution(imax=self._Imax, jmax=self._Jmax,
                                      r=self._R, z=self._Z)
        self._tpl_dev = self._shard(tl)
        self._tpl32_dev = self._tpl_dev.astype(jnp.int32)
        self._tpl32_r_dev = self.tpl_r.astype(jnp.int32)

        self._baselines_dev = ll_b
        if first:
            # the AddRead gate runs on DEVICE (no fetch: each device->host
            # round trip costs ~0.1-0.25 s over the tunneled link whatever
            # the payload); the host-visible statistics (statuses, zscores,
            # baselines, active) are fetched LAZILY on first access from
            # the stashed stack -- a bench-style refine+QV run never pays
            # for them at all
            z32 = (ll_b - mu) / jnp.sqrt(jnp.maximum(var, 1e-12))
            if np.isnan(self.min_zscore):
                ok_z = jnp.ones_like(z32, bool)
            else:
                ok_z = jnp.isfinite(z32) & (z32 >= np.float32(self.min_zscore))
            mated = _mated_mask_dev(ll_a, ll_b, self._rlens_dev,
                                    self._tstarts_dev, self._tends_dev)
            real = self._shard(self._real_rows, 1)
            self._active_dev = real & mated & ok_z
            statuses = jnp.where(
                ~real, -1,
                jnp.where(~mated, ADD_ALPHABETAMISMATCH,
                          jnp.where(~ok_z, ADD_POOR_ZSCORE, ADD_SUCCESS)))
            self._addread_stats_dev = jnp.stack(
                [ll_a, ll_b, mu, var, statuses.astype(ll_b.dtype)])
            self._stats_host = None
        else:
            # refinement-round rebuild: the active-mask update stays on
            # device (no stats fetch); host copies of baselines/active
            # reflect the AddRead-time state, which is all the pipeline
            # reads (statuses/zscores/global z-scores are draft statistics)
            self._active_dev = _update_active(
                self._active_dev, ll_a, ll_b, self._rlens_dev,
                self._tstarts_dev, self._tends_dev)

    def _setup_partial(self, changed: list[int]) -> None:
        """Refill only the ZMWs whose template changed this round, scattering
        the new windows/fills into the cached device state.  Late refinement
        rounds typically mutate a small fraction of the batch, and the full
        (Z, R) refill was a profiled per-round cost."""
        tl, tlens = self._template_arrays()
        self._tlens = tlens
        Zc = next_pow2(len(changed), 4)
        idx = np.full(Zc, self._Z, np.int32)      # OOB pad -> dropped scatter
        idx[: len(changed)] = changed
        safe = np.clip(idx, 0, self._Z - 1)
        g = lambda a: jnp.asarray(np.asarray(a)[safe])

        sub = _batch_setup(
            g(tl), g(tlens), g(self._host_tables),
            g(self._reads), g(self._rlens), g(self._strands),
            g(self._tstarts), g(self._tends), self._W,
            use_pallas=fills_use_pallas(),
            guided_passes=guided_fill_passes(self._Jmax))
        (w_tpl, w_trans, wlens, s_alpha, s_beta, ll_a, ll_b, apre, bsuf,
         trans_f, tpl_r, trans_r, _table, mu, var) = sub

        full = (self.win_tpl, self.win_trans, self.wlens, self.alpha,
                self.beta, self.a_prefix, self.b_suffix, self.trans_f,
                self.tpl_r, self.trans_r)
        subset = (w_tpl, w_trans, wlens, s_alpha, s_beta, apre, bsuf,
                  trans_f, tpl_r, trans_r)
        (self.win_tpl, self.win_trans, self.wlens, self.alpha, self.beta,
         self.a_prefix, self.b_suffix, self.trans_f, self.tpl_r,
         self.trans_r) = _scatter_z(full, subset, jnp.asarray(idx))

        self._tstarts_dev = self._shard(self._tstarts, 1)
        self._tends_dev = self._shard(self._tends, 1)
        self._tlens_dev = self._shard(tlens)
        tl_dev = jnp.asarray(tl)
        self._tpl_dev = tl_dev
        self._tpl32_dev = tl_dev.astype(jnp.int32)
        self._tpl32_r_dev = self.tpl_r.astype(jnp.int32)

        self._baselines_dev = _scatter_z(self._baselines_dev, ll_b,
                                         jnp.asarray(idx))
        real = self._real_rows[safe]
        self._active_dev = _update_active_partial(
            self._active_dev, ll_a, ll_b, g(self._rlens),
            g(self._tstarts), g(self._tends), jnp.asarray(real),
            jnp.asarray(idx))

    # ---------------------------------------------------------------- scoring

    def _dispatch_chunk(self, pos_f, end_f, mtype, base_f, pos_r, base_r,
                        valid):
        """Dispatch one (Z, MUT_CHUNK) slab's device programs without
        blocking; pair with _collect_chunk.  Keeping several chunks in
        flight hides dispatch latency behind device compute (the profile
        showed ~2 host syncs per chunk serializing the refinement round)."""
        Z = self._Z
        # (Z, R, M) host-side classification
        ts = self._tstarts[:, :, None]
        te = self._tends[:, :, None]
        strand = self._strands[:, :, None]
        ms, me = pos_f[:, None, :], end_f[:, None, :]
        is_ins = (mtype == INS)[:, None, :]
        overlap = np.where(is_ins, (ts <= me) & (ms <= te), (ts < me) & (ms < te))
        p_w = np.where(strand == 0, ms - ts, te - me)
        e_w = np.where(strand == 0, me - ts, te - ms)
        wlen = te - ts
        interior = (p_w >= 3) & (e_w <= wlen - 2)
        # geometry-only masks (real read rows only): the read-active mask
        # stays on device and is ANDed in-program, so refinement rounds need
        # no active-mask fetch
        geo = valid[:, None, :] & overlap & self._real_rows[:, :, None]
        int_mask = geo & interior
        edge_mask = geo & ~interior

        totals_dev, patches_f, patches_r = _batch_interior_totals(
            self._reads_dev, self._rlens_dev,
            self._strands_dev, self._tstarts_dev,
            self._tends_dev,
            self.win_tpl, self.win_trans, self.wlens,
            self.alpha.vals, self.alpha.offsets, self.alpha.log_scales,
            self.beta.vals, self.beta.offsets, self.beta.log_scales,
            self.a_prefix, self.b_suffix, self._baselines_dev,
            self._tpl32_dev, self.trans_f, self._tpl32_r_dev, self.trans_r,
            self.table, self._tlens_dev,
            self._shard(pos_f), self._shard(end_f), self._shard(mtype),
            self._shard(base_f), self._shard(pos_r), self._shard(base_r),
            self._shard(int_mask, 1), self._active_dev)

        # boundary mutations on adequately long windows: short extension
        # programs over (Z, R, EDGE_SLAB) slabs
        fast_mask = edge_mask & (wlen >= MIN_FAST_EDGE_WLEN)
        fb_mask = edge_mask & (wlen < MIN_FAST_EDGE_WLEN)
        em_any = fast_mask.any(axis=1)                      # (Z, M)
        counts = em_any.sum(axis=1)
        if counts.any():
            # Vectorized ragged->dense marshalling: a stable argsort on
            # ~em_any packs each row's edge-mutation indices to the front
            # (True sorts before False), so every slab is a pure numpy
            # gather with no per-(slab, Z) Python loop.
            Mc = int(counts.max())
            order = np.argsort(~em_any, axis=1, kind="stable")[:, :Mc]
            packed_valid = np.take_along_axis(em_any, order, axis=1)
            L_arr = self._tpl_lengths()
            d_pos_f = np.broadcast_to((L_arr // 2)[:, None], (Z, Mc))
            d_end_f = d_pos_f + 1
            d_pos_r = np.broadcast_to((L_arr - L_arr // 2 - 1)[:, None],
                                      (Z, Mc))
            gath = lambda a: np.take_along_axis(a, order, axis=1)
            g_pos_f = np.where(packed_valid, gath(pos_f), d_pos_f)
            g_end_f = np.where(packed_valid, gath(end_f), d_end_f)
            g_mtype = np.where(packed_valid, gath(mtype), SUB)
            g_base_f = np.where(packed_valid, gath(base_f), 0)
            g_pos_r = np.where(packed_valid, gath(pos_r), d_pos_r)
            g_base_r = np.where(packed_valid, gath(base_r), 0)
            g_mask = np.take_along_axis(fast_mask, order[:, None, :],
                                        axis=2) & packed_valid[:, None, :]
            n_slabs = (Mc + EDGE_SLAB - 1) // EDGE_SLAB
            pad = n_slabs * EDGE_SLAB - Mc
            if pad:
                padz = lambda a, fill: np.concatenate(
                    [a, np.broadcast_to(fill, a.shape[:-1] + (pad,))], axis=-1)
                g_pos_f = padz(g_pos_f, d_pos_f[:, :1])
                g_end_f = padz(g_end_f, d_end_f[:, :1])
                g_mtype = padz(g_mtype, SUB)
                g_base_f = padz(g_base_f, 0)
                g_pos_r = padz(g_pos_r, d_pos_r[:, :1])
                g_base_r = padz(g_base_r, 0)
                g_mask = padz(g_mask, False)
                order = padz(order, 0)
                packed_valid = padz(packed_valid, False)
            for k in range(n_slabs):
                sl = slice(k * EDGE_SLAB, (k + 1) * EDGE_SLAB)
                spos_f = np.ascontiguousarray(g_pos_f[:, sl], np.int32)
                send_f = np.ascontiguousarray(g_end_f[:, sl], np.int32)
                smtype = np.ascontiguousarray(g_mtype[:, sl], np.int32)
                sbase_f = np.ascontiguousarray(g_base_f[:, sl], np.int32)
                spos_r = np.ascontiguousarray(g_pos_r[:, sl], np.int32)
                sbase_r = np.ascontiguousarray(g_base_r[:, sl], np.int32)
                smask = np.ascontiguousarray(g_mask[:, :, sl])
                sel_idx = np.ascontiguousarray(order[:, sl], np.int64)
                used = np.ascontiguousarray(packed_valid[:, sl])
                et_dev = _batch_edge_fast_totals(
                    self._reads_dev, self._rlens_dev,
                    self._strands_dev, self._tstarts_dev, self._tends_dev,
                    self.win_tpl, self.win_trans, self.wlens,
                    self.alpha.vals, self.alpha.offsets, self.alpha.log_scales,
                    self.beta.vals, self.beta.offsets, self.beta.log_scales,
                    self.a_prefix, self.b_suffix, self._baselines_dev,
                    self._tpl32_dev, self.trans_f, self._tpl32_r_dev,
                    self.trans_r, self.table, self._tlens_dev,
                    self._shard(spos_f), self._shard(send_f),
                    self._shard(smtype), self._shard(sbase_f),
                    self._shard(spos_r), self._shard(sbase_r),
                    self._shard(smask, 1), self._active_dev)
                totals_dev = _fold_edge_slab(totals_dev, et_dev,
                                             jnp.asarray(sel_idx),
                                             jnp.asarray(used))

        # tiny-window fallback pairs: marshalling needs patch values on the
        # host (one fetch); rare -- only windows below MIN_FAST_EDGE_WLEN
        ez_all, er_all, em_all = np.nonzero(fb_mask)
        if len(ez_all):
            pf_b = np.asarray(patches_f.bases)
            pf_t = np.asarray(patches_f.trans)
            pf_s = np.asarray(patches_f.shift)
            pr_b = np.asarray(patches_r.bases)
            pr_t = np.asarray(patches_r.trans)
            pr_s = np.asarray(patches_r.shift)
            # chunk the edge pairs: one huge pallas fill batch can exceed the
            # compiler's limits, and pow2 chunks keep the shape set bounded
            EDGE_CHUNK = 1024
            for lo in range(0, len(ez_all), EDGE_CHUNK):
                ez = ez_all[lo: lo + EDGE_CHUNK]
                er = er_all[lo: lo + EDGE_CHUNK]
                em = em_all[lo: lo + EDGE_CHUNK]
                E = len(ez)
                Epad = next_pow2(E, 64)
                zi = np.zeros(Epad, np.int32)
                ri = np.zeros(Epad, np.int32)
                pp = np.zeros(Epad, np.int32)
                pt = np.zeros(Epad, np.int32)
                pb = np.zeros((Epad, 2), np.int32)
                ptr = np.zeros((Epad, 2, 4), np.float32)
                psh = np.zeros(Epad, np.int32)
                mi = np.zeros(Epad, np.int32)
                ok = np.zeros(Epad, bool)
                zi[:E], ri[:E], mi[:E], ok[:E] = ez, er, em, True
                pp[:E] = p_w[ez, er, em]
                pt[:E] = mtype[ez, em]
                fwd = self._strands[ez, er] == 0
                pb[:E] = np.where(fwd[:, None], pf_b[ez, em], pr_b[ez, em])
                ptr[:E] = np.where(fwd[:, None, None], pf_t[ez, em], pr_t[ez, em])
                psh[:E] = np.where(fwd, pf_s[ez, em], pr_s[ez, em])
                ll_dev = _batch_edge(
                    self._reads_dev, self._rlens_dev,
                    self.win_tpl, self.win_trans, self.wlens,
                    jnp.asarray(zi), jnp.asarray(ri), jnp.asarray(pp),
                    jnp.asarray(pt), jnp.asarray(pb), jnp.asarray(ptr),
                    jnp.asarray(psh), self._W,
                    fills_use_pallas() and self.mesh is None)
                totals_dev = _fold_fallback(
                    totals_dev, ll_dev, self._baselines_dev,
                    self._active_dev,
                    jnp.asarray(zi), jnp.asarray(ri), jnp.asarray(mi),
                    jnp.asarray(ok))
        return totals_dev

    def score_mutation_arrays(self, arrs: Sequence[mutlib.MutationArrays]
                              ) -> list[np.ndarray]:
        """Per-ZMW arrays of summed mutation scores from MutationArrays
        batches — the vectorized-marshalling fast path (parity with
        ArrowMultiReadScorer.score_mutations, batched over Z)."""
        assert len(arrs) == self.n_zmws
        Z = self._Z
        Mmax = max((a.size for a in arrs), default=0)
        if Mmax == 0:
            return [np.zeros(0) for _ in arrs]
        rcs = [mutlib.reverse_complement_arrays(a, len(self.tpls[z]))
               for z, a in enumerate(arrs)]
        n_chunks = (Mmax + MUT_CHUNK - 1) // MUT_CHUNK

        # Ragged->dense marshalling without per-(chunk, Z) Python loops and
        # without (Z, Mmax)-padded planes: the per-ZMW mutation arrays are
        # concatenated once (actual data size, no padding) and every chunk's
        # (Z, MUT_CHUNK) slab is one vectorized clipped gather, ~15 MB of
        # transient per chunk regardless of Mmax.  Default dummies sit
        # mid-template to stay interior & cheap.
        sizes = np.array([a.size for a in arrs], np.int64)
        offs = np.zeros(self.n_zmws + 1, np.int64)
        np.cumsum(sizes, out=offs[1:])
        catf = lambda field, src: np.concatenate(
            [getattr(a, field) for a in src]) if offs[-1] else \
            np.zeros(0, np.int32)
        flat_pos_f = catf("start", arrs)
        flat_end_f = catf("end", arrs)
        flat_mtype = catf("mtype", arrs)
        flat_base_f = catf("new_base", arrs)
        flat_pos_r = catf("start", rcs)
        flat_base_r = catf("new_base", rcs)

        L_arr = self._tpl_lengths()
        d_pos_f = np.broadcast_to((L_arr // 2)[:, None], (Z, MUT_CHUNK))
        d_end_f = d_pos_f + 1
        d_pos_r = np.broadcast_to((L_arr - L_arr // 2 - 1)[:, None],
                                  (Z, MUT_CHUNK))

        # dispatch every chunk before collecting any: the device works
        # through the queued programs while the host marshals ahead
        states = []
        m = np.arange(MUT_CHUNK, dtype=np.int64)[None, :]
        for c in range(n_chunks):
            lo = c * MUT_CHUNK
            valid = np.zeros((Z, MUT_CHUNK), bool)
            valid[: self.n_zmws] = (lo + m) < sizes[:, None]
            gidx = np.zeros((Z, MUT_CHUNK), np.int64)
            gidx[: self.n_zmws] = np.minimum(
                offs[:-1, None] + lo + m, offs[1:, None] - 1)
            gidx = np.clip(gidx, 0, max(offs[-1] - 1, 0))
            pick = lambda flat, dflt: np.where(
                valid, flat[gidx], dflt) if len(flat) else \
                np.broadcast_to(dflt, (Z, MUT_CHUNK)).copy()
            states.append(self._dispatch_chunk(
                pick(flat_pos_f, d_pos_f).astype(np.int32),
                pick(flat_end_f, d_end_f).astype(np.int32),
                pick(flat_mtype, SUB).astype(np.int32),
                pick(flat_base_f, 0).astype(np.int32),
                pick(flat_pos_r, d_pos_r).astype(np.int32),
                pick(flat_base_r, 0).astype(np.int32),
                valid))

        # one stacked fetch for the whole call: every device->host transfer
        # over the tunneled link costs ~0.1-0.25 s regardless of payload
        stacked = device_fetch(_stack_chunks(states), np.float64)
        out = []
        for z in range(self.n_zmws):
            # (C, M) row view -> one contiguous copy of this ZMW's scores
            out.append(np.ascontiguousarray(
                stacked[:, z, :]).reshape(-1)[: arrs[z].size])
        return out

    def score_mutations(self, muts_per_zmw: Sequence[Sequence[mutlib.Mutation]]
                        ) -> list[np.ndarray]:
        """Object-list convenience wrapper over score_mutation_arrays."""
        return self.score_mutation_arrays(
            [mutlib.arrays_from_mutations(m) for m in muts_per_zmw])

    # --------------------------------------------------------------- mutation

    def apply_mutations(self, best_per_zmw: Sequence[Sequence[mutlib.Mutation]]
                        ) -> None:
        """Splice per-ZMW mutations, remap read windows, rebuild fills."""
        changed: list[int] = []
        self._tpl_lengths_cache = None
        self._cont.qv_cache = None
        for z, best in enumerate(best_per_zmw):
            if not best:
                continue
            changed.append(z)
            L = len(self.tpls[z])
            mtp = mutlib.target_to_query_positions(best, L)
            self.tpls[z] = mutlib.apply_mutations(self.tpls[z], best)
            self._tstarts[z] = mtp[np.clip(self._tstarts[z], 0, L)]
            self._tends[z] = mtp[np.clip(self._tends[z], 0, L)]
        if not changed:
            return
        max_l = max(len(t) for t in self.tpls)
        rebucket = max_l + 2 > self._Jmax
        if rebucket:
            self._Jmax = _jmax_bucket(max_l)  # rebucket (recompiles)
        # partial refill when a minority of ZMWs changed (mesh runs always
        # rebuild in full: the compacted sub-batch breaks the sharding)
        if (self.mesh is None and not rebucket
                and len(changed) * 2 <= self.n_zmws):
            self._setup_partial(changed)
        else:
            self._setup(first=False)

    # ------------------------------------------------------------- refinement

    def _device_resident_enabled(self) -> bool:
        """One source of truth for the device-resident-path gate (the
        refinement loop and the QV sweep must agree); opt-out via
        PBCCS_DEVICE_REFINE=0/false/off/no.  Mesh runs ride the sharded
        loop (device_refine.run_refine_loop_sharded), which requires the
        dense scoring path -- without it they fall back to the host
        loop's sharded per-round programs."""
        if os.environ.get("PBCCS_DEVICE_REFINE", "").strip().lower() in (
                "0", "false", "off", "no"):
            return False
        if self.mesh is not None:
            from pbccs_tpu.ops.dense_score_pallas import dense_score_enabled

            return dense_score_enabled(self._Jmax)
        return True

    def _loop_state(self, skip=None, it0: int = 0):
        """Assemble the device-resident loop/sweep state from the adopted
        device tensors (parallel/device_refine.RefineLoopState).

        When the dense scoring path is on, the kernel-layout pre-bake
        happens HERE (state_layout): the loop and the QV sweep launch on
        baked buffers, and only fill-rebuilding rounds re-derive them."""
        from pbccs_tpu.ops.dense_score_pallas import dense_score_enabled
        from pbccs_tpu.parallel import device_refine as dr

        Z, Jmax = self._Z, self._Jmax
        tl, tlens = self._template_arrays()
        done0 = np.zeros(Z, bool)
        done0[self.n_zmws:] = True
        for z in (skip or ()):
            done0[z] = True
        dlayout = None
        if dense_score_enabled(Jmax):
            dlayout = dr.state_layout(
                self._reads_dev, self._rlens_dev, self.win_tpl,
                self.win_trans, self.wlens,
                self._shard(self._host_tables), self.alpha, self.beta,
                self.a_prefix, self.b_suffix, width=self._W)
        H = 48
        return dr.RefineLoopState(
            tpl=jnp.asarray(tl), tlens=jnp.asarray(tlens),
            tstarts=self._tstarts_dev, tends=self._tends_dev,
            win_tpl=self.win_tpl, win_trans=self.win_trans,
            wlens=self.wlens, alpha=self.alpha, beta=self.beta,
            a_prefix=self.a_prefix, b_suffix=self.b_suffix,
            baselines=self._baselines_dev, trans_f=self.trans_f,
            tpl_r=self.tpl_r, trans_r=self.trans_r,
            active=self._active_dev,
            # it0 > 0 (a straggler continuation) starts the round counter
            # at the rounds already spent: the static max_iterations bound
            # is unchanged (one executable per shape) while the loop runs
            # at most the remaining rounds
            it=jnp.int32(it0),
            done=jnp.asarray(done0),
            converged=jnp.zeros(Z, bool),
            iterations=jnp.zeros(Z, jnp.int32),
            n_tested=jnp.zeros(Z, jnp.int32),
            n_applied=jnp.zeros(Z, jnp.int32),
            allowed=jnp.ones((Z, Jmax), bool),
            history=jnp.zeros((Z, H), jnp.uint32),
            hist_n=jnp.zeros(Z, jnp.int32),
            overflow=jnp.asarray(False),
            dlayout=dlayout)

    def refine_device(self, opts: RefineOptions | None = None,
                      skip=None, budget: int | None = None
                      ) -> list[RefineResult] | None:
        """Device-resident refinement: the whole loop runs inside one
        jitted lax.while_loop (parallel/device_refine.py) and the host
        fetches ONCE at the end -- over the tunneled device link the host
        loop's per-round fetch chain is ~80% of polish wall time.

        Returns None when the loop bailed (template outgrew the bucket or
        a tiny-window fallback pair appeared); the caller falls back to
        the host loop.  Mesh runs shard the whole loop over the
        ('zmw', 'read') mesh (run_refine_loop_sharded): the read-axis
        score reduction all-reduces over ICI and the host still fetches
        ONCE at the end."""
        from pbccs_tpu.ops.dense_score_pallas import dense_score_enabled
        from pbccs_tpu.parallel import device_refine as dr

        if self.mesh is not None and not dense_score_enabled(self._Jmax):
            return None
        opts = opts or RefineOptions()
        budget = opts.max_iterations if budget is None else budget
        # rebuild-if-stale + drop loop-end state (invariants: _Continuation)
        self._cont.begin_refine(self)
        Z, R, Jmax = self._Z, self._R, self._Jmax

        st = self._loop_state(skip, it0=opts.max_iterations - budget)

        loop_statics = dict(
            width=self._W, use_pallas=fills_use_pallas(),
            max_iterations=opts.max_iterations,
            separation=opts.mutation_separation,
            neighborhood=opts.mutation_neighborhood,
            chunk=MUT_CHUNK, min_fast_edge=MIN_FAST_EDGE_WLEN,
            dense=dense_score_enabled(self._Jmax),
            guided_passes=guided_fill_passes(self._Jmax))
        loop_args = (st, self._reads_dev, self._rlens_dev,
                     self._strands_dev, self._shard(self._host_tables),
                     self._shard(self._real_rows, 1))
        if self.mesh is not None:
            out = dr.run_refine_loop_sharded(
                self.mesh, ZMW_AXIS, READ_AXIS, *loop_args, **loop_statics)
        else:
            out = dr.run_refine_loop(*loop_args, **loop_statics)
        # Eager QV sweep on the loop's final state, dispatched back-to-back
        # with the loop program (no host sync between them): consensus_qvs
        # serves from the cached integers, so a refine+QV polish pays ONE
        # device->host fetch total instead of a separate ~1.5 MB score
        # fetch + round trip over the tunneled link.
        qv_skip = np.zeros(Z, bool)
        qv_skip[self.n_zmws:] = True
        for z in (skip or ()):
            qv_skip[z] = True
        qv_statics = dict(chunk=MUT_CHUNK, min_fast_edge=MIN_FAST_EDGE_WLEN,
                          dense=dense_score_enabled(self._Jmax))
        qv_args = (out, self._reads_dev, self._rlens_dev,
                   self._strands_dev, self._shard(self._host_tables),
                   self._shard(self._real_rows, 1), self._shard(qv_skip))
        if self.mesh is not None:
            qv_i, qv_fb = dr.run_qv_ints_sharded(
                self.mesh, ZMW_AXIS, READ_AXIS, *qv_args, **qv_statics)
        else:
            qv_i, qv_fb = dr.run_qv_ints(*qv_args, **qv_statics)
        # ONE stacked fetch of every outcome plane (each device->host round
        # trip costs ~0.1-0.25 s over the tunneled link; three sequential
        # fetches here were ~0.5 s of pure latency per polish)
        R = self._R
        packed = jnp.concatenate([
            jnp.stack([out.tlens.astype(jnp.int32),
                       out.converged.astype(jnp.int32),
                       out.iterations, out.n_tested, out.n_applied,
                       jnp.broadcast_to(out.overflow.astype(jnp.int32),
                                        (Z,)),
                       jnp.broadcast_to(qv_fb.astype(jnp.int32), (Z,))],
                      axis=1),
            out.tpl.astype(jnp.int32),
            out.tstarts.astype(jnp.int32),
            out.tends.astype(jnp.int32),
            qv_i,
        ], axis=1)
        h = device_fetch(packed, np.int64)
        tlens_h, conv_h, iters_h = h[:, 0], h[:, 1], h[:, 2]
        tested_h, applied_h, overflow_h = h[:, 3], h[:, 4], h[:, 5]
        if overflow_h[0]:
            return None  # host loop re-runs from the polisher's last state
        if not h[0, 6]:  # no tiny-window fallback in the QV sweep
            self._cont.qv_cache = (frozenset(skip or ()),
                                   h[:, 7 + Jmax + 2 * R:].astype(np.int32))

        tpl_h = h[:, 7: 7 + Jmax].astype(np.int8)
        for z in range(self.n_zmws):
            self.tpls[z] = tpl_h[z, : tlens_h[z]].copy()
        self._tstarts = h[:, 7 + Jmax: 7 + Jmax + R].astype(np.int32)
        self._tends = h[:, 7 + Jmax + R: 7 + Jmax + 2 * R].astype(np.int32)
        self._tpl_lengths_cache = None

        # adopt the loop's final device state so the QV sweep reuses it
        (self.win_tpl, self.win_trans, self.wlens, self.alpha, self.beta,
         self.a_prefix, self.b_suffix) = (
            out.win_tpl, out.win_trans, out.wlens, out.alpha, out.beta,
            out.a_prefix, out.b_suffix)
        self._baselines_dev = out.baselines
        self._active_dev = out.active
        self.trans_f, self.tpl_r, self.trans_r = (out.trans_f, out.tpl_r,
                                                  out.trans_r)
        self._tpl_dev = out.tpl
        self._tpl32_dev = out.tpl.astype(jnp.int32)
        self._tpl32_r_dev = out.tpl_r.astype(jnp.int32)
        self._tstarts_dev = out.tstarts
        self._tends_dev = out.tends
        self._tlens_dev = out.tlens
        self._tlens = tlens_h.astype(np.int32)

        # skip/padding ZMWs start done and can never set converged on device
        results = [RefineResult(converged=bool(conv_h[z]),
                                n_tested=int(tested_h[z]),
                                n_applied=int(applied_h[z]),
                                iterations=int(iters_h[z]))
                   for z in range(self.n_zmws)]

        # flight recorder: the device-resident loop is one jitted program
        # (per-round host callbacks would reintroduce the fetch-per-round
        # chain), so its per-round occupancy is RECONSTRUCTED from the
        # fetched iteration counts -- a ZMW with k iterations was live in
        # rounds 0..k-1, which is exact for the lockstep loop
        it0_rounds = opts.max_iterations - budget
        iters_live = iters_h[: self.n_zmws]
        for rnd in range(int(iters_live.max(initial=0))):
            obs_flight.record_round(
                self._flight_tag, it0_rounds + rnd,
                int((iters_live > rnd).sum()), self.n_zmws, self._Z,
                source="device")

        # Straggler continuation: the loop exits early once few ZMWs remain
        # (full-width lockstep rounds for 1-2 cycling ZMWs would dominate,
        # e.g. a 40-round budget); finish them in a compact small-Z
        # sub-polisher whose own device loop runs tiny rounds fetch-free.
        skipset = set(skip or ())
        stragglers = [z for z in range(self.n_zmws)
                      if z not in skipset and not results[z].converged
                      and results[z].iterations < budget]
        # stragglers share one iteration count by construction: the device
        # loop is lockstep, a ZMW leaves it only by converging (which
        # excludes it from `stragglers`), so every straggler ran every
        # round up to the early exit -- max() == each straggler's count
        sub_budget = (budget - max(results[z].iterations
                                   for z in stragglers)) if stragglers else 0
        if stragglers and sub_budget > 0 and self.n_zmws > len(stragglers) \
                and self.mesh is None:
            # the continuation carries the REMAINING round budget (total
            # iterations across parent + sub match the host loop and the
            # reference's single max_iterations bound); the static
            # max_iterations stays the executable-cache key, the spent
            # rounds ride in as the dynamic initial round counter.
            # Shapes pin to the parent's buckets + ONE canonical Z (the
            # pow2 of the loop's straggler-exit threshold, an upper bound
            # on the straggler count) so every draw's straggler set --
            # whatever its size -- reuses the same compiled programs
            # (_straggler_sub; pre-warmable via warm_straggler_shapes).
            sub = self._straggler_sub(stragglers)
            # parent gating carries over; the sub-polisher must not re-gate
            # (it sees mid-refinement templates, not the draft).  The live
            # read-active mask is on device (host copy is the AddRead-time
            # snapshot by design); fetch just the straggler rows.
            act = device_fetch(out.active)
            sub_active = np.zeros((sub._Z, sub._R), bool)
            for i, z in enumerate(stragglers):
                n = min(sub._R, self._R)
                sub_active[i, :n] = act[z, :n]
            sub._active_dev = sub._shard(sub_active, 1)
            sub_res = sub.refine(opts, budget=sub_budget)
            for i, z in enumerate(stragglers):
                self.tpls[z] = sub.tpls[i]
                r = sub_res[i]
                results[z] = RefineResult(
                    converged=r.converged,
                    n_tested=results[z].n_tested + r.n_tested,
                    n_applied=results[z].n_applied + r.n_applied,
                    iterations=results[z].iterations + r.iterations)
            self._tpl_lengths_cache = None
            self._cont.record_continuation(
                {z: (sub, i) for i, z in enumerate(stragglers)})
        return results

    def straggler_shape_min_z(self) -> int:
        """The canonical ZMW-axis size of this polisher's straggler
        continuation sub-batches (device_refine.run_refine_loop exits
        early once <= Z//32 ZMWs remain; the sub-batch pads to this one
        pow2 size so its compiled shapes are draw-independent)."""
        return next_pow2(max(self._Z // 32, 1), 4)

    def _straggler_sub(self, zmws: Sequence[int]) -> "BatchPolisher":
        """Construct the canonical straggler-continuation sub-batch for
        the given parent rows — ONE shape recipe shared by the live
        continuation (refine_device) and warm_straggler_shapes, so the
        pre-warm compiles exactly the executables the continuation uses."""
        sub_tasks = []
        for z in zmws:
            rows = np.nonzero(self._real_rows[z])[0]
            sub_tasks.append(ZmwTask(
                f"straggler/{z}", self.tpls[z].copy(), self._snrs[z],
                [self._reads[z, r, : self._rlens[z, r]].copy()
                 for r in rows],
                [int(self._strands[z, r]) for r in rows],
                [int(self._tstarts[z, r]) for r in rows],
                [int(self._tends[z, r]) for r in rows]))
        return BatchPolisher(sub_tasks, config=self.config,
                             buckets=(self._Imax, self._Jmax, self._R),
                             min_z=self.straggler_shape_min_z())

    def warm_straggler_shapes(self, opts: RefineOptions | None = None
                              ) -> None:
        """Compile the straggler-continuation shapes ahead of timed work.

        Whether a batch produces stragglers is data-dependent; their first
        appearance used to cold-compile a ~minute-long device loop inside
        a timed run (the round-3 53x tail-latency outlier).  `opts` must
        match the opts later passed to refine() -- max_iterations is part
        of the executable cache key."""
        if self._Z // 32 < 1 or self.n_zmws < 1:
            return  # this Z has no straggler early exit
        sub = self._straggler_sub([0])
        sub.refine(opts)
        sub.consensus_qvs()

    def refine(self, opts: RefineOptions | None = None,
               skip=None, budget: int | None = None) -> list[RefineResult]:
        """Lockstep greedy refinement across the batch.

        Single-device runs route through the device-resident loop
        (refine_device: the whole loop in one program, one fetch) unless
        PBCCS_DEVICE_REFINE=0; mesh runs and device-loop bails (template
        outgrew the bucket, tiny-window fallback pair) use the host loop
        below, whose behavior the device loop is parity-tested against.

        ZMW indices in `skip` take no part in refinement (their RefineResult
        stays non-converged): the pipeline excludes ZMWs that already failed
        a yield gate so their slots cost no mutation work and their templates
        cannot grow the bucket.

        `budget` caps the number of refinement rounds this call may run
        (defaults to opts.max_iterations); a straggler continuation passes
        its remaining rounds so parent + continuation together never exceed
        the reference's single max_iterations bound."""
        with obs_roofline.refine_scope(imax=self._Imax, jmax=self._Jmax,
                                       r=self._R):
            return self._refine_impl(opts, skip, budget)

    def _refine_impl(self, opts, skip, budget) -> list[RefineResult]:
        opts = opts or RefineOptions()
        if budget is None:
            budget = opts.max_iterations
        if self._device_resident_enabled():
            results = self.refine_device(opts, skip, budget=budget)
            if results is not None:
                return results
        Z = self.n_zmws
        results = [RefineResult(converged=False) for _ in range(Z)]
        history: list[set[int]] = [set() for _ in range(Z)]
        favorable: list[list[mutlib.Mutation]] = [[] for _ in range(Z)]
        done = np.zeros(Z, bool)
        for z in (skip or ()):
            done[z] = True

        empty = mutlib.MutationArrays(*(np.zeros(0, np.int32),) * 4)
        for it in range(budget):
            # f32 score-noise floor, recomputed PER ROUND from the current
            # device-side baselines/active mask -- the same favorability
            # rule (and the same f32 arithmetic) as the device-resident
            # loop and the per-round serial scorer, so all three polish
            # paths select identically.  One tiny (Z,)-fetch per round;
            # this loop is already the fetch-per-round fallback path.
            eps_z = device_fetch(
                _favorability_eps(self._baselines_dev, self._active_dev),
                np.float64)
            arrs: list[mutlib.MutationArrays] = []
            for z in range(Z):
                if done[z]:
                    arrs.append(empty)
                elif it == 0:
                    arrs.append(mutlib.enumerate_unique_arrays(self.tpls[z]))
                else:
                    arrs.append(mutlib.unique_nearby_arrays(
                        self.tpls[z], favorable[z], opts.mutation_neighborhood))
            if all(done):
                break
            live = int((~done).sum())
            obs_flight.record_round(self._flight_tag, it, live,
                                    self.n_zmws, self._Z)
            with obs_trace.span("polish.round", round=it, live=live):
                scores = self.score_mutation_arrays(arrs)

                best_per_zmw: list[list[mutlib.Mutation]] = []
                for z in range(Z):
                    if done[z]:
                        best_per_zmw.append([])
                        continue
                    results[z].iterations = it + 1
                    results[z].n_tested += arrs[z].size
                    favi = np.nonzero(scores[z] > eps_z[z])[0]
                    fav = arrs[z].take(favi).to_mutations(scores[z][favi])
                    favorable[z] = fav
                    if not fav:
                        results[z].converged = True
                        done[z] = True
                        best_per_zmw.append([])
                        continue
                    best = mutlib.best_subset(fav, opts.mutation_separation)
                    # cycle avoidance (Consensus-inl.hpp:229-241): trim a
                    # visited multi-mutation result to its best single
                    # mutation, but keep iterating (a repeated template does
                    # not terminate; see models/arrow/refine.py)
                    if len(best) > 1:
                        nxt = mutlib.apply_mutations(self.tpls[z], best)
                        if hash(nxt.tobytes()) in history[z]:
                            best = [max(best, key=lambda m: m.score)]
                    history[z].add(hash(self.tpls[z].tobytes()))
                    results[z].n_applied += len(best)
                    best_per_zmw.append(best)

                self.apply_mutations(best_per_zmw)

        return results

    # ------------------------------------------------------------------- QVs

    def consensus_qvs(self, skip=None) -> list[np.ndarray]:
        """Per-ZMW per-position QVs (parity: ConsensusQVs,
        Consensus-inl.hpp:277-297), one batched sweep.  ZMWs in `skip` get
        empty QV arrays and cost no device work.  ZMWs the device loop
        finished in a straggler sub-polisher (refine_device) pull their QVs
        from it -- the parent's fills for those slots are pre-continuation."""
        skip = set(skip or ())
        out = self._consensus_qvs_impl(
            skip | set(self._cont.sub_polishers))
        return self._cont.delegated_qvs(out, skip)

    def _consensus_qvs_impl(self, skip) -> list[np.ndarray]:
        # refine_device leaves per-position integer QVs computed on the
        # loop's final state (run_qv_ints); serve from that cache when
        # every live ZMW was live in the cached sweep too.  The cached
        # reduction ran in f32 on device; the fallback below reduces in
        # f64 on host -- identical except where the exact QV lands within
        # f32 rounding of a .5 boundary (a <=1-unit knife-edge, invisible
        # after the [0, 93] output clamp)
        cached = self._cont.cached_qvs(self.n_zmws, set(skip), self.tpls)
        if cached is not None:
            return cached
        empty = mutlib.MutationArrays(*(np.zeros(0, np.int32),) * 4)
        arrs = [empty if z in skip else mutlib.enumerate_unique_arrays(t)
                for z, t in enumerate(self.tpls[: self.n_zmws])]
        skipped = [z in skip for z in range(self.n_zmws)]
        scores = None
        if self._device_resident_enabled() and self.mesh is None:
            # mesh runs serve QVs from the refine-time cache (run_qv_ints
            # sharded); a cache miss falls through to the chunked sharded
            # scoring path rather than the unsharded grid program
            scores = self._qv_scores_device(skip, arrs)
        if scores is None:
            scores = self.score_mutation_arrays(arrs)
        out = []
        for z in range(self.n_zmws):
            if skipped[z]:
                out.append(np.zeros(0, np.int32))
                continue
            ssum = np.zeros(len(self.tpls[z]))
            neg = scores[z] < 0.0
            np.add.at(ssum, arrs[z].start[neg], np.exp(scores[z][neg]))
            out.append(mutlib.qvs_from_neg_sums(ssum))
        return out

    def _qv_scores_device(self, skip, arrs) -> list[np.ndarray] | None:
        """QV-sweep slot-grid scores in ONE device program + one fetch.

        The chunked host path (score_mutation_arrays) dispatches C programs
        with numpy mask building between them -- ~1 s of wall for ~80 ms of
        device compute on the bench workload.  Per-slot values are
        identical (packing only reorders the chunk axis), so the host
        aggregation downstream is unchanged.  Returns None when a
        tiny-window fallback pair exists (the chunked path handles it)."""
        from pbccs_tpu.parallel import device_refine as dr

        st = self._loop_state(skip)
        skip_mask = np.zeros(self._Z, bool)
        skip_mask[self.n_zmws:] = True
        for z in skip:
            skip_mask[z] = True
        from pbccs_tpu.ops.dense_score_pallas import dense_score_enabled

        packed, fb = dr.run_qv_grid(
            st, self._reads_dev, self._rlens_dev, self._strands_dev,
            self._shard(self._host_tables), jnp.asarray(self._real_rows),
            jnp.asarray(skip_mask),
            chunk=MUT_CHUNK, min_fast_edge=MIN_FAST_EDGE_WLEN,
            dense=dense_score_enabled(self._Jmax))
        stacked = device_fetch(jnp.concatenate(
            [packed, jnp.broadcast_to(fb.astype(packed.dtype),
                                      (1, packed.shape[1]))], axis=0),
            np.float64)
        if stacked[-1, 0] > 0.5:
            return None  # tiny-window fallback pair: chunked path handles
        out = []
        for z in range(self.n_zmws):
            if skip_mask[z]:
                out.append(np.zeros(0))
                continue
            # row z's leading entries are its valid-slot scores in host
            # enumeration order (run_qv_grid packing contract)
            out.append(stacked[z, : arrs[z].size])
        return out

    # -------------------------------------------------------------- banding

    def banding_report(self) -> dict:
        """Banding / matrix-usage introspection (the TPU analogue of the
        reference's AllocatedMatrixEntries / UsedMatrixEntries /
        NumFlipFlops counters, Arrow/MultiReadMutationScorer.hpp:139-144):
        band occupancy of the current alpha fills, mating-gate outcomes,
        and the static VMEM footprint of the dense kernel's grid cell.
        One device fetch; intended for logs and the bench artifact, and
        for justifying W-per-length-bucket schedules."""
        from pbccs_tpu.ops.dense_score_pallas import (cell_vmem_bytes,
                                                      whole_row_mode)

        W = self._W
        nc = int(self.alpha.vals.shape[2])
        # occupancy: fraction of band lanes holding live probability mass
        # per in-window column, averaged over real active reads
        live_col = (jnp.arange(nc)[None, None, :]
                    <= self.wlens[:, :, None])
        nz = jnp.sum((self.alpha.vals > 0) & live_col[:, :, :, None],
                     axis=(2, 3))
        denom = jnp.maximum(jnp.sum(live_col, axis=2) * W, 1)
        occ = nz / denom
        act = self._active_dev
        occ_mean = jnp.sum(jnp.where(act, occ, 0.0)) / jnp.maximum(
            jnp.sum(act), 1)
        occ_max = jnp.max(jnp.where(act, occ, 0.0))
        vals = device_fetch(jnp.stack([occ_mean, occ_max]), np.float64)
        self._ensure_stats()
        statuses = self._stats_host["statuses"]
        real = self._real_rows
        jm = int(self.win_tpl.shape[2])   # the kernel's actual bucket
        whole_row = whole_row_mode(jm)
        vmem_cell = cell_vmem_bytes(jm, W)
        return {
            "band_width": W,
            "jmax_bucket": self._Jmax,
            "imax_bucket": self._Imax,
            "band_occupancy_mean": round(float(vals[0]), 4),
            "band_occupancy_max": round(float(vals[1]), 4),
            "reads_total": int(real.sum()),
            "mating_failures": int(((statuses == ADD_ALPHABETAMISMATCH)
                                    & real).sum()),
            "zscore_drops": int(((statuses == ADD_POOR_ZSCORE)
                                 & real).sum()),
            "dense_kernel_mode": "whole_row" if whole_row else "halo",
            "dense_kernel_vmem_per_cell_bytes": int(vmem_cell),
            "guided_fill_passes": guided_fill_passes(self._Jmax),
        }

    def global_zscores(self) -> np.ndarray:
        """(Z,) z-score of the summed log-likelihood per ZMW.

        Reports DRAFT-template statistics: baselines/active are AddRead-time
        host snapshots by design (refinement rounds keep their updates on
        device; see _setup), so calling this after refine() still describes
        the pre-refinement template -- which is what the pipeline reports,
        matching the serial path and the reference's draft-time ZScores."""
        out = np.full(self.n_zmws, np.nan)
        for z in range(self.n_zmws):
            act = self.active[z]
            if not act.any():
                continue
            var = self._ll_var[z][act].sum()
            if var <= 0:
                continue
            ll = self.baselines[z][act].sum()
            out[z] = (ll - self._ll_mu[z][act].sum()) / np.sqrt(var)
        return out
