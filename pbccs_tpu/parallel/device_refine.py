"""Device-resident refinement primitives: enumeration, selection, splice.

The host lockstep refinement loop (parallel/batch.py refine) fetches the
(Z, M) mutation scores every round to run selection and template splicing
in numpy; over this environment's tunneled device link each fetch costs
~0.1-0.25 s regardless of size, and the per-round fetch chain dominates
polish wall time (profiled: ~80%).  These primitives re-express the
host-side round logic as fixed-shape device ops so the whole refinement
loop can run inside one jitted program (see batch.BatchPolisher.refine's
device path), fetching once at the end.

Parity targets (each pinned by tests/test_device_refine.py):
  * slot_candidates == mutations.enumerate_unique_arrays (same candidate
    set in the same pos-major order; rounds > 0 apply the same
    center-window position filter as unique_nearby_arrays, though the
    host's center-major candidate ORDER is not reproduced -- order only
    matters for exact score ties);
  * greedy_well_separated == mutations.best_subset (greedy max-score with
    inclusive +-separation start exclusion; ties resolve to the earlier
    candidate, matching the host's first-max rule in round 0);
  * splice_templates == mutations.apply_mutations +
    target_to_query_positions (the mtp map: mtp[j] = j - dels(<j) +
    ins(<=j)).

Candidate slot grid: position-major, 9 slots per template position in the
host enumeration order (subs by base, ins by base, del); invalid slots are
masked, never reordered, so slot index == candidate identity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pbccs_tpu.models.arrow.mutations import (_SLOT_BASES, _SLOT_ENDOFF,
                                              _SLOT_TYPES, DELETION,
                                              INSERTION, SUBSTITUTION)

N_SLOTS = 9
# slot layout per position: the host enumeration's own tables (one source
# of truth for the slot-index == candidate-identity contract)
SLOT_BASES = _SLOT_BASES
SLOT_TYPES = _SLOT_TYPES
SLOT_ENDOFF = _SLOT_ENDOFF

_HASH_MULT = np.uint32(2654435761)  # Knuth multiplicative constant


def slot_candidates(tpl: jax.Array, tlen: jax.Array,
                    allowed_pos: jax.Array | None = None):
    """All unique single-base mutation candidates of one padded template.

    Returns (start, end, mtype, new_base, valid), each (Jmax * 9,), in the
    host enumeration order.  `allowed_pos` ((Jmax,) bool) restricts
    candidate start positions (the nearby-window filter of rounds > 0)."""
    Jmax = tpl.shape[0]
    t = tpl.astype(jnp.int32)
    prev = jnp.concatenate([jnp.array([-1], jnp.int32), t[:-1]])
    pos = jnp.arange(Jmax, dtype=jnp.int32)

    valid = jnp.zeros((Jmax, N_SLOTS), bool)
    valid = valid.at[:, :4].set(SLOT_BASES[None, :4] != t[:, None])
    valid = valid.at[:, 4:8].set(SLOT_BASES[None, 4:8] != prev[:, None])
    valid = valid.at[:, 8].set(t != prev)
    valid &= (pos < tlen)[:, None]
    if allowed_pos is not None:
        valid &= allowed_pos[:, None]

    start = jnp.repeat(pos, N_SLOTS)
    end = start + jnp.asarray(SLOT_ENDOFF)[None, :].repeat(Jmax, 0).reshape(-1)
    mtype = jnp.tile(jnp.asarray(SLOT_TYPES), Jmax)
    base = jnp.tile(jnp.asarray(SLOT_BASES), Jmax)
    return start, end, mtype, base, valid.reshape(-1)


def rc_candidates(start, end, base, tlen):
    """Reverse-complement frame of the slot grid (mutations
    reverse_complement_arrays): (start_r, base_r)."""
    comp = jnp.where(base < 0, -1, 3 - base)
    return tlen - end, comp


def greedy_well_separated(scores: jax.Array, start: jax.Array,
                          favorable: jax.Array, separation: int,
                          jmax: int) -> jax.Array:
    """(M,) bool taken-mask: greedy max-score subset with starts more than
    `separation` apart (inclusive exclusion), ties to the earlier slot.

    Scan over candidates in stable score-descending order carrying a
    blocked-positions mask -- the device best_subset."""
    if separation == 0:  # best_subset: no exclusion, keep every favorable
        return favorable
    M = scores.shape[0]
    neg = jnp.where(favorable, -scores, jnp.inf)
    order = jnp.argsort(neg, stable=True)  # score desc, slot-index ties

    pos = jnp.arange(jmax, dtype=jnp.int32)

    def step(carry, i):
        blocked, taken = carry
        cand = order[i]
        s = start[cand]
        ok = favorable[cand] & ~blocked[s]
        window = (pos >= s - separation) & (pos <= s + separation) & ok
        return (blocked | window, taken.at[cand].set(ok)), None

    (blocked, taken), _ = lax.scan(
        step, (jnp.zeros(jmax, bool), jnp.zeros(M, bool)),
        jnp.arange(M))
    return taken


def splice_templates(tpl: jax.Array, tlen: jax.Array,
                     start: jax.Array, mtype: jax.Array, base: jax.Array,
                     taken: jax.Array):
    """Apply a well-separated taken-set of single-base mutations.

    Returns (new_tpl (Jmax,), new_tlen, mtp (Jmax+1,)) where mtp is the
    old->new position map (target_to_query_positions).  Separation >= 1
    guarantees at most one taken mutation per start position, so the edit
    at each position is unique and the splice is two scatters.

    Capacity contract: new_tlen is returned UNCLAMPED; bases past Jmax are
    dropped by the scatters, so the caller MUST treat new_tlen > Jmax as
    an overflow (the loop sets its bail-to-host flag) rather than carry
    the inconsistent (tpl, tlen) pair into another round."""
    Jmax = tpl.shape[0]
    pos = jnp.arange(Jmax, dtype=jnp.int32)

    # per-position edit planes from the taken set
    safe_start = jnp.clip(start, 0, Jmax - 1)
    is_sub = taken & (mtype == SUBSTITUTION)
    is_ins = taken & (mtype == INSERTION)
    is_del = taken & (mtype == DELETION)
    sub_at = jnp.zeros(Jmax, bool).at[safe_start].max(is_sub)
    sub_base = jnp.zeros(Jmax, jnp.int32).at[safe_start].max(
        jnp.where(is_sub, base, 0))
    ins_at = jnp.zeros(Jmax + 1, bool).at[jnp.clip(start, 0, Jmax)].max(is_ins)
    ins_base = jnp.zeros(Jmax + 1, jnp.int32).at[jnp.clip(start, 0, Jmax)].max(
        jnp.where(is_ins, base, 0))
    del_at = jnp.zeros(Jmax, bool).at[safe_start].max(is_del)

    # mtp[j] = j - dels(start < j) + ins(start <= j)
    dels_before = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(del_at.astype(jnp.int32))])
    ins_upto = jnp.cumsum(ins_at.astype(jnp.int32))
    mtp = jnp.arange(Jmax + 1, dtype=jnp.int32) - dels_before + ins_upto

    new_tlen = mtp[tlen]

    edited = jnp.where(sub_at, sub_base, tpl.astype(jnp.int32))
    new_tpl = jnp.full(Jmax, 4, jnp.int32)
    keep = (~del_at) & (pos < tlen)
    dst = jnp.where(keep, mtp[:-1], Jmax)           # OOB drop for dels/pad
    new_tpl = new_tpl.at[dst].set(edited, mode="drop")
    ins_dst = jnp.where(ins_at & (jnp.arange(Jmax + 1) <= tlen),
                        mtp - 1, Jmax)
    new_tpl = new_tpl.at[ins_dst].set(ins_base, mode="drop")
    return new_tpl.astype(tpl.dtype), new_tlen, mtp


def template_hash(tpl: jax.Array, tlen: jax.Array) -> jax.Array:
    """Rolling uint32 hash of the live template prefix (cycle detection)."""
    Jmax = tpl.shape[0]
    j = jnp.arange(Jmax, dtype=jnp.uint32)
    powers = jnp.power(_HASH_MULT, j + 1)  # uint32 wraparound
    live = (j < tlen.astype(jnp.uint32))
    vals = jnp.where(live, tpl.astype(jnp.uint32) + 2, 0)
    return (vals * powers).sum(dtype=jnp.uint32) ^ tlen.astype(jnp.uint32)


def nearby_allowed(fav_start: jax.Array, fav_end: jax.Array,
                   fav_mask: jax.Array, neighborhood: int,
                   jmax: int) -> jax.Array:
    """(Jmax,) bool: positions within `neighborhood` of any favorable
    mutation's [start, end) -- the unique_nearby window filter.

    Matches unique_nearby_arrays: each center m contributes candidate
    starts in [m.start - n, m.end + n)."""
    lo = jnp.where(fav_mask, jnp.maximum(fav_start - neighborhood, 0), jmax)
    hi = jnp.where(fav_mask, jnp.minimum(fav_end + neighborhood, jmax), 0)
    diff = jnp.zeros(jmax + 1, jnp.int32)
    diff = diff.at[jnp.clip(lo, 0, jmax)].add(
        jnp.where(fav_mask, 1, 0), mode="drop")
    diff = diff.at[jnp.clip(hi, 0, jmax)].add(
        jnp.where(fav_mask, -1, 0), mode="drop")
    return jnp.cumsum(diff[:-1]) > 0
