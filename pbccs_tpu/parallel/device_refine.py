"""Device-resident refinement primitives: enumeration, selection, splice.

The host lockstep refinement loop (parallel/batch.py refine) fetches the
(Z, M) mutation scores every round to run selection and template splicing
in numpy; over this environment's tunneled device link each fetch costs
~0.1-0.25 s regardless of size, and the per-round fetch chain dominates
polish wall time (profiled: ~80%).  These primitives re-express the
host-side round logic as fixed-shape device ops so the whole refinement
loop can run inside one jitted program (see batch.BatchPolisher.refine's
device path), fetching once at the end.

Parity targets (each pinned by tests/test_device_refine.py):
  * slot_candidates == mutations.enumerate_unique_arrays (same candidate
    set in the same pos-major order; rounds > 0 apply the same
    center-window position filter as unique_nearby_arrays, though the
    host's center-major candidate ORDER is not reproduced -- order only
    matters for exact score ties);
  * greedy_well_separated == mutations.best_subset (greedy max-score with
    inclusive +-separation start exclusion; ties resolve to the earlier
    candidate, matching the host's first-max rule in round 0).  At
    separation == 0 (unused by any caller) the device deviates: it keeps
    at most one mutation per start (see the in-function comment);
  * splice_templates == mutations.apply_mutations +
    target_to_query_positions (the mtp map: mtp[j] = j - dels(<j) +
    ins(<=j)).

Candidate slot grid: position-major, 9 slots per template position in the
host enumeration order (subs by base, ins by base, del); invalid slots are
masked, never reordered, so slot index == candidate identity.
"""

from __future__ import annotations

import functools
import typing
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pbccs_tpu.models.arrow.mutations import (_LN10 as _MUT_LN10,
                                              _SLOT_BASES, _SLOT_ENDOFF,
                                              _SLOT_TYPES, DELETION,
                                              INSERTION, QV_SATURATED,
                                              SUBSTITUTION)
from pbccs_tpu.ops.fwdbwd import BandedMatrix

N_SLOTS = 9
EDGE_BUDGET = 64  # packed edge-mutation slab width per scoring chunk
# slot layout per position: the host enumeration's own tables (one source
# of truth for the slot-index == candidate-identity contract)
SLOT_BASES = _SLOT_BASES
SLOT_TYPES = _SLOT_TYPES
SLOT_ENDOFF = _SLOT_ENDOFF

_HASH_MULT = np.uint32(2654435761)  # Knuth multiplicative constant


def slot_candidates(tpl: jax.Array, tlen: jax.Array,
                    allowed_pos: jax.Array | None = None):
    """All unique single-base mutation candidates of one padded template.

    Returns (start, end, mtype, new_base, valid), each (Jmax * 9,), in the
    host enumeration order.  `allowed_pos` ((Jmax,) bool) restricts
    candidate start positions (the nearby-window filter of rounds > 0)."""
    Jmax = tpl.shape[0]
    t = tpl.astype(jnp.int32)
    prev = jnp.concatenate([jnp.array([-1], jnp.int32), t[:-1]])
    pos = jnp.arange(Jmax, dtype=jnp.int32)

    valid = jnp.zeros((Jmax, N_SLOTS), bool)
    valid = valid.at[:, :4].set(SLOT_BASES[None, :4] != t[:, None])
    valid = valid.at[:, 4:8].set(SLOT_BASES[None, 4:8] != prev[:, None])
    valid = valid.at[:, 8].set(t != prev)
    valid &= (pos < tlen)[:, None]
    if allowed_pos is not None:
        valid &= allowed_pos[:, None]

    start = jnp.repeat(pos, N_SLOTS)
    end = start + jnp.asarray(SLOT_ENDOFF)[None, :].repeat(Jmax, 0).reshape(-1)
    mtype = jnp.tile(jnp.asarray(SLOT_TYPES), Jmax)
    base = jnp.tile(jnp.asarray(SLOT_BASES), Jmax)
    return start, end, mtype, base, valid.reshape(-1)


def rc_candidates(start, end, base, tlen):
    """Reverse-complement frame of the slot grid (mutations
    reverse_complement_arrays): (start_r, base_r)."""
    comp = jnp.where(base < 0, -1, 3 - base)
    return tlen - end, comp


def _lex_window_max(sc, sl, separation: int):
    """Windowed lexicographic max over positions: for each position p,
    the (score desc, slot asc) best among positions [p-sep, p+sep].
    2*sep static shift-combines (sep is small: default 10)."""
    def shift(x, d, fill):
        if d > 0:
            return jnp.concatenate([x[d:], jnp.full(d, fill, x.dtype)])
        return jnp.concatenate([jnp.full(-d, fill, x.dtype), x[:d]])

    best_sc, best_sl = sc, sl
    for d in range(1, separation + 1):
        for s in (d, -d):
            c_sc = shift(sc, s, -jnp.inf)
            c_sl = shift(sl, s, jnp.iinfo(sl.dtype).max)
            win = (c_sc > best_sc) | ((c_sc == best_sc) & (c_sl < best_sl))
            best_sc = jnp.where(win, c_sc, best_sc)
            best_sl = jnp.where(win, c_sl, best_sl)
    return best_sc, best_sl


def _window_or(mask, separation: int):
    """positions within +-separation of any set position (static shifts)."""
    out = mask
    for d in range(1, separation + 1):
        out = out | jnp.concatenate([mask[d:], jnp.zeros(d, bool)])
        out = out | jnp.concatenate([jnp.zeros(d, bool), mask[:-d]])
    return out


def greedy_well_separated(scores: jax.Array, start: jax.Array,
                          favorable: jax.Array, separation: int,
                          jmax: int) -> jax.Array:
    """(M,) bool taken-mask: greedy max-score subset with starts more than
    `separation` apart (inclusive exclusion), ties to the earlier slot.

    Data-parallel local-max PEELING instead of an M-step sequential scan
    (the scan's per-candidate scatter was ~7% of all device time in the
    round-3 profile): each peel round simultaneously takes every live
    candidate that is the lexicographic (score desc, slot asc) maximum
    among live candidates within +-separation of its start, then blocks
    their neighborhoods.  Winners of one round are mutually >separation
    apart by construction (two winners within the window would each have
    to lexicographically beat the other), and the result equals the
    sequential greedy scan: a candidate survives to be taken iff it is
    not dominated by a taken candidate in its window, which the peeling
    resolves layer by layer.  Parity with the scan implementation is
    pinned by tests/test_device_refine.py::test_greedy_peel_matches_scan.
    """
    M = scores.shape[0]
    if separation == 0:
        # DOCUMENTED DEVIATION from the host at separation == 0 (a setting
        # no caller uses; RefineOptions defaults to 10): host best_subset
        # keeps every favorable and apply_mutations can apply several
        # same-start edits, but splice_templates' scatters silently merge
        # same-start edits, so the device keeps only the best-scoring
        # favorable per start (ties to the earlier slot) rather than
        # corrupt the template
        seg = jnp.full(jmax, -jnp.inf).at[jnp.clip(start, 0, jmax - 1)].max(
            jnp.where(favorable, scores, -jnp.inf))
        is_best = favorable & (scores == seg[jnp.clip(start, 0, jmax - 1)])
        slot = jnp.arange(M, dtype=jnp.int32)
        first = jnp.full(jmax, M, jnp.int32).at[
            jnp.clip(start, 0, jmax - 1)].min(jnp.where(is_best, slot, M))
        return is_best & (slot == first[jnp.clip(start, 0, jmax - 1)])

    slot = jnp.arange(M, dtype=jnp.int32)
    sstart = jnp.clip(start, 0, jmax - 1)
    sc32 = scores.astype(jnp.float32)

    def body(st):
        taken, blocked, alive = st
        live_sc = jnp.where(alive, sc32, -jnp.inf)
        # per-position best live candidate: (max score, then min slot
        # among the score-achievers) -- two scatters
        pos_sc = jnp.full(jmax, -jnp.inf).at[sstart].max(live_sc)
        hit = alive & (sc32 == pos_sc[sstart])
        pos_sl = jnp.full(jmax, M, jnp.int32).at[sstart].min(
            jnp.where(hit, slot, M))
        win_sc, win_sl = _lex_window_max(pos_sc, pos_sl, separation)
        winner = alive & (win_sl[sstart] == slot)
        taken = taken | winner
        win_pos = jnp.zeros(jmax, bool).at[sstart].max(winner)
        blocked = blocked | _window_or(win_pos, separation)
        alive = alive & ~winner & ~blocked[sstart]
        return taken, blocked, alive

    taken, _, _ = lax.while_loop(
        lambda st: st[2].any(), body,
        (jnp.zeros(M, bool), jnp.zeros(jmax, bool), favorable))
    return taken


def greedy_well_separated_posmajor(scores: jax.Array, favorable: jax.Array,
                                   separation: int, jmax: int) -> jax.Array:
    """greedy_well_separated for the canonical position-major slot grid
    (slot_candidates: start[m] == m // N_SLOTS — what every loop-body
    caller passes).  The general form's per-peel `x[start]` gathers and
    `.at[start]` scatters (vmapped → TPU scalar core; ~6% of device time
    at the 30-pass config) all collapse to (jmax, 9) reshapes with axis
    reductions/broadcasts.  Parity with the general form is pinned by
    tests/test_device_refine.py."""
    M = scores.shape[0]
    ns = M // jmax
    sc2 = scores.astype(jnp.float32).reshape(jmax, ns)
    slot2 = jnp.arange(M, dtype=jnp.int32).reshape(jmax, ns)

    def body(st):
        taken, blocked, alive = st
        live_sc = jnp.where(alive, sc2, -jnp.inf)
        pos_sc = live_sc.max(axis=1)
        hit = alive & (sc2 == pos_sc[:, None])
        pos_sl = jnp.where(hit, slot2, M).min(axis=1)
        win_sc, win_sl = _lex_window_max(pos_sc, pos_sl, separation)
        winner = alive & (win_sl[:, None] == slot2)
        taken = taken | winner
        win_pos = winner.any(axis=1)
        blocked = blocked | _window_or(win_pos, separation)
        alive = alive & ~winner & ~blocked[:, None]
        return taken, blocked, alive

    taken, _, _ = lax.while_loop(
        lambda st: st[2].any(), body,
        (jnp.zeros((jmax, ns), bool), jnp.zeros(jmax, bool),
         favorable.reshape(jmax, ns)))
    return taken.reshape(M)


def greedy_well_separated_scan(scores: jax.Array, start: jax.Array,
                               favorable: jax.Array, separation: int,
                               jmax: int) -> jax.Array:
    """The original M-step sequential-scan greedy (kept as the parity
    oracle for the peeling implementation; not used on the hot path)."""
    M = scores.shape[0]
    if separation == 0:
        return greedy_well_separated(scores, start, favorable, 0, jmax)
    neg = jnp.where(favorable, -scores, jnp.inf)
    order = jnp.argsort(neg, stable=True)  # score desc, slot-index ties

    pos = jnp.arange(jmax, dtype=jnp.int32)

    def step(carry, i):
        blocked, taken = carry
        cand = order[i]
        s = start[cand]
        ok = favorable[cand] & ~blocked[s]
        window = (pos >= s - separation) & (pos <= s + separation) & ok
        return (blocked | window, taken.at[cand].set(ok)), None

    (blocked, taken), _ = lax.scan(
        step, (jnp.zeros(jmax, bool), jnp.zeros(M, bool)),
        jnp.arange(M))
    return taken


def splice_templates(tpl: jax.Array, tlen: jax.Array,
                     start: jax.Array, mtype: jax.Array, base: jax.Array,
                     taken: jax.Array):
    """Apply a well-separated taken-set of single-base mutations.

    Returns (new_tpl (Jmax,), new_tlen, mtp (Jmax+1,)) where mtp is the
    old->new position map (target_to_query_positions).  Separation >= 1
    guarantees at most one taken mutation per start position, so the edit
    at each position is unique and the splice is two scatters.

    Capacity contract: new_tlen is returned UNCLAMPED; bases past Jmax are
    dropped by the scatters, so the caller MUST treat new_tlen > Jmax as
    an overflow (the loop sets its bail-to-host flag) rather than carry
    the inconsistent (tpl, tlen) pair into another round."""
    Jmax = tpl.shape[0]
    pos = jnp.arange(Jmax, dtype=jnp.int32)

    # per-position edit planes from the taken set
    safe_start = jnp.clip(start, 0, Jmax - 1)
    is_sub = taken & (mtype == SUBSTITUTION)
    is_ins = taken & (mtype == INSERTION)
    is_del = taken & (mtype == DELETION)
    sub_at = jnp.zeros(Jmax, bool).at[safe_start].max(is_sub)
    sub_base = jnp.zeros(Jmax, jnp.int32).at[safe_start].max(
        jnp.where(is_sub, base, 0))
    ins_at = jnp.zeros(Jmax + 1, bool).at[jnp.clip(start, 0, Jmax)].max(is_ins)
    ins_base = jnp.zeros(Jmax + 1, jnp.int32).at[jnp.clip(start, 0, Jmax)].max(
        jnp.where(is_ins, base, 0))
    del_at = jnp.zeros(Jmax, bool).at[safe_start].max(is_del)

    # mtp[j] = j - dels(start < j) + ins(start <= j)
    dels_before = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(del_at.astype(jnp.int32))])
    ins_upto = jnp.cumsum(ins_at.astype(jnp.int32))
    mtp = jnp.arange(Jmax + 1, dtype=jnp.int32) - dels_before + ins_upto

    new_tlen = mtp[tlen]

    edited = jnp.where(sub_at, sub_base, tpl.astype(jnp.int32))
    new_tpl = jnp.full(Jmax, 4, jnp.int32)
    keep = (~del_at) & (pos < tlen)
    dst = jnp.where(keep, mtp[:-1], Jmax)           # OOB drop for dels/pad
    new_tpl = new_tpl.at[dst].set(edited, mode="drop")
    ins_dst = jnp.where(ins_at & (jnp.arange(Jmax + 1) <= tlen),
                        mtp - 1, Jmax)
    new_tpl = new_tpl.at[ins_dst].set(ins_base, mode="drop")
    return new_tpl.astype(tpl.dtype), new_tlen, mtp


def template_hash(tpl: jax.Array, tlen: jax.Array) -> jax.Array:
    """Rolling uint32 hash of the live template prefix (cycle detection)."""
    Jmax = tpl.shape[0]
    j = jnp.arange(Jmax, dtype=jnp.uint32)
    powers = jnp.power(_HASH_MULT, j + 1)  # uint32 wraparound
    live = (j < tlen.astype(jnp.uint32))
    vals = jnp.where(live, tpl.astype(jnp.uint32) + 2, 0)
    return (vals * powers).sum(dtype=jnp.uint32) ^ tlen.astype(jnp.uint32)


class RefineLoopState(NamedTuple):
    """Carry of the device-resident refinement while_loop.

    Loop-constant read tensors (reads/rlens/strands/table) are closed over
    by the jitted loop, not carried."""

    tpl: jax.Array          # (Z, Jmax) int8 forward template
    tlens: jax.Array        # (Z,) int32
    tstarts: jax.Array      # (Z, R) int32 read windows (fwd frame)
    tends: jax.Array
    win_tpl: jax.Array      # per-read oriented windows + fills
    win_trans: jax.Array
    wlens: jax.Array
    alpha: BandedMatrix     # leaves (Z, R, ...)
    beta: BandedMatrix
    a_prefix: jax.Array
    b_suffix: jax.Array
    baselines: jax.Array    # (Z, R)
    trans_f: jax.Array      # (Z, Jmax, 4)
    tpl_r: jax.Array        # (Z, Jmax) int8 reverse-complement template
    trans_r: jax.Array
    active: jax.Array       # (Z, R) bool
    it: jax.Array           # () int32
    done: jax.Array         # (Z,) bool
    converged: jax.Array    # (Z,) bool
    iterations: jax.Array   # (Z,) int32
    n_tested: jax.Array     # (Z,) int32
    n_applied: jax.Array    # (Z,) int32
    allowed: jax.Array      # (Z, Jmax) bool candidate-position filter
    history: jax.Array      # (Z, H) uint32 template-hash ring
    hist_n: jax.Array       # (Z,) int32
    overflow: jax.Array     # () bool: bail-to-host flag
    # pre-baked dense-kernel layout (ops.dense_score_pallas.DenseLayout
    # with (Z, R)-leading leaves), rebuilt only when fills rebuild; None
    # on the chunked scoring path.  Rounds that apply no mutation (and
    # the eager QV sweep after the loop) relaunch the kernel on the
    # previous rebuild's baked buffers instead of re-deriving the
    # layout in-graph every round.
    dlayout: typing.Any = None


def _chunk_count(jmax: int, chunk: int) -> int:
    return (jmax * N_SLOTS + chunk - 1) // chunk


def slot_geometry(ts, te, strand, ms, me, is_ins):
    """Interior-vs-edge classification of mutation slots against read
    windows (ONE definition, shared by the chunked and dense scoring
    paths; mirrors the host _dispatch_chunk rules).  All args broadcast;
    returns (overlap, interior, wlen)."""
    overlap = jnp.where(is_ins, (ts <= me) & (ms <= te),
                        (ts < me) & (ms < te))
    p_w = jnp.where(strand == 0, ms - ts, te - me)
    e_w = jnp.where(strand == 0, me - ts, te - ms)
    wlen = te - ts
    interior = (p_w >= 3) & (e_w <= wlen - 2)
    return overlap, interior, wlen


def _state_layout(reads, rlens, win_tpl, win_trans, wlens, table,
                  alpha: BandedMatrix, beta: BandedMatrix, a_prefix,
                  b_suffix, width: int):
    """(Z, R)-leading DenseLayout for RefineLoopState.dlayout: flatten
    the batch to the kernel's (Z*R)-flat read frame, bake the layout
    (ops.dense_score_pallas.build_dense_layout), reshape leaves back.
    Plain function for enclosing traces (the loop's rebuild);
    state_layout below is the jitted prepare-time entry."""
    from pbccs_tpu.ops.dense_score_pallas import build_dense_layout

    Z, R = reads.shape[:2]
    flat = lambda a: a.reshape((Z * R,) + a.shape[2:])
    tables = flat(jnp.broadcast_to(table[:, None],
                                   (Z, R) + table.shape[1:]))
    alpha_f = BandedMatrix(flat(alpha.vals), flat(alpha.offsets),
                           flat(alpha.log_scales))
    beta_f = BandedMatrix(flat(beta.vals), flat(beta.offsets),
                          flat(beta.log_scales))
    lay = build_dense_layout(flat(reads), flat(rlens), flat(win_tpl),
                             flat(win_trans), flat(wlens), tables,
                             alpha_f, beta_f, flat(a_prefix),
                             flat(b_suffix), width)
    return jax.tree.map(lambda a: a.reshape((Z, R) + a.shape[1:]), lay)


state_layout = functools.partial(jax.jit, static_argnames=("width",))(
    _state_layout)


def _score_slot_grid_dense(st: "RefineLoopState", reads, rlens, strands,
                           table, real_rows, start, end, mtype, base,
                           valid, *, min_fast_edge: int):
    """Dense-path (Z, M) slot-grid totals: interior scores come from the
    Pallas dense kernel (ops/dense_score_pallas) -- one whole-grid pass
    with VMEM-resident intermediates instead of the chunk scan whose
    materialized (Z, R, chunk, W) intermediates made the packed path
    HBM-bound (docs/PROFILE_r03.md).  Edge slots live at STATIC
    window-frame rows ({0,1,2} and {J-2,J-1,J}), so they are scored by
    the small window-frame edge program (edge_window_scores_batch) and
    spliced into the kernel grid before the orientation mapping -- the
    whole grid then maps and reduces in one pass, with no packed edge
    slab, no edge budget, and no template-frame edge machinery."""
    from pbccs_tpu.ops.dense_score_pallas import (
        band_read_windows, dense_interior_scores_batch, dense_patch_grids,
        edge_window_scores_batch, splice_edge_rows, window_grid_to_template)

    Z, R = reads.shape[:2]
    # pre-baked kernel layout carried in the loop state: flatten its
    # (Z, R)-leading leaves to the call's (Z*R)-flat read batch
    lay = st.dlayout
    if lay is not None:
        lay = jax.tree.map(
            lambda a: a.reshape((Z * R,) + a.shape[2:]), lay)
    jmax = st.tpl.shape[1]
    M = jmax * N_SLOTS

    # geometry classification over the full grid
    overlap, interior, wlen = slot_geometry(
        st.tstarts[:, :, None], st.tends[:, :, None], strands[:, :, None],
        start[None, None, :], end[None, None, :],
        (mtype == INSERTION)[None, None, :])
    geo = valid[:, None, :] & overlap & real_rows[:, :, None]
    # tiny windows (wlen < min_fast_edge) cannot ride the window-frame
    # edge program (its two regimes would overlap); bail to the host loop
    fb = (geo & ~interior & (wlen < min_fast_edge)).any()

    flat = lambda a: a.reshape((Z * R,) + a.shape[2:])
    tables = flat(jnp.broadcast_to(table[:, None], (Z, R) + table.shape[1:]))
    W = st.alpha.vals.shape[-1]
    f_reads, f_rlens = flat(reads), flat(rlens)
    f_wt, f_wtr, f_wl = flat(st.win_tpl), flat(st.win_trans), flat(st.wlens)
    alpha_f = BandedMatrix(flat(st.alpha.vals), flat(st.alpha.offsets),
                           flat(st.alpha.log_scales))
    beta_f = BandedMatrix(flat(st.beta.vals), flat(st.beta.offsets),
                          flat(st.beta.log_scales))
    f_apre, f_bsuf = flat(st.a_prefix), flat(st.b_suffix)
    ptrans = None if lay is not None else jax.vmap(dense_patch_grids)(
        f_wt.astype(jnp.int32), f_wtr, tables, f_wl)
    # (read, position-block) live mask: rounds > 0 restrict candidates to
    # nearby windows, so most kernel grid cells have no valid slot and
    # can skip all compute.  A block is live iff any valid candidate
    # POSITION maps into its window rows (over-approximated by +-1 to
    # cover the ins/subdel row offset in the reverse frame).
    from pbccs_tpu.ops.dense_score_pallas import _PB
    NB = -(-jmax // _PB)
    pos_any = valid.reshape(Z, jmax, N_SLOTS).any(-1)
    pref = jnp.concatenate(
        [jnp.zeros((Z, 1), jnp.int32),
         jnp.cumsum(pos_any.astype(jnp.int32), axis=1)], axis=1)
    b = jnp.arange(NB, dtype=jnp.int32)[None, None, :]
    ts3, te3 = st.tstarts[:, :, None], st.tends[:, :, None]
    lo_f, hi_f = ts3 + b * _PB, ts3 + (b + 1) * _PB
    lo_r, hi_r = te3 - (b + 1) * _PB - 1, te3 - b * _PB + 1
    fwd3 = strands[:, :, None] == 0
    lo = jnp.clip(jnp.where(fwd3, lo_f, lo_r) - 1, 0, jmax)
    hi = jnp.clip(jnp.where(fwd3, hi_f, hi_r) + 1, 0, jmax)
    # pref[hi] - pref[lo]: below the size gate, ONE one-hot einsum on the
    # MXU (the take_along_axis pair lowers to the scalar core, ~4% of
    # device time at the headline config); the einsum's (Z, R*NB, jmax+1)
    # selector is O(jmax) larger than the gathers, so long-template
    # buckets keep the gather form.
    if Z * R * NB * (jmax + 1) <= (1 << 26):
        grid_pos = jnp.arange(jmax + 1, dtype=jnp.int32)
        sel = ((hi.reshape(Z, -1, 1) == grid_pos).astype(jnp.float32)
               - (lo.reshape(Z, -1, 1) == grid_pos).astype(jnp.float32))
        diff = jnp.einsum("zmn,zn->zm", sel, pref.astype(jnp.float32),
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST
                          ).reshape(Z, R, NB)
        live = diff > 0.5
    else:
        take = lambda idx: jnp.take_along_axis(
            pref, idx.reshape(Z, -1), axis=1).reshape(Z, R, NB)
        live = (take(hi) - take(lo)) > 0
    live = live & real_rows[:, :, None] & st.active[:, :, None]
    # one shared per-column read-window computation serves the interior
    # kernel and the edge program (the edge program's former per-read
    # dynamic slices were ~13% of device time on the round-5 profile);
    # with a pre-baked layout even that is already done
    rwin = None if lay is not None else \
        band_read_windows(f_reads, alpha_f.offsets, W)
    grid_w = dense_interior_scores_batch(
        f_reads, f_rlens, f_wt, f_wtr, f_wl, tables, alpha_f, beta_f,
        f_apre, f_bsuf, W, ptrans, live.reshape(Z * R, NB), rwin,
        layout=lay)

    # edge slots always compute (not gated behind a cond): the edge
    # program has no data dependence on the kernel output, so XLA
    # overlaps the two -- a measured win over skipping edges in the
    # rounds that don't need them
    e6 = edge_window_scores_batch(f_reads, f_rlens, f_wt, f_wtr, f_wl,
                                  alpha_f, beta_f, f_apre, f_bsuf,
                                  ptrans, W, rwin, layout=lay)
    grid_w = jax.vmap(splice_edge_rows)(grid_w, e6, f_wl.astype(jnp.int32))
    mapped = jax.vmap(
        lambda g, s, a, b: window_grid_to_template(g, s, a, b, jmax)
    )(grid_w, flat(strands), flat(st.tstarts), flat(st.tends))
    mapped = mapped.reshape(Z, R, M)
    score_mask = geo & st.active[:, :, None]
    out = jnp.sum(
        jnp.where(score_mask, mapped - st.baselines[:, :, None], 0.0),
        axis=1)
    return out, fb


def score_slot_grid(st: "RefineLoopState", reads, rlens, strands, table,
                    real_rows, start, end, mtype, base, valid, *,
                    chunk: int, min_fast_edge: int, dense: bool = False,
                    read_axis: str | None = None):
    """(Z, M) totals over all candidate slots; also returns the
    tiny-window fallback flag (LOCAL under shard_map -- the caller makes
    it global).  Shared by the refinement loop's per-round scoring and
    the one-dispatch QV sweep (run_qv_grid).

    `read_axis` names the mesh axis the read dimension is sharded over
    when running inside jax.shard_map: each device reduces its local
    reads and the final (Z, M) totals all-reduce over that axis (XLA
    lowers the psum onto ICI).  Only the dense path supports it.

    With dense=True the interior scores come from the Pallas dense-grid
    kernel (_score_slot_grid_dense, the TPU path).  Otherwise candidates
    are packed per ZMW (stable argsort puts each row's valid slots first)
    and scored in fixed chunks: the live work of sparse rounds -- nearby
    windows cover a small fraction of the slot grid after round 0 --
    compacts into the leading chunk(s) and the all-invalid tail chunks
    short-circuit.  Scores scatter back to slot-grid layout."""
    if dense:
        out, fb = _score_slot_grid_dense(st, reads, rlens, strands, table,
                                         real_rows, start, end, mtype,
                                         base, valid,
                                         min_fast_edge=min_fast_edge)
        if read_axis is not None:
            out = lax.psum(out, read_axis)
        return out, fb
    assert read_axis is None, "mesh scoring requires the dense path"
    from pbccs_tpu.parallel import batch as batchmod

    Z = reads.shape[0]
    jmax = st.tpl.shape[1]
    M = jmax * N_SLOTS
    C = _chunk_count(jmax, chunk)
    Mpad = C * chunk
    pad = Mpad - M

    pack = jnp.argsort(~valid, axis=1, stable=True)      # (Z, M)
    gz = lambda a: jnp.take_along_axis(a, pack, axis=1)
    gm = lambda a: jnp.take_along_axis(
        jnp.broadcast_to(a[None, :], (Z, M)), pack, axis=1)
    p_start, p_end = gm(start), gm(end)
    p_mtype, p_base = gm(mtype), gm(base)
    p_valid = gz(valid)

    def padz(a, fill):
        return jnp.pad(a, [(0, 0), (0, pad)], constant_values=fill)

    cshape = lambda a: a.reshape(Z, C, chunk).transpose(1, 0, 2)
    pos_f = cshape(padz(p_start, 0))
    end_f = cshape(padz(p_end, 1))
    mt = cshape(padz(p_mtype, SUBSTITUTION))
    mb = cshape(padz(p_base, 0))
    vz = cshape(padz(p_valid, False))

    tpl32 = st.tpl.astype(jnp.int32)
    tpl32_r = st.tpl_r.astype(jnp.int32)

    def one_chunk(_, xs):
        p1, e1, t1, b1, v1 = xs
        # rounds > 0 restrict candidates to the nearby windows, which
        # cluster in a few chunks: chunks with no valid candidate
        # short-circuit (their scores are -inf-masked anyway), cutting
        # most of the late-round interior compute the host loop avoids
        # by shrinking its mutation arrays
        return None, lax.cond(v1.any(),
                              lambda: _chunk_compute(p1, e1, t1, b1, v1),
                              lambda: (jnp.zeros((Z, chunk)),
                                       jnp.asarray(False)))

    def _chunk_compute(p1, e1, t1, b1, v1):
        # p1/e1/t1/b1/v1 are (Z, chunk): per-ZMW packed candidates
        mpos_f, mend_f, mtyp, mbase_f = p1, e1, t1, b1
        mpos_r = st.tlens[:, None] - e1
        mbase_r = jnp.where(b1 < 0, -1, 3 - b1)

        # geometry classification (the host _dispatch_chunk logic)
        overlap, interior, wlen = slot_geometry(
            st.tstarts[:, :, None], st.tends[:, :, None],
            strands[:, :, None], mpos_f[:, None, :], mend_f[:, None, :],
            (mtyp == INSERTION)[:, None, :])
        geo = v1[:, None, :] & overlap & real_rows[:, :, None]
        int_mask = geo & interior
        edge_mask = geo & ~interior
        fb = (edge_mask & (wlen < min_fast_edge)).any()

        int_tot, _, _ = batchmod._batch_interior_totals.__wrapped__(
            reads, rlens, strands, st.tstarts, st.tends,
            st.win_tpl, st.win_trans, st.wlens,
            st.alpha.vals, st.alpha.offsets, st.alpha.log_scales,
            st.beta.vals, st.beta.offsets, st.beta.log_scales,
            st.a_prefix, st.b_suffix, st.baselines,
            tpl32, st.trans_f, tpl32_r, st.trans_r, table, st.tlens,
            mpos_f, mend_f, mtyp, mbase_f, mpos_r, mbase_r,
            int_mask, st.active)

        # edge mutations are a handful per chunk (window boundaries):
        # pack them to a fixed slab on device (stable argsort puts
        # edge-active columns first) so the edge program runs at
        # EDGE_BUDGET width, not the full chunk; budget overflow bails
        # to the host loop
        eb = EDGE_BUDGET
        e_ok = edge_mask & (wlen >= min_fast_edge)
        em_any = e_ok.any(axis=1)                       # (Z, chunk)
        e_over = em_any.sum(axis=1).max() > eb
        order = jnp.argsort(~em_any, axis=1, stable=True)[:, :eb]
        packed = jnp.take_along_axis(em_any, order, axis=1)
        g = lambda a: jnp.take_along_axis(a, order, axis=1)
        ge_mask = jnp.take_along_axis(
            e_ok, order[:, None, :].repeat(e_ok.shape[1], 1), axis=2)
        edge_packed = batchmod._batch_edge_fast_totals.__wrapped__(
            reads, rlens, strands, st.tstarts, st.tends,
            st.win_tpl, st.win_trans, st.wlens,
            st.alpha.vals, st.alpha.offsets, st.alpha.log_scales,
            st.beta.vals, st.beta.offsets, st.beta.log_scales,
            st.a_prefix, st.b_suffix, st.baselines,
            tpl32, st.trans_f, tpl32_r, st.trans_r, table, st.tlens,
            g(mpos_f), g(mend_f), g(mtyp), g(mbase_f),
            g(mpos_r), g(mbase_r),
            ge_mask, st.active)
        zidx = jnp.arange(Z, dtype=jnp.int32)[:, None]
        edge_tot = jnp.zeros_like(int_tot).at[zidx, order].add(
            jnp.where(packed, edge_packed, 0.0))
        return (int_tot + edge_tot, fb | e_over)

    _, (totals, fbs) = lax.scan(one_chunk, None,
                                (pos_f, end_f, mt, mb, vz))
    packed_totals = totals.transpose(1, 0, 2).reshape(Z, Mpad)[:, :M]
    # scatter back to slot-grid layout
    zidx = jnp.arange(Z, dtype=jnp.int32)[:, None]
    out = jnp.zeros((Z, M)).at[zidx, pack].set(packed_totals)
    return out, fbs.any()


def qv_from_slot_grid(totals: jax.Array, valid: jax.Array) -> jax.Array:
    """(Z, Jmax) int32 per-position consensus QVs from slot-grid totals.

    Device analogue of mutations.qvs_from_neg_sums (reference ConsensusQVs,
    Consensus-inl.hpp:277-297): per position, t = logsumexp of the
    negative-scoring valid slots, QV = -10*(t - softplus(t))/ln 10 =
    -10*log10(ssum/(1+ssum)); positions with no negative slot saturate to
    QV_SATURATED.  Slot starts are position-major with start == position
    for every slot kind (slot_candidates), so the per-position reduction
    is a reshape."""
    Z, M = totals.shape
    sc = jnp.where(valid & (totals < 0.0), totals.astype(jnp.float32),
                   -jnp.inf).reshape(Z, M // N_SLOTS, N_SLOTS)
    m = jnp.max(sc, axis=-1)
    any_neg = jnp.isfinite(m)
    safe_m = jnp.where(any_neg, m, 0.0)
    t = safe_m + jnp.log(jnp.sum(jnp.exp(sc - safe_m[..., None]), axis=-1))
    qv = -10.0 * (t - jax.nn.softplus(t)) / _MUT_LN10
    return jnp.where(any_neg, jnp.round(qv),
                     float(QV_SATURATED)).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("chunk", "min_fast_edge",
                                             "dense", "axis"))
def run_qv_ints(state: "RefineLoopState", reads, rlens, strands, table,
                real_rows, skip_mask, *, chunk: int, min_fast_edge: int,
                dense: bool = False,
                axis: tuple[str, str] | None = None):
    """One-dispatch QV sweep reduced to per-position integer QVs on
    device: (Z, Jmax) int32 + the tiny-window fallback flag.

    Dispatched back-to-back with run_refine_loop (its output state is
    this function's input, still enqueued -- no host sync between them)
    so the refine fetch and the QV fetch merge into ONE packed transfer:
    the separate (Z, 9*Jmax) f32 score fetch moved ~1.5 MB over a
    ~7 MB/s tunneled link plus a dispatch round trip, for data whose only
    consumer was the host per-position reduction now done here."""
    start, end, mtype, base, _ = slot_candidates(state.tpl[0],
                                                 state.tlens[0])
    valid = jax.vmap(
        lambda t, L: slot_candidates(t, L)[4]
    )(state.tpl, state.tlens)
    valid &= ~skip_mask[:, None]
    totals, fb = score_slot_grid(
        state, reads, rlens, strands, table, real_rows,
        start, end, mtype, base, valid,
        chunk=chunk, min_fast_edge=min_fast_edge, dense=dense,
        read_axis=axis[1] if axis else None)
    if axis is not None:
        fb = lax.psum(fb.astype(jnp.int32), axis) > 0
    return qv_from_slot_grid(totals, valid), fb


@functools.partial(jax.jit, static_argnames=("chunk", "min_fast_edge",
                                             "dense"))
def run_qv_grid(state: "RefineLoopState", reads, rlens, strands, table,
                real_rows, skip_mask, *, chunk: int, min_fast_edge: int,
                dense: bool = False):
    """One-dispatch QV sweep: the full slot-grid scores of every non-skip
    ZMW against its current template, computed on device in a single
    program (the host-chunked path dispatched C programs with numpy mask
    building in between -- ~1 s of wall for ~80 ms of device time on the
    bench workload).  Returns (packed scores (Z, M) f32, fallback): each
    row's valid-slot scores packed to the front in slot order (stable
    argsort), which is the host enumeration order, so row z's first
    arrs[z].size entries line up with enumerate_unique_arrays(tpls[z]).
    Per-slot values are identical to the chunked path (packing only
    reorders the chunk axis; no cross-slot arithmetic), and the packed
    f32 fetch is ~4x smaller than fetching (scores, valid) -- the
    tunneled link moves ~7 MB/s, so fetch bytes ARE wall time."""
    start, end, mtype, base, _ = slot_candidates(state.tpl[0],
                                                 state.tlens[0])
    valid = jax.vmap(
        lambda t, L: slot_candidates(t, L)[4]
    )(state.tpl, state.tlens)
    valid &= ~skip_mask[:, None]
    totals, fb = score_slot_grid(
        state, reads, rlens, strands, table, real_rows,
        start, end, mtype, base, valid,
        chunk=chunk, min_fast_edge=min_fast_edge, dense=dense)
    pack = jnp.argsort(~valid, axis=1, stable=True)
    packed = jnp.take_along_axis(jnp.where(valid, totals, 0.0), pack, axis=1)
    return packed.astype(jnp.float32), fb


@functools.partial(jax.jit, static_argnames=(
    "width", "use_pallas", "max_iterations", "separation", "neighborhood",
    "chunk", "min_fast_edge", "dense", "axis", "guided_passes"))
def run_refine_loop(state: "RefineLoopState", reads, rlens, strands, table,
                    real_rows, *, width: int, use_pallas: bool,
                    max_iterations: int, separation: int,
                    neighborhood: int, chunk: int, min_fast_edge: int,
                    dense: bool = False,
                    axis: tuple[str, str] | None = None,
                    guided_passes: int = 0):
    """The jitted device refinement loop: up to max_iterations rounds of
    enumerate -> score -> select -> splice -> rebuild entirely on device
    (lax.while_loop with early exit), so the host fetches once.  A
    module-level jit keyed on shapes/statics: every BatchPolisher at the
    same bucket shape shares one executable.

    Semantics mirror BatchPolisher.refine's host loop (which mirrors the
    reference AbstractRefineConsensus, Consensus-inl.hpp:160-245), with two
    documented deviations: candidate ORDER in rounds > 0 is position-major
    rather than the host's center-major (ties across distinct mutations
    resolve differently -- same candidate set), and cycle detection uses a
    48-deep rolling-hash ring rather than an unbounded exact set.

    `axis` = (zmw_axis, read_axis) mesh axis names when the loop body runs
    inside jax.shard_map (see run_refine_loop_sharded): score totals
    all-reduce over the read axis, and the loop condition / overflow flag
    reduce over the WHOLE mesh so every device runs the same number of
    iterations (divergent conds would deadlock the in-body collectives).
    The straggler early exit is disabled under a mesh -- the continuation
    sub-batch is a host-side construct that would break the sharding."""
    from pbccs_tpu.models.arrow.params import (revcomp_padded,
                                               template_transition_params)
    from pbccs_tpu.models.arrow.scorer import (fill_alpha_beta_batch_zr,
                                               oriented_window)
    from pbccs_tpu.parallel import batch as batchmod

    Z, R = reads.shape[:2]
    Jmax = None  # bound at trace time from state.tpl
    # whether this trace carries a pre-baked dense layout (static: the
    # initial state either has one or not; the dense scoring path uses
    # it when present and rebuilds it whenever the fills rebuild)
    with_layout = state.dlayout is not None

    def rebuild(tpl, tlens, tstarts, tends, active):
        def one_zmw(t, L, tb, st1, ts1, te1):
            trans_f = template_transition_params(t, tb, L)
            t_r = revcomp_padded(t, L)
            trans_r = template_transition_params(t_r, tb, L)
            win = jax.vmap(
                lambda s, a, b: oriented_window(s, a, b, t, t_r, L, tb)
            )(st1, ts1, te1)
            return win + (trans_f, t_r, trans_r)

        (win_tpl, win_trans, wlens, trans_f, tpl_r, trans_r) = jax.vmap(
            one_zmw)(tpl, tlens, table, strands, tstarts, tends)
        alpha, beta, ll_a, ll_b, apre, bsuf = fill_alpha_beta_batch_zr(
            reads, rlens, win_tpl, win_trans, wlens, width, use_pallas,
            guided_passes=guided_passes)
        active = batchmod._update_active.__wrapped__(
            active, ll_a, ll_b, rlens, tstarts, tends)
        dlay = _state_layout(reads, rlens, win_tpl, win_trans, wlens,
                             table, alpha, beta, apre, bsuf,
                             width) if with_layout else None
        return (win_tpl, win_trans, wlens, alpha, beta, apre, bsuf,
                ll_b, trans_f, tpl_r, trans_r, active, dlay)

    def score_all(st: RefineLoopState, start, end, mtype, base, valid):
        return score_slot_grid(st, reads, rlens, strands, table, real_rows,
                               start, end, mtype, base, valid,
                               chunk=chunk, min_fast_edge=min_fast_edge,
                               dense=dense,
                               read_axis=axis[1] if axis else None)

    def body(st: RefineLoopState) -> RefineLoopState:
        jmax = st.tpl.shape[1]

        # 1. candidates (slot geometry is ZMW-independent; validity is not)
        start, end, mtype, base, _ = slot_candidates(
            st.tpl[0], st.tlens[0])
        valid = jax.vmap(
            lambda t, L, al: slot_candidates(t, L, al)[4]
        )(st.tpl, st.tlens, st.allowed)
        valid &= ~st.done[:, None]

        # 2. scores
        totals, fb_any = score_all(st, start, end, mtype, base, valid)
        scores = jnp.where(valid, totals, -jnp.inf)
        # favorability above the f32 score-noise floor (one source of
        # truth: refine.favorability_threshold; the scaled floor is a
        # deliberate deviation from the reference's fixed +0.04-nat
        # acceptance, MultiReadMutationScorer.cpp:56 -- docs/PARITY.md)
        # -- sub-noise deltas at long templates read "favorable" in BOTH
        # directions of an ins/del pair and ping-pong the loop to its
        # budget
        from pbccs_tpu.models.arrow.refine import favorability_threshold
        eps_z = favorability_threshold(jnp.sum(
            jnp.where(st.active, jnp.abs(st.baselines), 0.0), axis=1))
        favorable = valid & (scores > eps_z[:, None])
        fav_any = favorable.any(axis=1)

        iterations = st.iterations + (~st.done).astype(jnp.int32)
        n_tested = st.n_tested + jnp.where(st.done, 0,
                                           valid.sum(axis=1, dtype=jnp.int32))

        newly_converged = (~st.done) & (~fav_any)
        converged = st.converged | newly_converged
        done_now = st.done | newly_converged

        # 3. greedy selection + cycle trim (position-major fast form:
        # slot_candidates' start is m // N_SLOTS by construction)
        taken = jax.vmap(
            lambda s, f: greedy_well_separated_posmajor(s, f, separation,
                                                        jmax)
        )(scores.astype(jnp.float32), favorable & ~done_now[:, None])

        def splice_z(t, L, tk):
            return splice_templates(t, L, start, mtype, base, tk)

        nxt_tpl, nxt_tlen, _ = jax.vmap(splice_z)(st.tpl, st.tlens, taken)
        nxt_hash = jax.vmap(template_hash)(nxt_tpl, nxt_tlen)
        seen = ((st.history == nxt_hash[:, None])
                & (jnp.arange(st.history.shape[1])[None, :]
                   < st.hist_n[:, None])).any(axis=1)
        multi = taken.sum(axis=1) > 1
        trim = seen & multi
        top1 = jnp.argmax(jnp.where(taken, scores, -jnp.inf), axis=1)
        taken = jnp.where(
            trim[:, None],
            jax.nn.one_hot(top1, taken.shape[1], dtype=bool) & taken,
            taken)

        # 4. history push (current template, pre-apply) where a round ran
        cur_hash = jax.vmap(template_hash)(st.tpl, st.tlens)
        pushing = (~st.done) & fav_any
        slot = st.hist_n % st.history.shape[1]
        history = jnp.where(
            pushing[:, None],
            st.history.at[jnp.arange(Z), slot].set(cur_hash),
            st.history)
        hist_n = st.hist_n + pushing.astype(jnp.int32)

        # 5. apply
        apply_mask = pushing
        new_tpl, new_tlen, mtp = jax.vmap(splice_z)(st.tpl, st.tlens, taken)
        tpl = jnp.where(apply_mask[:, None], new_tpl, st.tpl)
        tlens = jnp.where(apply_mask, new_tlen, st.tlens)
        n_applied = st.n_applied + jnp.where(
            apply_mask, taken.sum(axis=1, dtype=jnp.int32), 0)

        def remap(m, ts_row, te_row, L):
            # host: mtp[clip(window, 0, old_L)]
            return m[jnp.clip(ts_row, 0, L)], m[jnp.clip(te_row, 0, L)]

        ts_new, te_new = jax.vmap(remap)(mtp, st.tstarts, st.tends, st.tlens)
        tstarts = jnp.where(apply_mask[:, None], ts_new, st.tstarts)
        tends = jnp.where(apply_mask[:, None], te_new, st.tends)

        ov_local = fb_any | \
            (jnp.where(apply_mask, new_tlen, 0) + 2 > jmax).any()
        if axis is not None:
            # global any: every device must agree on the bail-out (a
            # device continuing alone would hang on the body collectives)
            ov_local = lax.psum(ov_local.astype(jnp.int32), axis) > 0
        overflow = st.overflow | ov_local

        # 6. rebuild fills against the updated templates (skipped entirely
        # when no ZMW applied anything this round -- the final round of a
        # converging batch)
        same = (st.win_tpl, st.win_trans, st.wlens, st.alpha, st.beta,
                st.a_prefix, st.b_suffix, st.baselines, st.trans_f,
                st.tpl_r, st.trans_r, st.active, st.dlayout)
        (win_tpl, win_trans, wlens, alpha, beta, apre, bsuf, baselines,
         trans_f, tpl_r, trans_r, active, dlayout) = lax.cond(
            apply_mask.any(),
            lambda: rebuild(tpl, tlens, tstarts, tends, st.active),
            lambda: same)

        # 7. next round's nearby filter from this round's favorables
        def allowed_z(fv):
            return nearby_allowed(start, end, fv, neighborhood, jmax)

        allowed = jnp.where(fav_any[:, None],
                            jax.vmap(allowed_z)(favorable),
                            st.allowed)

        return RefineLoopState(
            tpl=tpl, tlens=tlens, tstarts=tstarts, tends=tends,
            win_tpl=win_tpl, win_trans=win_trans, wlens=wlens,
            alpha=alpha, beta=beta, a_prefix=apre, b_suffix=bsuf,
            baselines=baselines, trans_f=trans_f, tpl_r=tpl_r,
            trans_r=trans_r, active=active,
            it=st.it + 1, done=done_now, converged=converged,
            iterations=iterations, n_tested=n_tested, n_applied=n_applied,
            allowed=allowed, history=history, hist_n=hist_n,
            overflow=overflow, dlayout=dlayout)

    # Straggler early exit: each lockstep round costs full (Z, ...) compute
    # whatever the active count, so once only a handful of ZMWs remain
    # (e.g. one cycling toward the 40-round budget) the loop returns and
    # the caller finishes them in a compact small-Z sub-batch instead of
    # paying Z-wide rounds (batch.BatchPolisher.refine).  Z <= 32 has no
    # early exit (threshold 0); mesh runs have none (the continuation is a
    # host-side construct) and count live ZMWs across all zmw shards.
    straggler_exit = 0 if axis is not None else reads.shape[0] // 32

    def cond(st: RefineLoopState):
        live = (~st.done).sum()
        if axis is not None:
            live = lax.psum(live, axis[0])
        return ((st.it < max_iterations)
                & (live > straggler_exit)
                & ~st.overflow)

    return lax.while_loop(cond, body, state)


def _state_specs(zmw: str, read: str,
                 with_layout: bool = False) -> "RefineLoopState":
    """PartitionSpec pytree of RefineLoopState under a (zmw, read) mesh:
    per-ZMW planes shard on the zmw axis, per-(ZMW, read) planes on both,
    scalars replicate.  `with_layout` mirrors whether the state carries a
    pre-baked DenseLayout (all of whose leaves are (Z, R)-leading)."""
    from jax.sharding import PartitionSpec as P

    from pbccs_tpu.ops.dense_score_pallas import DenseLayout

    z, zr, rep = P(zmw), P(zmw, read), P()
    bm = BandedMatrix(zr, zr, zr)
    return RefineLoopState(
        tpl=z, tlens=z, tstarts=zr, tends=zr,
        win_tpl=zr, win_trans=zr, wlens=zr,
        alpha=bm, beta=bm, a_prefix=zr, b_suffix=zr,
        baselines=zr, trans_f=z, tpl_r=z, trans_r=z, active=zr,
        it=rep, done=z, converged=z, iterations=z, n_tested=z,
        n_applied=z, allowed=z, history=z, hist_n=z, overflow=rep,
        dlayout=DenseLayout(*([zr] * 8)) if with_layout else None)


@functools.lru_cache(maxsize=64)
def _sharded_loop_fn(mesh, zmw_axis: str, read_axis: str,
                     statics: tuple):
    """Memoized jitted shard_map wrapper for run_refine_loop: building a
    fresh jit(shard_map(partial(...))) per call would defeat the jit
    trace cache and re-trace the whole loop every polish."""
    from jax.sharding import PartitionSpec as P

    sd = dict(statics)
    # mesh states carry a pre-baked DenseLayout exactly when the dense
    # scoring path is on (batch._loop_state uses the same gate)
    specs = _state_specs(zmw_axis, read_axis,
                         with_layout=sd.get("dense", False))
    zr, z = P(zmw_axis, read_axis), P(zmw_axis)
    from pbccs_tpu.parallel.mesh import shard_map

    f = functools.partial(run_refine_loop.__wrapped__,
                          axis=(zmw_axis, read_axis), **sd)
    return jax.jit(shard_map(
        f, mesh=mesh,
        in_specs=(specs, zr, zr, zr, z, zr),
        out_specs=specs, check_vma=False))


@functools.lru_cache(maxsize=64)
def _sharded_qv_fn(mesh, zmw_axis: str, read_axis: str, statics: tuple):
    from jax.sharding import PartitionSpec as P

    sd = dict(statics)
    specs = _state_specs(zmw_axis, read_axis,
                         with_layout=sd.get("dense", False))
    zr, z = P(zmw_axis, read_axis), P(zmw_axis)
    from pbccs_tpu.parallel.mesh import shard_map

    f = functools.partial(run_qv_ints.__wrapped__,
                          axis=(zmw_axis, read_axis), **sd)
    return jax.jit(shard_map(
        f, mesh=mesh,
        in_specs=(specs, zr, zr, zr, z, zr, z),
        out_specs=(z, P()), check_vma=False))


def run_refine_loop_sharded(mesh, zmw_axis: str, read_axis: str,
                            state: "RefineLoopState", reads, rlens,
                            strands, table, real_rows, **statics):
    """run_refine_loop under jax.shard_map over a (zmw, read) mesh: each
    device owns a (Z/nz, R/nr) block and the WHOLE while_loop runs
    device-resident per shard, with the score all-reduce over the read
    axis and globally-agreed loop condition (the DP-over-ZMW-shards
    design of SURVEY.md section 2.3, with the read axis riding ICI).
    check_vma=False: pallas_call outputs carry no varying-mesh-axes
    metadata (same caveat as scorer.fill_alpha_beta_batch_zr)."""
    fn = _sharded_loop_fn(mesh, zmw_axis, read_axis,
                          tuple(sorted(statics.items())))
    return fn(state, reads, rlens, strands, table, real_rows)


def run_qv_ints_sharded(mesh, zmw_axis: str, read_axis: str,
                        state: "RefineLoopState", reads, rlens, strands,
                        table, real_rows, skip_mask, **statics):
    """run_qv_ints under the same shard_map contract as
    run_refine_loop_sharded; returns ((Z, Jmax) int32 QVs sharded on the
    zmw axis, global fallback flag)."""
    fn = _sharded_qv_fn(mesh, zmw_axis, read_axis,
                        tuple(sorted(statics.items())))
    return fn(state, reads, rlens, strands, table, real_rows, skip_mask)


def nearby_allowed(fav_start: jax.Array, fav_end: jax.Array,
                   fav_mask: jax.Array, neighborhood: int,
                   jmax: int) -> jax.Array:
    """(Jmax,) bool: positions within `neighborhood` of any favorable
    mutation's [start, end) -- the unique_nearby window filter.

    Matches unique_nearby_arrays: each center m contributes candidate
    starts in [m.start - n, m.end + n)."""
    lo = jnp.where(fav_mask, jnp.maximum(fav_start - neighborhood, 0), jmax)
    hi = jnp.where(fav_mask, jnp.minimum(fav_end + neighborhood, jmax), 0)
    diff = jnp.zeros(jmax + 1, jnp.int32)
    diff = diff.at[jnp.clip(lo, 0, jmax)].add(
        jnp.where(fav_mask, 1, 0), mode="drop")
    diff = diff.at[jnp.clip(hi, 0, jmax)].add(
        jnp.where(fav_mask, -1, 0), mode="drop")
    return jnp.cumsum(diff[:-1]) > 0
