"""Device-mesh parallelism: batched ZMW polishing sharded over TPU cores.

The algorithm has no cross-ZMW coupling (reference parallelism is a
thread-per-ZMW WorkQueue, include/pacbio/ccs/WorkQueue.h:53-217), so the
distribution story is:

  * `zmw` mesh axis  -- data parallelism over the ZMW batch dimension
  * `read` mesh axis -- intra-ZMW parallelism over subreads; mutation-score
    totals reduce over this axis, so XLA inserts an all-reduce across it
    (the analogue of tensor parallelism's psum)

Both axes ride ICI inside a pod slice; scale-out across hosts shards BAM
chunks over DCN (pure data parallelism, no collectives required).
"""

from pbccs_tpu.parallel.mesh import make_zmw_mesh, shard_batch
from pbccs_tpu.parallel.batch import BatchPolisher

__all__ = ["make_zmw_mesh", "shard_batch", "BatchPolisher"]
