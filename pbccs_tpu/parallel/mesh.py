"""Mesh construction and sharding specs for the ZMW batch pipeline.

TPU-native replacement for the reference's thread-pool scheduling
(reference include/pacbio/ccs/WorkQueue.h:53-217): instead of handing one
ZMW to one thread, batches of bucketed ZMWs are laid out on a 2-D device
mesh ('zmw' x 'read') and every polish round is one jitted program; XLA
partitions it and inserts the read-axis all-reduce for score totals.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ZMW_AXIS = "zmw"
READ_AXIS = "read"


def shard_map(f, **kwargs):
    """Version-compat shim: newer JAX exports jax.shard_map at top level
    (with a `check_vma` kwarg), this pin (0.4.x) keeps it in
    jax.experimental.shard_map with the same kwarg named `check_rep`.
    Single sharding entry point for the fills (models/arrow/scorer.py)
    and the sharded device-resident loop (parallel/device_refine.py)."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    if "check_vma" in kwargs:
        import inspect

        if "check_vma" not in inspect.signature(sm).parameters:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return sm(f, **kwargs)


def make_zmw_mesh(n_zmw: int | None = None, n_read: int = 1,
                  devices: Sequence[jax.Device] | None = None) -> Mesh:
    """A ('zmw', 'read') mesh over the available devices.

    By default all devices go to the 'zmw' (data-parallel) axis; pass
    n_read > 1 to dedicate a read-parallel subaxis (useful for high-pass
    ZMWs where R is large and Z is small).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n_zmw is None:
        if n % n_read != 0:
            raise ValueError(f"{n} devices not divisible by n_read={n_read}")
        n_zmw = n // n_read
    if n_zmw * n_read > n:
        raise ValueError(f"mesh {n_zmw}x{n_read} needs more than {n} devices")
    grid = np.asarray(devices[: n_zmw * n_read]).reshape(n_zmw, n_read)
    return Mesh(grid, (ZMW_AXIS, READ_AXIS))


def zmw_spec(ndim: int, read_axis: int | None = None) -> P:
    """PartitionSpec for an array with a leading ZMW axis and (optionally) a
    read axis at position `read_axis`; other axes replicated."""
    parts: list = [ZMW_AXIS] + [None] * (ndim - 1)
    if read_axis is not None:
        parts[read_axis] = READ_AXIS
    return P(*parts)


def shard_batch(mesh: Mesh, tree, read_axis_of=lambda path: None):
    """Device_put a pytree of batch arrays with ZMW-sharded leading axes."""
    def place(x):
        x = np.asarray(x)
        spec = zmw_spec(x.ndim)
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(place, tree)


def pad_to(n: int, quantum: int) -> int:
    """Round n up to a multiple of `quantum` (>= quantum)."""
    return max(quantum, int(math.ceil(n / quantum)) * quantum)
