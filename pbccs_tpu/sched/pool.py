"""Device-fleet scheduler core: one executor thread per jax.Device.

The round-5 verdict's biggest unclaimed multiplier: an 8-device mesh sits
idle outside a dryrun while both drivers are single-device-owner (the
serve engine explicitly so, the batch CLI implicitly through its one
WorkQueue-fed BatchPolisher).  The sharded mesh path (parallel/mesh.py)
splits ONE batch across devices -- the right shape when Z is huge; this
module is the complementary shape for the common case: many independent
bucketed batches, each small enough for one device, dispatched across
the fleet so every device is fed (Pathways-style gang dispatch at batch
granularity; Orca-style continuous batching stays in serve/batcher.py
and simply feeds this pool instead of a single executor).

Design points:

  * **One worker thread per device.**  Each task runs under
    ``jax.default_device(worker.device)`` on its worker's thread, so all
    arrays a task materializes -- a BatchPolisher's cached fills, the
    compiled-program menu -- live on that device.  The GIL is released
    for most of a polish (device execution + transfers), so W workers
    genuinely overlap W devices.
  * **Sticky bucket routing** (the default policy): compiled executables
    are cached per (program, shapes, device), so a bucket shape that
    polished on device k replays for free there and pays a (disk-cached)
    compile anywhere else.  A task's bucket key prefers a device that
    already ran that key ("home"); an idle home always wins, a busy home
    loses to the least-loaded healthy device (work-conserving: stickiness
    never leaves a device idle while work queues), which then becomes an
    additional home for the bucket.  Policies: ``sticky`` | ``least`` |
    ``roundrobin``.
  * **Device health.**  A task failure counts a strike against its
    device only when it is device-shaped -- a WatchdogTimeout (hung
    dispatch), an XLA runtime error (resilience.retry already absorbs
    transient ones inside the dispatch; RetriesExhausted counts), or an
    injected chaos fault -- AND it is the task's FIRST failure (a
    poisoned task is task-shaped and must not bench every device it
    visits; plain Python exceptions never strike).  A device-shaped
    failure requeues to a healthy device the task has not yet failed on
    (``task.excluded`` bounds the tour to the fleet size); a task-shaped
    failure gets ONE healthy-device retry, then surfaces -- touring a
    deterministic bug would cost fleet-size polish durations just to
    return the same error.  ``bench_after`` device-shaped strikes in a
    row bench the
    device: its queued tasks requeue to healthy devices and it takes no
    further work.  The LAST healthy device is never benched -- a
    degraded run beats no run.
  * **Fault site** ``sched.dispatch`` (keys: the worker name ``cpu:3``/
    ``tpu:0`` and the task key), sitting OUTSIDE the task callable: a
    chaos spec targets a *device*, exercising exactly the bench/requeue
    machinery, while poison-*ZMW* specs keep firing inside
    pipeline._guarded_dispatch as before.

Metrics (obs registry): ``ccs_sched_tasks_total{device}``,
``ccs_sched_task_failures_total{device}``, ``ccs_sched_requeues_total``,
``ccs_sched_device_benched_total{device}``,
``ccs_sched_queue_depth{device}``,
``ccs_sched_sticky_routes_total{outcome=home|spill|new}``.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import traceback
from typing import Any, Callable, Hashable, Sequence

from pbccs_tpu.obs.metrics import default_registry
from pbccs_tpu.runtime.logging import Logger
from pbccs_tpu.sched.health import StickyMap

_reg = default_registry()
_m_requeues = _reg.counter(
    "ccs_sched_requeues_total",
    "Tasks re-routed to another device after a device-shaped failure")
_m_sticky = {outcome: _reg.counter(
    "ccs_sched_sticky_routes_total",
    "Sticky routing decisions by outcome", outcome=outcome)
    for outcome in ("home", "spill", "new")}


def select_devices(n: int) -> list:
    """First-n visible-device selection shared by every fleet entry point
    (batch CLI ``--devices``, ``ServeConfig.devices``, ``ccs warmup``):
    ``n == 0`` means every visible device, ``n > 0`` the first n.  A
    negative n is a usage error, never a from-the-end slice."""
    import jax

    if n < 0:
        raise ValueError(f"devices must be >= 0, got {n}")
    devs = list(jax.devices())
    if n > len(devs):
        # a silent clamp would run a "--devices 8" fleet on one device
        # at single-device throughput with nothing flagging the
        # driver/visibility misconfiguration
        Logger.default().warn(
            f"requested {n} devices but only {len(devs)} visible; "
            f"running on {len(devs)}")
    return devs[:n] if n else devs


class PoolClosed(RuntimeError):
    """submit() after close(), or a task failed by a non-waiting close."""


class NoHealthyDevice(RuntimeError):
    """A task ran out of healthy devices it has not already failed on."""


@dataclasses.dataclass(frozen=True)
class DevicePoolConfig:
    """Scheduler knobs (see module docstring for the policy they drive)."""

    policy: str = "sticky"        # sticky | least | roundrobin
    # consecutive device-shaped failures before a device is benched
    bench_after: int = 2
    # a busy home keeps a sticky task only while its depth (queued +
    # running) is <= spill_depth; 0 = work-conserving (idle homes only)
    spill_depth: int = 0

    def __post_init__(self):
        if self.policy not in ("sticky", "least", "roundrobin"):
            raise ValueError(f"unknown sched policy {self.policy!r}")
        if self.bench_after < 1:
            raise ValueError("bench_after must be >= 1")


class SchedFuture:
    """Completion handle for one submitted task (threading-based)."""

    def __init__(self, callback: Callable[["SchedFuture"], None] | None = None):
        self._done = threading.Event()
        self._result: Any = None
        self._exc: BaseException | None = None
        self._callback = callback

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: float | None = None) -> Any:
        if not self._done.wait(timeout):
            raise TimeoutError("task not complete")
        if self._exc is not None:
            raise self._exc
        return self._result

    def exception(self, timeout: float | None = None) -> BaseException | None:
        if not self._done.wait(timeout):
            raise TimeoutError("task not complete")
        return self._exc

    def _finish(self, result: Any = None,
                exc: BaseException | None = None) -> None:
        if self._done.is_set():
            return   # complete exactly once (defensive: a racing close)
        self._result, self._exc = result, exc
        self._done.set()
        if self._callback is not None:
            try:
                self._callback(self)
            except Exception as e:  # noqa: BLE001 -- a completion callback
                # must never take the worker thread down with it
                Logger.default().debug(f"sched callback failed: {e!r}")


@dataclasses.dataclass
class _Task:
    key: Hashable
    fn: Callable[[Any], Any]          # fn(jax.Device) -> result
    zmws: int
    future: SchedFuture
    excluded: set = dataclasses.field(default_factory=set)  # worker indices
    # pin=True submissions (warmup, per-device bench legs) must run on
    # THEIR device or fail loudly -- a silent requeue elsewhere would let
    # a warmup "succeed" while leaving the pinned device cold
    pinned: bool = False
    # the resources.shape_bucket this task polishes in: a capacity-shaped
    # (OOM) failure records a governor ceiling under it and requeues the
    # task to the SAME device, where the pipeline's admission pre-split
    # dispatches it in ceiling-sized parts.  None = no capacity handling
    # (the failure classifies task-shaped instead).
    capacity_bucket: Hashable | None = None
    capacity_requeues: int = 0


class _Worker:
    """Bookkeeping for one device executor (state guarded by pool lock)."""

    def __init__(self, index: int, device):
        self.index = index
        self.device = device
        self.name = f"{device.platform}:{device.id}"
        self.pending: collections.deque[_Task] = collections.deque()
        self.busy = False
        self.benched = False
        self.strikes = 0
        self.tasks_done = 0
        self.failures = 0
        self.thread: threading.Thread | None = None
        self.m_tasks = _reg.counter("ccs_sched_tasks_total",
                                    "Tasks completed per device",
                                    device=self.name)
        self.m_failures = _reg.counter("ccs_sched_task_failures_total",
                                       "Task attempts that raised, per device",
                                       device=self.name)
        self.m_depth = _reg.gauge("ccs_sched_queue_depth",
                                  "Queued + running tasks per device",
                                  device=self.name)

    def depth(self) -> int:
        return len(self.pending) + (1 if self.busy else 0)


class DevicePool:
    """A fleet of per-device executor threads with sticky bucket routing
    and health-based benching (see module docstring)."""

    def __init__(self, devices: Sequence | None = None,
                 config: DevicePoolConfig | None = None, *,
                 logger: Logger | None = None):
        import jax

        self.config = config or DevicePoolConfig()
        self._log = logger or Logger.default()
        devices = list(devices if devices is not None else jax.devices())
        if not devices:
            raise ValueError("DevicePool needs at least one device")
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._workers = [_Worker(i, d) for i, d in enumerate(devices)]
        # bucket key -> worker indices that have run it (sticky "homes";
        # the map itself is shared with the serve router -- sched/health)
        self._sticky = StickyMap()
        self._rr = -1
        self._closed = False
        for w in self._workers:
            w.thread = threading.Thread(
                target=self._worker_loop, args=(w,), daemon=True,
                name=f"ccs-sched-{w.name}")
            w.thread.start()
        self._log.info(
            f"device pool up: {len(self._workers)} device(s) "
            f"[{', '.join(w.name for w in self._workers)}] "
            f"policy={self.config.policy}")

    @property
    def n_devices(self) -> int:
        return len(self._workers)

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for w in self._workers if not w.benched)

    # ------------------------------------------------------------- submission

    def submit(self, key: Hashable, fn: Callable[[Any], Any], *,
               zmws: int = 1,
               callback: Callable[[SchedFuture], None] | None = None,
               worker_index: int | None = None,
               pin: bool = False,
               capacity_bucket: Hashable | None = None) -> SchedFuture:
        """Queue fn(device) on a device chosen by the routing policy.

        `key` is the sticky-routing bucket (callers pass the compiled
        shape key so a bucket's program menu stays warm on its home
        device).  `worker_index` places the task on one device; with
        `pin=True` it must also COMPLETE there -- a pinned task that
        fails surfaces its exception instead of requeueing (a per-device
        warmup that silently succeeded elsewhere would leave the pinned
        device cold while reporting success).  Without `pin`, placement
        is initial-only and failures requeue normally.  The future
        completes with fn's result, or -- after device-level requeues
        are exhausted -- its last exception.

        `capacity_bucket` (a resources.shape_bucket) opts the task into
        OOM-adaptive handling: a capacity-shaped failure records a
        MemoryGovernor ceiling for (device, bucket) and requeues to the
        SAME device -- no strike, no bench, no fleet tour -- where the
        pipeline's admission pre-split re-dispatches it in ceiling-sized
        parts (see resilience.resources)."""
        if pin and worker_index is None:
            raise ValueError("pin=True requires worker_index")
        if worker_index is not None and not (
                0 <= worker_index < len(self._workers)):
            # no negative-index wrap: a pinned task landing on the LAST
            # device via an off-by-one would "succeed" while the intended
            # device stays cold
            raise ValueError(
                f"worker_index {worker_index} out of range "
                f"[0, {len(self._workers)})")
        task = _Task(key, fn, zmws, SchedFuture(callback), pinned=pin,
                     capacity_bucket=capacity_bucket)
        with self._cv:
            if self._closed:
                raise PoolClosed("device pool is closed")
            if worker_index is not None:
                w = self._workers[worker_index]
                if w.benched:
                    raise NoHealthyDevice(f"device {w.name} is benched")
            else:
                w = self._route_locked(task)
            self._enqueue_locked(w, task)
            self._cv.notify_all()
        return task.future

    def _route_locked(self, task: _Task) -> _Worker:
        healthy = [w for w in self._workers
                   if not w.benched and w.index not in task.excluded]
        if not healthy:
            raise NoHealthyDevice(
                f"no healthy device left for bucket {task.key!r}")
        policy = self.config.policy
        if policy == "roundrobin":
            self._rr += 1
            return healthy[self._rr % len(healthy)]
        # least-loaded tie-break: fewer resident buckets first (spread the
        # compiled-program menu across the fleet), then device order
        def load(w: _Worker):
            return (w.depth(), self._sticky.resident_count(w.index),
                    w.index)

        if policy == "sticky":
            target, outcome = self._sticky.route(
                task.key, healthy, member_id=lambda w: w.index, load=load,
                depth=lambda w: w.depth(),
                spill_depth=self.config.spill_depth)
            _m_sticky[outcome].inc()
            return target
        return min(healthy, key=load)

    def _enqueue_locked(self, w: _Worker, task: _Task) -> None:
        self._sticky.note(task.key, w.index)
        w.pending.append(task)
        w.m_depth.set(w.depth())

    # ------------------------------------------------------------ worker loop

    def _worker_loop(self, w: _Worker) -> None:
        while True:
            with self._cv:
                while not w.pending and not self._closed and not w.benched:
                    self._cv.wait()
                if w.benched:
                    return  # _bench_locked already requeued w.pending
                if not w.pending:  # closed and drained
                    return
                task = w.pending.popleft()
                w.busy = True
                w.m_depth.set(w.depth())
            self._run_task(w, task)
            with self._cv:
                w.busy = False
                w.m_depth.set(w.depth())
                self._cv.notify_all()

    def _run_task(self, w: _Worker, task: _Task) -> None:
        import jax

        from pbccs_tpu.obs import roofline
        from pbccs_tpu.resilience import faults, resources

        # per-dispatch roofline scope: wall + device-wait for THIS task,
        # keyed by its shape bucket when it declared one (serve flushes
        # do; ad-hoc closures fall back to the task key)
        rl_label = (roofline.label_from_capacity_bucket(task.capacity_bucket)
                    or str(task.key))
        try:
            # the device-level chaos site: keyed by WORKER name so a spec
            # can sicken one device (ZMW-poison specs live inside the
            # dispatch fn, at pipeline's polish.dispatch site); oom-kind
            # specs here model the device rejecting the batch shape.
            # device_scope tags the thread so the pipeline's governor
            # lookups/records key ceilings per THIS device.
            with resources.device_scope(w.name):
                faults.maybe_fail("sched.dispatch",
                                  keys=[w.name, str(task.key)])
                with jax.default_device(w.device), \
                        roofline.dispatch_scope(rl_label, zmws=task.zmws):
                    result = task.fn(w.device)
        except BaseException as e:  # noqa: BLE001 -- classified below
            self._on_task_error(w, task, e)
            return
        with self._lock:
            w.strikes = 0
            w.tasks_done += 1
        w.m_tasks.inc()
        task.future._finish(result=result)

    def _on_task_error(self, w: _Worker, task: _Task,
                       exc: BaseException) -> None:
        from pbccs_tpu.resilience import faults, resources, retry, watchdog

        w.m_failures.inc()
        # CAPACITY-shaped failures (device OOM / RESOURCE_EXHAUSTED) are
        # classified FIRST: the batch SHAPE overflows the device, which
        # is neither sick hardware (striking/benching a healthy device
        # would shrink the fleet for a workload problem) nor a poison
        # input (quarantine would tour healthy ZMWs).  Record the shape
        # ceiling and requeue to the SAME device: the pipeline's
        # admission pre-split (polish_prepared_batch) dispatches the
        # requeued batch in ceiling-sized parts there.
        if (task.capacity_bucket is not None and not task.pinned
                and resources.is_capacity_error(exc)
                # halvings are bounded: each requeue lowers the ceiling,
                # so a closure that somehow ignores the governor still
                # terminates in O(log Z) requeues and surfaces
                and task.capacity_requeues <= max(1, task.zmws).bit_length()):
            resources.default_governor().record_oom(
                task.capacity_bucket, max(1, task.zmws), device=w.name)
            resources.note_oom_split()
            self._log.warn(
                f"sched: capacity failure on {w.name} (bucket "
                f"{task.key!r}, {task.zmws} ZMW(s)): "
                f"{type(exc).__name__}: {exc}; requeueing for a "
                "governor-split re-dispatch on the same device")
            with self._cv:
                task.capacity_requeues += 1
                if not self._closed and not w.benched:
                    _m_requeues.inc()
                    self._enqueue_locked(w, task)
                    self._cv.notify_all()
                    return
            # pool closed (or the device benched) under us: surface
            task.future._finish(exc=exc)
            return
        # device-shaped = the failure modes that indicate SICK HARDWARE,
        # not a bad input: a hang (WatchdogTimeout), an XLA runtime error
        # (transient ones were already retried inside the dispatch by
        # DEVICE_RETRY, so one surfacing here is persistent -- including
        # RetriesExhausted wrapping a transient that never cleared), or
        # an injected chaos fault.  Plain Python exceptions (a poison
        # input escaping quarantine, a code bug) requeue WITHOUT striking
        # the device: benching cannot fix them, and with sticky routing a
        # stream of poison requests at one home would otherwise bench
        # healthy devices one by one.
        device_shaped = (
            isinstance(exc, (watchdog.WatchdogTimeout,
                             retry.RetriesExhausted,
                             faults.InjectedFault))
            or type(exc).__name__ == "XlaRuntimeError")
        tb = "".join(traceback.format_exception(type(exc), exc,
                                                exc.__traceback__))
        self._log.warn(
            f"sched: task (bucket {task.key!r}, {task.zmws} ZMW(s)) failed "
            f"on {w.name} with {type(exc).__name__}: {exc} "
            f"[device_shaped={device_shaped}]")
        self._log.debug(f"sched: {w.name} failure traceback:\n{tb}")
        stranded: list[_Task] = []
        with self._cv:
            w.failures += 1
            # only a task's FIRST failure strikes its device: a poisoned
            # task touring the fleet (same batch failing everywhere) is
            # task-shaped, not device-shaped, and must not bench every
            # device it visits -- a sick device still accumulates strikes
            # because each NEW task fails there first
            first_failure = not task.excluded
            task.excluded.add(w.index)
            if device_shaped and first_failure and not self._closed:
                w.strikes += 1
                healthy = sum(1 for x in self._workers if not x.benched)
                if (w.strikes >= self.config.bench_after and not w.benched
                        and healthy > 1):
                    stranded = self._bench_locked(w, exc)
                elif w.strikes >= self.config.bench_after:
                    self._log.warn(
                        f"sched: {w.name} reached {w.strikes} strike(s) but "
                        "is the last healthy device; keeping it in service")
            # requeue to a healthy device this task has not failed on --
            # NEVER after close(): a drained worker may already have
            # exited its loop, so a post-close requeue would park the
            # task on a dead deque and strand its future (close()'s
            # leftover sweep only covers requeues that happen before the
            # worker joins complete).  Task-shaped failures get ONE
            # healthy-device retry, not a tour: a deterministic bug
            # re-polishing on every device would cost fleet-size polish
            # durations just to surface the same error.  Device-shaped
            # failures keep touring -- each hop is evidence against a
            # device, and benching needs it.
            # Pinned tasks never requeue: the pin IS the point.
            if self._closed or task.pinned or (
                    not device_shaped and not first_failure):
                target = None
            else:
                try:
                    target = self._route_locked(task)
                except NoHealthyDevice:
                    target = None
            if target is not None:
                _m_requeues.inc()
                self._enqueue_locked(target, task)
                self._cv.notify_all()
                self._log.warn(
                    f"sched: requeued bucket {task.key!r} "
                    f"({task.zmws} ZMW(s)) {w.name} -> {target.name}")
        # futures complete OUTSIDE the pool lock: completion callbacks run
        # arbitrary caller code (the serve engine's replies can block on a
        # slow client socket) that must never stall the scheduler
        for t in stranded:
            t.future._finish(exc=NoHealthyDevice(
                f"bucket {t.key!r}: no eligible healthy device left "
                "(failed everywhere, or pinned to a benched device)"))
        if target is None:
            # out of devices (or the pool closed): the failure is the
            # caller's (the pipeline's quarantine/tally machinery
            # accounts the ZMWs; nothing is lost silently)
            task.future._finish(exc=exc)

    def _bench_locked(self, w: _Worker,
                      exc: BaseException) -> list[_Task]:
        """Take a sick device out of service; requeue its queued tasks.
        Caller holds the lock.  Returns tasks with no healthy device left
        -- the CALLER fails their futures after releasing the lock
        (completion callbacks must never run under the pool lock)."""
        w.benched = True
        _reg.counter("ccs_sched_device_benched_total",
                     "Devices benched by repeated device-shaped failures",
                     device=w.name).inc()
        queued = list(w.pending)
        w.pending.clear()
        w.m_depth.set(0)
        self._sticky.forget_member(w.index)
        self._log.error(
            f"sched: benching device {w.name} after {w.strikes} "
            f"device-shaped failure(s) (last: {type(exc).__name__}: {exc}); "
            f"requeuing {len(queued)} queued task(s)")
        stranded: list[_Task] = []
        for task in queued:
            if task.pinned:   # pinned to this now-benched device
                stranded.append(task)
                continue
            try:
                target = self._route_locked(task)
            except NoHealthyDevice:
                stranded.append(task)
                continue
            _m_requeues.inc()
            self._enqueue_locked(target, task)
        self._cv.notify_all()
        return stranded

    # -------------------------------------------------------------- lifecycle

    def close(self, wait: bool = True, *,
              join_timeout_s: float | None = None) -> None:
        """Stop the pool.  wait=True (default) drains queued tasks first;
        wait=False fails queued tasks with PoolClosed (running tasks
        still finish -- a device program cannot be interrupted).
        join_timeout_s (None = unbounded) caps the per-worker thread join
        so an abort-path caller (the serve engine's drain-deadline
        fallback) is not held hostage by a hung device program; a capped
        join may fail still-queued tasks with PoolClosed, so the default
        stays unbounded to honor the wait=True drain contract."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            stranded: list[_Task] = []
            if not wait:
                for w in self._workers:
                    stranded.extend(w.pending)
                    w.pending.clear()
                    w.m_depth.set(w.depth())
            self._cv.notify_all()
        for task in stranded:
            task.future._finish(exc=PoolClosed("device pool closed"))
        for w in self._workers:
            if w.thread is not None:
                w.thread.join(timeout=join_timeout_s)
        # a task requeued onto a worker that had already drained and
        # exited would otherwise strand with an incomplete future
        with self._lock:
            leftovers = [t for w in self._workers for t in w.pending]
            for w in self._workers:
                w.pending.clear()
                w.m_depth.set(0)
        for task in leftovers:
            task.future._finish(exc=PoolClosed("device pool closed"))
        self._log.info("device pool down")

    def __enter__(self) -> "DevicePool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------ intro

    def status(self) -> dict:
        """Per-device breakdown (the serve `status` verb embeds this)."""
        with self._lock:
            bucket_count = {w.index: self._sticky.resident_count(w.index)
                            for w in self._workers}
            return {
                "policy": self.config.policy,
                "devices": [{
                    "device": w.name,
                    "benched": w.benched,
                    "busy": w.busy,
                    "queued": len(w.pending),
                    "strikes": w.strikes,
                    "tasks_done": w.tasks_done,
                    "failures": w.failures,
                    "buckets": bucket_count[w.index],
                } for w in self._workers],
            }
