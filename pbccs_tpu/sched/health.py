"""Fleet-health and sticky-routing helpers shared across failure domains.

PR 5 built bench-and-requeue at DEVICE granularity inside DevicePool;
the serve router (pbccs_tpu/serve/router.py) needs the identical idioms
at REPLICA granularity (a whole `ccs serve` process as the failure
domain).  This module lifts the two reusable pieces out of pool.py so
both layers share one implementation instead of drifting copies:

  * ``StickyMap`` -- the bucket-key -> home-member affinity map behind
    sticky routing (an idle home always wins; a busy home loses to the
    least-loaded healthy member, which then becomes an additional home).
    DevicePool routes compiled-shape buckets to devices with it; the
    router routes them to replicas, keeping each replica's
    compiled-program menu hot.
  * ``HealthTracker`` -- consecutive-failure strike counting with
    benching and success-driven re-admission.  DevicePool's strikes are
    interwoven with its requeue transaction and stay in pool.py; the
    tracker serves members whose health is PROBED (the router's periodic
    `status` checks), where a recovered member must re-admit -- a
    benched device never comes back, a restarted replica routinely does.

Both classes are lock-free on purpose: the owner already serializes
routing decisions under its own lock, and a second lock here would only
create ordering hazards (ccs-analyze CONC003).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Hashable, Sequence, TypeVar

M = TypeVar("M")

# routing outcomes (metric label values shared by pool and router)
ROUTE_HOME = "home"
ROUTE_SPILL = "spill"
ROUTE_NEW = "new"


class StickyMap:
    """Bucket-key -> home-member affinity for sticky routing.

    Members are referenced by a hashable id (worker index, replica
    name); the caller supplies the live member objects plus ``load`` /
    ``depth`` accessors at route time, so the map itself never holds a
    stale member reference.  NOT thread-safe: callers route under their
    own scheduler lock.
    """

    def __init__(self) -> None:
        self._homes: dict[Hashable, set[Hashable]] = {}

    def note(self, key: Hashable, member_id: Hashable) -> None:
        """Record that `key` ran on `member_id` (it becomes a home)."""
        self._homes.setdefault(key, set()).add(member_id)

    def forget_member(self, member_id: Hashable) -> None:
        """Drop a member from every home set (benched / left the fleet):
        nothing should stick to a member that cannot take work."""
        for homes in self._homes.values():
            homes.discard(member_id)

    def homes(self, key: Hashable) -> set[Hashable]:
        return set(self._homes.get(key, ()))

    def resident_count(self, member_id: Hashable) -> int:
        """How many distinct bucket keys call this member home (the
        routing tie-break prefers members with fewer resident buckets,
        spreading the compiled-program menu across the fleet)."""
        return sum(1 for homes in self._homes.values()
                   if member_id in homes)

    def route(self, key: Hashable, members: Sequence[M], *,
              member_id: Callable[[M], Hashable],
              load: Callable[[M], tuple],
              depth: Callable[[M], int],
              spill_depth: int = 0) -> tuple[M, str]:
        """Pick a member for `key` among `members` (already filtered to
        healthy + eligible).  Returns (member, outcome) with outcome in
        home|spill|new; the caller records the route via note() once the
        work is actually enqueued (so a raced rejection never mints a
        phantom home).

        `load` is the least-loaded total order (ties broken inside it);
        `depth` is the queued+running count the spill threshold compares
        against."""
        if not members:
            raise ValueError("route() needs at least one member")
        home_set = self._homes.get(key, ())
        homes = [m for m in members if member_id(m) in home_set]
        if homes:
            best = min(homes, key=load)
            if depth(best) <= spill_depth:
                return best, ROUTE_HOME
            # a busy home can still be the least-loaded member on a
            # saturated fleet -- that route is home, not spill
            target = min(members, key=load)
            return target, (ROUTE_HOME if member_id(target) in home_set
                            else ROUTE_SPILL)
        return min(members, key=load), ROUTE_NEW


@dataclasses.dataclass(frozen=True)
class HealthPolicy:
    """Strike/re-admission knobs for probed members."""

    # consecutive failures before a member is marked unhealthy
    bench_after: int = 2
    # consecutive probe successes an UNHEALTHY member needs before
    # re-admission (1 = first good probe readmits; >1 damps flapping)
    readmit_after: int = 1

    def __post_init__(self):
        if self.bench_after < 1:
            raise ValueError("bench_after must be >= 1")
        if self.readmit_after < 1:
            raise ValueError("readmit_after must be >= 1")


class _MemberHealth:
    __slots__ = ("healthy", "strikes", "successes", "failures_total",
                 "benched_total")

    def __init__(self) -> None:
        self.healthy = True
        self.strikes = 0          # consecutive failures while healthy
        self.successes = 0        # consecutive successes while unhealthy
        self.failures_total = 0
        self.benched_total = 0


class HealthTracker:
    """Consecutive-failure benching with probe-driven re-admission.

    record_failure()/record_success() return True exactly on the
    transition (became unhealthy / recovered), so the caller can count
    metrics and run its requeue sweep once per transition instead of
    once per probe.  NOT thread-safe (see module docstring).
    """

    def __init__(self, policy: HealthPolicy | None = None) -> None:
        self.policy = policy or HealthPolicy()
        self._members: dict[Hashable, _MemberHealth] = {}

    def _member(self, member_id: Hashable) -> _MemberHealth:
        m = self._members.get(member_id)
        if m is None:
            m = self._members[member_id] = _MemberHealth()
        return m

    def healthy(self, member_id: Hashable) -> bool:
        return self._member(member_id).healthy

    def forget(self, member_id: Hashable) -> None:
        """Drop a member's health state entirely (it left the fleet);
        a future member reusing the name starts healthy, no strikes."""
        self._members.pop(member_id, None)

    def record_failure(self, member_id: Hashable) -> bool:
        """One failed probe/dispatch; True when this strike benched the
        member (the caller fails over its in-flight work ONCE)."""
        m = self._member(member_id)
        m.failures_total += 1
        m.successes = 0
        if not m.healthy:
            return False
        m.strikes += 1
        if m.strikes >= self.policy.bench_after:
            m.healthy = False
            m.benched_total += 1
            m.strikes = 0
            return True
        return False

    def record_success(self, member_id: Hashable) -> bool:
        """One successful probe/dispatch; True when it re-admitted a
        previously-unhealthy member (flapping members re-enter only
        after readmit_after consecutive good probes)."""
        m = self._member(member_id)
        m.strikes = 0
        if m.healthy:
            return False
        m.successes += 1
        if m.successes >= self.policy.readmit_after:
            m.healthy = True
            m.successes = 0
            return True
        return False

    def snapshot(self, member_id: Hashable) -> dict:
        m = self._member(member_id)
        return {"healthy": m.healthy, "strikes": m.strikes,
                "failures": m.failures_total,
                "benched_times": m.benched_total}
