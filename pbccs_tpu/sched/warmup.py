"""`ccs warmup`: precompile the polish-program menu for declared buckets.

The first polish of a bucket shape pays the XLA compile (~a minute per
shape set on the tunneled dev TPU, noted in PR 3); a serving engine or a
production batch run that knows its workload geometry can pay it BEFORE
traffic instead of inside it.  Each `--bucket ZxPASSESxLEN` entry names a
compiled-shape bucket by workload geometry -- Z ZMWs per batch, PASSES
subreads per ZMW, LEN-base templates -- and warmup drives one synthetic
batch of exactly that geometry through the full polish surface
(BatchPolisher setup + refine + QV sweep + the straggler-continuation
shapes), populating the in-process executable cache and the persistent
compilation cache (runtime/cache.py) that later processes load from.

By default each bucket warms on ONE device (the persistent cache serves
the other devices' compiles as disk hits); `--allDevices` compiles on
every visible device for fleets whose per-device executable caches must
be hot before the first request.

    ccs warmup --bucket 64x8x300 --bucket 16x3x2000 --allDevices
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from pbccs_tpu.runtime.logging import Logger, LogLevel


def parse_bucket(spec: str) -> tuple[int, int, int]:
    """'ZxPASSESxLEN' -> (n_zmws, n_passes, tpl_len)."""
    parts = spec.lower().split("x")
    if len(parts) != 3:
        raise SystemExit(
            f"--bucket {spec!r}: want ZxPASSESxLEN, e.g. 64x8x300")
    try:
        z, p, length = (int(x) for x in parts)
    except ValueError:
        raise SystemExit(
            f"--bucket {spec!r}: want ZxPASSESxLEN, e.g. 64x8x300") from None
    if min(z, p, length) < 1:
        raise SystemExit(
            f"--bucket {spec!r}: want three positive ints ZxPASSESxLEN")
    return z, p, length


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ccs warmup",
        description="Precompile the polish-program menu for declared "
                    "workload buckets (kills the cold-compile latency of "
                    "the first batch/request at each shape).")
    p.add_argument("--bucket", action="append", default=None,
                   metavar="ZxPASSESxLEN",
                   help="One compiled-shape bucket by workload geometry: "
                        "Z ZMWs per batch, PASSES subreads per ZMW, "
                        "LEN-base templates.  Repeatable.  May be "
                        "omitted when --tuneProfile supplies a "
                        "warmup_buckets menu.")
    p.add_argument("--tuneProfile", default=None, metavar="PATH|auto",
                   help="ccs-tune host profile to apply (band width, "
                        "dense blocking) so the warmed executables match "
                        "what a tuned batch/serve process will request; "
                        "its warmup_buckets menu is the default --bucket "
                        "list.  'auto' scans the profiles/ directory for "
                        "a fingerprint match.  Default: "
                        "PBCCS_TUNE_PROFILE, else no profile.")
    p.add_argument("--devices", type=int, default=0,
                   help="Devices visible to the warmed fleet (0 = all; "
                        "bounds what --allDevices compiles on). "
                        "Default = %(default)s")
    p.add_argument("--allDevices", action="store_true",
                   help="Compile every bucket on every device (default: "
                        "one device; the persistent compilation cache "
                        "serves the rest as disk hits).")
    p.add_argument("--compileCache", default=None, metavar="DIR",
                   help="Persistent XLA compilation-cache directory to "
                        "populate -- point the serve fleet's "
                        "--compileCache at the same DIR so replica "
                        "(re)starts load the warmed executables from "
                        "disk (default: JAX_COMPILATION_CACHE_DIR, else "
                        "the checkout-local .jax_cache).")
    p.add_argument("--logLevel", default="INFO")
    return p


def _warm_one(tasks) -> dict:
    """Full polish surface at this bucket's shapes; returns the effective
    compiled shapes (what a matching production batch will reuse)."""
    from pbccs_tpu.models.arrow.refine import RefineOptions
    from pbccs_tpu.parallel.batch import BatchPolisher

    opts = RefineOptions()
    polisher = BatchPolisher(tasks)
    polisher.refine(opts)
    polisher.consensus_qvs()
    polisher.warm_straggler_shapes(opts)
    return {"Z": polisher._Z, "R": polisher._R,
            "Jmax": polisher._Jmax, "Imax": polisher._Imax,
            "W": polisher._W}


def _synth_tasks(n_zmws: int, n_passes: int, tpl_len: int):
    from pbccs_tpu.parallel.batch import ZmwTask
    from pbccs_tpu.simulate import simulate_zmw

    rng = np.random.default_rng(20260729)
    tasks = []
    for z in range(n_zmws):
        tpl, reads, strands, snr = simulate_zmw(rng, tpl_len, n_passes)
        draft = tpl.copy()
        if tpl_len > 10:  # corrupt so refinement does real mutation work
            pos = int(rng.integers(5, tpl_len - 5))
            draft[pos] = (draft[pos] + 1) % 4
        tasks.append(ZmwTask(f"warmup/{z}", draft, snr, reads, strands,
                             [0] * n_passes, [len(draft)] * n_passes))
    return tasks


def run_warmup(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    log = Logger.default(Logger(level=LogLevel.from_string(args.logLevel)))

    from pbccs_tpu.runtime import tuning

    tuning.configure(args.tuneProfile, logger=log)
    if not args.bucket:
        args.bucket = tuning.knob_str_list("warmup_buckets")
    if not args.bucket:
        raise SystemExit(
            "ccs warmup: --bucket is required (no applied tune profile "
            "supplies a warmup_buckets menu)")

    from pbccs_tpu.runtime.cache import enable_compilation_cache

    enable_compilation_cache(args.compileCache)

    import jax

    from pbccs_tpu.sched.pool import select_devices

    try:
        devices = select_devices(args.devices)
    except ValueError as e:
        raise SystemExit(f"--devices: {e}") from None
    targets = devices if args.allDevices else devices[:1]
    entries = [parse_bucket(b) for b in args.bucket]

    from pbccs_tpu.obs import roofline
    from pbccs_tpu.parallel.batch import effective_shapes
    from pbccs_tpu.resilience import resources

    gov = resources.default_governor()
    report = []
    for (z, passes, length) in entries:
        tasks = _synth_tasks(z, passes, length)
        imax, jmax, r, _ = effective_shapes(
            len(tasks), max(len(t.reads) for t in tasks),
            max(len(rd) for t in tasks for rd in t.reads),
            max(len(t.tpl) for t in tasks))
        bucket = resources.shape_bucket(imax, jmax, r)
        for dev in targets:
            name = f"{dev.platform}:{dev.id}"
            # the warmup menu consults the same ceilings production
            # dispatch learns: warming a Z the device cannot hold would
            # compile (and OOM) a shape no batch will ever run at
            cap = gov.cap(bucket, device=name)
            sub = tasks if cap is None else tasks[:cap]
            if len(sub) < len(tasks):
                log.warn(f"warmup: bucket {z}x{passes}x{length} clamped "
                         f"to Z={len(sub)} by the memory governor "
                         f"ceiling on {name}")
            log.info(f"warmup: bucket {z}x{passes}x{length} on {name}")
            t0 = time.monotonic()
            shapes = None
            while True:
                try:
                    with resources.device_scope(name), \
                            jax.default_device(dev):
                        shapes = _warm_one(sub)
                    break
                except Exception as e:  # noqa: BLE001 -- classified below
                    if not resources.is_capacity_error(e) or len(sub) == 1:
                        raise
                    # warmup discovers the ceiling BEFORE traffic does:
                    # record it and warm the largest Z that fits
                    ceiling = gov.record_oom(bucket, len(sub), device=name)
                    log.warn(f"warmup: {z}x{passes}x{length} OOMed at "
                             f"Z={len(sub)} on {name}; retrying at "
                             f"Z={ceiling}")
                    sub = sub[:ceiling]
            dt = time.monotonic() - t0
            entry = {"bucket": f"{z}x{passes}x{length}", "device": name,
                     "seconds": round(dt, 2), "shapes": shapes}
            if len(sub) < len(tasks):
                entry["governor_clamped_z"] = len(sub)
            # the polish above minted (and persisted) this bucket's
            # roofline CostCard; surface it so warmup output doubles as
            # the bound report for the menu
            card = roofline.tracker().card(
                roofline.bucket_label(imax, jmax, r))
            if card is not None:
                entry["cost_card"] = {
                    "label": card.label, "flops": card.flops,
                    "bytes_accessed": card.bytes_accessed,
                    "peak_hbm_bytes": card.peak_hbm_bytes,
                    "intensity": card.intensity, "card_z": card.z}
            report.append(entry)
            log.info(f"warmup: {entry['bucket']} on {name}: "
                     f"{dt:.1f}s, shapes {shapes}")
    out: dict = {"warmed": report}
    cards_file = roofline.cards_path()
    if cards_file:
        out["roofline_cards"] = cards_file
    print(json.dumps(out))
    log.flush()
    return 0


if __name__ == "__main__":
    sys.exit(run_warmup())
