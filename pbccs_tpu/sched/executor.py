"""Pipelined batch executor: host prepare overlapped with device polish.

The offline driver's round-5 profile runs end to end at 42% of polish
throughput because the serial host-side POA draft gates the device: the
WorkQueue overlaps whole work items, but each worker still runs
prepare -> polish sequentially, so with one device the prepare of item
k+1 only overlaps the polish of item k when a second worker happens to
hold it.  This executor makes the overlap structural and fleet-wide:

    reader ──> prepare pool (N host threads: filter -> POA -> mapping)
                   │ prepared batches, keyed by compiled-shape bucket
                   ▼
               DevicePool (one executor thread per device)
                   │ per-batch outcome tallies
                   ▼
               ordered emission (results yield in submission order, so
               checkpoint journaling and output BAM order are identical
               to the single-threaded driver)

Batch composition is untouched -- the same --chunkSize groups, prepared
and polished with the same shape derivation as pipeline.process_chunks
-- so a multi-device run's output is byte-identical to the
single-device run (same bucket shapes => same compiled programs => same
arithmetic), merely reordered in time.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Iterable, Iterator

from pbccs_tpu import pipeline
from pbccs_tpu.obs import trace as obs_trace
from pbccs_tpu.obs.metrics import default_registry, log_buckets
from pbccs_tpu.runtime.logging import Logger
from pbccs_tpu.sched.pool import DevicePool

# offline-driver analogue of the serve engine's per-request stage
# histograms: per BATCH intervals through the prepare pool and the
# device fleet, so a fleet bench's latency story decomposes the same
# way a serve trace does (prepare / dispatch wait / polish)
_reg = default_registry()
_m_stages = {stage: _reg.histogram(
    "ccs_sched_stage_latency_seconds",
    "Per-batch stage intervals through the scheduled pipeline "
    "(prepare, dispatch wait, polish)",
    buckets=log_buckets(1e-4, 600.0), stage=stage)
    for stage in ("prepare", "dispatch", "polish")}
# batches the scheduled pipeline submitted to the device pool -- a
# CPU-deterministic perf-ledger counter (obs/ledger.py), distinct from
# ccs_sched_tasks_total{device} whose device attribution is
# routing-dependent
_m_batches = _reg.counter(
    "ccs_sched_batches_total",
    "Prepared batches submitted to the device pool by the scheduled "
    "pipeline")


class ScheduledPipeline:
    """Run (index, chunk-batch) work items through prepare workers and a
    DevicePool, yielding (index, ResultTally) in submission order."""

    def __init__(self, pool: DevicePool,
                 settings: "pipeline.ConsensusSettings",
                 prepare_workers: int = 2, on_error: str = "bisect",
                 max_inflight: int | None = None,
                 budget=None,
                 logger: Logger | None = None):
        self.pool = pool
        self.settings = settings
        self.prepare_workers = max(1, prepare_workers)
        self.on_error = on_error
        # bounds batches simultaneously past the reader (prepping, queued
        # on a device, or done-but-not-yet-emitted) so a fast reader
        # cannot buffer a whole cell's preps in memory
        self.max_inflight = max_inflight or (
            self.prepare_workers + pool.n_devices + 2)
        # optional resources.HostBudget (--memBudget): each batch charges
        # its marshalled-bytes estimate before the prebake builds and
        # releases when its POLISH completes -- the true lifetime of the
        # charged planes (they are garbage once the dispatch consumed
        # them), and a release point that cannot deadlock: emission is
        # strictly ordered, so a release tied to emission could wait on
        # an earlier batch whose prep is itself blocked in admit().
        # Parked results stay count-bounded by max_inflight.
        self.budget = budget
        self._log = logger or Logger.default()

    # Each input item is (index, chunks, precomputed) -- precomputed is a
    # ResultTally for work restored from a checkpoint journal (emitted in
    # order without recomputation) and None for real work.
    def run(self, items: Iterable[tuple[int, Any, Any]]
            ) -> Iterator[tuple[int, "pipeline.ResultTally"]]:
        cv = threading.Condition()
        done: dict[int, Any] = {}        # seq -> (idx, tally) | exception
        sem = threading.Semaphore(self.max_inflight)
        n_fed = [0]
        feeder_done = threading.Event()
        feeder_error: list[BaseException] = []

        def finish(seq: int, payload) -> None:
            with cv:
                done[seq] = payload
                cv.notify_all()

        def polish_done(seq, idx, tally, preps, fut, lease=None) -> None:
            # runs as a SchedFuture callback, whose exceptions the pool
            # only debug-logs: anything raising here must still finish()
            # this slot or run()'s ordered emission waits forever
            if lease is not None:
                # the polish consumed (or abandoned) the marshalled
                # planes; their budget charge ends here regardless of
                # outcome (release is idempotent)
                lease.release()
            try:
                exc = fut.exception()
                if exc is not None:
                    # the pool exhausted every healthy device on this
                    # batch: account each ZMW (logged + counted), never
                    # drop silently
                    pipeline.record_zmw_failure(
                        "sched.polish", exc, zmw=f"batch[{len(preps)}]")
                    for _ in preps:
                        tally.tally(pipeline.Failure.OTHER)
                else:
                    outcomes = fut.result()
                    if len(outcomes) != len(preps):
                        raise RuntimeError(
                            f"polish returned {len(outcomes)} outcomes "
                            f"for {len(preps)} prepared ZMWs")
                    for failure, result in outcomes:
                        tally.tally(failure)
                        if result is not None:
                            tally.results.append(result)
                finish(seq, (idx, tally))
            except BaseException as e:  # noqa: BLE001 -- surfaced in run()
                finish(seq, e)

        def prep_one(seq: int, idx: int, chunks, precomputed) -> None:
            lease = None
            t_prep0 = time.monotonic()
            try:
                if precomputed is not None:
                    finish(seq, (idx, precomputed))
                    return
                tally, preps = pipeline.prepare_batch(chunks, self.settings)
                if not preps:
                    finish(seq, (idx, tally))
                    return
                (imax, jmax, r), z = pipeline._pinned_batch_shapes(
                    preps, None, 1)
                key = (jmax, imax, r, z)
                # host-budget gate (--memBudget): charge this batch's
                # marshalled-bytes estimate BEFORE building the prebake;
                # blocks (a visible resource.throttle, not a crash)
                # while other batches hold the budget, released when
                # this batch's polish completes
                if self.budget is not None:
                    from pbccs_tpu.parallel.batch import premarshal_nbytes

                    lease = self.budget.admit(
                        premarshal_nbytes((imax, jmax, r, z)),
                        site="sched.prepare", abort=stop.is_set)
                    if stop.is_set():
                        if lease is not None:
                            lease.release()
                        return
                # pre-bake the polish marshalling HERE, on the prepare
                # worker: padded numpy planes + f64 SNR tables build while
                # the device threads polish earlier batches, so
                # BatchPolisher on the executor thread adopts arrays
                # instead of marshalling.  Quiver polishes per ZMW and
                # never reads a prebake; any prebake failure falls back
                # to inline marshalling (accounted, never fatal).
                prebaked = None
                if self.settings.model != "quiver":
                    try:
                        prebaked = pipeline.prebake_polish(preps)
                    except Exception as e:  # noqa: BLE001 -- inline fallback
                        pipeline.record_zmw_failure(
                            "prepare.prebake", e,
                            zmw=f"batch[{len(preps)}]")
                settings, on_error = self.settings, self.on_error
                fleet = self.pool.n_devices > 1
                attempts = [0]
                t_submit = time.monotonic()
                _m_stages["prepare"].observe(max(t_submit - t_prep0, 0.0))

                def polish(_device):
                    # first attempt on a fleet: let a device-shaped
                    # failure (hang/XLA error) escape to the pool, which
                    # strikes/benches the sick device and requeues the
                    # WHOLE batch to a healthy one -- quarantine would
                    # otherwise bisect on the same sick device.  The
                    # requeued attempt quarantines locally as usual (a
                    # failure that followed the batch across devices is
                    # task-shaped: poison input, not hardware).
                    attempts[0] += 1
                    t_polish0 = time.monotonic()
                    if attempts[0] == 1:
                        _m_stages["dispatch"].observe(
                            max(t_polish0 - t_submit, 0.0))
                    try:
                        with obs_trace.span("polish", zmws=len(preps)):
                            return pipeline.polish_prepared_batch(
                                preps, settings, on_error=on_error,
                                raise_device_shaped=fleet
                                and attempts[0] == 1,
                                prebaked=prebaked)
                    finally:
                        _m_stages["polish"].observe(
                            max(time.monotonic() - t_polish0, 0.0))

                from pbccs_tpu.resilience import resources

                _m_batches.inc()
                self.pool.submit(
                    key, polish, zmws=len(preps),
                    capacity_bucket=resources.shape_bucket(imax, jmax, r),
                    callback=lambda fut: polish_done(seq, idx, tally,
                                                     preps, fut, lease))
            except BaseException as e:  # noqa: BLE001 -- surfaced in run()
                # the callback never ran (pool closed, prebake blew up):
                # the budget charge must not outlive the batch (release
                # is idempotent, so a raced callback is harmless)
                if lease is not None:
                    lease.release()
                finish(seq, e)

        prep_pool = ThreadPoolExecutor(
            self.prepare_workers, thread_name_prefix="ccs-sched-prep")
        stop = threading.Event()   # consumer bailed: unwedge the feeder

        def feed() -> None:
            try:
                for idx, chunks, precomputed in items:
                    sem.acquire()
                    if stop.is_set():
                        return
                    seq = n_fed[0]
                    n_fed[0] += 1
                    prep_pool.submit(prep_one, seq, idx, chunks, precomputed)
            except BaseException as e:  # noqa: BLE001 -- surfaced in run()
                feeder_error.append(e)
            finally:
                feeder_done.set()
                with cv:
                    cv.notify_all()

        feeder = threading.Thread(target=feed, daemon=True,
                                  name="ccs-sched-feeder")
        feeder.start()
        try:
            next_seq = 0
            while True:
                with cv:
                    while next_seq not in done and not (
                            feeder_done.is_set() and next_seq >= n_fed[0]):
                        cv.wait(timeout=0.2)
                    if next_seq not in done:
                        break  # feeder finished and everything emitted
                    payload = done.pop(next_seq)
                if isinstance(payload, BaseException):
                    raise payload
                yield payload
                sem.release()
                next_seq += 1
            if feeder_error:
                raise feeder_error[0]
        finally:
            # a consumer that bailed mid-stream (journal write failed,
            # generator closed) leaves the feeder parked in sem.acquire;
            # wake it so the thread (and the input reader it holds) ends.
            # A prep worker parked in budget.admit() observes the abort
            # flag (admit polls it), so shutdown never hangs on the
            # budget; in-flight batches release their leases from the
            # polish_done callback when the pool settles their futures.
            stop.set()
            sem.release()
            feeder_done.wait(timeout=10.0)
            prep_pool.shutdown(wait=True)
