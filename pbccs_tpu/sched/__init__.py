"""Device-fleet scheduler: pipelined multi-device dispatch shared by the
batch CLI (`--devices`) and the serve engine (`ServeConfig.devices`).

  * pool.py      DevicePool / per-device executor threads, sticky bucket
                 routing, health-based benching + requeue
  * health.py    StickyMap + HealthTracker: the routing/benching idioms
                 shared with the serve router (replica granularity)
  * executor.py  ScheduledPipeline: host prepare pool overlapped with
                 in-flight device polishes, ordered result emission
  * warmup.py    `ccs warmup`: precompile a declared bucket menu
"""

from pbccs_tpu.sched.health import (  # noqa: F401
    HealthPolicy,
    HealthTracker,
    StickyMap,
)
from pbccs_tpu.sched.pool import (  # noqa: F401
    DevicePool,
    DevicePoolConfig,
    NoHealthyDevice,
    PoolClosed,
    SchedFuture,
    select_devices,
)
from pbccs_tpu.sched.executor import ScheduledPipeline  # noqa: F401
