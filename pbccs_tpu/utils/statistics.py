"""Statistics helpers (reference ConsensusCore/include/ConsensusCore/
Statistics/Binomial.hpp and src/C++/Statistics/Binomial.cpp)."""

from __future__ import annotations

import math


def binomial_survival(q: int, size: int, prob: float,
                      as_phred: bool = False) -> float:
    """P[X > q] for X ~ Binom(size, prob) (R's pbinom(q, size, prob,
    lower.tail=F)); as_phred converts to -10*log10(p)
    (reference Binomial.hpp:42-47)."""
    if size < 0:
        raise ValueError("size must be >= 0")
    p = 0.0
    for k in range(max(q + 1, 0), size + 1):
        p += math.comb(size, k) * prob ** k * (1.0 - prob) ** (size - k)
    p = min(max(p, 0.0), 1.0)
    if as_phred:
        return -10.0 * math.log10(p) if p > 0 else float("inf")
    return p
