"""Read-coverage helpers (reference ConsensusCore/include/ConsensusCore/
Coverage.hpp:51-64, src/C++/Coverage.cpp): per-position coverage inside a
window and minimum-coverage intervals, from read (tStart, tEnd) spans."""

from __future__ import annotations

import numpy as np

from pbccs_tpu.utils.intervals import Interval


def coverage_in_window(tstarts, tends, win_start: int, win_len: int) -> np.ndarray:
    """Per-position read depth over [win_start, win_start+win_len)
    (difference-array sweep; reference Coverage.cpp CoverageInWindow)."""
    tstarts = np.asarray(tstarts, np.int64)
    tends = np.asarray(tends, np.int64)
    diff = np.zeros(win_len + 1, np.int64)
    lo = np.clip(tstarts - win_start, 0, win_len)
    hi = np.clip(tends - win_start, 0, win_len)
    np.add.at(diff, lo, 1)
    np.add.at(diff, hi, -1)
    return np.cumsum(diff[:-1]).astype(np.int32)


def covered_intervals(min_coverage: int, tstarts, tends,
                      win_start: int, win_len: int) -> list[Interval]:
    """Maximal intervals with coverage >= min_coverage inside the window
    (reference Coverage.cpp CoveredIntervals)."""
    cov = coverage_in_window(tstarts, tends, win_start, win_len)
    ok = cov >= min_coverage
    out: list[Interval] = []
    start = None
    for i, v in enumerate(ok):
        if v and start is None:
            start = i
        elif not v and start is not None:
            out.append(Interval(win_start + start, win_start + i))
            start = None
    if start is not None:
        out.append(Interval(win_start + start, win_start + len(ok)))
    return out
