"""Shared host-side utilities (intervals, coverage, sequences, statistics)."""
