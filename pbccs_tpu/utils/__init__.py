"""Shared host-side utilities (intervals, coverage, sequences, statistics)."""


def next_pow2(n: int, lo: int = 8) -> int:
    """Smallest power of two >= n, at least `lo` (shared padding-bucket
    policy: pow2 buckets keep the set of compiled shapes small)."""
    v = lo
    while v < n:
        v *= 2
    return v
