"""Half-open integer intervals + a self-merging interval set.

Parity: reference include/pacbio/ccs/Interval.h (FromString at :210-234) and
IntervalTree.h (self-merging multiset, :52-205).
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import Iterable


@dataclasses.dataclass(frozen=True, order=True)
class Interval:
    """[left, right) with right >= left."""

    left: int
    right: int

    def __post_init__(self):
        if self.left > self.right:
            raise ValueError(f"invalid interval [{self.left}, {self.right})")

    def __len__(self) -> int:
        return self.right - self.left

    def contains(self, x: int) -> bool:
        return self.left <= x < self.right

    def overlaps(self, other: "Interval") -> bool:
        return self.left < other.right and other.left < self.right

    def touches(self, other: "Interval") -> bool:
        """Overlapping or directly adjacent (mergeable)."""
        return self.left <= other.right and other.left <= self.right

    @staticmethod
    def from_string(s: str) -> "Interval":
        """"5" -> [5,6); "3-7" -> [3,8) (inclusive right in the spec)."""
        parts = s.split("-")
        try:
            if len(parts) == 1:
                left = int(parts[0])
                if left < 0:
                    raise ValueError
                return Interval(left, left + 1)
            if len(parts) == 2:
                left, right = int(parts[0]), int(parts[1])
                if 0 <= left <= right:
                    return Interval(left, right + 1)
        except ValueError:
            pass
        raise ValueError(f"invalid Interval specification: {s!r}")

    def __str__(self) -> str:
        if len(self) == 1:
            return str(self.left)
        return f"{self.left}-{self.right - 1}"


class IntervalTree:
    """Sorted set of disjoint intervals; inserts merge with neighbors."""

    def __init__(self, intervals: Iterable[Interval] = ()):
        self._ivals: list[Interval] = []
        self._lefts: list[int] = []
        for i in intervals:
            self.insert(i)

    def insert(self, interval: Interval) -> None:
        lo = bisect.bisect_left(self._lefts, interval.left)
        # absorb any neighbor that overlaps or touches
        start = lo
        while start > 0 and self._ivals[start - 1].touches(interval):
            start -= 1
        end = lo
        while end < len(self._ivals) and self._ivals[end].touches(interval):
            end += 1
        merged = interval
        for i in self._ivals[start:end]:
            merged = Interval(min(merged.left, i.left), max(merged.right, i.right))
        self._ivals[start:end] = [merged]
        self._lefts[start:end] = [merged.left]

    def contains(self, x: int) -> bool:
        idx = bisect.bisect_right(self._lefts, x) - 1
        return idx >= 0 and self._ivals[idx].contains(x)

    def gaps(self) -> "IntervalTree":
        """Intervals between stored intervals (reference IntervalTree::Gaps)."""
        out = IntervalTree()
        for a, b in zip(self._ivals, self._ivals[1:]):
            out.insert(Interval(a.right, b.left))
        return out

    def __iter__(self):
        return iter(self._ivals)

    def __len__(self) -> int:
        return len(self._ivals)

    @staticmethod
    def from_string(s: str) -> "IntervalTree":
        """Comma-separated interval specs: "1-3,5" (reference
        IntervalTree::FromString)."""
        tree = IntervalTree()
        for part in s.split(","):
            tree.insert(Interval.from_string(part))
        return tree
