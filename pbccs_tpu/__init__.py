"""pbccs_tpu: a TPU-native circular consensus sequencing (CCS) framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of PacBio's pbccs
(reference: /root/reference): per-ZMW subread filtering, partial-order-alignment
drafting, Arrow pair-HMM polishing with mutation refinement, and per-base
quality emission -- expressed as fixed-shape, batched array programs that
`vmap` over ZMWs and `shard_map` over TPU meshes.

Layer map (top to bottom), mirroring the reference's stage boundaries
(SURVEY.md section 1) but not its implementation:

  cli.py            ccs-equivalent command line driver
  pipeline.py       per-ZMW-batch orchestration (filter -> draft -> polish -> emit)
  runtime/          host scheduling: bucketing, ordered work pipeline, whitelist
  poa/              draft stage: partial-order alignment (host)
  models/arrow/     the Arrow pair-HMM statistical model (params, expectations)
  ops/              device kernels: banded forward/backward, mutation scoring
  parallel/         device mesh + sharding of ZMW batches
  io/               FASTA/BAM/report IO
"""

__version__ = "0.1.0"
