"""Affine gap-penalty alignment (two-state model), vectorized row sweeps.

Behavior parity: reference ConsensusCore Align/AffineAlignment.{hpp,cpp} —
the Durbin et al. two-state formulation with a single GAP state shared by
both gap directions, defaults (0, -1, -1, -0.5) and the IUPAC-aware
variant that half-penalizes partial ambiguity matches
(AffineAlignment.cpp:66-78, 228-236).

Row sweep: M[i,*] depends only on the previous row; the GAP row's in-row
recurrence ``G[i,j] = max(W[j], G[i,j-1] + extend)`` is a prefix max of
``W[j] - j*extend``, so each row is a handful of numpy ops.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from pbccs_tpu.align.pairwise import PairwiseAlignment

_NEG = np.float32(-1e30)

_IUPAC = {
    "R": "AG", "Y": "CT", "S": "GC", "W": "AT", "K": "GT", "M": "AC",
}


@dataclasses.dataclass(frozen=True)
class AffineAlignmentParams:
    """Reference AffineAlignment.hpp:51-66."""

    match: float = 0.0
    mismatch: float = -1.0
    gap_open: float = -1.0
    gap_extend: float = -0.5
    partial_match: float = 0.0

    @classmethod
    def default(cls) -> "AffineAlignmentParams":
        return cls(0.0, -1.0, -1.0, -0.5, 0.0)

    @classmethod
    def iupac_aware(cls) -> "AffineAlignmentParams":
        return cls(0.0, -1.0, -1.0, -0.5, -0.25)


def _substitution_row(t: str, qc: str, p: AffineAlignmentParams,
                      iupac: bool) -> np.ndarray:
    tb = np.frombuffer(t.encode(), np.uint8)
    sub = np.where(tb == ord(qc), p.match, p.mismatch).astype(np.float64)
    if iupac:
        for code, pair in _IUPAC.items():
            if qc == code:
                hit = np.isin(tb, np.frombuffer(pair.encode(), np.uint8))
                sub = np.where(hit & (tb != ord(qc)), p.partial_match, sub)
            hit = (tb == ord(code)) & (qc in pair) & (qc != code)
            sub = np.where(hit, p.partial_match, sub)
    return sub


def _align_affine(target: str, query: str, p: AffineAlignmentParams,
                  iupac: bool) -> PairwiseAlignment:
    I, J = len(query), len(target)
    M = np.full((I + 1, J + 1), _NEG, np.float64)
    G = np.full((I + 1, J + 1), _NEG, np.float64)
    M[0, 0] = 0.0
    ramp = p.gap_open + np.arange(J, dtype=np.float64) * p.gap_extend
    G[0, 1:] = ramp
    G[1:, 0] = p.gap_open + np.arange(I, dtype=np.float64) * p.gap_extend
    ej = np.arange(J + 1, dtype=np.float64) * p.gap_extend
    for i in range(1, I + 1):
        sub = _substitution_row(target, query[i - 1], p, iupac)
        M[i, 1:] = np.maximum(M[i - 1, :-1], G[i - 1, :-1]) + sub
        w = np.empty(J + 1, np.float64)
        w[0] = G[i, 0]
        w[1:] = np.maximum(np.maximum(M[i, :-1] + p.gap_open,
                                      M[i - 1, 1:] + p.gap_open),
                           G[i - 1, 1:] + p.gap_extend)
        G[i] = np.maximum.accumulate(w - ej) + ej

    # traceback (reference AffineAlignment.cpp:156-209: M-state ties win)
    gt, gq = [], []
    i, j = I, J
    in_match = M[I, J] >= G[I, J]
    while i > 0 or j > 0:
        if in_match:
            in_match = M[i - 1, j - 1] >= G[i - 1, j - 1]
            i -= 1; j -= 1
            gt.append(target[j]); gq.append(query[i])
        else:
            cand = [
                M[i, j - 1] + p.gap_open if j > 0 else _NEG,
                G[i, j - 1] + p.gap_extend if j > 0 else _NEG,
                M[i - 1, j] + p.gap_open if i > 0 else _NEG,
                G[i - 1, j] + p.gap_extend if i > 0 else _NEG,
            ]
            k = int(np.argmax(cand))
            in_match = k in (0, 2)
            if k in (0, 1):
                j -= 1
                gt.append(target[j]); gq.append("-")
            else:
                i -= 1
                gt.append("-"); gq.append(query[i])
    gt.reverse(); gq.reverse()
    return PairwiseAlignment("".join(gt), "".join(gq))


def align_affine(target: str, query: str,
                 params: AffineAlignmentParams | None = None
                 ) -> PairwiseAlignment:
    """Affine gap-penalty global alignment (reference AlignAffine)."""
    return _align_affine(target, query,
                         params or AffineAlignmentParams.default(), False)


def align_affine_iupac(target: str, query: str,
                       params: AffineAlignmentParams | None = None
                       ) -> PairwiseAlignment:
    """Affine alignment half-penalizing IUPAC partial matches
    (reference AlignAffineIupac)."""
    return _align_affine(target, query,
                         params or AffineAlignmentParams.iupac_aware(), True)
