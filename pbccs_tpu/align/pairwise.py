"""Needleman-Wunsch style pairwise alignment with vectorized row sweeps.

Behavior parity: reference ConsensusCore Align/PairwiseAlignment.{hpp,cpp}
(transcript conventions per Gusfield: M/R/I/D with I = gap in target,
D = gap in query; move preference diagonal > insert > delete) and
Align/AlignConfig.{hpp,cpp} (edit-distance defaults 0/-1/-1/-1, GLOBAL).

The reference fills the DP cell-by-cell; here each row is one numpy sweep:
the horizontal (delete) move's in-row recurrence
``S[i,j] = max(V[j], S[i,j-1] + d)`` is a prefix max of ``V[j] - j*d``,
so the whole row vectorizes.  The reference's ``Align`` supports GLOBAL
only (PairwiseAlignment.cpp:137 throws otherwise); SEMIGLOBAL and LOCAL
here are a documented extension matching the AlignMode enum.
"""

from __future__ import annotations

import dataclasses

import numpy as np

GLOBAL, SEMIGLOBAL, LOCAL = 0, 1, 2


@dataclasses.dataclass(frozen=True)
class AlignParams:
    """Linear-gap scoring; defaults are edit distance
    (reference AlignConfig.cpp:59-62)."""

    match: int = 0
    mismatch: int = -1
    insert: int = -1   # gap in target (consumes query)
    delete: int = -1   # gap in query (consumes target)


@dataclasses.dataclass(frozen=True)
class AlignConfig:
    params: AlignParams = dataclasses.field(default_factory=AlignParams)
    mode: int = GLOBAL


class PairwiseAlignment:
    """A gapped alignment: target/query strings of equal length with '-'
    gaps, and the Gusfield transcript (M match, R mismatch, I insertion,
    D deletion).  Reference PairwiseAlignment.hpp:64-96."""

    def __init__(self, target: str, query: str,
                 target_begin: int = 0, query_begin: int = 0):
        if len(target) != len(query):
            raise ValueError("gapped strings must have equal length")
        tr = []
        for t, q in zip(target, query):
            if t == "-" and q == "-":
                raise ValueError("column with two gaps")
            tr.append("M" if t == q else "I" if t == "-" else
                      "D" if q == "-" else "R")
        self.target = target
        self.query = query
        self.transcript = "".join(tr)
        # start offsets of the aligned region (LOCAL/SEMIGLOBAL extension)
        self.target_begin = target_begin
        self.query_begin = query_begin

    @classmethod
    def from_transcript(cls, transcript: str, target: str, query: str
                        ) -> "PairwiseAlignment":
        """Reconstruct the gapped strings from a transcript over the
        unaligned sequences (reference PairwiseAlignment::FromTranscript)."""
        gt, gq = [], []
        ti = qi = 0
        for c in transcript:
            if c in "MR":
                gt.append(target[ti]); gq.append(query[qi]); ti += 1; qi += 1
            elif c == "D":
                gt.append(target[ti]); gq.append("-"); ti += 1
            elif c == "I":
                gt.append("-"); gq.append(query[qi]); qi += 1
            else:
                raise ValueError(f"bad transcript op {c!r}")
        if ti != len(target) or qi != len(query):
            raise ValueError("transcript does not span the sequences")
        return cls("".join(gt), "".join(gq))

    @property
    def length(self) -> int:
        return len(self.target)

    @property
    def matches(self) -> int:
        return self.transcript.count("M")

    @property
    def mismatches(self) -> int:
        return self.transcript.count("R")

    @property
    def insertions(self) -> int:
        return self.transcript.count("I")

    @property
    def deletions(self) -> int:
        return self.transcript.count("D")

    @property
    def errors(self) -> int:
        return self.length - self.matches

    @property
    def accuracy(self) -> float:
        return self.matches / self.length if self.length else 0.0

    def __repr__(self):
        return f"PairwiseAlignment({self.target!r}, {self.query!r})"


def _fill(query: str, target: str, p: AlignParams, mode: int) -> np.ndarray:
    """(I+1, J+1) int32 score matrix; rows sweep the query."""
    I, J = len(query), len(target)
    q = np.frombuffer(query.encode(), np.uint8)
    t = np.frombuffer(target.encode(), np.uint8)
    S = np.empty((I + 1, J + 1), np.int32)
    j = np.arange(1, J + 1, dtype=np.int32)
    if mode == GLOBAL:
        S[0, 0] = 0
        S[0, 1:] = j * p.delete
    else:  # SEMIGLOBAL / LOCAL: leading target overhang is free
        S[0] = 0
    dj = np.arange(J + 1, dtype=np.int64) * p.delete
    for i in range(1, I + 1):
        sub = np.where(t == q[i - 1], p.match, p.mismatch).astype(np.int64)
        v = np.empty(J + 1, np.int64)
        if mode == GLOBAL or mode == SEMIGLOBAL:
            v[0] = i * p.insert
        else:
            v[0] = 0
        v[1:] = np.maximum(S[i - 1, :-1] + sub, S[i - 1, 1:] + p.insert)
        if mode == LOCAL:
            v = np.maximum(v, 0)
        # horizontal move as prefix max: S[i,j] = max_{k<=j} v[k] + (j-k)*d
        S[i] = np.maximum.accumulate(v - dj) + dj
        if mode == LOCAL:
            S[i] = np.maximum(S[i], 0)
    return S


def align(target: str, query: str, config: AlignConfig | None = None,
          ) -> PairwiseAlignment:
    """Align query against target; returns the gapped alignment.

    GLOBAL output matches the reference's Align (PairwiseAlignment.cpp:
    124-215) including traceback preference.  SEMIGLOBAL keeps the full
    target, padding the overhang with deletions; LOCAL returns the aligned
    region with `target_begin`/`query_begin` offsets."""
    cfg = config or AlignConfig()
    p, mode = cfg.params, cfg.mode
    I, J = len(query), len(target)
    S = _fill(query, target, p, mode)

    if mode == GLOBAL:
        i, j = I, J
        stop = lambda i, j: i == 0 and j == 0
    elif mode == SEMIGLOBAL:
        i, j = I, int(np.argmax(S[I]))
        stop = lambda i, j: i == 0
    else:
        i, j = np.unravel_index(int(np.argmax(S)), S.shape)
        i, j = int(i), int(j)
        stop = lambda i, j: S[i, j] == 0

    end_i, end_j = i, j
    gt, gq = [], []
    while not stop(i, j):
        if i == 0:
            move = 2
        elif j == 0:
            move = 1
        else:
            sub = p.match if query[i - 1] == target[j - 1] else p.mismatch
            cand = (S[i - 1, j - 1] + sub, S[i - 1, j] + p.insert,
                    S[i, j - 1] + p.delete)
            # diagonal > insert > delete on ties (reference ArgMax3)
            move = 0 if cand[0] >= cand[1] and cand[0] >= cand[2] else \
                1 if cand[1] >= cand[2] else 2
        if move == 0:
            i -= 1; j -= 1
            gt.append(target[j]); gq.append(query[i])
        elif move == 1:
            i -= 1
            gt.append("-"); gq.append(query[i])
        else:
            j -= 1
            gt.append(target[j]); gq.append("-")

    gt.reverse(); gq.reverse()
    if mode == SEMIGLOBAL:
        # pad free target overhangs back in as deletions
        gt = list(target[:j]) + gt + list(target[end_j:])
        gq = ["-"] * j + gq + ["-"] * (J - end_j)
        j = 0
    return PairwiseAlignment("".join(gt), "".join(gq),
                             target_begin=j, query_begin=i)


def align_score(target: str, query: str, config: AlignConfig | None = None
                ) -> int:
    """The optimal alignment score alone (no traceback)."""
    cfg = config or AlignConfig()
    S = _fill(query, target, cfg.params, cfg.mode)
    if cfg.mode == GLOBAL:
        return int(S[-1, -1])
    if cfg.mode == SEMIGLOBAL:
        return int(S[-1].max())
    return int(S.max())


def target_to_query_positions(transcript: str) -> np.ndarray:
    """len(target)+1 indices into the query, per transcript op
    (reference PairwiseAlignment.cpp TargetToQueryPositions)."""
    out = [0]
    pos = 0
    for c in transcript:
        if c in "MR":
            pos += 1
            out.append(pos)
        elif c == "I":
            pos += 1
            out[-1] = pos
        elif c == "D":
            out.append(pos)
        else:
            raise ValueError(f"bad transcript op {c!r}")
    return np.asarray(out, np.int32)
