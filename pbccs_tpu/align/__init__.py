"""Exact pairwise aligners (host utilities).

Parity targets: reference ConsensusCore/include/ConsensusCore/Align/
{AlignConfig,PairwiseAlignment,AffineAlignment,LinearAlignment}.hpp.
"""

from pbccs_tpu.align.pairwise import (
    GLOBAL,
    LOCAL,
    SEMIGLOBAL,
    AlignConfig,
    AlignParams,
    PairwiseAlignment,
    align,
    target_to_query_positions,
)
from pbccs_tpu.align.affine import (
    AffineAlignmentParams,
    align_affine,
    align_affine_iupac,
)
from pbccs_tpu.align.linear import align_linear

__all__ = [
    "GLOBAL", "SEMIGLOBAL", "LOCAL",
    "AlignParams", "AlignConfig", "PairwiseAlignment",
    "align", "target_to_query_positions",
    "AffineAlignmentParams", "align_affine", "align_affine_iupac",
    "align_linear",
]
