"""O(N)-memory global alignment (Hirschberg divide and conquer).

Behavior parity: reference ConsensusCore Align/LinearAlignment.{hpp,cpp}
(AlignLinear: global alignment in linear memory).  The divide-and-conquer
keeps only two score rows at a time; base cases fall back to the quadratic
aligner over tiny strips, so outputs are optimal global alignments under
the same AlignConfig scoring.
"""

from __future__ import annotations

import numpy as np

from pbccs_tpu.align.pairwise import (
    GLOBAL,
    AlignConfig,
    PairwiseAlignment,
    align,
)


def _last_row(query: str, target: str, p) -> np.ndarray:
    """Final NW row (scores of query vs every target prefix), O(J) memory."""
    J = len(target)
    t = np.frombuffer(target.encode(), np.uint8)
    dj = np.arange(J + 1, dtype=np.int64) * p.delete
    row = dj.copy()
    for i, qc in enumerate(query.encode(), start=1):
        sub = np.where(t == qc, p.match, p.mismatch).astype(np.int64)
        v = np.empty(J + 1, np.int64)
        v[0] = i * p.insert
        v[1:] = np.maximum(row[:-1] + sub, row[1:] + p.insert)
        row = np.maximum.accumulate(v - dj) + dj
    return row


def _hirschberg(target: str, query: str, cfg: AlignConfig) -> tuple[str, str]:
    I, J = len(query), len(target)
    if I <= 1 or J <= 1:
        a = align(target, query, cfg)
        return a.target, a.query
    mid = I // 2
    upper = _last_row(query[:mid], target, cfg.params)
    lower = _last_row(query[mid:][::-1], target[::-1], cfg.params)[::-1]
    split = int(np.argmax(upper + lower))
    lt, lq = _hirschberg(target[:split], query[:mid], cfg)
    rt, rq = _hirschberg(target[split:], query[mid:], cfg)
    return lt + rt, lq + rq


def align_linear(target: str, query: str, config: AlignConfig | None = None
                 ) -> PairwiseAlignment:
    """Optimal global alignment in O(min-side) memory
    (reference AlignLinear, LinearAlignment.cpp)."""
    cfg = config or AlignConfig()
    if cfg.mode != GLOBAL:
        raise ValueError("align_linear is global-only "
                         "(reference AlignLinear, LinearAlignment.cpp:93)")
    gt, gq = _hirschberg(target, query, cfg)
    return PairwiseAlignment(gt, gq)


def align_linear_score(target: str, query: str,
                       config: AlignConfig | None = None) -> int:
    """Global alignment score in O(J) memory."""
    cfg = config or AlignConfig()
    return int(_last_row(query, target, cfg.params)[-1])
