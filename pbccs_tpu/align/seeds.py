"""k-mer seed finding + sparse dynamic programming (SDP) seed chaining.

Behavior parity: reference include/pacbio/ccs/SparseAlignment.h (FindSeeds
over a q-gram index with homopolymer-seed masking, SparseAlign<K>) and
src/ChainSeeds.cpp (LinkScore with matches/mismatches/indels accounting,
positive-gain chaining, traceback of the best chain).

Vectorized re-design: the reference walks a SeqAn q-gram index k-mer by
k-mer and keeps sweep-line visibility sets to bound candidate predecessors
(an O(n log n) CPU trick).  Here k-mer hashes for both sequences are
computed as one polynomial matmul, matched via argsort + searchsorted, and
the chain DP runs row-group by row-group with numpy-broadcast LinkScore
over all previous seeds — simpler, cache-friendly, and exact (it searches
a superset of the reference's candidate lists, so chains are never worse).

Seed convention matches the reference: a seed is (pos1, pos2) = start of a
shared k-mer in seq1 ("H", the target/consensus) and seq2 ("V", the
query/read).
"""

from __future__ import annotations

import numpy as np

DEFAULT_SEED_SIZE = 10  # reference FindSeedsConfig<TSize = 10>


def kmer_hashes(codes: np.ndarray, k: int) -> np.ndarray:
    """Base-4 polynomial hash of every k-mer; windows containing non-ACGT
    codes hash to -1."""
    codes = np.asarray(codes, np.int64)
    n = len(codes) - k + 1
    if n <= 0:
        return np.zeros(0, np.int64)
    win = np.lib.stride_tricks.sliding_window_view(codes, k)
    powers = 4 ** np.arange(k - 1, -1, -1, dtype=np.int64)
    h = win @ powers
    return np.where((win >= 0).all(axis=1) & (win < 4).all(axis=1), h, -1)


def _homopolymer_hashes(k: int) -> np.ndarray:
    """Hashes of AAAA.., CCCC.., GGGG.., TTTT.. (reference HpHasher)."""
    powers = 4 ** np.arange(k - 1, -1, -1, dtype=np.int64)
    return np.array([powers.sum() * b for b in range(4)], np.int64)


def find_seeds(seq1: np.ndarray, seq2: np.ndarray,
               k: int = DEFAULT_SEED_SIZE,
               max_occ: int | None = None) -> np.ndarray:
    """(N, 2) int32 array of (pos1, pos2) shared-k-mer seeds, homopolymer
    k-mers masked (reference FindSeeds, SparseAlignment.h:100-137).
    `max_occ` additionally masks k-mers occurring more than that many times
    in seq1 (the reference FilterSeeds quota intent) -- used by the POA
    banding to bound seed growth on repetitive long inserts."""
    h1 = kmer_hashes(seq1, k)
    h2 = kmer_hashes(seq2, k)
    if not len(h1) or not len(h2):
        return np.zeros((0, 2), np.int32)
    hp = _homopolymer_hashes(k)
    ok2 = (h2 >= 0) & ~np.isin(h2, hp)

    order = np.argsort(h1, kind="stable")
    sorted_h1 = h1[order]
    lo = np.searchsorted(sorted_h1, h2, side="left")
    hi = np.searchsorted(sorted_h1, h2, side="right")
    counts = np.where(ok2, hi - lo, 0)
    if max_occ is not None:
        counts = np.where(counts > max_occ, 0, counts)
    total = int(counts.sum())
    if total == 0:
        return np.zeros((0, 2), np.int32)
    j_idx = np.repeat(np.arange(len(h2), dtype=np.int32), counts)
    # occurrence offsets within each j's [lo, hi) run
    offs = np.arange(total, dtype=np.int64) - np.repeat(
        np.cumsum(counts) - counts, counts)
    i_idx = order[np.repeat(lo, counts) + offs].astype(np.int32)
    return np.stack([i_idx, j_idx], axis=1)


def chain_seeds(seeds: np.ndarray, k: int,
                match_reward: int = 3) -> np.ndarray:
    """Best positive-gain chain through the seeds (reference ChainSeeds,
    ChainSeeds.cpp:203-361; LinkScore at :104-122).  Returns the chained
    subset of `seeds`, in chain order.  Dispatches to the native C++
    implementation (native/pbccs_native.cpp) when built; the numpy path
    below is the reference implementation.

    Seeds in the same row (equal pos2) never link to each other; a link's
    gain is matchReward*matches - indels - mismatches over the implied
    extension and must leave the running score positive."""
    n = len(seeds)
    if n == 0:
        return np.zeros((0, 2), np.int32)
    from pbccs_tpu import native
    nat = native.chain_seeds(seeds, k, match_reward)
    if nat is not None:
        return nat
    s = seeds[np.lexsort((seeds[:, 0], seeds[:, 1]))].astype(np.int64)
    H, V = s[:, 0], s[:, 1]
    diag = H - V
    scores = np.full(n, k, np.int64)
    pred = np.full(n, -1, np.int64)

    # row groups (equal V): link each group against all earlier rows at once
    row_starts = np.flatnonzero(np.r_[True, V[1:] != V[:-1]])
    row_ends = np.r_[row_starts[1:], n]
    for lo, hi in zip(row_starts, row_ends):
        if lo == 0:
            continue
        aH, aV, aD = H[lo:hi, None], V[lo:hi, None], diag[lo:hi, None]
        bH, bV, bD = H[None, :lo], V[None, :lo], diag[None, :lo]
        fwd = np.minimum(aH - bH, aV - bV)
        matches = k - np.maximum(0, k - fwd)
        link = (match_reward * matches - np.abs(aD - bD) - (fwd - matches))
        # links must advance in seq1 too: every reference candidate list
        # (colSet / sweep-above / visible-left) has bH < aH, which keeps
        # chain anchors strictly increasing in both coordinates
        link = np.where(bH < aH, link, np.int64(-(2 ** 40)))
        cand = scores[None, :lo] + link
        # prefer the nearest predecessor on ties (the reference's sweep
        # structure links adjacent overlapping seeds, keeping anchors dense)
        best = lo - 1 - cand[:, ::-1].argmax(axis=1)
        best_score = cand[np.arange(hi - lo), best]
        take = best_score > 0
        scores[lo:hi] = np.where(take, best_score, k)
        pred[lo:hi] = np.where(take, best, -1)

    linked = pred >= 0
    if not linked.any():
        # no positive-gain link anywhere -> no chain (reference ChainSeeds
        # only tracks chain ends that were linked, ChainSeeds.cpp:296-305)
        return np.zeros((0, 2), np.int32)
    end = int(np.where(linked, scores, np.int64(-1)).argmax())
    chain = []
    while end >= 0:
        chain.append(end)
        end = int(pred[end])
    chain.reverse()
    return s[chain].astype(np.int32)


def sparse_align(seq1: np.ndarray, seq2: np.ndarray,
                 k: int = DEFAULT_SEED_SIZE,
                 max_occ: int | None = None) -> np.ndarray:
    """Find + chain seeds between two int8 base vectors (reference
    SparseAlign<TSize>, SparseAlignment.h:294-313); (N, 2) (pos1, pos2)."""
    return chain_seeds(find_seeds(seq1, seq2, k, max_occ), k)


def anchor_bands(chain: np.ndarray, len1: int, len2: int,
                 width: int = 30) -> np.ndarray:
    """(len1, 2) per-seq1-position [begin, end) alignable ranges on seq2,
    from chain anchors +- width, monotonically closed.

    This is the banding product of the reference's SdpRangeFinder
    (ConsensusCore/src/C++/Poa/RangeFinder.cpp:72-167): direct ranges
    around anchors, then forward/reverse closure so every position has a
    nonempty, monotone range."""
    lo = np.full(len1, np.int64(len2))
    hi = np.zeros(len1, np.int64)
    if len(chain):
        i, j = chain[:, 0].astype(np.int64), chain[:, 1].astype(np.int64)
        np.minimum.at(lo, i, np.maximum(j - width, 0))
        np.maximum.at(hi, i, np.minimum(j + width, len2))
    # forward closure: ranges never shrink backwards; fill gaps from
    # predecessors, then reverse closure from successors
    have = hi > 0
    if not have.any():
        return np.stack([np.zeros(len1, np.int64),
                         np.full(len1, len2, np.int64)], axis=1)
    lo = np.where(have, lo, np.int64(0))
    np.maximum.accumulate(lo, out=lo)
    hi = np.where(have, hi, np.int64(len2))
    hi = hi[::-1]
    np.minimum.accumulate(hi, out=hi)
    hi = hi[::-1]
    hi = np.maximum(hi, lo + 1)
    return np.stack([lo, np.minimum(hi, len2)], axis=1)
