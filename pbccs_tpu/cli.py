"""`ccs`-equivalent command line driver.

    python -m pbccs_tpu.cli [OPTIONS] OUTPUT FILES...

Reads subreads from BAM (PacBio conventions) or FASTA (records named
movie/zmw[/s_e], grouped by ZMW), runs the consensus pipeline over a
bounded ordered work pipeline, and writes a CCS BAM plus a CSV yield
report.  Flags, defaults, CLI-level filters (whitelist, chemistry, SNR,
read score, pass count) and output tags mirror the reference driver
(reference src/main/ccs.cpp:284-519).
"""

from __future__ import annotations

import argparse
import math
import os
import sys

import numpy as np

from pbccs_tpu import __version__
from pbccs_tpu.io.bam import (
    BamDecodeError,
    BamHeader,
    BamReader,
    BamRecord,
    BamWriter,
    ReadGroupInfo,
    make_read_group_id,
)
from pbccs_tpu.io.fasta import flatten_fofn, read_fasta
from pbccs_tpu.io.report import write_report_file as write_results_report_file
from pbccs_tpu.models.arrow.params import encode_bases
from pbccs_tpu.pipeline import (
    Chunk,
    ConsensusSettings,
    Failure,
    ResultTally,
    Subread,
    process_chunks,
)
from pbccs_tpu.runtime.chemistry import verify_chemistry
from pbccs_tpu.runtime.logging import Logger, LogLevel, install_signal_handlers
from pbccs_tpu.runtime.whitelist import Whitelist
from pbccs_tpu.runtime.workqueue import WorkQueue

DESCRIPTION = ("Generate circular consensus sequences (ccs) from subreads "
               "-- TPU-native implementation.")

FASTA_EXTS = (".fa", ".fasta", ".fsa", ".fa.gz", ".fasta.gz", ".fsa.gz")


def add_consensus_args(p: argparse.ArgumentParser) -> None:
    """The consensus-gate flags shared verbatim by `ccs` and `ccs serve`
    (serve.server.build_serve_parser): one definition, one set of
    defaults, so the two drivers cannot desynchronize."""
    p.add_argument("--minSnr", type=float, default=4.0,
                   help="Minimum SNR of input subreads. Default = %(default)s")
    p.add_argument("--minReadScore", type=float, default=0.75,
                   help="Minimum read score of input subreads. Default = %(default)s")
    p.add_argument("--minLength", type=int, default=10,
                   help="Minimum length of subreads. Default = %(default)s")
    p.add_argument("--minPasses", type=int, default=3,
                   help="Minimum number of subreads required. Default = %(default)s")
    p.add_argument("--minPredictedAccuracy", type=float, default=0.90,
                   help="Minimum predicted accuracy. Default = %(default)s")
    p.add_argument("--minZScore", type=float, default=-5.0,
                   help="Minimum subread z-score; NaN disables. Default = %(default)s")
    p.add_argument("--maxDropFraction", type=float, default=0.34,
                   help="Maximum fraction of droppable subreads. Default = %(default)s")
    p.add_argument("--model", choices=("arrow", "quiver"), default="arrow",
                   help="Polish model family (default: arrow, the ccs "
                        "model; quiver is the QV-feature model -- reads "
                        "without QV tracks use flat default tracks).")
    p.add_argument("--degradeQuarantined", action="store_true",
                   help="Emit quarantined poison ZMWs (batch AND serial "
                        "polish failed) as draft-only consensus with a "
                        "`df` tag and capped QVs instead of dropping "
                        "them as Other.")


def add_resilience_args(p: argparse.ArgumentParser) -> None:
    """Fault-handling knobs shared by `ccs` and `ccs serve`."""
    p.add_argument("--faults", default=None, metavar="SPEC",
                   help="Arm deterministic fault injection (chaos "
                        "testing), e.g. 'polish.dispatch:error~m/3'. "
                        "See pbccs_tpu/resilience/faults.py for the "
                        "grammar; PBCCS_FAULTS is the env equivalent.")
    p.add_argument("--faultSeed", type=int, default=0,
                   help="Seed for probabilistic fault specs. "
                        "Default = %(default)s")
    p.add_argument("--polishTimeout", type=float, default=None,
                   metavar="SECONDS",
                   help="Watchdog deadline per device dispatch: a hung "
                        "polish becomes a structured timeout and the "
                        "affected ZMWs quarantine instead of stalling "
                        "the run (default: PBCCS_WATCHDOG_S, else off).")


def apply_resilience_args(args) -> None:
    from pbccs_tpu.resilience import faults, watchdog

    if args.faults is not None:
        faults.configure(args.faults, seed=args.faultSeed)
    if args.polishTimeout is not None:
        watchdog.configure(args.polishTimeout)


def consensus_settings_from_args(args) -> ConsensusSettings:
    return ConsensusSettings(
        min_length=args.minLength,
        min_passes=args.minPasses,
        min_snr=args.minSnr,
        min_predicted_accuracy=args.minPredictedAccuracy,
        min_zscore=args.minZScore,
        max_drop_fraction=args.maxDropFraction,
        model=args.model,
        degrade_quarantined=args.degradeQuarantined)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ccs", description=DESCRIPTION,
        epilog="`ccs serve [OPTIONS]` starts the long-lived online serving "
               "engine instead, and `ccs router [OPTIONS]` the "
               "multi-replica front door over N serve processes; both "
               "take --tlsCert/--tlsKey/--authTokens for a TLS + "
               "token-authenticated multi-tenant edge (see "
               "`ccs serve --help` / `ccs router --help`).")
    p.add_argument("--version", action="version", version=__version__)
    p.add_argument("--zmws", default="all",
                   help="ZMWs to process: all, or ranges like 1-3,5 or "
                        "movie:1-3,5;movie2:*. Default = %(default)s")
    add_consensus_args(p)
    p.add_argument("--numThreads", type=int, default=0,
                   help="Number of host pipeline threads (0 = auto); with "
                        "--devices it seeds the prepare pool unless "
                        "--prepareWorkers is given. Default = %(default)s")
    p.add_argument("--chunkSize", type=int, default=64,
                   help="ZMWs per work item; each work item polishes as one "
                        "lockstep device batch. Default = %(default)s")
    p.add_argument("--devices", type=int, default=1,
                   help="Polish across a device fleet (pbccs_tpu.sched): "
                        "N>1 uses the first N visible devices, 0 all of "
                        "them, 1 the legacy single-device WorkQueue "
                        "driver. Default = %(default)s")
    p.add_argument("--prepareWorkers", type=int, default=0,
                   help="Host prepare (POA draft) threads overlapping "
                        "in-flight device polishes in the scheduled "
                        "driver (0 = auto; only used with --devices). "
                        "Default = %(default)s")
    p.add_argument("--schedPolicy", choices=("sticky", "least", "roundrobin"),
                   default="sticky",
                   help="Device-fleet routing: sticky keeps a compiled-"
                        "shape bucket on the device that already compiled "
                        "it (least-loaded otherwise). "
                        "Default = %(default)s")
    p.add_argument("--logFile", default=None, help="Log to a file vs stderr.")
    p.add_argument("--logLevel", default="INFO",
                   help="TRACE..FATAL. Default = %(default)s")
    p.add_argument("--trace-out", dest="trace_out", default=None,
                   metavar="FILE",
                   help="Write a Chrome-trace/Perfetto JSON of per-ZMW "
                        "spans (filter/draft/polish/emit, wall vs "
                        "device-wait) to FILE.")
    p.add_argument("--profile-dir", dest="profile_dir", default=None,
                   metavar="DIR",
                   help="Capture a jax.profiler trace of the run into DIR "
                        "(TensorBoard/XProf format).")
    p.add_argument("--reportFile", default="ccs_report.csv",
                   help="Where to write the yield report. Default = %(default)s")
    p.add_argument("--perfLedger", default=None, metavar="PATH",
                   help="Append one schema-versioned NDJSON performance "
                        "record for this run (obs/ledger.py) to PATH: "
                        "compile/refine/padding counters, wall time, "
                        "peak RSS, governor interventions -- the record "
                        "tools/perf_gate.py defends baselines against. "
                        "Default: off.")
    p.add_argument("--tuneProfile", default=None, metavar="PATH|auto",
                   help="Apply a `ccs tune` host profile: tuned knob "
                        "defaults (band width, prepare workers, memory "
                        "budget, ...) resolved as explicit flag/env > "
                        "profile > hand-tuned constants.  `auto` scans "
                        "the committed profiles/ directory for this "
                        "host's fingerprint; a mismatched or corrupt "
                        "profile degrades to defaults with a note "
                        "(PBCCS_TUNE_PROFILE is the env equivalent). "
                        "Default: off.")
    p.add_argument("--checkpoint", default=None, metavar="FILE",
                   help="Journal completed chunks to FILE (NDJSON) so a "
                        "killed run can restart with --resume. Default: "
                        "off (--resume implies OUTPUT.ckpt).")
    p.add_argument("--resume", action="store_true",
                   help="Restore completed chunks from the checkpoint "
                        "journal and compute only the rest; the final "
                        "tally and output are identical to an "
                        "uninterrupted run.")
    p.add_argument("--memBudget", default=None, metavar="SIZE",
                   help="Host-memory budget for batch backlog in the "
                        "fleet driver (--devices != 1), e.g. 8G or "
                        "512M: the prepare pool throttles (visible as "
                        "ccs_resource_throttles_total, never a crash) "
                        "while prepared-batch bytes in flight would "
                        "exceed it.  Default: unbounded.")
    p.add_argument("--batchFallback", choices=("bisect", "serial"),
                   default="bisect",
                   help="Recovery when a lockstep polish batch fails: "
                        "bisect isolates the poison ZMW(s) in O(k log Z) "
                        "re-dispatches; serial re-runs the whole batch "
                        "per-ZMW (legacy). Default = %(default)s")
    add_resilience_args(p)
    p.add_argument("--decodePolicy", choices=("strict", "lenient", "salvage"),
                   default="strict",
                   help="BAM corruption handling: strict aborts on the "
                        "first corrupt byte (reference behavior); lenient "
                        "skips bad records and counts them; salvage "
                        "additionally resyncs past corrupt BGZF blocks so "
                        "one flipped bit costs <=64 KiB of input, not the "
                        "cell. Default = %(default)s")
    p.add_argument("--skipChemistryCheck", action="store_true",
                   help="Accept non-P6-C4 read groups (required for FASTA "
                        "input, which carries no chemistry metadata).")
    p.add_argument("output", help="Output BAM (or FASTA) path")
    p.add_argument("files", nargs="+", help="Input subread BAM/FASTA/FOFN files")
    return p


def _iter_fasta_chunks(path: str, log: Logger):
    """Group FASTA records named movie/zmw[/s_e] into per-ZMW chunks."""
    current: Chunk | None = None
    for name, seq in read_fasta(path):
        parts = name.split("/")
        try:
            movie, zmw = parts[0], int(parts[1])
        except (IndexError, ValueError):
            log.warn(f"skipping read {name}: name is not movie/zmw[/s_e]")
            continue
        zid = f"{movie}/{zmw}"
        if current is None or current.id != zid:
            if current is not None:
                yield current, None
            current = Chunk(zid, [], np.full(4, 8.0))
        current.reads.append(Subread.from_str(name, seq))
    if current is not None:
        yield current, None


def _iter_bam_chunks(path: str, log: Logger, policy: str = "strict"):
    """Group BAM subread records into per-ZMW chunks.

    Yields (chunk, read_group) so the caller can apply the chemistry gate."""
    reader = BamReader(path, policy=policy)
    rgs = {rg.id: rg for rg in reader.header.read_groups}
    current: Chunk | None = None
    current_rg: ReadGroupInfo | None = None
    for rec in reader:
        parts = rec.name.split("/")
        if len(parts) < 2:
            log.warn(f"skipping read {rec.name}: bad name")
            continue
        movie = parts[0]
        try:
            hole = int(rec.tags.get("zm", parts[1]))
        except (TypeError, ValueError):
            log.warn(f"skipping read {rec.name}: no usable ZMW number")
            continue
        zid = f"{movie}/{hole}"
        if current is None or current.id != zid:
            if current is not None:
                yield current, current_rg
            try:
                snr = np.asarray(rec.tags.get("sn", [8.0] * 4), np.float64)
            except (TypeError, ValueError):
                # validate_chunk downstream rejects the bad shape; here
                # only the crash matters (a string `sn` must not abort
                # a lenient run)
                snr = np.full(4, np.nan)
            current = Chunk(zid, [], snr)
            rg_id = rec.tags.get("RG", "")
            current_rg = rgs.get(rg_id)
        try:
            flags = int(rec.tags.get("cx", 3))
            accuracy = float(rec.tags.get("rq", 0.8))
        except (TypeError, ValueError) as e:
            # structurally valid record, semantically garbage tag values
            # (e.g. cx as a string): degrade the record, never the run
            if policy == "strict":
                raise BamDecodeError(
                    "bad_tag_value",
                    f"{rec.name}: cx/rq tag not numeric: {e}") from None
            # count through reader.stats so the end-of-file rejection
            # summary below includes these skips too
            reader.stats.count("bad_tag_value")
            log.warn(f"skipping read {rec.name}: cx/rq tag not numeric "
                     "[reason=bad_tag_value]")
            continue
        current.reads.append(Subread(rec.name, encode_bases(rec.seq),
                                     flags=flags, read_accuracy=accuracy))
    reader.close()
    stats = reader.stats
    if stats.total_invalid or stats.bytes_lost:
        by_reason = ", ".join(f"{k}={v}" for k, v
                              in sorted(stats.invalid_records.items()))
        log.warn(f"{path}: decode policy '{policy}' rejected "
                 f"{stats.total_invalid} record(s)/block(s) [{by_reason}], "
                 f"salvaged {stats.salvaged_blocks} block resync(s), "
                 f"{stats.bytes_lost} byte(s) lost"
                 + (" (input truncated mid-stream; pair with --resume "
                    "after re-fetching)" if stats.truncated else ""))
    if current is not None:
        yield current, current_rg


def _chunks_from_files(files, whitelist: Whitelist, args, log,
                       tally: ResultTally):
    """Apply CLI-level gates and yield batches of chunks."""
    from pbccs_tpu.io.validate import ChunkValidationError, validate_chunk

    batch: list[Chunk] = []
    for path in files:
        is_fasta = any(path.endswith(e) for e in FASTA_EXTS)
        it = (_iter_fasta_chunks(path, log) if is_fasta
              else _iter_bam_chunks(path, log, policy=args.decodePolicy))
        for chunk, rg in it:
            movie, hole_s = chunk.id.split("/")[:2]
            hole = int(hole_s)
            if not whitelist.contains(movie, hole):
                continue
            try:
                # the shared input contract (io.validate): the serve
                # front door rejects the same garbage with the same
                # reasons at `submit` (protocol.chunk_from_wire)
                validate_chunk(chunk)
            except ChunkValidationError as e:
                log.warn(f"rejecting ZMW {chunk.id}: {e} "
                         f"[reason={e.reason}]")
                continue
            if not args.skipChemistryCheck:
                if rg is None or not verify_chemistry(rg):
                    log.notice(f"Skipping ZMW {chunk.id}, invalid chemistry "
                               "(not P6/C4)")
                    continue
            if float(np.min(chunk.snr)) < args.minSnr:
                log.debug(f"Skipping ZMW {chunk.id}, fails SNR threshold")
                tally.tally(Failure.POOR_SNR)
                continue
            chunk.reads = [r for r in chunk.reads
                           if r.read_accuracy >= args.minReadScore]
            if len(chunk.reads) < args.minPasses:
                log.debug(f"Skipping ZMW {chunk.id}, insufficient passes")
                tally.tally(Failure.TOO_FEW_PASSES)
                continue
            batch.append(chunk)
            if len(batch) >= args.chunkSize:
                yield batch
                batch = []
    if batch:
        yield batch


def run(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "serve":
        # `ccs serve`: the long-lived online engine (pbccs_tpu/serve/)
        from pbccs_tpu.serve.server import run_serve

        return run_serve(argv[1:])
    if argv and argv[0] == "router":
        # `ccs router`: multi-replica front door (pbccs_tpu/serve/router)
        from pbccs_tpu.serve.router import run_router

        return run_router(argv[1:])
    if argv and argv[0] == "fleet":
        # `ccs fleet`: self-healing supervised fleet (serve/supervisor)
        from pbccs_tpu.serve.supervisor import run_fleet

        return run_fleet(argv[1:])
    if argv and argv[0] == "warmup":
        # `ccs warmup`: precompile a declared bucket menu (pbccs_tpu/sched)
        from pbccs_tpu.sched.warmup import run_warmup

        return run_warmup(argv[1:])
    if argv and argv[0] == "tune":
        # `ccs tune`: ledger-driven autotuner (pbccs_tpu/tune)
        from pbccs_tpu.tune.cli import run_tune

        return run_tune(argv[1:])
    if argv and argv[0] == "analyze":
        # `ccs analyze`: project-native static analysis (pbccs_tpu/analysis)
        from pbccs_tpu.analysis.cli import run_analyze

        return run_analyze(argv[1:])
    if argv and argv[0] == "top":
        # `ccs top`: live fleet console over a router/serve endpoint
        from pbccs_tpu.obs.console import run_top

        return run_top(argv[1:])
    if argv and argv[0] == "roofline":
        # `ccs roofline`: per-bucket CostCard bound vs measured report
        from pbccs_tpu.obs.roofline import run_roofline

        return run_roofline(argv[1:])
    args = build_parser().parse_args(argv)
    apply_resilience_args(args)

    from pbccs_tpu.runtime.cache import enable_compilation_cache

    enable_compilation_cache()

    log = Logger.default(Logger(
        stream=open(args.logFile, "w") if args.logFile else sys.stderr,
        level=LogLevel.from_string(args.logLevel)))
    install_signal_handlers(log)

    from pbccs_tpu.runtime import tuning

    # opt-in tuned-knob resolution (runtime/tuning.py): explicit flag /
    # env still beats anything a profile carries
    tuning.configure(args.tuneProfile, logger=log)

    try:
        whitelist = Whitelist(args.zmws)
    except ValueError as e:
        print(f"option --zmws: invalid specification: {e}", file=sys.stderr)
        return 2

    if args.devices < 0:
        print(f"option --devices: must be >= 0, got {args.devices}",
              file=sys.stderr)
        return 2

    if args.memBudget is not None:
        from pbccs_tpu.resilience.resources import parse_size

        try:
            args.memBudget = parse_size(args.memBudget)
            if args.memBudget < 1:
                # '0' / '0.5' parse but HostBudget would reject them
                # mid-run; surface the usage error before reading input
                raise ValueError(
                    f"must be >= 1 byte, got {args.memBudget}")
        except ValueError as e:
            print(f"option --memBudget: {e}", file=sys.stderr)
            return 2
    elif args.devices != 1:
        # resolution ladder: no explicit --memBudget, so a tuned
        # profile's byte budget (already stored in bytes) applies; the
        # single-device WorkQueue driver has no prepare backlog to gate
        args.memBudget = tuning.knob_int("mem_budget_bytes")

    settings = consensus_settings_from_args(args)

    files = flatten_fofn(args.files)
    for f in files:
        if not os.path.exists(f):
            print(f"input file does not exist: {f}", file=sys.stderr)
            return 2

    from pbccs_tpu.obs import profiling
    from pbccs_tpu.obs import trace as obs_trace
    from pbccs_tpu.runtime import timing

    # end-of-run observability: a measurement window over this run (the
    # summary table below reports its deltas) plus the opt-in capture
    # surfaces (--trace-out spans, --profile-dir jax profiler)
    run_window = timing.window()
    tracer = None
    if args.trace_out:
        tracer = obs_trace.Tracer()
        if not obs_trace.install_tracer(tracer):  # CAS: never hijack a
            # capture another owner (e.g. an in-process serve engine)
            # already has running
            log.warn("--trace-out ignored: another span capture is "
                     "already running in this process")
            tracer = None
    from pbccs_tpu.resilience.resources import OutputWriteError

    import time as time_mod

    t_run0 = time_mod.monotonic()
    tally = None
    try:
        with profiling.profile_capture(args.profile_dir):
            tally = _run_pipeline(args, files, whitelist, settings, log)
    except OutputWriteError as e:
        # a full disk is an OPERATIONAL failure, not a bug: report what
        # was durably written and how to resume, exit nonzero without a
        # traceback.  The checkpoint journal (if any) keeps every
        # completed chunk, so a rerun with --resume after freeing space
        # completes byte-identically.
        log.error(f"output failure: {e}")
        print(f"ccs: {e}\n"
              "ccs: free disk space and re-run (add --resume to restore "
              "completed chunks from the checkpoint journal)",
              file=sys.stderr)
        log.flush()
        return 1
    finally:
        if tracer is not None:
            obs_trace.clear_tracer(tracer)
            tracer.write_json(args.trace_out)
            log.info(f"trace spans written to {args.trace_out}")

    from pbccs_tpu.obs.metrics import default_registry

    summary = default_registry().summary_table(run_window)
    log.info("run metrics:\n" + summary)
    if args.perfLedger:
        # one perf-ledger record per run: the registry deltas over this
        # run's window + what only the driver knows (wall, yield)
        from pbccs_tpu.obs.ledger import PerfLedger, run_record

        ledger = PerfLedger(args.perfLedger, logger=log)
        ledger.append(run_record(
            run_window, kind="batch_run", source="ccs",
            workload={"files": [os.path.basename(f) for f in files],
                      "chunk_size": args.chunkSize,
                      "devices": args.devices,
                      "model": args.model},
            wall_s=time_mod.monotonic() - t_run0,
            zmws=tally.total if tally is not None else None,
            results=len(tally.results) if tally is not None else None))
        ledger.close()
        log.info(f"perf ledger record appended to {args.perfLedger}")
    log.flush()
    return 0


def _run_pipeline(args, files, whitelist, settings, log) -> ResultTally:
    """The reader -> WorkQueue -> batched polish -> writer body of a CLI
    run (split from run() so the observability capture scopes wrap it)."""
    # Default to at least 2 workers even on a 1-core host: a worker
    # blocks on the device with the GIL released for most of a batch
    # polish, so a second worker drafts the NEXT batch (host POA) during
    # that wait -- the reference's reader/worker/writer overlap
    # (ccs.cpp:388-499) re-expressed for a device-bound polish stage.
    n_threads = args.numThreads or max(2, min(8, os.cpu_count() or 1))
    tally = ResultTally()

    # collect movie names for the output header
    movies: dict[str, ReadGroupInfo] = {}

    def writer_record(result) -> BamRecord:
        movie = result.id.split("/")[0]
        hole = int(result.id.split("/")[1])
        return BamRecord(
            name=f"{result.id}/ccs",
            seq=result.sequence,
            qual=result.qualities,
            tags={
                "RG": make_read_group_id(movie, "CCS"),
                "zm": hole,
                "np": int(result.num_passes),
                "rq": int(1000 * result.predicted_accuracy),
                "sn": [float(s) for s in result.snr],
                "pq": float(result.predicted_accuracy),
                "za": float(result.avg_zscore),
                "zs": [float(z) if math.isfinite(z) else 0.0
                       for z in result.zscores],
                "rs": [int(c) for c in result.status_counts],
                # draft-only degradation marker (resilience.quarantine):
                # the sequence is the unpolished POA draft, QVs capped
                **({"df": 1} if result.draft_only else {}),
            })

    to_fasta = any(args.output.endswith(e) for e in (".fa", ".fasta", ".fsa"))

    from pbccs_tpu.obs import trace as obs_trace
    from pbccs_tpu.runtime import timing

    # The work queue's max_pending bounds results not yet CONSUMED, so the
    # consumer must run concurrently with the produce loop (the reference's
    # reader/worker/writer overlap, ccs.cpp:388-499) -- a produce-everything-
    # then-consume loop would deadlock once the pipeline fills.
    import threading

    # checkpoint journal: restore completed chunks (--resume) and record
    # each chunk as its results are consumed, in submission order, so a
    # killed run loses at most the in-flight chunks
    journal = None
    restored: dict[int, ResultTally] = {}
    ckpt_path = args.checkpoint or (args.output + ".ckpt"
                                    if args.resume else None)
    if ckpt_path:
        from pbccs_tpu.resilience.checkpoint import (
            CheckpointJournal,
            run_fingerprint,
        )

        # every knob that changes chunk COMPOSITION must fingerprint:
        # minReadScore filters reads and skipChemistryCheck drops ZMWs
        # before batching (the rest ride in via settings/files)
        fp = run_fingerprint(
            files, args.chunkSize, settings,
            extra={"zmws": args.zmws,
                   "min_read_score": args.minReadScore,
                   "skip_chemistry_check": bool(args.skipChemistryCheck)})
        journal = CheckpointJournal(ckpt_path, logger=log)
        if args.resume:
            restored = journal.load(fp)
            # output order must match an uninterrupted run: restored
            # chunks splice ahead of recomputed ones, so only a
            # CONTIGUOUS prefix is usable (a dropped mid-journal record
            # invalidates everything after it -- recomputed, not stale)
            k = 0
            while k in restored:
                k += 1
            if len(restored) > k:
                log.warn(f"resume: journal has a gap at chunk {k}; "
                         f"recomputing {len(restored) - k} chunk(s) "
                         "after it to preserve output order")
            restored = {i: t for i, t in restored.items() if i < k}
        journal.start(fp, resume=args.resume and bool(restored))

    def _read_batches(gate_tally: ResultTally):
        """Shared reader loop of BOTH drivers: stream (idx, batch) with
        read-stage timing and output-header movie registration.  CLI-gate
        skips tally into `gate_tally` (the fleet driver passes a separate
        one because this generator runs on its feeder thread)."""
        it = iter(_chunks_from_files(files, whitelist, args, log,
                                     gate_tally))
        idx = -1
        while True:
            with timing.stage("read"):
                batch = next(it, None)
            if batch is None:
                return
            idx += 1
            for chunk in batch:
                movie = chunk.id.split("/")[0]
                movies.setdefault(movie, ReadGroupInfo(movie, "CCS"))
            yield idx, batch

    if args.devices != 1:
        # Device-fleet scheduler (pbccs_tpu/sched): host prepare workers
        # overlap in-flight device polishes and batches fan out across
        # the pool with sticky bucket routing.  Batch composition and
        # shape derivation are IDENTICAL to the WorkQueue driver (same
        # --chunkSize groups, same effective_shapes), so the output is
        # byte-identical to a --devices 1 run.
        from pbccs_tpu.sched import (DevicePool, DevicePoolConfig,
                                     select_devices)
        from pbccs_tpu.sched.executor import ScheduledPipeline

        devs = select_devices(args.devices)
        # --numThreads sizes the legacy WorkQueue driver; in fleet mode
        # it seeds the host prepare pool instead of being silently
        # dropped (an explicit --prepareWorkers still wins).  A tuned
        # profile slots between the explicit flags and the auto default
        # (the runtime/tuning.py resolution ladder).
        from pbccs_tpu.runtime import tuning

        prep_workers = (args.prepareWorkers or args.numThreads
                        or tuning.knob_int("prepare_workers")
                        or max(2, min(4, os.cpu_count() or 1)))
        # --memBudget: byte-bound the prepared-batch backlog (prep pool
        # + parked results) so a full-cell stream cannot outrun the
        # devices into the OOM killer (resilience.resources.HostBudget)
        budget = None
        if args.memBudget is not None:
            from pbccs_tpu.resilience.resources import HostBudget

            budget = HostBudget(args.memBudget, logger=log)
        pool = DevicePool(devs, DevicePoolConfig(policy=args.schedPolicy),
                          logger=log)
        pipe = ScheduledPipeline(pool, settings,
                                 prepare_workers=prep_workers,
                                 on_error=args.batchFallback,
                                 budget=budget, logger=log)

        # the reader runs on the pipeline's feeder thread, so its
        # CLI-gate skips tally into their own ResultTally (merged below)
        # instead of racing the main thread's result merges;
        # journal-restored chunks ride through the scheduler as
        # precomputed tallies so they merge at their index slot
        gate_tally = ResultTally()
        items = ((idx, batch, restored.get(idx))
                 for idx, batch in _read_batches(gate_tally))
        try:
            for idx, sub_tally in pipe.run(items):
                tally.merge(sub_tally)
                if journal is not None and idx not in restored:
                    journal.record_chunk(idx, sub_tally)
        except BaseException:
            # the run is already doomed: fail queued batches fast
            # (PoolClosed) instead of polishing minutes of device work
            # whose results nothing will consume
            pool.close(wait=False)
            raise
        pool.close()
        tally.merge(gate_tally)
    else:
        if args.memBudget is not None:
            log.warn("--memBudget gates the fleet driver's prepare "
                     "backlog; the single-device WorkQueue driver "
                     "(--devices 1) is already bounded by --numThreads "
                     "work items, so the flag is ignored here")

        def _run_batch(idx, batch):
            return idx, process_chunks(batch, settings,
                                       on_error=args.batchFallback)

        consumed = ResultTally()
        consumer_error: list[BaseException] = []

        with WorkQueue(n_threads) as wq:
            def _consume():
                try:
                    for idx, sub_tally in wq.results():
                        consumed.merge(sub_tally)
                        if journal is not None:
                            journal.record_chunk(idx, sub_tally)
                except BaseException as e:  # noqa: BLE001 -- re-raised below
                    consumer_error.append(e)

            consumer = threading.Thread(target=_consume,
                                        name="pbccs-consumer")
            consumer.start()
            for idx, batch in _read_batches(tally):
                if idx in restored:
                    # journaled chunks restore in index order BEFORE any
                    # newly computed chunk merges (journal records form a
                    # prefix), so output order matches an uninterrupted run
                    tally.merge(restored[idx])
                    continue
                with timing.stage("queue"):
                    wq.produce(_run_batch, idx, batch)
            wq.finalize()
            consumer.join()
        if consumer_error:
            raise consumer_error[0]
        tally.merge(consumed)
    log.info(f"processed {tally.total} ZMWs: "
             f"{tally.counts[Failure.SUCCESS]} successes")

    if to_fasta:
        from pbccs_tpu.io.fasta import write_fasta
        with obs_trace.span("emit", results=len(tally.results)), \
                timing.stage("write"):
            write_fasta(args.output,
                        ((f"{r.id}/ccs", r.sequence) for r in tally.results))
    else:
        header = BamHeader(read_groups=list(movies.values()),
                           program_lines=[
                               f"@PG\tID:ccs-{__version__}\tPN:ccs\t"
                               f"VN:{__version__}"])
        # companion .pbi, as the reference's PbiBuilder does alongside the
        # output BAM (reference src/main/ccs.cpp:120, 380)
        from pbccs_tpu.io.pbi import PbiBuilder, read_group_numeric_id
        from pbccs_tpu.resilience.resources import OutputWriteError
        uposs = []
        with obs_trace.span("emit", results=len(tally.results)), \
                timing.stage("write"):
            with BamWriter(args.output, header) as bw:
                for result in tally.results:
                    uposs.append(bw.write(writer_record(result)))
                bw_handle = bw
            # PbiBuilder publishes atomically itself (tmp+fsync+rename
            # inside close(), OutputWriteError on ENOSPC) -- the same
            # contract as the BamWriter beside it
            pbi_path = args.output + ".pbi"
            with PbiBuilder(pbi_path) as pbi:
                for result, upos in zip(tally.results, uposs):
                    movie = result.id.split("/")[0]
                    hole = int(result.id.split("/")[1])
                    pbi.add_record(
                        read_group_numeric_id(
                            make_read_group_id(movie, "CCS")),
                        -1, -1, hole, result.predicted_accuracy, 0,
                        bw_handle.voffset(upos))

    write_results_report_file(args.reportFile, tally)
    if journal is not None:
        # only a run whose OUTPUTS landed needs no resume point: a
        # disk-full failure writing the BAM/report above keeps the
        # journal, so --resume restores every completed chunk and
        # re-emits byte-identically once space is freed.  (A later
        # --resume against fresh inputs still cannot splice stale
        # results -- the fingerprint refuses it.)
        journal.remove()
    return tally


def main() -> None:
    sys.exit(run())


if __name__ == "__main__":
    main()
