"""Partial-order alignment (POA) draft stage -- host implementation.

The draft stage is graph-shaped and branchy, so (like the reference, which
keeps it a small fraction of per-ZMW cost next to polishing) it runs on the
host; the polish stage on device then dominates.  Column fills are
vectorized over read positions with a prefix-max trick for the within-column
insertion recurrence, so the Python layer does O(V) vector ops, not O(V*I)
scalar ops.  A native C++ engine is the planned drop-in replacement.

Semantics parity (re-derived, not transcribed):
  * LOCAL alignment of each read against the DAG, params
    match=+3, mismatch=-5, insert=-4, delete=-4
    (reference PoaConsensus.cpp:54-59 DefaultPoaConfig).
  * Each read is tried in both orientations; the better-scoring one is
    committed if its score >= 0 (reference src/SparsePoa.cpp:96-137).
  * Threading: every read base maps to a graph vertex (matched vertices are
    reused and their read count incremented; mismatches/inserts/unaligned
    prefixes+suffixes fork new vertex chains)
    (reference PoaGraphTraversals.cpp:227-395 tracebackAndThread).
  * Spanning-read tagging over the aligned span
    (reference PoaGraphTraversals.cpp:106-113 tagSpan).
  * Consensus = best-sum path over vertex scores
    2*reads - max(spanning, min_coverage) - 1e-4, DP over topological order
    (reference PoaGraphTraversals.cpp:116-192 consensusPath).
"""

from __future__ import annotations

import dataclasses
from collections import deque

import numpy as np

MATCH_S, MISMATCH_S, INSERT_S, DELETE_S = 3.0, -5.0, -4.0, -4.0

# traceback move codes
_START, _MATCH, _DELETE, _EXTRA = 0, 1, 2, 3


@dataclasses.dataclass
class AlignmentPlan:
    """Result of a tentative read-vs-graph alignment (TryAddRead)."""

    score: float
    read: np.ndarray
    reverse_complemented: bool
    best_vertex: int
    best_row: int
    cols: np.ndarray       # (n_idx, I+1) scores per aligned vertex column
    match_pred: np.ndarray  # (n_idx, I+1) best predecessor for match move
    del_pred: np.ndarray    # (n_idx, I+1) best predecessor for delete move
    ranges: np.ndarray | None = None  # (n_idx, 2) banded DP rows, None=full


class PoaGraph:
    """A DAG of single bases with per-vertex read/spanning counts."""

    def __init__(self):
        self.base: list[int] = []
        self.nreads: list[int] = []
        self.spanning: list[int] = []
        self.preds: list[list[int]] = []
        self.succs: list[list[int]] = []
        self.n_reads = 0

    # ------------------------------------------------------------- plumbing

    def _add_vertex(self, base: int) -> int:
        # any graph mutation invalidates the consensus-path vertex scores
        # (find_possible_variants must see scores for the current topology)
        if hasattr(self, "vertex_score"):
            del self.vertex_score
        v = len(self.base)
        self.base.append(int(base))
        self.nreads.append(1)
        self.spanning.append(0)
        self.preds.append([])
        self.succs.append([])
        return v

    def _add_edge(self, u: int, v: int) -> None:
        if u == v:
            return
        if v not in self.succs[u]:
            self.succs[u].append(v)
            self.preds[v].append(u)

    def topo_order(self) -> list[int]:
        n = len(self.base)
        indeg = np.zeros(n, np.int64)
        for v in range(n):
            indeg[v] = len(self.preds[v])
        q = deque(v for v in range(n) if indeg[v] == 0)
        order = []
        while q:
            v = q.popleft()
            order.append(v)
            for w in self.succs[v]:
                indeg[w] -= 1
                if indeg[w] == 0:
                    q.append(w)
        assert len(order) == n, "cycle in POA graph"
        return order

    # ------------------------------------------------------------ threading

    def add_first_read(self, read: np.ndarray) -> list[int]:
        """threadFirstRead (PoaGraphTraversals.cpp:194-225)."""
        path = []
        prev = -1
        for b in read:
            v = self._add_vertex(b)
            if prev >= 0:
                self._add_edge(prev, v)
            path.append(v)
            prev = v
        self.n_reads += 1
        self._tag_span(path[0], path[-1])
        return path

    def try_add_read(self, read: np.ndarray, reverse_complemented: bool = False,
                     ranges: np.ndarray | None = None,
                     order: list[int] | None = None) -> AlignmentPlan:
        """LOCAL-align `read` against the current graph without mutating it.

        `ranges` (from poa.banding.sdp_vertex_ranges) bands each vertex's
        column to DP rows [lo, hi); cells outside the band keep value 0 =
        "a LOCAL alignment may start here", so the banded fill stays a
        well-formed LOCAL DP and compute drops to O(V * band).  Storage
        here remains full-width (the native engine stores only the bands;
        this fallback favors simplicity).  `order` lets the caller reuse an
        already-computed topological order."""
        I = len(read)
        order = self.topo_order() if order is None else order
        n = len(self.base)

        cols = np.zeros((n, I + 1), np.float32)
        match_pred = np.full((n, I + 1), -1, np.int64)
        del_pred = np.full((n, I + 1), -1, np.int64)
        zeros = np.zeros(I + 1, np.float32)
        ramp = INSERT_S * np.arange(I + 1, dtype=np.float32)
        subs = np.where(read[None, :] == np.arange(4)[:, None],
                        MATCH_S, MISMATCH_S).astype(np.float32)

        for v in order:
            lo, hi = (0, I + 1) if ranges is None else map(int, ranges[v])
            L = hi - lo
            s = max(lo, 1)  # first row with a match/extra move
            sub = subs[self.base[v]] if 0 <= self.base[v] < 4 \
                else np.full(I, MISMATCH_S, np.float32)
            best_m = np.full(L, -np.inf, np.float32)
            best_d = np.full(L, -np.inf, np.float32)
            bm_pred = np.full(L, -1, np.int64)
            bd_pred = np.full(L, -1, np.int64)
            preds = self.preds[v] or [-1]
            for p in preds:
                pc = zeros if p < 0 else cols[p]
                m = np.full(L, -np.inf, np.float32)
                m[s - lo:] = pc[s - 1: hi - 1] + sub[s - 1: hi - 1]
                upd = m > best_m
                best_m = np.where(upd, m, best_m)
                bm_pred[upd] = p
                d = pc[lo:hi] + DELETE_S
                upd = d > best_d
                best_d = np.where(upd, d, best_d)
                bd_pred[upd] = p
            # cell = max(0, match, delete, extra) where extra chains within
            # the column: solved by prefix-max of (b - insert_ramp).
            b = np.maximum(0.0, np.maximum(best_m, best_d))
            cols[v, lo:hi] = np.maximum.accumulate(b - ramp[lo:hi]) + ramp[lo:hi]
            match_pred[v, lo:hi] = bm_pred
            del_pred[v, lo:hi] = bd_pred

        # best local end anywhere (EndMove, LOCAL)
        flat = int(np.argmax(cols))
        best_vertex, best_row = divmod(flat, I + 1)
        score = float(cols[best_vertex, best_row])
        return AlignmentPlan(score, np.asarray(read), reverse_complemented,
                             best_vertex, best_row, cols, match_pred, del_pred,
                             ranges)

    def commit_add(self, plan: AlignmentPlan) -> list[int]:
        """Thread the read along the traceback of `plan`; returns the read
        path (one vertex per read base).

        Mirrors tracebackAndThread (PoaGraphTraversals.cpp:227-395): matched
        vertices are reused; mismatch/extra bases fork new vertices chained
        toward `fork` (the next vertex of the read's path); deletions skip
        graph vertices; unaligned read prefix/suffix become fresh chains."""
        read = plan.read
        I = len(read)
        path = [-1] * I
        cols = plan.cols

        def new_chain_vertex(i, fork):
            nv = self._add_vertex(read[i - 1])
            if fork >= 0:
                self._add_edge(nv, fork)
            path[i - 1] = nv
            return nv

        # thread unaligned suffix (EndMove, LOCAL)
        fork = -1
        i = I
        while i > plan.best_row:
            fork = new_chain_vertex(i, fork)
            i -= 1

        v = plan.best_vertex
        prev_visited = -1  # reference's `v`: vertex last visited in traceback
        while v >= 0 and i >= 0:
            if plan.ranges is not None and not (
                    plan.ranges[v, 0] <= i < plan.ranges[v, 1]):
                break  # walked outside the band: treat as StartMove
            cell = cols[v, i]
            vb = self.base[v]
            mp = plan.match_pred[v, i]
            dp = plan.del_pred[v, i]
            if i > 0:
                sub = MATCH_S if read[i - 1] == vb else MISMATCH_S
                m_val = (cols[mp, i - 1] if mp >= 0 else 0.0) + sub
                e_val = cols[v, i - 1] + INSERT_S
            else:
                m_val = e_val = -np.inf
            d_val = (cols[dp, i] if dp >= 0 else 0.0) + DELETE_S

            if i > 0 and cell == m_val:
                if read[i - 1] == vb:
                    if hasattr(self, "vertex_score"):
                        del self.vertex_score  # coverage changed
                    self.nreads[v] += 1
                    if fork >= 0:
                        self._add_edge(v, fork)
                        fork = -1
                    path[i - 1] = v
                else:
                    if fork < 0:
                        fork = prev_visited
                    fork = new_chain_vertex(i, fork)
                i -= 1
                prev_visited = v
                v = mp
            elif cell == d_val and dp >= 0:
                if fork < 0:
                    fork = prev_visited
                prev_visited = v
                v = dp
            elif i > 0 and cell == e_val:
                if fork < 0:
                    fork = prev_visited
                fork = new_chain_vertex(i, fork)
                i -= 1
            else:
                break  # StartMove: alignment starts here

        # thread remaining prefix as a new chain
        if i > 0 and fork < 0:
            fork = prev_visited
        while i > 0:
            fork = new_chain_vertex(i, fork)
            i -= 1

        self.n_reads += 1
        self._tag_span(path[0], plan.best_vertex)
        return path

    def _tag_span(self, start: int, end: int) -> None:
        """SpanningReads++ on every vertex lying on a path start->end."""
        fwd = self._reachable(start, self.succs)
        bwd = self._reachable(end, self.preds)
        for v in fwd & bwd:
            self.spanning[v] += 1

    def _reachable(self, root: int, adj: list[list[int]]) -> set[int]:
        seen = {root}
        stack = [root]
        while stack:
            u = stack.pop()
            for w in adj[u]:
                if w not in seen:
                    seen.add(w)
                    stack.append(w)
        return seen

    # ------------------------------------------------------------ consensus

    def consensus_path(self, min_coverage: int) -> list[int]:
        order = self.topo_order()
        reach = {}
        best_prev = {}
        best_v, best_score = -1, -np.inf
        self.vertex_score = np.zeros(len(self.base), np.float32)
        for v in order:
            score = 2.0 * self.nreads[v] - max(self.spanning[v], min_coverage) - 1e-4
            self.vertex_score[v] = score
            r = score
            bp = -1
            for p in self.preds[v]:
                c = score + reach[p]
                if c > r:
                    r = c
                    bp = p
            reach[v] = r
            best_prev[v] = bp
            if r > best_score or (r == best_score and v < best_v):
                best_score = r
                best_v = v
        path = []
        v = best_v
        while v >= 0:
            path.append(v)
            v = best_prev[v]
        path.reverse()
        return path

    def find_possible_variants(self, best_path: list[int]):
        """Scored candidate variants of the consensus path read off the graph
        topology (parity: PoaGraphImpl::findPossibleVariants, reference
        PoaGraphTraversals.cpp:396-498): for each interior path vertex,

        * an edge path[i] -> path[i+2] suggests DELETING path position i+1
          (score = -vertex score of the skipped vertex);
        * a vertex that is both child of path[i] and parent of path[i+1]
          suggests INSERTING its base before position i+1;
        * an off-path vertex that is child of path[i] and parent of
          path[i+2] suggests SUBSTITUTING it at position i+1.

        Ties between candidate vertices break toward the lower vertex id.
        Requires consensus_path() to have been run (vertex scores).
        Returns a list of scored mutations in template coordinates.
        """
        from pbccs_tpu.models.arrow import mutations as mutlib

        if not hasattr(self, "vertex_score"):
            raise RuntimeError(
                "run consensus_path() (after the last graph change) before "
                "find_possible_variants()")
        variants: list[mutlib.Mutation] = []
        for i in range(2, len(best_path) - 2):
            v = best_path[i]
            children = self.succs[v]

            if best_path[i + 2] in children:
                score = -float(self.vertex_score[best_path[i + 1]])
                variants.append(
                    mutlib.deletion(i + 1).with_score(score))

            look_back = self.preds[best_path[i + 1]]
            best = -1
            for c in children:
                if c in look_back and (
                        best < 0
                        or self.vertex_score[c] > self.vertex_score[best]
                        or (self.vertex_score[c] == self.vertex_score[best]
                            and c < best)):
                    best = c
            if best >= 0:
                variants.append(
                    mutlib.insertion(i + 1, self.base[best])
                    .with_score(float(self.vertex_score[best])))

            look_back = self.preds[best_path[i + 2]]
            best = -1
            for c in children:
                if c == best_path[i + 1]:
                    continue
                if c in look_back and (
                        best < 0
                        or self.vertex_score[c] > self.vertex_score[best]
                        or (self.vertex_score[c] == self.vertex_score[best]
                            and c < best)):
                    best = c
            if best >= 0:
                variants.append(
                    mutlib.substitution(i + 1, self.base[best])
                    .with_score(float(self.vertex_score[best])))
        return variants


    def write_graphviz(self, fh, consensus_vertices=None) -> None:
        """Dump the DAG in GraphViz dot format (parity:
        PoaGraph::WriteGraphVizFile, reference ConsensusCore/src/C++/Poa/
        PoaGraph.cpp / PoaGraphImpl::writeGraphVizFile): one node per
        vertex labeled base/#reads, consensus-path vertices highlighted."""
        from pbccs_tpu.models.arrow.params import BASES

        on_path = set(consensus_vertices or ())
        fh.write("digraph G {\n  rankdir=\"LR\";\n")
        for v in range(len(self.base)):
            base = BASES[self.base[v]] if 0 <= self.base[v] < 4 else "N"
            style = ' style="filled", fillcolor="lightblue",' if v in on_path else ""
            fh.write(f'  {v} [shape=Mrecord,{style} label="{{ {base} | '
                     f'{self.nreads[v]} }}"];\n')
        for v in range(len(self.base)):
            for w in self.succs[v]:
                fh.write(f"  {v} -> {w};\n")
        fh.write("}\n")
