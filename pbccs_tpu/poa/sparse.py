"""SparsePoa equivalent: orientation handling + consensus + per-read extents.

Parity: reference src/SparsePoa.cpp:96-199 / include/pacbio/ccs/SparsePoa.h.

The alignment/threading engine has two behavior-identical backends: the
native C++ engine (native/pbccs_native.cpp, used when the library loads --
the draft stage is the host-side bottleneck once polishing runs on the
accelerator) and the pure-Python PoaGraph (the reference implementation and
fallback; PBCCS_NATIVE=0 forces it).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from pbccs_tpu import native
from pbccs_tpu.align.seeds import find_seeds
from pbccs_tpu.models.arrow.params import revcomp
from pbccs_tpu.poa.banding import (_MAX_OCC, anchor_chain, anchor_k,
                                   banding_enabled, sdp_vertex_ranges)
from pbccs_tpu.poa.graph import PoaGraph


@dataclasses.dataclass
class PoaAlignmentSummary:
    """Reference SparsePoa.h:71-86."""

    reverse_complemented: bool = False
    extent_on_read: tuple[int, int] = (0, 0)
    extent_on_consensus: tuple[int, int] = (0, 0)


class SparsePoa:
    def __init__(self):
        self._native = native.native_poa()
        self._graph = PoaGraph() if self._native is None else None
        self._snapshot: PoaGraph | None = None
        self.read_paths: list[list[int]] = []
        self.reverse_complemented: list[bool] = []

    @property
    def graph(self) -> PoaGraph:
        """The POA graph.  On the Python backend this is the live graph; on
        the native backend it is a READ-ONLY snapshot (bases/edges/counts/
        consensus scores) cached until the next added read -- mutations made
        to the snapshot are discarded."""
        if self._native is not None:
            if self._snapshot is None:
                self._snapshot = self._native.export_graph()
            return self._snapshot
        return self._graph

    def orient_and_add_read(self, read: np.ndarray, min_score_to_add: float = 0.0) -> int:
        """Try both orientations, commit the better one if it clears the
        score bar; returns the read key or -1
        (reference SparsePoa.cpp:96-137)."""
        if self._native is not None:
            res = self._native.orient_add(read, min_score_to_add)
            self._snapshot = None
            if res is None:
                return -1
            path, rc = res
            self.read_paths.append(path)
            self.reverse_complemented.append(rc)
            return len(self.read_paths) - 1

        if self._graph.n_reads == 0:
            path = self._graph.add_first_read(read)
            self.read_paths.append(path)
            self.reverse_complemented.append(False)
            return 0
        ranges_fwd = ranges_rev = None
        g = self._graph
        order = g.topo_order()
        if banding_enabled():
            # the reference computes SDP ranges against the graph's current
            # consensus each TryAddRead (PoaGraphImpl.cpp:394-401)
            css_path = g.consensus_path(0)
            # the min_cov=0 scores consensus_path just cached are
            # banding-internal; do not let them masquerade as a
            # caller-requested consensus
            del g.vertex_score
            css = np.asarray([g.base[v] for v in css_path], np.int8)
            rc = revcomp(read)
            k = anchor_k(len(css), len(read))
            chain_f = anchor_chain(find_seeds(css, read, k, max_occ=_MAX_OCC))
            chain_r = anchor_chain(find_seeds(css, rc, k, max_occ=_MAX_OCC))
            # Orientation triage by chain density: the wrong strand chains
            # only a few spurious anchors, so a much thinner chain means
            # that orientation is (almost surely) wrong -- give it a
            # minimal one-row band (scores ~0, loses the orientation
            # contest) instead of a wide garbage band or a full O(V*I)
            # fill.  Comparable chains (palindromic inserts) band both.
            minimal = np.zeros((len(g.base), 2), np.int64)
            minimal[:, 1] = 1
            if len(chain_f) >= 2 and len(chain_f) >= 4 * len(chain_r):
                ranges_fwd = sdp_vertex_ranges(len(g.base), order, g.preds,
                                               g.succs, css_path, chain_f,
                                               len(read))
                ranges_rev = minimal
            elif len(chain_r) >= 2 and len(chain_r) >= 4 * len(chain_f):
                ranges_rev = sdp_vertex_ranges(len(g.base), order, g.preds,
                                               g.succs, css_path, chain_r,
                                               len(rc))
                ranges_fwd = minimal
            else:
                ranges_fwd = sdp_vertex_ranges(len(g.base), order, g.preds,
                                               g.succs, css_path, chain_f,
                                               len(read))
                ranges_rev = sdp_vertex_ranges(len(g.base), order, g.preds,
                                               g.succs, css_path, chain_r,
                                               len(rc))
        fwd = self._graph.try_add_read(read, False, ranges=ranges_fwd,
                                       order=order)
        rev = self._graph.try_add_read(revcomp(read), True, ranges=ranges_rev,
                                       order=order)
        plan = fwd if fwd.score >= rev.score else rev
        if plan.score < min_score_to_add:
            return -1
        path = self._graph.commit_add(plan)
        self.read_paths.append(path)
        self.reverse_complemented.append(plan.reverse_complemented)
        return len(self.read_paths) - 1

    def find_consensus(self, min_coverage: int):
        """Returns (consensus codes, per-read PoaAlignmentSummary list)
        (reference SparsePoa.cpp:139-199)."""
        if self._native is not None:
            path = self._native.consensus_path(min_coverage)
            self._snapshot = None  # consensus (re)computes vertex scores
            css = self._native.bases()[np.asarray(path, np.int64)] \
                if path else np.zeros(0, np.int8)
        else:
            path = self._graph.consensus_path(min_coverage)
            css = np.asarray([self._graph.base[v] for v in path], np.int8)
        self.last_consensus_path = path
        css_position = {v: i for i, v in enumerate(path)}

        summaries = []
        for key, read_path in enumerate(self.read_paths):
            read_s = read_e = css_s = css_e = 0
            found = False
            for read_pos, v in enumerate(read_path):
                if v in css_position:
                    if not found:
                        css_s = css_position[v]
                        read_s = read_pos
                        found = True
                    css_e = css_position[v] + 1
                    read_e = read_pos + 1
            summaries.append(PoaAlignmentSummary(
                reverse_complemented=self.reverse_complemented[key],
                extent_on_read=(read_s, read_e),
                extent_on_consensus=(css_s, css_e)))
        return css, summaries
