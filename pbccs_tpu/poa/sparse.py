"""SparsePoa equivalent: orientation handling + consensus + per-read extents.

Parity: reference src/SparsePoa.cpp:96-199 / include/pacbio/ccs/SparsePoa.h.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from pbccs_tpu.models.arrow.params import revcomp
from pbccs_tpu.poa.graph import PoaGraph


@dataclasses.dataclass
class PoaAlignmentSummary:
    """Reference SparsePoa.h:71-86."""

    reverse_complemented: bool = False
    extent_on_read: tuple[int, int] = (0, 0)
    extent_on_consensus: tuple[int, int] = (0, 0)


class SparsePoa:
    def __init__(self):
        self.graph = PoaGraph()
        self.read_paths: list[list[int]] = []
        self.reverse_complemented: list[bool] = []

    def orient_and_add_read(self, read: np.ndarray, min_score_to_add: float = 0.0) -> int:
        """Try both orientations, commit the better one if it clears the
        score bar; returns the read key or -1
        (reference SparsePoa.cpp:96-137)."""
        if self.graph.n_reads == 0:
            path = self.graph.add_first_read(read)
            self.read_paths.append(path)
            self.reverse_complemented.append(False)
            return 0
        fwd = self.graph.try_add_read(read, False)
        rev = self.graph.try_add_read(revcomp(read), True)
        plan = fwd if fwd.score >= rev.score else rev
        if plan.score < min_score_to_add:
            return -1
        path = self.graph.commit_add(plan)
        self.read_paths.append(path)
        self.reverse_complemented.append(plan.reverse_complemented)
        return len(self.read_paths) - 1

    def find_consensus(self, min_coverage: int):
        """Returns (consensus codes, per-read PoaAlignmentSummary list)
        (reference SparsePoa.cpp:139-199)."""
        path = self.graph.consensus_path(min_coverage)
        self.last_consensus_path = path
        css = np.asarray([self.graph.base[v] for v in path], np.int8)
        css_position = {v: i for i, v in enumerate(path)}

        summaries = []
        for key, read_path in enumerate(self.read_paths):
            read_s = read_e = css_s = css_e = 0
            found = False
            for read_pos, v in enumerate(read_path):
                if v in css_position:
                    if not found:
                        css_s = css_position[v]
                        read_s = read_pos
                        found = True
                    css_e = css_position[v] + 1
                    read_e = read_pos + 1
            summaries.append(PoaAlignmentSummary(
                reverse_complemented=self.reverse_complemented[key],
                extent_on_read=(read_s, read_e),
                extent_on_consensus=(css_s, css_e)))
        return css, summaries
