"""SDP-anchored POA banding: per-vertex alignable read ranges.

Parity: reference ConsensusCore/src/C++/Poa/RangeFinder.cpp:72-167
(SdpRangeFinder::InitRangeFinder) + src/SparsePoa.cpp:65-69 (anchors from
SparseAlign).  Semantics re-derived:

  * anchors = chained shared k-mers between the graph's current consensus
    sequence and the read, (cssPos, readPos) pairs;
  * a consensus-path vertex whose cssPos carries an anchor gets the direct
    range [readPos - WIDTH, readPos + WIDTH) clamped to the read;
  * a forward pass in topological order gives anchorless vertices the union
    of their predecessors' ranges stepped +1 (clamped), a reverse pass the
    union of successors' ranges stepped -1; the final range is the hull of
    both passes.

Note the reference snapshot *computes* these ranges but its
makeAlignmentColumn ignores beginRow/endRow and still fills full columns
(PoaGraphImpl.cpp:235-352); here the ranges genuinely band the fill, making
the draft stage O(V * band) instead of O(V * I) -- the behavior later
upstream versions adopted and the property long reads need.

k-mer size: the reference uses k=6 (SparsePoa.cpp:65-69).  At k=6 two L-bp
sequences share ~L^2/4096 random k-mers, which is fine at the reference's
operating point but quadratic-explodes for 10kb+ inserts, so beyond
_LONG_SEQ the anchor finder switches to k=10 (the reference's own default
FindSeedsConfig TSize elsewhere, SparseAlignment.h:278) where random
collisions stay rare while true anchors remain dense.
"""

from __future__ import annotations

import bisect
import os

import numpy as np

from pbccs_tpu.align.seeds import find_seeds


def banding_enabled() -> bool:
    """SDP-anchored banding of the read-vs-graph fill (PBCCS_POA_BAND=0
    disables, falling back to full-width columns for A/B comparison)."""
    return os.environ.get("PBCCS_POA_BAND", "").strip().lower() not in (
        "0", "false", "off", "no")

WIDTH = 30          # reference RangeFinder.cpp:15
_LONG_SEQ = 1000    # switch from k=6 to k=10 above this length
_MAX_OCC = 64       # mask k-mers occurring more often than this in the css
_BIG = np.int64(1) << 40


def anchor_k(len_css: int, len_read: int) -> int:
    return 6 if max(len_css, len_read) < _LONG_SEQ else 10


def anchor_chain(seeds: np.ndarray) -> np.ndarray:
    """Longest strictly-increasing (cssPos, readPos) subsequence of the
    seeds -- the banding anchor chain.

    The reference chains banding anchors with its full gain-scored SDP
    (ChainSeeds.cpp:203-361, O(n log n) via sweep-line visibility sets);
    the numpy/native chainers here are O(n^2) all-pairs, quadratic in
    template length since anchors ~ L/5.  Banding only needs a monotone
    anchor backbone (ranges are +-WIDTH hulls anyway), so this O(n log n)
    patience LIS -- implemented identically in native/pbccs_native.cpp
    (AnchorChain) -- replaces the scored chain on the banding path only."""
    n = len(seeds)
    if n == 0:
        return seeds.reshape(0, 2)
    # sort by cssPos asc, readPos DESC so equal-cssPos seeds cannot chain
    # onto each other under the strict-increase rule below
    s = seeds[np.lexsort((-seeds[:, 1], seeds[:, 0]))]
    rs = s[:, 1].tolist()
    tails_r: list[int] = []
    tails_i: list[int] = []
    parent = [-1] * n
    for i, r in enumerate(rs):
        k = bisect.bisect_left(tails_r, r)  # strictly increasing readPos
        parent[i] = tails_i[k - 1] if k else -1
        if k == len(tails_r):
            tails_r.append(r)
            tails_i.append(i)
        else:
            tails_r[k] = r
            tails_i[k] = i
    chain = []
    i = tails_i[-1]
    while i >= 0:
        chain.append(i)
        i = parent[i]
    chain.reverse()
    return s[chain]


def sdp_vertex_ranges(n_vertices: int,
                      order: list[int],
                      preds: list[list[int]],
                      succs: list[list[int]],
                      css_path: list[int],
                      chain: np.ndarray,
                      read_len: int,
                      width: int = WIDTH) -> np.ndarray | None:
    """(n_vertices, 2) DP-row ranges [lo, hi) per vertex from a chained
    anchor set (anchor_chain over find_seeds css<->read), or None when the
    chain is too thin to band safely (caller falls back to the full-width
    fill)."""
    I = read_len
    if len(chain) < 2:
        return None

    # hull-identity encoding: empty = (+BIG, -BIG)
    lo = np.full(n_vertices, _BIG, np.int64)
    hi = np.full(n_vertices, -_BIG, np.int64)
    direct = np.zeros(n_vertices, bool)
    path = np.asarray(css_path, np.int64)
    vs = path[chain[:, 0]]
    rp = chain[:, 1].astype(np.int64)
    lo[vs] = np.maximum(rp - width, 0)
    hi[vs] = np.minimum(rp + width, I)
    direct[vs] = True

    flo, fhi = lo.copy(), hi.copy()
    for v in order:
        if not direct[v] and preds[v]:
            b, e = _BIG, -_BIG
            for p in preds[v]:
                if flo[p] <= fhi[p]:  # stepped empty stays empty
                    b = min(b, min(flo[p] + 1, I))
                    e = max(e, min(fhi[p] + 1, I))
            flo[v], fhi[v] = b, e

    rlo, rhi = lo.copy(), hi.copy()
    for v in reversed(order):
        if not direct[v] and succs[v]:
            b, e = _BIG, -_BIG
            for s in succs[v]:
                if rlo[s] <= rhi[s]:
                    b = min(b, max(rlo[s] - 1, 0))
                    e = max(e, max(rhi[s] - 1, 0))
            rlo[v], rhi[v] = b, e

    lo = np.minimum(flo, rlo)
    hi = np.maximum(fhi, rhi)
    empty = lo > hi
    lo[empty] = 0
    hi[empty] = I

    # read positions [lo, hi] -> DP rows [lo, hi+2) (row i consumes read
    # position i-1; +1 more so a trailing delete/extra row is reachable)
    out = np.empty((n_vertices, 2), np.int64)
    out[:, 0] = np.clip(lo, 0, I)
    out[:, 1] = np.clip(hi + 2, 1, I + 1)
    out[:, 1] = np.maximum(out[:, 1], out[:, 0] + 1)
    return out
