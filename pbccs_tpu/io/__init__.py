"""File IO: FASTA, BAM (BGZF), CSV yield reports, .fofn flattening."""
