"""PacBio BAM index (.pbi) writer/reader.

Parity target: pbbam's PbiBuilder as used by the reference CLI
(reference src/main/ccs.cpp:52-54, 120: `PbiBuilder` aggregates one row per
record so SMRT tools can address ZMWs without scanning the BAM).  pbbam is
not vendored in the reference tree; this implements the published PacBio
BAM index format spec (BasicData section): a BGZF-compressed file

  magic "PBI\\x01" | version u32 | pbi_flags u16 | n_reads u32 | 18B reserved
  rgId i32[n] | qStart i32[n] | qEnd i32[n] | holeNumber i32[n]
  readQual f32[n] | ctxtFlag u8[n] | fileOffset u64[n]

fileOffset is the BGZF virtual offset (coffset << 16 | uoffset) of the
record in the companion BAM."""

from __future__ import annotations

import io
import struct

import numpy as np

from pbccs_tpu.io.bam import BgzfReader, BgzfWriter

PBI_MAGIC = b"PBI\x01"
PBI_VERSION = 0x00000301          # format 3.0.1
FLAG_BASIC = 0x0000


def read_group_numeric_id(rg_id: str) -> int:
    """pbbam convention: the read-group id is the first 8 hex chars of the
    MD5-derived id string, interpreted as a signed int32."""
    return np.int32(int(rg_id[:8], 16) - (1 << 32 if int(rg_id[:8], 16) >= 1 << 31 else 0))


class PbiBuilder:
    """Accumulates one index row per BAM record; close() publishes the
    .pbi ATOMICALLY (tmp+fsync+rename via resources.atomic_output), the
    same durability contract as the companion BamWriter: an ENOSPC or
    crash mid-index never leaves a torn .pbi beside a valid BAM, and a
    filesystem failure surfaces as a structured OutputWriteError
    (sink="pbi")."""

    def __init__(self, path: str):
        self._path = path
        self.rg_ids: list[int] = []
        self.q_starts: list[int] = []
        self.q_ends: list[int] = []
        self.holes: list[int] = []
        self.read_quals: list[float] = []
        self.ctxt_flags: list[int] = []
        self.offsets: list[int] = []

    def add_record(self, rg_id: int, q_start: int, q_end: int, hole: int,
                   read_qual: float, ctxt_flag: int, file_offset: int) -> None:
        self.rg_ids.append(int(rg_id))
        self.q_starts.append(int(q_start))
        self.q_ends.append(int(q_end))
        self.holes.append(int(hole))
        self.read_quals.append(float(read_qual))
        self.ctxt_flags.append(int(ctxt_flag))
        self.offsets.append(int(file_offset))

    def close(self) -> None:
        n = len(self.holes)
        payload = io.BytesIO()
        payload.write(PBI_MAGIC)
        payload.write(struct.pack("<IHI", PBI_VERSION, FLAG_BASIC, n))
        payload.write(b"\x00" * 18)
        payload.write(np.asarray(self.rg_ids, "<i4").tobytes())
        payload.write(np.asarray(self.q_starts, "<i4").tobytes())
        payload.write(np.asarray(self.q_ends, "<i4").tobytes())
        payload.write(np.asarray(self.holes, "<i4").tobytes())
        payload.write(np.asarray(self.read_quals, "<f4").tobytes())
        payload.write(np.asarray(self.ctxt_flags, "u1").tobytes())
        payload.write(np.asarray(self.offsets, "<u8").tobytes())
        from pbccs_tpu.resilience.resources import atomic_output

        with atomic_output(self._path, "pbi", mode="wb") as fh:
            w = BgzfWriter(fh)
            w.write(payload.getvalue())
            w.close()

    def __enter__(self) -> "PbiBuilder":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        # publish only on clean exit: an exception mid-accumulation must
        # not atomically rename a PARTIAL index over a previous valid one
        if exc_type is None:
            self.close()


class PbiIndex:
    """Parsed .pbi; arrays indexed per record."""

    def __init__(self, path: str):
        with open(path, "rb") as fh:
            raw = fh.read()
        from pbccs_tpu import native
        data = native.bgzf_decompress(raw)
        if data is None:                     # no native lib: python path
            rd = BgzfReader(io.BytesIO(raw))
            data = b""
            while True:
                chunk = rd.read(1 << 20)
                if not chunk:
                    break
                data += chunk
        if data[:4] != PBI_MAGIC:
            raise ValueError("not a PBI file")
        self.version, self.flags, n = struct.unpack_from("<IHI", data, 4)
        off = 4 + 10 + 18
        take = lambda dt: (np.frombuffer(data, dt, n, off), off + n * np.dtype(dt).itemsize)
        self.rg_ids, off = take("<i4")
        self.q_starts, off = take("<i4")
        self.q_ends, off = take("<i4")
        self.holes, off = take("<i4")
        self.read_quals, off = take("<f4")
        self.ctxt_flags, off = take("u1")
        self.offsets, off = take("<u8")
        self.n_reads = n

    def rows_for_zmw(self, hole: int) -> np.ndarray:
        return np.flatnonzero(self.holes == hole)
