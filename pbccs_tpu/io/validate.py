"""The shared input contract both front doors enforce identically.

The CLI reader (cli._chunks_from_files) and the serve `submit` verb
(serve.protocol.chunk_from_wire) both admit Chunks into the same polish
pipeline, so they must reject garbage identically: one validate_chunk()
with structured machine-readable reasons, counted under the same
``ccs_input_invalid_records_total{reason}`` family the salvaging BAM
decoder uses.  A chunk that passes here is safe to hand to
pipeline.prepare_chunk -- no NaN SNRs reaching device math, no
pathological read counts/lengths minting absurd compiled shapes, no
out-of-range accuracies skewing the read-score gate.

Bounds are deliberately generous (an order of magnitude past anything a
real SMRT cell produces) so they only ever reject hostile or corrupt
input, never legitimate workloads."""

from __future__ import annotations

import math

import numpy as np

# one shared {reason}-labeled rejection counter with the BAM decoder --
# a garbage chunk and a garbage record are the same metric family
from pbccs_tpu.io.bam import count_invalid_record as _count

# generous physical bounds: real ZMWs top out around ~3k passes of ~50 kb
MAX_READS_PER_CHUNK = 8192
MAX_READ_LEN = 1 << 22          # 4 Mbase per subread
MAX_TOTAL_BASES = 1 << 26       # 64 Mbase per ZMW across all subreads


class ChunkValidationError(ValueError):
    """A chunk violates the shared input contract; ``reason`` is the
    machine-readable class counted in the metrics registry."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


def _fail(reason: str, message: str) -> None:
    _count(reason)
    raise ChunkValidationError(reason, message)


def validate_chunk(chunk) -> None:
    """Raise ChunkValidationError (and count the rejection) unless
    `chunk` satisfies the shared input contract:

      * snr is 4 finite non-negative numbers (ACGT order);
      * 1..MAX_READS_PER_CHUNK reads, each 1..MAX_READ_LEN bases,
        MAX_TOTAL_BASES total;
      * every read_accuracy is a finite number in [0, 1].
    """
    try:
        snr = np.asarray(chunk.snr, dtype=np.float64)
    except (TypeError, ValueError):
        snr = None
    if snr is None or snr.shape != (4,):
        _fail("snr_shape", "snr must be 4 numbers (ACGT)")
    if not np.all(np.isfinite(snr)) or np.any(snr < 0):
        _fail("bad_snr", "snr values must be finite and non-negative")
    reads = chunk.reads
    if not reads:
        _fail("no_reads", "chunk has no reads")
    if len(reads) > MAX_READS_PER_CHUNK:
        _fail("reads_count",
              f"{len(reads)} reads exceeds the {MAX_READS_PER_CHUNK} bound")
    total = 0
    for i, read in enumerate(reads):
        n = len(read.seq)
        if n < 1 or n > MAX_READ_LEN:
            _fail("read_length",
                  f"reads[{i}] length {n} outside [1, {MAX_READ_LEN}]")
        total += n
        try:
            acc = float(read.read_accuracy)
        except (TypeError, ValueError):
            acc = float("nan")
        if not math.isfinite(acc) or not 0.0 <= acc <= 1.0:
            _fail("accuracy_range",
                  f"reads[{i}] accuracy {read.read_accuracy!r} "
                  "outside [0, 1]")
    if total > MAX_TOTAL_BASES:
        _fail("total_bases",
              f"{total} total bases exceeds the {MAX_TOTAL_BASES} bound")
