"""End-of-run CSV yield report.

Parity: reference WriteResultsReport (src/main/ccs.cpp:233-262): one line
per yield category with count and percentage of total ZMWs.
"""

from __future__ import annotations

from typing import TextIO

from pbccs_tpu.pipeline import Failure, ResultTally

_LABELS: list[tuple[Failure, str]] = [
    (Failure.SUCCESS, "Success -- CCS generated"),
    (Failure.POOR_SNR, "Failed -- Below SNR threshold"),
    (Failure.NO_SUBREADS, "Failed -- No usable subreads"),
    (Failure.TOO_SHORT, "Failed -- Insert size too small"),
    (Failure.TOO_FEW_PASSES, "Failed -- Not enough full passes"),
    (Failure.TOO_MANY_UNUSABLE, "Failed -- Too many unusable subreads"),
    (Failure.NON_CONVERGENT, "Failed -- CCS did not converge"),
    (Failure.POOR_QUALITY, "Failed -- CCS below minimum predicted accuracy"),
    (Failure.OTHER, "Failed -- Exception thrown"),
]


def write_results_report(out: TextIO, tally: ResultTally) -> None:
    total = max(tally.total, 1)
    for failure, label in _LABELS:
        if failure == Failure.OTHER and tally.counts[failure] == 0:
            continue  # the reference has no Other line; only emit if nonzero
        count = tally.counts[failure]
        out.write(f"{label},{count},{100.0 * count / total:.2f}%\n")


def write_report_file(path: str, tally: ResultTally) -> None:
    """Disk-full-safe report write: the CSV lands through a same-dir
    temp file + rename (resilience.resources.atomic_output), so an
    ENOSPC mid-write surfaces as a structured OutputWriteError and
    never publishes a torn report.  The ``output.write`` fault site
    (key ``report``) injects the failure deterministically."""
    from pbccs_tpu.resilience import faults
    from pbccs_tpu.resilience.resources import atomic_output

    with atomic_output(path, "report") as out:
        faults.maybe_fail("output.write", keys=["report", path])
        write_results_report(out, tally)
