"""BAM IO: BGZF (de)compression + unaligned PacBio BAM records, pure host.

The reference delegates BAM IO to pbbam/htslib (CMakeLists.txt:54-66,
src/main/ccs.cpp:52-54); this module provides the same capabilities
natively: BGZF block framing over zlib raw-deflate, BAM record
encode/decode, PacBio read-group conventions (movie//READTYPE derived
read-group ids), and the CCS output tags (src/main/ccs.cpp:105-172).

The writer/reader operate streamingly block-by-block so full SMRT cells
never materialize in memory; a native C++ BGZF codec is the planned drop-in
for the compression hot path.
"""

from __future__ import annotations

import dataclasses
import hashlib
import struct
import zlib
from typing import BinaryIO, Iterator

_BGZF_HEADER = (b"\x1f\x8b\x08\x04\x00\x00\x00\x00\x00\xff\x06\x00\x42\x43\x02\x00")
_BGZF_EOF = bytes.fromhex("1f8b08040000000000ff0600424302001b0003000000000000000000")
_MAX_BLOCK = 64 * 1024 - 512  # uncompressed payload per BGZF block

# 4-bit nucleotide encoding ("=ACMGRSVTWYHKDBN")
_NIBBLE = {c: i for i, c in enumerate("=ACMGRSVTWYHKDBN")}
_NIBBLE_INV = "=ACMGRSVTWYHKDBN"


class BgzfWriter:
    def __init__(self, fh: BinaryIO):
        self._fh = fh
        self._buf = bytearray()
        self._upos = 0            # total uncompressed bytes accepted
        self._cpos = 0            # total compressed bytes emitted
        self._block_comp_starts: list[int] = []  # comp offset of each block

    def utell(self) -> int:
        """Total uncompressed bytes written so far (all blocks are exactly
        _MAX_BLOCK payload except the final one, so an uncompressed offset
        resolves to a BGZF virtual offset after close via voffset())."""
        return self._upos

    def voffset(self, upos: int) -> int:
        """BGZF virtual file offset (coffset << 16 | uoffset) of the
        uncompressed position `upos`; valid after the block containing it
        is flushed (always true after close())."""
        blk = upos // _MAX_BLOCK
        if blk >= len(self._block_comp_starts):
            raise ValueError(
                f"uncompressed offset {upos} is in a block that has not been "
                "flushed yet; resolve virtual offsets after close()")
        return (self._block_comp_starts[blk] << 16) | (upos - blk * _MAX_BLOCK)

    def write(self, data: bytes) -> None:
        self._upos += len(data)
        self._buf += data
        if len(self._buf) >= 4 * _MAX_BLOCK:
            # batch path: the native codec compresses whole-block runs
            # across threads (native/pbccs_native.cpp)
            from pbccs_tpu import native
            nblocks = len(self._buf) // _MAX_BLOCK
            chunk = bytes(self._buf[: nblocks * _MAX_BLOCK])
            packed = native.bgzf_compress(chunk)
            if packed is not None:
                # walk the packed blocks to record their compressed starts
                off = 0
                while off < len(packed):
                    self._block_comp_starts.append(self._cpos + off)
                    bsize = packed[off + 16] | (packed[off + 17] << 8)
                    off += bsize + 1
                self._fh.write(packed)
                self._cpos += len(packed)
                del self._buf[: nblocks * _MAX_BLOCK]
                return
        while len(self._buf) >= _MAX_BLOCK:
            self._flush_block(self._buf[:_MAX_BLOCK])
            del self._buf[:_MAX_BLOCK]

    def _flush_block(self, chunk: bytes) -> None:
        co = zlib.compressobj(6, zlib.DEFLATED, -15)
        comp = co.compress(bytes(chunk)) + co.flush()
        bsize = len(comp) + len(_BGZF_HEADER) + 2 + 8  # +BSIZE +CRC/ISIZE
        self._block_comp_starts.append(self._cpos)
        self._cpos += bsize
        self._fh.write(_BGZF_HEADER)
        self._fh.write(struct.pack("<H", bsize - 1))
        self._fh.write(comp)
        self._fh.write(struct.pack("<I", zlib.crc32(bytes(chunk)) & 0xFFFFFFFF))
        self._fh.write(struct.pack("<I", len(chunk) & 0xFFFFFFFF))

    def close(self) -> None:
        if self._buf:
            self._flush_block(bytes(self._buf))
            self._buf.clear()
        self._fh.write(_BGZF_EOF)
        self._fh.flush()


class BgzfReader:
    """Streaming BGZF reader: decodes one block at a time."""

    def __init__(self, fh: BinaryIO):
        self._fh = fh
        self._buf = bytearray()
        self._eof = False

    def _fill(self) -> bool:
        head = self._fh.read(12)
        if len(head) < 12:
            self._eof = True
            return False
        magic1, magic2, method, flags, _mtime, _xfl, _os, xlen = struct.unpack(
            "<BBBBIBBH", head)
        if (magic1, magic2) != (0x1F, 0x8B):
            raise ValueError("not a BGZF/gzip stream")
        extra = self._fh.read(xlen)
        bsize = None
        off = 0
        while off + 4 <= len(extra):
            si1, si2, slen = extra[off], extra[off + 1], struct.unpack(
                "<H", extra[off + 2: off + 4])[0]
            if (si1, si2) == (66, 67) and slen == 2:
                bsize = struct.unpack("<H", extra[off + 4: off + 6])[0] + 1
            off += 4 + slen
        if bsize is None:
            raise ValueError("missing BGZF BC subfield (plain gzip?)")
        comp_len = bsize - 12 - xlen - 8
        comp = self._fh.read(comp_len)
        crc, isize = struct.unpack("<II", self._fh.read(8))
        data = zlib.decompress(comp, -15)
        if len(data) != isize or (zlib.crc32(data) & 0xFFFFFFFF) != crc:
            raise ValueError("corrupt BGZF block")
        if not data:  # EOF marker block
            return self._fill()
        self._buf += data
        return True

    def read(self, n: int) -> bytes:
        while len(self._buf) < n and not self._eof:
            self._fill()
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


def make_read_group_id(movie_name: str, read_type: str) -> str:
    """8-hex-digit read-group id from movie//READTYPE (PacBio convention
    used by MakeReadGroupId, src/main/ccs.cpp:134)."""
    return hashlib.md5(f"{movie_name}//{read_type}".encode()).hexdigest()[:8]


@dataclasses.dataclass
class ReadGroupInfo:
    """One @RG header line (PacBio conventions: PU = movie name, DS holds
    READTYPE/kits/basecaller-version key-values)."""

    movie_name: str
    read_type: str = "SUBREAD"
    binding_kit: str = ""
    sequencing_kit: str = ""
    basecaller_version: str = ""
    frame_rate_hz: str = ""

    @property
    def id(self) -> str:
        return make_read_group_id(self.movie_name, self.read_type)

    def to_sam(self) -> str:
        ds = [f"READTYPE={self.read_type}"]
        if self.binding_kit:
            ds.append(f"BINDINGKIT={self.binding_kit}")
        if self.sequencing_kit:
            ds.append(f"SEQUENCINGKIT={self.sequencing_kit}")
        if self.basecaller_version:
            ds.append(f"BASECALLERVERSION={self.basecaller_version}")
        if self.frame_rate_hz:
            ds.append(f"FRAMERATEHZ={self.frame_rate_hz}")
        return (f"@RG\tID:{self.id}\tPL:PACBIO\tDS:{';'.join(ds)}"
                f"\tPU:{self.movie_name}")

    @staticmethod
    def from_sam(line: str) -> "ReadGroupInfo":
        fields = dict(f.split(":", 1) for f in line.strip().split("\t")[1:]
                      if ":" in f)
        ds = dict(kv.split("=", 1) for kv in fields.get("DS", "").split(";")
                  if "=" in kv)
        return ReadGroupInfo(
            movie_name=fields.get("PU", ""),
            read_type=ds.get("READTYPE", ""),
            binding_kit=ds.get("BINDINGKIT", ""),
            sequencing_kit=ds.get("SEQUENCINGKIT", ""),
            basecaller_version=ds.get("BASECALLERVERSION", ""),
            frame_rate_hz=ds.get("FRAMERATEHZ", ""))


@dataclasses.dataclass
class BamHeader:
    read_groups: list[ReadGroupInfo] = dataclasses.field(default_factory=list)
    program_lines: list[str] = dataclasses.field(default_factory=list)
    version: str = "1.5"
    pacbio_version: str = "3.0b7"
    sort_order: str = "unknown"

    def to_text(self) -> str:
        lines = [f"@HD\tVN:{self.version}\tSO:{self.sort_order}"
                 f"\tpb:{self.pacbio_version}"]
        lines += [rg.to_sam() for rg in self.read_groups]
        lines += self.program_lines
        return "\n".join(lines) + "\n"

    @staticmethod
    def from_text(text: str) -> "BamHeader":
        header = BamHeader()
        for line in text.splitlines():
            if line.startswith("@RG"):
                header.read_groups.append(ReadGroupInfo.from_sam(line))
            elif line.startswith("@PG"):
                header.program_lines.append(line)
        return header


@dataclasses.dataclass
class BamRecord:
    """An unaligned BAM record: name + seq + quals + tag dict.

    Tag values: int, float, str, bytes (H), or list[int]/list[float]
    (B arrays)."""

    name: str
    seq: str
    qual: str = ""  # phred+33 ASCII, "" = absent (0xFF fill)
    tags: dict = dataclasses.field(default_factory=dict)
    flag: int = 4  # unmapped


def _encode_tags(tags: dict) -> bytes:
    out = bytearray()
    for key, val in tags.items():
        kb = key.encode()
        if isinstance(val, bool):
            raise TypeError("bool tag unsupported")
        if isinstance(val, int):
            out += kb + b"i" + struct.pack("<i", val)
        elif isinstance(val, float):
            out += kb + b"f" + struct.pack("<f", val)
        elif isinstance(val, str):
            out += kb + b"Z" + val.encode() + b"\x00"
        elif isinstance(val, (list, tuple)):
            if all(isinstance(v, int) for v in val):
                out += kb + b"B" + b"i" + struct.pack("<I", len(val))
                out += struct.pack(f"<{len(val)}i", *val)
            else:
                out += kb + b"B" + b"f" + struct.pack("<I", len(val))
                out += struct.pack(f"<{len(val)}f", *[float(v) for v in val])
        else:
            raise TypeError(f"unsupported tag type for {key}: {type(val)}")
    return bytes(out)


_TAG_SCALARS = {"A": ("c", 1), "c": ("b", 1), "C": ("B", 1), "s": ("h", 2),
                "S": ("H", 2), "i": ("i", 4), "I": ("I", 4), "f": ("f", 4)}


def _decode_tags(data: bytes) -> dict:
    tags = {}
    off = 0
    while off + 3 <= len(data):
        key = data[off: off + 2].decode()
        typ = chr(data[off + 2])
        off += 3
        if typ in _TAG_SCALARS:
            fmt, size = _TAG_SCALARS[typ]
            val = struct.unpack_from("<" + fmt, data, off)[0]
            if typ == "A":
                val = val.decode()
            off += size
        elif typ in ("Z", "H"):
            end = data.index(b"\x00", off)
            val = data[off:end].decode()
            off = end + 1
        elif typ == "B":
            sub = chr(data[off])
            n = struct.unpack_from("<I", data, off + 1)[0]
            fmt, size = _TAG_SCALARS[sub]
            val = list(struct.unpack_from(f"<{n}{fmt}", data, off + 5))
            off += 5 + n * size
        else:
            raise ValueError(f"unknown tag type {typ!r}")
        tags[key] = val
    return tags


class BamWriter:
    """Unaligned BAM writer (no reference sequences)."""

    def __init__(self, path: str, header: BamHeader):
        self._fh = open(path, "wb")
        self._bgzf = BgzfWriter(self._fh)
        text = header.to_text().encode()
        self._bgzf.write(b"BAM\x01" + struct.pack("<i", len(text)) + text
                         + struct.pack("<i", 0))

    def write(self, rec: BamRecord) -> int:
        """Write one record; returns its uncompressed stream offset (resolve
        to a .pbi virtual file offset with `voffset()` after close)."""
        upos = self._bgzf.utell()
        name = rec.name.encode() + b"\x00"
        seq = rec.seq.upper()
        l_seq = len(seq)
        packed = bytearray()
        for i in range(0, l_seq - 1, 2):
            packed.append((_NIBBLE.get(seq[i], 15) << 4)
                          | _NIBBLE.get(seq[i + 1], 15))
        if l_seq % 2:
            packed.append(_NIBBLE.get(seq[-1], 15) << 4)
        if rec.qual:
            qual = bytes(ord(c) - 33 for c in rec.qual)
        else:
            qual = b"\xff" * l_seq
        tags = _encode_tags(rec.tags)
        body = struct.pack("<iiBBHHHiiii", -1, -1, len(name), 255, 0, 0,
                           rec.flag, l_seq, -1, -1, 0)
        body += name + bytes(packed) + qual + tags
        self._bgzf.write(struct.pack("<i", len(body)) + body)
        return upos

    def voffset(self, upos: int) -> int:
        return self._bgzf.voffset(upos)

    def close(self) -> None:
        self._bgzf.close()
        self._fh.close()

    def __enter__(self) -> "BamWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class BamReader:
    """Iterate records of a BAM file (unaligned or aligned; alignments are
    exposed as plain records, cigars ignored)."""

    def __init__(self, path: str):
        self._fh = open(path, "rb")
        self._bgzf = BgzfReader(self._fh)
        magic = self._bgzf.read(4)
        if magic != b"BAM\x01":
            raise ValueError(f"{path}: not a BAM file")
        l_text = struct.unpack("<i", self._bgzf.read(4))[0]
        self.header = BamHeader.from_text(self._bgzf.read(l_text).decode())
        n_ref = struct.unpack("<i", self._bgzf.read(4))[0]
        for _ in range(n_ref):
            l_name = struct.unpack("<i", self._bgzf.read(4))[0]
            self._bgzf.read(l_name + 4)

    def __iter__(self) -> Iterator[BamRecord]:
        while True:
            head = self._bgzf.read(4)
            if len(head) < 4:
                return
            block_size = struct.unpack("<i", head)[0]
            body = self._bgzf.read(block_size)
            (_refid, _pos, l_name, _mapq, _bin, n_cigar, flag, l_seq,
             _nref, _npos, _tlen) = struct.unpack_from("<iiBBHHHiiii", body)
            off = 32
            name = body[off: off + l_name - 1].decode()
            off += l_name + 4 * n_cigar
            nseq = (l_seq + 1) // 2
            seq_bytes = body[off: off + nseq]
            off += nseq
            seq = "".join(
                _NIBBLE_INV[(seq_bytes[i // 2] >> (4 if i % 2 == 0 else 0)) & 0xF]
                for i in range(l_seq))
            qual_raw = body[off: off + l_seq]
            off += l_seq
            qual = ("" if not qual_raw or qual_raw[0] == 0xFF
                    else "".join(chr(q + 33) for q in qual_raw))
            tags = _decode_tags(body[off:])
            yield BamRecord(name=name, seq=seq, qual=qual, tags=tags,
                            flag=flag)

    def close(self) -> None:
        self._fh.close()

    def __enter__(self) -> "BamReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
